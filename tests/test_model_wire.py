"""Model wire-format v2: delta frames, keyframes, resync, zero-copy swap.

The contract under test (ISSUE 5 acceptance): an actor fed v2 frames —
deltas, a forced keyframe, and a forced resync after a dropped frame —
holds params BYTE-IDENTICAL to the v1 full-bundle path, on all three
transports. Delta encode/apply runs in the integer domain (zigzag of the
storage-word difference), so equality is exact by construction; these
tests pin it, plus the framing/codec/chunking machinery around it.
"""

import time
import warnings

import jax
import numpy as np
import pytest

from relayrl_tpu.config import ConfigLoader
from relayrl_tpu.runtime.agent import Agent
from relayrl_tpu.runtime.policy_actor import PolicyActor, apply_wire_swap
from relayrl_tpu.runtime.vector_actor import VectorActorHost
from relayrl_tpu.transport import make_server_transport, modelwire as mw
from relayrl_tpu.types.model_bundle import (
    ModelBundle,
    leaf_manifest,
    tree_from_leaves,
)

from _util import free_port as _free_port  # noqa: E402

ARCH = {"kind": "mlp_discrete", "obs_dim": 4, "act_dim": 2,
        "hidden_sizes": [8]}


def _params(seed=0, scale=0.05):
    rng = np.random.default_rng(seed)
    return {
        "dense": {"kernel": (rng.standard_normal((16, 32)) * scale)
                  .astype(np.float32),
                  "bias": np.zeros(32, np.float32)},
        "head": {"kernel": (rng.standard_normal((32, 4)) * scale)
                 .astype(np.float32)},
        "counts": rng.integers(0, 100, 7).astype(np.int32),
        "table": rng.integers(0, 255, (5, 5)).astype(np.uint8),
    }


def _step(params, seed, eps=3e-4, only=None):
    """A realistic consecutive update: small dense perturbation of the
    float leaves (``only`` restricts to a dotted-path subset — the
    frozen-trunk shape); integer leaves stay put. Works on any pytree
    (the real MLP params in the actor tests, the fixture dict here)."""
    rng = np.random.default_rng(seed)

    def bump(path, leaf):
        leaf = np.asarray(leaf)
        key = ".".join(
            str(getattr(k, "key",
                        getattr(k, "name", getattr(k, "idx", k))))
            for k in path)
        if leaf.dtype.kind != "f" or (only is not None and key not in only):
            return leaf
        return (leaf + eps * rng.standard_normal(leaf.shape)).astype(
            leaf.dtype)

    return jax.tree_util.tree_map_with_path(bump, params)


def _assert_tree_bytes_equal(a, b, msg=""):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb), msg
    for x, y in zip(la, lb):
        assert np.asarray(x).tobytes() == np.asarray(y).tobytes(), msg


def _wait(pred, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


class TestFraming:
    def test_delta_roundtrip_bit_identical_across_dtypes(self):
        enc = mw.ModelWireEncoder(keyframe_interval=100, small_model_bytes=0)
        dec = mw.ModelWireDecoder()
        cur = _params()
        history = []
        for v in range(1, 6):
            frame, info = enc.encode(v, ARCH, cur)
            history.append((v, frame, jax.tree.map(np.copy, cur),
                            info["kind"]))
            cur = _step(cur, seed=v)
        kinds = [k for *_rest, k in history]
        assert kinds[0] == "keyframe" and set(kinds[1:]) == {"delta"}
        for v, frame, want, _kind in history:
            out = dec.decode(frame)
            assert out is not None
            ver, arch, tree = out
            assert ver == v and arch == ARCH
            _assert_tree_bytes_equal(tree, want, f"version {v}")

    def test_unchanged_leaves_skipped_and_identical_publish_tiny(self):
        enc = mw.ModelWireEncoder(keyframe_interval=100, small_model_bytes=0)
        p = _params()
        enc.encode(1, ARCH, p)
        frame, info = enc.encode(2, ARCH, p)  # nothing changed
        assert info["kind"] == "delta"
        _kind, hdr, _payload = mw.parse_frame(frame)
        assert hdr["leaves"] == []
        assert info["frame_bytes"] < 1024

        # A partial update ships only the touched leaves.
        q = _step(p, seed=9, only={"head.kernel"})
        frame, _ = enc.encode(3, ARCH, q)
        _kind, hdr, _payload = mw.parse_frame(frame)
        manifest, _ = leaf_manifest(p)
        touched = {tuple(manifest[idx][0]) for idx, _enc, _n in hdr["leaves"]}
        assert touched == {("head", "kernel")}

    def test_keyframe_interval_and_force(self):
        enc = mw.ModelWireEncoder(keyframe_interval=3, small_model_bytes=0)
        cur = _params()
        kinds = []
        for v in range(1, 8):
            _frame, info = enc.encode(v, ARCH, cur)
            kinds.append(info["kind"])
            cur = _step(cur, seed=v)
        assert kinds == ["keyframe", "delta", "delta",
                         "keyframe", "delta", "delta", "keyframe"]
        enc.force_keyframe()
        _frame, info = enc.encode(8, ARCH, cur)
        assert info["kind"] == "keyframe"

    def test_manifest_change_forces_keyframe(self):
        enc = mw.ModelWireEncoder(keyframe_interval=100, small_model_bytes=0)
        p = _params()
        enc.encode(1, ARCH, p)
        grown = dict(p, extra=np.ones(3, np.float32))
        _frame, info = enc.encode(2, ARCH, grown)
        assert info["kind"] == "keyframe"

    def test_crc_corruption_rejected_without_state_damage(self):
        enc = mw.ModelWireEncoder(keyframe_interval=100, small_model_bytes=0)
        dec = mw.ModelWireDecoder()
        p = _params()
        f1, _ = enc.encode(1, ARCH, p)
        q = _step(p, seed=1)
        f2, _ = enc.encode(2, ARCH, q)
        dec.decode(f1)
        corrupt = bytearray(f2)
        corrupt[-1] ^= 0xFF  # payload byte flip
        with pytest.raises(mw.WireFrameError):
            dec.decode(bytes(corrupt))
        assert dec.version == 1  # state not advanced
        out = dec.decode(f2)  # the pristine frame still applies
        assert out is not None and out[0] == 2
        _assert_tree_bytes_equal(out[2], q)

    def test_base_mismatch_raises_once_then_blacks_out_until_keyframe(self):
        enc = mw.ModelWireEncoder(keyframe_interval=100, small_model_bytes=0)
        dec = mw.ModelWireDecoder()
        cur = _params()
        frames = []
        for v in range(1, 6):
            frames.append(enc.encode(v, ARCH, cur)[0])
            cur = _step(cur, seed=v)
        enc.force_keyframe()
        key_frame, info = enc.encode(6, ARCH, cur)
        assert info["kind"] == "keyframe"
        dec.decode(frames[0])
        dec.decode(frames[1])
        # frames[2] (v3) dropped on the wire: v4's base=3 mismatches
        with pytest.raises(mw.WireBaseMismatch) as ei:
            dec.decode(frames[3])
        assert ei.value.base == 3 and ei.value.held == 2
        # further deltas are dropped SILENTLY (no exception spam)
        assert dec.decode(frames[4]) is None
        assert dec.awaiting_keyframe and dec.resyncs == 1
        out = dec.decode(key_frame)
        assert out is not None and out[0] == 6
        _assert_tree_bytes_equal(out[2], cur)
        assert not dec.awaiting_keyframe

    def test_stale_duplicate_frames_dropped(self):
        enc = mw.ModelWireEncoder(keyframe_interval=100, small_model_bytes=0)
        dec = mw.ModelWireDecoder()
        p = _params()
        f1, _ = enc.encode(1, ARCH, p)
        f2, _ = enc.encode(2, ARCH, _step(p, seed=1))
        assert dec.decode(f1) is not None
        assert dec.decode(f2) is not None
        assert dec.decode(f1) is None  # re-delivery: stale
        assert dec.decode(f2) is None
        assert dec.version == 2

    def test_codec_rides_header_and_incompressible_skip(self):
        # Low-lr float deltas compress; the codec id lands in the header.
        enc = mw.ModelWireEncoder(keyframe_interval=100, compress="auto",
                                 small_model_bytes=0)
        big = {"w": np.zeros((64, 1024), np.float32)}
        enc.encode(1, ARCH, big)
        frame, _ = enc.encode(2, ARCH, _step(big, seed=1, eps=1e-6))
        _kind, hdr, _p = mw.parse_frame(frame)
        assert hdr["codec"] != mw.CODEC_RAW
        # Incompressible random bytes skip compression entirely.
        rng = np.random.default_rng(0)
        noisy = {"t": rng.integers(0, 255, 400_000).astype(np.uint8)}
        enc2 = mw.ModelWireEncoder(keyframe_interval=100, compress="auto",
                                 small_model_bytes=0)
        enc2.encode(1, ARCH, noisy)
        frame, _ = enc2.encode(
            2, ARCH, {"t": rng.integers(0, 255, 400_000).astype(np.uint8)})
        _kind, hdr, _p = mw.parse_frame(frame)
        assert hdr["codec"] == mw.CODEC_RAW

    def test_compress_off_knob(self):
        enc = mw.ModelWireEncoder(keyframe_interval=100, compress=False,
                                 small_model_bytes=0)
        frame, _ = enc.encode(1, ARCH, _params())
        _kind, hdr, _p = mw.parse_frame(frame)
        assert hdr["codec"] == mw.CODEC_RAW

    def test_v1_bundle_bytes_are_not_wire_frames(self):
        alg_bytes = ModelBundle(1, ARCH, _params()).to_bytes()
        assert not mw.is_wire_frame(alg_bytes)
        frame, _ = mw.ModelWireEncoder(small_model_bytes=0).encode(1, ARCH, _params())
        assert mw.is_wire_frame(frame)


class TestChunking:
    def test_split_reassemble_roundtrip(self):
        frame, _ = mw.ModelWireEncoder(compress=False, small_model_bytes=0).encode(
            1, ARCH, _params())
        parts = mw.split_frame(frame, 256, version=1)
        assert len(parts) > 1 and all(mw.is_chunk_frame(p) for p in parts)
        re = mw.ChunkReassembler()
        got = [re.feed(p) for p in parts]
        assert got[:-1] == [None] * (len(parts) - 1)
        assert got[-1] == frame

    def test_small_frame_not_wrapped(self):
        assert mw.split_frame(b"tiny", 256, version=1) == [b"tiny"]
        assert mw.ChunkReassembler().feed(b"tiny") == b"tiny"

    def test_missing_chunk_drops_partial_never_delivers(self):
        frame, _ = mw.ModelWireEncoder(compress=False, small_model_bytes=0).encode(
            1, ARCH, _params())
        parts = mw.split_frame(frame, 256, version=1)
        re = mw.ChunkReassembler()
        for p in parts[:2]:
            assert re.feed(p) is None
        # chunk 2 lost; chunk 3 arrives out of sequence -> partial dropped
        assert re.feed(parts[3]) is None
        assert re.dropped_partials >= 1
        # a fresh complete run still assembles
        assert [re.feed(p) for p in parts][-1] == frame


class TestActorSwap:
    def _actor(self, seed=0):
        from relayrl_tpu.models import build_policy

        policy = build_policy(dict(ARCH))
        params = jax.device_get(policy.init_params(jax.random.PRNGKey(seed)))
        bundle = ModelBundle(version=1, arch=dict(ARCH), params=params)
        return PolicyActor(bundle, seed=seed), params

    def test_wire_swap_matches_v1_path_including_resync(self):
        """The acceptance scenario at decoder level: >=3 updates with a
        forced keyframe and a forced resync after a dropped frame — the
        v2 actor's params stay byte-identical to a v1 full-bundle twin
        fed the same versions."""
        actor_v2, params = self._actor()
        actor_v1, _ = self._actor()
        enc = mw.ModelWireEncoder(keyframe_interval=100, small_model_bytes=0)
        enc.encode(1, dict(ARCH), params)  # seed the base at the handshake
        cur = params
        versions = {}
        for v in range(2, 6):
            cur = _step(cur, seed=v)
            versions[v] = cur
        f2 = enc.encode(2, dict(ARCH), versions[2])[0]
        f3 = enc.encode(3, dict(ARCH), versions[3])[0]  # will be dropped
        f4 = enc.encode(4, dict(ARCH), versions[4])[0]
        enc.force_keyframe()
        f5 = enc.encode(5, dict(ARCH), versions[5])[0]

        assert actor_v2.swap_from_wire(2, f2) is not None
        with pytest.raises(mw.WireBaseMismatch):
            actor_v2.swap_from_wire(4, f4)  # f3 never arrived
        assert actor_v2.version == 2  # still serving the last good model
        assert actor_v2.swap_from_wire(5, f5) is not None  # keyframe snaps
        assert actor_v2.version == 5

        v1_bytes = ModelBundle(5, dict(ARCH), versions[5]).to_bytes()
        actor_v1.swap_from_bytes(v1_bytes)
        assert actor_v1.version == 5
        _assert_tree_bytes_equal(actor_v2.params, actor_v1.params)
        _assert_tree_bytes_equal(actor_v2.params, versions[5])

    def test_transformer_policy_wire_swap_bit_identical(self):
        """Same scenario for a transformer policy (sequence serving path,
        positional table, layernorms): deltas + forced keyframe + forced
        resync, byte-identical to the v1 twin."""
        from relayrl_tpu.models import build_policy

        t_arch = {"kind": "transformer_discrete", "obs_dim": 6, "act_dim": 3,
                  "d_model": 16, "n_layers": 1, "n_heads": 2,
                  "max_seq_len": 32, "has_critic": True}
        policy = build_policy(dict(t_arch))
        params = jax.device_get(policy.init_params(jax.random.PRNGKey(0)))
        bundle = ModelBundle(version=1, arch=dict(t_arch), params=params)
        v2 = PolicyActor(bundle, seed=0)
        v1 = PolicyActor(ModelBundle(1, dict(t_arch), params), seed=0)

        enc = mw.ModelWireEncoder(keyframe_interval=100, small_model_bytes=0)
        enc.encode(1, dict(t_arch), params)
        cur = params
        frames = {}
        for v in range(2, 6):
            cur = jax.tree.map(
                lambda x, _v=v: (np.asarray(x) + np.float32(1e-4) *
                                 np.random.default_rng(_v)
                                 .standard_normal(np.shape(x))
                                 .astype(np.float32)).astype(np.float32)
                if np.asarray(x).dtype == np.float32 else np.asarray(x), cur)
            if v == 5:
                enc.force_keyframe()
            frames[v] = enc.encode(v, dict(t_arch), cur)[0]
        final = cur

        assert v2.swap_from_wire(2, frames[2]) is not None
        with pytest.raises(mw.WireBaseMismatch):
            v2.swap_from_wire(4, frames[4])  # 3 dropped
        assert v2.swap_from_wire(5, frames[5]) is not None  # keyframe
        v1.swap_from_bytes(ModelBundle(5, dict(t_arch), final).to_bytes())
        _assert_tree_bytes_equal(v2.params, v1.params)
        # The swapped policy still serves.
        rec = v2.request_for_action(np.zeros(6, np.float32))
        assert rec.act is not None

    def test_installed_params_isolated_from_decoder_buffers(self):
        """device_put inside the swap gate must COPY out of the decoder's
        preallocated buffers: the next delta applies in place, and a
        swap that aliased them would silently mutate the installed
        (version-N) params into version-N+1 bytes."""
        actor, params = self._actor()
        enc = mw.ModelWireEncoder(keyframe_interval=100, small_model_bytes=0)
        enc.encode(1, dict(ARCH), params)
        p2 = _step(params, seed=2)
        p3 = _step(p2, seed=3)
        assert actor.swap_from_wire(2, enc.encode(2, dict(ARCH), p2)[0])
        installed = jax.tree.map(lambda x: np.asarray(x).copy(), actor.params)
        # Decode v3 WITHOUT swapping (decoder mutates its buffers).
        actor._wire_decoder.decode(enc.encode(3, dict(ARCH), p3)[0])
        _assert_tree_bytes_equal(actor.params, installed,
                                 "delta apply leaked into installed params")
        _assert_tree_bytes_equal(actor.params, p2)

    def test_v1_delivery_reseeds_decoder_midstream(self):
        """Mixed fleet: a v1 full bundle arriving between v2 deltas must
        reset the wire state so later deltas (based on it) apply."""
        actor, params = self._actor()
        enc = mw.ModelWireEncoder(keyframe_interval=100, small_model_bytes=0)
        enc.encode(1, dict(ARCH), params)
        p2 = _step(params, seed=2)
        assert actor.swap_from_wire(2, enc.encode(2, dict(ARCH), p2)[0])
        # v3 arrives as a LEGACY v1 bundle (rolling compat)
        p3 = _step(p2, seed=3)
        enc.encode(3, dict(ARCH), p3)  # encoder advances its base too
        assert actor.swap_from_wire(
            3, ModelBundle(3, dict(ARCH), p3).to_bytes()) is not None
        # v4 delta based on v3 applies cleanly post-reseed
        p4 = _step(p3, seed=4)
        assert actor.swap_from_wire(4, enc.encode(4, dict(ARCH), p4)[0])
        _assert_tree_bytes_equal(actor.params, p4)

    def test_vector_host_single_swap_serves_all_lanes(self):
        from relayrl_tpu.models import build_policy

        policy = build_policy(dict(ARCH))
        params = jax.device_get(policy.init_params(jax.random.PRNGKey(0)))
        host = VectorActorHost(ModelBundle(1, dict(ARCH), params),
                               num_envs=4, seed=0)
        enc = mw.ModelWireEncoder(keyframe_interval=100, small_model_bytes=0)
        enc.encode(1, dict(ARCH), params)
        p2 = _step(params, seed=2)
        assert host.swap_from_wire(2, enc.encode(2, dict(ARCH), p2)[0])
        assert host.version == 2
        _assert_tree_bytes_equal(host.params, p2)
        recs = host.request_for_actions(np.zeros((4, 4), np.float32))
        assert len(recs) == 4


class TestManifest:
    def test_template_assembly_preserves_custom_nodes(self):
        from flax.core import FrozenDict, freeze

        tree = freeze({"a": {"w": np.ones((2, 2), np.float32)},
                       "b": np.zeros(3, np.float32)})
        manifest, leaves = leaf_manifest(tree)
        plain = tree_from_leaves(manifest, leaves)
        assert isinstance(plain, dict) and not isinstance(plain, FrozenDict)
        rebuilt = tree_from_leaves(manifest, leaves, params_template=tree)
        assert isinstance(rebuilt, FrozenDict)
        _assert_tree_bytes_equal(rebuilt, tree)

    def test_manifest_matches_across_live_and_restored_trees(self):
        """The publisher flattens the LIVE params tree (a list node
        flattens via SequenceKey) while a subscriber may seed from a
        flax-restored v1 bundle (the state dict renders sequences as
        {'0': ...} str-key dicts). Path keys are normalized to strings
        so both derive the SAME manifest hash — a mismatch would make
        every delta resync forever on such trees."""
        live = {"layers": [{"w": np.ones((2, 2), np.float32)},
                           {"w": np.zeros((2, 2), np.float32)}],
                "head": np.ones(3, np.float32)}
        buf = ModelBundle(1, dict(ARCH), live).to_bytes()
        restored = ModelBundle.from_bytes(
            buf, params_template=ModelBundle.RAW_TREE).params
        m_live, l_live = leaf_manifest(live)
        m_rest, l_rest = leaf_manifest(restored)
        assert mw.manifest_hash(m_live) == mw.manifest_hash(m_rest)
        for a, b in zip(l_live, l_rest):
            assert a.tobytes() == b.tobytes()

    def test_manifest_hash_stable_and_layout_sensitive(self):
        m1, _ = leaf_manifest(_params())
        m2, _ = leaf_manifest(_params(seed=7))  # values differ, layout same
        assert mw.manifest_hash(m1) == mw.manifest_hash(m2)
        grown, _ = leaf_manifest(dict(_params(),
                                      extra=np.ones(2, np.float32)))
        assert mw.manifest_hash(grown) != mw.manifest_hash(m1)


def _transport_addrs(kind, p1, p2, p3):
    if kind == "zmq":
        return ({"agent_listener_addr": f"tcp://127.0.0.1:{p1}",
                 "trajectory_addr": f"tcp://127.0.0.1:{p2}",
                 "model_pub_addr": f"tcp://127.0.0.1:{p3}"},
                {"agent_listener_addr": f"tcp://127.0.0.1:{p1}",
                 "trajectory_addr": f"tcp://127.0.0.1:{p2}",
                 "model_sub_addr": f"tcp://127.0.0.1:{p3}"})
    if kind == "grpc":
        return ({"bind_addr": f"127.0.0.1:{p1}", "native_grpc": False},
                {"server_addr": f"127.0.0.1:{p1}"})
    return ({"bind_addr": f"127.0.0.1:{p1}"},
            {"server_addr": f"127.0.0.1:{p1}"})


@pytest.mark.parametrize("kind", ["zmq", "grpc", "native"])
def test_e2e_bit_identical_with_keyframe_and_resync(tmp_cwd, kind):
    """The acceptance scenario over LIVE transports: a REINFORCE-shaped
    MLP actor driven through v2 deltas, a dropped frame (forced resync),
    and a forced keyframe ends byte-identical to the v1 full-bundle
    reference — on zmq (broadcast), grpc (long-poll, server-side
    delta-vs-full), and the native framed-TCP core (opaque pass-through
    + handshake bytes)."""
    if kind == "native":
        from relayrl_tpu.transport.native_backend import native_available

        if not native_available():
            pytest.skip("native library not built (make -C native)")
    cfg = ConfigLoader(create_if_missing=False)
    srv_over, ag_over = _transport_addrs(
        kind, _free_port(), _free_port(), _free_port())
    from relayrl_tpu.models import build_policy

    policy = build_policy(dict(ARCH))
    params = jax.device_get(policy.init_params(jax.random.PRNGKey(0)))
    versions = {1: params}
    for v in range(2, 7):
        versions[v] = _step(versions[v - 1], seed=v)
    enc = mw.ModelWireEncoder(keyframe_interval=100, small_model_bytes=0)

    def v1_bytes(v):
        return ModelBundle(v, dict(ARCH), versions[v]).to_bytes()

    srv = make_server_transport(kind, cfg, **srv_over)
    state = {"ver": 1}
    srv.get_model = lambda: (state["ver"], v1_bytes(state["ver"]))
    srv.get_model_update = (
        lambda known: (enc.frame_for(known)
                       or (state["ver"], v1_bytes(state["ver"]))))
    srv.start()
    try:
        agent = Agent(server_type=kind, handshake_timeout_s=30, seed=0,
                      model_path=str(tmp_cwd / "client.rlx"), **ag_over)
        try:
            assert agent.model_version == 1
            enc.encode(1, dict(ARCH), versions[1])  # base = handshake model

            def publish_until(v, frame, pred, what):
                # Re-publish in a loop: a SUB subscription still joining
                # can miss early broadcasts (repo convention — the blast
                # pattern in test_model_swap_isolation); re-deliveries of
                # the same frame are stale-dropped by the decoder.
                state["ver"] = v
                deadline = time.monotonic() + 20
                while time.monotonic() < deadline:
                    if getattr(srv, "needs_handshake_bytes", False):
                        srv.publish_model(v, frame,
                                          handshake_bytes=v1_bytes(v))
                    else:
                        srv.publish_model(v, frame)
                    if _wait(pred, timeout=0.5):
                        return
                raise AssertionError(f"{kind}: {what}")

            # v2, v3: plain deltas
            for v in (2, 3):
                publish_until(v, enc.encode(v, dict(ARCH), versions[v])[0],
                              lambda _v=v: agent.model_version == _v,
                              f"never reached version {v}")
            # v4 is DROPPED: encode (the publisher's base advances) but
            # never publish — the fleet misses it.
            enc.encode(4, dict(ARCH), versions[4])
            state["ver"] = 4
            # v5 delta has base=4 -> undecodable for the actor at 3:
            # grpc recovers server-side (full-bundle fallback when the
            # frame base mismatches the poll's known version); zmq/native
            # raise WireBaseMismatch and wait for a keyframe.
            frame5 = enc.encode(5, dict(ARCH), versions[5])[0]
            if kind == "grpc":
                publish_until(5, frame5,
                              lambda: agent.model_version == 5,
                              "full-bundle resync never converged")
            else:
                publish_until(
                    5, frame5,
                    lambda: (agent.actor._wire_decoder is not None
                             and agent.actor._wire_decoder.resyncs >= 1),
                    "base mismatch never observed")
                assert agent.model_version == 3  # still on the last good
            # forced keyframe snaps everyone to 6
            enc.force_keyframe()
            publish_until(6, enc.encode(6, dict(ARCH), versions[6])[0],
                          lambda: agent.model_version == 6,
                          "keyframe resync never converged")

            ref = ModelBundle.from_bytes(
                v1_bytes(6), params_template=ModelBundle.RAW_TREE)
            _assert_tree_bytes_equal(agent.actor.params, ref.params,
                                     f"{kind}: v2 diverged from v1 bytes")
            if kind != "grpc":
                dec = agent.actor._wire_decoder
                assert dec is not None and dec.resyncs >= 1
        finally:
            agent.disable_agent()
    finally:
        srv.stop()


@pytest.mark.parametrize("kind", ["zmq", "native"])
def test_e2e_chunked_keyframe_reassembles(tmp_cwd, kind):
    """transport.chunk_bytes splits a broadcast frame into many wire
    messages; the listener reassembles and the swap still lands (and is
    still byte-identical)."""
    if kind == "native":
        from relayrl_tpu.transport.native_backend import native_available

        if not native_available():
            pytest.skip("native library not built (make -C native)")
    cfg = ConfigLoader(create_if_missing=False)
    srv_over, ag_over = _transport_addrs(
        kind, _free_port(), _free_port(), _free_port())
    srv_over["chunk_bytes"] = 2048  # force multi-chunk model frames
    from relayrl_tpu.models import build_policy

    big_arch = dict(ARCH, hidden_sizes=[64, 64])  # ~18 KB of params
    policy = build_policy(big_arch)
    params = jax.device_get(policy.init_params(jax.random.PRNGKey(0)))
    p2 = _step(params, seed=2)
    enc = mw.ModelWireEncoder(keyframe_interval=100, compress=False,
                                 small_model_bytes=0)

    srv = make_server_transport(kind, cfg, **srv_over)
    srv.get_model = lambda: (1, ModelBundle(1, big_arch, params).to_bytes())
    srv.start()
    try:
        agent = Agent(server_type=kind, handshake_timeout_s=30, seed=0,
                      model_path=str(tmp_cwd / "client.rlx"), **ag_over)
        try:
            enc.encode(1, big_arch, params)
            enc.force_keyframe()
            frame = enc.encode(2, big_arch, p2)[0]
            assert len(frame) > 4 * 2048  # really exercises chunking
            hs = ModelBundle(2, big_arch, p2).to_bytes()
            deadline = time.monotonic() + 20
            while agent.model_version != 2:
                assert time.monotonic() < deadline, \
                    f"{kind}: chunked keyframe never installed"
                if getattr(srv, "needs_handshake_bytes", False):
                    srv.publish_model(2, frame, handshake_bytes=hs)
                else:
                    srv.publish_model(2, frame)
                _wait(lambda: agent.model_version == 2, timeout=0.5)
            _assert_tree_bytes_equal(agent.actor.params, p2)
        finally:
            agent.disable_agent()
    finally:
        srv.stop()


class TestBundleFallback:
    """Satellite: the no-template ModelBundle.from_bytes fallback is
    explicit — warns, and RAW_TREE opts in silently."""

    def test_no_template_warns_and_restores_plain_dicts(self):
        buf = ModelBundle(3, dict(ARCH), _params()).to_bytes()
        with warnings.catch_warnings(record=True) as got:
            warnings.simplefilter("always")
            out = ModelBundle.from_bytes(buf)
        assert any("params_template" in str(w.message) for w in got)
        assert isinstance(out.params, dict)

    def test_raw_tree_sentinel_is_silent(self):
        buf = ModelBundle(3, dict(ARCH), _params()).to_bytes()
        with warnings.catch_warnings(record=True) as got:
            warnings.simplefilter("always")
            out = ModelBundle.from_bytes(
                buf, params_template=ModelBundle.RAW_TREE)
        assert not [w for w in got if "params_template" in str(w.message)]
        _assert_tree_bytes_equal(out.params, _params())

    def test_template_roundtrip_preserves_custom_nodes(self):
        from flax.core import FrozenDict, freeze

        tree = freeze({"a": {"w": np.ones((2, 2), np.float32)}})
        buf = ModelBundle(1, dict(ARCH), tree).to_bytes()
        out = ModelBundle.from_bytes(buf, params_template=tree)
        assert isinstance(out.params, FrozenDict)
        _assert_tree_bytes_equal(out.params, tree)
