"""ApplicationAbstract: the user-app contract + its canonical loop.

Reference parity: _common/_examples/BaseApplication.py:4-31 defines the
three-method contract its examples subclass; ours additionally ships the
loop (drive_episode), so these tests pin the loop's wire-visible
behavior — reward credit, terminal flags, truncation bootstrapping, and
mask routing — against the actual serialized trajectory.
"""

import numpy as np
import pytest

from relayrl_tpu.runtime import ApplicationAbstract
from relayrl_tpu.runtime.policy_actor import PolicyActor
from relayrl_tpu.types.model_bundle import ModelBundle
from relayrl_tpu.types.trajectory import deserialize_actions

OBS_DIM, ACT_DIM = 3, 2


class CountdownEnv:
    """raw state = steps remaining; reward 1.0 per step; terminates at 0."""

    def __init__(self, n=4):
        self.n = n

    def reset(self):
        self.left = self.n
        return self.left

    def step(self, act):
        self.left -= 1
        return self.left, 1.0, self.left == 0, False


class EndlessEnv(CountdownEnv):
    """Never terminates on its own — exercises the max_steps truncation."""

    def step(self, act):
        self.left -= 1
        return self.left, 1.0, False, False


class CountdownApp(ApplicationAbstract):
    def __init__(self, agent, terminal_bonus=0.0, with_mask=False):
        super().__init__(agent)
        self.terminal_bonus = terminal_bonus
        self.with_mask = with_mask
        self.built = 0

    def run_application(self, env, episodes=1, max_steps=None):
        return [self.drive_episode(env, max_steps=max_steps)
                for _ in range(episodes)]

    def build_observation(self, raw):
        self.built += 1
        obs = np.full(OBS_DIM, float(raw), np.float32)
        if self.with_mask:
            return obs, np.ones(ACT_DIM, np.float32)
        return obs

    def calculate_performance_return(self, last_reward, *, terminated,
                                     truncated):
        return last_reward + (self.terminal_bonus if terminated else 0.0)


@pytest.fixture
def actor():
    import jax

    from relayrl_tpu.models import build_policy

    arch = {"kind": "mlp_discrete", "obs_dim": OBS_DIM, "act_dim": ACT_DIM,
            "hidden_sizes": [8]}
    policy = build_policy(arch)
    params = policy.init_params(jax.random.PRNGKey(0))
    sent: list[bytes] = []
    a = PolicyActor(ModelBundle(version=1, arch=arch, params=params),
                    max_traj_length=100, on_send=sent.append, seed=0)
    a._sent = sent
    return a


def _records(actor):
    assert len(actor._sent) == 1, "episode should send exactly one trajectory"
    return deserialize_actions(actor._sent[0])


def test_contract_is_abstract():
    with pytest.raises(TypeError):
        ApplicationAbstract(agent=None)  # all three methods abstract


def test_episode_wire_shape_and_reward_credit(actor):
    app = CountdownApp(actor)
    (total,) = app.run_application(CountdownEnv(4), episodes=1)
    assert total == 4.0
    recs = _records(actor)
    # 4 acting records + terminal marker
    assert len(recs) == 5 and recs[-1].done and not recs[-1].truncated
    # rewards for actions 1..3 are back-attached on the next request; the
    # LAST action's reward rides the terminal marker (the learner's fold
    # credits it back — the same wire convention test_reward_alignment pins)
    assert [float(r.rew) for r in recs] == [1.0, 1.0, 1.0, 0.0, 1.0]
    # observations follow the raw countdown 4,3,2,1
    assert [float(r.obs[0]) for r in recs[:-1]] == [4.0, 3.0, 2.0, 1.0]
    # genuine terminal: no successor obs forwarded
    assert recs[-1].obs is None


def test_terminal_shaping_reaches_the_wire(actor):
    app = CountdownApp(actor, terminal_bonus=10.0)
    app.run_application(CountdownEnv(2), episodes=1)
    recs = _records(actor)
    assert float(recs[-1].rew) == 11.0  # last_reward 1.0 + bonus


def test_truncation_forwards_final_obs(actor):
    app = CountdownApp(actor)
    (total,) = app.run_application(EndlessEnv(10), episodes=1, max_steps=3)
    assert total == 3.0
    recs = _records(actor)
    assert recs[-1].done and recs[-1].truncated
    # successor state (raw 10-3=7) forwarded for bootstrapping
    assert recs[-1].obs is not None and float(recs[-1].obs[0]) == 7.0


def test_mask_tuple_routes_to_requests(actor):
    app = CountdownApp(actor, with_mask=True)
    app.run_application(CountdownEnv(2), episodes=1)
    recs = _records(actor)
    for r in recs[:-1]:
        assert r.mask is not None and r.mask.shape == (ACT_DIM,)
    # truncation-free terminal: mask not forwarded either
    assert recs[-1].mask is None
