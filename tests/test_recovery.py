"""End-to-end crash recovery (ISSUE 6 tentpole): durable actor spool,
idempotent ingest, and the learner/actor SIGKILL drills.

Unit layer: TrajectorySpool retention/disk/breaker semantics and the
SequenceLedger dedup window + sidecar persistence.

Drill layer (all three transports): a real TrainingServer subprocess
(benches/_chaos_server.py) is SIGKILLed mid-training while a live Agent
keeps stepping; the respawned server resumes from orbax + the ingest-
ledger sidecar, the agent heals (breaker probe / zmq socket monitor /
native heartbeat), replays its spool, and the final sequence accounting
proves zero loss and zero double-training: every sequence number the
actor ever assigned is accepted exactly once on the surviving line of
history, replay surplus lands in the duplicate counter, and the model
version the actor holds advances monotonically across the crash.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from relayrl_tpu import faults, telemetry
from relayrl_tpu.runtime.spool import SequenceLedger, TrajectorySpool
from tests._util import free_port

BENCHES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "benches")


@pytest.fixture(autouse=True)
def _clean_planes():
    faults.reset_for_tests()
    telemetry.reset_for_tests()
    yield
    faults.reset_for_tests()
    telemetry.reset_for_tests()


class TestTrajectorySpool:
    def test_bounded_eviction_keeps_newest(self):
        spool = TrajectorySpool(send_fn=None, max_entries=3)
        for i in range(6):
            spool.send(b"p%d" % i, "a")
        assert spool.depth == 3
        assert [seq for _, seq, _ in spool._entries] == [4, 5, 6]
        assert spool.sent_counts() == {"a": 6}

    def test_byte_bound_evicts(self):
        spool = TrajectorySpool(send_fn=None, max_entries=100,
                                max_bytes=1 << 16)
        big = b"x" * 30_000
        for _ in range(5):
            spool.send(big, "a")
        assert spool.depth <= 2

    def test_disk_spool_survives_process_death(self, tmp_path):
        """The actor-crash half of durability: a NEW spool over the same
        directory restores the retained window AND continues the seq
        space (no reused sequence numbers — reuse would alias distinct
        trajectories in the server's dedup window)."""
        d = str(tmp_path)
        spool = TrajectorySpool(send_fn=None, max_entries=10,
                                directory=d, name="worker0")
        for i in range(4):
            spool.send(b"payload-%d" % i, "lane0")
        spool.send(b"other", "lane1")
        spool.close()  # process "crash" (file already flushed per append)

        reborn = TrajectorySpool(send_fn=None, max_entries=10,
                                 directory=d, name="worker0")
        assert reborn.depth == 5
        assert reborn.sent_counts() == {"lane0": 4, "lane1": 1}
        assert reborn.send(b"new", "lane0") == 5  # continues, not reuses
        sent = []
        reborn.send_fn = lambda p, tagged: sent.append((p, tagged))
        assert reborn.replay() == 6
        assert (b"payload-0", "lane0#s1") in sent

    def test_disk_spool_tolerates_torn_tail(self, tmp_path):
        d = str(tmp_path)
        spool = TrajectorySpool(send_fn=None, directory=d, name="t")
        spool.send(b"whole", "a")
        spool.close()
        path = os.path.join(d, "t.spool")
        with open(path, "ab") as f:
            f.write(b"\x00\x00\x00\xffTORN")  # half a record
        reborn = TrajectorySpool(send_fn=None, directory=d, name="t")
        assert reborn.depth == 1  # the whole record, not the torn one
        # The torn bytes must be TRUNCATED before appends resume:
        # records written after a surviving torn tail would be
        # unreachable to the NEXT load (it stops at the first torn
        # record) — the double-crash case.
        reborn.send(b"second-life", "a")
        reborn.close()
        third = TrajectorySpool(send_fn=None, directory=d, name="t")
        assert third.depth == 2
        assert third.sent_counts() == {"a": 2}

    def test_breaker_opens_then_heal_replays(self):
        """Dead-server shape: sends fail → breaker opens (actor stops
        paying wire timeouts) → server returns → the half-open probe
        send succeeds → the spool auto-replays the outage window."""
        from relayrl_tpu.transport.retry import CircuitBreaker, RetryPolicy

        alive = {"up": False}
        delivered = []

        def send_fn(payload, tagged):
            if not alive["up"]:
                raise ConnectionError("server down")
            delivered.append((payload, tagged))

        spool = TrajectorySpool(
            send_fn=send_fn, max_entries=100,
            retry=RetryPolicy(base_delay_s=0.001, max_delay_s=0.002,
                              deadline_s=0.01, max_attempts=2),
            breaker=CircuitBreaker("t", failure_threshold=2,
                                   reset_timeout_s=0.05))
        spool.send(b"a", "x")
        spool.send(b"b", "x")  # second failure opens the breaker
        assert spool.breaker.state == "open"
        spool.send(b"c", "x")  # buffered without touching the wire
        assert not delivered and spool.depth == 3
        alive["up"] = True
        time.sleep(0.06)  # half-open window
        spool.send(b"d", "x")  # probe succeeds → closes → auto-replay
        assert spool.breaker.state == "closed"
        payloads = [p for p, _ in delivered]
        assert payloads.count(b"a") >= 1 and payloads.count(b"c") >= 1
        assert set(payloads) == {b"a", b"b", b"c", b"d"}


class TestSequenceLedger:
    def test_monotonic_accept_and_dup_drop(self):
        led = SequenceLedger(window=64)
        assert all(led.accept("a", s) for s in (1, 2, 3))
        assert not led.accept("a", 2)  # replay
        assert led.accept("b", 1)      # independent per-agent space
        assert led.total_duplicates() == 1
        assert led.counts()["a"] == {"max_seq": 3, "accepted": 3,
                                     "contiguous": True}

    def test_out_of_order_within_window(self):
        led = SequenceLedger(window=16)
        assert led.accept("a", 5)
        assert led.accept("a", 3)  # late but inside the window
        assert not led.accept("a", 3)
        assert led.counts()["a"]["contiguous"] is False  # 1,2,4 missing

    def test_below_window_treated_as_duplicate(self):
        led = SequenceLedger(window=4)
        assert led.accept("a", 100)
        assert not led.accept("a", 95)  # <= 100 - 4: conservatively dup
        assert led.accept("a", 97)

    def test_sidecar_roundtrip(self, tmp_path):
        led = SequenceLedger(window=32)
        for s in (1, 2, 4):
            led.accept("a", s)
        led.accept("a", 2)  # a duplicate, for the counter
        path = str(tmp_path / "ledger.json")
        led.save(path)
        back = SequenceLedger.load(path)
        assert back.window == 32
        assert back.total_duplicates() == 1
        assert not back.accept("a", 4)  # still deduped after restore
        assert back.accept("a", 3)      # still open after restore

    def test_retract_reopens_seq(self):
        led = SequenceLedger(window=16)
        assert led.accept("a", 1)
        led.retract("a", 1)  # queue-full downstream: loss, not dedup
        assert led.accept("a", 1)
        assert led.counts()["a"]["accepted"] == 1


class TestIdempotentIngestLive:
    def test_replay_never_double_trains_zmq(self, tmp_cwd):
        """In-process loop: an Agent ships episodes, then force-replays
        its whole spool window twice. The server's trajectory counter
        must count each unique episode ONCE; the surplus lands in the
        duplicate counter."""
        from relayrl_tpu.runtime.agent import Agent
        from relayrl_tpu.runtime.server import TrainingServer

        addrs = {
            "agent_listener_addr": f"tcp://127.0.0.1:{free_port()}",
            "trajectory_addr": f"tcp://127.0.0.1:{free_port()}",
            "model_pub_addr": f"tcp://127.0.0.1:{free_port()}",
        }
        worker_addrs = {
            "agent_listener_addr": addrs["agent_listener_addr"],
            "trajectory_addr": addrs["trajectory_addr"],
            "model_sub_addr": addrs["model_pub_addr"],
        }
        server = TrainingServer(
            "REINFORCE", obs_dim=4, act_dim=2, env_dir=str(tmp_cwd),
            hyperparams={"traj_per_epoch": 100, "hidden_sizes": [16, 16]},
            **addrs)
        try:
            agent = Agent(server_type="zmq", handshake_timeout_s=30,
                          seed=0, probe=False, **worker_addrs)
            try:
                rng = np.random.default_rng(0)
                n_episodes = 6
                for _ in range(n_episodes):
                    for _ in range(3):
                        agent.request_for_action(
                            rng.standard_normal(4).astype(np.float32))
                    agent.flag_last_action(1.0, terminated=True)
                assert agent.spool is not None
                assert agent.spool.replay() == n_episodes
                agent.spool.replay()  # and again
                deadline = time.monotonic() + 30
                while (server.ingest_accounting()["duplicates"]
                       < 2 * n_episodes and time.monotonic() < deadline):
                    time.sleep(0.05)
                server.drain(timeout=30)
                acct = server.ingest_accounting()
                row = acct["agents"][agent.transport.identity]
                assert row == {"max_seq": n_episodes,
                               "accepted": n_episodes, "contiguous": True}
                assert acct["duplicates"] == 2 * n_episodes
                assert server.stats["trajectories"] == n_episodes
            finally:
                agent.disable_agent()
        finally:
            server.disable_server()


def _spawn_server(scratch: str, transport: str, addrs: dict,
                  resume: bool) -> subprocess.Popen:
    cfg = {
        "algorithm": "REINFORCE", "obs_dim": 6, "act_dim": 3,
        "hyperparams": {"traj_per_epoch": 4, "hidden_sizes": [16, 16],
                        "with_vf_baseline": False},
        "server_type": transport, "scratch": scratch,
        "checkpoint_every": 1, "resume": resume,
        "status_path": os.path.join(scratch, "status.json"),
        **addrs,
    }
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(BENCHES)
    return subprocess.Popen(
        [sys.executable, os.path.join(BENCHES, "_chaos_server.py"),
         json.dumps(cfg)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def _read_status(scratch: str) -> dict | None:
    try:
        with open(os.path.join(scratch, "status.json")) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _wait_status(scratch: str, proc: subprocess.Popen, pred,
                 timeout_s: float, what: str) -> dict:
    deadline = time.monotonic() + timeout_s
    status = None
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            out, _ = proc.communicate()
            raise AssertionError(
                f"chaos server died waiting for {what} "
                f"(rc={proc.returncode}):\n{out[-3000:]}")
        status = _read_status(scratch)
        if status is not None and pred(status):
            return status
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}; last={status}")


def _drive_episodes(agent, rng, n: int, steps: int = 4) -> None:
    for _ in range(n):
        for _ in range(steps):
            agent.request_for_action(
                rng.standard_normal(6).astype(np.float32))
        agent.flag_last_action(1.0, terminated=True)


def _transport_addrs(transport: str) -> tuple[dict, dict]:
    """(server-side, agent-side) address overrides on fresh fixed ports
    (fixed so the RESTARTED server binds where the agent reconnects)."""
    if transport in ("native", "grpc"):
        port = free_port()
        return ({"bind_addr": f"127.0.0.1:{port}"},
                {"server_addr": f"127.0.0.1:{port}"})
    ports = [free_port() for _ in range(3)]
    return ({"agent_listener_addr": f"tcp://127.0.0.1:{ports[0]}",
             "trajectory_addr": f"tcp://127.0.0.1:{ports[1]}",
             "model_pub_addr": f"tcp://127.0.0.1:{ports[2]}"},
            {"agent_listener_addr": f"tcp://127.0.0.1:{ports[0]}",
             "trajectory_addr": f"tcp://127.0.0.1:{ports[1]}",
             "model_sub_addr": f"tcp://127.0.0.1:{ports[2]}"})


def _require_transport(transport: str) -> None:
    if transport == "native":
        from relayrl_tpu.transport.native_backend import native_available

        if not native_available():
            pytest.skip("native .so unavailable")
    if transport == "grpc":
        pytest.importorskip("grpc")


# ISSUE 17 wall re-fit: the drill is transport-agnostic above the wire;
# zmq stays in the fast tier, the grpc/native twins ride the slow tier
# (same convention as the columnar SIGKILL trio in PR 14).
@pytest.mark.parametrize(
    "transport",
    ["zmq",
     pytest.param("grpc", marks=pytest.mark.slow),
     pytest.param("native", marks=pytest.mark.slow)])
def test_learner_sigkill_resume_zero_loss_zero_dup(transport, tmp_path,
                                                   tmp_cwd):
    """THE learner crash drill: SIGKILL the training server mid-run,
    restart it with resume, and assert (a) sequence accounting — every
    trajectory the actor sent is accepted exactly once on the surviving
    line of history (contiguous, max_seq == actor's sent count), with
    replay surplus visible as duplicates, and (b) model-version
    continuity — the version the actor holds strictly advances across
    the crash (orbax restores the version counter; wire-v2 keyframes
    resync the fleet)."""
    _require_transport(transport)
    scratch = str(tmp_path)
    server_addrs, agent_addrs = _transport_addrs(transport)
    proc = _spawn_server(scratch, transport, server_addrs, resume=False)
    agent = None
    try:
        _wait_status(scratch, proc, lambda s: True, 120, "server up")
        from relayrl_tpu.runtime.agent import Agent

        extra = {"heartbeat_s": 1.0} if transport == "native" else {}
        agent = Agent(server_type=transport, handshake_timeout_s=60,
                      seed=0, probe=False, **agent_addrs, **extra)
        rng = np.random.default_rng(0)
        # Phase 1: train until at least one checkpoint (version > 0 and
        # a ledger sidecar on disk) so the resume has a base.
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            _drive_episodes(agent, rng, 2)
            status = _read_status(scratch)
            if (status and status["version"] >= 2
                    and status["accounting"]["agents"]):
                break
            time.sleep(0.1)
        status = _read_status(scratch)
        assert status and status["version"] >= 2, "no training before kill"
        v_before = status["version"]
        agent_v_before = agent.model_version

        # Phase 2: SIGKILL. No shutdown path runs — the drill.
        proc.kill()
        proc.wait(timeout=30)

        # Phase 3: the actor keeps playing into the outage (sends fail
        # into the spool / the zmq pipe; the breaker keeps the env loop
        # fast).
        _drive_episodes(agent, rng, 8)
        sent_during_outage = agent.spool.sent_counts()[
            agent.transport.identity]

        # Phase 4: restart with resume; the agent must heal on its own
        # (breaker probe / socket monitor / heartbeat redial) and the
        # fleet must train PAST the pre-kill version (continuity).
        proc = _spawn_server(scratch, transport, server_addrs, resume=True)
        _wait_status(scratch, proc, lambda s: True, 120, "server restart")
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            _drive_episodes(agent, rng, 2)
            status = _read_status(scratch)
            if (status and status["version"] > v_before
                    and agent.model_version > agent_v_before):
                break
            time.sleep(0.1)
        assert status["version"] > v_before, (
            f"server never trained past the crash: {status['version']} "
            f"<= {v_before}")
        assert agent.model_version > agent_v_before, (
            "actor never resynced to the post-crash model line")

        # Phase 5: belt-and-braces full replay, then the accounting
        # assertion — the heart of the drill.
        agent.spool.replay()
        ident = agent.transport.identity
        sent_total = agent.spool.sent_counts()[ident]
        assert sent_total >= sent_during_outage

        def recovered(s):
            row = s["accounting"]["agents"].get(ident)
            return (row is not None and row["max_seq"] == sent_total
                    and row["contiguous"])

        status = _wait_status(scratch, proc, recovered, 120,
                              "zero-loss accounting")
        row = status["accounting"]["agents"][ident]
        assert row["accepted"] == sent_total, (
            f"double-training or loss: {row} vs sent={sent_total}")
        # The replay after recovery re-sent already-accepted sequences:
        # the dedup ledger must show them as duplicates, not train them.
        assert status["accounting"]["duplicates"] >= 1
        # Recovery left its breadcrumbs in the server telemetry.
        names = {m["name"] for m in status["telemetry"]["metrics"]}
        assert "relayrl_server_duplicate_trajectories_total" in names
    finally:
        if agent is not None:
            agent.disable_agent()
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.communicate(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()


_ACTOR_LOOP = """
import json, sys, time
import numpy as np
from relayrl_tpu.runtime.agent import Agent

cfg = json.loads(sys.argv[1])
agent = Agent(server_type="native", handshake_timeout_s=60, seed=1,
              probe=False, server_addr=cfg["server_addr"])
rng = np.random.default_rng(1)
print("actor-ready", flush=True)
while True:
    for _ in range(4):
        agent.request_for_action(rng.standard_normal(6).astype(np.float32))
    agent.flag_last_action(1.0, terminated=True)
"""


def test_actor_sigkill_reap_and_replacement_recovers(tmp_cwd):
    """The actor crash drill (native reaping plane): SIGKILL a live
    actor process → the kernel-closed connection unregisters it; a
    replacement joins and training throughput recovers (updates keep
    advancing past the churn)."""
    _require_transport("native")
    from relayrl_tpu.runtime.server import TrainingServer

    port = free_port()
    server = TrainingServer(
        "REINFORCE", obs_dim=6, act_dim=3, env_dir=str(tmp_cwd),
        hyperparams={"traj_per_epoch": 4, "hidden_sizes": [16, 16]},
        server_type="native", bind_addr=f"127.0.0.1:{port}")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(BENCHES)

    def spawn_actor():
        return subprocess.Popen(
            [sys.executable, "-c", _ACTOR_LOOP,
             json.dumps({"server_addr": f"127.0.0.1:{port}"})],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=str(tmp_cwd))

    def registry_size():
        with server._registry_lock:
            return len(server.agent_ids)

    victim = spawn_actor()
    try:
        deadline = time.monotonic() + 120
        while ((registry_size() < 1 or server.stats["updates"] < 1)
               and time.monotonic() < deadline):
            assert victim.poll() is None, victim.communicate()[0][-2000:]
            time.sleep(0.1)
        assert registry_size() >= 1 and server.stats["updates"] >= 1
        updates_at_kill = server.stats["updates"]

        victim.kill()  # SIGKILL: kernel closes the sockets
        victim.wait(timeout=30)
        deadline = time.monotonic() + 60
        while registry_size() > 0 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert registry_size() == 0, "dead actor never reaped"

        replacement = spawn_actor()
        try:
            deadline = time.monotonic() + 120
            while ((registry_size() < 1
                    or server.stats["updates"] <= updates_at_kill)
                   and time.monotonic() < deadline):
                assert replacement.poll() is None, (
                    replacement.communicate()[0][-2000:])
                time.sleep(0.1)
            assert registry_size() >= 1, "replacement never registered"
            assert server.stats["updates"] > updates_at_kill, (
                "training did not recover after the churn")
        finally:
            replacement.kill()
            replacement.wait(timeout=30)
    finally:
        if victim.poll() is None:
            victim.kill()
            victim.wait(timeout=30)
        server.disable_server()
