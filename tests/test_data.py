"""Batching / epoch-buffer tests (fixed-shape discipline, SURVEY.md §7.4.3)."""

import numpy as np
import pytest

from relayrl_tpu.data import EpochBuffer, pad_trajectory, pick_bucket, stack_trajectories
from relayrl_tpu.types.action import ActionRecord


def _episode(n, obs_dim=4, done=True, with_aux=True):
    acts = []
    for i in range(n):
        data = {"logp_a": np.float32(-0.5 * i), "v": np.float32(0.1 * i)} if with_aux else None
        acts.append(ActionRecord(
            obs=np.full(obs_dim, i, np.float32),
            act=np.int64(i % 2),
            rew=1.0,
            data=data,
            done=(done and i == n - 1),
        ))
    return acts


class TestPickBucket:
    def test_smallest_fit(self):
        assert pick_bucket(10, [64, 256, 1000]) == 64
        assert pick_bucket(64, [64, 256, 1000]) == 64
        assert pick_bucket(65, [64, 256, 1000]) == 256
        assert pick_bucket(5000, [64, 256, 1000]) == 1000


class TestPadTrajectory:
    def test_shapes_and_mask(self):
        padded = pad_trajectory(_episode(5), horizon=8, obs_dim=4, act_dim=2)
        assert padded.obs.shape == (8, 4)
        assert padded.act.shape == (8,)
        assert padded.valid.tolist() == [1, 1, 1, 1, 1, 0, 0, 0]
        assert padded.length == 5
        assert padded.terminated is True
        assert padded.last_val == 0.0

    def test_aux_extracted(self):
        padded = pad_trajectory(_episode(3), horizon=4, obs_dim=4, act_dim=2)
        np.testing.assert_allclose(padded.logp[:3], [0.0, -0.5, -1.0])
        np.testing.assert_allclose(padded.val[:3], [0.0, 0.1, 0.2], rtol=1e-6)

    def test_truncated_bootstraps_from_last_val(self):
        padded = pad_trajectory(_episode(3, done=False), horizon=4, obs_dim=4, act_dim=2)
        assert padded.terminated is False
        assert padded.last_val == pytest.approx(0.2, rel=1e-5)

    def test_overlong_truncates(self):
        padded = pad_trajectory(_episode(10), horizon=4, obs_dim=4, act_dim=2)
        assert padded.length == 4
        assert padded.terminated is False  # cut episodes aren't terminal

    def test_continuous_actions(self):
        acts = [ActionRecord(obs=np.zeros(3, np.float32),
                             act=np.array([0.1, 0.2], np.float32), rew=0.0)]
        padded = pad_trajectory(acts, horizon=2, obs_dim=3, act_dim=2, discrete=False)
        assert padded.act.shape == (2, 2)
        np.testing.assert_allclose(padded.act[0], [0.1, 0.2])

    def test_empty_rejected(self):
        with pytest.raises(ValueError):
            pad_trajectory([], horizon=4, obs_dim=2, act_dim=2)

    def test_terminal_marker_folds_into_last_step(self):
        # flag_last_action appends a marker with no obs/act carrying the
        # final reward; it must not become a fictitious step (review fix).
        acts = _episode(3, done=False)
        acts.append(ActionRecord(rew=5.0, done=True))
        padded = pad_trajectory(acts, horizon=8, obs_dim=4, act_dim=2)
        assert padded.length == 3
        assert padded.rew[2] == pytest.approx(1.0 + 5.0)
        assert padded.terminated is True
        assert padded.last_val == 0.0
        assert padded.valid.sum() == 3

    def test_truncation_marker_keeps_bootstrap(self):
        # A time-limit truncation (marker with truncated=True) is an
        # episode end but NOT a terminal state: last_val must bootstrap
        # from the stored value instead of zeroing.
        acts = _episode(3, done=False)
        acts.append(ActionRecord(obs=np.full(4, 9, np.float32), rew=2.0,
                                 done=True, truncated=True))
        padded = pad_trajectory(acts, horizon=8, obs_dim=4, act_dim=2)
        assert padded.length == 3
        assert padded.rew[2] == pytest.approx(1.0 + 2.0)
        assert padded.terminated is False
        assert padded.last_val == pytest.approx(0.2, rel=1e-5)

    def test_marker_only_trajectory_rejected(self):
        with pytest.raises(ValueError, match="terminal markers"):
            pad_trajectory([ActionRecord(rew=1.0, done=True)],
                           horizon=4, obs_dim=2, act_dim=2)


class TestEpochBuffer:
    def test_ready_after_traj_per_epoch(self):
        buf = EpochBuffer(obs_dim=4, act_dim=2, traj_per_epoch=3, buckets=[8, 16])
        assert buf.add_episode(_episode(5)) is False
        assert buf.add_episode(_episode(6)) is False
        assert buf.add_episode(_episode(7)) is True
        batch = buf.drain()
        assert batch.batch_size == 3
        assert batch.horizon == 8  # all fit the 8-bucket
        assert len(buf) == 0

    def test_mixed_buckets_repad(self):
        buf = EpochBuffer(obs_dim=4, act_dim=2, traj_per_epoch=2, buckets=[8, 32])
        buf.add_episode(_episode(4))
        buf.add_episode(_episode(20))  # lands in the 32-bucket
        batch = buf.drain()
        assert batch.horizon == 32
        np.testing.assert_allclose(batch.valid.sum(axis=1), [4, 20])

    def test_episode_stats(self):
        buf = EpochBuffer(obs_dim=4, act_dim=2, traj_per_epoch=2, buckets=[8])
        buf.add_episode(_episode(3))
        buf.add_episode(_episode(5))
        rets, lens = buf.pop_episode_stats()
        assert rets == [3.0, 5.0]
        assert lens == [3, 5]
        assert buf.pop_episode_stats() == ([], [])

    def test_drain_empty_raises(self):
        buf = EpochBuffer(obs_dim=4, act_dim=2, traj_per_epoch=1)
        with pytest.raises(ValueError):
            buf.drain()

    def test_stack_rejects_mixed_horizons(self):
        a = pad_trajectory(_episode(3), 4, 4, 2)
        b = pad_trajectory(_episode(3), 8, 4, 2)
        with pytest.raises(ValueError, match="mixed horizons"):
            stack_trajectories([a, b])

    def test_max_traj_length_caps_buckets(self):
        buf = EpochBuffer(obs_dim=4, act_dim=2, traj_per_epoch=1,
                          buckets=[64, 256, 1000], max_traj_length=100)
        assert buf.buckets == (64,)
