"""REINFORCE learner tests: mechanics fast, learning on CartPole (slow).

The CartPole improvement test is the Stage-2 north-star check
(BASELINE.md: CartPole-v1 avg return ≥ 475 at convergence; in CI we assert
clear improvement within a bounded budget, full convergence runs in the
bench/examples)."""

import os

import numpy as np
import pytest

from relayrl_tpu.algorithms import REINFORCE, build_algorithm, registered_algorithms
from relayrl_tpu.types.action import ActionRecord


def _episode(n, obs_dim=4, act_dim=2, seed=0):
    rng = np.random.default_rng(seed)
    acts = []
    for i in range(n):
        acts.append(ActionRecord(
            obs=rng.standard_normal(obs_dim).astype(np.float32),
            act=np.int64(rng.integers(act_dim)),
            rew=float(rng.random()),
            data={"logp_a": np.float32(-0.69), "v": np.float32(0.0)},
            done=(i == n - 1),
        ))
    return acts


@pytest.fixture
def algo(tmp_cwd):
    return build_algorithm(
        "REINFORCE", obs_dim=4, act_dim=2, traj_per_epoch=2,
        hidden_sizes=[16, 16], env_dir=str(tmp_cwd),
        logger_kwargs={"output_dir": str(tmp_cwd / "logs")},
    )


class TestMechanics:
    def test_registry(self):
        assert "REINFORCE" in registered_algorithms()

    def test_train_window_persists_across_calls(self, tmp_cwd):
        """The early-stop window must span train() calls: a per-call
        window can be as short as ~5 episodes for off-policy families,
        and a --target stop on it triggers on a lucky streak (the SAC
        LunarLander golden's first run did exactly that)."""
        from relayrl_tpu.runtime import LocalRunner

        from relayrl_tpu.envs.spaces import Box, Discrete

        class FixedReturnEnv:
            """Each episode returns a scripted total reward."""

            def __init__(self, rewards):
                self._rewards = list(rewards)
                self._t = 0
                self.observation_space = Box(-1.0, 1.0, (4,), np.float32)
                self.action_space = Discrete(2)

            def reset(self, seed=None):
                self._t = 0
                self._r = self._rewards.pop(0)
                return np.zeros(4, np.float32), {}

            def step(self, action):
                self._t += 1
                return (np.zeros(4, np.float32), float(self._r),
                        self._t >= 1, False, {})

        # 1-step episodes with scripted returns: call 1 sees all-zeros,
        # call 2 sees all-hundreds. A per-call window would report 100.
        env = FixedReturnEnv([0.0] * 4 + [100.0] * 4 + [0.0] * 99)
        runner = LocalRunner(env, algorithm_name="REINFORCE",
                             traj_per_epoch=1, hidden_sizes=[8],
                             with_vf_baseline=False, env_dir=str(tmp_cwd))
        r1 = runner.train(epochs=4)
        assert r1["avg_return_last_window"] == 0.0
        r2 = runner.train(epochs=4)
        # persistent 50-episode window: (4*0 + 4*100) / 8
        assert r2["avg_return_last_window"] == 50.0

    def test_runner_seed_reaches_the_learner(self, tmp_cwd):
        """An explicit LocalRunner seed must seed BOTH sides of the
        pipeline. Historically `--hp seed=N` was swallowed by the
        runner's own `seed` kwarg and only varied actor-side action
        sampling: the learner stayed at its default seed (the logs of
        two 'seed' runs both landing in `..._s1` dirs was the tell)."""
        import json
        import os.path as osp

        from relayrl_tpu.envs.spaces import Box, Discrete
        from relayrl_tpu.runtime import LocalRunner

        class OneStepEnv:
            observation_space = Box(-1.0, 1.0, (4,), np.float32)
            action_space = Discrete(2)

            def reset(self, seed=None):
                return np.zeros(4, np.float32), {}

            def step(self, action):
                return np.zeros(4, np.float32), 0.0, True, False, {}

        runner = LocalRunner(OneStepEnv(), "REINFORCE", seed=7,
                             traj_per_epoch=1, hidden_sizes=[8],
                             with_vf_baseline=False, env_dir=str(tmp_cwd))
        out = runner.algorithm.logger.output_dir
        assert osp.basename(out).endswith("_s7"), out
        cfg = json.load(open(osp.join(out, "config.json")))
        assert cfg["seed"] == 7
        # seed_salt rides through independently of the runner seed: the
        # salt is pinned while the same seed still reaches the learner.
        # (There is no separate algorithm-level seed path to exercise —
        # LocalRunner's own `seed` kwarg IS the override it forwards.)
        runner2 = LocalRunner(OneStepEnv(), "REINFORCE", seed=7, seed_salt=0,
                              traj_per_epoch=1, hidden_sizes=[8],
                              with_vf_baseline=False, env_dir=str(tmp_cwd),
                              logger_kwargs={
                                  "output_dir": str(tmp_cwd / "lg2")})
        cfg2 = json.load(open(osp.join(
            runner2.algorithm.logger.output_dir, "config.json")))
        assert cfg2["seed"] == 7 and cfg2["seed_salt"] == 0

    def test_pinned_seed_is_bit_deterministic(self, tmp_cwd):
        """seed + seed_salt pin the learner init exactly (base.py
        promises identical seeds give identical initial state); a
        different seed must actually move the params."""
        import jax
        import jax.numpy as jnp

        def build(seed, tag):
            return build_algorithm(
                "REINFORCE", obs_dim=4, act_dim=2, traj_per_epoch=1,
                hidden_sizes=[8], with_vf_baseline=False,
                seed=seed, seed_salt=0,
                logger_kwargs={"output_dir": str(tmp_cwd / tag)})

        a, b, c = build(7, "a"), build(7, "b"), build(8, "c")
        flat_a = jax.tree_util.tree_leaves(a.state.params)
        flat_b = jax.tree_util.tree_leaves(b.state.params)
        flat_c = jax.tree_util.tree_leaves(c.state.params)
        assert all(jnp.array_equal(x, y) for x, y in zip(flat_a, flat_b))
        assert any(not jnp.array_equal(x, y)
                   for x, y in zip(flat_a, flat_c))

    def test_trains_after_traj_per_epoch(self, algo):
        assert algo.receive_trajectory(_episode(5, seed=1)) is False
        assert algo.version == 0
        assert algo.receive_trajectory(_episode(7, seed=2)) is True
        assert algo.version == 1
        assert "LossPi" in algo._last_metrics

    def test_update_changes_pi_params_only_without_baseline(self, tmp_cwd):
        algo = build_algorithm(
            "REINFORCE", obs_dim=4, act_dim=2, traj_per_epoch=1,
            with_vf_baseline=False, hidden_sizes=[8],
            logger_kwargs={"output_dir": str(tmp_cwd / "logs")})
        import jax

        before = jax.device_get(algo.state.params)
        algo.receive_trajectory(_episode(6))
        after = jax.device_get(algo.state.params)
        changed = any(
            not np.allclose(b, a)
            for b, a in zip(jax.tree.leaves(before), jax.tree.leaves(after))
        )
        assert changed

    def test_baseline_updates_value_params(self, tmp_cwd):
        algo = build_algorithm(
            "REINFORCE", obs_dim=4, act_dim=2, traj_per_epoch=1,
            with_vf_baseline=True, train_vf_iters=3, hidden_sizes=[8],
            logger_kwargs={"output_dir": str(tmp_cwd / "logs")})
        import jax

        before = jax.device_get(algo.state.params["params"]["vf_head"]["kernel"])
        algo.receive_trajectory(_episode(6))
        after = jax.device_get(algo.state.params["params"]["vf_head"]["kernel"])
        assert not np.allclose(before, after)
        assert algo._last_metrics["DeltaLossV"] < 0, "vf iterations should reduce LossV"

    def test_progress_txt_written(self, algo, tmp_cwd):
        algo.receive_trajectory(_episode(3, seed=1))
        algo.receive_trajectory(_episode(3, seed=2))
        progress = tmp_cwd / "logs" / "progress.txt"
        assert progress.is_file()
        header = progress.read_text().splitlines()[0].split("\t")
        for col in ("Epoch", "AverageEpRet", "StdEpRet", "MaxEpRet", "MinEpRet",
                    "EpLen", "LossPi", "KL", "Entropy"):
            assert col in header

    def test_bundle_version_tracks_steps(self, algo):
        assert algo.bundle().version == 0
        algo.receive_trajectory(_episode(3, seed=1))
        algo.receive_trajectory(_episode(3, seed=2))
        assert algo.bundle().version == 1

    def test_save_load(self, algo, tmp_cwd):
        algo.save(tmp_cwd / "m.rlx")
        from relayrl_tpu.types.model_bundle import ModelBundle

        bundle = ModelBundle.load(tmp_cwd / "m.rlx")
        assert bundle.arch["kind"] == "mlp_discrete"


@pytest.mark.slow
class TestLearning:
    def test_cartpole_improves(self, tmp_cwd):
        import gymnasium as gym

        from relayrl_tpu.runtime import LocalRunner

        env = gym.make("CartPole-v1")
        env.reset(seed=0)
        runner = LocalRunner(
            env, "REINFORCE", env_dir=str(tmp_cwd), seed=0,
            with_vf_baseline=True, traj_per_epoch=8, train_vf_iters=40,
            hidden_sizes=[64, 64], pi_lr=1e-2, vf_lr=1e-2, gamma=0.99, lam=0.97,
            logger_kwargs={"output_dir": str(tmp_cwd / "logs")},
        )
        first = runner.train(epochs=2, max_steps=500)
        baseline = first["avg_return_last_window"]
        result = runner.train(epochs=28, max_steps=500)
        final = result["avg_return_last_window"]
        assert final > baseline + 30, (
            f"no learning: first-window {baseline:.1f} -> final {final:.1f}")
        assert final > 100, f"final avg return too low: {final:.1f}"

    @pytest.mark.skipif(
        not os.environ.get("RELAYRL_SOLVE_TEST"),
        reason="full CartPole solve takes tens of minutes; set "
               "RELAYRL_SOLVE_TEST=1 (CI learning job / release gate)")
    def test_cartpole_solved(self, tmp_cwd):
        """BASELINE.md north star: CartPole-v1 avg return >= 475.

        The committed golden curve from this exact configuration is
        examples/golden/cartpole_reinforce_baseline/progress.txt (solved
        at epoch ~105-115). Budget: 400 updates (3200 episodes) with
        early stop once the rolling 50-episode average crosses the bar.
        """
        import gymnasium as gym

        from relayrl_tpu.runtime import LocalRunner

        env = gym.make("CartPole-v1")
        env.reset(seed=0)
        runner = LocalRunner(
            env, "REINFORCE", env_dir=str(tmp_cwd), seed=1,
            with_vf_baseline=True,
            logger_kwargs={"output_dir": str(tmp_cwd / "logs")},
        )
        best = -float("inf")
        for _ in range(80):  # 80 x 5 updates = 400-update budget
            result = runner.train(epochs=5, max_steps=500)
            best = max(best, result["avg_return_last_window"])
            if best >= 475.0:
                break
        assert best >= 475.0, f"not solved within budget: best {best:.1f}"
