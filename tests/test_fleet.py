"""Fleet telemetry aggregation (ISSUE 15): snapshot-frame codec, merge
semantics (commutative/associative, epoch-aware counters, bucket-wise
histogram sums), fleet-table staleness, relay fan-in, the SLO alert
engine, the /fleet endpoints, the --fleet pane, and the live-zmq drill
asserting root totals == sum of per-process registries bit-exactly.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import pytest

from tests._util import free_port

pytestmark = pytest.mark.fleet

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def fresh_registry():
    from relayrl_tpu import telemetry

    registry = telemetry.Registry(run_id="test-fleet")
    telemetry.set_registry(registry)
    yield registry
    telemetry.reset_for_tests()


def _registry_with(counters=None, gauges=None, hists=None, run_id="p"):
    from relayrl_tpu.telemetry import Registry

    reg = Registry(run_id=run_id)
    for name, v in (counters or {}).items():
        reg.counter(name).inc(v)
    for name, v in (gauges or {}).items():
        reg.gauge(name).set(v)
    for name, samples in (hists or {}).items():
        h = reg.histogram(name, buckets=(0.01, 0.1, 1.0))
        for s in samples:
            h.observe(s)
    return reg


def _value(doc, name, labels=None):
    from relayrl_tpu.telemetry.aggregate import snapshot_metric

    return snapshot_metric(doc, name, labels)


def _entry(doc, name):
    return next(m for m in doc["metrics"] if m["name"] == name)


# ---------------------------------------------------------------------------
# snapshot frames
# ---------------------------------------------------------------------------

class TestSnapshotFrames:
    def test_round_trip(self):
        from relayrl_tpu.telemetry import aggregate as ag

        reg = _registry_with(counters={"relayrl_x_total": 7})
        sec = ag.snapshot_section(reg.snapshot(), "proc-a", "actor",
                                  123.5, 4)
        frame = ag.encode_snapshot_frame([sec])
        assert ag.is_snapshot_frame(frame)
        back = ag.parse_snapshot_frame(frame)
        assert len(back) == 1
        assert back[0]["proc"] == "proc-a"
        assert back[0]["tier"] == "actor"
        assert back[0]["epoch"] == 123.5 and back[0]["seq"] == 4
        assert _value(back[0]["snapshot"], "relayrl_x_total") == 7

    def test_multi_proc_frame(self):
        from relayrl_tpu.telemetry import aggregate as ag

        secs = [ag.snapshot_section({"metrics": []}, f"p{i}", "actor",
                                    1.0, i) for i in range(3)]
        back = ag.parse_snapshot_frame(ag.encode_snapshot_frame(secs))
        assert [s["proc"] for s in back] == ["p0", "p1", "p2"]

    @pytest.mark.parametrize("bad", [
        b"",
        b"RLS",
        b"NOPE" + b"x" * 10,
        b"RLS1" + b"\xff\xff\xff",                       # undecodable
        b"RLS1" + b"\x81\xa1v\x02",                       # wrong version
    ])
    def test_malformed_frames_raise_value_error(self, bad):
        from relayrl_tpu.telemetry import aggregate as ag

        with pytest.raises(ValueError):
            ag.parse_snapshot_frame(bad)

    def test_section_missing_proc_rejected(self):
        import msgpack

        from relayrl_tpu.telemetry import aggregate as ag

        frame = ag.SNAP_MAGIC + msgpack.packb(
            {"v": 1, "procs": [{"snapshot": {}}]}, use_bin_type=True)
        with pytest.raises(ValueError):
            ag.parse_snapshot_frame(frame)

    def test_unknown_tier_normalizes(self):
        from relayrl_tpu.telemetry import aggregate as ag

        sec = ag.snapshot_section({}, "p", "mystery-tier", 1.0, 1)
        assert sec["tier"] == "other"


# ---------------------------------------------------------------------------
# merge semantics
# ---------------------------------------------------------------------------

class TestMergeSemantics:
    def _three(self):
        a = _registry_with(counters={"relayrl_c_total": 10},
                           gauges={"relayrl_g": 5},
                           hists={"relayrl_h_seconds": [0.005, 0.5]},
                           run_id="a").snapshot()
        b = _registry_with(counters={"relayrl_c_total": 32},
                           gauges={"relayrl_g": 9},
                           hists={"relayrl_h_seconds": [0.05]},
                           run_id="b").snapshot()
        c = _registry_with(counters={"relayrl_c_total": 100},
                           gauges={"relayrl_g": 1},
                           hists={"relayrl_h_seconds": [2.0, 2.0]},
                           run_id="c").snapshot()
        return a, b, c

    def test_counters_sum_gauges_spread_hists_bucketwise(self):
        from relayrl_tpu.telemetry.aggregate import merge_snapshots

        a, b, c = self._three()
        m = merge_snapshots([a, b, c])
        assert _value(m, "relayrl_c_total") == 142
        g = _entry(m, "relayrl_g")
        assert (g["value"], g["min"], g["max"], g["count"]) == (15, 1, 9, 3)
        h = _entry(m, "relayrl_h_seconds")
        assert h["count"] == 5
        assert h["sum"] == pytest.approx(0.005 + 0.5 + 0.05 + 2.0 + 2.0)
        ha, hb, hc = (_entry(s, "relayrl_h_seconds") for s in (a, b, c))
        assert h["counts"] == [x + y + z for x, y, z in
                               zip(ha["counts"], hb["counts"],
                                   hc["counts"])]

    def test_commutative(self):
        from relayrl_tpu.telemetry.aggregate import merge_snapshots

        a, b, c = self._three()
        m1 = merge_snapshots([a, b, c])["metrics"]
        m2 = merge_snapshots([c, a, b])["metrics"]
        # Integer-valued inputs: float addition order cannot matter.
        assert m1 == m2

    def test_associative(self):
        from relayrl_tpu.telemetry.aggregate import merge_snapshots

        a, b, c = self._three()
        flat = merge_snapshots([a, b, c])["metrics"]
        nested = merge_snapshots(
            [merge_snapshots([a, b]), c])["metrics"]
        assert flat == nested

    def test_histogram_grid_mismatch_counted_not_mixed(self):
        from relayrl_tpu.telemetry import Registry
        from relayrl_tpu.telemetry.aggregate import merge_snapshots

        r1, r2 = Registry(run_id="1"), Registry(run_id="2")
        r1.histogram("relayrl_h", buckets=(0.1, 1.0)).observe(0.05)
        r2.histogram("relayrl_h", buckets=(0.2, 2.0)).observe(0.05)
        m = merge_snapshots([r1.snapshot(), r2.snapshot()])
        assert m["grid_mismatches"] == 1
        assert _entry(m, "relayrl_h")["count"] == 1  # first grid kept

    def test_none_values_skipped(self):
        from relayrl_tpu.telemetry.aggregate import merge_snapshots

        snaps = [{"metrics": [
            {"name": "relayrl_c_total", "kind": "counter", "labels": {},
             "value": None},
            {"name": "relayrl_g", "kind": "gauge", "labels": {},
             "value": None}]},
            {"metrics": [
                {"name": "relayrl_c_total", "kind": "counter",
                 "labels": {}, "value": 3.0},
                {"name": "relayrl_g", "kind": "gauge", "labels": {},
                 "value": 2.0}]}]
        m = merge_snapshots(snaps)
        assert _value(m, "relayrl_c_total") == 3.0
        g = _entry(m, "relayrl_g")
        assert g["count"] == 1 and g["value"] == 2.0

    def test_labels_distinguish_children(self):
        from relayrl_tpu.telemetry import Registry
        from relayrl_tpu.telemetry.aggregate import merge_snapshots

        r1, r2 = Registry(run_id="1"), Registry(run_id="2")
        r1.counter("relayrl_c_total", labels={"backend": "zmq"}).inc(1)
        r2.counter("relayrl_c_total", labels={"backend": "grpc"}).inc(2)
        m = merge_snapshots([r1.snapshot(), r2.snapshot()])
        assert _value(m, "relayrl_c_total", {"backend": "zmq"}) == 1
        assert _value(m, "relayrl_c_total", {"backend": "grpc"}) == 2


# ---------------------------------------------------------------------------
# fleet table: epoch-aware counters, staleness, ordering
# ---------------------------------------------------------------------------

class TestFleetTable:
    def _table(self, stale_s=15.0):
        from relayrl_tpu.telemetry import Registry
        from relayrl_tpu.telemetry.aggregate import FleetTable

        return FleetTable(stale_s=stale_s, registry=Registry(run_id="root"))

    def _section(self, proc, epoch, seq, counters, hists=None, tier="actor"):
        from relayrl_tpu.telemetry.aggregate import snapshot_section

        reg = _registry_with(counters=counters, hists=hists, run_id=proc)
        return snapshot_section(reg.snapshot(), proc, tier, epoch, seq)

    def test_counter_monotonic_across_restart(self):
        t = self._table()
        t.ingest_sections([self._section("p", 1.0, 1,
                                         {"relayrl_c_total": 100})])
        assert _value(t.merged(), "relayrl_c_total") == 100
        # Restart: fresh epoch, counter reset to 7 — the fleet total
        # must never go backwards.
        t.ingest_sections([self._section("p", 2.0, 1,
                                         {"relayrl_c_total": 7})])
        assert _value(t.merged(), "relayrl_c_total") == 107
        # Second restart stacks the baseline.
        t.ingest_sections([self._section("p", 3.0, 1,
                                         {"relayrl_c_total": 1})])
        assert _value(t.merged(), "relayrl_c_total") == 108
        assert t.procs()[0]["restarts"] == 2

    def test_histogram_folds_across_restart(self):
        t = self._table()
        t.ingest_sections([self._section(
            "p", 1.0, 1, {}, hists={"relayrl_h_seconds": [0.005, 0.5]})])
        t.ingest_sections([self._section(
            "p", 2.0, 1, {}, hists={"relayrl_h_seconds": [2.0]})])
        h = _entry(t.merged(), "relayrl_h_seconds")
        assert h["count"] == 3
        assert h["sum"] == pytest.approx(2.505)

    def test_out_of_order_sections_dropped(self):
        t = self._table()
        t.ingest_sections([self._section("p", 2.0, 5,
                                         {"relayrl_c_total": 50})])
        # older seq, same epoch
        t.ingest_sections([self._section("p", 2.0, 3,
                                         {"relayrl_c_total": 10})])
        # older epoch entirely
        t.ingest_sections([self._section("p", 1.0, 99,
                                         {"relayrl_c_total": 999})])
        assert _value(t.merged(), "relayrl_c_total") == 50
        assert t._m_stale_sections.total() == 2

    def test_stale_proc_evicted(self):
        t = self._table(stale_s=5.0)
        now = time.monotonic()
        t.ingest_sections([self._section("old", 1.0, 1,
                                         {"relayrl_c_total": 5})], now=now)
        t.ingest_sections([self._section("fresh", 1.0, 1,
                                         {"relayrl_c_total": 3})],
                          now=now + 4)
        evicted = t.sweep(now=now + 6)
        assert evicted == ["old"]
        assert [p["proc"] for p in t.procs()] == ["fresh"]
        assert _value(t.merged(), "relayrl_c_total") == 3
        assert t._m_evicted.total() == 1

    def test_merged_exactly_sums_per_proc(self):
        t = self._table()
        values = [3.0, 11.0, 29.0, 1.5]
        for i, v in enumerate(values):
            t.ingest_sections([self._section(f"p{i}", 1.0, 1,
                                             {"relayrl_c_total": v})])
        expect = 0.0
        for v in values:  # p0..p3 — already the sorted-proc order
            expect += v
        assert _value(t.merged(), "relayrl_c_total") == expect

    def test_frame_ingest_counts_frames_and_sections(self):
        from relayrl_tpu.telemetry.aggregate import encode_snapshot_frame

        t = self._table()
        frame = encode_snapshot_frame([
            self._section("a", 1.0, 1, {"relayrl_c_total": 1}),
            self._section("b", 1.0, 1, {"relayrl_c_total": 2})])
        t.ingest_frame(frame)
        assert t._m_frames.total() == 1
        assert t._m_sections.total() == 2
        assert t.proc_count() == 2

    def test_document_and_prometheus_labels(self):
        t = self._table()
        t.ingest_sections([
            self._section("actor-1", 1.0, 1, {"relayrl_c_total": 4}),
            self._section("relay-1", 1.0, 1, {"relayrl_c_total": 6},
                          tier="relay")])
        doc = t.document()
        assert doc["schema"] == "relayrl-fleet-v1"
        tiers = {p["proc"]: p["tier"] for p in doc["procs"]}
        assert tiers == {"actor-1": "actor", "relay-1": "relay"}
        assert _value(doc["merged"], "relayrl_c_total") == 10
        text = t.prometheus_text()
        assert 'proc="actor-1"' in text and 'tier="relay"' in text
        assert "# TYPE relayrl_c_total counter" in text


# ---------------------------------------------------------------------------
# relay fan-in buffer
# ---------------------------------------------------------------------------

class TestFleetRelayBuffer:
    def test_latest_per_proc_and_dirty_drain(self):
        from relayrl_tpu.telemetry.aggregate import (
            FleetRelayBuffer,
            snapshot_section,
        )

        buf = FleetRelayBuffer()
        buf.ingest_sections([snapshot_section({}, "a", "actor", 1.0, 1)])
        buf.ingest_sections([snapshot_section({}, "a", "actor", 1.0, 2),
                             snapshot_section({}, "b", "actor", 1.0, 1)])
        drained = buf.drain()
        assert [s["proc"] for s in drained] == ["a", "b"]
        assert drained[0]["seq"] == 2  # latest won
        assert buf.drain() == []  # nothing dirty until a new section
        # Stale (older epoch/seq) never replaces the held section.
        buf.ingest_sections([snapshot_section({}, "a", "actor", 1.0, 1)])
        assert buf.drain() == []

    def test_restarted_leaf_replaces_old_epoch(self):
        from relayrl_tpu.telemetry.aggregate import (
            FleetRelayBuffer,
            snapshot_section,
        )

        buf = FleetRelayBuffer()
        buf.ingest_sections([snapshot_section({}, "a", "actor", 1.0, 99)])
        buf.drain()
        buf.ingest_sections([snapshot_section({}, "a", "actor", 2.0, 1)])
        drained = buf.drain()
        assert drained[0]["epoch"] == 2.0 and drained[0]["seq"] == 1


class TestRelayNodeFanIn:
    def _node(self, tmp_path, interval=5.0):
        from tests.test_relay import _make_fakes

        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text(json.dumps(
            {"telemetry": {"fleet_interval_s": interval}}))
        FakeUp, FakeDown = _make_fakes()
        up, down = FakeUp(), FakeDown()
        from relayrl_tpu.relay import RelayNode

        node = RelayNode(config_path=str(cfg_path), name="relay-t",
                         batch_max=1, spool_entries=0,
                         upstream_transport=up, downstream_transport=down)
        return node, up, down

    def test_subtree_frames_merge_into_one_upstream_frame(
            self, tmp_path, fresh_registry):
        from relayrl_tpu.telemetry import aggregate as ag

        node, up, down = self._node(tmp_path)
        try:
            for i, proc in enumerate(("w1", "w2")):
                reg = _registry_with(
                    counters={"relayrl_actor_env_steps_total": 10 * (i + 1)},
                    run_id=proc)
                frame = ag.encode_snapshot_frame([ag.snapshot_section(
                    reg.snapshot(), proc, "actor", 1.0, 1)])
                node._on_subtree_trajectory(ag.fleet_wire_id(proc), frame)
            assert up.sent == []  # buffered, NOT forwarded per-frame
            node._fleet_flush()
            fleet_sends = [(wid, p) for wid, p in up.sent
                           if ag.is_snapshot_frame(p)]
            assert len(fleet_sends) == 1  # ONE frame for the subtree
            wid, payload = fleet_sends[0]
            assert wid == ag.fleet_wire_id("relay-t")
            sections = ag.parse_snapshot_frame(payload)
            procs = [s["proc"] for s in sections]
            # both leaves verbatim + the relay's own section
            assert procs[:2] == ["w1", "w2"] and "relay-t" in procs
            w1 = next(s for s in sections if s["proc"] == "w1")
            assert w1["epoch"] == 1.0 and w1["seq"] == 1
            assert _value(w1["snapshot"],
                          "relayrl_actor_env_steps_total") == 10
            relay_sec = next(s for s in sections
                             if s["proc"] == "relay-t")
            assert relay_sec["tier"] == "relay"
            # second flush with nothing new: only the relay's own section
            node._fleet_flush()
            _, payload2 = [(w, p) for w, p in up.sent
                           if ag.is_snapshot_frame(p)][-1]
            assert [s["proc"] for s in
                    ag.parse_snapshot_frame(payload2)] == ["relay-t"]
        finally:
            node.close()

    def test_snapshot_frames_never_enter_forward_path(
            self, tmp_path, fresh_registry):
        from relayrl_tpu.telemetry import aggregate as ag

        node, up, down = self._node(tmp_path)
        try:
            frame = ag.encode_snapshot_frame([ag.snapshot_section(
                {}, "w1", "actor", 1.0, 1)])
            node._on_subtree_trajectory("w1", frame)
            node._on_subtree_trajectory("w1#s1", b"real-payload")
            assert [(wid, p) for wid, p in up.sent] == [
                ("w1#s1", b"real-payload")]
        finally:
            node.close()

    def test_fleet_plane_off_forwards_frames_verbatim(
            self, tmp_path, fresh_registry):
        from relayrl_tpu.telemetry import aggregate as ag

        node, up, down = self._node(tmp_path, interval=0.0)
        try:
            assert node._fleet_buf is None
            frame = ag.encode_snapshot_frame([ag.snapshot_section(
                {}, "w1", "actor", 1.0, 1)])
            node._on_subtree_trajectory("@fleet/w1", frame)
            assert up.sent == [("@fleet/w1", frame)]
        finally:
            node.close()


# ---------------------------------------------------------------------------
# SLO alert engine
# ---------------------------------------------------------------------------

class TestAlertEngine:
    def _engine(self, rules, registry=None):
        from relayrl_tpu.telemetry import Registry
        from relayrl_tpu.telemetry.aggregate import AlertEngine, AlertRule

        self.events = []
        reg = registry or Registry(run_id="alerts")
        return AlertEngine(
            [AlertRule.from_dict(r) for r in rules], registry=reg,
            emit=lambda ev, **f: self.events.append({"event": ev, **f})), reg

    @staticmethod
    def _snap(value, name="relayrl_m", kind="gauge"):
        return {"metrics": [{"name": name, "kind": kind, "labels": {},
                             "value": value}]}

    def test_threshold_fire_and_resolve_with_gauge(self):
        eng, reg = self._engine([{"name": "depth", "metric": "relayrl_m",
                                  "agg": "max", "op": ">",
                                  "threshold": 10}])
        eng.evaluate(self._snap(5), now=0)
        assert self.events == [] and eng.active() == []
        eng.evaluate(self._snap(50), now=1)
        assert [e["event"] for e in self.events] == ["alert_fired"]
        assert eng.active() == ["depth"]
        snap = reg.snapshot()
        assert _value(snap, "relayrl_alert_active", {"rule": "depth"}) == 1
        eng.evaluate(self._snap(5), now=2)
        assert [e["event"] for e in self.events] == ["alert_fired",
                                                    "alert_resolved"]
        assert _value(reg.snapshot(), "relayrl_alert_active",
                      {"rule": "depth"}) == 0

    def test_for_s_hold_down(self):
        eng, _ = self._engine([{"name": "d", "metric": "relayrl_m",
                                "agg": "max", "op": ">", "threshold": 1,
                                "for_s": 5.0}])
        eng.evaluate(self._snap(9), now=0)
        assert eng.active() == []  # pending, not fired
        eng.evaluate(self._snap(9), now=3)
        assert eng.active() == []
        # condition cleared mid-hold-down: pending resets
        eng.evaluate(self._snap(0), now=4)
        eng.evaluate(self._snap(9), now=6)
        assert eng.active() == []
        eng.evaluate(self._snap(9), now=11.5)
        assert eng.active() == ["d"]

    def test_increase_agg_needs_two_observations(self):
        eng, _ = self._engine([{"name": "drops",
                                "metric": "relayrl_d_total",
                                "agg": "increase", "op": ">",
                                "threshold": 0}])
        base = self._snap(100, name="relayrl_d_total", kind="counter")
        eng.evaluate(base, now=0)
        assert eng.active() == []  # first sight: no delta yet
        eng.evaluate(self._snap(103, name="relayrl_d_total",
                                kind="counter"), now=1)
        assert eng.active() == ["drops"]
        eng.evaluate(self._snap(103, name="relayrl_d_total",
                                kind="counter"), now=2)
        assert eng.active() == []  # no further increase -> resolved

    def test_histogram_quantile_rule(self):
        from relayrl_tpu.telemetry import Registry

        reg = Registry(run_id="h")
        h = reg.histogram("relayrl_age_seconds", buckets=(0.1, 1.0, 10.0))
        for _ in range(100):
            h.observe(5.0)
        eng, _ = self._engine([{"name": "age", "metric":
                                "relayrl_age_seconds", "agg": "p95",
                                "op": ">", "threshold": 1.0}])
        eng.evaluate(reg.snapshot(), now=0)
        assert eng.active() == ["age"]

    def test_gauge_max_rule_reads_per_proc_spread_not_fleet_sum(self):
        from relayrl_tpu.telemetry.aggregate import merge_snapshots

        # 100 healthy procs each holding depth 5: the fleet SUM is 500
        # but the worst PROCESS is 5 — a max rule must read the spread
        # the merged gauge entry carries, not the collapsed sum.
        snaps = [_registry_with(gauges={"relayrl_depth": 5}).snapshot()
                 for _ in range(100)]
        merged = merge_snapshots(snaps)
        eng, _ = self._engine([{"name": "depth", "metric": "relayrl_depth",
                                "agg": "max", "op": ">", "threshold": 400}])
        eng.evaluate(merged, now=0)
        assert eng.active() == []
        # one genuinely bad proc trips it
        snaps.append(_registry_with(
            gauges={"relayrl_depth": 500}).snapshot())
        eng.evaluate(merge_snapshots(snaps), now=1)
        assert eng.active() == ["depth"]
        # min and avg read the spread too
        eng2, _ = self._engine([
            {"name": "mn", "metric": "relayrl_depth", "agg": "min",
             "op": "<", "threshold": 6},
            {"name": "av", "metric": "relayrl_depth", "agg": "avg",
             "op": ">", "threshold": 6}])
        eng2.evaluate(merged, now=0)  # all procs at 5: min 5, avg 5
        assert eng2.active() == ["mn"]

    def test_increase_rebaselines_on_membership_change(self):
        eng, _ = self._engine([{"name": "steps",
                                "metric": "relayrl_s_total",
                                "agg": "increase", "op": ">",
                                "threshold": 1000}])

        def snap(v):
            return self._snap(v, name="relayrl_s_total", kind="counter")

        eng.evaluate(snap(10_000), now=0, membership={"a", "b"})
        eng.evaluate(snap(10_100), now=1, membership={"a", "b"})
        assert eng.active() == []  # genuine delta 100 < threshold
        # proc b evicted: sum collapses — clamped, no fire
        eng.evaluate(snap(100), now=2, membership={"a"})
        assert eng.active() == []
        # proc b rejoins with its lifetime total: the +10k step must
        # REBASELINE (membership changed), not fire
        eng.evaluate(snap(10_200), now=3, membership={"a", "b"})
        assert eng.active() == []
        # steady membership again: genuine deltas resume
        eng.evaluate(snap(12_000), now=4, membership={"a", "b"})
        assert eng.active() == ["steps"]

    def test_missing_metric_never_fires_and_resolves(self):
        eng, _ = self._engine([{"name": "d", "metric": "relayrl_m",
                                "agg": "max", "op": ">", "threshold": 1}])
        eng.evaluate(self._snap(9), now=0)
        assert eng.active() == ["d"]
        eng.evaluate({"metrics": []}, now=1)
        assert eng.active() == []

    def test_default_pack_and_config_rules(self):
        from relayrl_tpu.telemetry.aggregate import (
            default_alert_rules,
            rules_from_config,
        )

        names = {r.name for r in default_alert_rules()}
        assert names == {"ingest_drops", "breaker_open", "guardrail_halt",
                         "nonfinite_publish_blocked", "ingest_queue_depth",
                         "trace_data_age_p95"}
        rules = rules_from_config({
            "alerts_default_pack": True,
            "alerts": [
                {"name": "ingest_drops", "metric": "relayrl_x_total",
                 "agg": "sum", "op": ">", "threshold": 9},  # override
                {"name": "custom", "metric": "relayrl_y", "agg": "max",
                 "op": ">=", "threshold": 2, "for_s": 3},
                {"name": "broken", "metric": "relayrl_z",
                 "agg": "nonsense", "op": ">", "threshold": 0},
            ]})
        by_name = {r.name: r for r in rules}
        assert by_name["ingest_drops"].metric == "relayrl_x_total"
        assert by_name["custom"].for_s == 3.0
        assert "broken" not in by_name  # warned + skipped
        only_user = rules_from_config({
            "alerts_default_pack": False,
            "alerts": [{"name": "only", "metric": "relayrl_y"}]})
        assert [r.name for r in only_user] == ["only"]

    def test_invalid_rule_shapes_raise(self):
        from relayrl_tpu.telemetry.aggregate import AlertRule

        with pytest.raises(ValueError):
            AlertRule.from_dict({"metric": "m"})  # no name
        with pytest.raises(ValueError):
            AlertRule.from_dict({"name": "r", "metric": "m", "op": "!="})
        with pytest.raises(ValueError):
            AlertRule.from_dict({"name": "r", "metric": "m",
                                 "bogus_key": 1})


# ---------------------------------------------------------------------------
# endpoints + pane + config
# ---------------------------------------------------------------------------

class TestEndpointsAndPane:
    def test_fleet_endpoints(self, fresh_registry):
        import urllib.error
        import urllib.request

        from relayrl_tpu.telemetry.aggregate import (
            AlertEngine,
            FleetTable,
            default_alert_rules,
            snapshot_section,
        )
        from relayrl_tpu.telemetry.export import TelemetryExporter

        exporter = TelemetryExporter(fresh_registry, port=0)
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(exporter.url + "/fleet", timeout=5)
            assert err.value.code == 404
            table = FleetTable(registry=fresh_registry)
            reg = _registry_with(counters={"relayrl_c_total": 3},
                                 run_id="w")
            table.ingest_sections([snapshot_section(
                reg.snapshot(), "w-1", "actor", 1.0, 1)])
            engine = AlertEngine(default_alert_rules(),
                                 registry=fresh_registry)
            exporter.set_fleet(table, engine)
            with urllib.request.urlopen(exporter.url + "/fleet",
                                        timeout=5) as resp:
                doc = json.loads(resp.read().decode())
            assert doc["schema"] == "relayrl-fleet-v1"
            assert doc["procs"][0]["proc"] == "w-1"
            assert {a["name"] for a in doc["alerts"]} >= {"ingest_drops"}
            with urllib.request.urlopen(exporter.url + "/fleet/metrics",
                                        timeout=5) as resp:
                text = resp.read().decode()
            assert 'relayrl_c_total{proc="w-1",tier="actor"} 3' in text
        finally:
            exporter.close()

    def test_render_fleet_pane(self):
        from relayrl_tpu.telemetry.top import render_fleet

        doc = {
            "schema": "relayrl-fleet-v1",
            "stale_s": 15.0,
            "procs": [
                {"proc": "server-1", "tier": "server", "age_s": 0.2,
                 "uptime_s": 100.0},
                {"proc": "relay-a", "tier": "relay", "age_s": 0.4,
                 "uptime_s": 90.0},
                {"proc": "w-0", "tier": "actor", "age_s": 0.5,
                 "uptime_s": 80.0, "restarts": 1},
            ],
            "merged": {"metrics": [
                {"name": "relayrl_actor_env_steps_total",
                 "kind": "counter", "labels": {}, "value": 12345}]},
            "alerts": [
                {"name": "ingest_drops", "op": ">", "threshold": 0,
                 "active": True, "value": 3.0},
                {"name": "breaker_open", "op": ">=", "threshold": 2,
                 "active": False, "value": 0.0}],
        }
        pane = render_fleet(doc)
        assert "3 proc(s)" in pane
        assert "server=1 relay=1 actor=1" in pane
        assert "ALERTS: 1 active" in pane and "ingest_drops" in pane
        assert "-- server " in pane and "-- relay " in pane \
            and "-- actor " in pane
        assert "restarts 1" in pane
        assert "env_steps_total" in pane
        # no active alerts renders the armed count instead
        doc["alerts"][0]["active"] = False
        assert "alerts: none active (2 rule(s) armed)" \
            in render_fleet(doc)

    def test_config_knobs_clamped(self, tmp_path):
        from relayrl_tpu.config import ConfigLoader

        cfg = tmp_path / "c.json"
        cfg.write_text(json.dumps({"telemetry": {
            "fleet_interval_s": -3, "fleet_stale_s": 0.25,
            "alerts": [{"name": "x", "metric": "m"}],
            "alerts_default_pack": 0}}))
        params = ConfigLoader(None, str(cfg)).get_telemetry_params()
        assert params["fleet_interval_s"] == 0.0
        assert params["fleet_stale_s"] == 1.0  # floor clamp
        assert params["alerts"] == [{"name": "x", "metric": "m"}]
        assert params["alerts_default_pack"] is False
        defaults = ConfigLoader(
            None, str(tmp_path / "missing.json"),
            create_if_missing=False).get_telemetry_params()
        assert defaults["fleet_interval_s"] == 0.0
        assert defaults["fleet_stale_s"] == 15.0
        assert defaults["alerts"] is None
        assert defaults["alerts_default_pack"] is True

    def test_config_stale_floor_and_alert_shapes(self, tmp_path):
        import warnings as _w

        from relayrl_tpu.config import ConfigLoader

        # stale_s must cover >= 2 emission intervals or the table flaps
        cfg = tmp_path / "flap.json"
        cfg.write_text(json.dumps({"telemetry": {
            "fleet_interval_s": 30.0, "fleet_stale_s": 15.0}}))
        with _w.catch_warnings(record=True) as caught:
            _w.simplefilter("always")
            params = ConfigLoader(None, str(cfg)).get_telemetry_params()
        assert params["fleet_stale_s"] == 60.0
        assert any("fleet_stale_s" in str(w.message) for w in caught)
        # a single rule object is accepted as a one-element list
        cfg2 = tmp_path / "one.json"
        cfg2.write_text(json.dumps({"telemetry": {
            "alerts": {"name": "x", "metric": "m"}}}))
        params = ConfigLoader(None, str(cfg2)).get_telemetry_params()
        assert params["alerts"] == [{"name": "x", "metric": "m"}]
        # any other non-list shape warns and drops (never silently)
        cfg3 = tmp_path / "bad.json"
        cfg3.write_text(json.dumps({"telemetry": {"alerts": "nope"}}))
        with _w.catch_warnings(record=True) as caught:
            _w.simplefilter("always")
            params = ConfigLoader(None, str(cfg3)).get_telemetry_params()
        assert params["alerts"] is None
        assert any("telemetry.alerts" in str(w.message) for w in caught)


# ---------------------------------------------------------------------------
# live-zmq drill: root totals == sum of per-process registries, bit-exact
# ---------------------------------------------------------------------------

class TestLiveFleetDrill:
    # ISSUE 17 wall re-fit: live-zmq e2e rides the slow tier with the
    # committed fleet_zmq.json bench drill; merge/relay semantics stay
    # covered fast by the unit suite above.
    @pytest.mark.slow
    def test_live_zmq_root_totals_bit_exact(self, tmp_path, tmp_cwd):
        from relayrl_tpu import telemetry
        from relayrl_tpu.runtime.server import TrainingServer

        scratch = str(tmp_path)
        interval = 0.25
        cfg = {
            "learner": {"checkpoint_dir": "",
                        "checkpoint_every_epochs": 1_000_000},
            "telemetry": {"enabled": True, "port": 0,
                          "fleet_interval_s": interval,
                          "fleet_stale_s": 60.0},
        }
        cfg_path = os.path.join(scratch, "cfg.json")
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)
        addrs = {
            "agent_listener_addr": f"tcp://127.0.0.1:{free_port()}",
            "trajectory_addr": f"tcp://127.0.0.1:{free_port()}",
            "model_pub_addr": f"tcp://127.0.0.1:{free_port()}",
        }
        server = TrainingServer("REINFORCE", obs_dim=4, act_dim=2,
                                server_type="zmq", env_dir=scratch,
                                config_path=cfg_path, **addrs)
        try:
            assert server._fleet is not None
            stop_file = os.path.join(scratch, "stop")
            workers = []
            results = []
            for w in range(2):
                ident = f"drill-w{w}"
                result_path = os.path.join(scratch, f"{ident}.json")
                results.append(result_path)
                wcfg = {
                    "identity": ident, "agents_per_proc": 2,
                    "scratch": scratch, "config_path": cfg_path,
                    "seed": w, "obs_dim": 4, "episode_len": 3,
                    "duration_s": 120, "stop_file": stop_file,
                    "result_path": result_path,
                    "agent_listener_addr": addrs["agent_listener_addr"],
                    "trajectory_addr": addrs["trajectory_addr"],
                    "model_sub_addr": addrs["model_pub_addr"],
                }
                env = dict(os.environ)
                env["JAX_PLATFORMS"] = "cpu"
                env["PYTHONPATH"] = REPO_ROOT
                workers.append(subprocess.Popen(
                    [sys.executable,
                     os.path.join(REPO_ROOT, "benches",
                                  "_fleet_worker.py"),
                     json.dumps(wcfg)],
                    env=env, stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT, text=True))
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if all(os.path.exists(os.path.join(
                        scratch, f"ready_drill-w{w}")) for w in range(2)):
                    break
                for p in workers:
                    assert p.poll() is None, p.communicate()[0][-3000:]
                time.sleep(0.1)
            time.sleep(8 * interval)  # a few live frames
            with open(stop_file, "w") as f:
                f.write("stop")
            worker_rows = []
            for p, path in zip(workers, results):
                out, _ = p.communicate(timeout=120)
                assert p.returncode == 0 and os.path.exists(path), \
                    out[-3000:]
                with open(path) as f:
                    worker_rows.append(json.load(f))
            time.sleep(2 * interval)
            server._fleet_tick()  # deterministic final tick
            doc = server._fleet.document(alerts=server._alerts)
            tiers = {p["proc"]: p["tier"] for p in doc["procs"]}
            assert tiers.get("drill-w0") == "actor"
            assert tiers.get("drill-w1") == "actor"
            assert "server" in set(tiers.values())
            assert server._fleet._m_frames.total() > 0  # live wire frames
            # THE exactness bar: every relayrl_actor_* counter family in
            # the merged doc equals the float sum of the two workers'
            # committed registries, bit for bit.
            merged = doc["merged"]
            families = {}
            for row in sorted(worker_rows, key=lambda r: r["identity"]):
                for m in row["snapshot"]["metrics"]:
                    if m["kind"] != "counter" or \
                            not m["name"].startswith("relayrl_actor_"):
                        continue
                    key = (m["name"], tuple(sorted(
                        (m.get("labels") or {}).items())))
                    families[key] = families.get(key, 0.0) + m["value"]
            assert families, "workers recorded no actor counters"
            checked = 0
            for (name, labels), expect in sorted(families.items()):
                got = next(
                    (m["value"] for m in merged["metrics"]
                     if m["name"] == name and m["kind"] == "counter"
                     and tuple(sorted(m["labels"].items())) == labels),
                    None)
                assert got == expect, (name, labels, got, expect)
                checked += 1
            # the vector tier's counter families: env_steps + dispatches
            assert checked >= 2
            # steps actually happened and landed in the merged totals
            steps = next(m["value"] for m in merged["metrics"]
                         if m["name"] == "relayrl_actor_env_steps_total")
            assert steps > 0
        finally:
            server.disable_server()
            telemetry.reset_for_tests()
