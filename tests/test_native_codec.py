"""Native columnar decoder parity: native/codec.cc vs the Python decode path.

The native decoder re-implements the trajectory wire decode + terminal-
marker folding in C++ (the reference keeps its whole ingest decode native,
training_zmq.rs:994-1011). These tests pin the two paths together: for a
wide range of trajectories, decoding natively and padding via the columnar
fast path must produce byte-identical learner inputs to deserializing in
Python and padding per-step.
"""

import numpy as np
import pytest

from relayrl_tpu.data.batching import (
    fold_trailing_markers,
    pad_decoded,
    pad_trajectory,
    pick_bucket,
)
from relayrl_tpu.data.step_buffer import StepReplayBuffer
from relayrl_tpu.transport.base import pack_trajectory_envelope
from relayrl_tpu.types.action import ActionRecord
from relayrl_tpu.types.columnar import (
    DecodedTrajectory,
    NativeDecoder,
    RawTrajectory,
    native_codec_available,
)
from relayrl_tpu.types.trajectory import deserialize_actions, serialize_actions

pytestmark = pytest.mark.skipif(
    not native_codec_available(), reason="native codec not built")


@pytest.fixture(scope="module")
def decoder():
    return NativeDecoder()


def _mk_steps(n, obs_dim=4, act_dim=2, discrete=True, with_mask=False,
              with_aux=True, seed=0):
    rng = np.random.default_rng(seed)
    steps = []
    for i in range(n):
        act = (np.int64(rng.integers(act_dim)) if discrete
               else rng.standard_normal(act_dim).astype(np.float32))
        data = None
        if with_aux:
            data = {"logp_a": np.float32(rng.standard_normal()),
                    "v": np.float32(rng.standard_normal())}
        steps.append(ActionRecord(
            obs=rng.standard_normal(obs_dim).astype(np.float32),
            act=act,
            mask=(np.ones(act_dim, np.float32) if with_mask else None),
            rew=float(rng.standard_normal()),
            data=data,
            done=(i == n - 1),
        ))
    return steps


def _assert_pad_parity(actions, decoder, obs_dim=4, act_dim=2, discrete=True,
                       horizon=None):
    payload = serialize_actions(actions)
    item = decoder.decode(payload, agent_id="parity")
    assert isinstance(item, DecodedTrajectory), f"fell back: {item!r}"
    assert item.agent_id == "parity"
    assert item.n_records == len(actions)
    folded, final_obs, truncated, final_mask = fold_trailing_markers(
        deserialize_actions(payload))
    assert item.n_steps == len(folded)
    assert item.marker_truncated == truncated
    if final_obs is None:
        assert item.final_obs is None
    else:
        np.testing.assert_array_equal(
            np.asarray(item.final_obs, np.float32), final_obs)
    if final_mask is None:
        assert item.final_mask is None
    else:
        np.testing.assert_array_equal(
            np.asarray(item.final_mask, np.float32), final_mask)

    h = horizon or pick_bucket(len(actions), (64, 256, 1000))
    want = pad_trajectory(deserialize_actions(payload), h, obs_dim, act_dim,
                          discrete)
    got = pad_decoded(item, h, obs_dim, act_dim, discrete)
    for field in ("obs", "act", "act_mask", "rew", "val", "logp", "valid"):
        np.testing.assert_array_equal(
            getattr(got, field), getattr(want, field), err_msg=field)
    assert got.length == want.length
    assert got.terminated == want.terminated
    assert got.last_val == want.last_val
    return item


class TestColumnarParity:
    def test_plain_discrete_episode(self, decoder):
        _assert_pad_parity(_mk_steps(17), decoder)

    def test_continuous_episode(self, decoder):
        _assert_pad_parity(_mk_steps(9, act_dim=3, discrete=False),
                           decoder, act_dim=3, discrete=False)

    def test_with_masks(self, decoder):
        _assert_pad_parity(_mk_steps(12, with_mask=True), decoder)

    def test_no_aux(self, decoder):
        _assert_pad_parity(_mk_steps(5, with_aux=False), decoder)

    def test_uint8_pixel_obs(self, decoder):
        """The byte-sized pixel wire (envs obs_dtype="uint8"): the C++
        columnar decoder must carry uint8 obs columns and the padded
        learner batch must match the Python path bit-for-bit (pixels
        0..255 upcast once, at batch build)."""
        rng = np.random.default_rng(7)
        obs_dim = 12 * 12 * 2  # small pixel-ish frame, byte range
        steps = [ActionRecord(
            obs=rng.integers(0, 256, obs_dim, dtype=np.uint8),
            act=np.int64(rng.integers(3)), rew=float(rng.random()),
            data={"logp_a": np.float32(-0.3), "v": np.float32(0.1)},
            done=(i == 7)) for i in range(8)]
        item = _assert_pad_parity(steps, decoder, obs_dim=obs_dim,
                                  act_dim=3)
        # the decoded column itself must still be bytes, not floats
        assert item.columns["o"].dtype == np.uint8
        np.testing.assert_array_equal(item.columns["o"][0], steps[0].obs)

    def test_terminal_marker(self, decoder):
        steps = _mk_steps(10)
        steps[-1] = ActionRecord(obs=steps[-1].obs, act=steps[-1].act,
                                 rew=steps[-1].rew, data=steps[-1].data,
                                 done=False)
        steps.append(ActionRecord(rew=2.5, done=True))  # flag_last_action
        _assert_pad_parity(steps, decoder)

    def test_truncation_marker_with_bootstrap_obs(self, decoder):
        steps = _mk_steps(8)
        steps[-1] = ActionRecord(obs=steps[-1].obs, act=steps[-1].act,
                                 rew=steps[-1].rew, data=steps[-1].data,
                                 done=False)
        steps.append(ActionRecord(
            obs=np.arange(4, dtype=np.float32), rew=0.5, done=True,
            truncated=True, mask=np.ones(2, np.float32)))
        item = _assert_pad_parity(steps, decoder)
        assert item.marker_truncated
        assert item.final_obs is not None and item.final_mask is not None

    def test_multiple_trailing_markers(self, decoder):
        steps = _mk_steps(6)
        steps.append(ActionRecord(rew=1.0, done=False))
        steps.append(ActionRecord(obs=np.full(4, 7, np.float32), rew=2.0,
                                  done=True, truncated=True))
        _assert_pad_parity(steps, decoder)

    def test_marker_only_trajectory(self, decoder):
        payload = serialize_actions([ActionRecord(rew=1.0, done=True)])
        item = decoder.decode(payload)
        assert isinstance(item, DecodedTrajectory)
        assert item.n_steps == 0 and item.n_records == 1

    def test_long_episode_truncates_to_horizon(self, decoder):
        _assert_pad_parity(_mk_steps(40), decoder, horizon=16)

    def test_envelope_decode(self, decoder):
        steps = _mk_steps(4)
        env = pack_trajectory_envelope("agent-xyz", serialize_actions(steps))
        item = decoder.decode(env, has_envelope=True)
        assert isinstance(item, DecodedTrajectory)
        assert item.agent_id == "agent-xyz"
        assert item.n_steps == 4

    def test_image_observations(self, decoder):
        # pixel policies flatten server-side; the column keeps the raw shape
        rng = np.random.default_rng(3)
        steps = [ActionRecord(obs=rng.integers(0, 255, (8, 8, 3)).astype(np.uint8),
                              act=np.int64(1), rew=1.0,
                              done=(i == 2)) for i in range(3)]
        payload = serialize_actions(steps)
        item = decoder.decode(payload)
        assert isinstance(item, DecodedTrajectory)
        assert item.columns["o"].shape == (3, 8, 8, 3)
        assert item.columns["o"].dtype == np.uint8


class TestFallbacks:
    def test_mixed_obs_shapes_fall_back(self, decoder):
        steps = _mk_steps(4)
        steps[2] = ActionRecord(obs=np.zeros(7, np.float32), act=np.int64(0),
                                rew=0.0, done=False)
        payload = serialize_actions(steps)
        item = decoder.decode(payload, agent_id="fb")
        assert isinstance(item, RawTrajectory)
        assert item.payload == payload  # Python decoder can take over
        assert deserialize_actions(item.payload)[2].obs.shape == (7,)

    def test_string_aux_falls_back(self, decoder):
        steps = _mk_steps(3)
        steps[1] = ActionRecord(obs=steps[1].obs, act=steps[1].act, rew=0.0,
                                data={"note": "hello"}, done=False)
        item = decoder.decode(serialize_actions(steps))
        assert isinstance(item, RawTrajectory)

    def test_mixed_aux_keys_fall_back(self, decoder):
        steps = _mk_steps(3)
        steps[1] = ActionRecord(obs=steps[1].obs, act=steps[1].act, rew=0.0,
                                data={"v": np.float32(1.0)}, done=False)
        item = decoder.decode(serialize_actions(steps))
        assert isinstance(item, RawTrajectory)

    def test_garbage_falls_back(self, decoder):
        item = decoder.decode(b"definitely not msgpack", agent_id="g")
        assert isinstance(item, RawTrajectory)
        assert item.payload == b"definitely not msgpack"

    def test_wrong_wire_version_falls_back(self, decoder):
        import msgpack

        payload = msgpack.packb({"v": 99, "acts": []})
        assert isinstance(decoder.decode(payload), RawTrajectory)


class TestStepBufferParity:
    def _compare(self, actions, obs_dim=4, act_dim=2, discrete=True):
        payload = serialize_actions(actions)
        dec = NativeDecoder().decode(payload)
        assert isinstance(dec, DecodedTrajectory)

        buf_py = StepReplayBuffer(obs_dim, act_dim, 128, discrete=discrete)
        n_py = buf_py.add_episode(deserialize_actions(payload))
        buf_nat = StepReplayBuffer(obs_dim, act_dim, 128, discrete=discrete)
        n_nat = buf_nat.add_episode(dec)
        assert n_nat == n_py
        for field in ("obs", "obs2", "act", "mask2", "rew", "done"):
            np.testing.assert_array_equal(
                getattr(buf_nat, field)[:n_py], getattr(buf_py, field)[:n_py],
                err_msg=field)
        assert buf_nat.ptr == buf_py.ptr and buf_nat.size == buf_py.size

    def test_terminal_episode(self):
        self._compare(_mk_steps(11))

    def test_truncated_with_bootstrap(self):
        steps = _mk_steps(7)
        steps[-1] = ActionRecord(obs=steps[-1].obs, act=steps[-1].act,
                                 rew=steps[-1].rew, data=steps[-1].data,
                                 done=False)
        steps.append(ActionRecord(obs=np.full(4, 3, np.float32), rew=1.0,
                                  done=True, truncated=True))
        self._compare(steps)

    def test_truncated_without_bootstrap_drops_last(self):
        steps = _mk_steps(5)
        steps[-1] = ActionRecord(obs=steps[-1].obs, act=steps[-1].act,
                                 rew=steps[-1].rew, data=steps[-1].data,
                                 done=False, truncated=True)
        self._compare(steps)

    def test_continuous(self):
        self._compare(_mk_steps(6, act_dim=3, discrete=False), act_dim=3,
                      discrete=False)


class TestFuzzParity:
    def test_random_trajectories(self, decoder):
        rng = np.random.default_rng(42)
        for trial in range(60):
            n = int(rng.integers(1, 24))
            obs_dim = int(rng.integers(1, 9))
            act_dim = int(rng.integers(1, 5))
            discrete = bool(rng.integers(2))
            with_mask = bool(rng.integers(2))
            with_aux = bool(rng.integers(2))
            steps = _mk_steps(n, obs_dim, act_dim, discrete, with_mask,
                              with_aux, seed=trial)
            if rng.integers(2):  # add a flag_last_action marker
                steps[-1] = ActionRecord(
                    obs=steps[-1].obs, act=steps[-1].act, rew=steps[-1].rew,
                    mask=steps[-1].mask, data=steps[-1].data, done=False)
                marker_obs = (rng.standard_normal(obs_dim).astype(np.float32)
                              if rng.integers(2) else None)
                steps.append(ActionRecord(
                    obs=marker_obs, rew=float(rng.standard_normal()),
                    done=True, truncated=bool(rng.integers(2))))
            _assert_pad_parity(steps, decoder, obs_dim=obs_dim,
                               act_dim=act_dim, discrete=discrete)
