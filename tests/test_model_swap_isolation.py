"""Hot-swap trust boundary: a corrupt or wrong-arch model broadcast must
never take an actor down — or worse, leave it silently serving nothing.

The publish plane ships whole ModelBundle bytes (the reference ships
TorchScript files and load-panics on corruption, agent_zmq.rs:645-679).
Here the agent's _on_model isolates ANY decode/validation failure,
keeps serving the installed policy, and installs the next valid bundle
as if the bad one never happened. Runs over a live zmq transport pair —
the real listener thread, not a direct maybe_swap call (that unit angle
lives in test_offpolicy.py).
"""

import socket
import time

import numpy as np
import pytest

from relayrl_tpu.algorithms import build_algorithm
from relayrl_tpu.config import ConfigLoader
from relayrl_tpu.runtime.agent import Agent
from relayrl_tpu.transport import make_server_transport


from _util import free_port as _free_port  # noqa: E402


@pytest.fixture
def cfg(tmp_cwd):
    return ConfigLoader(create_if_missing=False)


def _wait(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


@pytest.mark.parametrize("kind", ["zmq", "native"])
def test_corrupt_then_valid_broadcast(cfg, tmp_cwd, kind):
    if kind == "native":
        # Runtime check (repo convention, test_transport.py): a skipif
        # argument would trigger the native build during collection of
        # every pytest run.
        from relayrl_tpu.transport.native_backend import native_available

        if not native_available():
            pytest.skip("native library not built (make -C native)")
    alg = build_algorithm("REINFORCE", obs_dim=4, act_dim=2,
                          env_dir=str(tmp_cwd), hidden_sizes=[8])
    bundle_v1 = alg.bundle().to_bytes()

    p1, p2, p3 = _free_port(), _free_port(), _free_port()
    if kind == "native":
        srv_addr = {"bind_addr": f"127.0.0.1:{p1}"}
        ag_addr = {"server_addr": f"127.0.0.1:{p1}"}
    else:
        srv_addr = {"agent_listener_addr": f"tcp://127.0.0.1:{p1}",
                    "trajectory_addr": f"tcp://127.0.0.1:{p2}",
                    "model_pub_addr": f"tcp://127.0.0.1:{p3}"}
        ag_addr = {"agent_listener_addr": f"tcp://127.0.0.1:{p1}",
                   "trajectory_addr": f"tcp://127.0.0.1:{p2}",
                   "model_sub_addr": f"tcp://127.0.0.1:{p3}"}
    srv = make_server_transport(kind, cfg, **srv_addr)
    srv.get_model = lambda: (1, bundle_v1)
    srv.start()
    try:
        agent = Agent(server_type=kind, handshake_timeout_s=30, seed=0,
                      config_path=None,
                      model_path=str(tmp_cwd / "client.msgpack"),
                      **ag_addr)
        try:
            assert agent.model_version == 1

            # Ordered triplet on ONE publish channel: corrupt bytes (v2),
            # wrong-arch bundle (v3), honest sentinel (v4). Transport
            # ordering means the sentinel's arrival PROVES v2/v3 were
            # delivered first and rejected — no sleep-and-hope negative
            # assertions (and a listener thread killed by v2 would never
            # install v4). Republished in a loop so a slow SUB
            # subscription can't drop the whole triplet and pass
            # vacuously: versions only move forward, so re-sends of v2/v3
            # after v4 installs are stale-rejected by design.
            other = build_algorithm("REINFORCE", obs_dim=4, act_dim=2,
                                    env_dir=str(tmp_cwd),
                                    hidden_sizes=[16, 16])
            wrong_arch = other.bundle()
            wrong_arch.version = 3
            good = alg.bundle()
            good.version = 4

            def blast():
                srv.publish_model(2, b"\xde\xad\xbe\xef not a bundle")
                srv.publish_model(3, wrong_arch.to_bytes())
                srv.publish_model(4, good.to_bytes())

            deadline = time.monotonic() + 15
            while agent.model_version != 4:
                assert time.monotonic() < deadline, \
                    "sentinel never installed (listener dead or drop)"
                blast()
                _wait(lambda: agent.model_version == 4, timeout=1.0)
            # v2 (undecodable) and v3 (arch guard) were delivered before
            # v4 and rejected; the actor still serves the ORIGINAL arch.
            assert agent.model_version == 4
            assert agent.actor.arch["hidden_sizes"] == [8]
            act = agent.request_for_action(np.zeros(4, np.float32))
            assert act.get_act() is not None
        finally:
            agent.disable_agent()
    finally:
        srv.stop()
