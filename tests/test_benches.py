"""Smoke tests: bench scripts emit well-formed JSON lines in --quick mode.

Only the cheap benches run here (codec); the socket/learner benches are
exercised manually and by the driver — this guards the harness contract
(one JSON object per line with bench/config/value/unit keys).
"""

import json
import subprocess
import sys
from pathlib import Path

BENCH_DIR = Path(__file__).resolve().parent.parent / "benches"


def test_bench_codec_quick_emits_json(tmp_path):
    out = subprocess.run(
        [sys.executable, str(BENCH_DIR / "bench_codec.py"), "--quick"],
        capture_output=True, text=True, timeout=240,
        cwd=tmp_path,
        env={"PYTHONPATH": f"{BENCH_DIR.parent}:{BENCH_DIR}",
             "PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    assert len(lines) >= 7 * 3 + 2  # dtypes x sizes + trajectory rows
    for line in lines:
        rec = json.loads(line)
        assert set(rec) == {"bench", "config", "value", "unit"}
        assert rec["value"] > 0
