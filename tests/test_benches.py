"""Smoke tests: bench scripts emit well-formed JSON lines in --quick mode.

The multi-process / socket bench smokes are ``slow``-marked (tier-1
wall budget, ISSUE 15: clean HEAD overran the 870 s budget and these
ten smokes alone cost ~290 s on the 2-core bench host) — run them via
``pytest -m slow tests/test_benches.py`` or the per-plane markers. The
fast set keeps the cheap harness-contract smokes plus every
committed-artifact invariant test (those only parse files). The
full-scale socket benches and the chip benches stay manual/driver-run.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

BENCH_DIR = Path(__file__).resolve().parent.parent / "benches"


def _run_bench(script: str, cwd, *args, timeout: int = 420,
               script_path=None, env_overrides=None, want_stderr=False):
    """Run a bench script in an isolated cwd (config auto-create writes
    there) and return its parsed JSON lines. ``script_path`` overrides
    the default BENCH_DIR/<script> --quick invocation (used for the
    repo-root bench.py, which takes no flags)."""
    argv = ([sys.executable, str(script_path), *args] if script_path
            else [sys.executable, str(BENCH_DIR / script), "--quick", *args])
    env = {"PYTHONPATH": f"{BENCH_DIR.parent}:{BENCH_DIR}",
           "PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
           "HOME": "/tmp", **(env_overrides or {})}
    out = subprocess.run(argv, capture_output=True, text=True,
                         timeout=timeout, cwd=cwd, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [json.loads(l) for l in out.stdout.splitlines()
             if l.startswith("{")]
    assert lines, out.stdout[-500:]
    return (lines, out.stderr) if want_stderr else lines


def test_bench_codec_quick_emits_json(tmp_path):
    lines = _run_bench("bench_codec.py", tmp_path, timeout=240)
    assert len(lines) >= 7 * 3 + 2  # dtypes x sizes + trajectory rows
    for rec in lines:
        assert set(rec) == {"bench", "config", "value", "unit"}
        assert rec["value"] > 0


@pytest.mark.slow
def test_bench_learner_quick_emits_json(tmp_path):
    lines = _run_bench("bench_learner.py", tmp_path)
    algos = {r["config"]["algorithm"] for r in lines}
    assert {"REINFORCE", "IMPALA", "DQN", "SAC"} <= algos
    assert all(r["value"] > 0 for r in lines)


@pytest.mark.slow
def test_bench_inference_quick_emits_json(tmp_path):
    lines = _run_bench("bench_inference.py", tmp_path)
    assert any(r["bench"] == "agent_inference" for r in lines)
    assert any(r["bench"] == "seq_serving_per_step" for r in lines)


@pytest.mark.slow
def test_headline_bench_degraded_contract(tmp_path):
    """bench.py is the driver-recorded headline; when the accelerator is
    unreachable it must degrade INFORMATIVELY (VERDICT r3 weak #1): one
    JSON line, honestly renamed metric, degraded flag, and a
    last_good_chip block pointing at the committed same-round chip
    evidence — never a bare CPU ratio as the round's only record.

    JAX_PLATFORMS=tpu on a CPU-only host drives the GENUINE dead-backend
    path: the probe subprocess fails (no tpu plugin), the retry loop
    exhausts, and _ensure_live_backend falls back to CPU — the same
    branch a dead tunnel takes."""
    lines, stderr = _run_bench(
        "", tmp_path, timeout=540,
        script_path=BENCH_DIR.parent / "bench.py",
        env_overrides={"JAX_PLATFORMS": "tpu"}, want_stderr=True)
    assert len(lines) == 1
    r = lines[0]
    assert r["metric"] == "learner_steps_per_sec_cpu_fallback"
    assert r["degraded"] is True
    assert r["value"] > 0 and r["vs_baseline"] > 0
    good = r["last_good_chip"]
    assert good["headline_updates_per_sec"] > 0
    assert 0 < good["headline_mfu"] <= 1
    assert "headline_chip" in good["source"] or "BENCH_r" in good["source"]
    # the probe must report unreachability, and the degraded line must
    # point at the chip evidence
    assert "backend probe attempt" in stderr
    assert "last-good chip headline" in stderr


@pytest.mark.slow
def test_bench_soak_quick_slos(tmp_path):
    # The full fleet loop in --quick shape: SLOs (0 dropped, all agents
    # complete, drained blast) are asserted inside the script itself.
    lines = _run_bench("bench_soak.py", tmp_path, timeout=600)
    soak = next(r for r in lines if r["bench"].startswith("soak_multi"))
    assert soak["server_stats"]["dropped"] == 0
    blast = next(r for r in lines if r["bench"] == "ingest_blast_zmq")
    assert blast["drained"]
    # Every soak row embeds the server-plane telemetry snapshot in the
    # production /snapshot schema (ISSUE 4): bench artifacts and live
    # scrapes are read by the same tooling.
    for row in (soak, blast):
        snap = row["telemetry"]
        assert snap["schema"] == "relayrl-telemetry-v1" and snap["enabled"]
        names = {m["name"] for m in snap["metrics"]}
        assert "relayrl_server_trajectories_total" in names
    traj = next(m for m in soak["telemetry"]["metrics"]
                if m["name"] == "relayrl_server_trajectories_total")
    assert traj["value"] == soak["server_stats"]["trajectories"]
    # Distributed-tracing block (ISSUE 14): every soak row embeds the
    # pooled data-age / model-age attribution; the soak runs at sample
    # rate 1.0, so data age must carry real samples, and the schema is
    # stable even for empty distributions.
    ages = soak["age_attribution"]
    for key in ("data_age_s", "model_age_s", "data_age_versions"):
        assert "count" in ages[key], ages
    assert ages["trace_sampled"] > 0
    assert ages["data_age_s"]["count"] > 0
    assert {"mean", "p50", "p95"} <= set(ages["data_age_s"])


@pytest.mark.slow
def test_bench_soak_chaos_quick_smoke(tmp_path):
    """Fast --chaos soak smoke (ISSUE 6): the learner SIGKILL/resume
    drill under the standard fault plan must hold its SLOs (asserted
    in-script: zero-loss accounting, full spool flush, MTTR measured,
    faults actually injected) and emit a well-formed chaos row carrying
    the injection ledger + recovery counters."""
    lines = _run_bench("bench_soak.py", tmp_path, "--chaos", timeout=600)
    row = next(r for r in lines if r["bench"].startswith("chaos_soak"))
    assert row["accounting"]["zero_loss"] is True
    assert row["accounting"]["zero_double_train"] is True
    assert row["agents_crashed"] == 0
    assert row["mttr_s"] is not None and row["mttr_s"] >= 0
    assert row["config"]["fault_plan"]["rules"], "no fault plan committed"
    injected = sum(v for k, v in row["worker_fault_counters"].items()
                   if k.startswith("relayrl_faults_injected_total"))
    assert injected > 0, "chaos row ran fault-free"
    # every agent's ledger line must reconcile against its sent count
    for ident, n in row["accounting"]["sent_totals"].items():
        ledger = row["accounting"]["agents"][ident]
        assert ledger["max_seq"] == n and ledger["contiguous"], ledger


@pytest.mark.guardrails
@pytest.mark.slow
def test_bench_soak_guardrail_drill_quick_smoke(tmp_path):
    """Fast --poison guardrail drill smoke (ISSUE 8): a NaN-poison
    stream against a live fleet must quarantine the offending agent,
    trip the watchdog, auto-roll the learner back to a healthy
    checkpoint (never halt), and end with finite params — with the full
    guardrail evidence block in the emitted row. The committed full-
    length row additionally proves reward-target convergence; the smoke
    runs target-free to stay fast."""
    lines = _run_bench("bench_soak.py", tmp_path, "--poison", timeout=600)
    row = next(r for r in lines if r["bench"].startswith("guardrail_drill"))
    # asserted in-script too (_finish_guardrail_drill); re-asserted here
    # so a schema drift can't silently weaken the smoke
    assert row["quarantine"]["quarantines_total"] >= 1
    assert row["rollbacks_total"] >= 1
    assert row["halted"] is False
    assert row["final_params_finite"] is True
    assert row["strikes"] >= row["config"]["guardrails"]["strike_threshold"]
    assert row["poison_episodes_sent"] >= 1
    injected = sum(v for k, v in row["poison_worker_counters"].items()
                   if k.startswith("relayrl_faults_injected_total"))
    assert injected >= 1, "the poison plan never fired"
    # the restored line kept publishing (forced-keyframe resync path;
    # per-actor resync version is asserted in-script when the rollback
    # lands inside the clean window)
    assert row["final_version"] > (
        row["timeline_s"]["version_at_recovery"] or 0)
    snap = row["telemetry"]
    assert snap["schema"] == "relayrl-telemetry-v1" and snap["enabled"]


@pytest.mark.anakin
@pytest.mark.slow
def test_bench_soak_anakin_quick_smoke(tmp_path):
    """Fast bench_soak --anakin smoke (ISSUE 7): a tiny fused-rollout
    fleet (one process, on-device CartPole lanes) must land >= 1 REAL
    trajectory per logical agent with per-lane attribution, zero drops,
    and a row carrying the engine-plane timing block + the server
    /snapshot schema."""
    import os

    sys.path.insert(0, str(BENCH_DIR))
    monkey_cwd = os.getcwd()
    try:
        import bench_soak

        os.chdir(tmp_path)
        result = bench_soak.run_soak(
            n_actors=4, agents_per_proc=4, duration_s=3.0,
            traj_per_epoch=8, anakin=True, unroll_length=16)
    finally:
        os.chdir(monkey_cwd)
        sys.path.pop(0)
    assert result["config"]["mode"] == "anakin"
    assert result["config"]["obs_dim"] == 4  # sized to the REAL env
    assert result["agents_completed"] == 4
    assert result["agents_crashed"] == 0
    assert result["server_stats"]["dropped"] == 0
    assert result["min_episodes_per_agent"] >= 1
    assert result["distinct_traj_agents"] == 4  # per-lane attribution
    engine = result["anakin_engine"]
    assert engine["windows"] >= 1
    assert engine["dispatch_s_total"] > 0
    snap = result["telemetry"]
    assert snap["schema"] == "relayrl-telemetry-v1"
    names = {m["name"] for m in snap["metrics"]}
    assert "relayrl_server_trajectories_total" in names


@pytest.mark.serving
@pytest.mark.slow
def test_bench_soak_serving_quick_smoke(tmp_path):
    """Fast --serving soak smoke (ISSUE 10): a tiny thin-client fleet
    against the server-colocated InferenceService must complete >= 1
    action round-trip per client (steps > 0 per row), land >= 1
    trajectory per client through the UNCHANGED ingest plane, show
    batching actually engaged (measured occupancy > 1), zero drops, and
    carry the serving SLO block (latency percentiles + close-reason
    split) in the row."""
    import os

    sys.path.insert(0, str(BENCH_DIR))
    monkey_cwd = os.getcwd()
    try:
        import bench_soak

        os.chdir(tmp_path)
        result = bench_soak.run_soak(
            n_actors=4, agents_per_proc=4, duration_s=4.0,
            traj_per_epoch=8, serving=True, max_batch=4,
            batch_timeout_ms=5.0)
    finally:
        os.chdir(monkey_cwd)
        sys.path.pop(0)
    assert result["config"]["mode"] == "serving"
    assert result["agents_completed"] == 4
    assert result["agents_crashed"] == 0
    assert result["server_stats"]["dropped"] == 0
    assert result["env_steps_total"] >= 4      # >= 1 round-trip each...
    assert result["min_episodes_per_agent"] >= 1  # ...in fact episodes
    assert result["distinct_traj_agents"] == 4  # ingest plane unchanged
    serving = result["serving"]
    assert serving["requests_total"] >= result["env_steps_total"]
    assert serving["rejected_total"] == 0
    assert serving["batch_occupancy_mean"] > 1, \
        "dynamic batching never engaged"
    assert (serving["close_reasons"]["size"]
            + serving["close_reasons"]["deadline"]) > 0
    assert serving["action_latency_ms"]["p50"] > 0
    assert serving["action_latency_ms"]["p99"] >= \
        serving["action_latency_ms"]["p50"]
    snap = result["telemetry"]
    assert snap["schema"] == "relayrl-telemetry-v1"
    names = {m["name"] for m in snap["metrics"]}
    assert "relayrl_serving_requests_total" in names


@pytest.mark.serving
@pytest.mark.slow
def test_bench_soak_serving_mux_quick_smoke(tmp_path):
    """Streamed-mux --serving smoke (ISSUE 18): two MultiplexedRemoteClient
    processes x 4 lanes against the colocated InferenceService. Each
    streaming client must demonstrably PIPELINE — >= 2 requests in
    flight on its one DEALER socket at some point (the lock-step
    baseline can never exceed 1) — with zero rejects, zero LRU
    evictions, per-lane trajectory attribution intact, and the
    session/nack split present in the SLO block."""
    import os

    sys.path.insert(0, str(BENCH_DIR))
    monkey_cwd = os.getcwd()
    try:
        import bench_soak

        os.chdir(tmp_path)
        result = bench_soak.run_soak(
            n_actors=8, agents_per_proc=4, duration_s=4.0,
            traj_per_epoch=8, serving=True, serving_mux=True,
            max_batch=4, batch_timeout_ms=5.0)
    finally:
        os.chdir(monkey_cwd)
        sys.path.pop(0)
    assert result["config"]["mode"] == "serving"
    assert result["config"]["streamed_mux"] is True
    assert result["agents_completed"] == 8
    assert result["agents_crashed"] == 0
    assert result["server_stats"]["dropped"] == 0
    assert result["distinct_traj_agents"] == 8  # per-lane sids intact
    sv = result["serving"]
    assert sv["rejected_total"] == 0
    assert sv["batch_occupancy_mean"] > 1, \
        "dynamic batching never engaged"
    mux = sv["mux"]
    assert mux["clients"] == 2  # one streaming client per worker proc
    assert len(mux["inflight_high_water_per_client"]) == 2
    assert all(hw >= 2 for hw in mux["inflight_high_water_per_client"]), \
        f"a streaming client never pipelined: {mux}"
    split = sv["session_nack_split"]
    assert split["evicted_lru"] == 0  # sized table: no working-set churn
    assert {"evicted_ttl", "session_resyncs",
            "session_nacked"} <= set(split)


@pytest.mark.serving
@pytest.mark.slow
def test_serving_replica_sigkill_drill(tmp_path):
    """Multi-replica SIGKILL drill (ISSUE 18): two StandaloneInferenceHost
    replica PROCESSES serve a windowed transformer policy behind the
    session-affine router; SIGKILL the replica that owns lane 0
    mid-episode. The streamed client must re-route the orphaned lanes to
    the survivor and resync their session windows — every post-kill
    round still answers all lanes, with >= 1 recorded resync."""
    import os
    import time

    from _util import free_port
    from relayrl_tpu import telemetry
    from relayrl_tpu.runtime.inference import MultiplexedRemoteClient
    from relayrl_tpu.runtime.server import TrainingServer

    telemetry.set_registry(telemetry.Registry(run_id="sigkill-drill"))
    scratch = str(tmp_path)
    cfg_path = os.path.join(scratch, "drill_cfg.json")
    with open(cfg_path, "w") as f:
        json.dump({"serving": {"enabled": True, "max_batch": 4,
                               "batch_timeout_ms": 2.0,
                               "request_timeout_s": 1.0}}, f)
    addrs = {
        "agent_listener_addr": f"tcp://127.0.0.1:{free_port()}",
        "trajectory_addr": f"tcp://127.0.0.1:{free_port()}",
        "model_pub_addr": f"tcp://127.0.0.1:{free_port()}",
    }
    # Root trains + publishes only; serving lives in the replicas.
    server = TrainingServer(
        "REINFORCE", obs_dim=6, act_dim=3, env_dir=scratch,
        server_type="zmq",
        hyperparams={"traj_per_epoch": 10_000,
                     "model_kind": "transformer_discrete", "d_model": 16,
                     "n_layers": 1, "n_heads": 2, "max_seq_len": 16,
                     "bucket_lengths": (16,)},
        **addrs)
    procs, serving_addrs, client = [], [], None
    stop_file = os.path.join(scratch, "replica_stop")
    try:
        for r in range(2):
            saddr = f"tcp://127.0.0.1:{free_port()}"
            serving_addrs.append(saddr)
            rcfg = {
                "name": f"drill-replica-{r}", "config_path": cfg_path,
                "server_type": "zmq", "serving_addr": saddr,
                "ready_file": os.path.join(scratch, f"r{r}_ready"),
                "stop_file": stop_file,
                "result_path": os.path.join(scratch, f"r{r}_result.json"),
                "handshake_timeout_s": 180.0,
                "agent_listener_addr": addrs["agent_listener_addr"],
                "trajectory_addr": addrs["trajectory_addr"],
                "model_sub_addr": addrs["model_pub_addr"],
            }
            procs.append(subprocess.Popen(
                [sys.executable, str(BENCH_DIR / "_serving_replica.py"),
                 json.dumps(rcfg)],
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=scratch))
        deadline = time.time() + 180
        for r, proc in enumerate(procs):
            ready = os.path.join(scratch, f"r{r}_ready")
            while not os.path.exists(ready):
                if proc.poll() is not None:
                    raise AssertionError(
                        f"replica {r} died during startup:\n"
                        f"{proc.stdout.read()[-2000:]}")
                assert time.time() < deadline, f"replica {r} never ready"
                time.sleep(0.1)
        import numpy as np

        client = MultiplexedRemoteClient(
            config_path=cfg_path, server_type="zmq", lanes=4, seed=17,
            identity="drill-mux", serving_addrs=serving_addrs,
            agent_listener_addr=addrs["agent_listener_addr"],
            trajectory_addr=addrs["trajectory_addr"],
            model_sub_addr=addrs["model_pub_addr"])
        assert len(client._clients) == 2  # one stream per replica
        rng = np.random.default_rng(5)

        def run_rounds(n):
            for _ in range(n):
                obs = [o.astype(np.float32)
                       for o in rng.standard_normal((4, 6))]
                recs = client.request_for_actions(
                    obs, rewards=[0.1] * 4)
                assert len(recs) == 4
                assert all(r is not None for r in recs)

        run_rounds(3)
        victim = client._lane_client[0]  # lane 0's home replica
        procs[victim].kill()             # SIGKILL, no goodbye
        procs[victim].wait(timeout=30)
        run_rounds(3)                    # must still answer every lane
        assert client._lane_client[0] == 1 - victim, \
            "lane 0 never re-routed off the dead replica"
        assert client._m_resyncs.total() >= 1, \
            "re-route happened without a session window resync"
    finally:
        with open(stop_file, "w") as f:
            f.write("stop")
        if client is not None:
            client.disable_agent()
        for proc in procs:
            try:
                proc.communicate(timeout=30)
            except Exception:
                proc.kill()
        server.disable_server()


@pytest.mark.relay
@pytest.mark.slow
def test_bench_soak_relay_quick_smoke(tmp_path):
    """Fast relay-tree soak smoke (ISSUE 11): 2 relays fronting 2 anakin
    hosts x 4 lanes. The root's broadcast plane must serve RELAYS
    streams (subscriber gauge == 2, not 8), every logical agent must
    land >= 1 trajectory through its relay with zero drops, and each
    relay's embedded telemetry snapshot must carry nonzero relay
    counters on both planes."""
    import os

    sys.path.insert(0, str(BENCH_DIR))
    monkey_cwd = os.getcwd()
    try:
        import bench_soak

        os.chdir(tmp_path)
        result = bench_soak.run_soak(
            n_actors=8, agents_per_proc=4, duration_s=4.0,
            traj_per_epoch=8, anakin=True, unroll_length=16, relays=2)
    finally:
        os.chdir(monkey_cwd)
        sys.path.pop(0)
    assert result["bench"].endswith("_relay")
    assert result["agents_completed"] == 8
    assert result["agents_crashed"] == 0
    assert result["server_stats"]["dropped"] == 0
    assert result["min_episodes_per_agent"] >= 1
    assert result["distinct_traj_agents"] == 8  # attribution through hops
    topo = result["relay_topology"]
    assert topo["relays"] == 2
    # THE O(relays) proof: the root publisher sees 2 streams for an
    # 8-actor fleet.
    assert topo["root_subscribers"] == 2
    assert len(topo["relays_detail"]) == 2
    for detail in topo["relays_detail"]:
        stats = detail["stats"]
        assert stats["model_frames_forwarded"] > 0
        assert stats["trajectory_frames_forwarded"] > 0
        snap = detail["telemetry"]
        assert snap["schema"] == "relayrl-telemetry-v1"
        fwd = {tuple(sorted((m.get("labels") or {}).items())): m["value"]
               for m in snap["metrics"]
               if m["name"] == "relayrl_relay_frames_forwarded_total"}
        assert fwd[(("plane", "model"),)] > 0
        assert fwd[(("plane", "trajectory"),)] > 0


@pytest.mark.relay
def test_committed_relay_scaling_curve_invariants():
    """The committed relay curve (ISSUE 11 acceptance artifact): every
    scaling row's root stream count equals its relay count while actors
    grow to 1k+, bytes-per-publish at the root stays flat at fixed
    relay count, zero drops/crashes everywhere, and the relay-SIGKILL
    chaos row reports zero loss, zero double-train, and an MTTR."""
    path = BENCH_DIR / "results" / "soak_scaling_zmq_relay.json"
    rows = [json.loads(line) for line in path.read_text().splitlines()
            if line.strip()]
    scaling = [r for r in rows if r["bench"].startswith("soak_multi")]
    chaos = [r for r in rows if r["bench"] == "relay_chaos_zmq"]
    assert scaling and chaos
    assert max(r["config"]["actors"] for r in scaling) >= 1024
    by_relays: dict[int, list] = {}
    for r in scaling:
        assert r["server_stats"]["dropped"] == 0, r["bench"]
        assert r["agents_crashed"] == 0
        assert r["agents_completed"] == r["config"]["actors"]
        assert r["distinct_traj_agents"] == r["config"]["actors"]
        topo = r["relay_topology"]
        assert topo["root_subscribers"] == topo["relays"]
        assert topo["root_bytes_per_publish"] and topo["root_publishes"]
        by_relays.setdefault(topo["relays"], []).append(r)
    # flatness: at a FIXED relay count, root bytes/publish must not grow
    # with the actor count (allow measurement noise).
    for rows_at in by_relays.values():
        if len(rows_at) < 2:
            continue
        rows_at.sort(key=lambda r: r["config"]["actors"])
        lo = rows_at[0]["relay_topology"]["root_bytes_per_publish"]
        hi = rows_at[-1]["relay_topology"]["root_bytes_per_publish"]
        assert hi <= 1.25 * lo, (lo, hi)
    drill = chaos[0]
    assert drill["accounting"]["zero_loss"] is True
    assert drill["accounting"]["zero_double_train"] is True
    assert drill["agents_crashed"] == 0
    assert drill["mttr_s"] is not None and drill["mttr_s"] >= 0


@pytest.mark.anakin
@pytest.mark.slow
def test_bench_anakin_quick_emits_json(tmp_path):
    """bench_anakin --quick: baseline + fused rate lines for every grid
    point, and a headline carrying the equal-lane-count speedup map plus
    the best fused row's dispatch/unstack split (the full per-row detail
    goes to the results file under --write)."""
    lines = _run_bench("bench_anakin.py", tmp_path, timeout=420)
    base = [r for r in lines if r.get("bench") == "anakin_vector_baseline"]
    fused = [r for r in lines if r.get("bench") == "anakin_fused_rollout"]
    assert base and fused
    # both wire forms measured per grid cell (ISSUE 9)
    assert {r["config"]["wire"] for r in fused} == {"columnar", "records"}
    headline = next(r for r in lines if r.get("bench") == "anakin_headline")
    for lanes, speedup in headline["speedup_rollout_at_equal_lanes"].items():
        assert speedup > 1.0, (lanes, speedup)
    assert headline["best_rollout"]["rollout_steps_per_sec"] > 0
    assert headline["best_e2e_columnar"] > 0
    assert headline["speedup_columnar_e2e_vs_records"], \
        "columnar-vs-records e2e map missing"


@pytest.mark.telemetry
def test_bench_telemetry_quick_asserts_hotpath_cost(tmp_path):
    # The microbench carries its own ceiling asserts (disabled-path inc
    # must stay an attribute call, enabled inc lock-free); this smoke
    # keeps it runnable and its JSON well-formed.
    lines = _run_bench("bench_telemetry.py", tmp_path, timeout=240)
    ops = {r["config"]["op"]: r for r in lines
           if r["bench"] == "telemetry_hotpath"}
    assert {"counter_inc_disabled", "counter_inc_enabled",
            "histogram_observe_enabled"} <= set(ops)
    assert all(r["ns_per_op"] > 0 for r in ops.values())
    assert any(r["bench"] == "telemetry_snapshot" for r in lines)


@pytest.mark.slow
def test_bench_model_wire_quick_smoke(tmp_path):
    """Model-wire v2 bench (--quick): bytes rows with sane ratios, the
    RLHF-style fine-tune scenario beating full-train, and latency rows
    for both wire versions on the live zmq pair."""
    lines = _run_bench("bench_model_wire.py", tmp_path, timeout=420)
    bytes_rows = [r for r in lines if r["bench"] == "model_wire_bytes"]
    assert bytes_rows, "no bytes rows emitted"
    for r in bytes_rows:
        assert r["delta_reduction_x"] >= 1.0
        assert r["keyframe_bytes"] > 0
        assert r["v1_bytes_per_publish"] > r["delta_bytes_mean"] or \
            r["delta_reduction_x"] >= 0.99
        assert r["encode_ms_mean"] > 0 and r["decode_apply_ms_mean"] > 0
    finetune = [r for r in bytes_rows
                if r["config"]["scenario"].startswith("rlhf_finetune")]
    full = [r for r in bytes_rows
            if "train" in r["config"]["scenario"]
            and not r["config"]["scenario"].startswith("rlhf")]
    assert finetune and full
    # The per-leaf skip must show up: frozen-trunk deltas beat the best
    # full-train row.
    assert (max(r["delta_reduction_x"] for r in finetune)
            > min(r["delta_reduction_x"] for r in full))
    lat = {r["config"]["wire_version"]: r for r in lines
           if r["bench"] == "model_wire_latency"
           and r["config"].get("wire_policy") == "auto"}
    assert {1, 2} <= set(lat)
    assert lat[2]["publish_to_swap_ms_p50"] > 0
    # v2 rows carry the wire counters in the /snapshot schema (the
    # soak-row convention).
    snap = lat[2]["telemetry"]
    assert snap["schema"] == "relayrl-telemetry-v1"
    names = {m["name"] for m in snap["metrics"]}
    assert "relayrl_wire_publish_bytes_total" in names


@pytest.mark.rlhf
@pytest.mark.slow
def test_bench_rlhf_quick_smoke(tmp_path):
    """RLHF e2e scenario bench (--quick): schema + the reward-improved
    assert (the satellite contract), the per-stage split, the train-lag
    distribution, zero-loss accounting, and in-scenario frozen-leaf
    wire savings."""
    lines = _run_bench("bench_rlhf.py", tmp_path, timeout=560)
    rows = [r for r in lines if r["bench"] == "rlhf_e2e"]
    assert rows, "no rlhf_e2e row emitted"
    row = rows[0]
    assert row["config"]["scorer"] == "reward_model"
    # reward improved: the run ends above where it started, against the
    # stated threshold's baseline anchors.
    assert row["reward_final_mean"] > row["reward_baseline_mean"]
    assert row["threshold_met"] is True
    # the four-way stage split is present and non-trivial
    stages = row["stage_seconds"]
    for key in ("generate", "score", "emit", "update_dispatch", "publish"):
        assert key in stages and stages[key]["count"] > 0, key
    # behavior-vs-learner lag distribution observed at train time
    lag = row["version_lag"]["train"]
    assert lag["observations"] > 0 and lag["mean"] >= 0
    # dataflow correctness + the frozen-leaf wire claim
    assert row["zero_loss_accounting"] is True
    assert row["wire"]["frozen_leaves"] > 0
    assert row["wire"]["publish_bytes_saved_total"] > 0
    assert row["updates"] > 0 and row["tokens_generated"] > 0


@pytest.mark.rlhf
def test_committed_rlhf_e2e_invariants():
    """The committed benches/results/rlhf_e2e.json artifact keeps the
    acceptance claims: threshold met on the reward-model row, per-stage
    split + lag distribution present, frozen-leaf savings per row."""
    sys.path.insert(0, str(BENCH_DIR))
    try:
        from common import load_results
    finally:
        sys.path.pop(0)
    rows = [r for r in load_results(BENCH_DIR / "results" / "rlhf_e2e.json")
            if r.get("bench") == "rlhf_e2e"]
    assert rows, "committed artifact has no rlhf_e2e rows"
    rm_rows = [r for r in rows if r["config"]["scorer"] == "reward_model"]
    assert rm_rows
    assert any(r["threshold_met"] for r in rm_rows)
    for r in rows:
        assert r["reward_final_mean"] > r["reward_baseline_mean"]
        assert {"generate", "score", "update_dispatch",
                "publish"} <= set(r["stage_seconds"])
        assert r["version_lag"]["train"]["observations"] > 0
        assert r["zero_loss_accounting"] is True
        if r["config"]["freeze"]:
            assert r["wire"]["publish_bytes_saved_total"] > 0
        assert r["telemetry"]["schema"] == "relayrl-telemetry-v1"


def test_committed_results_all_parse_with_shared_loader():
    """Satellite (ISSUE 5): every committed benches/results/*.json file
    parses through common.load_results — the one reader for both the
    NDJSON and single-document shapes (a plain json.load fails on the
    NDJSON ones; see benches/README.md "results format")."""
    sys.path.insert(0, str(BENCH_DIR))
    try:
        from common import load_results
    finally:
        sys.path.pop(0)
    results = sorted((BENCH_DIR / "results").glob("*.json"))
    assert results, "no committed results found"
    for path in results:
        rows = load_results(path)
        assert isinstance(rows, list) and rows, path.name
        assert all(isinstance(r, (dict, list)) for r in rows), path.name


@pytest.mark.slow
@pytest.mark.fleet
def test_bench_fleet_quick_smoke(tmp_path):
    """Fleet aggregation drill in --quick shape (ISSUE 15): 2 relays x
    1 vector worker x 4 lanes over live zmq — /fleet lists every proc
    with its tier, merged actor counters match the per-process
    registries bit-exactly, and the induced-drop alert fires + resolves
    (all asserted inside the script)."""
    lines = _run_bench("bench_fleet.py", tmp_path, timeout=600)
    assert any(r.get("ok") for r in lines if "ok" in r)
    row = next(r for r in lines if r.get("bench") == "fleet_zmq")
    assert row["value"] > 0  # fleet frames arrived at the root


def test_committed_fleet_drill_invariants():
    """The committed fleet drill (ISSUE 15 acceptance artifact): 64+
    logical actors behind >= 2 relays, every proc tabled with its tier,
    bit-exact merged counter check green, the induced alert fired AND
    resolved with journal events, and the root's fleet-frame rate flat
    as actors doubled at fixed relay count (O(relays) ingest)."""
    path = BENCH_DIR / "results" / "fleet_zmq.json"
    doc = json.loads(path.read_text())
    rows = [r for r in doc["rows"] if r.get("bench") == "fleet_zmq"]
    assert rows
    big = max(rows, key=lambda r: r["config"]["logical_actors"])
    assert big["config"]["logical_actors"] >= 64
    assert big["config"]["relays"] >= 2
    tiers = {p["tier"] for p in big["procs"]}
    assert {"server", "relay", "actor"} <= tiers
    n_actor_procs = sum(1 for p in big["procs"] if p["tier"] == "actor")
    assert n_actor_procs == (big["config"]["relays"]
                             * big["config"]["workers_per_relay"])
    for r in rows:
        check = r["counter_check"]
        assert check["exact"] and not check["mismatches"]
        assert check["families_checked"] >= 2
        assert r["env_steps_merged"] and r["env_steps_merged"] > 0
        assert "ingest_drops" in r["alerts_armed"]
    drill = next(r["alert_drill"] for r in rows if r.get("alert_drill"))
    assert drill["fired"]["event"] == "alert_fired"
    assert drill["fired"]["rule"] == "ingest_drops"
    assert drill["resolved"]["event"] == "alert_resolved"
    assert drill["active_gauge_seen"] is True
    o_relays = next(r for r in doc["rows"]
                    if r.get("bench") == "fleet_zmq_o_relays")
    assert 0.5 <= o_relays["ratio"] <= 1.5
