"""Smoke tests: bench scripts emit well-formed JSON lines in --quick mode.

Codec, learner, inference, and the --quick fleet soak all run (CPU, a
couple of minutes total); the full-scale socket benches and the chip
benches stay manual/driver-run. This guards the harness contract (JSON
lines with bench/config/value/unit-shaped records and the soak SLOs).
"""

import json
import subprocess
import sys
from pathlib import Path

BENCH_DIR = Path(__file__).resolve().parent.parent / "benches"


def _run_bench(script: str, cwd, *args, timeout: int = 420):
    """Run a bench --quick in an isolated cwd (config auto-create writes
    there) and return its parsed JSON lines."""
    out = subprocess.run(
        [sys.executable, str(BENCH_DIR / script), "--quick", *args],
        capture_output=True, text=True, timeout=timeout,
        cwd=cwd,
        env={"PYTHONPATH": f"{BENCH_DIR.parent}:{BENCH_DIR}",
             "PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "HOME": "/tmp"},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [json.loads(l) for l in out.stdout.splitlines()
             if l.startswith("{")]
    assert lines, out.stdout[-500:]
    return lines


def test_bench_codec_quick_emits_json(tmp_path):
    lines = _run_bench("bench_codec.py", tmp_path, timeout=240)
    assert len(lines) >= 7 * 3 + 2  # dtypes x sizes + trajectory rows
    for rec in lines:
        assert set(rec) == {"bench", "config", "value", "unit"}
        assert rec["value"] > 0


def test_bench_learner_quick_emits_json(tmp_path):
    lines = _run_bench("bench_learner.py", tmp_path)
    algos = {r["config"]["algorithm"] for r in lines}
    assert {"REINFORCE", "IMPALA", "DQN", "SAC"} <= algos
    assert all(r["value"] > 0 for r in lines)


def test_bench_inference_quick_emits_json(tmp_path):
    lines = _run_bench("bench_inference.py", tmp_path)
    assert any(r["bench"] == "agent_inference" for r in lines)
    assert any(r["bench"] == "seq_serving_per_step" for r in lines)


def test_bench_soak_quick_slos(tmp_path):
    # The full fleet loop in --quick shape: SLOs (0 dropped, all agents
    # complete, drained blast) are asserted inside the script itself.
    lines = _run_bench("bench_soak.py", tmp_path, timeout=600)
    soak = next(r for r in lines if r["bench"].startswith("soak_multi"))
    assert soak["server_stats"]["dropped"] == 0
    blast = next(r for r in lines if r["bench"] == "ingest_blast_zmq")
    assert blast["drained"]
