"""Telemetry subsystem (relayrl_tpu/telemetry/): metrics core semantics,
Prometheus text-format conformance, snapshot consistency under concurrent
increment, the null-registry no-op path, the HTTP exporter, the NDJSON
event journal, the epoch-logger mirror, and the acceptance guard that
enabling telemetry leaves learner numerics bit-identical."""

import json
import re
import threading
import urllib.request

import numpy as np
import pytest

from relayrl_tpu import telemetry
from relayrl_tpu.telemetry import (
    EventJournal,
    NullRegistry,
    Registry,
    TelemetryExporter,
    read_events,
    render_prometheus,
)

pytestmark = pytest.mark.telemetry


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    """Each test starts from pristine disabled state and restores it —
    the module-global registry must not leak between tests (or into the
    rest of the suite)."""
    telemetry.reset_for_tests()
    yield
    telemetry.reset_for_tests()


class TestCore:
    def test_counter_aggregates_across_threads(self):
        reg = Registry(run_id="t")
        c = reg.counter("relayrl_t_total", "help")

        def work():
            for _ in range(10_000):
                c.inc()

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.total() == 80_000

    def test_counter_get_or_create_is_idempotent_per_label_set(self):
        reg = Registry()
        a = reg.counter("relayrl_t_total", labels={"backend": "zmq"})
        b = reg.counter("relayrl_t_total", labels={"backend": "zmq"})
        other = reg.counter("relayrl_t_total", labels={"backend": "grpc"})
        assert a is b and a is not other

    def test_kind_collision_raises(self):
        reg = Registry()
        reg.counter("relayrl_t_thing")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("relayrl_t_thing")

    def test_histogram_buckets_sum_count(self):
        reg = Registry()
        h = reg.histogram("relayrl_t_seconds", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        counts, total, n = h.totals()
        assert counts == [1, 1, 1, 1]  # one per bucket incl. +Inf
        assert n == 4 and abs(total - 5.555) < 1e-9

    def test_histogram_timer_context(self):
        reg = Registry()
        h = reg.histogram("relayrl_t_seconds", buckets=(10.0,))
        with h.time():
            pass
        _, _, n = h.totals()
        assert n == 1

    def test_gauge_fn_pulls_at_snapshot_and_survives_errors(self):
        reg = Registry()
        reg.gauge_fn("relayrl_t_depth", lambda: 7)
        reg.gauge_fn("relayrl_t_broken", lambda: 1 / 0)
        entries = {m["name"]: m for m in reg.snapshot()["metrics"]}
        assert entries["relayrl_t_depth"]["value"] == 7
        assert "relayrl_t_broken" not in entries  # omitted, not fatal

    def test_non_finite_values_null_in_snapshot_nan_in_prometheus(self):
        """A diverged stat (NaN loss) must not poison the JSON document:
        the snapshot carries null (strict JSON), the Prometheus text
        renders the legal NaN literal."""
        reg = Registry()
        reg.gauge("relayrl_t_nan").set(float("nan"))
        reg.gauge("relayrl_t_inf").set(float("inf"))
        h = reg.histogram("relayrl_t_seconds", buckets=(1.0,))
        h.observe(float("inf"))
        snap = reg.snapshot()
        text = json.dumps(snap, allow_nan=False)  # raises on bare NaN/Inf
        parsed = {m["name"]: m for m in json.loads(text)["metrics"]}
        assert parsed["relayrl_t_nan"]["value"] is None
        assert parsed["relayrl_t_inf"]["value"] is None
        assert parsed["relayrl_t_seconds"]["sum"] is None
        assert parsed["relayrl_t_seconds"]["count"] == 1
        prom = render_prometheus(snap)
        assert "relayrl_t_nan NaN" in prom
        assert "relayrl_t_seconds_sum NaN" in prom

    def test_gauge_fn_kind_collision_raises_gauge_rebind_allowed(self):
        reg = Registry()
        reg.counter("relayrl_t_total")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge_fn("relayrl_t_total", lambda: 1)
        reg.gauge_fn("relayrl_t_depth", lambda: 1)
        reg.gauge_fn("relayrl_t_depth", lambda: 2)  # rebind: fine
        entry = [m for m in reg.snapshot()["metrics"]
                 if m["name"] == "relayrl_t_depth"][0]
        assert entry["value"] == 2

    def test_gauge_stores_device_handle_resolves_at_snapshot(self):
        import jax.numpy as jnp

        reg = Registry()
        g = reg.gauge("relayrl_t_lazy")
        g.set(jnp.float32(2.5))  # stored as the handle, no float() here
        entry = [m for m in reg.snapshot()["metrics"]
                 if m["name"] == "relayrl_t_lazy"][0]
        assert entry["value"] == 2.5

    def test_snapshot_under_concurrent_increment_is_consistent(self):
        """Snapshots taken while 4 threads hammer a counter must be
        monotonic non-decreasing and the final total exact — per-thread
        shards may lag each other but may never lose or double-count."""
        reg = Registry()
        c = reg.counter("relayrl_t_total")
        per_thread, n_threads = 50_000, 4
        stop = threading.Event()
        seen: list[float] = []

        def snapshotter():
            while not stop.is_set():
                entry = [m for m in reg.snapshot()["metrics"]
                         if m["name"] == "relayrl_t_total"][0]
                seen.append(entry["value"])

        def work():
            for _ in range(per_thread):
                c.inc()

        snap_t = threading.Thread(target=snapshotter)
        workers = [threading.Thread(target=work) for _ in range(n_threads)]
        snap_t.start()
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        stop.set()
        snap_t.join()
        assert seen, "snapshotter never ran"
        assert all(b >= a for a, b in zip(seen, seen[1:]))
        assert c.total() == per_thread * n_threads

    def test_null_registry_is_total_noop(self):
        reg = NullRegistry()
        c = reg.counter("x")
        h = reg.histogram("y")
        g = reg.gauge("z")
        assert c is h is g  # one shared null object
        c.inc()
        h.observe(1.0)
        g.set(3)
        with h.time():
            pass
        assert c.total() == 0.0
        snap = reg.snapshot()
        assert snap["enabled"] is False and snap["metrics"] == []

    def test_global_default_is_null_and_set_registry_sticks(self):
        assert telemetry.get_registry().enabled is False
        reg = Registry(run_id="explicit")
        telemetry.set_registry(reg)
        assert telemetry.get_registry() is reg


class TestPrometheusConformance:
    """Text exposition format 0.0.4 against a snapshot with all three
    metric kinds and labeled children."""

    def _text(self):
        reg = Registry(run_id="conf")
        c = reg.counter("relayrl_c_total", "a counter",
                        labels={"backend": "zmq"})
        c.inc(3)
        reg.counter("relayrl_c_total", "a counter",
                    labels={"backend": "grpc"}).inc(1)
        reg.gauge("relayrl_g", "a gauge").set(2.5)
        h = reg.histogram("relayrl_h_seconds", "a histogram",
                          buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 2.0):
            h.observe(v)
        return render_prometheus(reg.snapshot())

    def test_help_and_type_once_per_family(self):
        text = self._text()
        assert text.count("# HELP relayrl_c_total a counter") == 1
        assert text.count("# TYPE relayrl_c_total counter") == 1
        assert "# TYPE relayrl_g gauge" in text
        assert "# TYPE relayrl_h_seconds histogram" in text

    def test_histogram_children_cumulative_with_inf_sum_count(self):
        text = self._text()
        assert 'relayrl_h_seconds_bucket{le="0.1"} 1' in text
        assert 'relayrl_h_seconds_bucket{le="1"} 2' in text
        assert 'relayrl_h_seconds_bucket{le="+Inf"} 3' in text
        assert "relayrl_h_seconds_count 3" in text
        assert re.search(r"relayrl_h_seconds_sum 2\.55", text)

    def test_labeled_children_and_escaping(self):
        text = self._text()
        assert 'relayrl_c_total{backend="zmq"} 3' in text
        assert 'relayrl_c_total{backend="grpc"} 1' in text
        reg = Registry()
        reg.counter("relayrl_esc_total",
                    labels={"k": 'a"b\\c\nd'}).inc()
        esc = render_prometheus(reg.snapshot())
        assert '{k="a\\"b\\\\c\\nd"}' in esc

    def test_every_sample_line_parses(self):
        """Each non-comment line is `<name>[{labels}] <value>`."""
        sample = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9eE+.\-]+$")
        for line in self._text().strip().splitlines():
            if line.startswith("#"):
                continue
            assert sample.match(line), line

    def test_trailing_newline(self):
        assert self._text().endswith("\n")


class TestExporter:
    def test_endpoints(self):
        reg = Registry(run_id="http")
        reg.counter("relayrl_t_total").inc(5)
        exporter = TelemetryExporter(reg, port=0)
        try:
            with urllib.request.urlopen(exporter.url + "/metrics") as resp:
                assert resp.status == 200
                assert "version=0.0.4" in resp.headers["Content-Type"]
                assert b"relayrl_t_total 5" in resp.read()
            with urllib.request.urlopen(exporter.url + "/snapshot") as resp:
                snap = json.loads(resp.read())
            assert snap["run_id"] == "http"
            assert snap["schema"] == "relayrl-telemetry-v1"
            assert snap["metrics"][0]["value"] == 5
            with urllib.request.urlopen(exporter.url + "/healthz") as resp:
                assert resp.read() == b"ok\n"
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(exporter.url + "/nope")
        finally:
            exporter.close()


class TestEvents:
    def test_journal_ndjson_schema_and_torn_tail(self, tmp_path):
        path = tmp_path / "events.ndjson"
        journal = EventJournal(str(path), run_id="r1")
        journal.emit("model_publish", version=3, bytes=100)
        journal.emit("drop", n=np.int64(2), total=np.float32(2.0))
        journal.close()
        with open(path, "a") as f:
            f.write('{"torn": ')  # crash mid-write
        events = read_events(str(path))
        assert len(events) == 2
        first = events[0]
        assert first["event"] == "model_publish" and first["run_id"] == "r1"
        assert first["version"] == 3
        assert {"t_unix", "mono_ns"} <= set(first)
        # numpy scalars landed as plain JSON numbers
        assert events[1]["n"] == 2 and events[1]["total"] == 2.0

    def test_module_emit_routes_to_configured_journal(self, tmp_path):
        path = tmp_path / "ev.ndjson"
        telemetry.set_journal(EventJournal(str(path), run_id="m"))
        telemetry.emit("checkpoint", version=1)
        telemetry.get_journal().close()
        assert read_events(str(path))[0]["event"] == "checkpoint"

    def test_emit_without_journal_is_noop(self):
        telemetry.emit("drain")  # must not raise


class TestConfigWiring:
    def _loader(self, tmp_path, telem: dict):
        from relayrl_tpu.config import ConfigLoader

        cfg = tmp_path / "relayrl_config.json"
        cfg.write_text(json.dumps({"telemetry": telem}))
        return ConfigLoader(None, str(cfg))

    def test_disabled_config_keeps_null_registry(self, tmp_path):
        reg = telemetry.configure_from_config(
            self._loader(tmp_path, {"enabled": False}))
        assert reg.enabled is False

    def test_enabled_config_installs_registry_and_journal(self, tmp_path):
        loader = self._loader(tmp_path, {
            "enabled": True, "port": 0, "run_id": "cfg",
            "events_path": str(tmp_path / "ev.ndjson")})
        reg = telemetry.configure_from_config(loader)
        assert reg.enabled and reg.run_id == "cfg"
        telemetry.emit("drain")
        assert telemetry.maybe_serve() is not None
        telemetry.shutdown()
        assert read_events(str(tmp_path / "ev.ndjson"))[0]["event"] == "drain"

    def test_maybe_serve_bind_failure_degrades_not_crashes(self, tmp_path):
        """A held telemetry.port must not take down the process being
        observed: maybe_serve returns None, metrics stay in-process."""
        import socket

        blocker = socket.socket()
        blocker.bind(("127.0.0.1", 0))
        blocker.listen(1)
        held_port = blocker.getsockname()[1]
        try:
            loader = self._loader(tmp_path, {"enabled": True,
                                             "port": held_port})
            reg = telemetry.configure_from_config(loader)
            assert reg.enabled
            assert telemetry.maybe_serve() is None
            reg.counter("relayrl_t_total").inc()  # registry still live
        finally:
            blocker.close()

    def test_first_configure_wins(self, tmp_path):
        first = telemetry.configure_from_config(
            self._loader(tmp_path, {"enabled": True, "run_id": "one"}))
        second = telemetry.configure_from_config(
            self._loader(tmp_path, {"enabled": True, "run_id": "two"}))
        assert second is first and first.run_id == "one"

    def test_malformed_section_degrades(self, tmp_path):
        loader = self._loader(tmp_path, {"enabled": "yes", "port": "junk"})
        params = loader.get_telemetry_params()
        assert params["enabled"] is True and params["port"] == 9100

    def test_transport_heartbeat_knob(self, tmp_path):
        from relayrl_tpu.config import ConfigLoader

        cfg = tmp_path / "relayrl_config.json"
        cfg.write_text(json.dumps({"transport": {"heartbeat_s": 1.5}}))
        assert ConfigLoader(
            None, str(cfg)).get_transport_params()["heartbeat_s"] == 1.5
        cfg.write_text(json.dumps({"transport": {"heartbeat_s": "x"}}))
        assert ConfigLoader(
            None, str(cfg)).get_transport_params()["heartbeat_s"] == 5.0

    def test_native_agent_heartbeat_wired_from_config(self, tmp_path):
        """transport.heartbeat_s reaches the native agent transport (the
        old hard-coded 5.0 in start_model_listener), and its liveness
        gauge is registered. Construction is connection-lazy, so no
        server is needed."""
        from relayrl_tpu.config import ConfigLoader
        from relayrl_tpu.transport import make_agent_transport
        from relayrl_tpu.transport.native_backend import native_available

        if not native_available():
            pytest.skip("native library not built")
        telemetry.set_registry(Registry())
        cfg = tmp_path / "relayrl_config.json"
        cfg.write_text(json.dumps({"transport": {"heartbeat_s": 1.25}}))
        transport = make_agent_transport(
            "native", ConfigLoader(None, str(cfg)), probe=False,
            server_addr="127.0.0.1:1")
        try:
            assert transport._heartbeat_default == 1.25
            names = {m["name"] for m in
                     telemetry.get_registry().snapshot()["metrics"]}
            assert "relayrl_transport_heartbeat_state" in names
        finally:
            transport.close()


class TestEpochLoggerMirror:
    def test_dump_tabular_mirrors_row_into_registry(self, tmp_path):
        from relayrl_tpu.utils.logger import EpochLogger

        reg = Registry()
        telemetry.set_registry(reg)
        logger = EpochLogger(output_dir=str(tmp_path))
        logger.store(EpRet=[1.0, 3.0])
        logger.log_tabular("Epoch", 1)
        logger.log_tabular("EpRet", average_only=True)
        logger.dump_tabular()
        by_stat = {m["labels"]["stat"]: m["value"]
                   for m in reg.snapshot()["metrics"]
                   if m["name"] == "relayrl_epoch_stat"}
        assert by_stat["Epoch"] == 1 and by_stat["EpRet"] == 2.0

    def test_dump_tabular_with_null_registry_unchanged(self, tmp_path):
        from relayrl_tpu.utils.logger import EpochLogger

        logger = EpochLogger(output_dir=str(tmp_path))
        logger.log_tabular("Epoch", 1)
        logger.dump_tabular()  # must not raise, must still write the TSV
        with open(tmp_path / "progress.txt") as f:
            assert f.read().splitlines() == ["Epoch", "1"]


class TestTopCli:
    def _snap(self, reg):
        return reg.snapshot()

    def test_render_sections_and_rates(self):
        from relayrl_tpu.telemetry import top

        reg = Registry(run_id="top")
        c = reg.counter("relayrl_server_trajectories_total")
        h = reg.histogram("relayrl_learner_publish_seconds",
                          buckets=(0.1, 1.0))
        c.inc(10)
        h.observe(0.05)
        first = self._snap(reg)
        c.inc(10)
        second = self._snap(reg)
        second["mono_ns"] = first["mono_ns"] + int(2e9)  # 2s apart
        frame = top.render(second, first)
        assert "run top" in frame
        assert "-- server" in frame and "-- learner" in frame
        assert "trajectories_total: 20 (5/s)" in frame
        assert "p50=" in frame

    def test_render_disabled(self):
        from relayrl_tpu.telemetry import top

        assert "disabled" in top.render(NullRegistry().snapshot())

    def test_histogram_quantile_estimate(self):
        from relayrl_tpu.telemetry.top import histogram_quantile

        entry = {"buckets": [1.0, 2.0, 4.0], "counts": [0, 10, 0, 0],
                 "count": 10}
        # all mass in (1, 2]: p50 interpolates to 1.5
        assert histogram_quantile(entry, 0.5) == pytest.approx(1.5)
        assert histogram_quantile({"buckets": [1.0], "counts": [0, 0],
                                   "count": 0}, 0.5) is None

    def test_main_once_against_live_exporter(self, capsys):
        from relayrl_tpu.telemetry import top

        reg = Registry(run_id="cli")
        reg.counter("relayrl_server_updates_total").inc(2)
        exporter = TelemetryExporter(reg, port=0)
        try:
            assert top.main(["--url", exporter.url, "--once"]) == 0
        finally:
            exporter.close()
        out = capsys.readouterr().out
        assert "updates_total: 2" in out

    def test_main_unreachable_errors(self):
        from relayrl_tpu.telemetry import top

        assert top.main(["--url", "http://127.0.0.1:9", "--once"]) == 1


class TestLearnerParity:
    def test_enabled_telemetry_is_bit_identical_to_disabled(self, tmp_path,
                                                            monkeypatch):
        """The acceptance bar: telemetry must be observation only — the
        learner's final params with a live registry + journal are
        BIT-identical to the disabled run on the same stream."""
        import jax

        from relayrl_tpu.algorithms import build_algorithm

        def episode(n, seed):
            rng = np.random.default_rng(seed)
            from relayrl_tpu.types.action import ActionRecord

            return [ActionRecord(
                obs=rng.standard_normal(4).astype(np.float32),
                act=np.int64(rng.integers(2)),
                rew=float(rng.random()),
                data={"logp_a": np.float32(-0.69),
                      "v": np.float32(rng.standard_normal())},
                done=(i == n - 1)) for i in range(n)]

        def run(enabled: bool):
            telemetry.reset_for_tests()
            if enabled:
                telemetry.set_registry(Registry(run_id="parity"))
                telemetry.set_journal(EventJournal(
                    str(tmp_path / "parity.ndjson"), run_id="parity"))
            algo = build_algorithm(
                "REINFORCE", obs_dim=4, act_dim=2, traj_per_epoch=2,
                hidden_sizes=[16], with_vf_baseline=True, train_vf_iters=2,
                seed_salt=0,
                logger_kwargs={"output_dir":
                               str(tmp_path / f"logs_{enabled}")})
            for i in range(6):
                algo.receive_trajectory(episode(8, seed=i))
            params = jax.device_get(algo.state.params)
            version = algo.version
            telemetry.reset_for_tests()
            return params, version

        params_off, v_off = run(enabled=False)
        params_on, v_on = run(enabled=True)
        assert v_on == v_off > 0
        for off, on in zip(jax.tree_util.tree_leaves(params_off),
                           jax.tree_util.tree_leaves(params_on)):
            np.testing.assert_array_equal(np.asarray(off), np.asarray(on))

    def test_instrumented_hot_paths_accept_null_registry(self):
        """Every instrumented primitive constructed under the default
        (disabled) registry runs its hot path with null metrics."""
        import jax.numpy as jnp

        from relayrl_tpu.runtime.pipeline import InflightWindow

        win = InflightWindow(max_in_flight=1)
        win.push(jnp.float32(1.0))
        win.drain()
        assert win.fenced_count == 1
        assert telemetry.get_registry().enabled is False
