"""Vector actor host: batched-step parity, atomic multi-lane swap,
logical-agent multiplexing over one connection, and the vector-soak smoke.

The acceptance surface of the vectorized actor plane
(runtime/vector_actor.py):

* a batch-of-1 VectorActorHost is BIT-IDENTICAL to a plain PolicyActor for
  the same PRNG key (the vector host is a batching change, not a numerics
  change);
* a mid-episode model swap applies atomically across all lanes — no
  dispatch ever mixes versions;
* all three transports carry N logical agents over ONE connection: N
  distinct registry entries, per-agent trajectory attribution preserved;
* a tiny vector soak produces >= 1 trajectory per logical agent.
"""

import threading
import time

import jax
import numpy as np
import pytest

from _util import free_port


def _reinforce_bundle(scratch, obs_dim=6, act_dim=3):
    from relayrl_tpu.algorithms import build_algorithm

    algo = build_algorithm(
        "REINFORCE", env_dir=scratch, obs_dim=obs_dim, act_dim=act_dim,
        hidden_sizes=[16], traj_per_epoch=4, with_vf_baseline=True)
    return algo.bundle()


class TestBatchOf1Parity:
    def test_bit_identical_actions_and_aux(self, tmp_cwd):
        """Same key, same obs stream → the batched path and the single
        path emit bit-equal actions, logp, and v over a whole episode,
        including the reward-attachment side channel."""
        from relayrl_tpu.runtime.policy_actor import PolicyActor
        from relayrl_tpu.runtime.vector_actor import VectorActorHost

        bundle = _reinforce_bundle(str(tmp_cwd))
        sent_single, sent_vec = [], []
        single = PolicyActor(bundle, seed=11,
                             on_send=lambda p: sent_single.append(p))
        host = VectorActorHost(
            bundle, num_envs=1,
            on_send=lambda lane, p: sent_vec.append(p),
            rng_keys=np.asarray(jax.random.PRNGKey(11))[None])
        rng = np.random.default_rng(0)
        for i in range(8):
            obs = rng.standard_normal(6).astype(np.float32)
            reward = 0.0 if i == 0 else 0.5
            r1 = single.request_for_action(obs, reward=reward)
            [r2] = host.request_for_actions(obs[None], rewards=[reward])
            assert np.array_equal(np.asarray(r1.act), np.asarray(r2.act))
            for key in r1.data:
                assert np.array_equal(np.asarray(r1.data[key]),
                                      np.asarray(r2.data[key])), key
        single.flag_last_action(1.0, terminated=True)
        host.flag_last_action(0, 1.0, terminated=True)
        # The shipped episodes are byte-identical too (same records, same
        # wire codec) — lane 0 IS a single actor.
        assert sent_single == sent_vec

    def test_masked_parity(self, tmp_cwd):
        from relayrl_tpu.runtime.policy_actor import PolicyActor
        from relayrl_tpu.runtime.vector_actor import VectorActorHost

        bundle = _reinforce_bundle(str(tmp_cwd))
        single = PolicyActor(bundle, seed=3)
        host = VectorActorHost(
            bundle, num_envs=1,
            rng_keys=np.asarray(jax.random.PRNGKey(3))[None])
        rng = np.random.default_rng(1)
        mask = np.array([1.0, 0.0, 1.0], np.float32)
        for _ in range(4):
            obs = rng.standard_normal(6).astype(np.float32)
            r1 = single.request_for_action(obs, mask=mask)
            [r2] = host.request_for_actions(obs[None], masks=mask[None])
            assert np.array_equal(np.asarray(r1.act), np.asarray(r2.act))
            assert int(np.asarray(r2.act)) != 1  # mask respected

    def test_window_policy_parity(self, tmp_cwd):
        """Sequence policies: the batched padded-window path must be
        bit-identical to PolicyActor's window path for the same key,
        through window fill AND past the cap into rolling (this is the
        test that pins step_window's t = count-of-real-rows convention)."""
        from relayrl_tpu.models import build_policy
        from relayrl_tpu.runtime.policy_actor import PolicyActor
        from relayrl_tpu.runtime.vector_actor import VectorActorHost
        from relayrl_tpu.types.model_bundle import ModelBundle

        arch = {"kind": "transformer_discrete", "obs_dim": 5, "act_dim": 3,
                "d_model": 16, "n_layers": 1, "n_heads": 2,
                "max_seq_len": 8}
        policy = build_policy(arch)
        bundle = ModelBundle(version=1, arch=dict(arch),
                             params=policy.init_params(jax.random.PRNGKey(0)))
        # use_kv_cache=False pins the single actor to the window path the
        # vector host vmaps — the comparison is then exact, not
        # cache-vs-window numerics.
        single = PolicyActor(bundle, seed=9, use_kv_cache=False)
        host = VectorActorHost(
            bundle, num_envs=1,
            rng_keys=np.asarray(jax.random.PRNGKey(9))[None])
        rng = np.random.default_rng(4)
        for i in range(12):  # 8-slot window: fills at 8, rolls after
            obs = rng.standard_normal(5).astype(np.float32)
            r1 = single.request_for_action(obs)
            [r2] = host.request_for_actions(obs[None])
            assert np.array_equal(np.asarray(r1.act),
                                  np.asarray(r2.act)), f"step {i}"
            for key in r1.data:
                assert np.array_equal(np.asarray(r1.data[key]),
                                      np.asarray(r2.data[key])), (i, key)
        # episode boundary resets both window stores identically
        single.flag_last_action(1.0, terminated=True)
        host.flag_last_action(0, 1.0, terminated=True)
        obs = rng.standard_normal(5).astype(np.float32)
        r1 = single.request_for_action(obs)
        [r2] = host.request_for_actions(obs[None])
        assert np.array_equal(np.asarray(r1.act), np.asarray(r2.act))

    def test_lanes_decorrelate(self, tmp_cwd):
        """Distinct per-lane keys → lanes do not emit one shared action
        stream (the whole point of per-env key splitting)."""
        from relayrl_tpu.runtime.vector_actor import VectorActorHost

        bundle = _reinforce_bundle(str(tmp_cwd))
        host = VectorActorHost(bundle, num_envs=8, seed=0)
        rng = np.random.default_rng(2)
        obs = np.repeat(rng.standard_normal(6).astype(np.float32)[None],
                        8, axis=0)
        acts = []
        for _ in range(16):
            acts.append([int(np.asarray(r.act))
                         for r in host.request_for_actions(obs)])
        acts = np.asarray(acts)  # [steps, lanes], identical obs every lane
        assert any(len(set(acts[:, lane].tolist()))
                   != len(set(acts[:, 0].tolist()))
                   or not np.array_equal(acts[:, lane], acts[:, 0])
                   for lane in range(1, 8)), "all lanes sampled identically"


class TestAtomicSwap:
    def _versioned_bundle(self, bundle, version):
        """Params whose value head outputs exactly ``version`` for any
        obs (zero weights, bias=version): aux['v'] reveals which params
        produced each action."""
        from relayrl_tpu.types.model_bundle import ModelBundle

        params = jax.tree_util.tree_map(np.asarray, bundle.params)
        import copy

        params = copy.deepcopy(params)
        params["params"]["vf_head"]["kernel"] = np.zeros_like(
            params["params"]["vf_head"]["kernel"])
        params["params"]["vf_head"]["bias"] = np.full_like(
            params["params"]["vf_head"]["bias"], float(version))
        vt = params["params"]["vf_trunk"]
        for layer in vt.values():
            layer["bias"] = np.zeros_like(layer["bias"])
        return ModelBundle(arch=dict(bundle.arch), params=params,
                           version=version)

    def test_swap_applies_atomically_across_lanes(self, tmp_cwd):
        """A swapper thread races the stepping thread: every dispatch's
        aux['v'] must be constant across lanes (one params read per
        batch), and the final dispatches must run on the newest version."""
        from relayrl_tpu.runtime.vector_actor import VectorActorHost

        base = _reinforce_bundle(str(tmp_cwd))
        n_lanes = 8
        host = VectorActorHost(self._versioned_bundle(base, 1),
                               num_envs=n_lanes, seed=0, validate=False)
        rng = np.random.default_rng(0)
        stop = threading.Event()
        next_version = [2]

        def swapper():
            while not stop.is_set():
                host.maybe_swap(
                    self._versioned_bundle(base, next_version[0]))
                next_version[0] += 1
                time.sleep(0.002)

        t = threading.Thread(target=swapper, daemon=True)
        t.start()
        try:
            mixed = []
            for _ in range(100):
                obs = rng.standard_normal((n_lanes, 6)).astype(np.float32)
                records = host.request_for_actions(obs)
                versions = {float(np.asarray(r.data["v"])) for r in records}
                if len(versions) != 1:
                    mixed.append(versions)
        finally:
            stop.set()
            t.join(timeout=5)
        assert not mixed, f"dispatch mixed model versions: {mixed[:3]}"
        assert host.version >= 2  # swaps actually landed mid-run

    def test_stale_and_mismatched_swaps_rejected(self, tmp_cwd):
        from relayrl_tpu.runtime.vector_actor import VectorActorHost

        base = _reinforce_bundle(str(tmp_cwd))
        host = VectorActorHost(self._versioned_bundle(base, 5),
                               num_envs=2, seed=0, validate=False)
        assert not host.maybe_swap(self._versioned_bundle(base, 5))
        assert not host.maybe_swap(self._versioned_bundle(base, 4))
        assert host.maybe_swap(self._versioned_bundle(base, 6))
        assert host.version == 6


def _multiplex_roundtrip(server, make_agent, n_lanes=4):
    """N logical agents over ONE agent transport: N registry entries,
    per-agent trajectory attribution preserved."""
    received, registered = [], []
    server.get_model = lambda: (1, b"MODEL")
    server.on_trajectory = lambda aid, p: received.append((aid, p))
    server.on_register = registered.append
    server.start()
    try:
        agent = make_agent()
        try:
            assert agent.fetch_model(timeout_s=15) == (1, b"MODEL")
            lane_ids = [f"{agent.identity}.lane{k}" for k in range(n_lanes)]
            for lane_id in lane_ids:
                assert agent.register(lane_id, timeout_s=10), lane_id
            for k, lane_id in enumerate(lane_ids):
                agent.send_trajectory(b"traj-%d" % k, agent_id=lane_id)
            deadline = time.monotonic() + 10
            while len(received) < n_lanes and time.monotonic() < deadline:
                time.sleep(0.01)
            assert sorted(received) == [
                (lane_ids[k], b"traj-%d" % k) for k in range(n_lanes)]
            deadline = time.monotonic() + 10
            while (len(set(registered)) < n_lanes
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert set(lane_ids) <= set(registered)
        finally:
            agent.close()
    finally:
        server.stop()


class TestMultiplexedRegistration:
    def test_zmq(self, tmp_cwd):
        from relayrl_tpu.config import ConfigLoader
        from relayrl_tpu.transport import (
            make_agent_transport,
            make_server_transport,
        )

        cfg = ConfigLoader(create_if_missing=False)
        ports = [free_port() for _ in range(3)]
        server = make_server_transport(
            "zmq", cfg,
            agent_listener_addr=f"tcp://127.0.0.1:{ports[0]}",
            trajectory_addr=f"tcp://127.0.0.1:{ports[1]}",
            model_pub_addr=f"tcp://127.0.0.1:{ports[2]}")
        _multiplex_roundtrip(server, lambda: make_agent_transport(
            "zmq", cfg, probe=False,
            agent_listener_addr=f"tcp://127.0.0.1:{ports[0]}",
            trajectory_addr=f"tcp://127.0.0.1:{ports[1]}",
            model_sub_addr=f"tcp://127.0.0.1:{ports[2]}"))

    def test_grpc(self, tmp_cwd):
        from relayrl_tpu.config import ConfigLoader
        from relayrl_tpu.transport import (
            make_agent_transport,
            make_server_transport,
        )

        cfg = ConfigLoader(create_if_missing=False)
        port = free_port()
        # Pin the pure-grpcio server: the native gRPC plane is covered by
        # its own fuzz suite, and this test targets the Python servicer's
        # logical-registration path.
        server = make_server_transport("grpc", cfg,
                                       bind_addr=f"127.0.0.1:{port}",
                                       native_grpc=False)
        _multiplex_roundtrip(server, lambda: make_agent_transport(
            "grpc", cfg, probe=False, server_addr=f"127.0.0.1:{port}"))

    def test_native(self, tmp_cwd):
        from relayrl_tpu.config import ConfigLoader
        from relayrl_tpu.transport import (
            make_agent_transport,
            make_server_transport,
        )
        from relayrl_tpu.transport.native_backend import native_available

        if not native_available():
            pytest.skip("native library not built (make -C native)")
        cfg = ConfigLoader(create_if_missing=False)
        port = free_port()
        server = make_server_transport("native", cfg,
                                       bind_addr=f"127.0.0.1:{port}")
        _multiplex_roundtrip(server, lambda: make_agent_transport(
            "native", cfg, probe=False, server_addr=f"127.0.0.1:{port}"))

    def test_native_unregisters_every_lane_on_drop(self, tmp_cwd):
        """A dead vector host must reap ALL of its logical agents from
        the registry, not just the last-registered one."""
        from relayrl_tpu.config import ConfigLoader
        from relayrl_tpu.transport import (
            make_agent_transport,
            make_server_transport,
        )
        from relayrl_tpu.transport.native_backend import native_available

        if not native_available():
            pytest.skip("native library not built (make -C native)")
        cfg = ConfigLoader(create_if_missing=False)
        port = free_port()
        server = make_server_transport("native", cfg,
                                       bind_addr=f"127.0.0.1:{port}")
        server.get_model = lambda: (1, b"M")
        unregistered = []
        server.on_unregister = unregistered.append
        server.start()
        try:
            agent = make_agent_transport("native", cfg, probe=False,
                                         server_addr=f"127.0.0.1:{port}")
            agent.fetch_model(timeout_s=15)
            for k in range(3):
                assert agent.register(f"lane-{k}", timeout_s=10)
            agent.close()
            deadline = time.monotonic() + 10
            while len(unregistered) < 3 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert sorted(unregistered) == ["lane-0", "lane-1", "lane-2"]
        finally:
            server.stop()


class TestSyncVectorEnv:
    def test_autoreset_preserves_final_observation(self):
        from relayrl_tpu.envs import CartPoleEnv, SyncVectorEnv

        venv = SyncVectorEnv([CartPoleEnv for _ in range(3)])
        obs, _ = venv.reset(seed=0)
        assert obs.shape == (3, 4)
        done_seen = False
        for _ in range(200):
            obs, rews, terms, truncs, infos = venv.step([1, 1, 1])
            assert obs.shape == (3, 4)
            for lane in range(3):
                if terms[lane] or truncs[lane]:
                    done_seen = True
                    final = infos[lane]["final_observation"]
                    # autoreset: the row is the NEXT episode's first obs,
                    # the pre-reset obs rides the info dict
                    assert final.shape == (4,)
                    assert not np.array_equal(obs[lane], final)
            if done_seen:
                break
        assert done_seen, "always-right CartPole never terminated?"

    def test_autoreset_preserves_reset_info_dict(self):
        """The autoreset's reset() info dict must survive under
        "reset_info" (it used to be discarded with ``obs, _ =
        env.reset()``), alongside the final_observation."""
        from relayrl_tpu.envs import SyncVectorEnv

        class InfoEnv:
            """Counts resets and echoes the seed it was reset with."""

            def __init__(self):
                from relayrl_tpu.envs import Box, Discrete

                self.observation_space = Box(-1, 1, shape=(2,))
                self.action_space = Discrete(2)
                self.resets = 0

            def reset(self, seed=None):
                self.resets += 1
                return (np.zeros(2, np.float32),
                        {"reset_seed": seed, "nth_reset": self.resets})

            def step(self, action):
                return np.ones(2, np.float32), 1.0, True, False, {}

        venv = SyncVectorEnv([InfoEnv for _ in range(2)])
        venv.reset(seed=100)
        _, _, terms, _, infos = venv.step([0, 0])
        assert terms.all()
        for lane in range(2):
            info = infos[lane]
            np.testing.assert_array_equal(info["final_observation"],
                                          np.ones(2, np.float32))
            assert info["reset_info"]["nth_reset"] == 2

    def test_autoreset_derived_seed_reproducible(self):
        """Seeded stacks stay reproducible across autoresets: episode e
        of lane k resets with ``seed + k + num_envs*e`` (episode 0 is
        exactly the documented ``seed + lane`` contract), so two
        identically-seeded stacks replay identical state streams forever,
        and distinct (lane, episode) pairs never share a seed."""
        from relayrl_tpu.envs import CartPoleEnv, SyncVectorEnv

        def run(n_steps=120):
            venv = SyncVectorEnv([CartPoleEnv for _ in range(3)])
            obs, _ = venv.reset(seed=42)
            rows, seeds = [obs], []
            for _ in range(n_steps):
                obs, _, terms, truncs, infos = venv.step([1, 1, 1])
                rows.append(obs)
                for lane in range(3):
                    if terms[lane] or truncs[lane]:
                        seeds.append(
                            infos[lane]["reset_info"].get("seed_used"))
            return np.concatenate(rows), venv._episode

        a, eps_a = run()
        b, eps_b = run()
        np.testing.assert_array_equal(a, b)
        assert eps_a == eps_b and sum(eps_a) >= 3  # boundaries crossed
        # unseeded stacks keep entropy-seeded autoresets (no determinism)
        from relayrl_tpu.envs import CartPoleEnv as CP, SyncVectorEnv as SV

        venv = SV([CP for _ in range(1)])
        venv.reset()  # no seed
        assert venv._autoreset_seed(0) is None

    def test_autoreset_seed_derivation_is_collision_free(self):
        from relayrl_tpu.envs import CartPoleEnv, SyncVectorEnv

        venv = SyncVectorEnv([CartPoleEnv for _ in range(4)])
        venv.reset(seed=7)
        seen = set()
        for lane in range(4):
            for ep in range(5):
                venv._episode[lane] = ep
                seen.add(venv._autoreset_seed(lane))
        assert len(seen) == 20  # distinct across every (lane, episode)

    def test_vector_loop_with_host(self, tmp_cwd):
        """run_vector_gym_loop end-to-end over a raw host: every lane
        ships episodes through the wire codec."""
        from relayrl_tpu.envs import CartPoleEnv, SyncVectorEnv
        from relayrl_tpu.runtime.vector_actor import (
            VectorActorHost,
            run_vector_gym_loop,
        )
        from relayrl_tpu.types.trajectory import deserialize_actions

        bundle = _reinforce_bundle(str(tmp_cwd), obs_dim=4, act_dim=2)
        sent: list[tuple[int, bytes]] = []
        host = VectorActorHost(
            bundle, num_envs=3,
            on_send=lambda lane, p: sent.append((lane, p)))
        venv = SyncVectorEnv([CartPoleEnv for _ in range(3)])
        returns = run_vector_gym_loop(host, venv, steps=120, seed=0)
        lanes_shipped = {lane for lane, _ in sent}
        assert lanes_shipped == {0, 1, 2}
        assert all(returns[lane] for lane in range(3))
        # each lane's shipped episode decodes, ending in a terminal marker
        lane0 = next(p for lane, p in sent if lane == 0)
        actions = deserialize_actions(lane0)
        assert actions[-1].done


class TestVectorSoakSmoke:
    # ISSUE 17 wall re-fit: soak smokes live in the slow tier alongside
    # the bench-scale soak (tests/test_soak.py keeps the fast quick shape).
    @pytest.mark.slow
    def test_quick_vector_soak_one_traj_per_logical_agent(
            self, monkeypatch, tmp_path):
        """Tiny bench_soak --quick --vector shape: 4 logical agents in
        one process must each land >= 1 attributed trajectory (the CI
        gate for the vector actor plane)."""
        import os
        import sys

        benches = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "benches")
        monkeypatch.syspath_prepend(benches)
        monkeypatch.chdir(tmp_path)
        import bench_soak

        result = bench_soak.run_soak(
            n_actors=4, agents_per_proc=4, duration_s=3.0,
            traj_per_epoch=8, vector=True)
        assert result["agents_completed"] == 4
        assert result["agents_crashed"] == 0
        assert result["server_stats"]["dropped"] == 0
        assert result["min_episodes_per_agent"] >= 1
        assert result["distinct_traj_agents"] == 4  # per-lane attribution
