"""Reward-credit alignment on the wire path (the round-4 fix).

The protocol delivers the reward for action t with request t+1 (or with
the terminal marker). The reference stores that incoming reward on the
NEW record (agent_grpc.rs:434-441), shifting every reward one step late —
tolerable for return-to-go policy gradients, but it inverts 1-step TD
credit (DQN on a bandit converged to the WRONG arm). Our actor instead
back-attaches the reward to the previous record via ``update_reward``, so
``ActionRecord.rew`` always means "reward earned BY this action". These
tests pin that invariant at every consumer: the raw wire bytes, the
on-policy padded fold, and the off-policy transition assembly.
"""

import numpy as np
import pytest

from relayrl_tpu.runtime.policy_actor import PolicyActor
from relayrl_tpu.types.model_bundle import ModelBundle
from relayrl_tpu.types.trajectory import deserialize_actions

OBS_DIM, ACT_DIM = 4, 2


@pytest.fixture
def actor():
    from relayrl_tpu.models import build_policy

    arch = {"kind": "mlp_discrete", "obs_dim": OBS_DIM, "act_dim": ACT_DIM,
            "hidden_sizes": [8]}
    policy = build_policy(arch)
    import jax

    params = policy.init_params(jax.random.PRNGKey(0))
    sent: list[bytes] = []
    a = PolicyActor(ModelBundle(version=1, arch=arch, params=params),
                    max_traj_length=100, on_send=sent.append, seed=0)
    a._sent = sent
    return a


def drive_episode(actor, rewards):
    """The canonical loop: reward for action t arrives with request t+1;
    the last action's reward rides the terminal marker."""
    obs = np.zeros(OBS_DIM, np.float32)
    actor.request_for_action(obs, reward=0.0)
    for r in rewards[:-1]:
        actor.request_for_action(obs, reward=r)
    actor.flag_last_action(rewards[-1], truncated=False)


def test_wire_records_carry_earned_rewards(actor):
    rewards = [1.0, -2.0, 3.0, 0.5]
    drive_episode(actor, rewards)
    assert len(actor._sent) == 1
    records = deserialize_actions(actor._sent[0])
    steps = [r for r in records if r.act is not None]
    marker = [r for r in records if r.act is None]
    assert len(steps) == 4 and len(marker) == 1
    # Every step's rew is the reward ITS action earned (marker carries the
    # final one; fold_trailing_markers adds it to the last step).
    assert [s.rew for s in steps] == [1.0, -2.0, 3.0, 0.0]
    assert marker[0].rew == 0.5


def test_onpolicy_fold_total_and_alignment(actor):
    from relayrl_tpu.data.batching import pad_trajectory

    rewards = [1.0, -2.0, 3.0, 0.5]
    drive_episode(actor, rewards)
    padded = pad_trajectory(deserialize_actions(actor._sent[0]),
                            horizon=8, obs_dim=OBS_DIM, act_dim=ACT_DIM,
                            discrete=True)
    assert padded.length == 4
    assert list(padded.rew[:4]) == [1.0, -2.0, 3.0, 0.5]
    assert float(padded.rew.sum()) == pytest.approx(sum(rewards))


def test_offpolicy_transitions_pair_action_with_its_reward(actor):
    from relayrl_tpu.data.step_buffer import StepReplayBuffer

    rewards = [1.0, -2.0, 3.0, 0.5]
    drive_episode(actor, rewards)
    buf = StepReplayBuffer(obs_dim=OBS_DIM, act_dim=ACT_DIM, capacity=100,
                           discrete=True, seed=0)
    stored = buf.add_episode(deserialize_actions(actor._sent[0]))
    assert stored == 4
    assert list(buf.rew[:4]) == [1.0, -2.0, 3.0, 0.5]
    assert buf.done[3] == 1.0 and all(buf.done[:3] == 0.0)


def test_zero_rewards_do_not_mark_updated(actor):
    drive_episode(actor, [0.0, 0.0, 1.0])
    records = deserialize_actions(actor._sent[0])
    steps = [r for r in records if r.act is not None]
    assert [s.rew for s in steps] == [0.0, 0.0, 0.0]
    assert not steps[0].reward_updated
