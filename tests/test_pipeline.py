"""Pipeline parallelism: schedule correctness + the pp transformer family.

All on the conftest's 8 virtual CPU devices. The pipelined result must be
numerically identical (up to reduction order) to the plain sequential scan
over the same stacked layer params — forward AND gradients, since the
learner differentiates through the schedule.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relayrl_tpu.models import build_policy
from relayrl_tpu.parallel import make_mesh
from relayrl_tpu.parallel.context import use_mesh
from relayrl_tpu.parallel.pipeline import pipeline_apply, resolve_microbatches


def _stacked_mlp(n_layers=4, width=16, seed=0):
    rng = np.random.default_rng(seed)
    w = jnp.asarray(rng.standard_normal((n_layers, width, width)) * 0.3,
                    jnp.float32)
    b = jnp.asarray(rng.standard_normal((n_layers, width)) * 0.1, jnp.float32)
    return {"w": w, "b": b}


def _stage(params, h):
    def layer(c, p):
        return jnp.tanh(c @ p[0] + p[1]), None

    return jax.lax.scan(layer, h, (params["w"], params["b"]))[0]


class TestPipelineApply:
    @pytest.mark.parametrize("mesh_spec,n_micro", [
        ({"dp": -1, "pp": 4}, None),
        ({"dp": 2, "pp": 4}, 4),
        ({"dp": -1, "pp": 2}, 2),
    ])
    def test_matches_sequential(self, mesh_spec, n_micro):
        mesh = make_mesh(mesh_spec)
        params = _stacked_mlp()
        x = jnp.asarray(np.random.default_rng(1).standard_normal((8, 16)),
                        jnp.float32)
        want = _stage(params, x)
        got = jax.jit(lambda p, h: pipeline_apply(
            _stage, p, h, mesh, n_microbatches=n_micro))(params, x)
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_gradients_match_sequential(self):
        mesh = make_mesh({"dp": 2, "pp": 4})
        params = _stacked_mlp()
        x = jnp.asarray(np.random.default_rng(2).standard_normal((8, 16)),
                        jnp.float32)

        want = jax.grad(
            lambda p: jnp.sum(jnp.sin(_stage(p, x))))(params)
        got = jax.jit(jax.grad(lambda p: jnp.sum(jnp.sin(
            pipeline_apply(_stage, p, x, mesh)))))(params)
        for key in ("w", "b"):
            np.testing.assert_allclose(got[key], want[key], atol=1e-4,
                                       rtol=1e-4, err_msg=key)

    def test_single_stage_passthrough(self):
        mesh = make_mesh({"dp": -1, "pp": 1})
        params = _stacked_mlp()
        x = jnp.ones((4, 16), jnp.float32)
        np.testing.assert_allclose(
            pipeline_apply(_stage, params, x, mesh), _stage(params, x))

    def test_resolve_microbatches(self):
        assert resolve_microbatches(8, 4) == 4
        assert resolve_microbatches(8, 4, requested=8) == 8
        assert resolve_microbatches(6, 4) == 3       # largest divisor <= 4
        assert resolve_microbatches(7, 4) == 1
        assert resolve_microbatches(8, 4, requested=3) == 4  # 3 ∤ 8 -> auto


class TestPPTransformerPolicy:
    ARCH = {"kind": "transformer_pp_discrete", "obs_dim": 6, "act_dim": 3,
            "d_model": 16, "n_layers": 4, "n_heads": 2, "max_seq_len": 8}

    def test_pipelined_evaluate_matches_local(self):
        policy = build_policy(self.ARCH)
        params = policy.init_params(jax.random.PRNGKey(0))
        obs = jnp.asarray(
            np.random.default_rng(0).standard_normal((4, 8, 6)), jnp.float32)
        act = jnp.zeros((4, 8), jnp.int32)
        logp0, ent0, v0 = policy.evaluate(params, obs, act)

        mesh = make_mesh({"dp": 2, "pp": 4})
        with use_mesh(mesh):
            logp1, ent1, v1 = jax.jit(policy.evaluate)(params, obs, act)
        np.testing.assert_allclose(logp1, logp0, atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(v1, v0, atol=1e-5, rtol=1e-5)

    def test_sharded_reinforce_update_on_pp_mesh(self):
        from relayrl_tpu.algorithms.reinforce import (
            ReinforceState,
            make_optimizers,
            make_reinforce_update,
        )
        from relayrl_tpu.parallel import (
            make_sharded_update,
            place_batch,
            place_state,
        )

        mesh = make_mesh({"dp": 2, "pp": 4})
        policy = build_policy(self.ARCH)
        params = policy.init_params(jax.random.PRNGKey(0))
        tx_pi, tx_vf = make_optimizers(params, 3e-4, 1e-3)
        state = ReinforceState(params=params, pi_opt_state=tx_pi.init(params),
                               vf_opt_state=tx_vf.init(params),
                               rng=jax.random.PRNGKey(1), step=jnp.int32(0))
        update = make_reinforce_update(policy, 3e-4, 1e-3, 2, 0.99, 0.95,
                                       with_baseline=True)
        rng = np.random.default_rng(0)
        B, T = 8, 8
        batch = {
            "obs": rng.standard_normal((B, T, 6)).astype(np.float32),
            "act": rng.integers(0, 3, (B, T)).astype(np.int32),
            "act_mask": np.ones((B, T, 3), np.float32),
            "rew": np.ones((B, T), np.float32),
            "val": np.zeros((B, T), np.float32),
            "logp": np.zeros((B, T), np.float32),
            "valid": np.ones((B, T), np.float32),
            "last_val": np.zeros((B,), np.float32),
        }
        sharded = make_sharded_update(update, mesh, state, donate_state=False)
        new_state, metrics = sharded(place_state(state, mesh),
                                     place_batch(batch, mesh))
        jax.block_until_ready(new_state)
        assert int(new_state.step) == 1
        assert np.isfinite(float(metrics["LossPi"]))
        # blocks must actually be sharded over pp
        from relayrl_tpu.parallel.sharding import param_pspec

        spec = param_pspec(
            (jax.tree_util.DictKey("params"), jax.tree_util.DictKey("blocks"),
             jax.tree_util.DictKey("qkv"), jax.tree_util.DictKey("kernel")),
            jnp.zeros((4, 16, 48)), mesh)
        assert spec[0] == "pp"


class TestCombinedAxes:
    def test_pp_with_fsdp_and_dp(self):
        # pp shards the layer stack; fsdp takes non-block params; dp splits
        # the batch — all three in one mesh must compose (the rule order
        # in parallel/sharding.py: pp before ep/fsdp).
        from relayrl_tpu.algorithms.reinforce import (
            ReinforceState,
            make_optimizers,
            make_reinforce_update,
        )
        from relayrl_tpu.parallel import (
            make_sharded_update,
            place_batch,
            place_state,
        )

        mesh = make_mesh({"dp": 2, "fsdp": 2, "pp": 2})
        policy = build_policy({"kind": "transformer_pp_discrete",
                               "obs_dim": 6, "act_dim": 3, "d_model": 16,
                               "n_layers": 4, "n_heads": 2,
                               "max_seq_len": 8})
        params = policy.init_params(jax.random.PRNGKey(0))
        tx_pi, tx_vf = make_optimizers(params, 3e-4, 1e-3)
        state = ReinforceState(params=params, pi_opt_state=tx_pi.init(params),
                               vf_opt_state=tx_vf.init(params),
                               rng=jax.random.PRNGKey(1), step=jnp.int32(0))
        update = make_reinforce_update(policy, 3e-4, 1e-3, 1, 0.99, 0.95,
                                       with_baseline=True)
        rng = np.random.default_rng(0)
        B, T = 8, 8
        batch = {
            "obs": rng.standard_normal((B, T, 6)).astype(np.float32),
            "act": rng.integers(0, 3, (B, T)).astype(np.int32),
            "act_mask": np.ones((B, T, 3), np.float32),
            "rew": np.ones((B, T), np.float32),
            "val": np.zeros((B, T), np.float32),
            "logp": np.zeros((B, T), np.float32),
            "valid": np.ones((B, T), np.float32),
            "last_val": np.zeros((B,), np.float32),
        }
        sharded = make_sharded_update(update, mesh, state, donate_state=False)
        new_state, metrics = sharded(place_state(state, mesh),
                                     place_batch(batch, mesh))
        jax.block_until_ready(new_state)
        assert int(new_state.step) == 1
        assert np.isfinite(float(metrics["LossPi"]))
