"""GAE/discount ops vs. straightforward numpy references (the reference's
scipy lfilter math, BaseReplayBuffer.py:6-83 / replay_buffer.py:48-79)."""

import numpy as np
import pytest

from relayrl_tpu.ops import (
    discount_cumsum,
    gae_advantages,
    masked_mean_std,
    normalize_advantages,
    rewards_to_go,
)


def np_discount_cumsum(x, discount):
    out = np.zeros_like(x, dtype=np.float64)
    running = 0.0
    for t in reversed(range(len(x))):
        running = x[t] + discount * running
        out[t] = running
    return out


class TestDiscountCumsum:
    @pytest.mark.parametrize("discount", [0.0, 0.5, 0.99, 1.0])
    def test_matches_reference_math(self, discount):
        rng = np.random.default_rng(0)
        x = rng.standard_normal(37).astype(np.float32)
        out = np.asarray(discount_cumsum(x, discount))
        np.testing.assert_allclose(out, np_discount_cumsum(x, discount), rtol=1e-4, atol=1e-5)

    def test_batched(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((4, 16)).astype(np.float32)
        out = np.asarray(discount_cumsum(x, 0.9))
        for b in range(4):
            np.testing.assert_allclose(out[b], np_discount_cumsum(x[b], 0.9), rtol=1e-4, atol=1e-5)


class TestRewardsToGo:
    def test_padding_zeroed(self):
        rew = np.array([[1, 1, 1, 0, 0]], dtype=np.float32)
        valid = np.array([[1, 1, 1, 0, 0]], dtype=np.float32)
        out = np.asarray(rewards_to_go(rew, valid, 1.0))
        np.testing.assert_allclose(out[0], [3, 2, 1, 0, 0], atol=1e-6)

    def test_padding_does_not_leak(self):
        # Garbage in padded reward slots must not affect valid outputs.
        rew = np.array([[1, 1, 99, 99]], dtype=np.float32)
        valid = np.array([[1, 1, 0, 0]], dtype=np.float32)
        out = np.asarray(rewards_to_go(rew, valid, 0.9))
        np.testing.assert_allclose(out[0, :2], [1 + 0.9, 1.0], atol=1e-5)


class TestGAE:
    def test_terminal_episode_matches_reference_formula(self):
        # Hand-computed GAE on a 3-step terminal episode.
        gamma, lam = 0.9, 0.8
        rew = np.array([[1.0, 2.0, 3.0, 0.0]], dtype=np.float32)
        val = np.array([[0.5, 0.4, 0.3, 0.0]], dtype=np.float32)
        valid = np.array([[1, 1, 1, 0]], dtype=np.float32)
        adv, ret = gae_advantages(rew, val, valid, gamma, lam, np.zeros(1, np.float32))
        deltas = [
            1.0 + gamma * 0.4 - 0.5,
            2.0 + gamma * 0.3 - 0.4,
            3.0 + gamma * 0.0 - 0.3,
        ]
        expected = np_discount_cumsum(np.array(deltas), gamma * lam)
        np.testing.assert_allclose(np.asarray(adv)[0, :3], expected, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(adv)[0, 3], 0.0)
        np.testing.assert_allclose(
            np.asarray(ret)[0, :3], np_discount_cumsum(rew[0, :3], gamma), rtol=1e-4)

    def test_truncated_bootstrap(self):
        gamma, lam = 0.99, 0.95
        rew = np.array([[1.0, 1.0]], dtype=np.float32)
        val = np.array([[0.2, 0.3]], dtype=np.float32)
        valid = np.array([[1, 1]], dtype=np.float32)
        last_val = np.array([0.7], dtype=np.float32)
        adv, _ = gae_advantages(rew, val, valid, gamma, lam, last_val)
        deltas = [1.0 + gamma * 0.3 - 0.2, 1.0 + gamma * 0.7 - 0.3]
        expected = np_discount_cumsum(np.array(deltas), gamma * lam)
        np.testing.assert_allclose(np.asarray(adv)[0], expected, rtol=1e-4, atol=1e-5)

    def test_batch_of_mixed_lengths(self):
        gamma, lam = 0.95, 0.9
        rew = np.array([[1, 1, 1, 1], [2, 2, 0, 0]], dtype=np.float32)
        val = np.zeros((2, 4), dtype=np.float32)
        valid = np.array([[1, 1, 1, 1], [1, 1, 0, 0]], dtype=np.float32)
        adv, ret = gae_advantages(rew, val, valid, gamma, lam, np.zeros(2, np.float32))
        np.testing.assert_allclose(np.asarray(ret)[1, 2:], 0.0)
        np.testing.assert_allclose(
            np.asarray(ret)[1, :2], np_discount_cumsum(np.array([2.0, 2.0]), gamma), rtol=1e-4)


class TestNormalization:
    def test_masked_mean_std(self):
        x = np.array([[1.0, 2.0, 3.0, 100.0]], dtype=np.float32)
        valid = np.array([[1, 1, 1, 0]], dtype=np.float32)
        mean, std = masked_mean_std(x, valid)
        assert float(mean) == pytest.approx(2.0, abs=1e-5)
        assert float(std) == pytest.approx(np.std([1, 2, 3]), abs=1e-4)

    def test_normalize_ignores_padding(self):
        x = np.array([[1.0, 2.0, 3.0, 1e6]], dtype=np.float32)
        valid = np.array([[1, 1, 1, 0]], dtype=np.float32)
        out = np.asarray(normalize_advantages(x, valid))
        assert out[0, 3] == 0.0
        assert abs(out[0, :3].mean()) < 1e-5
