"""jaxlint (relayrl_tpu.analysis) — rule units, suppression/baseline
mechanics, CLI contract, and the repo-wide lint gate.

Layout mirrors docs/static_analysis.md: every rule has at least one
positive (fires) and one negative (stays silent) snippet; the gate test
at the bottom is the CI hook — it fails the suite the moment a new
non-baselined finding lands anywhere in the framework tree.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from relayrl_tpu.analysis import (
    all_rules,
    analyze_paths,
    analyze_source,
    apply_baseline,
    load_baseline,
    main,
    rules_by_code,
    write_baseline,
)

pytestmark = pytest.mark.jaxlint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "relayrl_tpu")
BASELINE = os.path.join(PKG, "analysis", "baseline.json")

# Everything the gate covers: the package plus every committed harness
# that ships with the framework.
GATE_PATHS = [
    PKG,
    os.path.join(REPO, "benches"),
    os.path.join(REPO, "examples"),
    os.path.join(REPO, "scripts"),
    os.path.join(REPO, "tests"),
    os.path.join(REPO, "bench.py"),
]


def codes(src: str) -> list[str]:
    return [f.rule for f in analyze_source(textwrap.dedent(src), "x.py")]


class TestRegistry:
    def test_at_least_eight_rules(self):
        assert len(all_rules()) >= 8

    def test_codes_unique_and_described(self):
        by_code = rules_by_code()  # raises on duplicates
        for code, rule in by_code.items():
            assert code and rule.name and rule.description, code


class TestPrngKeyReuse:
    def test_positive_reuse(self):
        assert codes("""
            import jax
            def f(rng):
                a = jax.random.normal(rng, (3,))
                b = jax.random.uniform(rng, (3,))
                return a + b
        """) == ["JAX01"]

    def test_positive_reuse_in_loop(self):
        assert "JAX01" in codes("""
            import jax
            def f(rng, n):
                out = []
                for _ in range(n):
                    out.append(jax.random.normal(rng, (3,)))
                return out
        """)

    def test_negative_split_chain(self):
        assert codes("""
            import jax
            def f(rng):
                rng, sub = jax.random.split(rng)
                a = jax.random.normal(sub, (3,))
                rng, sub = jax.random.split(rng)
                return a + jax.random.uniform(sub, (3,))
        """) == []

    def test_negative_loop_with_resplit(self):
        assert codes("""
            import jax
            def f(rng, n):
                out = []
                for _ in range(n):
                    rng, sub = jax.random.split(rng)
                    out.append(jax.random.normal(sub, (3,)))
                return out
        """) == []

    def test_negative_prngkey_int_seed_is_not_a_key(self):
        # PRNGKey(seed) consumes an INT, not a key — a seeded loop of
        # fresh keys (test/bench idiom) must not flag.
        assert codes("""
            import jax
            def f(policy, params, obs):
                for seed in range(5):
                    policy.step(params, jax.random.PRNGKey(seed), obs)
        """) == []

    def test_negative_two_lambdas_each_binding_rng(self):
        # lambda params are fresh bindings — no cross-lambda reuse
        assert codes("""
            import jax
            f = lambda rng: jax.random.normal(rng, (3,))
            g = lambda rng: jax.random.uniform(rng, (3,))
        """) == []

    def test_negative_comprehension_iteration_var(self):
        # the canonical `for k in jax.random.split(rng, n)` fan-out
        assert codes("""
            import jax
            def f(rng, n):
                keys = jax.random.split(rng, n)
                a = [jax.random.normal(k, (3,)) for k in keys]
                b = [jax.random.uniform(k, (3,)) for k in keys]
                return a, b
        """) == []

    def test_positive_reuse_inside_one_lambda(self):
        assert "JAX01" in codes("""
            import jax
            f = lambda rng: (jax.random.normal(rng, (3,))
                             + jax.random.uniform(rng, (3,)))
        """)

    def test_negative_branches_use_key_once_each(self):
        assert codes("""
            import jax
            def f(rng, greedy):
                if greedy:
                    return jax.random.categorical(rng, None)
                else:
                    return jax.random.normal(rng, (3,))
        """) == []


class TestHostSyncInJit:
    def test_positive_numpy_and_cast(self):
        got = codes("""
            import jax
            import numpy as np
            @jax.jit
            def f(x):
                y = np.asarray(x)
                return float(y)
        """)
        assert got.count("JAX02") == 2

    def test_positive_item_in_scan_body(self):
        assert "JAX02" in codes("""
            import jax
            def body(c, x):
                c = c + x.item()
                return c, x
            def g(xs):
                return jax.lax.scan(body, 0.0, xs)
        """)

    def test_negative_trace_time_static_casts(self):
        # float(len(x)) / int(x.shape[0]) are static under trace — legal
        assert codes("""
            import jax
            @jax.jit
            def f(x):
                scale = float(len(x))
                n = int(x.shape[0])
                return x * scale / n
        """) == []

    def test_negative_jnp_and_host_code(self):
        assert codes("""
            import jax
            import jax.numpy as jnp
            import numpy as np
            @jax.jit
            def f(x):
                return jnp.asarray(x) * 2
            def host(v):
                return float(np.asarray(v))  # not traced: fine
        """) == []


class TestPrintInJit:
    def test_positive(self):
        assert "JAX03" in codes("""
            import jax
            @jax.jit
            def f(x):
                print(x)
                return x
        """)

    def test_negative_debug_print_and_host_print(self):
        assert codes("""
            import jax
            @jax.jit
            def f(x):
                jax.debug.print("x={x}", x=x)
                return x
            def host():
                print("hello")
        """) == []


class TestUntraceableArgNoStatic:
    def test_positive_str_param(self):
        assert "JAX04" in codes("""
            import jax
            def f(x, mode: str):
                return x
            g = jax.jit(f)
        """)

    def test_negative_with_static_argnames(self):
        assert codes("""
            import jax
            def f(x, mode: str):
                return x
            g = jax.jit(f, static_argnames=("mode",))
        """) == []

    def test_negative_method_does_not_shadow_wrapped_function(self):
        # jit wraps the module-level `loss`; the same-named method's
        # str param must not be attributed to it
        assert codes("""
            import jax
            def loss(x):
                return x
            g = jax.jit(loss)
            class Trainer:
                def loss(self, x, mode: str):
                    return x
        """) == []

    def test_negative_pytree_dict_batch_is_traceable(self):
        # dict batches are pytrees — the learner's own signature.
        assert codes("""
            import jax
            from typing import Mapping
            def update(state, batch: Mapping[str, jax.Array]):
                return state
            g = jax.jit(update, donate_argnums=0)
        """) == []


class TestMissingDonate:
    def test_positive_update_name(self):
        assert "JAX05" in codes("""
            import jax
            def train_step(state, batch):
                return state
            step = jax.jit(train_step)
        """)

    def test_positive_target_name(self):
        assert "JAX05" in codes("""
            import jax
            class A:
                def setup(self, run):
                    self._update = jax.jit(run)
        """)

    def test_negative_with_donate(self):
        assert codes("""
            import jax
            def train_step(state, batch):
                return state
            step = jax.jit(train_step, donate_argnums=0)
        """) == []

    def test_negative_non_update_name(self):
        assert codes("""
            import jax
            def evaluate(params, obs):
                return obs
            ev = jax.jit(evaluate)
        """) == []


class TestUntimedJitDispatch:
    def test_positive(self):
        assert "JAX06" in codes("""
            import jax, time
            def g(x): return x
            f = jax.jit(g)
            def bench(x):
                t0 = time.perf_counter()
                y = f(x)
                return y, time.perf_counter() - t0
        """)

    def test_negative_with_block(self):
        assert codes("""
            import jax, time
            def g(x): return x
            f = jax.jit(g)
            def bench(x):
                t0 = time.perf_counter()
                y = jax.block_until_ready(f(x))
                return y, time.perf_counter() - t0
        """) == []

    def test_negative_np_asarray_host_fence(self):
        assert codes("""
            import jax, time
            import numpy as np
            def g(x): return x
            f = jax.jit(g)
            def bench(x):
                t0 = time.perf_counter()
                y = np.asarray(f(x))
                return y, time.perf_counter() - t0
        """) == []

    def test_negative_float_host_fence(self):
        # The committed bench idiom: a host readback of a value that
        # depends on the chain fences it (bench.py's documented pattern).
        assert codes("""
            import jax, time
            def g(x): return x
            f = jax.jit(g)
            def bench(x):
                t0 = time.perf_counter()
                y = f(x)
                float(y)
                return time.perf_counter() - t0
        """) == []


class TestDirectShardMapBinding:
    def test_positive_from_experimental(self):
        assert "JAX07" in codes("""
            from jax.experimental.shard_map import shard_map
            def f(fn, mesh, spec):
                return shard_map(fn, mesh=mesh, in_specs=spec,
                                 out_specs=spec)
        """)

    def test_positive_jax_attribute(self):
        # The pre-migration pipeline.py idiom: binding the moved surface.
        assert "JAX07" in codes("""
            import jax
            shard_map = jax.shard_map
        """)

    def test_positive_experimental_module_attribute(self):
        assert "JAX07" in codes("""
            import jax.experimental.shard_map as shmap
            def f(fn, mesh, spec):
                return shmap.shard_map(fn, mesh=mesh, in_specs=spec,
                                       out_specs=spec)
        """)

    def test_one_report_per_site(self):
        # A full dotted chain is several Attribute nodes sharing one
        # position — exactly one finding per call site.
        found = [f for f in analyze_source(textwrap.dedent("""
            import jax
            f = jax.experimental.shard_map.shard_map
        """), "x.py") if f.rule == "JAX07"]
        assert len(found) == 1

    def test_negative_compat_import(self):
        assert codes("""
            from relayrl_tpu.parallel.compat import shard_map
            def f(fn, mesh, spec):
                return shard_map(fn, mesh=mesh, in_specs=spec,
                                 out_specs=spec, check_vma=False)
        """) == []

    def test_compat_module_itself_is_sanctioned(self):
        src = textwrap.dedent("""
            import jax
            raw = getattr(jax, "shard_map", None) or jax.shard_map
        """)
        paths = {f.rule
                 for f in analyze_source(src, "relayrl_tpu/parallel/compat.py")}
        assert "JAX07" not in paths
        assert "JAX07" in {f.rule for f in analyze_source(src, "other.py")}


class TestBlockingUnderLock:
    def test_positive_sleep(self):
        assert "CONC01" in codes("""
            import time, threading
            lock = threading.Lock()
            def f():
                with lock:
                    time.sleep(1.0)
        """)

    def test_positive_recv_under_attr_lock(self):
        assert "CONC01" in codes("""
            class T:
                def f(self, sock):
                    with self._pub_lock:
                        return sock.recv()
        """)

    def test_negative_sleep_outside_lock(self):
        assert codes("""
            import time
            def f(lock):
                with lock:
                    x = 1
                time.sleep(0.1)
                return x
        """) == []

    def test_positive_thread_join_under_lock(self):
        assert "CONC01" in codes("""
            class T:
                def f(self):
                    with self._lock:
                        self._listener_thread.join()
        """)

    def test_negative_string_and_path_join_under_lock(self):
        # str.join / os.path.join are not blocking I/O
        assert codes("""
            import os
            def f(lock, items):
                with lock:
                    name = ", ".join(items)
                    return os.path.join("a", name)
        """) == []

    def test_negative_nested_def_not_executed_under_lock(self):
        assert codes("""
            import time
            def f(lock):
                with lock:
                    def cb():
                        time.sleep(1.0)
                return cb
        """) == []


class TestWallClockLatency:
    def test_positive_inline_interval(self):
        assert "TEL01" in codes("""
            import time
            def f(hist, work):
                t0 = time.time()
                work()
                hist.observe(time.time() - t0)
        """)

    def test_positive_named_interval_through_set(self):
        assert "TEL01" in codes("""
            import time
            def f(gauge, work):
                start = time.time()
                work()
                elapsed = time.time() - start
                gauge.set(elapsed)
        """)

    def test_negative_monotonic_interval(self):
        assert codes("""
            import time
            def f(hist, work):
                t0 = time.monotonic()
                work()
                hist.observe(time.monotonic() - t0)
        """) == []

    def test_negative_wall_timestamp_not_interval(self):
        # recording the wall clock itself is the cross-host-timestamp
        # use case the convention keeps time.time() for
        assert codes("""
            import time
            def f(gauge):
                gauge.set(time.time())
        """) == []


class TestBareExcept:
    def test_positive(self):
        assert "CONC02" in codes("""
            def f():
                try:
                    pass
                except:
                    pass
        """)

    def test_negative_typed(self):
        assert codes("""
            def f():
                try:
                    pass
                except Exception:
                    pass
        """) == []


class TestModuleLevelDeviceTouch:
    def test_positive_module_scope(self):
        assert "IMP01" in codes("""
            import jax
            DEVICES = jax.devices()
        """)

    def test_positive_config_update_in_class_body(self):
        assert "IMP01" in codes("""
            import jax
            class Cfg:
                jax.config.update("jax_enable_x64", True)
        """)

    def test_negative_inside_function(self):
        assert codes("""
            import jax
            def devices():
                return jax.devices()
        """) == []

    def test_negative_exempt_init(self):
        src = "import jax\nD = jax.devices()\n"
        assert [f.rule for f in
                analyze_source(src, "pkg/__init__.py")] == []


class TestSuppression:
    BAD = "import jax\nD = jax.devices()\n"

    def test_same_line(self):
        src = ("import jax\n"
               "D = jax.devices()  # jaxlint: disable=IMP01\n")
        assert analyze_source(src, "x.py") == []

    def test_line_above_and_slug(self):
        src = ("import jax\n"
               "# jaxlint: disable=module-level-device-touch\n"
               "D = jax.devices()\n")
        assert analyze_source(src, "x.py") == []

    def test_disable_all(self):
        src = ("import jax\n"
               "D = jax.devices()  # jaxlint: disable=all\n")
        assert analyze_source(src, "x.py") == []

    def test_wrong_code_does_not_suppress(self):
        src = ("import jax\n"
               "D = jax.devices()  # jaxlint: disable=JAX01\n")
        assert [f.rule for f in analyze_source(src, "x.py")] == ["IMP01"]

    def test_inline_disable_does_not_leak_to_next_line(self):
        src = ("import jax\n"
               "D = jax.devices()  # jaxlint: disable=IMP01\n"
               "E = jax.devices()\n")
        got = analyze_source(src, "x.py")
        assert [(f.rule, f.line) for f in got] == [("IMP01", 3)]

    def test_above_line_disable_requires_comment_only_line(self):
        # a CODE line above with a trailing disable covers itself only
        src = ("import jax\n"
               "x = 1  # jaxlint: disable=IMP01\n"
               "E = jax.devices()\n")
        assert [f.rule for f in analyze_source(src, "x.py")] == ["IMP01"]

    def test_trailing_reason_still_suppresses(self):
        # the documented style pairs every disable with a reason
        src = ("import jax\n"
               "D = jax.devices()  # jaxlint: disable=IMP01 - entry "
               "script, backend already up\n")
        assert analyze_source(src, "x.py") == []


class TestEngineMechanics:
    def test_syntax_error_is_a_parse_finding(self):
        got = analyze_source("def broken(:\n", "x.py")
        assert [f.rule for f in got] == ["PARSE"]

    def test_paths_relative_to_scan_root_parent(self, tmp_path):
        pkg = tmp_path / "mypkg"
        pkg.mkdir()
        (pkg / "m.py").write_text("import jax\nD = jax.devices()\n")
        findings = analyze_paths([str(pkg)])
        assert [f.path for f in findings] == ["mypkg/m.py"]

    def test_file_arg_under_cwd_keys_like_directory_scan(self, tmp_path,
                                                         monkeypatch):
        # A per-file run from the repo root must produce the same baseline
        # key as the directory scan, or baselined findings resurface.
        pkg = tmp_path / "mypkg"
        pkg.mkdir()
        (pkg / "m.py").write_text("import jax\nD = jax.devices()\n")
        monkeypatch.chdir(tmp_path)
        by_dir = analyze_paths([str(pkg)])
        by_file = analyze_paths(["mypkg/m.py"])
        by_dot = analyze_paths(["."])
        assert [f.key() for f in by_file] == [f.key() for f in by_dir]
        assert [f.key() for f in by_dot] == [f.key() for f in by_dir]

    def test_keys_anchor_at_repo_root_regardless_of_cwd(self, tmp_path,
                                                        monkeypatch):
        # with a repo marker present, a scan from a SUBDIRECTORY must
        # produce the same baseline keys as one from the repo root
        (tmp_path / "pyproject.toml").write_text("")
        pkg = tmp_path / "mypkg"
        pkg.mkdir()
        (pkg / "m.py").write_text("import jax\nD = jax.devices()\n")
        monkeypatch.chdir(tmp_path)
        from_root = analyze_paths(["mypkg"])
        monkeypatch.chdir(pkg)
        from_subdir = analyze_paths(["."])
        by_abs = analyze_paths([str(pkg / "m.py")])
        assert [f.path for f in from_root] == ["mypkg/m.py"]
        assert [f.key() for f in from_subdir] == [f.key() for f in from_root]
        assert [f.key() for f in by_abs] == [f.key() for f in from_root]

    def test_baseline_roundtrip_match_and_stale(self, tmp_path):
        pkg = tmp_path / "p"
        pkg.mkdir()
        (pkg / "m.py").write_text("import jax\nD = jax.devices()\n")
        findings = analyze_paths([str(pkg)])
        assert len(findings) == 1
        bl = tmp_path / "baseline.json"
        write_baseline(bl, findings)
        new, matched, stale = apply_baseline(findings, load_baseline(bl))
        assert (new, matched, stale) == ([], 1, [])
        # fix the code -> the entry goes stale, nothing is new
        (pkg / "m.py").write_text("import jax\n")
        new, matched, stale = apply_baseline(
            analyze_paths([str(pkg)]), load_baseline(bl))
        assert new == [] and matched == 0 and len(stale) == 1

    def test_baseline_count_absorbs_exactly_n(self, tmp_path):
        pkg = tmp_path / "p"
        pkg.mkdir()
        # two IDENTICAL lines -> one baseline key with count=2
        (pkg / "m.py").write_text(
            "import jax\nD = jax.devices()\nD = jax.devices()\n")
        findings = analyze_paths([str(pkg)])
        assert len(findings) == 2
        bl = tmp_path / "b.json"
        write_baseline(bl, findings)
        data = json.loads(bl.read_text())
        assert data["findings"][0]["count"] == 2
        # a third copy of the same line is NEW
        (pkg / "m.py").write_text(
            "import jax\n" + "D = jax.devices()\n" * 3)
        new, matched, _ = apply_baseline(
            analyze_paths([str(pkg)]), load_baseline(bl))
        assert matched == 2 and len(new) == 1


class TestCli:
    def test_list_rules_exits_zero(self, capsys):
        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for code in ("JAX01", "CONC01", "IMP01"):
            assert code in out

    def test_unknown_select_exits_two(self, capsys):
        assert main(["--select", "NOPE99", str(PKG)]) == 2

    def test_missing_path_exits_two(self):
        assert main(["/no/such/dir-jaxlint"]) == 2

    def test_new_finding_exits_one_then_baselined_zero(self, tmp_path,
                                                       capsys):
        bad = tmp_path / "bad.py"
        bad.write_text("import jax\nD = jax.devices()\n")
        bl = tmp_path / "b.json"
        assert main([str(bad), "--baseline", str(bl)]) == 1
        assert main([str(bad), "--baseline", str(bl),
                     "--write-baseline"]) == 0
        assert main([str(bad), "--baseline", str(bl)]) == 0

    # ISSUE 17 wall re-fit: subprocess CLI round-trip; still runs in
    # scripts/check.sh stage 2 (no marker filter there).
    @pytest.mark.slow
    def test_scoped_write_baseline_needs_explicit_path(self, tmp_path,
                                                       capsys):
        bad = tmp_path / "bad.py"
        bad.write_text("import jax\nD = jax.devices()\n")
        # --write-baseline without an explicit --baseline PATH is always
        # refused: any scan covers only a slice of the gate's scope, so
        # writing it to the shared default would drop grandfathered
        # entries from everywhere else.
        assert main([str(bad), "--write-baseline"]) == 2
        assert main([str(bad), "--select", "IMP01",
                     "--write-baseline"]) == 2
        assert main(["--write-baseline"]) == 2
        # explicit --baseline path -> allowed
        bl = tmp_path / "b.json"
        assert main([str(bad), "--select", "IMP01", "--baseline", str(bl),
                     "--write-baseline"]) == 0
        assert bl.is_file()

    def test_corrupt_baseline_exits_two_with_diagnostic(self, tmp_path,
                                                        capsys):
        bad = tmp_path / "bad.py"
        bad.write_text("import jax\nD = jax.devices()\n")
        bl = tmp_path / "broken.json"
        bl.write_text("{not json")
        assert main([str(bad), "--baseline", str(bl)]) == 2
        assert "cannot read baseline" in capsys.readouterr().err

    def test_hidden_and_venv_dirs_are_pruned(self, tmp_path):
        pkg = tmp_path / "proj"
        (pkg / ".venv" / "lib").mkdir(parents=True)
        (pkg / "src").mkdir()
        (pkg / ".venv" / "lib" / "vendored.py").write_text(
            "import jax\nD = jax.devices()\n")
        (pkg / "src" / "m.py").write_text("import jax\nD = jax.devices()\n")
        findings = analyze_paths([str(pkg)])
        assert [f.path for f in findings] == ["proj/src/m.py"]

    def test_json_format(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text("import jax\nD = jax.devices()\n")
        assert main([str(bad), "--no-baseline", "--format", "json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["new"][0]["rule"] == "IMP01"


class TestRepoGate:
    """The CI gate: the framework tree must be clean modulo the
    committed baseline. A finding here means either fix the code,
    suppress it with a reasoned `# jaxlint: disable=...`, or (for
    pre-existing debt only) regenerate the baseline."""

    def test_framework_tree_has_no_new_findings(self):
        findings = analyze_paths(GATE_PATHS)
        baseline = load_baseline(BASELINE) if os.path.isfile(BASELINE) else {}
        new, _matched, _stale = apply_baseline(findings, baseline)
        assert not new, "new jaxlint findings:\n" + "\n".join(
            f.format() for f in new)

    def test_package_gate_via_module_invocation(self):
        # The exact invocation CI and the docs use, end to end.
        proc = subprocess.run(
            [sys.executable, "-m", "relayrl_tpu.analysis", PKG],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_module_invocation_fails_on_new_finding(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import jax\nD = jax.devices()\n")
        proc = subprocess.run(
            [sys.executable, "-m", "relayrl_tpu.analysis", str(bad)],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "IMP01" in proc.stdout
