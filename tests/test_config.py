"""Config loader tests (ref behavior: config_loader.rs auto-create, getters,
fallbacks — SURVEY.md §2.2)."""

import json

import pytest

from relayrl_tpu.config import (
    DEFAULT_CONFIG_FILENAME,
    ConfigLoader,
    default_config,
)


class TestAutoCreate:
    def test_creates_default_in_cwd(self, tmp_cwd):
        loader = ConfigLoader("REINFORCE")
        created = tmp_cwd / DEFAULT_CONFIG_FILENAME
        assert created.is_file()
        on_disk = json.loads(created.read_text())
        assert "algorithms" in on_disk and "server" in on_disk
        assert loader.get_max_traj_length() == 1000

    def test_no_create_when_disabled(self, tmp_cwd):
        ConfigLoader("REINFORCE", create_if_missing=False)
        assert not (tmp_cwd / DEFAULT_CONFIG_FILENAME).exists()

    def test_explicit_path(self, tmp_path):
        path = tmp_path / "sub" / "cfg.json"
        loader = ConfigLoader("REINFORCE", config_path=path)
        assert path.is_file()
        assert loader.get_train_server().port == "50051"


class TestGetters:
    def test_algorithm_params(self, tmp_cwd):
        loader = ConfigLoader("REINFORCE")
        params = loader.get_algorithm_params()
        assert params["gamma"] == pytest.approx(0.98)
        assert params["traj_per_epoch"] == 8
        assert params["with_vf_baseline"] is False

    def test_case_insensitive_algo(self, tmp_cwd):
        loader = ConfigLoader("reinforce")
        assert loader.get_algorithm_params()["gamma"] == pytest.approx(0.98)

    def test_user_overrides_merge_over_defaults(self, tmp_path):
        path = tmp_path / "cfg.json"
        cfg = default_config()
        cfg["algorithms"]["REINFORCE"] = {"gamma": 0.5}
        path.write_text(json.dumps(cfg))
        loader = ConfigLoader("REINFORCE", config_path=path)
        params = loader.get_algorithm_params()
        assert params["gamma"] == 0.5
        assert params["pi_lr"] == pytest.approx(3e-4)  # default survives

    def test_endpoints(self, tmp_cwd):
        loader = ConfigLoader()
        assert loader.get_train_server().address == "tcp://127.0.0.1:50051"
        assert loader.get_traj_server().address == "tcp://127.0.0.1:7776"
        assert loader.get_agent_listener().address == "tcp://127.0.0.1:7777"

    def test_endpoint_fallback_on_missing_key(self, tmp_path):
        path = tmp_path / "cfg.json"
        path.write_text(json.dumps({"server": {}}))
        loader = ConfigLoader(config_path=path)
        assert loader.get_traj_server().port == "7776"

    def test_model_paths_not_swapped(self, tmp_cwd):
        # Ref bug (config_loader.rs:504-534): fallbacks return client/server
        # paths crossed. Ours must not.
        loader = ConfigLoader()
        assert "client" in loader.get_client_model_path()
        assert "server" in loader.get_server_model_path()

    def test_idle_timeout_seconds(self, tmp_cwd):
        loader = ConfigLoader()
        assert loader.get_grpc_idle_timeout_s() == pytest.approx(30.0)

    def test_tb_params(self, tmp_cwd):
        params = ConfigLoader().get_tb_params()
        assert params["global_step_tag"] == "Epoch"
        assert "_comment1" not in params

    def test_plugin_algorithm_warns(self, tmp_cwd):
        with pytest.warns(UserWarning):
            ConfigLoader("MY_CUSTOM_ALGO")

    def test_learner_params(self, tmp_cwd):
        params = ConfigLoader().get_learner_params()
        assert params["mesh"]["dp"] == -1
        assert params["precision"] == "float32"  # CPU-safe default; TPU benches set bf16


class TestEnvDirAnchoring:
    """Default-named run artifacts anchor under env_dir, not the caller's
    cwd (VERDICT r3 #8: example runs were leaving server_model.rlx,
    checkpoints/ and logs/ at the repo root)."""

    def test_algorithm_artifacts_anchor_under_env_dir(self, tmp_cwd,
                                                      tmp_path):
        import os

        from relayrl_tpu.algorithms import build_algorithm

        env_dir = tmp_path / "run"
        algo = build_algorithm("REINFORCE", env_dir=str(env_dir),
                               obs_dim=3, act_dim=2, hidden_sizes=[8],
                               with_vf_baseline=False)
        assert algo.server_model_path == os.path.join(str(env_dir),
                                                      "server_model.rlx")
        # the logger already landed its run dir under env_dir/logs
        assert str(algo.logger.output_dir).startswith(
            os.path.join(str(env_dir), "logs"))
        # absolute configured paths pass through untouched
        from relayrl_tpu.algorithms.base import anchor_path

        assert anchor_path("/abs/model.rlx", str(env_dir)) == "/abs/model.rlx"
        assert anchor_path("rel.rlx", None) == "rel.rlx"

    def test_server_checkpoint_dir_anchors_under_env_dir(self, tmp_cwd,
                                                         tmp_path):
        import os

        from relayrl_tpu.runtime.server import TrainingServer

        env_dir = tmp_path / "run2"
        server = TrainingServer(
            "REINFORCE", obs_dim=3, act_dim=2, env_dir=str(env_dir),
            start=False, hyperparams={"hidden_sizes": [8]})
        assert server._checkpoint_dir == os.path.join(str(env_dir),
                                                      "checkpoints")
