"""Adversarial-input tests for the native framed-TCP transport server
(native/transport.cc).

The reference's low-latency plane rides libzmq — a hardened library
(reference: relayrl_framework/src/network/server/training_zmq.rs:71-1059).
Ours is a hand-rolled epoll loop with a 5-byte frame header
(u32 LE payload_len | u8 type | payload), so it gets the adversarial
coverage a library would bring, the same way test_grpc_native_fuzz.py
covers the hand-rolled HTTP/2 parser. Every attack ends with the real
assertion: a FRESH connection still completes Ping -> Pong (the epoll
loop is alive and accepting), and where state is involved, a well-formed
handshake still works.

Covered classes: oversize/truncated length fields, cross-protocol
greetings (ZMTP, HTTP/2 preface — the fail-fast mismatch breadcrumbs),
unknown frame types, huge/empty agent ids, garbage trajectory payloads
surfacing through poll without killing the loop, read-budget abuse
(many frames in one send), connection churn, and hypothesis-driven raw
byte soup / framed soup.
"""

from __future__ import annotations

import socket
import struct
import threading
import time

import pytest

# A clean env (no [test] extra) must still COLLECT with zero errors
# (ISSUE 6 satellite): skip, don't explode, when hypothesis is absent.
pytest.importorskip(
    "hypothesis",
    reason="fuzz suite needs the [test] extra (pip install "
           "relayrl-tpu[test])")
from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

from relayrl_tpu.config import ConfigLoader
from relayrl_tpu.transport import make_server_transport

# frame types (native/transport.cc)
TRAJ, GET_MODEL, MODEL, MODEL_SET, ID_LOGGED, SUBSCRIBE, MODEL_PUSH = (
    1, 2, 3, 4, 5, 6, 7)
PING, PONG = 8, 9
HEADER = 5
MAX_FRAME = 1 << 30

ZMTP_GREETING = bytes([0xFF, 0, 0, 0, 0, 0, 0, 0, 1, 0x7F])
H2_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"


def frame(ftype: int, payload: bytes = b"") -> bytes:
    return struct.pack("<I", len(payload)) + bytes([ftype]) + payload


def recv_frame(sock: socket.socket, timeout: float = 3.0):
    """Read one complete frame off the socket, or None on close/timeout."""
    sock.settimeout(timeout)
    buf = b""
    try:
        while len(buf) < HEADER:
            chunk = sock.recv(65536)
            if not chunk:
                return None
            buf += chunk
        ln = struct.unpack("<I", buf[:4])[0]
        ftype = buf[4]
        body = buf[HEADER:]
        while len(body) < ln:
            chunk = sock.recv(65536)
            if not chunk:
                return None
            body += chunk
        return ftype, body[:ln]
    except (socket.timeout, OSError):
        return None


@pytest.fixture(autouse=True)
def _require_native_lib():
    from relayrl_tpu.transport.native_backend import native_available

    if not native_available():
        pytest.skip("native library not built (make -C native)")


@pytest.fixture
def cfg(tmp_cwd):
    return ConfigLoader(create_if_missing=False)


@pytest.fixture
def server(cfg):
    srv = make_server_transport("native", cfg, bind_addr="127.0.0.1:0")
    srv.get_model = lambda: (1, b"model-bytes-v1")
    srv.events = {"traj": [], "reg": [], "unreg": []}
    srv.on_trajectory = lambda aid, p: srv.events["traj"].append((aid, p))
    srv.on_register = srv.events["reg"].append
    srv.on_unregister = srv.events["unreg"].append
    srv.start()
    yield srv
    srv.stop()


def wait_for(pred, timeout: float = 5.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def assert_alive(port: int) -> None:
    """The real assertion after every attack: a fresh connection still
    round-trips Ping -> Pong through the epoll loop."""
    with socket.create_connection(("127.0.0.1", port), timeout=3.0) as s:
        s.sendall(frame(PING))
        got = recv_frame(s)
        assert got is not None and got[0] == PONG, \
            f"server not answering pings (got {got!r})"


def attack(port: int, raw: bytes, linger: float = 0.0) -> None:
    with socket.create_connection(("127.0.0.1", port), timeout=3.0) as s:
        try:
            s.sendall(raw)
        except OSError:
            pass  # server may legitimately slam the door mid-send
        if linger:
            time.sleep(linger)


class TestMalformedFrames:
    def test_oversize_length_drops_connection(self, server):
        # Length field over the 1 GiB cap: the connection must be cut
        # without any attempt to buffer toward it.
        with socket.create_connection(("127.0.0.1", server.port)) as s:
            s.sendall(struct.pack("<I", MAX_FRAME + 1) + bytes([TRAJ]))
            assert recv_frame(s, timeout=2.0) is None  # server closed
        assert_alive(server.port)

    def test_huge_claimed_length_partial_body(self, server):
        # Claim 512 MiB, deliver 1 MiB, close. The rbuf must not balloon
        # (the read loop only ever buffers what arrives) and the loop must
        # not wait on the phantom remainder.
        raw = struct.pack("<I", 512 << 20) + bytes([TRAJ]) + b"\x00" * (1 << 20)
        attack(server.port, raw, linger=0.2)
        assert_alive(server.port)

    def test_truncated_header(self, server):
        attack(server.port, b"\x05\x00", linger=0.1)
        assert_alive(server.port)

    def test_truncated_frame_then_close(self, server):
        raw = frame(TRAJ, b"x" * 100)[:40]
        attack(server.port, raw, linger=0.1)
        assert_alive(server.port)

    def test_zmtp_greeting_dropped(self, server):
        # A zmq peer's ZMTP greeting is the fail-fast mismatch breadcrumb:
        # connection dropped, loop alive (transport/probe.py negotiation).
        with socket.create_connection(("127.0.0.1", server.port)) as s:
            s.sendall(ZMTP_GREETING)
            assert recv_frame(s, timeout=2.0) is None
        assert_alive(server.port)

    def test_http2_preface_dropped(self, server):
        with socket.create_connection(("127.0.0.1", server.port)) as s:
            s.sendall(H2_PREFACE)
            assert recv_frame(s, timeout=2.0) is None
        assert_alive(server.port)

    def test_unknown_frame_types_ignored(self, server):
        # Forward compat: unknown types skip cleanly, later frames on the
        # same connection still work.
        with socket.create_connection(("127.0.0.1", server.port)) as s:
            s.sendall(frame(0, b"???") + frame(200, b"\x00" * 64)
                      + frame(255) + frame(PING))
            got = recv_frame(s)
            assert got is not None and got[0] == PONG
        assert_alive(server.port)


class TestStatefulAbuse:
    def test_get_model_with_garbage_payload(self, server):
        # GET_MODEL carries no payload by contract; one with garbage must
        # still be answered (payload ignored), not misparsed.
        with socket.create_connection(("127.0.0.1", server.port)) as s:
            s.sendall(frame(GET_MODEL, b"\xde\xad\xbe\xef"))
            got = recv_frame(s)
            assert got is not None and got[0] == MODEL
            version = struct.unpack("<Q", got[1][:8])[0]
            assert version == 1 and got[1][8:] == b"model-bytes-v1"
        assert_alive(server.port)

    def test_huge_agent_id_registered_and_unregistered(self, server):
        huge_id = "A" * (1 << 20)
        with socket.create_connection(("127.0.0.1", server.port)) as s:
            s.sendall(frame(MODEL_SET, huge_id.encode()))
            got = recv_frame(s, timeout=5.0)
            assert got is not None and got[0] == ID_LOGGED
        # registration + unregister-on-drop both surface as events
        assert wait_for(lambda: huge_id in server.events["reg"])
        assert wait_for(lambda: huge_id in server.events["unreg"])
        assert_alive(server.port)

    def test_non_utf8_agent_id_survives(self, server):
        # Registration ids are decoded with errors="replace" on the Python
        # side — raw invalid UTF-8 must neither crash the poll thread nor
        # the loop.
        with socket.create_connection(("127.0.0.1", server.port)) as s:
            s.sendall(frame(MODEL_SET, b"\xff\xfe\x80\x81 id"))
            got = recv_frame(s)
            assert got is not None and got[0] == ID_LOGGED
        assert wait_for(lambda: len(server.events["reg"]) > 0)
        assert_alive(server.port)

    def test_empty_agent_id(self, server):
        with socket.create_connection(("127.0.0.1", server.port)) as s:
            s.sendall(frame(MODEL_SET))
            got = recv_frame(s)
            assert got is not None and got[0] == ID_LOGGED
        assert_alive(server.port)

    def test_garbage_trajectory_dropped_valid_one_survives(self, server):
        # The wire accepts any TRAJ payload; the Python wrapper drops
        # non-envelope garbage (decode isolation — test_native_codec.py
        # covers envelope-level garbage). Neither the drop nor a valid
        # envelope right behind it may disturb the loop.
        from relayrl_tpu.transport.base import pack_trajectory_envelope

        garbage = bytes(range(256)) * 7
        good = pack_trajectory_envelope("fuzz-agent", b"real-payload")
        with socket.create_connection(("127.0.0.1", server.port)) as s:
            s.sendall(frame(TRAJ, garbage) + frame(TRAJ, good) + frame(PING))
            got = recv_frame(s)
            assert got is not None and got[0] == PONG
        assert wait_for(
            lambda: ("fuzz-agent", b"real-payload") in server.events["traj"])
        assert_alive(server.port)

    def test_many_frames_single_send(self, server):
        # One send() carrying hundreds of frames exercises the per-wakeup
        # read budget: all must parse (level-triggered epoll re-fires),
        # none dropped.
        from relayrl_tpu.transport.base import pack_trajectory_envelope

        n = 500
        payload = b"".join(
            frame(TRAJ, pack_trajectory_envelope("blaster", b"t%d" % i))
            for i in range(n)) + frame(PING)
        with socket.create_connection(("127.0.0.1", server.port)) as s:
            s.sendall(payload)
            got = recv_frame(s, timeout=5.0)
            assert got is not None and got[0] == PONG
        assert wait_for(lambda: len(server.events["traj"]) >= n, timeout=10.0)
        assert len(server.events["traj"]) == n
        assert_alive(server.port)

    def test_subscriber_death_does_not_block_broadcast(self, server):
        # A subscriber that stops reading then dies must not wedge
        # publish_model for the healthy path.
        dead = socket.create_connection(("127.0.0.1", server.port))
        dead.sendall(frame(SUBSCRIBE))
        time.sleep(0.1)
        dead.close()
        server.publish_model(2, b"model-v2")
        with socket.create_connection(("127.0.0.1", server.port)) as s:
            s.sendall(frame(SUBSCRIBE))
            time.sleep(0.1)
            server.publish_model(3, b"model-v3")
            got = recv_frame(s, timeout=5.0)
            assert got is not None and got[0] == MODEL_PUSH
            version = struct.unpack("<Q", got[1][:8])[0]
            assert version == 3 and got[1][8:] == b"model-v3"
        assert_alive(server.port)

    def test_connection_churn(self, server):
        # Rapid open/close (with and without bytes) must not leak the loop
        # into a bad state.
        for i in range(50):
            with socket.create_connection(("127.0.0.1", server.port)) as s:
                if i % 3 == 0:
                    s.sendall(frame(PING)[:3])
                elif i % 3 == 1:
                    s.sendall(b"\xff" * 7)
        assert_alive(server.port)


class TestByteSoup:
    @settings(max_examples=25, deadline=None,
              suppress_health_check=[HealthCheck.function_scoped_fixture])
    @given(blob=st.binary(min_size=0, max_size=4096))
    def test_raw_bytes_never_kill_server(self, server, blob):
        attack(server.port, blob)
        assert_alive(server.port)

    @settings(max_examples=25, deadline=None,
              suppress_health_check=[HealthCheck.function_scoped_fixture])
    @given(frames=st.lists(
        st.tuples(st.integers(min_value=0, max_value=255),
                  st.binary(min_size=0, max_size=512)),
        min_size=1, max_size=20))
    def test_framed_soup_never_kills_server(self, server, frames):
        raw = b"".join(frame(t, p) for t, p in frames)
        attack(server.port, raw)
        assert_alive(server.port)

    @settings(max_examples=15, deadline=None,
              suppress_health_check=[HealthCheck.function_scoped_fixture])
    @given(cut=st.integers(min_value=1, max_value=60),
           blob=st.binary(min_size=0, max_size=64))
    def test_split_writes_reassemble(self, server, cut, blob):
        # A valid PING split at an arbitrary byte boundary, with trailing
        # soup on the same connection, must still answer the ping.
        raw = frame(PING) + frame(TRAJ, blob)
        cut = min(cut, len(raw) - 1)
        with socket.create_connection(("127.0.0.1", server.port)) as s:
            s.sendall(raw[:cut])
            time.sleep(0.02)
            s.sendall(raw[cut:])
            got = recv_frame(s)
            assert got is not None and got[0] == PONG
        assert_alive(server.port)
