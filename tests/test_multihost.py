"""Real 2-process ``jax.distributed`` execution (CPU simulation).

Spawns two OS processes running ``tests/_multihost_worker.py`` against a
real coordinator barrier — the multi-host CPU simulation SURVEY.md §4
prescribes. This covers what `test_distributed_init.py` cannot: the
``jax.distributed.initialize`` call itself, the coordinator-asymmetric
ingest broadcast, a cross-process sharded update, and orbax save/restore
with all processes participating.
"""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")


from _util import free_port as _free_port  # noqa: E402


def test_two_process_distributed(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    # Repo root ONLY: an inherited PYTHONPATH can carry a sitecustomize
    # that registers an accelerator PJRT plugin in the workers (the axon
    # harness does), overriding the CPU simulation this test needs.
    env["PYTHONPATH"] = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    env["JAX_PLATFORMS"] = "cpu"
    # The workers set their own XLA_FLAGS; scrub the conftest's
    # single-process settings so they don't double-apply.
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(rank), str(port), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost workers hung:\n" + "\n---\n".join(
            p.stdout.read() if p.stdout else "" for p in procs))
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"MULTIHOST_OK rank={rank}" in out, out
        # Ring attention with the sp ring spanning both processes.
        assert f"MULTIHOST_RING_OK rank={rank}" in out, out
    # Both ranks computed the identical replicated loss.
    losses = {line.split("loss_pi=")[1]
              for out in outs for line in out.splitlines()
              if "MULTIHOST_OK" in line}
    assert len(losses) == 1, losses
