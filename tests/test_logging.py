"""EpochLogger + TensorboardWriter tests (ref: utils/logger.py and
training_tensorboard.py behavior, SURVEY.md §5.5)."""

import os.path as osp

import pytest

from relayrl_tpu.utils import EpochLogger, setup_logger_kwargs, statistics_scalar
from relayrl_tpu.utils.tb_writer import TensorboardWriter


class TestEpochLogger:
    def test_progress_tsv_layout(self, tmp_path):
        logger = EpochLogger(output_dir=str(tmp_path))
        for epoch in range(1, 3):
            logger.store(EpRet=10.0 * epoch)
            logger.store(EpRet=20.0 * epoch)
            logger.log_tabular("Epoch", epoch)
            logger.log_tabular("EpRet", with_min_and_max=True)
            logger.dump_tabular()
        lines = (tmp_path / "progress.txt").read_text().splitlines()
        header = lines[0].split("\t")
        assert header == ["Epoch", "AverageEpRet", "StdEpRet", "MaxEpRet", "MinEpRet"]
        assert len(lines) == 3
        row1 = dict(zip(header, lines[1].split("\t")))
        assert float(row1["AverageEpRet"]) == pytest.approx(15.0)
        assert float(row1["MaxEpRet"]) == pytest.approx(20.0)

    def test_new_key_after_first_epoch_rejected(self, tmp_path):
        logger = EpochLogger(output_dir=str(tmp_path))
        logger.log_tabular("A", 1)
        logger.dump_tabular()
        with pytest.raises(KeyError):
            logger.log_tabular("B", 2)

    def test_save_config(self, tmp_path):
        logger = EpochLogger(output_dir=str(tmp_path), exp_name="exp")
        logger.save_config({"gamma": 0.99, "weird": object()})
        assert (tmp_path / "config.json").is_file()

    def test_setup_logger_kwargs_layout(self):
        kwargs = setup_logger_kwargs("myexp", seed=7, data_dir="/data")
        assert kwargs["output_dir"] == osp.join("/data", "myexp", "myexp_s7")

    def test_statistics_scalar(self):
        mean, std, mn, mx = statistics_scalar([1.0, 2.0, 3.0], with_min_and_max=True)
        assert mean == pytest.approx(2.0)
        assert (mn, mx) == (1.0, 3.0)


class TestTensorboardWriter:
    def _write_progress(self, path, rows):
        header = "Epoch\tAverageEpRet\tLossPi\n"
        path.write_text(header + "".join(
            f"{e}\t{r}\t{l}\n" for e, r, l in rows))

    def test_poll_writes_scalars(self, tmp_path):
        progress = tmp_path / "progress.txt"
        self._write_progress(progress, [(1, 10.0, 0.5), (2, 20.0, 0.4)])
        writer = TensorboardWriter(str(progress),
                                   scalar_tags="AverageEpRet;LossPi",
                                   logdir=str(tmp_path / "tb"))
        assert writer.poll() == 2
        assert writer.poll() == 0  # no new rows
        self._write_progress(progress, [(1, 10.0, 0.5), (2, 20.0, 0.4), (3, 30.0, 0.3)])
        assert writer.poll() == 1  # only the new row
        writer.close()
        import glob

        assert glob.glob(str(tmp_path / "tb" / "*")), "no event files written"

    def test_missing_tag_warns_but_works(self, tmp_path, capsys):
        progress = tmp_path / "progress.txt"
        self._write_progress(progress, [(1, 10.0, 0.5)])
        writer = TensorboardWriter(str(progress), scalar_tags="NotAColumn",
                                   logdir=str(tmp_path / "tb"))
        assert writer.poll() == 1
        assert "NotAColumn" in capsys.readouterr().out
        writer.close()

    def test_missing_file_is_noop(self, tmp_path):
        writer = TensorboardWriter(str(tmp_path / "nope.txt"))
        assert writer.poll() == 0
