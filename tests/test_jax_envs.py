"""Dynamics-parity goldens: each on-device JAX env vs its numpy built-in,
plus the in-scan autoreset and the unified env registry.

Parity contract (see the precision note in ``envs/jax/base.py``): every
discrete field — rewards where integral, terminated/truncated flags, step
counters, and Recall's ENTIRE observation — must match the numpy twin
EXACTLY; continuous observations must match to float32 precision
(``atol=rtol=2e-6``) per step. Full float bitwise equality between the
two planes is not physically achievable on this backend: XLA contracts
mul+add chains into FMAs and its cos/sin differ from libm's by 1 ulp
(both measured — see the probe test), so the goldens pin the strongest
true invariant instead: per-step agreement from IDENTICAL injected
states, so errors never compound, across termination, truncation, and
autoreset boundaries. Byte-exact reproducibility WITHIN the JAX plane is
pinned separately (tests/test_anakin.py cross-process determinism).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relayrl_tpu.envs import CartPoleEnv, PendulumEnv, RecallEnv, list_envs

pytestmark = pytest.mark.anakin
from relayrl_tpu.envs.jax import (
    JAX_ENVS,
    make_jax,
    step_autoreset,
)

ATOL = RTOL = 2e-6  # float32-grade per-step agreement


def test_xla_float_parity_bound_probe():
    """The evidence for the parity contract above: XLA's jitted float32
    math agrees with numpy's to ~1 ulp but NOT bitwise (FMA contraction
    + transcendental implementations). If this ever starts failing, the
    backend's float behavior changed and the golden tolerances need a
    fresh look."""
    xs = np.linspace(-3.2, 3.2, 4001, dtype=np.float32)
    jit_cos = np.asarray(jax.jit(jnp.cos)(xs))
    ulp = np.abs(jit_cos.view(np.int32).astype(np.int64)
                 - np.cos(xs).view(np.int32).astype(np.int64)).max()
    assert ulp <= 4, f"XLA cos drifted {ulp} ulp from libm"


class TestCartPoleParity:
    def test_per_step_dynamics_across_boundaries(self):
        """400 steps of per-step injected parity under a fixed action
        stream: before every step the numpy twin is set to the JAX env's
        exact state, both step, and all five return fields are compared.
        Episodes end by termination (pole falls under random actions) and
        the JAX lane autoresets in the same call chain the fused rollout
        uses, so the comparison crosses many episode boundaries."""
        jenv = make_jax("CartPole-v1")
        nenv = CartPoleEnv()
        nenv.reset(seed=0)  # state is overwritten by injection below
        step = jax.jit(jenv.step)
        rng = np.random.default_rng(7)
        key = jax.random.PRNGKey(7)
        key, sub = jax.random.split(key)
        state, _ = jenv.reset(sub)
        episodes = 0
        for _ in range(400):
            nenv._state = np.asarray(state.state, np.float64).copy()
            nenv._t = int(state.t)
            action = int(rng.integers(2))
            state, jobs, jrew, jterm, jtrunc = step(state, jnp.int32(action))
            nobs, nrew, nterm, ntrunc, _ = nenv.step(action)
            np.testing.assert_allclose(np.asarray(jobs), nobs,
                                       atol=ATOL, rtol=RTOL)
            assert float(jrew) == nrew == 1.0
            assert bool(jterm) == nterm and bool(jtrunc) == ntrunc
            if bool(jterm) or bool(jtrunc):
                episodes += 1
                key, sub = jax.random.split(key)
                state, _ = jenv.reset(sub)
        assert episodes >= 5, "golden never crossed an episode boundary"

    def test_truncation_flag_parity(self):
        """Time-limit endings: a short max_steps forces truncation; the
        flag must fire on the same step with the same independent-flags
        semantics as the numpy twin (both-true is representable)."""
        jenv = make_jax("CartPole-v1", max_steps=6)
        nenv = CartPoleEnv(max_steps=6)
        nenv.reset(seed=1)
        step = jax.jit(jenv.step)
        state, _ = jenv.reset(jax.random.PRNGKey(1))
        for i in range(6):
            nenv._state = np.asarray(state.state, np.float64).copy()
            nenv._t = int(state.t)
            action = i % 2
            state, _, _, jterm, jtrunc = step(state, jnp.int32(action))
            _, _, nterm, ntrunc, _ = nenv.step(action)
            assert bool(jterm) == nterm and bool(jtrunc) == ntrunc
        assert bool(jtrunc), "max_steps=6 must truncate on step 6"

    def test_reset_distribution(self):
        """Seeded resets land in CartPole's U(-0.05, 0.05) init box and
        differ across keys (the PRNG streams are necessarily different
        between the planes; the CONTRACT is the distribution)."""
        jenv = make_jax("CartPole-v1")
        a = np.asarray(jenv.reset(jax.random.PRNGKey(0))[1])
        b = np.asarray(jenv.reset(jax.random.PRNGKey(1))[1])
        assert np.abs(a).max() <= 0.05 and np.abs(b).max() <= 0.05
        assert not np.array_equal(a, b)
        # same key ⇒ same init, the reproducibility half
        c = np.asarray(jenv.reset(jax.random.PRNGKey(0))[1])
        np.testing.assert_array_equal(a, c)


class TestPendulumParity:
    def test_per_step_dynamics_and_reward(self):
        jenv = make_jax("Pendulum-v1", max_steps=25)
        nenv = PendulumEnv(max_steps=25)
        nenv.reset(seed=0)
        step = jax.jit(jenv.step)
        rng = np.random.default_rng(3)
        key = jax.random.PRNGKey(3)
        key, sub = jax.random.split(key)
        state, _ = jenv.reset(sub)
        truncations = 0
        for _ in range(120):
            nenv._theta = float(np.float32(state.theta))
            nenv._theta_dot = float(np.float32(state.theta_dot))
            nenv._t = int(state.t)
            action = np.float32(rng.uniform(-2.5, 2.5))  # incl. clip range
            state, jobs, jrew, jterm, jtrunc = step(
                state, jnp.asarray([action]))
            nobs, nrew, nterm, ntrunc, _ = nenv.step([action])
            np.testing.assert_allclose(np.asarray(jobs), nobs,
                                       atol=ATOL, rtol=RTOL)
            np.testing.assert_allclose(float(jrew), nrew,
                                       atol=ATOL, rtol=RTOL)
            assert not bool(jterm) and not nterm  # pendulum never terminates
            assert bool(jtrunc) == ntrunc
            if bool(jtrunc):
                truncations += 1
                key, sub = jax.random.split(key)
                state, _ = jenv.reset(sub)
        assert truncations >= 3

    def test_obs_is_cos_sin_thetadot(self):
        jenv = make_jax("Pendulum-v1")
        _, obs = jenv.reset(jax.random.PRNGKey(0))
        obs = np.asarray(obs)
        assert obs.shape == (3,)
        assert abs(obs[0] ** 2 + obs[1] ** 2 - 1.0) < 1e-5


class TestRecallParity:
    def test_full_bitwise_parity(self):
        """Recall's observation is integer-derived (one-hot, flag, and a
        power-of-two phase division), so here the parity claim is the
        full one: obs, reward, and flags are ALL bit-equal to the numpy
        twin, across several episodes with injected cues."""
        horizon, n_cues = 8, 3
        jenv = make_jax("Recall-v0", horizon=horizon, n_cues=n_cues)
        nenv = RecallEnv(horizon=horizon, n_cues=n_cues)
        nenv.reset(seed=0)
        step = jax.jit(jenv.step)
        rng = np.random.default_rng(11)
        key = jax.random.PRNGKey(11)
        key, sub = jax.random.split(key)
        state, jobs = jenv.reset(sub)
        # reset obs parity for the injected cue
        nenv._cue, nenv._t = int(state.cue), 0
        np.testing.assert_array_equal(np.asarray(jobs), nenv._obs())
        for _ in range(5 * horizon):
            nenv._cue, nenv._t = int(state.cue), int(state.t)
            action = int(rng.integers(n_cues))
            state, jobs, jrew, jterm, jtrunc = step(state, jnp.int32(action))
            nobs, nrew, nterm, ntrunc, _ = nenv.step(action)
            np.testing.assert_array_equal(np.asarray(jobs), nobs)
            assert float(jrew) == nrew
            assert bool(jterm) == nterm and bool(jtrunc) == ntrunc
            if bool(jterm):
                key, sub = jax.random.split(key)
                state, jobs = jenv.reset(sub)
                nenv._cue, nenv._t = int(state.cue), 0
                np.testing.assert_array_equal(np.asarray(jobs), nenv._obs())

    def test_memoryless_cap_and_query_reward(self):
        """The task's defining property carries over: only the query step
        pays, and it pays iff the action matches the episode's cue."""
        jenv = make_jax("Recall-v0", horizon=4, n_cues=2)
        state, _ = jenv.reset(jax.random.PRNGKey(0))
        cue = int(state.cue)
        step = jax.jit(jenv.step)
        rewards = []
        for t in range(4):
            state, _, rew, term, _ = step(state, jnp.int32(cue))
            rewards.append(float(rew))
        assert rewards == [0.0, 0.0, 0.0, 1.0] and bool(term)


class TestInScanAutoreset:
    def test_lanes_never_leave_device(self):
        """The fused composition: 600 scanned steps cross many episode
        boundaries; each boundary hands back the NEXT episode's reset
        observation (inside CartPole's init box) while the pre-reset
        observation rides final_obs — and the scanned flags exactly match
        a step-by-step replay of the same program."""
        env = make_jax("CartPole-v1")

        def body(c, _):
            key, state, obs = c
            (key, state, obs, rew, term, trunc,
             final_obs) = step_autoreset(env, key, state, jnp.int32(1))
            return (key, state, obs), {"obs": obs, "rew": rew,
                                       "term": term, "trunc": trunc,
                                       "final_obs": final_obs}

        key = jax.random.PRNGKey(5)
        rkey, ikey = jax.random.split(key)
        state, obs = env.reset(ikey)
        _, w = jax.jit(lambda c: jax.lax.scan(body, c, None, length=600))(
            (rkey, state, obs))
        term = np.asarray(w["term"])
        obs_w = np.asarray(w["obs"])
        final = np.asarray(w["final_obs"])
        assert term.sum() >= 10, "constant-push cartpole must fall often"
        done_idx = np.flatnonzero(term)
        # At a boundary t the emitted obs row is ALREADY the next
        # episode's reset (inside the init box) — the SyncVectorEnv
        # autoreset convention — while final_obs[t] is the fallen state
        # (outside it).
        for t in done_idx:
            assert np.abs(obs_w[t]).max() <= 0.05
            assert np.abs(final[t]).max() > 0.05
        assert bool((np.asarray(w["rew"]) == 1.0).all())

    def test_fixed_seed_reproducibility(self):
        """Same carry seed ⇒ identical scanned window, byte for byte —
        the in-process half of the determinism contract (the
        cross-process half lives in tests/test_anakin.py)."""
        env = make_jax("Recall-v0", horizon=8, n_cues=2)

        def run(seed):
            def body(c, _):
                key, state, obs = c
                (key, state, obs, rew, *_rest) = step_autoreset(
                    env, key, state, jnp.int32(0))
                return (key, state, obs), obs

            key = jax.random.PRNGKey(seed)
            rkey, ikey = jax.random.split(key)
            state, obs = env.reset(ikey)
            return np.asarray(jax.jit(
                lambda c: jax.lax.scan(body, c, None, length=64))(
                    (rkey, state, obs))[1])

        np.testing.assert_array_equal(run(9), run(9))
        assert not np.array_equal(run(9), run(10))


class TestGridWorldParity:
    def test_full_bitwise_parity(self):
        """All-integer dynamics (int32 positions, clamped moves,
        integral rewards): obs, reward, and BOTH flags are bit-equal to
        the numpy twin across injected states, terminations (goal
        reached), and time-limit truncations."""
        from relayrl_tpu.envs import GridWorldEnv

        jenv = make_jax("GridWorld-v0", size=4, max_steps=10)
        nenv = GridWorldEnv(size=4, max_steps=10)
        nenv.reset(seed=0)
        step = jax.jit(jenv.step)
        rng = np.random.default_rng(5)
        key = jax.random.PRNGKey(5)
        key, sub = jax.random.split(key)
        state, jobs = jenv.reset(sub)
        assert np.asarray(jobs).dtype == np.int32
        terms = truncs = 0
        for _ in range(400):
            nenv._pos = np.asarray(state.pos, np.int32).copy()
            nenv._t = int(state.t)
            action = int(rng.integers(4))
            state, jobs, jrew, jterm, jtrunc = step(state, jnp.int32(action))
            nobs, nrew, nterm, ntrunc, _ = nenv.step(action)
            np.testing.assert_array_equal(np.asarray(jobs), nobs)
            assert np.asarray(jobs).dtype == nobs.dtype == np.int32
            assert float(jrew) == nrew
            assert bool(jterm) == nterm and bool(jtrunc) == ntrunc
            terms += bool(jterm)
            truncs += bool(jtrunc) and not bool(jterm)
            if bool(jterm) or bool(jtrunc):
                key, sub = jax.random.split(key)
                state, jobs = jenv.reset(sub)
        assert terms >= 3 and truncs >= 3, (terms, truncs)

    def test_reset_distribution_excludes_goal(self):
        jenv = make_jax("GridWorld-v0", size=3)
        for i in range(32):
            state, obs = jenv.reset(jax.random.PRNGKey(i))
            assert not bool(np.all(np.asarray(state.pos) == 2)), i
            np.testing.assert_array_equal(np.asarray(obs),
                                          np.asarray(state.pos))
        # same key ⇒ same start, the reproducibility half
        a = np.asarray(jenv.reset(jax.random.PRNGKey(0))[1])
        b = np.asarray(jenv.reset(jax.random.PRNGKey(0))[1])
        np.testing.assert_array_equal(a, b)

    def test_goal_pays_exactly_once(self):
        from relayrl_tpu.envs.jax.gridworld import GridWorldState

        jenv = make_jax("GridWorld-v0", size=3, max_steps=20)
        step = jax.jit(jenv.step)
        # one cell left of the goal: move right -> terminal, reward 1.0
        state = GridWorldState(pos=jnp.array([2, 1], jnp.int32),
                               t=jnp.int32(0))
        state, obs, rew, term, trunc = step(state, jnp.int32(3))
        assert float(rew) == 1.0 and bool(term) and not bool(trunc)
        np.testing.assert_array_equal(np.asarray(obs), [2, 2])
        # stepping at a border clamps and pays nothing
        state = GridWorldState(pos=jnp.array([0, 0], jnp.int32),
                               t=jnp.int32(0))
        state, obs, rew, term, _ = step(state, jnp.int32(0))  # up at top
        assert float(rew) == 0.0 and not bool(term)
        np.testing.assert_array_equal(np.asarray(obs), [0, 0])


class TestBanditParity:
    def test_full_bitwise_parity(self):
        """All-integer dynamics (context one-hot, target-arm residue,
        0/1 reward): obs, reward, and BOTH flags are bit-equal to the
        numpy twin across injected contexts. Every step is an episode
        (one-step bandit), so this is also the densest autoreset
        exercise in the battery."""
        from relayrl_tpu.envs import BanditEnv

        jenv = make_jax("Bandit-v0", n_contexts=5, n_arms=3)
        nenv = BanditEnv(n_contexts=5, n_arms=3)
        nenv.reset(seed=0)
        step = jax.jit(jenv.step)
        rng = np.random.default_rng(2)
        key = jax.random.PRNGKey(2)
        hits = 0
        for i in range(64):
            key, sub = jax.random.split(key)
            state, jobs = jenv.reset(sub)
            nenv._ctx = int(state.ctx)
            np.testing.assert_array_equal(np.asarray(jobs), nenv._obs())
            assert np.asarray(jobs).dtype == np.int32
            action = int(rng.integers(3))
            _state, jobs, jrew, jterm, jtrunc = step(state,
                                                     jnp.int32(action))
            nobs, nrew, nterm, ntrunc, _ = nenv.step(action)
            np.testing.assert_array_equal(np.asarray(jobs), nobs)
            assert float(jrew) == nrew
            assert bool(jterm) == nterm is True
            assert bool(jtrunc) == ntrunc is False
            hits += int(nrew)
        assert 0 < hits < 64, "need both rewarded and unrewarded pulls"

    def test_target_arm_is_learnable_mapping(self):
        """The contract the fast-regression signal rests on: the correct
        arm is a deterministic function of the context, identical in
        both planes."""
        from relayrl_tpu.envs import BanditEnv
        from relayrl_tpu.envs.jax.bandit import BanditState

        jenv = make_jax("Bandit-v0", n_contexts=6, n_arms=4,
                        mult=3, shift=1)
        nenv = BanditEnv(n_contexts=6, n_arms=4, mult=3, shift=1)
        step = jax.jit(jenv.step)
        for ctx in range(6):
            target = nenv.target_arm(ctx)
            state = BanditState(ctx=jnp.int32(ctx))
            _s, _o, rew, _t, _x = step(state, jnp.int32(target))
            assert float(rew) == 1.0, (ctx, target)
            wrong = (target + 1) % 4
            _s, _o, rew, _t, _x = step(state, jnp.int32(wrong))
            assert float(rew) == 0.0


class TestTokenGenParity:
    def test_full_bitwise_parity_programmatic(self):
        """TokenGen with the all-integer programmatic scorer: obs
        (the token context window), reward (a count, integral in
        float32), and flags bit-equal to the numpy twin from injected
        states, across EOS endings and max_new_tokens endings."""
        from relayrl_tpu.envs import TokenGenEnv
        from relayrl_tpu.rlhf.scorers import ProgrammaticScorer

        scorer = ProgrammaticScorer(vocab_size=6)
        kwargs = dict(vocab_size=6, prompt_len=2, max_new_tokens=5,
                      scorer=scorer)
        jenv = make_jax("TokenGen-v0", **kwargs)
        nenv = TokenGenEnv(**kwargs)
        nenv.reset(seed=0)
        step = jax.jit(jenv.step)
        rng = np.random.default_rng(4)
        key = jax.random.PRNGKey(4)
        key, sub = jax.random.split(key)
        state, jobs = jenv.reset(sub)
        assert np.asarray(jobs).dtype == np.int32
        eos_ends = budget_ends = 0
        scored = 0.0
        for _ in range(300):
            nenv._tokens = np.asarray(state.tokens, np.int32).copy()
            nenv._t = int(state.t)
            action = int(rng.integers(6))
            state, jobs, jrew, jterm, jtrunc = step(state,
                                                    jnp.int32(action))
            nobs, nrew, nterm, ntrunc, _ = nenv.step(action)
            np.testing.assert_array_equal(np.asarray(jobs), nobs)
            assert float(jrew) == nrew
            assert bool(jterm) == nterm and bool(jtrunc) == ntrunc is False
            if bool(jterm):
                scored += float(jrew)
                # A terminal whose final action is NOT EOS can only be
                # the max_new_tokens budget ending — the second
                # termination type the parity must cover.
                eos_ends += int(action == 0)
                budget_ends += int(action != 0)
                key, sub = jax.random.split(key)
                state, jobs = jenv.reset(sub)
        assert eos_ends >= 3, "never saw an EOS ending"
        assert budget_ends >= 3, "never saw a max_new_tokens ending"
        assert scored > 0, "random play never hit a successor token"

    def test_prompt_excludes_eos_and_reset_reproducible(self):
        jenv = make_jax("TokenGen-v0", vocab_size=8, prompt_len=3,
                        max_new_tokens=4)
        for i in range(16):
            state, obs = jenv.reset(jax.random.PRNGKey(i))
            prompt = np.asarray(state.tokens)[:3]
            assert np.all(prompt >= 1) and np.all(prompt < 8)
            assert np.all(np.asarray(state.tokens)[3:] == 0)
        a = np.asarray(jenv.reset(jax.random.PRNGKey(0))[1])
        b = np.asarray(jenv.reset(jax.random.PRNGKey(0))[1])
        np.testing.assert_array_equal(a, b)

    def test_scorerless_mode_pays_zero(self):
        """The decoupled-dataflow contract: scorer=None means the env
        NEVER pays reward — the score stage owns it."""
        jenv = make_jax("TokenGen-v0", vocab_size=6, prompt_len=2,
                        max_new_tokens=3)
        state, _ = jenv.reset(jax.random.PRNGKey(0))
        step = jax.jit(jenv.step)
        for tok in (3, 4, 0):  # incl. an EOS terminal
            state, _obs, rew, _term, _tr = step(state, jnp.int32(tok))
            assert float(rew) == 0.0


class TestRegistry:
    def test_jax_registry_covers_builtins(self):
        assert set(JAX_ENVS) == {"CartPole-v1", "Pendulum-v1", "Recall-v0",
                                 "GridWorld-v0", "Bandit-v0", "TokenGen-v0"}

    def test_list_envs_has_both_planes(self):
        known = list_envs()
        assert known["builtin"] == sorted(known["builtin"])
        assert "CartPole-v1" in known["jax"]

    def test_make_jax_unknown_id_lists_registry(self):
        with pytest.raises(ValueError, match="CartPole-v1"):
            make_jax("NoSuchEnv-v0")

    def test_make_error_message_lists_both_planes(self):
        from relayrl_tpu.envs import make

        with pytest.raises(ValueError, match="on-device"):
            make("NoSuchEnv-v0")

    def test_make_jax_forwards_kwargs(self):
        env = make_jax("Recall-v0", horizon=16, n_cues=4)
        assert env.horizon == 16 and env.obs_dim == 6
