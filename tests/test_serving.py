"""Disaggregated batched-inference serving plane (runtime/inference.py).

The acceptance surface of ISSUE 10:

* the dynamic-batching queue closes on BOTH triggers (max_batch = size,
  batch_timeout_ms = deadline) and buckets dispatch shapes via
  pick_bucket, with padded rows provably inert;
* queue-limit overload answers a typed NACK_OVERLOADED with retry-after,
  and the thin client honors it without charging its circuit breaker;
* every batch is served by exactly ONE params version even against a
  racing swapper (the single read under the shared swap gate);
* served-mode parity: a RemoteActorClient's actions are BIT-identical to
  a local PolicyActor holding the same params version and seed — and the
  shipped trajectory bytes are byte-identical — on both the zmq ROUTER
  plane and the in-band grpc GetActions RPC;
* the agent.infer fault site + a killed/restarted service heal through
  the shared RetryPolicy/breaker without wedging the env loop.
"""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from _util import free_port

pytestmark = pytest.mark.serving


@pytest.fixture
def fresh_registry():
    from relayrl_tpu import telemetry
    from relayrl_tpu.transport.retry import reset_metrics_for_tests

    reg = telemetry.Registry(run_id="serving-test")
    telemetry.set_registry(reg)
    reset_metrics_for_tests()
    yield reg
    telemetry.reset_for_tests()
    reset_metrics_for_tests()


def _reinforce_bundle(scratch, obs_dim=6, act_dim=3):
    from relayrl_tpu.algorithms import build_algorithm

    algo = build_algorithm(
        "REINFORCE", env_dir=scratch, obs_dim=obs_dim, act_dim=act_dim,
        hidden_sizes=[16], traj_per_epoch=4, with_vf_baseline=True)
    return algo.bundle()


def _versioned_bundle(bundle, version):
    """Params whose value head outputs exactly ``version`` for any obs:
    aux['v'] reveals which params produced each action (the
    test_vector_actor atomic-swap probe)."""
    import copy

    from relayrl_tpu.types.model_bundle import ModelBundle

    params = jax.tree_util.tree_map(np.asarray, bundle.params)
    params = copy.deepcopy(params)
    params["params"]["vf_head"]["kernel"] = np.zeros_like(
        params["params"]["vf_head"]["kernel"])
    params["params"]["vf_head"]["bias"] = np.full_like(
        params["params"]["vf_head"]["bias"], float(version))
    for layer in params["params"]["vf_trunk"].values():
        layer["bias"] = np.zeros_like(layer["bias"])
    return ModelBundle(arch=dict(bundle.arch), params=params,
                       version=version)


def _submit(svc, key, obs, req_id=1, agent_id="t", mask=None):
    """One decoded request against a live service; returns (event, box) —
    box['reply'] is the decoded reply once event fires."""
    from relayrl_tpu.transport.serving import (
        pack_infer_request,
        unpack_infer_reply,
    )

    box: dict = {}
    done = threading.Event()

    def reply(b):
        box["reply"] = unpack_infer_reply(b)
        done.set()

    svc.handle_request(
        pack_infer_request(agent_id, req_id, key, obs, mask), reply)
    return done, box


class TestServingCodec:
    def test_scalar_and_array_round_trip(self):
        """0-d actions/aux must survive the wire as exact 0-d ndarrays
        (np.ascontiguousarray silently promotes them to 1-d — the shape
        is captured first)."""
        from relayrl_tpu.transport.serving import (
            pack_action_reply,
            unpack_infer_reply,
        )

        act = np.asarray(np.int32(2))
        aux = {"logp_a": np.asarray(np.float32(-1.5)),
               "vec": np.arange(3, dtype=np.float32)}
        key = np.array([1, 2], np.uint32)
        out = unpack_infer_reply(pack_action_reply(7, 3, act, key, aux))
        assert out["req"] == 7 and out["ver"] == 3
        assert out["act"].shape == () and out["act"].dtype == np.int32
        assert out["aux"]["logp_a"].shape == ()
        assert out["aux"]["logp_a"].dtype == np.float32
        assert np.array_equal(out["aux"]["vec"], aux["vec"])
        assert np.frombuffer(out["key"], np.uint32).tolist() == [1, 2]

    def test_request_round_trip_with_mask_and_uint8(self):
        from relayrl_tpu.transport.serving import (
            pack_infer_request,
            unpack_infer_request,
        )

        key = np.asarray(jax.random.PRNGKey(0))
        obs = np.arange(12, dtype=np.uint8).reshape(3, 4)
        mask = np.array([1.0, 0.0], np.float32)
        out = unpack_infer_request(
            pack_infer_request("agent-1", 42, key, obs, mask))
        assert out["id"] == "agent-1" and out["req"] == 42
        assert out["obs"].dtype == np.uint8 and out["obs"].shape == (3, 4)
        assert np.array_equal(out["obs"], obs)
        assert np.array_equal(out["mask"], mask)
        assert np.array_equal(out["key"], key)

    def test_malformed_request_answers_error(self, tmp_cwd, fresh_registry):
        from relayrl_tpu.runtime.inference import InferenceService

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=2, batch_timeout_ms=1.0)
        from relayrl_tpu.transport.serving import unpack_infer_reply

        got = []
        svc.handle_request(b"\x81\xa3junk", lambda b: got.append(
            unpack_infer_reply(b)))
        assert got and got[0]["code"] == 0


class TestBatchingQueue:
    def test_size_trigger_close(self, tmp_cwd, fresh_registry):
        """max_batch requests close the batch immediately (reason
        "size"), long before the deadline."""
        from relayrl_tpu.runtime.inference import InferenceService

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=4, batch_timeout_ms=5000.0)
        svc.start()
        try:
            keys = np.asarray(jax.random.split(jax.random.PRNGKey(0), 4))
            obs = np.random.default_rng(0).standard_normal(
                (4, 6)).astype(np.float32)
            # Warm the bucket-4 compile OUTSIDE the timed window (the
            # first dispatch traces + compiles; this test times the batch
            # CLOSE, not XLA).
            warm = [_submit(svc, keys[i], obs[i], req_id=100 + i)
                    for i in range(4)]
            for done, _ in warm:
                assert done.wait(60)
            t0 = time.monotonic()
            waits = [_submit(svc, keys[i], obs[i], req_id=i + 1)
                     for i in range(4)]
            for done, box in waits:
                assert done.wait(10), "size-triggered batch never closed"
                assert box["reply"]["code"] == 1
            assert time.monotonic() - t0 < 2.0, \
                "size close waited toward the deadline"
            assert svc._m_batches["size"].total() == 2
            assert svc._m_batches["deadline"].total() == 0
        finally:
            svc.stop()

    def test_deadline_trigger_close(self, tmp_cwd, fresh_registry):
        """A short batch closes batch_timeout_ms after its FIRST request
        (reason "deadline") instead of waiting for max_batch forever."""
        from relayrl_tpu.runtime.inference import InferenceService

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=64, batch_timeout_ms=40.0)
        svc.start()
        try:
            key = np.asarray(jax.random.PRNGKey(1))
            obs = np.zeros(6, np.float32)
            t0 = time.monotonic()
            done, box = _submit(svc, key, obs)
            assert done.wait(10), "deadline-triggered batch never closed"
            dt = time.monotonic() - t0
            assert box["reply"]["code"] == 1
            assert dt >= 0.030, f"closed before the deadline ({dt:.3f}s)"
            assert svc._m_batches["deadline"].total() == 1
        finally:
            svc.stop()

    def test_bucket_selection_and_padding_inert(self, tmp_cwd,
                                                fresh_registry):
        """3 requests dispatch at bucket 4 (smallest bucket >= n), and
        the padded row cannot perturb the real rows: every reply is
        bit-identical to the unpadded singles."""
        from relayrl_tpu.runtime.inference import InferenceService
        from relayrl_tpu.runtime.policy_actor import _fuse_rng

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=8, batch_timeout_ms=30.0,
                               buckets=[1, 2, 4, 8])
        shapes = []
        inner = svc._batched_fn

        def spying(params, keys, obs, masks, explore):
            shapes.append(tuple(np.asarray(keys).shape))
            return inner(params, keys, obs, masks, explore)

        svc._batched_fn = spying
        svc.start()
        try:
            keys = np.asarray(jax.random.split(jax.random.PRNGKey(3), 3))
            obs = np.random.default_rng(1).standard_normal(
                (3, 6)).astype(np.float32)
            waits = [_submit(svc, keys[i], obs[i], req_id=i + 1)
                     for i in range(3)]
            single = jax.jit(_fuse_rng(svc.policy.step))
            for i, (done, box) in enumerate(waits):
                assert done.wait(10)
                reply = box["reply"]
                assert reply["code"] == 1
                act, aux, nk = single(bundle.params, keys[i], obs[i], None)
                assert np.array_equal(reply["act"], np.asarray(act))
                for k in aux:
                    assert np.array_equal(reply["aux"][k],
                                          np.asarray(aux[k])), k
                assert np.array_equal(
                    np.frombuffer(reply["key"], np.uint32),
                    np.asarray(nk).ravel())
            assert shapes and shapes[0][0] == 4, \
                f"expected bucket-4 dispatch, saw {shapes}"
        finally:
            svc.stop()

    def test_queue_limit_overload_nack(self, tmp_cwd, fresh_registry):
        """Beyond serving.queue_limit, submissions answer the typed
        NACK_OVERLOADED with a retry-after hint instead of queueing
        unboundedly (the worker is NOT running, so nothing drains)."""
        from relayrl_tpu.runtime.inference import InferenceService
        from relayrl_tpu.transport.base import NACK_OVERLOADED

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=4, batch_timeout_ms=5.0,
                               queue_limit=2, retry_after_s=0.25)
        key = np.asarray(jax.random.PRNGKey(0))
        obs = np.zeros(6, np.float32)
        waits = [_submit(svc, key, obs, req_id=i + 1) for i in range(3)]
        done, box = waits[2]
        assert done.wait(5), "overload nack never delivered"
        assert box["reply"]["code"] == NACK_OVERLOADED
        assert box["reply"]["retry_after_s"] == pytest.approx(0.25)
        assert svc._m_rejected.total() == 1
        assert not waits[0][0].is_set() and not waits[1][0].is_set()
        # stop() answers the parked requests with a retryable nack too —
        # a restarting service must not leave clients hanging.
        svc.stop()
        for done_i, box_i in waits[:2]:
            assert done_i.wait(5)
            assert box_i["reply"]["code"] == NACK_OVERLOADED

    def test_single_params_version_per_batch_under_racing_swapper(
            self, tmp_cwd, fresh_registry):
        """A swapper thread hammers version-coded params while requests
        stream: every reply's aux['v'] must equal its reply 'ver' — no
        request is ever served params from a version other than the one
        its batch read under the gate."""
        from relayrl_tpu.runtime.inference import InferenceService

        base = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(_versioned_bundle(base, 1), max_batch=4,
                               batch_timeout_ms=2.0)
        svc.start()
        stop = threading.Event()
        next_version = [2]

        def swapper():
            while not stop.is_set():
                svc.maybe_swap(_versioned_bundle(base, next_version[0]))
                next_version[0] += 1

        t = threading.Thread(target=swapper, daemon=True)
        t.start()
        try:
            key = np.asarray(jax.random.PRNGKey(5))
            obs = np.random.default_rng(2).standard_normal(6).astype(
                np.float32)
            mismatches = []
            for i in range(40):
                done, box = _submit(svc, key, obs, req_id=i + 1)
                assert done.wait(10)
                reply = box["reply"]
                assert reply["code"] == 1
                v = float(reply["aux"]["v"])
                if v != float(reply["ver"]):
                    mismatches.append((reply["ver"], v))
                key = np.frombuffer(reply["key"], np.uint32)
            assert not mismatches, \
                f"replies served by params of another version: {mismatches[:3]}"
            assert svc.version >= 2  # swaps actually landed mid-run
        finally:
            stop.set()
            t.join(timeout=5)
            svc.stop()

    def test_stale_requests_nacked_unserved(self, tmp_cwd,
                                            fresh_registry):
        """Ghost-work guard: requests that outlive serving.stale_after_s
        in the queue (their client timed out and retried) are answered
        with a retryable nack at batch-gather time, never dispatched —
        under backlog a retry round must not double-serve."""
        from relayrl_tpu.runtime.inference import InferenceService
        from relayrl_tpu.transport.base import NACK_OVERLOADED

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=4, batch_timeout_ms=5.0,
                               stale_after_s=0.2)
        key = np.asarray(jax.random.PRNGKey(0))
        obs = np.zeros(6, np.float32)
        # Enqueue while the worker is NOT running, let them go stale,
        # then start the worker: the gather pass must nack both without
        # serving them.
        waits = [_submit(svc, key, obs, req_id=i + 1) for i in range(2)]
        time.sleep(0.4)
        svc.start()
        try:
            for done, box in waits:
                assert done.wait(10), "stale request never answered"
                assert box["reply"]["code"] == NACK_OVERLOADED
                assert "stale" in box["reply"]["error"]
            assert svc._m_stale.total() == 2
            assert (svc._m_batches["size"].total()
                    + svc._m_batches["deadline"].total()) == 0
            # fresh traffic still serves normally afterwards
            done, box = _submit(svc, key, obs, req_id=9)
            assert done.wait(30) and box["reply"]["code"] == 1
        finally:
            svc.stop()

    def test_sequence_policies_refused(self, tmp_cwd, fresh_registry):
        from relayrl_tpu.models import build_policy
        from relayrl_tpu.runtime.inference import InferenceService
        from relayrl_tpu.types.model_bundle import ModelBundle

        arch = {"kind": "transformer_discrete", "obs_dim": 5, "act_dim": 3,
                "d_model": 16, "n_layers": 1, "n_heads": 2,
                "max_seq_len": 8}
        policy = build_policy(arch)
        bundle = ModelBundle(version=1, arch=dict(arch),
                             params=policy.init_params(jax.random.PRNGKey(0)))
        with pytest.raises(ValueError, match="sequence policies"):
            InferenceService(bundle)

    def test_install_params_owns_memory(self, tmp_cwd, fresh_registry):
        """The colocated publish feed must copy: mutating the publisher's
        host tree after install must not change served params."""
        from relayrl_tpu.runtime.inference import InferenceService

        base = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(_versioned_bundle(base, 1), max_batch=1,
                               batch_timeout_ms=1.0)
        svc.start()
        try:
            host_tree = jax.tree_util.tree_map(
                np.array, _versioned_bundle(base, 2).params)
            assert svc.install_params(2, base.arch, host_tree)
            host_tree["params"]["vf_head"]["bias"][:] = 777.0
            key = np.asarray(jax.random.PRNGKey(0))
            done, box = _submit(svc, key, np.zeros(6, np.float32))
            assert done.wait(10)
            assert float(box["reply"]["aux"]["v"]) == 2.0
        finally:
            svc.stop()


class _FakeServingClient:
    """Scripted reply stream for the thin client's retry loop."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = 0

    def request(self, payload, req_id, timeout_s):
        self.calls += 1
        step = self.script.pop(0) if self.script else self.script_default
        if isinstance(step, Exception):
            raise step
        out = dict(step)
        out.setdefault("req", req_id)
        return out

    def close(self):
        pass


def _bare_client(fake, infer_deadline_s=5.0, request_timeout_s=0.2):
    """A RemoteActorClient wired straight to a fake serving channel —
    the retry/breaker/nack loop under test, no sockets."""
    from relayrl_tpu import telemetry
    from relayrl_tpu.runtime.inference import RemoteActorClient
    from relayrl_tpu.transport.retry import CircuitBreaker, RetryPolicy

    client = object.__new__(RemoteActorClient)
    client._serving = fake
    client._breaker = CircuitBreaker("test", failure_threshold=3,
                                     reset_timeout_s=0.2)
    client._retry = RetryPolicy(base_delay_s=0.01, max_delay_s=0.05)
    client._fault_infer = None
    client._rng = np.asarray(jax.random.PRNGKey(0))
    client._req_counter = 0
    client._request_timeout_s = request_timeout_s
    client._infer_deadline_s = infer_deadline_s
    client.version = -1

    class _T:
        identity = "bare"

    client.transport = _T()
    reg = telemetry.get_registry()
    client._m_request_s = reg.histogram("relayrl_serving_client_request_seconds", "t")
    client._m_retries = reg.counter("relayrl_serving_client_retries_total", "t")
    client._m_nacked = reg.counter("relayrl_serving_client_nacked_total", "t")
    return client


def _ok_reply(act=1, ver=3):
    key = np.array([9, 9], np.uint32)
    return {"code": 1, "ver": ver, "act": np.asarray(np.int32(act)),
            "key": key.tobytes(), "aux": {"v": np.asarray(np.float32(0.5))}}


class TestClientRetry:
    def test_overload_nack_honors_retry_after_without_breaker_charge(
            self, fresh_registry):
        from relayrl_tpu.transport.base import NACK_OVERLOADED

        fake = _FakeServingClient([
            {"code": NACK_OVERLOADED, "error": "full",
             "retry_after_s": 0.15},
            _ok_reply(),
        ])
        client = _bare_client(fake)
        t0 = time.monotonic()
        act, aux = client._infer(np.zeros(4, np.float32), None)
        dt = time.monotonic() - t0
        assert int(act) == 1 and client.version == 3
        assert dt >= 0.14, f"retry-after not honored ({dt:.3f}s)"
        assert fake.calls == 2
        assert client._breaker.state == "closed"
        assert client._m_nacked.total() == 1
        assert client._m_retries.total() == 0  # nacks are not failures

    def test_timeouts_charge_breaker_then_heal(self, fresh_registry):
        fake = _FakeServingClient([
            TimeoutError("t"), TimeoutError("t"), TimeoutError("t"),
            _ok_reply(ver=7),
        ])
        client = _bare_client(fake)
        act, aux = client._infer(np.zeros(4, np.float32), None)
        assert int(act) == 1 and client.version == 7
        # 3 failures opened the breaker (threshold 3); the half-open
        # probe then healed it — the env loop waited, never wedged.
        assert client._m_retries.total() == 3
        assert client._breaker.state == "closed"

    def test_deadline_exhaustion_raises(self, fresh_registry):
        fake = _FakeServingClient([])
        fake.script_default = None

        class _AlwaysTimeout(_FakeServingClient):
            def request(self, payload, req_id, timeout_s):
                self.calls += 1
                raise TimeoutError("dead service")

        client = _bare_client(_AlwaysTimeout([]), infer_deadline_s=0.6)
        with pytest.raises(RuntimeError, match="budget"):
            client._infer(np.zeros(4, np.float32), None)

    def test_error_reply_retries(self, fresh_registry):
        """A code-0 error (corrupt request drill: the service's decode
        guard answered) is retryable, not fatal."""
        fake = _FakeServingClient([
            {"code": 0, "error": "malformed inference request"},
            _ok_reply(ver=4),
        ])
        client = _bare_client(fake)
        act, _ = client._infer(np.zeros(4, np.float32), None)
        assert int(act) == 1 and client.version == 4
        assert fake.calls == 2


def _serving_stack(tmp_path, server_type="zmq", max_batch=4,
                   batch_timeout_ms=3.0, traj_per_epoch=64,
                   spool_entries=512):
    """One TrainingServer with serving enabled + its address block, on a
    fresh set of ports."""
    from relayrl_tpu.runtime.server import TrainingServer

    scratch = str(tmp_path)
    cfg_path = os.path.join(scratch, "serving_cfg.json")
    with open(cfg_path, "w") as f:
        json.dump({"serving": {"enabled": True, "max_batch": max_batch,
                               "batch_timeout_ms": batch_timeout_ms},
                   "actor": {"spool_entries": spool_entries}}, f)
    if server_type == "grpc":
        addrs = {"bind_addr": f"127.0.0.1:{free_port()}",
                 "native_grpc": False}
        client_addrs = {"server_addr": addrs["bind_addr"], "probe": False}
    else:
        addrs = {
            "agent_listener_addr": f"tcp://127.0.0.1:{free_port()}",
            "trajectory_addr": f"tcp://127.0.0.1:{free_port()}",
            "model_pub_addr": f"tcp://127.0.0.1:{free_port()}",
            "serving_addr": f"tcp://127.0.0.1:{free_port()}",
        }
        client_addrs = {
            "agent_listener_addr": addrs["agent_listener_addr"],
            "trajectory_addr": addrs["trajectory_addr"],
            "model_sub_addr": addrs["model_pub_addr"],
            "serving_addr": addrs["serving_addr"],
            "probe": False,
        }
    server = TrainingServer(
        "REINFORCE", obs_dim=6, act_dim=3, env_dir=scratch,
        config_path=cfg_path, server_type=server_type,
        hyperparams={"traj_per_epoch": traj_per_epoch,
                     "hidden_sizes": [16], "with_vf_baseline": True},
        **addrs)
    return server, cfg_path, client_addrs


class TestServedParityE2E:
    @pytest.mark.parametrize("server_type", ["zmq", "grpc"])
    def test_bit_identical_served_vs_local(self, tmp_cwd, fresh_registry,
                                           server_type):
        """The acceptance lock: at a pinned params version, a thin
        client's action stream (and its shipped episode BYTES) are
        identical to a local PolicyActor with the same seed holding the
        same bundle — on the zmq ROUTER plane and the grpc GetActions
        RPC."""
        from relayrl_tpu.runtime.inference import RemoteActorClient
        from relayrl_tpu.runtime.policy_actor import PolicyActor
        from relayrl_tpu.types.model_bundle import ModelBundle

        server, cfg_path, client_addrs = _serving_stack(
            tmp_cwd, server_type=server_type, traj_per_epoch=10_000)
        try:
            bundle = ModelBundle(
                version=server.algorithm.version,
                arch=dict(server.algorithm.bundle().arch),
                params=server.algorithm.bundle().params)
            sent_local, sent_remote = [], []
            local = PolicyActor(bundle, seed=23,
                                on_send=lambda p: sent_local.append(p))
            client = RemoteActorClient(
                config_path=cfg_path, server_type=server_type, seed=23,
                **client_addrs)
            client.trajectory._on_send = lambda p: sent_remote.append(p)
            rng = np.random.default_rng(11)
            for i in range(10):
                obs = rng.standard_normal(6).astype(np.float32)
                reward = 0.0 if i == 0 else 0.5
                r1 = local.request_for_action(obs, reward=reward)
                r2 = client.request_for_action(obs, reward=reward)
                assert np.array_equal(np.asarray(r1.act),
                                      np.asarray(r2.act)), f"step {i}"
                assert r1.act.dtype == r2.act.dtype
                assert r1.act.shape == r2.act.shape
                for k in r1.data:
                    assert np.array_equal(np.asarray(r1.data[k]),
                                          np.asarray(r2.data[k])), (i, k)
                    assert r1.data[k].dtype == r2.data[k].dtype, (i, k)
            local.flag_last_action(1.0, terminated=True)
            client.flag_last_action(1.0, terminated=True)
            assert sent_local == sent_remote and len(sent_local) == 1, \
                "served episode bytes differ from the local actor's"
            client.disable_agent()
        finally:
            server.disable_server()

    def test_masked_served_parity(self, tmp_cwd, fresh_registry):
        from relayrl_tpu.runtime.inference import RemoteActorClient
        from relayrl_tpu.runtime.policy_actor import PolicyActor
        from relayrl_tpu.types.model_bundle import ModelBundle

        server, cfg_path, client_addrs = _serving_stack(
            tmp_cwd, traj_per_epoch=10_000)
        try:
            bundle = ModelBundle(
                version=server.algorithm.version,
                arch=dict(server.algorithm.bundle().arch),
                params=server.algorithm.bundle().params)
            local = PolicyActor(bundle, seed=4)
            client = RemoteActorClient(config_path=cfg_path, seed=4,
                                       **client_addrs)
            mask = np.array([1.0, 0.0, 1.0], np.float32)
            rng = np.random.default_rng(3)
            for _ in range(5):
                obs = rng.standard_normal(6).astype(np.float32)
                r1 = local.request_for_action(obs, mask=mask)
                r2 = client.request_for_action(obs, mask=mask)
                assert np.array_equal(np.asarray(r1.act),
                                      np.asarray(r2.act))
                assert int(np.asarray(r2.act)) != 1  # mask respected
            client.disable_agent()
        finally:
            server.disable_server()

    def test_trajectories_train_and_model_version_advances(
            self, tmp_cwd, fresh_registry):
        """The full loop: thin-client episodes reach the learner through
        the UNCHANGED trajectory plane, updates publish, and the
        colocated service starts serving the new version (visible as the
        client's model_version advancing) — with batching provably
        active (occupancy histogram saw > 1)."""
        from relayrl_tpu.runtime.inference import RemoteActorClient

        server, cfg_path, client_addrs = _serving_stack(
            tmp_cwd, traj_per_epoch=2, max_batch=4, batch_timeout_ms=4.0)
        try:
            clients = [RemoteActorClient(config_path=cfg_path, seed=s,
                                         identity=f"thin-{s}",
                                         **client_addrs)
                       for s in range(3)]
            stop = threading.Event()

            def drive(client, seed):
                rng = np.random.default_rng(seed)
                while not stop.is_set():
                    obs = rng.standard_normal(6).astype(np.float32)
                    for _ in range(8):
                        client.request_for_action(obs, reward=1.0)
                        obs = rng.standard_normal(6).astype(np.float32)
                        if stop.is_set():
                            break
                    client.flag_last_action(1.0, terminated=True)

            threads = [threading.Thread(target=drive, args=(c, i),
                                        daemon=True)
                       for i, c in enumerate(clients)]
            for t in threads:
                t.start()
            deadline = time.monotonic() + 60
            while (time.monotonic() < deadline
                   and (server.stats["updates"] < 2
                        or max(c.model_version for c in clients) < 2)):
                time.sleep(0.1)
            stop.set()
            for t in threads:
                t.join(timeout=30)
            assert server.stats["updates"] >= 2, "thin-client episodes never trained"
            assert max(c.model_version for c in clients) >= 2, \
                "the colocated service never served the published version"
            occ = server.inference._m_occupancy.totals()
            counts, total, n = occ
            assert n > 0 and total / n > 1.0, \
                f"batching never engaged (mean occupancy {total}/{n})"
            for c in clients:
                c.disable_agent()
        finally:
            server.disable_server()


class TestFaultPlaneAndHeal:
    # ISSUE 17 wall re-fit: per-site fault sweep rides the slow tier; the
    # fast tier keeps the killed-service heal drill below.
    @pytest.mark.slow
    def test_agent_infer_fault_site_drop_and_corrupt_heal(
            self, tmp_cwd, fresh_registry):
        """agent.infer chaos: deterministic drops + corruption on the
        request plane — every action still lands (drop → timeout retry,
        corrupt → service decode-guard error reply → retry), and the
        injection ledger counted the faults."""
        from relayrl_tpu import faults
        from relayrl_tpu.faults import FaultPlan
        from relayrl_tpu.runtime.inference import (
            InferenceService,
            RemoteActorClient,
        )

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=2, batch_timeout_ms=2.0)
        addr = f"tcp://127.0.0.1:{free_port()}"
        svc.bind_zmq(addr)
        svc.start()
        plan = FaultPlan.from_dict({"seed": 3, "rules": [
            {"site": "agent.infer", "op": "drop", "prob": 0.2},
            {"site": "agent.infer", "op": "corrupt", "prob": 0.2},
        ]})
        faults.install_plan(plan)
        try:
            cfg_path = os.path.join(str(tmp_cwd), "cfg.json")
            with open(cfg_path, "w") as f:
                json.dump({"actor": {"spool_entries": 0},
                           "serving": {"request_timeout_s": 0.3}}, f)
            client = RemoteActorClient(
                config_path=cfg_path, seed=1, serving_addr=addr,
                probe=False,
                agent_listener_addr=f"tcp://127.0.0.1:{free_port()}",
                trajectory_addr=f"tcp://127.0.0.1:{free_port()}",
                model_sub_addr=f"tcp://127.0.0.1:{free_port()}")
            rng = np.random.default_rng(0)
            for _ in range(30):
                client.request_for_action(
                    rng.standard_normal(6).astype(np.float32), reward=1.0)
            site = plan.site("agent.infer")
            assert site is not None and site.injected > 0, \
                "the drill injected nothing"
            client.disable_agent()
        finally:
            faults.install_plan(None)
            svc.stop()

    def test_killed_service_heals_clients_without_wedging(
            self, tmp_cwd, fresh_registry):
        """The chaos drill: the inference service dies mid-run and
        restarts; a stepping client rides the breaker/backoff through
        the outage and completes every action — the env loop never
        wedges and never loses a step."""
        from relayrl_tpu.runtime.inference import (
            InferenceService,
            RemoteActorClient,
        )

        bundle = _reinforce_bundle(str(tmp_cwd))
        addr = f"tcp://127.0.0.1:{free_port()}"
        svc = InferenceService(bundle, max_batch=2, batch_timeout_ms=2.0)
        svc.bind_zmq(addr)
        svc.start()
        cfg_path = os.path.join(str(tmp_cwd), "cfg.json")
        with open(cfg_path, "w") as f:
            json.dump({"actor": {"spool_entries": 0},
                       "serving": {"request_timeout_s": 0.25,
                                   "infer_deadline_s": 60.0}}, f)
        client = RemoteActorClient(
            config_path=cfg_path, seed=2, serving_addr=addr, probe=False,
            agent_listener_addr=f"tcp://127.0.0.1:{free_port()}",
            trajectory_addr=f"tcp://127.0.0.1:{free_port()}",
            model_sub_addr=f"tcp://127.0.0.1:{free_port()}")
        steps = []
        stop_at = 60

        def loop():
            rng = np.random.default_rng(1)
            for _ in range(stop_at):
                steps.append(client.request_for_action(
                    rng.standard_normal(6).astype(np.float32),
                    reward=1.0))

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        # Let it step, kill the service, hold a real outage, restart.
        deadline = time.monotonic() + 20
        while len(steps) < 5 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(steps) >= 5
        svc.stop()
        time.sleep(1.0)
        svc2 = InferenceService(bundle, max_batch=2, batch_timeout_ms=2.0)
        svc2.bind_zmq(addr)
        svc2.start()
        t.join(timeout=90)
        try:
            assert not t.is_alive(), "env loop wedged through the outage"
            assert len(steps) == stop_at, \
                f"actions lost across the outage ({len(steps)}/{stop_at})"
        finally:
            client.disable_agent()
            svc2.stop()


class TestServingDisabledFailsFast:
    def test_grpc_without_serving_raises_pointed_error(self, tmp_cwd,
                                                       fresh_registry):
        """A grpc fleet whose server has serving.enabled false answers
        GetActions with the PERMANENT NACK_UNAVAILABLE — the thin client
        must fail fast with the pointed message, not retry a
        misconfiguration into a 60s deadline exhaustion."""
        from relayrl_tpu.runtime.inference import RemoteActorClient
        from relayrl_tpu.runtime.server import TrainingServer

        bind_addr = f"127.0.0.1:{free_port()}"
        server = TrainingServer(
            "REINFORCE", obs_dim=6, act_dim=3, env_dir=str(tmp_cwd),
            server_type="grpc", native_grpc=False, bind_addr=bind_addr,
            hyperparams={"traj_per_epoch": 64, "hidden_sizes": [16]})
        try:
            assert server.inference is None  # serving defaults off
            client = RemoteActorClient(
                server_type="grpc", seed=1, probe=False,
                server_addr=bind_addr)
            t0 = time.monotonic()
            with pytest.raises(RuntimeError,
                               match="serving is not enabled"):
                client.request_for_action(np.zeros(6, np.float32))
            assert time.monotonic() - t0 < 10, \
                "fail-fast path retried toward the deadline"
            client.disable_agent()
        finally:
            server.disable_server()


class TestAsyncEmitLifecycle:
    def test_close_then_restart_emitter(self, tmp_cwd, fresh_registry):
        """The emitter thread is restartable: close() (the
        disable_agent path) then start_emitter() (the enable path) must
        leave a working host — NOT a depth-2 hand-off deadlock on the
        third window — and close() must not leak the thread."""
        import jax as _jax

        from relayrl_tpu.models import build_policy
        from relayrl_tpu.runtime.anakin import AnakinActorHost
        from relayrl_tpu.types.model_bundle import ModelBundle

        arch = {"kind": "mlp_discrete", "obs_dim": 4, "act_dim": 2,
                "hidden_sizes": [16]}
        policy = build_policy(arch)
        bundle = ModelBundle(
            version=0, arch=arch,
            params=policy.init_params(_jax.random.PRNGKey(0)))
        sink = []
        host = AnakinActorHost(bundle, "CartPole-v1", num_envs=2,
                               unroll_length=8, async_emit=True,
                               on_send=lambda lane, p: sink.append(p),
                               seed=0)
        host.rollout()
        assert host.flush_emits()
        n_before = len(sink)
        assert n_before >= 0
        host.close()
        assert host._emit_thread is None
        host.start_emitter()
        for _ in range(4):  # past the depth-2 hand-off: would deadlock
            host.rollout()  # if the emitter were still stopped
        assert host.flush_emits()
        assert len(sink) > n_before
        host.close()


class TestConfig:
    def test_serving_params_defaults_and_clamps(self, tmp_cwd):
        from relayrl_tpu.config import ConfigLoader

        cfg_path = tmp_cwd / "cfg.json"
        cfg_path.write_text(json.dumps({"serving": {
            "enabled": True, "max_batch": "bogus",
            "batch_timeout_ms": -5, "buckets": [8, 2, "x"],
            "queue_limit": 0}}))
        p = ConfigLoader(None, str(cfg_path)).get_serving_params()
        assert p["enabled"] is True
        assert p["max_batch"] == 16          # malformed → default
        assert p["batch_timeout_ms"] == 0.0  # negative clamps to 0
        assert p["buckets"] is None          # malformed list → derived
        assert p["queue_limit"] == 1         # floor 1

    def test_bucket_list_covers_max_batch(self, tmp_cwd):
        from relayrl_tpu.config import ConfigLoader

        cfg_path = tmp_cwd / "cfg.json"
        cfg_path.write_text(json.dumps({"serving": {
            "max_batch": 32, "buckets": [2, 8]}}))
        p = ConfigLoader(None, str(cfg_path)).get_serving_params()
        assert p["buckets"] == [2, 8, 32]

    def test_default_buckets_powers_of_two(self):
        from relayrl_tpu.runtime.inference import default_buckets

        assert default_buckets(16) == [1, 2, 4, 8, 16]
        assert default_buckets(24) == [1, 2, 4, 8, 16, 24]
        assert default_buckets(1) == [1]

    def test_constructor_buckets_clamped_to_max_batch(self, tmp_cwd,
                                                      fresh_registry):
        """Direct construction with buckets smaller than max_batch must
        get the same cover-clamp the ConfigLoader applies — otherwise a
        size-closed full batch would pick a bucket BELOW its size and
        every full batch would fail the pad computation forever."""
        from relayrl_tpu.runtime.inference import InferenceService

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=16, buckets=[4, 8])
        assert svc.buckets[-1] == 16

    def test_remote_host_mode_accepted(self, tmp_cwd):
        from relayrl_tpu.config import ConfigLoader

        cfg_path = tmp_cwd / "cfg.json"
        cfg_path.write_text(json.dumps({"actor": {"host_mode": "remote"}}))
        p = ConfigLoader(None, str(cfg_path)).get_actor_params()
        assert p["host_mode"] == "remote"
