"""Disaggregated batched-inference serving plane (runtime/inference.py).

The acceptance surface of ISSUE 10:

* the dynamic-batching queue closes on BOTH triggers (max_batch = size,
  batch_timeout_ms = deadline) and buckets dispatch shapes via
  pick_bucket, with padded rows provably inert;
* queue-limit overload answers a typed NACK_OVERLOADED with retry-after,
  and the thin client honors it without charging its circuit breaker;
* every batch is served by exactly ONE params version even against a
  racing swapper (the single read under the shared swap gate);
* served-mode parity: a RemoteActorClient's actions are BIT-identical to
  a local PolicyActor holding the same params version and seed — and the
  shipped trajectory bytes are byte-identical — on both the zmq ROUTER
  plane and the in-band grpc GetActions RPC;
* the agent.infer fault site + a killed/restarted service heal through
  the shared RetryPolicy/breaker without wedging the env loop.
"""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from _util import free_port

pytestmark = pytest.mark.serving


@pytest.fixture
def fresh_registry():
    from relayrl_tpu import telemetry
    from relayrl_tpu.transport.retry import reset_metrics_for_tests

    reg = telemetry.Registry(run_id="serving-test")
    telemetry.set_registry(reg)
    reset_metrics_for_tests()
    yield reg
    telemetry.reset_for_tests()
    reset_metrics_for_tests()


def _reinforce_bundle(scratch, obs_dim=6, act_dim=3):
    from relayrl_tpu.algorithms import build_algorithm

    algo = build_algorithm(
        "REINFORCE", env_dir=scratch, obs_dim=obs_dim, act_dim=act_dim,
        hidden_sizes=[16], traj_per_epoch=4, with_vf_baseline=True)
    return algo.bundle()


def _versioned_bundle(bundle, version):
    """Params whose value head outputs exactly ``version`` for any obs:
    aux['v'] reveals which params produced each action (the
    test_vector_actor atomic-swap probe)."""
    import copy

    from relayrl_tpu.types.model_bundle import ModelBundle

    params = jax.tree_util.tree_map(np.asarray, bundle.params)
    params = copy.deepcopy(params)
    params["params"]["vf_head"]["kernel"] = np.zeros_like(
        params["params"]["vf_head"]["kernel"])
    params["params"]["vf_head"]["bias"] = np.full_like(
        params["params"]["vf_head"]["bias"], float(version))
    for layer in params["params"]["vf_trunk"].values():
        layer["bias"] = np.zeros_like(layer["bias"])
    return ModelBundle(arch=dict(bundle.arch), params=params,
                       version=version)


def _transformer_bundle(obs_dim=5, act_dim=3, max_seq_len=8, seed=0):
    """A tiny windowed (sequence) policy bundle — the arch the serving
    plane refused before serving v2."""
    from relayrl_tpu.models import build_policy
    from relayrl_tpu.types.model_bundle import ModelBundle

    arch = {"kind": "transformer_discrete", "obs_dim": obs_dim,
            "act_dim": act_dim, "d_model": 16, "n_layers": 1,
            "n_heads": 2, "max_seq_len": max_seq_len}
    policy = build_policy(arch)
    return ModelBundle(version=1, arch=dict(arch),
                       params=policy.init_params(jax.random.PRNGKey(seed)))


class _SessionDriver:
    """Hand-rolled serving-v2 session client against a live service:
    carries the PRNG key, the monotonic push cursor, the episode-start
    flag, and the episode mirror — the protocol RemoteActorClient speaks,
    laid bare so tests can replay/evict/desync at will."""

    def __init__(self, svc, sid, seed):
        self.svc = svc
        self.sid = sid
        self.key = np.asarray(jax.random.PRNGKey(seed))
        self.step = 0
        self.episode_start = True
        self.mirror: list = []
        self._req = 0

    def raw(self, obs, with_win=False, step=None, key=None, timeout=30):
        """One request (no client state advance): returns the decoded
        reply."""
        self._req += 1
        win = np.stack(self.mirror) if (with_win and self.mirror) else None
        done, box = _submit(
            self.svc, self.key if key is None else key, obs,
            req_id=self._req, agent_id=self.sid,
            session=self.sid, reset=self.episode_start, window=win,
            step=self.step + 1 if step is None else step)
        assert done.wait(timeout)
        return box["reply"]

    def act(self, obs):
        """One successful action with the full resync protocol: on a
        SESSION_EVICTED nack, resend with the episode window attached."""
        from relayrl_tpu.transport.base import NACK_SESSION_EVICTED

        reply = self.raw(obs)
        if reply["code"] == NACK_SESSION_EVICTED:
            reply = self.raw(obs, with_win=True)
        assert reply["code"] == 1, reply.get("error")
        self.key = np.frombuffer(reply["key"], self.key.dtype).copy()
        self.step += 1
        self.episode_start = False
        ctx = reply.get("ctx")
        assert ctx is not None
        self.mirror.append(np.asarray(obs, np.float32))
        if len(self.mirror) > ctx:
            del self.mirror[:len(self.mirror) - ctx]
        return reply

    def end_episode(self):
        self.episode_start = True
        self.mirror = []


def _submit(svc, key, obs, req_id=1, agent_id="t", mask=None,
            session=None, reset=False, window=None, step=0):
    """One decoded request against a live service; returns (event, box) —
    box['reply'] is the decoded reply once event fires. ``session`` /
    ``reset`` / ``window`` / ``step`` are the serving-v2 per-session
    fields (sequence policies)."""
    from relayrl_tpu.transport.serving import (
        pack_infer_request,
        unpack_infer_reply,
    )

    box: dict = {}
    done = threading.Event()

    def reply(b):
        box["reply"] = unpack_infer_reply(b)
        done.set()

    svc.handle_request(
        pack_infer_request(agent_id, req_id, key, obs, mask,
                           session=session, reset=reset, window=window,
                           step=step), reply)
    return done, box


class TestServingCodec:
    def test_scalar_and_array_round_trip(self):
        """0-d actions/aux must survive the wire as exact 0-d ndarrays
        (np.ascontiguousarray silently promotes them to 1-d — the shape
        is captured first)."""
        from relayrl_tpu.transport.serving import (
            pack_action_reply,
            unpack_infer_reply,
        )

        act = np.asarray(np.int32(2))
        aux = {"logp_a": np.asarray(np.float32(-1.5)),
               "vec": np.arange(3, dtype=np.float32)}
        key = np.array([1, 2], np.uint32)
        out = unpack_infer_reply(pack_action_reply(7, 3, act, key, aux))
        assert out["req"] == 7 and out["ver"] == 3
        assert out["act"].shape == () and out["act"].dtype == np.int32
        assert out["aux"]["logp_a"].shape == ()
        assert out["aux"]["logp_a"].dtype == np.float32
        assert np.array_equal(out["aux"]["vec"], aux["vec"])
        assert np.frombuffer(out["key"], np.uint32).tolist() == [1, 2]

    def test_session_fields_round_trip(self):
        """The serving-v2 wire fields (session id, reset flag, push
        cursor, resync window, reply ctx) survive the codec — and stay
        ABSENT on v1 frames so old clients and old services interop."""
        from relayrl_tpu.transport.serving import (
            pack_action_reply,
            pack_infer_request,
            unpack_infer_reply,
            unpack_infer_request,
        )

        key = np.asarray(jax.random.PRNGKey(1))
        obs = np.arange(5, dtype=np.float32)
        win = np.arange(10, dtype=np.float32).reshape(2, 5)
        out = unpack_infer_request(pack_infer_request(
            "a", 7, key, obs, None, session="a#L001", reset=True,
            window=win, step=3))
        assert out["sid"] == "a#L001" and out["rst"] is True
        assert out["stp"] == 3
        assert np.array_equal(out["win"], win)
        v1 = unpack_infer_request(pack_infer_request("a", 7, key, obs,
                                                     None))
        assert v1["sid"] is None and v1["rst"] is False
        assert v1["stp"] == 0 and v1["win"] is None
        reply = unpack_infer_reply(pack_action_reply(
            7, 3, np.asarray(np.int32(1)), np.array([1, 2], np.uint32),
            {}, ctx=8))
        assert reply["ctx"] == 8
        assert "ctx" not in unpack_infer_reply(pack_action_reply(
            7, 3, np.asarray(np.int32(1)), np.array([1, 2], np.uint32),
            {}))

    def test_request_round_trip_with_mask_and_uint8(self):
        from relayrl_tpu.transport.serving import (
            pack_infer_request,
            unpack_infer_request,
        )

        key = np.asarray(jax.random.PRNGKey(0))
        obs = np.arange(12, dtype=np.uint8).reshape(3, 4)
        mask = np.array([1.0, 0.0], np.float32)
        out = unpack_infer_request(
            pack_infer_request("agent-1", 42, key, obs, mask))
        assert out["id"] == "agent-1" and out["req"] == 42
        assert out["obs"].dtype == np.uint8 and out["obs"].shape == (3, 4)
        assert np.array_equal(out["obs"], obs)
        assert np.array_equal(out["mask"], mask)
        assert np.array_equal(out["key"], key)

    def test_wave_request_rows_match_single_wire(self):
        """Coalesced wave frames are a pure wire optimization: every
        decoded row is field-identical to the same request on the
        single-request wire (bit-exact obs/key/mask, same session
        columns)."""
        from relayrl_tpu.transport.serving import (
            pack_infer_request,
            pack_infer_wave,
            unpack_infer_any,
            unpack_infer_request,
        )

        rng = np.random.default_rng(3)
        entries = []
        for i in range(4):
            entries.append({
                "id": f"a#L{i:03d}", "req": 100 + i,
                "key": np.asarray(jax.random.PRNGKey(i)),
                "obs": rng.standard_normal(6).astype(np.float32),
                "mask": None, "sid": f"a#L{i:03d}", "stp": i + 1,
                "rst": i == 0})
        rows = unpack_infer_any(pack_infer_wave(entries))
        assert len(rows) == 4
        for e, row in zip(entries, rows):
            single = unpack_infer_request(pack_infer_request(
                e["id"], e["req"], e["key"], e["obs"], None,
                session=e["sid"], reset=e["rst"], step=e["stp"]))
            for k in ("id", "req", "sid", "rst", "stp", "win", "mask"):
                assert row[k] == single[k], k
            assert np.array_equal(row["obs"], single["obs"])
            assert row["obs"].dtype == single["obs"].dtype
            assert np.array_equal(row["key"], single["key"])
        # A single frame still decodes through the same entry point.
        assert unpack_infer_any(pack_infer_request(
            "b", 9, entries[0]["key"], entries[0]["obs"],
            None))[0]["req"] == 9

    def test_wave_reply_rows_match_single_wire(self):
        from relayrl_tpu.transport.serving import (
            pack_action_reply,
            pack_reply_wave,
            unpack_infer_reply,
            unpack_reply_any,
        )

        acts = np.asarray([2, 0, 1], np.int32)
        keys = np.asarray(jax.random.split(jax.random.PRNGKey(1), 3))
        aux = {"logp_a": np.asarray([-0.1, -0.2, -0.3], np.float32),
               "v": np.asarray([0.5, 0.6, 0.7], np.float32)}
        rows = unpack_reply_any(pack_reply_wave(
            [11, 12, 13], 5, acts, keys, aux, ctx=16))
        assert len(rows) == 3
        for i, row in enumerate(rows):
            single = unpack_infer_reply(pack_action_reply(
                11 + i, 5, acts[i, ...], keys[i],
                {k: v[i, ...] for k, v in aux.items()}, ctx=16))
            assert row["req"] == single["req"]
            assert row["code"] == single["code"] == 1
            assert row["ver"] == single["ver"] == 5
            assert row["ctx"] == single["ctx"] == 16
            assert row["key"] == single["key"]  # raw key bytes, verbatim
            assert np.array_equal(row["act"], single["act"])
            assert row["act"].dtype == single["act"].dtype
            assert row["act"].shape == single["act"].shape  # 0-d stays 0-d
            for k in aux:
                assert np.array_equal(row["aux"][k], single["aux"][k])
                assert row["aux"][k].dtype == single["aux"][k].dtype

    def test_malformed_request_answers_error(self, tmp_cwd, fresh_registry):
        from relayrl_tpu.runtime.inference import InferenceService

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=2, batch_timeout_ms=1.0)
        from relayrl_tpu.transport.serving import unpack_infer_reply

        got = []
        svc.handle_request(b"\x81\xa3junk", lambda b: got.append(
            unpack_infer_reply(b)))
        assert got and got[0]["code"] == 0


class TestBatchingQueue:
    def test_size_trigger_close(self, tmp_cwd, fresh_registry):
        """max_batch requests close the batch immediately (reason
        "size"), long before the deadline."""
        from relayrl_tpu.runtime.inference import InferenceService

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=4, batch_timeout_ms=5000.0)
        svc.start()
        try:
            keys = np.asarray(jax.random.split(jax.random.PRNGKey(0), 4))
            obs = np.random.default_rng(0).standard_normal(
                (4, 6)).astype(np.float32)
            # Warm the bucket-4 compile OUTSIDE the timed window (the
            # first dispatch traces + compiles; this test times the batch
            # CLOSE, not XLA).
            warm = [_submit(svc, keys[i], obs[i], req_id=100 + i)
                    for i in range(4)]
            for done, _ in warm:
                assert done.wait(60)
            t0 = time.monotonic()
            waits = [_submit(svc, keys[i], obs[i], req_id=i + 1)
                     for i in range(4)]
            for done, box in waits:
                assert done.wait(10), "size-triggered batch never closed"
                assert box["reply"]["code"] == 1
            assert time.monotonic() - t0 < 2.0, \
                "size close waited toward the deadline"
            assert svc._m_batches["size"].total() == 2
            assert svc._m_batches["deadline"].total() == 0
        finally:
            svc.stop()

    def test_deadline_trigger_close(self, tmp_cwd, fresh_registry):
        """A short batch closes batch_timeout_ms after its FIRST request
        (reason "deadline") instead of waiting for max_batch forever."""
        from relayrl_tpu.runtime.inference import InferenceService

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=64, batch_timeout_ms=40.0)
        svc.start()
        try:
            key = np.asarray(jax.random.PRNGKey(1))
            obs = np.zeros(6, np.float32)
            t0 = time.monotonic()
            done, box = _submit(svc, key, obs)
            assert done.wait(10), "deadline-triggered batch never closed"
            dt = time.monotonic() - t0
            assert box["reply"]["code"] == 1
            assert dt >= 0.030, f"closed before the deadline ({dt:.3f}s)"
            assert svc._m_batches["deadline"].total() == 1
        finally:
            svc.stop()

    def test_bucket_selection_and_padding_inert(self, tmp_cwd,
                                                fresh_registry):
        """3 requests dispatch at bucket 4 (smallest bucket >= n), and
        the padded row cannot perturb the real rows: every reply is
        bit-identical to the unpadded singles."""
        from relayrl_tpu.runtime.inference import InferenceService
        from relayrl_tpu.runtime.policy_actor import _fuse_rng

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=8, batch_timeout_ms=30.0,
                               buckets=[1, 2, 4, 8])
        shapes = []
        inner = svc._batched_fn

        def spying(params, keys, obs, masks, explore):
            shapes.append(tuple(np.asarray(keys).shape))
            return inner(params, keys, obs, masks, explore)

        svc._batched_fn = spying
        svc.start()
        try:
            keys = np.asarray(jax.random.split(jax.random.PRNGKey(3), 3))
            obs = np.random.default_rng(1).standard_normal(
                (3, 6)).astype(np.float32)
            waits = [_submit(svc, keys[i], obs[i], req_id=i + 1)
                     for i in range(3)]
            single = jax.jit(_fuse_rng(svc.policy.step))
            for i, (done, box) in enumerate(waits):
                assert done.wait(10)
                reply = box["reply"]
                assert reply["code"] == 1
                act, aux, nk = single(bundle.params, keys[i], obs[i], None)
                assert np.array_equal(reply["act"], np.asarray(act))
                for k in aux:
                    assert np.array_equal(reply["aux"][k],
                                          np.asarray(aux[k])), k
                assert np.array_equal(
                    np.frombuffer(reply["key"], np.uint32),
                    np.asarray(nk).ravel())
            assert shapes and shapes[0][0] == 4, \
                f"expected bucket-4 dispatch, saw {shapes}"
        finally:
            svc.stop()

    def test_queue_limit_overload_nack(self, tmp_cwd, fresh_registry):
        """Beyond serving.queue_limit, submissions answer the typed
        NACK_OVERLOADED with a retry-after hint instead of queueing
        unboundedly (the worker is NOT running, so nothing drains)."""
        from relayrl_tpu.runtime.inference import InferenceService
        from relayrl_tpu.transport.base import NACK_OVERLOADED

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=4, batch_timeout_ms=5.0,
                               queue_limit=2, retry_after_s=0.25)
        key = np.asarray(jax.random.PRNGKey(0))
        obs = np.zeros(6, np.float32)
        waits = [_submit(svc, key, obs, req_id=i + 1) for i in range(3)]
        done, box = waits[2]
        assert done.wait(5), "overload nack never delivered"
        assert box["reply"]["code"] == NACK_OVERLOADED
        assert box["reply"]["retry_after_s"] == pytest.approx(0.25)
        assert svc._m_rejected.total() == 1
        assert not waits[0][0].is_set() and not waits[1][0].is_set()
        # stop() answers the parked requests with a retryable nack too —
        # a restarting service must not leave clients hanging.
        svc.stop()
        for done_i, box_i in waits[:2]:
            assert done_i.wait(5)
            assert box_i["reply"]["code"] == NACK_OVERLOADED

    def test_single_params_version_per_batch_under_racing_swapper(
            self, tmp_cwd, fresh_registry):
        """A swapper thread hammers version-coded params while requests
        stream: every reply's aux['v'] must equal its reply 'ver' — no
        request is ever served params from a version other than the one
        its batch read under the gate."""
        from relayrl_tpu.runtime.inference import InferenceService

        base = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(_versioned_bundle(base, 1), max_batch=4,
                               batch_timeout_ms=2.0)
        svc.start()
        stop = threading.Event()
        next_version = [2]

        def swapper():
            while not stop.is_set():
                svc.maybe_swap(_versioned_bundle(base, next_version[0]))
                next_version[0] += 1

        t = threading.Thread(target=swapper, daemon=True)
        t.start()
        try:
            key = np.asarray(jax.random.PRNGKey(5))
            obs = np.random.default_rng(2).standard_normal(6).astype(
                np.float32)
            mismatches = []
            for i in range(40):
                done, box = _submit(svc, key, obs, req_id=i + 1)
                assert done.wait(10)
                reply = box["reply"]
                assert reply["code"] == 1
                v = float(reply["aux"]["v"])
                if v != float(reply["ver"]):
                    mismatches.append((reply["ver"], v))
                key = np.frombuffer(reply["key"], np.uint32)
            assert not mismatches, \
                f"replies served by params of another version: {mismatches[:3]}"
            assert svc.version >= 2  # swaps actually landed mid-run
        finally:
            stop.set()
            t.join(timeout=5)
            svc.stop()

    def test_stale_requests_nacked_unserved(self, tmp_cwd,
                                            fresh_registry):
        """Ghost-work guard: requests that outlive serving.stale_after_s
        in the queue (their client timed out and retried) are answered
        with a retryable nack at batch-gather time, never dispatched —
        under backlog a retry round must not double-serve."""
        from relayrl_tpu.runtime.inference import InferenceService
        from relayrl_tpu.transport.base import NACK_OVERLOADED

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=4, batch_timeout_ms=5.0,
                               stale_after_s=0.2)
        key = np.asarray(jax.random.PRNGKey(0))
        obs = np.zeros(6, np.float32)
        # Enqueue while the worker is NOT running, let them go stale,
        # then start the worker: the gather pass must nack both without
        # serving them.
        waits = [_submit(svc, key, obs, req_id=i + 1) for i in range(2)]
        time.sleep(0.4)
        svc.start()
        try:
            for done, box in waits:
                assert done.wait(10), "stale request never answered"
                assert box["reply"]["code"] == NACK_OVERLOADED
                assert "stale" in box["reply"]["error"]
            assert svc._m_stale.total() == 2
            assert (svc._m_batches["size"].total()
                    + svc._m_batches["deadline"].total()) == 0
            # fresh traffic still serves normally afterwards
            done, box = _submit(svc, key, obs, req_id=9)
            assert done.wait(30) and box["reply"]["code"] == 1
        finally:
            svc.stop()

    def test_sequence_requests_without_session_id_get_pointed_error(
            self, tmp_cwd, fresh_registry):
        """A v1 (session-less) request against a sequence policy answers
        with an error naming serving.max_sessions — the serving-v2
        replacement for the old constructor refusal."""
        from relayrl_tpu.runtime.inference import InferenceService

        svc = InferenceService(_transformer_bundle(), max_batch=1,
                               batch_timeout_ms=1.0)
        svc.start()
        try:
            key = np.asarray(jax.random.PRNGKey(0))
            obs = np.zeros(5, np.float32)
            done, box = _submit(svc, key, obs)
            assert done.wait(30)
            assert box["reply"]["code"] == 0
            assert "serving.max_sessions" in box["reply"]["error"]
        finally:
            svc.stop()

    def test_install_params_owns_memory(self, tmp_cwd, fresh_registry):
        """The colocated publish feed must copy: mutating the publisher's
        host tree after install must not change served params."""
        from relayrl_tpu.runtime.inference import InferenceService

        base = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(_versioned_bundle(base, 1), max_batch=1,
                               batch_timeout_ms=1.0)
        svc.start()
        try:
            host_tree = jax.tree_util.tree_map(
                np.array, _versioned_bundle(base, 2).params)
            assert svc.install_params(2, base.arch, host_tree)
            host_tree["params"]["vf_head"]["bias"][:] = 777.0
            key = np.asarray(jax.random.PRNGKey(0))
            done, box = _submit(svc, key, np.zeros(6, np.float32))
            assert done.wait(10)
            assert float(box["reply"]["aux"]["v"]) == 2.0
        finally:
            svc.stop()


class _FakeServingClient:
    """Scripted reply stream for the thin client's retry loop."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = 0

    def request(self, payload, req_id, timeout_s):
        self.calls += 1
        step = self.script.pop(0) if self.script else self.script_default
        if isinstance(step, Exception):
            raise step
        out = dict(step)
        out.setdefault("req", req_id)
        return out

    def close(self):
        pass


def _bare_client(fake, infer_deadline_s=5.0, request_timeout_s=0.2):
    """A RemoteActorClient wired straight to a fake serving channel —
    the retry/breaker/nack loop under test, no sockets."""
    from relayrl_tpu import telemetry
    from relayrl_tpu.runtime.inference import RemoteActorClient
    from relayrl_tpu.transport.retry import CircuitBreaker, RetryPolicy

    client = object.__new__(RemoteActorClient)
    client._serving = fake
    client._breaker = CircuitBreaker("test", failure_threshold=3,
                                     reset_timeout_s=0.2)
    client._retry = RetryPolicy(base_delay_s=0.01, max_delay_s=0.05)
    client._fault_infer = None
    client._rng = np.asarray(jax.random.PRNGKey(0))
    client._req_counter = 0
    client._request_timeout_s = request_timeout_s
    client._infer_deadline_s = infer_deadline_s
    client.version = -1
    client._session_id = "bare"
    client._session_step = 0
    client._episode_start = True
    client._mirror = []
    client._replica_addrs = None
    client._replica_idx = 0
    client._replica_fail_streak = 0
    client._serving_overrides = {}

    class _T:
        identity = "bare"

    client.transport = _T()
    reg = telemetry.get_registry()
    client._m_request_s = reg.histogram("relayrl_serving_client_request_seconds", "t")
    client._m_retries = reg.counter("relayrl_serving_client_retries_total", "t")
    client._m_nacked = reg.counter("relayrl_serving_client_nacked_total", "t")
    client._m_resyncs = reg.counter("relayrl_serving_client_resyncs_total", "t")
    client._m_reroutes = reg.counter("relayrl_serving_client_reroutes_total", "t")
    return client


def _ok_reply(act=1, ver=3):
    key = np.array([9, 9], np.uint32)
    return {"code": 1, "ver": ver, "act": np.asarray(np.int32(act)),
            "key": key.tobytes(), "aux": {"v": np.asarray(np.float32(0.5))}}


class TestClientRetry:
    def test_overload_nack_honors_retry_after_without_breaker_charge(
            self, fresh_registry):
        from relayrl_tpu.transport.base import NACK_OVERLOADED

        fake = _FakeServingClient([
            {"code": NACK_OVERLOADED, "error": "full",
             "retry_after_s": 0.15},
            _ok_reply(),
        ])
        client = _bare_client(fake)
        t0 = time.monotonic()
        act, aux = client._infer(np.zeros(4, np.float32), None)
        dt = time.monotonic() - t0
        assert int(act) == 1 and client.version == 3
        assert dt >= 0.14, f"retry-after not honored ({dt:.3f}s)"
        assert fake.calls == 2
        assert client._breaker.state == "closed"
        assert client._m_nacked.total() == 1
        assert client._m_retries.total() == 0  # nacks are not failures

    def test_timeouts_charge_breaker_then_heal(self, fresh_registry):
        fake = _FakeServingClient([
            TimeoutError("t"), TimeoutError("t"), TimeoutError("t"),
            _ok_reply(ver=7),
        ])
        client = _bare_client(fake)
        act, aux = client._infer(np.zeros(4, np.float32), None)
        assert int(act) == 1 and client.version == 7
        # 3 failures opened the breaker (threshold 3); the half-open
        # probe then healed it — the env loop waited, never wedged.
        assert client._m_retries.total() == 3
        assert client._breaker.state == "closed"

    def test_deadline_exhaustion_raises(self, fresh_registry):
        fake = _FakeServingClient([])
        fake.script_default = None

        class _AlwaysTimeout(_FakeServingClient):
            def request(self, payload, req_id, timeout_s):
                self.calls += 1
                raise TimeoutError("dead service")

        client = _bare_client(_AlwaysTimeout([]), infer_deadline_s=0.6)
        with pytest.raises(RuntimeError, match="budget"):
            client._infer(np.zeros(4, np.float32), None)

    def test_error_reply_retries(self, fresh_registry):
        """A code-0 error (corrupt request drill: the service's decode
        guard answered) is retryable, not fatal."""
        fake = _FakeServingClient([
            {"code": 0, "error": "malformed inference request"},
            _ok_reply(ver=4),
        ])
        client = _bare_client(fake)
        act, _ = client._infer(np.zeros(4, np.float32), None)
        assert int(act) == 1 and client.version == 4
        assert fake.calls == 2


def _serving_stack(tmp_path, server_type="zmq", max_batch=4,
                   batch_timeout_ms=3.0, traj_per_epoch=64,
                   spool_entries=512):
    """One TrainingServer with serving enabled + its address block, on a
    fresh set of ports."""
    from relayrl_tpu.runtime.server import TrainingServer

    scratch = str(tmp_path)
    cfg_path = os.path.join(scratch, "serving_cfg.json")
    with open(cfg_path, "w") as f:
        json.dump({"serving": {"enabled": True, "max_batch": max_batch,
                               "batch_timeout_ms": batch_timeout_ms},
                   "actor": {"spool_entries": spool_entries}}, f)
    if server_type == "grpc":
        addrs = {"bind_addr": f"127.0.0.1:{free_port()}",
                 "native_grpc": False}
        client_addrs = {"server_addr": addrs["bind_addr"], "probe": False}
    else:
        addrs = {
            "agent_listener_addr": f"tcp://127.0.0.1:{free_port()}",
            "trajectory_addr": f"tcp://127.0.0.1:{free_port()}",
            "model_pub_addr": f"tcp://127.0.0.1:{free_port()}",
            "serving_addr": f"tcp://127.0.0.1:{free_port()}",
        }
        client_addrs = {
            "agent_listener_addr": addrs["agent_listener_addr"],
            "trajectory_addr": addrs["trajectory_addr"],
            "model_sub_addr": addrs["model_pub_addr"],
            "serving_addr": addrs["serving_addr"],
            "probe": False,
        }
    server = TrainingServer(
        "REINFORCE", obs_dim=6, act_dim=3, env_dir=scratch,
        config_path=cfg_path, server_type=server_type,
        hyperparams={"traj_per_epoch": traj_per_epoch,
                     "hidden_sizes": [16], "with_vf_baseline": True},
        **addrs)
    return server, cfg_path, client_addrs


class TestServedParityE2E:
    @pytest.mark.parametrize("server_type", ["zmq", "grpc"])
    def test_bit_identical_served_vs_local(self, tmp_cwd, fresh_registry,
                                           server_type):
        """The acceptance lock: at a pinned params version, a thin
        client's action stream (and its shipped episode BYTES) are
        identical to a local PolicyActor with the same seed holding the
        same bundle — on the zmq ROUTER plane and the grpc GetActions
        RPC."""
        from relayrl_tpu.runtime.inference import RemoteActorClient
        from relayrl_tpu.runtime.policy_actor import PolicyActor
        from relayrl_tpu.types.model_bundle import ModelBundle

        server, cfg_path, client_addrs = _serving_stack(
            tmp_cwd, server_type=server_type, traj_per_epoch=10_000)
        try:
            bundle = ModelBundle(
                version=server.algorithm.version,
                arch=dict(server.algorithm.bundle().arch),
                params=server.algorithm.bundle().params)
            sent_local, sent_remote = [], []
            local = PolicyActor(bundle, seed=23,
                                on_send=lambda p: sent_local.append(p))
            client = RemoteActorClient(
                config_path=cfg_path, server_type=server_type, seed=23,
                **client_addrs)
            client.trajectory._on_send = lambda p: sent_remote.append(p)
            rng = np.random.default_rng(11)
            for i in range(10):
                obs = rng.standard_normal(6).astype(np.float32)
                reward = 0.0 if i == 0 else 0.5
                r1 = local.request_for_action(obs, reward=reward)
                r2 = client.request_for_action(obs, reward=reward)
                assert np.array_equal(np.asarray(r1.act),
                                      np.asarray(r2.act)), f"step {i}"
                assert r1.act.dtype == r2.act.dtype
                assert r1.act.shape == r2.act.shape
                for k in r1.data:
                    assert np.array_equal(np.asarray(r1.data[k]),
                                          np.asarray(r2.data[k])), (i, k)
                    assert r1.data[k].dtype == r2.data[k].dtype, (i, k)
            local.flag_last_action(1.0, terminated=True)
            client.flag_last_action(1.0, terminated=True)
            assert sent_local == sent_remote and len(sent_local) == 1, \
                "served episode bytes differ from the local actor's"
            client.disable_agent()
        finally:
            server.disable_server()

    def test_masked_served_parity(self, tmp_cwd, fresh_registry):
        from relayrl_tpu.runtime.inference import RemoteActorClient
        from relayrl_tpu.runtime.policy_actor import PolicyActor
        from relayrl_tpu.types.model_bundle import ModelBundle

        server, cfg_path, client_addrs = _serving_stack(
            tmp_cwd, traj_per_epoch=10_000)
        try:
            bundle = ModelBundle(
                version=server.algorithm.version,
                arch=dict(server.algorithm.bundle().arch),
                params=server.algorithm.bundle().params)
            local = PolicyActor(bundle, seed=4)
            client = RemoteActorClient(config_path=cfg_path, seed=4,
                                       **client_addrs)
            mask = np.array([1.0, 0.0, 1.0], np.float32)
            rng = np.random.default_rng(3)
            for _ in range(5):
                obs = rng.standard_normal(6).astype(np.float32)
                r1 = local.request_for_action(obs, mask=mask)
                r2 = client.request_for_action(obs, mask=mask)
                assert np.array_equal(np.asarray(r1.act),
                                      np.asarray(r2.act))
                assert int(np.asarray(r2.act)) != 1  # mask respected
            client.disable_agent()
        finally:
            server.disable_server()

    def test_trajectories_train_and_model_version_advances(
            self, tmp_cwd, fresh_registry):
        """The full loop: thin-client episodes reach the learner through
        the UNCHANGED trajectory plane, updates publish, and the
        colocated service starts serving the new version (visible as the
        client's model_version advancing) — with batching provably
        active (occupancy histogram saw > 1)."""
        from relayrl_tpu.runtime.inference import RemoteActorClient

        server, cfg_path, client_addrs = _serving_stack(
            tmp_cwd, traj_per_epoch=2, max_batch=4, batch_timeout_ms=4.0)
        try:
            clients = [RemoteActorClient(config_path=cfg_path, seed=s,
                                         identity=f"thin-{s}",
                                         **client_addrs)
                       for s in range(3)]
            stop = threading.Event()

            def drive(client, seed):
                rng = np.random.default_rng(seed)
                while not stop.is_set():
                    obs = rng.standard_normal(6).astype(np.float32)
                    for _ in range(8):
                        client.request_for_action(obs, reward=1.0)
                        obs = rng.standard_normal(6).astype(np.float32)
                        if stop.is_set():
                            break
                    client.flag_last_action(1.0, terminated=True)

            threads = [threading.Thread(target=drive, args=(c, i),
                                        daemon=True)
                       for i, c in enumerate(clients)]
            for t in threads:
                t.start()
            deadline = time.monotonic() + 60
            while (time.monotonic() < deadline
                   and (server.stats["updates"] < 2
                        or max(c.model_version for c in clients) < 2)):
                time.sleep(0.1)
            stop.set()
            for t in threads:
                t.join(timeout=30)
            assert server.stats["updates"] >= 2, "thin-client episodes never trained"
            assert max(c.model_version for c in clients) >= 2, \
                "the colocated service never served the published version"
            occ = server.inference._m_occupancy.totals()
            counts, total, n = occ
            assert n > 0 and total / n > 1.0, \
                f"batching never engaged (mean occupancy {total}/{n})"
            for c in clients:
                c.disable_agent()
        finally:
            server.disable_server()


class TestFaultPlaneAndHeal:
    # Wall re-fit: both single-service heal drills ride the slow tier —
    # the fast tier's serving-heal representative is now the replica
    # SIGKILL re-route drill in TestStreamingChannel (kills a live host,
    # heals through re-route + session resync).
    @pytest.mark.slow
    def test_agent_infer_fault_site_drop_and_corrupt_heal(
            self, tmp_cwd, fresh_registry):
        """agent.infer chaos: deterministic drops + corruption on the
        request plane — every action still lands (drop → timeout retry,
        corrupt → service decode-guard error reply → retry), and the
        injection ledger counted the faults."""
        from relayrl_tpu import faults
        from relayrl_tpu.faults import FaultPlan
        from relayrl_tpu.runtime.inference import (
            InferenceService,
            RemoteActorClient,
        )

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=2, batch_timeout_ms=2.0)
        addr = f"tcp://127.0.0.1:{free_port()}"
        svc.bind_zmq(addr)
        svc.start()
        plan = FaultPlan.from_dict({"seed": 3, "rules": [
            {"site": "agent.infer", "op": "drop", "prob": 0.2},
            {"site": "agent.infer", "op": "corrupt", "prob": 0.2},
        ]})
        faults.install_plan(plan)
        try:
            cfg_path = os.path.join(str(tmp_cwd), "cfg.json")
            with open(cfg_path, "w") as f:
                json.dump({"actor": {"spool_entries": 0},
                           "serving": {"request_timeout_s": 0.3}}, f)
            client = RemoteActorClient(
                config_path=cfg_path, seed=1, serving_addr=addr,
                probe=False,
                agent_listener_addr=f"tcp://127.0.0.1:{free_port()}",
                trajectory_addr=f"tcp://127.0.0.1:{free_port()}",
                model_sub_addr=f"tcp://127.0.0.1:{free_port()}")
            rng = np.random.default_rng(0)
            for _ in range(30):
                client.request_for_action(
                    rng.standard_normal(6).astype(np.float32), reward=1.0)
            site = plan.site("agent.infer")
            assert site is not None and site.injected > 0, \
                "the drill injected nothing"
            client.disable_agent()
        finally:
            faults.install_plan(None)
            svc.stop()

    @pytest.mark.slow
    def test_killed_service_heals_clients_without_wedging(
            self, tmp_cwd, fresh_registry):
        """The chaos drill: the inference service dies mid-run and
        restarts; a stepping client rides the breaker/backoff through
        the outage and completes every action — the env loop never
        wedges and never loses a step."""
        from relayrl_tpu.runtime.inference import (
            InferenceService,
            RemoteActorClient,
        )

        bundle = _reinforce_bundle(str(tmp_cwd))
        addr = f"tcp://127.0.0.1:{free_port()}"
        svc = InferenceService(bundle, max_batch=2, batch_timeout_ms=2.0)
        svc.bind_zmq(addr)
        svc.start()
        cfg_path = os.path.join(str(tmp_cwd), "cfg.json")
        with open(cfg_path, "w") as f:
            json.dump({"actor": {"spool_entries": 0},
                       "serving": {"request_timeout_s": 0.25,
                                   "infer_deadline_s": 60.0}}, f)
        client = RemoteActorClient(
            config_path=cfg_path, seed=2, serving_addr=addr, probe=False,
            agent_listener_addr=f"tcp://127.0.0.1:{free_port()}",
            trajectory_addr=f"tcp://127.0.0.1:{free_port()}",
            model_sub_addr=f"tcp://127.0.0.1:{free_port()}")
        steps = []
        stop_at = 60

        def loop():
            rng = np.random.default_rng(1)
            for _ in range(stop_at):
                steps.append(client.request_for_action(
                    rng.standard_normal(6).astype(np.float32),
                    reward=1.0))

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        # Let it step, kill the service, hold a real outage, restart.
        deadline = time.monotonic() + 20
        while len(steps) < 5 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(steps) >= 5
        svc.stop()
        time.sleep(1.0)
        svc2 = InferenceService(bundle, max_batch=2, batch_timeout_ms=2.0)
        svc2.bind_zmq(addr)
        svc2.start()
        t.join(timeout=90)
        try:
            assert not t.is_alive(), "env loop wedged through the outage"
            assert len(steps) == stop_at, \
                f"actions lost across the outage ({len(steps)}/{stop_at})"
        finally:
            client.disable_agent()
            svc2.stop()


class TestServingDisabledFailsFast:
    def test_grpc_without_serving_raises_pointed_error(self, tmp_cwd,
                                                       fresh_registry):
        """A grpc fleet whose server has serving.enabled false answers
        GetActions with the PERMANENT NACK_UNAVAILABLE — the thin client
        must fail fast with the pointed message, not retry a
        misconfiguration into a 60s deadline exhaustion."""
        from relayrl_tpu.runtime.inference import RemoteActorClient
        from relayrl_tpu.runtime.server import TrainingServer

        bind_addr = f"127.0.0.1:{free_port()}"
        server = TrainingServer(
            "REINFORCE", obs_dim=6, act_dim=3, env_dir=str(tmp_cwd),
            server_type="grpc", native_grpc=False, bind_addr=bind_addr,
            hyperparams={"traj_per_epoch": 64, "hidden_sizes": [16]})
        try:
            assert server.inference is None  # serving defaults off
            client = RemoteActorClient(
                server_type="grpc", seed=1, probe=False,
                server_addr=bind_addr)
            t0 = time.monotonic()
            with pytest.raises(RuntimeError,
                               match="serving is not enabled"):
                client.request_for_action(np.zeros(6, np.float32))
            assert time.monotonic() - t0 < 10, \
                "fail-fast path retried toward the deadline"
            client.disable_agent()
        finally:
            server.disable_server()


class TestAsyncEmitLifecycle:
    def test_close_then_restart_emitter(self, tmp_cwd, fresh_registry):
        """The emitter thread is restartable: close() (the
        disable_agent path) then start_emitter() (the enable path) must
        leave a working host — NOT a depth-2 hand-off deadlock on the
        third window — and close() must not leak the thread."""
        import jax as _jax

        from relayrl_tpu.models import build_policy
        from relayrl_tpu.runtime.anakin import AnakinActorHost
        from relayrl_tpu.types.model_bundle import ModelBundle

        arch = {"kind": "mlp_discrete", "obs_dim": 4, "act_dim": 2,
                "hidden_sizes": [16]}
        policy = build_policy(arch)
        bundle = ModelBundle(
            version=0, arch=arch,
            params=policy.init_params(_jax.random.PRNGKey(0)))
        sink = []
        host = AnakinActorHost(bundle, "CartPole-v1", num_envs=2,
                               unroll_length=8, async_emit=True,
                               on_send=lambda lane, p: sink.append(p),
                               seed=0)
        host.rollout()
        assert host.flush_emits()
        n_before = len(sink)
        assert n_before >= 0
        host.close()
        assert host._emit_thread is None
        host.start_emitter()
        for _ in range(4):  # past the depth-2 hand-off: would deadlock
            host.rollout()  # if the emitter were still stopped
        assert host.flush_emits()
        assert len(sink) > n_before
        host.close()


class TestConfig:
    def test_serving_params_defaults_and_clamps(self, tmp_cwd):
        from relayrl_tpu.config import ConfigLoader

        cfg_path = tmp_cwd / "cfg.json"
        cfg_path.write_text(json.dumps({"serving": {
            "enabled": True, "max_batch": "bogus",
            "batch_timeout_ms": -5, "buckets": [8, 2, "x"],
            "queue_limit": 0}}))
        p = ConfigLoader(None, str(cfg_path)).get_serving_params()
        assert p["enabled"] is True
        assert p["max_batch"] == 16          # malformed → default
        assert p["batch_timeout_ms"] == 0.0  # negative clamps to 0
        assert p["buckets"] is None          # malformed list → derived
        assert p["queue_limit"] == 1         # floor 1
        # serving-v2 knob defaults ride along untouched
        assert p["max_sessions"] == 4096
        assert p["session_ttl_s"] == 600.0
        assert p["stream_window"] == 32
        assert p["replicas"] is None

    def test_serving_v2_params_clamped(self, tmp_cwd):
        from relayrl_tpu.config import ConfigLoader

        cfg_path = tmp_cwd / "cfg.json"
        cfg_path.write_text(json.dumps({"serving": {
            "max_sessions": 0, "session_ttl_s": -3,
            "stream_window": "bogus",
            "replicas": ["tcp://a:1", "tcp://b:2"]}}))
        p = ConfigLoader(None, str(cfg_path)).get_serving_params()
        assert p["max_sessions"] == 1        # floor 1
        assert p["session_ttl_s"] == 0.0     # negative clamps to 0 (off)
        assert p["stream_window"] == 32      # malformed → default
        assert p["replicas"] == ["tcp://a:1", "tcp://b:2"]
        cfg_path.write_text(json.dumps({"serving": {"replicas": []}}))
        p = ConfigLoader(None, str(cfg_path)).get_serving_params()
        assert p["replicas"] is None         # empty list → single endpoint

    def test_bucket_list_covers_max_batch(self, tmp_cwd):
        from relayrl_tpu.config import ConfigLoader

        cfg_path = tmp_cwd / "cfg.json"
        cfg_path.write_text(json.dumps({"serving": {
            "max_batch": 32, "buckets": [2, 8]}}))
        p = ConfigLoader(None, str(cfg_path)).get_serving_params()
        assert p["buckets"] == [2, 8, 32]

    def test_default_buckets_powers_of_two(self):
        from relayrl_tpu.runtime.inference import default_buckets

        assert default_buckets(16) == [1, 2, 4, 8, 16]
        assert default_buckets(24) == [1, 2, 4, 8, 16, 24]
        assert default_buckets(1) == [1]

    def test_constructor_buckets_clamped_to_max_batch(self, tmp_cwd,
                                                      fresh_registry):
        """Direct construction with buckets smaller than max_batch must
        get the same cover-clamp the ConfigLoader applies — otherwise a
        size-closed full batch would pick a bucket BELOW its size and
        every full batch would fail the pad computation forever."""
        from relayrl_tpu.runtime.inference import InferenceService

        bundle = _reinforce_bundle(str(tmp_cwd))
        svc = InferenceService(bundle, max_batch=16, buckets=[4, 8])
        assert svc.buckets[-1] == 16

    def test_remote_host_mode_accepted(self, tmp_cwd):
        from relayrl_tpu.config import ConfigLoader

        cfg_path = tmp_cwd / "cfg.json"
        cfg_path.write_text(json.dumps({"actor": {"host_mode": "remote"}}))
        p = ConfigLoader(None, str(cfg_path)).get_actor_params()
        assert p["host_mode"] == "remote"


class TestServingSessions:
    """Serving v2: the server-side session table (sequence policies)."""

    def _svc(self, **kw):
        from relayrl_tpu.runtime.inference import InferenceService

        svc = InferenceService(_transformer_bundle(),
                               max_batch=kw.pop("max_batch", 1),
                               batch_timeout_ms=1.0, **kw)
        svc.start()
        return svc

    def test_sequence_parity_across_episodes(self, tmp_cwd,
                                             fresh_registry):
        """The acceptance lock extended to sequence policies: a session
        client's served action stream is bit-identical to a local
        windowed PolicyActor at the same seed — across an episode
        boundary (the server-side window must zero exactly where the
        local one does)."""
        from relayrl_tpu.runtime.policy_actor import PolicyActor

        svc = self._svc()
        try:
            local = PolicyActor(_transformer_bundle(), seed=7,
                                use_kv_cache=False)
            drv = _SessionDriver(svc, "par", seed=7)
            rng = np.random.default_rng(2)
            for episode in range(2):
                for _ in range(5):
                    obs = rng.standard_normal(5).astype(np.float32)
                    r1 = local.request_for_action(obs)
                    r2 = drv.act(obs)
                    assert np.array_equal(np.asarray(r1.act), r2["act"])
                    for k in r1.data:
                        assert np.array_equal(np.asarray(r1.data[k]),
                                              r2["aux"][k]), (episode, k)
                local.flag_last_action(1.0, terminated=True)
                drv.end_episode()
        finally:
            svc.stop()

    def test_idempotent_push_retry(self, tmp_cwd, fresh_registry):
        """An at-least-once redelivery (same ``stp``) recomputes from
        the current window WITHOUT re-pushing: identical action bytes,
        and the next step still sees a single push."""
        from relayrl_tpu.runtime.policy_actor import PolicyActor

        svc = self._svc()
        try:
            local = PolicyActor(_transformer_bundle(), seed=3,
                                use_kv_cache=False)
            drv = _SessionDriver(svc, "retry", seed=3)
            rng = np.random.default_rng(5)
            obs = rng.standard_normal(5).astype(np.float32)
            pre_key = drv.key.copy()
            pre_reset = drv.episode_start
            first = drv.act(obs)
            # Retry the ORIGINAL payload (client timed out, the reply
            # was lost): same cursor, same key, same reset flag.
            drv.episode_start = pre_reset
            replay = drv.raw(obs, step=drv.step, key=pre_key)
            drv.episode_start = False
            assert replay["code"] == 1
            assert np.array_equal(first["act"], replay["act"])
            assert replay["key"] == first["key"]
            local.request_for_action(obs)
            obs2 = rng.standard_normal(5).astype(np.float32)
            r1 = local.request_for_action(obs2)
            r2 = drv.act(obs2)
            assert np.array_equal(np.asarray(r1.act), r2["act"]), \
                "double-push corrupted the session window"
        finally:
            svc.stop()

    def test_out_of_step_cursor_nacks(self, tmp_cwd, fresh_registry):
        from relayrl_tpu.transport.base import NACK_SESSION_EVICTED

        svc = self._svc()
        try:
            drv = _SessionDriver(svc, "skew", seed=1)
            drv.act(np.zeros(5, np.float32))
            reply = drv.raw(np.ones(5, np.float32), step=drv.step + 5)
            assert reply["code"] == NACK_SESSION_EVICTED
            assert svc._m_session_nacked.total() >= 1
        finally:
            svc.stop()

    def test_lru_eviction_resync_bit_identical_continuation(
            self, tmp_cwd, fresh_registry):
        """max_sessions pressure evicts the LRU session; its client's
        next request draws NACK_SESSION_EVICTED, resends its episode
        window, and the CONTINUATION is bit-identical to an
        uninterrupted local actor — eviction is a resync, never a lost
        episode."""
        from relayrl_tpu.runtime.policy_actor import PolicyActor
        from relayrl_tpu.transport.base import NACK_SESSION_EVICTED

        svc = self._svc(max_sessions=2)
        try:
            local = PolicyActor(_transformer_bundle(), seed=11,
                                use_kv_cache=False)
            victim = _SessionDriver(svc, "victim", seed=11)
            rng = np.random.default_rng(8)
            seq = [rng.standard_normal(5).astype(np.float32)
                   for _ in range(6)]
            for obs in seq[:3]:
                r1 = local.request_for_action(obs)
                r2 = victim.act(obs)
                assert np.array_equal(np.asarray(r1.act), r2["act"])
            # Two fresher sessions push "victim" off the 2-entry table.
            for name in ("fresh-a", "fresh-b"):
                _SessionDriver(svc, name, seed=1).act(
                    np.zeros(5, np.float32))
            assert svc._m_evictions["lru"].total() >= 1
            # Mid-episode request now draws the typed resync nack...
            nack = victim.raw(seq[3])
            assert nack["code"] == NACK_SESSION_EVICTED
            # ...and the protocol-following client continues losslessly.
            for obs in seq[3:]:
                r1 = local.request_for_action(obs)
                r2 = victim.act(obs)
                assert np.array_equal(np.asarray(r1.act), r2["act"]), \
                    "post-resync continuation diverged from local"
            assert svc._m_resyncs.total() >= 1
            assert svc.accounting()["sessions"] <= 2
        finally:
            svc.stop()

    def test_ttl_expiry_reaps_idle_sessions(self, tmp_cwd,
                                            fresh_registry):
        svc = self._svc(session_ttl_s=0.05)
        try:
            idle = _SessionDriver(svc, "idle", seed=2)
            idle.act(np.zeros(5, np.float32))
            time.sleep(0.15)
            # Any later batch sweeps the expired session out.
            _SessionDriver(svc, "busy", seed=4).act(
                np.ones(5, np.float32))
            assert svc._m_evictions["ttl"].total() >= 1
            assert "idle" not in svc._sessions
        finally:
            svc.stop()


class TestStreamingChannel:
    """Serving v2: the pipelined request channel and the multiplexed
    client."""

    def test_streamed_out_of_order_matches_lockstep(self, tmp_cwd,
                                                    fresh_registry):
        """N pipelined submits collected in REVERSE order decode to
        byte-for-byte the replies the lock-step client gets for the same
        payloads — out-of-order delivery is a scheduling change, not a
        numerics change."""
        from relayrl_tpu.runtime.inference import InferenceService
        from relayrl_tpu.transport.serving import (
            ZmqServingClient,
            ZmqStreamingClient,
            pack_infer_request,
        )

        svc = InferenceService(_reinforce_bundle(str(tmp_cwd)),
                               max_batch=4, batch_timeout_ms=2.0)
        addr = f"tcp://127.0.0.1:{free_port()}"
        svc.bind_zmq(addr)
        svc.start()
        stream = ZmqStreamingClient(addr)
        serial = ZmqServingClient(addr)
        try:
            rng = np.random.default_rng(6)
            payloads = []
            for i in range(8):
                key = np.asarray(jax.random.PRNGKey(100 + i))
                obs = rng.standard_normal(6).astype(np.float32)
                payloads.append(pack_infer_request(f"s{i}", i + 1, key,
                                                   obs, None))
            waiters = [stream.submit(p, i + 1)
                       for i, p in enumerate(payloads)]
            assert stream.inflight_high_water >= 2, \
                "pipelined submits never overlapped"
            streamed = [stream.wait(w, 30) for w in reversed(waiters)]
            streamed.reverse()
            lockstep = [serial.request(p, i + 1, 30)
                        for i, p in enumerate(payloads)]
            for i, (a, b) in enumerate(zip(streamed, lockstep)):
                assert a["code"] == b["code"] == 1
                assert np.array_equal(a["act"], b["act"]), i
                assert a["key"] == b["key"], i
                for k in a["aux"]:
                    assert np.array_equal(a["aux"][k], b["aux"][k]), (i, k)
        finally:
            stream.close()
            serial.close()
            svc.stop()

    def test_multiplexed_client_matches_local_actors(self, tmp_cwd,
                                                     fresh_registry):
        """One MultiplexedRemoteClient process driving 4 env lanes over
        the live zmq stream channel produces, per lane, the exact action
        stream of a local PolicyActor(seed=seed+lane) — and its episode
        bytes ship through the standard trajectory plane unchanged."""
        from relayrl_tpu.runtime.inference import MultiplexedRemoteClient
        from relayrl_tpu.runtime.policy_actor import PolicyActor
        from relayrl_tpu.types.model_bundle import ModelBundle

        server, cfg_path, client_addrs = _serving_stack(
            tmp_cwd, traj_per_epoch=10_000)
        mux = None
        try:
            bundle = ModelBundle(
                version=server.algorithm.version,
                arch=dict(server.algorithm.bundle().arch),
                params=server.algorithm.bundle().params)
            lanes = 4
            sent_local = [[] for _ in range(lanes)]
            sent_mux = [[] for _ in range(lanes)]
            locals_ = [PolicyActor(bundle, seed=40 + i,
                                   on_send=sent_local[i].append)
                       for i in range(lanes)]
            mux = MultiplexedRemoteClient(config_path=cfg_path,
                                          lanes=lanes, seed=40,
                                          **client_addrs)
            for i in range(lanes):
                mux.trajectories[i]._on_send = sent_mux[i].append
            rng = np.random.default_rng(17)
            for step in range(6):
                obs = [rng.standard_normal(6).astype(np.float32)
                       for _ in range(lanes)]
                rewards = None if step == 0 else [0.5] * lanes
                recs = mux.request_for_actions(obs, rewards=rewards)
                for i in range(lanes):
                    r1 = locals_[i].request_for_action(
                        obs[i], reward=0.0 if step == 0 else 0.5)
                    assert np.array_equal(np.asarray(r1.act),
                                          np.asarray(recs[i].act)), \
                        (step, i)
                    for k in r1.data:
                        assert np.array_equal(
                            np.asarray(r1.data[k]),
                            np.asarray(recs[i].data[k])), (step, i, k)
            assert mux.inflight_high_water >= 2, \
                "multiplexed lanes never overlapped in flight"
            for i in range(lanes):
                locals_[i].flag_last_action(1.0, terminated=True)
                mux.flag_last_action(i, 1.0, terminated=True)
                assert sent_local[i] == sent_mux[i], \
                    f"lane {i} episode bytes differ from local"
        finally:
            if mux is not None:
                mux.disable_agent()
            server.disable_server()

    def test_replica_reroute_and_session_resync_after_kill(
            self, tmp_cwd, fresh_registry):
        """Two sequence-policy replicas; the client's session-affine home
        replica dies mid-episode. The client rotates to the survivor,
        answers its SESSION_EVICTED nack with the episode window, and the
        action stream continues bit-identical to an uninterrupted local
        windowed actor — replica death costs a resync round-trip, never
        an episode."""
        from relayrl_tpu.runtime.inference import (
            InferenceService,
            RemoteActorClient,
        )
        from relayrl_tpu.runtime.policy_actor import PolicyActor

        server, _, client_addrs = _serving_stack(tmp_cwd)
        cfg_path = os.path.join(str(tmp_cwd), "replica_cfg.json")
        with open(cfg_path, "w") as f:
            json.dump({"serving": {"enabled": True,
                                   "request_timeout_s": 0.25,
                                   "infer_deadline_s": 30.0},
                       "actor": {"spool_entries": 64}}, f)
        addrs = [f"tcp://127.0.0.1:{free_port()}" for _ in range(2)]
        replicas = []
        for addr in addrs:
            svc = InferenceService(_transformer_bundle(), max_batch=4,
                                   batch_timeout_ms=2.0)
            svc.bind_zmq(addr)
            svc.start()
            replicas.append(svc)
        client_addrs = {k: v for k, v in client_addrs.items()
                        if k != "serving_addr"}
        client = None
        try:
            local = PolicyActor(_transformer_bundle(), seed=13,
                                use_kv_cache=False)
            client = RemoteActorClient(
                config_path=cfg_path, seed=13, identity="drill-thin",
                serving_addrs=addrs, **client_addrs)
            rng = np.random.default_rng(21)
            seq = [rng.standard_normal(5).astype(np.float32)
                   for _ in range(6)]
            for obs in seq[:3]:
                r1 = local.request_for_action(obs)
                r2 = client.request_for_action(obs)
                assert np.array_equal(np.asarray(r1.act),
                                      np.asarray(r2.act))
            home = client._replica_idx
            replicas[home].stop()  # the drill: home replica dies
            for obs in seq[3:]:
                r1 = local.request_for_action(obs)
                r2 = client.request_for_action(obs)
                assert np.array_equal(np.asarray(r1.act),
                                      np.asarray(r2.act)), \
                    "post-re-route continuation diverged from local"
            assert client._replica_idx != home
            assert client._m_reroutes.total() >= 1
            assert client._m_resyncs.total() >= 1
        finally:
            if client is not None:
                client.disable_agent()
            for svc in replicas:
                svc.stop()
            server.disable_server()
