"""Multi-host init wrapper + profiling hooks."""

import os

import jax
import jax.numpy as jnp
import pytest

from relayrl_tpu.parallel import initialize_distributed, is_coordinator
from relayrl_tpu.utils import annotate, timed, trace


@pytest.fixture(autouse=True)
def _reset_topology_cache():
    """initialize_distributed caches its first resolution per process;
    tests need a fresh slate."""
    import relayrl_tpu.parallel.distributed as dist

    dist._info = None
    yield
    dist._info = None


class TestInitializeDistributed:
    def test_single_process_noop(self):
        info = initialize_distributed()
        assert info == {"multi_host": False, "process_id": 0,
                        "num_processes": 1}

    def test_config_without_coordinator_noop(self):
        info = initialize_distributed(
            config={"distributed": {"num_processes": 4}})
        assert info["multi_host"] is False

    def test_env_resolution_requires_both(self, monkeypatch):
        monkeypatch.setenv("RELAYRL_NUM_PROCESSES", "4")
        # no coordinator anywhere -> still a no-op (never calls
        # jax.distributed.initialize, which would hang)
        info = initialize_distributed()
        assert info["multi_host"] is False

    def test_repeat_call_returns_cached_topology(self):
        first = initialize_distributed()
        # Later bare query must agree with the first resolution, not
        # re-resolve from (possibly absent) args/env.
        assert initialize_distributed() == first

    def test_multi_host_without_process_id_raises(self):
        with pytest.raises(ValueError, match="per-host process id"):
            initialize_distributed(
                coordinator_address="127.0.0.1:1", num_processes=2)

    def test_config_process_id_rejected(self):
        with pytest.raises(ValueError, match="same rank"):
            initialize_distributed(
                coordinator_address="127.0.0.1:1",
                config={"distributed": {"num_processes": 2,
                                        "process_id": 0}})

    def test_is_coordinator_single_process(self):
        assert is_coordinator() is True


class TestProfiling:
    def test_trace_writes_artifacts(self, tmp_path):
        log_dir = tmp_path / "prof"
        with trace(str(log_dir)):
            jax.block_until_ready(jnp.ones((64, 64)) @ jnp.ones((64, 64)))
        produced = list(log_dir.rglob("*"))
        assert any(p.is_file() for p in produced), produced

    def test_annotate_scope(self):
        with annotate("test-scope"):
            jax.block_until_ready(jnp.ones(8) * 2)

    def test_timed(self):
        out, secs = timed(lambda: jnp.sum(jnp.ones((128, 128))))
        assert float(out) == 128 * 128
        assert secs >= 0
