"""Columnar trajectory wire (ISSUE 9): frame codec, anakin emitter
parity against the native per-record decode, ingest-level parity
(byte-identical staging batches, bit-identical learner params), the
server decode path (CRC rejection, guardrails through frames), live
accounting parity on all three transports, and the crash drill with
anakin actors shipping frames.

The parity contract under test: a columnar frame decodes into EXACTLY
the :class:`DecodedTrajectory` the native msgpack decoder produces from
the per-record wire for the same rollout — same columns, same dtypes,
same bytes — so everything downstream (validation, padding, staging
slabs, the learner) is provably wire-form-agnostic.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from relayrl_tpu.models import build_policy
from relayrl_tpu.types.columnar import (
    DecodedTrajectory,
    NativeDecoder,
    encode_columnar_frame,
    is_columnar_frame,
    native_codec_available,
    parse_frame,
)
from relayrl_tpu.types.model_bundle import ModelBundle
from tests._util import free_port

pytestmark = pytest.mark.columnar

BENCHES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benches")

OBS_DIM, ACT_DIM = 4, 2


def _bundle(arch_over=None, seed=0, version=0):
    arch = {"kind": "mlp_discrete", "obs_dim": OBS_DIM, "act_dim": ACT_DIM,
            "hidden_sizes": [16], **(arch_over or {})}
    policy = build_policy(arch)
    return ModelBundle(version=version, arch=arch,
                       params=policy.init_params(jax.random.PRNGKey(seed)))


def _decoded(n=3, rew=1.0, obs_dtype=np.float32):
    return DecodedTrajectory(
        agent_id="lane0", n_steps=n, n_records=n + 1, marker_truncated=True,
        columns={"o": np.arange(n * OBS_DIM).reshape(n, OBS_DIM).astype(
                     obs_dtype),
                 "a": np.arange(n, dtype=np.int32),
                 "r": np.full(n, rew, np.float32),
                 "t": np.eye(1, n, n - 1, dtype=np.uint8)[0],
                 "u": np.ones(n, np.uint8),
                 "x": np.eye(1, n, n - 1, dtype=np.uint8)[0]},
        aux={"v": np.linspace(0, 1, n).astype(np.float32),
             "logp_a": np.linspace(-1, 0, n).astype(np.float32)},
        final_obs=np.arange(OBS_DIM, dtype=np.float32))


def _collect(env, arch_over, columnar, windows=3, lanes=4, unroll=64,
             seed=7, max_traj=1000, **env_kwargs):
    """Run an AnakinActorHost and return (sent payloads, host)."""
    from relayrl_tpu.runtime.anakin import AnakinActorHost

    sent: list[tuple[int, bytes]] = []
    host = AnakinActorHost(
        _bundle(arch_over), env, num_envs=lanes, unroll_length=unroll,
        max_traj_length=max_traj, columnar_wire=columnar,
        on_send=lambda lane, p: sent.append((lane, p)), seed=seed,
        **env_kwargs)
    for _ in range(windows):
        host.rollout()
    return sent, host


# ---------------------------------------------------------------------------
# frame codec
# ---------------------------------------------------------------------------
class TestFrameCodec:
    def test_round_trip_preserves_columns_dtypes_and_flags(self):
        dt = _decoded()
        buf = encode_columnar_frame(dt)
        assert is_columnar_frame(buf)
        out = parse_frame(buf)
        assert out.agent_id == "lane0"
        assert (out.n_steps, out.n_records, out.marker_truncated) == (3, 4,
                                                                      True)
        for k, col in dt.columns.items():
            assert out.columns[k].dtype == col.dtype
            assert out.columns[k].tobytes() == col.tobytes()
        for k, col in dt.aux.items():
            assert out.aux[k].tobytes() == col.tobytes()
        np.testing.assert_array_equal(out.final_obs, dt.final_obs)
        assert out.final_mask is None

    def test_int_observation_column(self):
        dt = _decoded(obs_dtype=np.int32)
        out = parse_frame(encode_columnar_frame(dt))
        assert out.columns["o"].dtype == np.int32
        assert out.columns["o"].tobytes() == dt.columns["o"].tobytes()

    def test_envelope_attribution_overrides_embedded_id(self):
        buf = encode_columnar_frame(_decoded(), agent_id="")
        assert parse_frame(buf, agent_id="fleet.lane3").agent_id == \
            "fleet.lane3"

    def test_every_corruption_is_rejected(self):
        buf = encode_columnar_frame(_decoded())
        for i in range(4, len(buf), 7):
            bad = bytearray(buf)
            bad[i] ^= 0x5A
            with pytest.raises(ValueError):
                parse_frame(bytes(bad))

    def test_truncated_and_unfooted_frames_rejected(self):
        buf = encode_columnar_frame(_decoded())
        for cut in (len(buf) - 1, len(buf) - 5, 20, 7):
            with pytest.raises(ValueError):
                parse_frame(buf[:cut])
        # a C++-drain-style blob (no CRC footer) is not a wire frame
        import relayrl_tpu.types.columnar as col_mod

        footless = bytearray(buf[:-col_mod._FOOTER.size])
        flags_off = col_mod._HDR.size + len("lane0") + 8
        footless[flags_off] &= ~col_mod.FLAG_FOOTER & 0xFF
        with pytest.raises(ValueError, match="footer"):
            parse_frame(bytes(footless))

    def test_sniff_negative_on_msgpack_payloads(self):
        from relayrl_tpu.transport.base import pack_trajectory_envelope
        from relayrl_tpu.types.action import ActionRecord
        from relayrl_tpu.types.trajectory import serialize_actions

        payload = serialize_actions(
            [ActionRecord(obs=np.zeros(4, np.float32),
                          act=np.int32(0), rew=1.0, done=True)])
        assert not is_columnar_frame(payload)
        assert not is_columnar_frame(pack_trajectory_envelope("a", payload))
        assert not is_columnar_frame(b"")


# ---------------------------------------------------------------------------
# anakin emitter parity vs the native decode of the per-record wire
# ---------------------------------------------------------------------------
@pytest.mark.anakin
@pytest.mark.skipif(not native_codec_available(),
                    reason="native codec unavailable")
class TestEmitterParity:
    CASES = {
        "cartpole": ("CartPole-v1", None, {}, 1000),
        "cartpole_chunked": ("CartPole-v1", None, {}, 17),
        "cartpole_truncating": ("CartPole-v1", None, {"max_steps": 5}, 1000),
        # Fused-sequence scan (ISSUE 20): the rolling-window carry must
        # unstack to the same frames as the per-record path — truncating
        # past W=8 so the ring rolls AND resets inside the scan.
        "cartpole_sequence": (
            "CartPole-v1",
            {"kind": "transformer_discrete", "d_model": 16, "n_layers": 1,
             "n_heads": 2, "max_seq_len": 8}, {"max_steps": 18}, 1000),
        "pendulum_continuous": (
            "Pendulum-v1",
            {"kind": "mlp_continuous", "obs_dim": 3, "act_dim": 1}, {}, 1000),
        "gridworld_int_obs": (
            "GridWorld-v0",
            {"obs_dim": 2, "act_dim": 4}, {}, 1000),
    }

    @pytest.mark.parametrize("case", sorted(CASES))
    def test_frames_decode_identical_to_native_unstack(self, case, tmp_cwd):
        env_id, arch_over, env_kwargs, max_traj = self.CASES[case]
        windows = 8 if case == "pendulum_continuous" else 3
        frames, host_c = _collect(env_id, arch_over, True, windows=windows,
                                  max_traj=max_traj, **env_kwargs)
        records, host_r = _collect(env_id, arch_over, False, windows=windows,
                                   max_traj=max_traj, **env_kwargs)
        assert len(frames) == len(records) > 0
        assert host_c.episode_returns == host_r.episode_returns
        dec = NativeDecoder()
        for (lane_c, frame), (lane_r, payload) in zip(frames, records):
            assert lane_c == lane_r
            a = parse_frame(frame, agent_id="x")
            b = dec.decode(payload, agent_id="x")
            assert isinstance(b, DecodedTrajectory), type(b)
            assert (a.n_steps, a.n_records, a.marker_truncated) == \
                (b.n_steps, b.n_records, b.marker_truncated)
            assert set(a.columns) == set(b.columns)
            for k in a.columns:
                assert a.columns[k].dtype == b.columns[k].dtype, k
                assert a.columns[k].shape == b.columns[k].shape, k
                assert a.columns[k].tobytes() == b.columns[k].tobytes(), k
            assert set(a.aux) == set(b.aux)
            for k in a.aux:
                assert a.aux[k].dtype == b.aux[k].dtype, k
                assert a.aux[k].tobytes() == b.aux[k].tobytes(), k
            assert (a.final_obs is None) == (b.final_obs is None)
            if a.final_obs is not None:
                assert a.final_obs.dtype == b.final_obs.dtype
                assert a.final_obs.tobytes() == b.final_obs.tobytes()

    def test_padded_batches_byte_identical(self, tmp_cwd):
        """The staging-slab input: pad_decoded over both decodes of the
        same rollout yields byte-identical padded fields."""
        from relayrl_tpu.data.batching import pad_decoded

        frames, _ = _collect("CartPole-v1", None, True)
        records, _ = _collect("CartPole-v1", None, False)
        dec = NativeDecoder()
        for (_, frame), (_, payload) in zip(frames, records):
            a = pad_decoded(parse_frame(frame, agent_id="x"), 64,
                            OBS_DIM, ACT_DIM, discrete=True)
            b = pad_decoded(dec.decode(payload, agent_id="x"), 64,
                            OBS_DIM, ACT_DIM, discrete=True)
            for field in ("obs", "act", "act_mask", "rew", "val", "logp",
                          "valid"):
                assert getattr(a, field).tobytes() == \
                    getattr(b, field).tobytes(), field
            assert (a.length, a.terminated, a.last_val) == \
                (b.length, b.terminated, b.last_val)


# ---------------------------------------------------------------------------
# ingest-level parity: bit-identical learner params across wire forms
# ---------------------------------------------------------------------------
class StubTransport:
    def __init__(self):
        self.on_trajectory = None
        self.on_trajectory_decoded = None
        self.get_model = None
        self.on_register = None
        self.on_unregister = None
        self.check_ingest = None

    def start(self):
        pass

    def stop(self):
        pass

    def publish_model(self, version, raw):
        pass


@pytest.fixture
def stub_server_factory(tmp_cwd, monkeypatch):
    import relayrl_tpu.runtime.server as srv_mod
    from relayrl_tpu import telemetry

    # A live registry BEFORE the server configures (configure is
    # first-wins): the columnar decode counters must really count.
    telemetry.reset_for_tests()
    telemetry.set_registry(telemetry.Registry(run_id="columnar-test"))
    yield_registry_cleanup = telemetry.reset_for_tests

    def make(algorithm="REINFORCE", hp=None, cfg=None):
        monkeypatch.setattr(srv_mod, "make_server_transport",
                            lambda *a, **k: StubTransport())
        path = tmp_cwd / f"cfg_{len(os.listdir(tmp_cwd))}.json"
        path.write_text(json.dumps(cfg or {}))
        hyper = {"traj_per_epoch": 4, "hidden_sizes": [16],
                 "seed_salt": 0, **(hp or {})}
        return srv_mod.TrainingServer(
            algorithm, obs_dim=OBS_DIM, act_dim=ACT_DIM,
            env_dir=str(tmp_cwd), config_path=str(path), hyperparams=hyper)

    yield make
    yield_registry_cleanup()


def _feed_and_params(server, payloads, min_updates=2):
    """Feed sequence-tagged payloads through the real ingest funnel
    (transport callback → staging decode → learner), drain, return the
    final host params + accounting."""
    from relayrl_tpu.transport.base import tag_agent_seq

    server.wait_warmup(180)
    seqs: dict[str, int] = {}
    for lane, payload in payloads:
        agent_id = f"parity.lane{lane}"
        seqs[agent_id] = seqs.get(agent_id, 0) + 1
        server._on_trajectory(tag_agent_seq(agent_id, seqs[agent_id]),
                              payload)
    assert server.drain(timeout=120)
    assert server.stats["updates"] >= min_updates
    acct = server.ingest_accounting()
    params = jax.device_get(server.algorithm.bundle().params)
    return params, acct, dict(server.stats)


def _assert_trees_bit_identical(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        xa, ya = np.asarray(x), np.asarray(y)
        assert xa.dtype == ya.dtype and xa.shape == ya.shape
        assert xa.tobytes() == ya.tobytes()


@pytest.mark.parametrize("algorithm,hp", [
    ("REINFORCE", {"with_vf_baseline": False}),
    # ISSUE 17 wall re-fit: the wire-form equivalence is algorithm-agnostic;
    # REINFORCE stays fast, the PPO twin rides the slow tier.
    pytest.param("PPO", {"train_iters": 2, "minibatch_count": 2},
                 marks=pytest.mark.slow),
])
def test_learner_params_bit_identical_across_wire_forms(
        algorithm, hp, stub_server_factory, tmp_cwd):
    """THE ingest parity acceptance: the same rollout delivered as
    columnar frames vs per-record msgpack yields bit-identical learner
    params and identical accepted-step accounting."""
    frames, _ = _collect("CartPole-v1", None, True, windows=4, seed=3)
    records, _ = _collect("CartPole-v1", None, False, windows=4, seed=3)
    assert len(frames) == len(records) >= 8
    results = {}
    for label, payloads in (("columnar", frames), ("records", records)):
        server = stub_server_factory(algorithm=algorithm, hp=hp)
        try:
            results[label] = _feed_and_params(server, payloads)
        finally:
            server.disable_server()
    (p_a, acct_a, stats_a) = results["columnar"]
    (p_b, acct_b, stats_b) = results["records"]
    assert acct_a["agents"] == acct_b["agents"]
    assert stats_a["trajectories"] == stats_b["trajectories"]
    assert stats_a["updates"] == stats_b["updates"] >= 2
    _assert_trees_bit_identical(p_a, p_b)


# ---------------------------------------------------------------------------
# server decode path: CRC rejection + guardrails through frames
# ---------------------------------------------------------------------------
class TestServerColumnarPath:
    def test_crc_reject_counted_and_seq_replayable(self, stub_server_factory):
        """A corrupted frame drops with the columnar-reject counter AND
        retracts its seq from the dedup ledger, so the actor's spool
        replay can land the retained clean copy later."""
        from relayrl_tpu import telemetry
        from relayrl_tpu.transport.base import tag_agent_seq

        server = stub_server_factory()
        try:
            server.wait_warmup(180)
            frame = bytearray(encode_columnar_frame(_decoded()))
            frame[-10] ^= 0xFF  # corrupt inside the CRC-covered region
            server._on_trajectory(tag_agent_seq("crc.lane0", 1),
                                  bytes(frame))
            deadline = time.monotonic() + 30
            reg = telemetry.get_registry()

            def counter(name):
                return sum(m["value"] for m in reg.snapshot()["metrics"]
                           if m["name"] == name)

            while (counter("relayrl_server_columnar_rejects_total") < 1
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert counter("relayrl_server_columnar_rejects_total") == 1
            assert server.stats["trajectories"] == 0
            # the retained clean copy replays under the SAME seq and is
            # accepted — the corruption burned no sequence number
            server._on_trajectory(tag_agent_seq("crc.lane0", 1),
                                  encode_columnar_frame(_decoded()))
            server.drain(timeout=60)
            row = server.ingest_accounting()["agents"]["crc.lane0"]
            assert row["accepted"] == 1 and row["contiguous"]
        finally:
            server.disable_server()

    def test_nan_poison_quarantines_through_columnar_decode(
            self, stub_server_factory):
        """Guardrails' semantic trust boundary works per-frame: NaN
        rewards inside a wire-VALID columnar frame (CRC passes) are
        rejected as nonfinite, strike the sending agent, and quarantine
        it — while a clean agent on the same funnel keeps training."""
        server = stub_server_factory(cfg={"guardrails": {
            "strike_threshold": 2, "quarantine_cooldown_s": 300.0}})
        try:
            server.wait_warmup(180)
            poison = encode_columnar_frame(_decoded(rew=float("nan")))
            clean = encode_columnar_frame(_decoded())
            server._on_trajectory("evil", poison)
            server._on_trajectory("evil", poison)  # strike 2 → quarantine
            server._on_trajectory("good", clean)
            deadline = time.monotonic() + 30
            while (server.guardrails.quarantine.quarantines_total < 1
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert server.guardrails.quarantine.is_quarantined("evil")
            server.drain(timeout=60)
            assert server.stats["trajectories"] == 1  # only the clean one
            from relayrl_tpu import telemetry

            rejected = sum(
                m["value"]
                for m in telemetry.get_registry().snapshot()["metrics"]
                if m["name"] == "relayrl_guard_rejected_total"
                and m.get("labels", {}).get("reason") == "nonfinite")
            assert rejected >= 2
        finally:
            server.disable_server()


# ---------------------------------------------------------------------------
# live transports: accounting parity + the fast path actually taken
# ---------------------------------------------------------------------------
def _require_transport(transport: str) -> None:
    if transport == "native":
        from relayrl_tpu.transport.native_backend import native_available

        if not native_available():
            pytest.skip("native .so unavailable")
    if transport == "grpc":
        pytest.importorskip("grpc")


def _transport_addrs(transport: str) -> tuple[dict, dict]:
    if transport in ("native", "grpc"):
        port = free_port()
        return ({"bind_addr": f"127.0.0.1:{port}"},
                {"server_addr": f"127.0.0.1:{port}"})
    ports = [free_port() for _ in range(3)]
    return ({"agent_listener_addr": f"tcp://127.0.0.1:{ports[0]}",
             "trajectory_addr": f"tcp://127.0.0.1:{ports[1]}",
             "model_pub_addr": f"tcp://127.0.0.1:{ports[2]}"},
            {"agent_listener_addr": f"tcp://127.0.0.1:{ports[0]}",
             "trajectory_addr": f"tcp://127.0.0.1:{ports[1]}",
             "model_sub_addr": f"tcp://127.0.0.1:{ports[2]}"})


def _live_accounting(transport: str, columnar: bool, tmp_cwd,
                     windows: int = 4) -> tuple[dict, int]:
    """One VectorAgent(anakin) run against a live TrainingServer on
    ``transport``; returns (per-lane accounting, server columnar-frame
    count)."""
    from relayrl_tpu import telemetry
    from relayrl_tpu.runtime.agent import VectorAgent
    from relayrl_tpu.runtime.server import TrainingServer

    server_addrs, agent_addrs = _transport_addrs(transport)
    server = TrainingServer(
        "REINFORCE", obs_dim=4, act_dim=2, env_dir=str(tmp_cwd),
        server_type=transport,
        hyperparams={"traj_per_epoch": 100, "hidden_sizes": [8],
                     "with_vf_baseline": False},
        **server_addrs)
    try:
        agent = VectorAgent(
            num_envs=2, server_type=transport, handshake_timeout_s=60,
            seed=4, probe=False, host_mode="anakin",
            jax_env="CartPole-v1", unroll_length=32,
            columnar_wire=columnar, identity=f"parity-{transport}",
            **agent_addrs)
        try:
            for _ in range(windows):
                agent.rollout()
            sent = dict(agent.spool.sent_counts())
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                acct = server.ingest_accounting()["agents"]
                if all(acct.get(aid, {}).get("accepted") == n
                       for aid, n in sent.items()):
                    break
                time.sleep(0.1)
            server.drain(timeout=30)
            acct = server.ingest_accounting()["agents"]
            lanes = {aid: (row["accepted"], row["max_seq"],
                           row["contiguous"])
                     for aid, row in acct.items()
                     if aid.startswith(f"parity-{transport}.lane")}
            assert lanes, "no lane attribution"
            for aid, n in sent.items():
                assert lanes[aid] == (n, n, True), (aid, lanes[aid], n)
            frames = sum(
                m["value"]
                for m in telemetry.get_registry().snapshot()["metrics"]
                if m["name"] == "relayrl_server_columnar_frames_total")
            return lanes, int(frames)
        finally:
            agent.disable_agent()
    finally:
        server.disable_server()


# ISSUE 17 wall re-fit: zmq fast, grpc/native twins slow (the accounting
# path above the transport is shared; per-transport wire bytes are still
# covered fast by the codec/fuzz suites).
@pytest.mark.parametrize(
    "transport",
    ["zmq",
     pytest.param("grpc", marks=pytest.mark.slow),
     pytest.param("native", marks=pytest.mark.slow)])
def test_live_accounting_parity_all_transports(transport, tmp_cwd):
    """Same seed, same windows, both wire forms over a LIVE transport:
    per-lane accepted-step accounting is identical, zero loss on both,
    and the columnar run actually took the frame fast path (server-side
    decoded-frame counter advanced)."""
    from relayrl_tpu import telemetry

    _require_transport(transport)
    telemetry.reset_for_tests()
    telemetry.set_registry(telemetry.Registry(run_id="columnar-live"))
    try:
        lanes_c, frames_before = _live_accounting(transport, True, tmp_cwd)
        assert frames_before > 0, \
            "columnar run never exercised the fast path"
        lanes_r, frames_after = _live_accounting(transport, False, tmp_cwd)
        assert frames_after == frames_before, \
            "per-record run unexpectedly produced columnar frames"
        assert lanes_c == lanes_r
    finally:
        telemetry.reset_for_tests()


# ---------------------------------------------------------------------------
# the crash drill with frames (satellite: PR 6 chaos drill × columnar)
# ---------------------------------------------------------------------------
def _read_status(scratch: str) -> dict | None:
    try:
        with open(os.path.join(scratch, "status.json")) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _wait_status(scratch, proc, pred, timeout_s, what) -> dict:
    deadline = time.monotonic() + timeout_s
    status = None
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            out, _ = proc.communicate()
            raise AssertionError(
                f"chaos server died waiting for {what} "
                f"(rc={proc.returncode}):\n{out[-3000:]}")
        status = _read_status(scratch)
        if status is not None and pred(status):
            return status
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}; last={status}")


def _spawn_chaos_server(scratch, transport, addrs, resume):
    cfg = {
        "algorithm": "REINFORCE", "obs_dim": 4, "act_dim": 2,
        "hyperparams": {"traj_per_epoch": 4, "hidden_sizes": [16, 16],
                        "with_vf_baseline": False},
        "server_type": transport, "scratch": scratch,
        "checkpoint_every": 1, "resume": resume,
        "status_path": os.path.join(scratch, "status.json"),
        **addrs,
    }
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(BENCHES)
    return subprocess.Popen(
        [sys.executable, os.path.join(BENCHES, "_chaos_server.py"),
         json.dumps(cfg)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


# Tier-1 wall budget (ISSUE 15): slow-marked — the fast set keeps one
# SIGKILL drill per transport (tests/test_recovery.py); this variant
# re-runs the same contract with frames on the wire (~38 s for the
# trio). Run via `pytest -m columnar`.
@pytest.mark.slow
@pytest.mark.parametrize("transport", ["zmq", "grpc", "native"])
def test_learner_sigkill_columnar_replay_zero_loss(transport, tmp_path,
                                                   tmp_cwd):
    """The PR 6 chaos drill on the columnar wire, all three transports:
    SIGKILL the learner while anakin actors ship frames, windows keep
    landing in the spool through the outage, restart with resume, spool
    replays the retained frames, and per-lane accounting closes at
    accepted == max_seq == sent — zero loss, zero double-train, with
    frames (not per-record payloads) on the wire throughout."""
    _require_transport(transport)
    scratch = str(tmp_path)
    server_addrs, agent_addrs = _transport_addrs(transport)
    proc = _spawn_chaos_server(scratch, transport, server_addrs,
                               resume=False)
    agent = None
    try:
        _wait_status(scratch, proc, lambda s: True, 120, "server up")
        from relayrl_tpu.runtime.agent import VectorAgent

        extra = {"heartbeat_s": 1.0} if transport == "native" else {}
        agent = VectorAgent(
            num_envs=2, server_type=transport, handshake_timeout_s=60,
            seed=0, probe=False, host_mode="anakin",
            jax_env="CartPole-v1", unroll_length=16,
            identity=f"colchaos-{transport}", **agent_addrs, **extra)
        assert agent.columnar_wire, "anakin default must be columnar"
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            agent.rollout()
            status = _read_status(scratch)
            if (status and status["version"] >= 2
                    and status["accounting"]["agents"]):
                break
            time.sleep(0.05)
        status = _read_status(scratch)
        assert status and status["version"] >= 2, "no training before kill"
        v_before = status["version"]

        proc.kill()
        proc.wait(timeout=30)
        for _ in range(6):  # frames land in the spool through the outage
            agent.rollout()
        assert agent.spool.depth > 0

        proc = _spawn_chaos_server(scratch, transport, server_addrs,
                                   resume=True)
        _wait_status(scratch, proc, lambda s: True, 120, "server restart")
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            agent.rollout()
            status = _read_status(scratch)
            if status and status["version"] > v_before:
                break
            time.sleep(0.05)
        assert status["version"] > v_before, "no training past the crash"

        agent.spool.replay()
        sent_counts = agent.spool.sent_counts()
        lane_ids = [aid for aid in sent_counts
                    if aid.startswith(f"colchaos-{transport}.lane")]
        assert len(lane_ids) == 2

        def recovered(s):
            rows = s["accounting"]["agents"]
            return all(
                rows.get(aid, {}).get("max_seq") == sent_counts[aid]
                and rows[aid]["contiguous"] for aid in lane_ids)

        status = _wait_status(scratch, proc, recovered, 120,
                              "zero-loss accounting for every lane")
        for aid in lane_ids:
            row = status["accounting"]["agents"][aid]
            assert row["accepted"] == sent_counts[aid], (aid, row)
        assert status["accounting"]["duplicates"] >= 1
        # the wire really carried frames: the server-side decoded-frame
        # counter is in the status telemetry and advanced
        frames = sum(m["value"] for m in status["telemetry"]["metrics"]
                     if m["name"] == "relayrl_server_columnar_frames_total")
        assert frames > 0, "drill ran but no columnar frames were decoded"
    finally:
        if agent is not None:
            agent.disable_agent()
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.communicate(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
