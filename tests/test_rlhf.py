"""RLHF workload plane (ISSUE 13): scorers, the freeze mask, the
generate→score→update scheduler, and the acceptance locks.

Lock inventory (the ISSUE's acceptance criteria):

* generation through the scheduler is BIT-identical to a local
  ``step_window`` actor at the same seed + params version
  (TestGenerationBitIdentity — byte-equal wire payloads);
* frozen leaves are bit-identical before/after N updates under the
  ``learner.freeze`` mask, round-trip through checkpoint resume, and
  are skipped (counted in ``publish_bytes_saved``) by the wire-v2 delta
  encoder (TestFreezeMask);
* the SIGKILL chaos drill on the new plane: learner killed mid-run →
  spool replay → accepted == max_seq == sent per lane, zero loss, zero
  double-train, and the reward run still converges
  (test_chaos_learner_sigkill_rlhf_plane).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from relayrl_tpu import telemetry
from tests._util import free_port

pytestmark = pytest.mark.rlhf

BENCHES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "benches")


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset_for_tests()
    yield
    telemetry.reset_for_tests()


# ---------------------------------------------------------------------------
# scorers
# ---------------------------------------------------------------------------

class TestScorers:
    def test_programmatic_counts_successor_chain(self):
        from relayrl_tpu.rlhf.scorers import ProgrammaticScorer

        sc = ProgrammaticScorer(vocab_size=6)
        # prompt [2, 3]; generated [4, 5, 1, 0]: 4=3+1 hit, 5=4+1 hit,
        # 1 != 5+1 (=0 mod 6 is EOS anyway) miss, 0 is EOS (never counts)
        tokens = np.array([2, 3, 4, 5, 1, 0, 0], np.int32)
        assert sc.score_np(tokens, 2, 4) == 2.0
        # the same window scored as jax, bit-equal
        import jax.numpy as jnp

        assert float(sc.score_jax(jnp.asarray(tokens), 2, 4)) == 2.0
        # batch path agrees with singles
        batch = sc.score_batch_np(np.stack([tokens, tokens]), 2,
                                  np.array([4, 2]))
        assert batch[0] == 2.0 and batch[1] == sc.score_np(tokens, 2, 2)

    def test_reward_model_frozen_and_deterministic(self):
        from relayrl_tpu.rlhf.scorers import RewardModelScorer

        a = RewardModelScorer(vocab_size=6, context_len=8, seed=11)
        b = RewardModelScorer(vocab_size=6, context_len=8, seed=11)
        tokens = np.array([1, 2, 3, 4, 5, 0, 0, 0], np.int32)
        s = a.score_np(tokens, 2, 3)
        assert s == b.score_np(tokens, 2, 3), "same (shape, seed) must agree"
        assert -1.0 < s < 1.0, "tanh-squashed score"
        # batch path returns the identical bits as the single path
        batch = a.score_batch_np(np.stack([tokens, tokens]), 2,
                                 np.array([3, 3]))
        assert batch[0] == np.float32(s) == batch[1]
        # params are FROZEN: scoring never mutates them
        import jax

        before = jax.tree_util.tree_leaves(a.params)[0].copy()
        a.score_np(tokens, 2, 5)
        np.testing.assert_array_equal(
            before, jax.tree_util.tree_leaves(a.params)[0])

    def test_make_scorer_unknown_name(self):
        from relayrl_tpu.rlhf.scorers import make_scorer

        with pytest.raises(ValueError, match="programmatic"):
            make_scorer("nope")

    def test_tokengen_rm_parity_both_planes(self):
        """The RM-scored env: numpy twin and JAX twin pay the SAME
        reward bits at the terminal (both planes call one compiled
        scorer program)."""
        import jax
        import jax.numpy as jnp

        from relayrl_tpu.envs import TokenGenEnv, make_jax
        from relayrl_tpu.rlhf.scorers import RewardModelScorer

        rm = RewardModelScorer(vocab_size=5, context_len=6, seed=2)
        kwargs = dict(vocab_size=5, prompt_len=2, max_new_tokens=4,
                      scorer=rm)
        jenv = make_jax("TokenGen-v0", **kwargs)
        nenv = TokenGenEnv(**kwargs)
        nenv.reset(seed=0)
        step = jax.jit(jenv.step)
        key = jax.random.PRNGKey(9)
        rng = np.random.default_rng(9)
        terminals = 0
        key, sub = jax.random.split(key)
        state, _ = jenv.reset(sub)
        for _ in range(60):
            nenv._tokens = np.asarray(state.tokens, np.int32).copy()
            nenv._t = int(state.t)
            action = int(rng.integers(5))
            state, _obs, jrew, jterm, _tr = step(state, jnp.int32(action))
            _nobs, nrew, nterm, _nt, _ = nenv.step(action)
            assert np.float32(float(jrew)) == np.float32(nrew)
            assert bool(jterm) == nterm
            if bool(jterm):
                terminals += 1
                key, sub = jax.random.split(key)
                state, _ = jenv.reset(sub)
        assert terminals >= 5

    def test_jax_env_refuses_host_only_scorer(self):
        from relayrl_tpu.envs import make_jax

        with pytest.raises(ValueError, match="score_jax"):
            make_jax("TokenGen-v0", scorer=lambda tok, p, g: 0.0)


# ---------------------------------------------------------------------------
# score stage
# ---------------------------------------------------------------------------

def _generate_episode(seed: int, vocab=6, prompt_len=2, max_new=5):
    """One scorer-less TokenGen episode through a real PolicyActor
    (MLP), returning (payload bytes, actor)."""
    from relayrl_tpu.envs import TokenGenEnv
    from relayrl_tpu.runtime.policy_actor import PolicyActor
    from relayrl_tpu.types.model_bundle import ModelBundle
    from relayrl_tpu.models import build_policy
    import jax

    arch = {"kind": "mlp_discrete", "obs_dim": prompt_len + max_new,
            "act_dim": vocab, "hidden_sizes": [16], "has_critic": True}
    params = build_policy(arch).init_params(jax.random.PRNGKey(seed))
    sent = []
    actor = PolicyActor(ModelBundle(version=1, arch=arch, params=params),
                        on_send=sent.append, seed=seed)
    env = TokenGenEnv(vocab_size=vocab, prompt_len=prompt_len,
                      max_new_tokens=max_new, scorer=None)
    obs, _ = env.reset(seed=seed)
    for _ in range(max_new):
        rec = actor.request_for_action(obs)
        obs, _rew, term, _tr, _ = env.step(int(np.asarray(rec.act)))
        if term:
            actor.flag_last_action(0.0, terminated=True)
            break
    assert sent, "episode never shipped"
    return sent[0], env


def _fused_generation_frames(seed=0, vocab=6, prompt_len=2, max_new=6,
                             lanes=2, unroll=24):
    """Fused-scan TokenGen episodes as columnar frames through a real
    AnakinActorHost — the anakin generation tier's wire form (ISSUE 20):
    whole episodes, per-token logp_a/v aux, bver stamped at unstack."""
    import jax

    from relayrl_tpu.models import build_policy
    from relayrl_tpu.runtime.anakin import AnakinActorHost
    from relayrl_tpu.types.model_bundle import ModelBundle

    ctx = prompt_len + max_new
    arch = {"kind": "transformer_discrete", "obs_dim": ctx,
            "act_dim": vocab, "d_model": 16, "n_layers": 1, "n_heads": 2,
            "max_seq_len": ctx}
    policy = build_policy(arch)
    bundle = ModelBundle(version=2, arch=arch,
                         params=policy.init_params(jax.random.PRNGKey(seed)))
    sent: list[tuple[int, bytes]] = []
    host = AnakinActorHost(
        bundle, "TokenGen-v0", num_envs=lanes, unroll_length=unroll,
        columnar_wire=True, record_bver=True,
        on_send=lambda lane, p: sent.append((lane, p)), seed=seed,
        vocab_size=vocab, prompt_len=prompt_len, max_new_tokens=max_new)
    host.rollout()
    assert sent, "fused generation never shipped an episode"
    return sent


class TestScoreStage:
    def test_extract_generation_reconstructs_tokens(self):
        from relayrl_tpu.rlhf.scheduler import extract_generation
        from relayrl_tpu.types.trajectory import deserialize_actions

        payload, env = _generate_episode(0)
        records = deserialize_actions(payload)
        tokens, gen_len, marker = extract_generation(records, 2)
        # the env's own final buffer IS the ground truth
        np.testing.assert_array_equal(tokens, env._tokens)
        assert gen_len == env._t
        assert marker is not None and marker.act is None

    def test_scores_patch_marker_and_preserve_steps(self):
        from relayrl_tpu.rlhf.scheduler import ScoreStage
        from relayrl_tpu.types.trajectory import deserialize_actions

        payload, env = _generate_episode(1)

        class FixedScorer:
            def score_np(self, tokens, prompt_len, gen_len):
                return 7.25

        emitted = []
        stage = ScoreStage(FixedScorer(), prompt_len=2,
                           emit_fn=lambda lane, p: emitted.append((lane, p)),
                           batch=4)
        stage.submit(3, payload)
        stage.close()
        assert len(emitted) == 1 and emitted[0][0] == 3
        out = deserialize_actions(emitted[0][1])
        inp = deserialize_actions(payload)
        assert out[-1].act is None and out[-1].rew == 7.25
        assert inp[-1].rew == 0.0
        # every non-reward field of every record survives byte-for-byte
        for a, b in zip(inp[:-1], out[:-1]):
            np.testing.assert_array_equal(a.obs, b.obs)
            np.testing.assert_array_equal(a.act, b.act)
            assert a.rew == b.rew and a.done == b.done
        assert stage.scored_snapshot() == [7.25]

    def test_batched_scoring_pads_and_slices(self):
        """A partial batch pads with repeated rows (inert) — scores for
        the real rows must equal the single-path scores."""
        from relayrl_tpu.rlhf.scheduler import ScoreStage
        from relayrl_tpu.rlhf.scorers import ProgrammaticScorer
        from relayrl_tpu.types.trajectory import deserialize_actions

        sc = ProgrammaticScorer(vocab_size=6)
        payloads = [_generate_episode(s)[0] for s in range(3)]
        emitted = []
        stage = ScoreStage(sc, prompt_len=2,
                           emit_fn=lambda lane, p: emitted.append(p),
                           batch=8)  # > submissions: forced padding
        for i, p in enumerate(payloads):
            stage.submit(i, p)
        stage.close()
        assert len(emitted) == 3
        for src, out_bytes in zip(payloads, emitted):
            from relayrl_tpu.rlhf.scheduler import extract_generation

            records = deserialize_actions(src)
            tokens, gen_len, _ = extract_generation(records, 2)
            expected = sc.score_np(tokens, 2, gen_len)
            out = deserialize_actions(out_bytes)
            assert out[-1].rew == expected

    def test_extract_generation_frame_reconstructs_tokens(self, tmp_cwd):
        """The columnar twin of extract_generation: the full token
        buffer comes back from the LAST observation row plus the final
        action (the env never materializes the terminal row), and every
        generated slot equals the action column that wrote it."""
        from relayrl_tpu.rlhf.scheduler import extract_generation_frame
        from relayrl_tpu.types.columnar import parse_frame

        for _lane, frame in _fused_generation_frames():
            dt = parse_frame(frame)
            tokens, gen_len = extract_generation_frame(dt, 2)
            assert gen_len == dt.n_steps >= 1
            assert tokens.dtype == np.int32
            first = np.asarray(dt.columns["o"][0]).astype(np.int32)
            np.testing.assert_array_equal(tokens[:2], first[:2])
            acts = np.asarray(dt.columns["a"], np.int32).reshape(-1)
            for i in range(gen_len):
                assert int(tokens[2 + i]) == int(acts[i]), i

    def test_score_stage_patches_columnar_frame(self, tmp_cwd):
        """A fused-tier columnar frame flows through the SAME stage:
        the terminal reward cell is replaced with the score, every other
        column/aux byte survives, and the submitted frame is never
        mutated in place."""
        from relayrl_tpu.rlhf.scheduler import ScoreStage
        from relayrl_tpu.types.columnar import parse_frame

        _lane, frame = _fused_generation_frames()[0]

        class FixedScorer:
            def score_np(self, tokens, prompt_len, gen_len):
                return 7.25

        emitted = []
        stage = ScoreStage(FixedScorer(), prompt_len=2,
                           emit_fn=lambda lane, p: emitted.append((lane, p)),
                           batch=4)
        stage.submit(3, frame)
        stage.close()
        assert len(emitted) == 1 and emitted[0][0] == 3
        out = parse_frame(emitted[0][1])
        inp = parse_frame(frame)
        assert out.columns["r"][-1] == np.float32(7.25)
        assert inp.columns["r"][-1] == 0.0  # scorer-less env, unmutated
        np.testing.assert_array_equal(out.columns["r"][:-1],
                                      inp.columns["r"][:-1])
        for k in ("o", "a", "t", "u", "x"):
            assert out.columns[k].tobytes() == inp.columns[k].tobytes(), k
        assert set(out.aux) == set(inp.aux) >= {"logp_a", "bver"}
        for k in inp.aux:
            assert out.aux[k].tobytes() == inp.aux[k].tobytes(), k
        assert stage.scored_snapshot() == [7.25]


# ---------------------------------------------------------------------------
# freeze mask (acceptance lock: frozen leaves bit-identical + wire skip)
# ---------------------------------------------------------------------------

class TestFreezeMask:
    def test_normalize_spec_validates(self):
        from relayrl_tpu.algorithms.freeze import normalize_freeze_spec

        assert normalize_freeze_spec(None) == ()
        assert normalize_freeze_spec("") == ()
        assert normalize_freeze_spec("a.*b") == ("a.*b",)
        assert normalize_freeze_spec(["x", "y"]) == ("x", "y")
        with pytest.raises(ValueError, match="not a valid regex"):
            normalize_freeze_spec("[")
        with pytest.raises(ValueError, match="non-empty"):
            normalize_freeze_spec([""])

    def test_loader_validates_freeze_at_load(self, tmp_path):
        from relayrl_tpu.config import ConfigLoader

        p = tmp_path / "relayrl_config.json"
        p.write_text(json.dumps({"learner": {"freeze": "["}}))
        with pytest.warns(UserWarning, match="invalid learner.freeze"):
            loader = ConfigLoader(None, p, create_if_missing=False)
            assert loader.get_learner_params()["freeze"] is None
        p2 = tmp_path / "ok.json"
        p2.write_text(json.dumps({"learner": {"freeze": ["params/pi"]}}))
        loader = ConfigLoader(None, p2, create_if_missing=False)
        assert loader.get_learner_params()["freeze"] == ["params/pi"]

    @staticmethod
    def _leaf_map(params):
        import jax

        from relayrl_tpu.algorithms.freeze import leaf_path

        return {leaf_path(p): np.asarray(leaf).tobytes()
                for p, leaf in jax.tree_util.tree_leaves_with_path(params)}

    @staticmethod
    def _drive_epochs(algo, obs_dim, act_dim, epochs):
        from relayrl_tpu.types.action import ActionRecord

        rng = np.random.default_rng(0)
        for _ in range(epochs * algo.traj_per_epoch):
            ep = [ActionRecord(
                obs=rng.standard_normal(obs_dim).astype(np.float32),
                act=np.int64(rng.integers(act_dim)), rew=float(rng.random()),
                data={"logp_a": np.float32(-1.0), "v": np.float32(0.0)},
                done=(i == 3)) for i in range(4)]
            algo.receive_trajectory(ep)

    # Wall re-fit convention: REINFORCE is the fast per-algorithm
    # representative; the IMPALA/PPO twins ride the slow tier.
    @pytest.mark.parametrize("algo_name,extra", [
        pytest.param("IMPALA", {}, marks=pytest.mark.slow),
        ("REINFORCE", {"with_vf_baseline": True, "train_vf_iters": 2}),
        pytest.param("PPO", {"train_iters": 1, "minibatch_count": 2},
                     marks=pytest.mark.slow),
    ])
    def test_frozen_leaves_bit_identical_after_updates(self, algo_name,
                                                       extra, tmp_cwd):
        """THE mask lock, on every family that takes the knob: frozen
        leaves byte-equal after N real updates, trainable leaves moved."""
        import re
        import tempfile

        from relayrl_tpu.algorithms import build_algorithm

        pattern = r"params/(obs_embed|pos_embed|block_0)/"
        algo = build_algorithm(
            algo_name, obs_dim=6, act_dim=4, traj_per_epoch=2, seed_salt=0,
            model_kind="transformer_discrete", d_model=16, n_layers=2,
            n_heads=2, max_seq_len=8, bucket_lengths=[8],
            freeze=pattern,
            logger_kwargs={"output_dir": tempfile.mkdtemp()}, **extra)
        info = algo.freeze_info
        assert 0 < info["frozen_leaves"] < info["total_leaves"]
        before = self._leaf_map(algo.state.params)
        self._drive_epochs(algo, 6, 4, epochs=2)
        import jax

        jax.block_until_ready(algo.state.params)
        after = self._leaf_map(algo.state.params)
        rx = re.compile(pattern)
        moved = 0
        for name, buf in before.items():
            if rx.search(name):
                assert after[name] == buf, f"frozen leaf moved: {name}"
            else:
                moved += int(after[name] != buf)
        assert moved > 0, "no trainable leaf moved — update inert?"
        assert algo.version >= 2

    def test_checkpoint_roundtrip_and_mask_guard(self, tmp_cwd):
        """The mask rides checkpoint extras; resume under the same mask
        continues with leaves still frozen; resume under a DIFFERENT
        mask refuses with a pointed error."""
        import tempfile

        from relayrl_tpu.algorithms import build_algorithm
        from relayrl_tpu.checkpoint.manager import (
            checkpoint_algorithm,
            restore_algorithm,
        )

        pattern = r"params/block_0/"

        def build(freeze):
            kwargs = {"freeze": freeze} if freeze else {}
            return build_algorithm(
                "IMPALA", obs_dim=6, act_dim=4, traj_per_epoch=2,
                seed_salt=0, model_kind="transformer_discrete", d_model=16,
                n_layers=2, n_heads=2, max_seq_len=8, bucket_lengths=[8],
                logger_kwargs={"output_dir": tempfile.mkdtemp()}, **kwargs)

        algo = build(pattern)
        self._drive_epochs(algo, 6, 4, epochs=1)
        ckpt_dir = str(tmp_cwd / "ckpts")
        checkpoint_algorithm(algo, ckpt_dir, wait=True)
        extra = algo._ckpt_mgr.read_extra(algo._ckpt_mgr.latest_step())
        assert extra["freeze"]["patterns"] == [pattern]
        assert extra["freeze"]["frozen_leaves"] == \
            algo.freeze_info["frozen_leaves"]

        resumed = build(pattern)
        restore_algorithm(resumed, ckpt_dir)
        frozen_before = {k: v for k, v in
                         self._leaf_map(resumed.state.params).items()
                         if "block_0" in k}
        self._drive_epochs(resumed, 6, 4, epochs=1)
        import jax

        jax.block_until_ready(resumed.state.params)
        for name, buf in self._leaf_map(resumed.state.params).items():
            if "block_0" in name:
                assert frozen_before[name] == buf, name

        with pytest.raises(ValueError, match="learner.freeze"):
            restore_algorithm(build(None), ckpt_dir)

    def test_wire_v2_skips_frozen_leaves(self):
        """The savings surface: consecutive updates under the mask
        produce delta frames that OMIT every frozen leaf, and the
        publisher-side publish_bytes_saved counter grows by their
        bytes."""
        import re
        import tempfile

        import jax

        from relayrl_tpu.algorithms import build_algorithm
        from relayrl_tpu.algorithms.freeze import leaf_path
        from relayrl_tpu.transport import modelwire as mw
        from relayrl_tpu.types.model_bundle import leaf_manifest

        telemetry.set_registry(telemetry.Registry(run_id="freeze-wire"))
        pattern = r"params/(obs_embed|pos_embed|block_0)/"
        algo = build_algorithm(
            "IMPALA", obs_dim=6, act_dim=4, traj_per_epoch=2, seed_salt=0,
            model_kind="transformer_discrete", d_model=16, n_layers=2,
            n_heads=2, max_seq_len=8, bucket_lengths=[8], freeze=pattern,
            logger_kwargs={"output_dir": tempfile.mkdtemp()})
        enc = mw.ModelWireEncoder(keyframe_interval=10**9, compress="auto",
                                  small_model_bytes=0)
        params0 = jax.device_get(algo.state.params)
        manifest, leaves = leaf_manifest(params0)
        rx = re.compile(pattern)
        frozen_idx = {i for i, (p, _l) in enumerate(
            jax.tree_util.tree_leaves_with_path(params0)) if rx.search(
                leaf_path(p))}
        assert frozen_idx
        enc.encode(1, algo.arch, params0)  # keyframe seeds the base
        for v in range(2, 5):
            self._drive_epochs(algo, 6, 4, epochs=1)
            frame, info = enc.encode(v, algo.arch,
                                     jax.device_get(algo.state.params))
            assert info["kind"] == "delta"
            _k, hdr, _p = mw.parse_frame(frame)
            shipped = {entry[0] for entry in hdr["leaves"]}
            assert not (shipped & frozen_idx), (
                "a frozen leaf landed on the wire")
        snap = telemetry.get_registry().snapshot()
        saved = [m["value"] for m in snap["metrics"]
                 if m["name"] == "relayrl_wire_publish_bytes_saved_total"]
        frozen_bytes = sum(leaves[i].nbytes for i in frozen_idx)
        assert saved and saved[0] >= 3 * frozen_bytes


# ---------------------------------------------------------------------------
# generation bit-identity (acceptance lock)
# ---------------------------------------------------------------------------

class TestGenerationBitIdentity:
    def test_scheduler_generation_equals_local_step_window_actor(self):
        """A batch-of-1 GenerationStage (the scheduler's generate stage
        over a VectorActorHost, rng_keys pinned to the actor's key)
        produces byte-identical episode payloads to a local PolicyActor
        driving the same env stream through step_window — same tokens,
        same logp/v aux bits, same wire bytes."""
        import jax

        from relayrl_tpu.envs import SyncVectorEnv, TokenGenEnv
        from relayrl_tpu.models import build_policy
        from relayrl_tpu.rlhf.scheduler import GenerationStage
        from relayrl_tpu.runtime.policy_actor import PolicyActor
        from relayrl_tpu.runtime.vector_actor import VectorActorHost
        from relayrl_tpu.types.model_bundle import ModelBundle

        vocab, prompt_len, max_new = 6, 2, 5
        ctx = prompt_len + max_new
        arch = {"kind": "transformer_discrete", "obs_dim": ctx,
                "act_dim": vocab, "d_model": 16, "n_layers": 1,
                "n_heads": 2, "max_seq_len": max_new, "has_critic": True}
        params = build_policy(arch).init_params(jax.random.PRNGKey(42))
        bundle = ModelBundle(version=7, arch=arch, params=params)

        def env_fn():
            return TokenGenEnv(vocab_size=vocab, prompt_len=prompt_len,
                               max_new_tokens=max_new, scorer=None)

        # -- scheduler path: GenerationStage over a batch-of-1 host --
        stage_payloads = []
        host = VectorActorHost(
            bundle, num_envs=1,
            on_send=lambda lane, p: stage_payloads.append(p),
            rng_keys=np.asarray(jax.random.PRNGKey(0))[None],
            validate=False)
        venv = SyncVectorEnv([env_fn])
        stage = GenerationStage(host, venv, seed=123)
        rounds = 0
        while len(stage_payloads) < 6 and rounds < 200:
            stage.run_round()
            rounds += 1
        assert len(stage_payloads) >= 6

        # -- local actor path: PolicyActor + the same env stream --
        actor_payloads = []
        actor = PolicyActor(bundle, on_send=actor_payloads.append, seed=0,
                            validate=False)
        assert actor._window_fn is not None, "must exercise step_window"
        env = env_fn()
        episode = 0
        obs, _ = env.reset(seed=123)  # SyncVectorEnv lane-0 seeding
        while len(actor_payloads) < len(stage_payloads):
            rec = actor.request_for_action(obs)
            # the scheduler stamps the behavior version on every record
            rec.data["bver"] = np.int32(actor.version)
            obs, _rew, term, _tr, _ = env.step(int(np.asarray(rec.act)))
            if term:
                actor.flag_last_action(0.0, terminated=True)
                episode += 1
                # SyncVectorEnv autoreset seeding: base + lane + N*episode
                obs, _ = env.reset(seed=123 + episode)
        assert actor_payloads[:len(stage_payloads)] == stage_payloads, \
            "scheduler generation diverged from the local actor"


# ---------------------------------------------------------------------------
# live plane (in-process server)
# ---------------------------------------------------------------------------

def _zmq_addr_pair():
    addrs = {
        "agent_listener_addr": f"tcp://127.0.0.1:{free_port()}",
        "trajectory_addr": f"tcp://127.0.0.1:{free_port()}",
        "model_pub_addr": f"tcp://127.0.0.1:{free_port()}",
    }
    agent = {"agent_listener_addr": addrs["agent_listener_addr"],
             "trajectory_addr": addrs["trajectory_addr"],
             "model_sub_addr": addrs["model_pub_addr"]}
    return addrs, agent


def _write_rlhf_config(path, vocab=6, prompt_len=2, max_new=6, lanes=4,
                       freeze=None, extra=None):
    cfg = {
        "max_traj_length": 64,
        "learner": {"checkpoint_dir": "", "checkpoint_every_epochs":
                    1_000_000, "bucket_lengths": [8]},
        # Spool window sized for the chaos drill's volume (the PR 6
        # rule: window >= episode rate x (outage + replay time) — RLHF
        # episodes are short, so thousands of seqs per lane per run;
        # the 512-entry default would evict the in-flight-at-kill
        # window before phase 5 replays it).
        "actor": {"spool_entries": 32768, "spool_bytes": 268435456},
        "rlhf": {"vocab_size": vocab, "prompt_len": prompt_len,
                 "max_new_tokens": max_new, "scorer": "programmatic",
                 "lanes": lanes, "score_batch": lanes,
                 # Bounded staleness with a fast stall-trickle: the
                 # chaos drill generates through a learner outage at
                 # ~one round per pace_timeout.
                 "max_episodes_per_version": 32, "pace_timeout_s": 1.0},
    }
    if freeze:
        cfg["learner"]["freeze"] = freeze
    if extra:
        for k, v in extra.items():
            cfg.setdefault(k, {}).update(v)
    with open(path, "w") as f:
        json.dump(cfg, f)
    return str(path)


_TRANSFORMER_HP = {
    "traj_per_epoch": 8, "model_kind": "transformer_discrete",
    "d_model": 16, "n_layers": 2, "n_heads": 2, "max_seq_len": 8,
    "lr": 3e-3, "seed_salt": 0,
    # Episodes are <= max_new_tokens + 1 steps; the bucket must stay
    # within the transformer's positional table (max_seq_len) — the
    # default 64/256/1000 buckets would pad past it and fail every
    # update. Carried in hyperparams so subprocess drills (whose
    # scratch config lacks the test's learner section) agree.
    "bucket_lengths": [8],
}


class TestLivePlane:
    def test_generate_score_update_over_live_zmq(self, tmp_cwd):
        """The dataflow against a real TrainingServer: a transformer
        IMPALA learner (V-trace over the recorded behavior logp) trains
        on score-stage-assigned rewards, every lane's episodes are
        accepted exactly once, and the rlhf metric family is live."""
        from relayrl_tpu.rlhf.scheduler import RlhfScheduler
        from relayrl_tpu.runtime.server import TrainingServer

        config_path = _write_rlhf_config(tmp_cwd / "relayrl_config.json")
        addrs, agent_addrs = _zmq_addr_pair()
        telemetry.set_registry(telemetry.Registry(run_id="rlhf-live"))
        server = TrainingServer(
            "IMPALA", obs_dim=8, act_dim=6, env_dir=str(tmp_cwd),
            hyperparams=dict(_TRANSFORMER_HP), config_path=config_path,
            **addrs)
        sched = None
        try:
            sched = RlhfScheduler(config_path=config_path,
                                  server_type="zmq", seed=0,
                                  identity="rlhf-live",
                                  handshake_timeout_s=60, **agent_addrs)
            stats = sched.run(episodes=64, deadline_s=120)
            assert stats["episodes_scored"] >= 64
            sched.flush()
            deadline = time.monotonic() + 60
            while (server.stats["updates"] < 2
                   and time.monotonic() < deadline):
                time.sleep(0.1)
            assert server.stats["updates"] >= 2, "learner never trained"
            server.drain(timeout=60)
            acct = server.ingest_accounting()
            assert len(acct["agents"]) == 4
            sent = sched.agent.spool.sent_counts()
            for lane_id, row in acct["agents"].items():
                assert row["accepted"] == row["max_seq"] == sent[lane_id]
                assert row["contiguous"]
            names = {m["name"]
                     for m in telemetry.get_registry().snapshot()["metrics"]}
            for metric in ("relayrl_rlhf_generated_tokens_total",
                           "relayrl_rlhf_scored_episodes_total",
                           "relayrl_rlhf_stage_seconds",
                           "relayrl_rlhf_lag_versions"):
                assert metric in names, metric
        finally:
            if sched is not None:
                sched.close()
            server.disable_server()

    def test_fused_generation_tier_anakin(self, tmp_cwd):
        """ISSUE 20 acceptance: ``rlhf.generation_tier:
        "anakin"`` moves TokenGen INSIDE the fused scan. The live locks:
        FusedGenerationStage drives whole rollout windows, withheld
        episodes come back score-patched as columnar frames, the
        transformer IMPALA learner trains on them (per-token logp_a +
        bver intact for V-trace), and the per-lane zero-loss accounting
        holds on the same spool plane."""
        from relayrl_tpu.rlhf.scheduler import (FusedGenerationStage,
                                                RlhfScheduler)
        from relayrl_tpu.runtime.server import TrainingServer

        config_path = _write_rlhf_config(
            tmp_cwd / "relayrl_config.json",
            extra={"rlhf": {"generation_tier": "anakin"}})
        addrs, agent_addrs = _zmq_addr_pair()
        telemetry.set_registry(telemetry.Registry(run_id="rlhf-fused"))
        server = TrainingServer(
            "IMPALA", obs_dim=8, act_dim=6, env_dir=str(tmp_cwd),
            hyperparams=dict(_TRANSFORMER_HP), config_path=config_path,
            **addrs)
        sched = None
        try:
            sched = RlhfScheduler(config_path=config_path,
                                  server_type="zmq", seed=0,
                                  identity="rlhf-fused",
                                  handshake_timeout_s=60, **agent_addrs)
            assert isinstance(sched.generation, FusedGenerationStage)
            assert sched.venv is None  # no host-side envs at all
            stats = sched.run(episodes=64, deadline_s=180)
            assert stats["episodes_scored"] >= 64
            # lanes x unroll tokens per round, counted by the stage
            assert stats["tokens_generated"] >= 128
            sched.flush()
            deadline = time.monotonic() + 60
            while (server.stats["updates"] < 2
                   and time.monotonic() < deadline):
                time.sleep(0.1)
            assert server.stats["updates"] >= 2, "learner never trained"
            server.drain(timeout=60)
            acct = server.ingest_accounting()
            assert len(acct["agents"]) == 4
            sent = sched.agent.spool.sent_counts()
            for lane_id, row in acct["agents"].items():
                assert row["accepted"] == row["max_seq"] == sent[lane_id]
                assert row["contiguous"]
            names = {m["name"]
                     for m in telemetry.get_registry().snapshot()["metrics"]}
            for metric in ("relayrl_rlhf_generated_tokens_total",
                           "relayrl_rlhf_scored_episodes_total",
                           "relayrl_rlhf_stage_seconds"):
                assert metric in names, metric
        finally:
            if sched is not None:
                sched.close()
            server.disable_server()

    @pytest.mark.slow
    def test_remote_generation_tier_mlp(self, tmp_cwd):
        """(slow: spins a serving plane + thin clients — the fast suite
        keeps the vector-tier live test; run with ``-m rlhf``.)

        Thin-client generation where the serving contracts allow it:
        an MLP token policy served by the InferenceService; the score
        stage patches rewards on the client-side episodes exactly as on
        the vector tier."""
        from relayrl_tpu.rlhf.scheduler import RlhfScheduler
        from relayrl_tpu.runtime.server import TrainingServer

        config_path = _write_rlhf_config(
            tmp_cwd / "relayrl_config.json", lanes=2,
            extra={"serving": {"enabled": True, "max_batch": 4,
                               "batch_timeout_ms": 2.0},
                   "server": {"inference_server":
                              {"host": "127.0.0.1",
                               "port": str(free_port())}}})
        addrs, agent_addrs = _zmq_addr_pair()
        server = TrainingServer(
            "IMPALA", obs_dim=8, act_dim=6, env_dir=str(tmp_cwd),
            hyperparams={"traj_per_epoch": 4, "hidden_sizes": [16],
                         "seed_salt": 0},
            config_path=config_path, **addrs)
        sched = None
        try:
            sched = RlhfScheduler(config_path=config_path,
                                  server_type="zmq", seed=0,
                                  identity="rlhf-remote", lanes=2,
                                  generation_tier="remote",
                                  handshake_timeout_s=60, **agent_addrs)
            stats = sched.run(episodes=8, deadline_s=120)
            assert stats["episodes_scored"] >= 8
            sched.flush()
            server.drain(timeout=60)
            acct = server.ingest_accounting()
            assert len(acct["agents"]) == 2
            total = sum(r["accepted"] for r in acct["agents"].values())
            assert total >= 8
            for row in acct["agents"].values():
                assert row["accepted"] == row["max_seq"]
        finally:
            if sched is not None:
                sched.close()
            server.disable_server()

    def test_sequence_policies_are_servable(self):
        """Serving v2 flipped the old refusal: sequence policies build an
        InferenceService with a session window (ctx from max_seq_len), so
        the RLHF generation tier can sit behind the serving plane."""
        import jax

        from relayrl_tpu.models import build_policy
        from relayrl_tpu.runtime.inference import InferenceService
        from relayrl_tpu.types.model_bundle import ModelBundle

        arch = {"kind": "transformer_discrete", "obs_dim": 4, "act_dim": 3,
                "d_model": 16, "n_layers": 1, "n_heads": 2,
                "max_seq_len": 8, "has_critic": True}
        params = build_policy(arch).init_params(jax.random.PRNGKey(0))
        svc = InferenceService(ModelBundle(version=1, arch=arch,
                                           params=params))
        try:
            assert svc.ctx == 8
        finally:
            svc.stop()


# ---------------------------------------------------------------------------
# chaos drill (acceptance lock)
# ---------------------------------------------------------------------------

def _spawn_rlhf_server(scratch: str, addrs: dict,
                       resume: bool) -> subprocess.Popen:
    cfg = {
        "algorithm": "IMPALA", "obs_dim": 8, "act_dim": 6,
        "hyperparams": dict(_TRANSFORMER_HP),
        "server_type": "zmq", "scratch": scratch,
        "checkpoint_every": 2, "resume": resume,
        # One seq per (short) episode — thousands per lane per drill;
        # the dedup window must keep late replays re-acceptable for the
        # whole run (the columnar-drill sizing precedent).
        "dedup_window": 32768,
        "status_path": os.path.join(scratch, "status.json"),
        **addrs,
    }
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(BENCHES)
    return subprocess.Popen(
        [sys.executable, os.path.join(BENCHES, "_chaos_server.py"),
         json.dumps(cfg)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def _read_status(scratch: str):
    try:
        with open(os.path.join(scratch, "status.json")) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


@pytest.mark.slow
def test_chaos_learner_sigkill_rlhf_plane(tmp_path, tmp_cwd):
    """(slow: a multi-phase subprocess drill, ~1-3 min — the fast suite
    covers the plane's correctness via TestLivePlane; run this one with
    ``pytest -m rlhf`` or ``-m slow``.)

    THE drill on the new plane: SIGKILL the IMPALA learner mid-run
    while the scheduler keeps generating and scoring (episodes land in
    the spool), restart with resume, replay — per-lane accounting must
    read accepted == max_seq == sent (zero loss, zero double-train),
    the actor-held model version must advance across the crash, and the
    reward run must still converge (the scored curve improves over its
    random-start baseline)."""
    from relayrl_tpu.rlhf.scheduler import RlhfScheduler

    scratch = str(tmp_path)
    addrs, agent_addrs = _zmq_addr_pair()
    server_addrs = {k: addrs[k] for k in
                    ("agent_listener_addr", "trajectory_addr",
                     "model_pub_addr")}
    config_path = _write_rlhf_config(tmp_cwd / "relayrl_config.json",
                                     lanes=4)
    proc = _spawn_rlhf_server(scratch, server_addrs, resume=False)
    sched = None
    try:
        deadline = time.monotonic() + 120
        while _read_status(scratch) is None:
            assert proc.poll() is None, proc.communicate()[0][-3000:]
            assert time.monotonic() < deadline, "server never came up"
            time.sleep(0.2)
        sched = RlhfScheduler(config_path=config_path, server_type="zmq",
                              seed=0, identity="rlhf-chaos",
                              handshake_timeout_s=120, **agent_addrs)
        # Phase 1: train past a checkpoint so resume has a base.
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            sched.run(episodes=len(sched.score_stage.scored_snapshot()) + 16,
                      deadline_s=30)
            status = _read_status(scratch)
            if status and status["version"] >= 4:
                break
        status = _read_status(scratch)
        assert status and status["version"] >= 4, "no training before kill"
        v_before = status["version"]
        agent_v_before = sched.agent.model_version

        # Phase 2: SIGKILL, no shutdown path.
        proc.kill()
        proc.wait(timeout=30)

        # Phase 3: generation + scoring continue into the outage; scored
        # episodes land in the spool window.
        sched.run(episodes=len(sched.score_stage.scored_snapshot()) + 24,
                  deadline_s=60)

        # Phase 4: restart with resume; the agent heals and trains past
        # the pre-kill version.
        proc = _spawn_rlhf_server(scratch, server_addrs, resume=True)
        deadline = time.monotonic() + 240
        healed = False
        while time.monotonic() < deadline:
            sched.run(episodes=len(sched.score_stage.scored_snapshot()) + 8,
                      deadline_s=30)
            status = _read_status(scratch)
            if (status and status["version"] > v_before
                    and sched.agent.model_version > agent_v_before):
                healed = True
                break
        assert healed, (
            f"never trained past the crash: server "
            f"{status and status['version']} vs {v_before}, actor "
            f"{sched.agent.model_version} vs {agent_v_before}")

        # Phase 5: belt-and-braces replay + the accounting assertion.
        sched.flush()
        sched.agent.spool.replay()
        sent = sched.agent.spool.sent_counts()
        deadline = time.monotonic() + 120
        ok = False
        while time.monotonic() < deadline:
            status = _read_status(scratch)
            rows = (status or {}).get("accounting", {}).get("agents", {})
            if rows and all(
                    rows.get(lane, {}).get("accepted") == count
                    and rows.get(lane, {}).get("max_seq") == count
                    and rows.get(lane, {}).get("contiguous")
                    for lane, count in sent.items()):
                ok = True
                break
            time.sleep(0.3)
        assert ok, f"zero-loss accounting never settled: {rows} vs {sent}"
        assert status["accounting"]["duplicates"] >= 1, (
            "the replay should have produced deduped duplicates")

        # Phase 6: the reward run still converges — the scored curve's
        # final window beats its random-start window.
        scores = sched.score_stage.scored_snapshot()
        assert len(scores) >= 60
        first = float(np.mean(scores[:20]))
        last = float(np.mean(scores[-20:]))
        assert last > first - 0.25, (
            f"reward collapsed across the crash: {first:.2f} -> {last:.2f}")
    finally:
        if sched is not None:
            try:
                sched.close()
            except RuntimeError:
                pass
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.communicate(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()


# ---------------------------------------------------------------------------
# config + top
# ---------------------------------------------------------------------------

class TestConfigAndTop:
    def test_get_rlhf_params_clamps(self, tmp_path):
        from relayrl_tpu.config import ConfigLoader

        p = tmp_path / "relayrl_config.json"
        p.write_text(json.dumps({"rlhf": {
            "vocab_size": "junk", "prompt_len": -3, "lanes": 0,
            "scorer": "nope", "generation_tier": "warp",
            "generation_unroll": 0}}))
        loader = ConfigLoader(None, p, create_if_missing=False)
        params = loader.get_rlhf_params()
        assert params["vocab_size"] == 8
        assert params["prompt_len"] == 1
        assert params["lanes"] == 1
        assert params["scorer"] == "programmatic"
        assert params["generation_tier"] == "vector"
        assert params["generation_unroll"] == 1

    def test_generation_unroll_default_bounds_burst(self):
        from relayrl_tpu.config import ConfigLoader

        # The fused tier's burst size: one dispatch emits
        # lanes x generation_unroll same-version tokens, so the default
        # must stay near the episode budget (max_new_tokens), NOT the
        # rollout tier's unroll_length (32) — the measured failure mode
        # is triple-digit train-time version lag and a reward collapse.
        params = ConfigLoader(None, None).get_rlhf_params()
        assert params["generation_unroll"] <= params["max_new_tokens"]

    def test_generation_tier_anakin_accepted(self, tmp_path):
        from relayrl_tpu.config import ConfigLoader

        p = tmp_path / "relayrl_config.json"
        p.write_text(json.dumps({"rlhf": {"generation_tier": "anakin"}}))
        loader = ConfigLoader(None, p, create_if_missing=False)
        assert loader.get_rlhf_params()["generation_tier"] == "anakin"

    def test_unknown_rlhf_key_warns(self, tmp_path):
        from relayrl_tpu.config import ConfigLoader

        p = tmp_path / "relayrl_config.json"
        p.write_text(json.dumps({"rlhf": {"vocab_sizes": 8}}))
        with pytest.warns(UserWarning, match="rlhf.vocab_sizes"):
            ConfigLoader(None, p, create_if_missing=False)

    def test_small_model_bytes_knob(self, tmp_path):
        from relayrl_tpu.config import ConfigLoader

        p = tmp_path / "relayrl_config.json"
        p.write_text(json.dumps({"transport": {"small_model_bytes": 0}}))
        loader = ConfigLoader(None, p, create_if_missing=False)
        assert loader.get_transport_params()["small_model_bytes"] == 0
        p2 = tmp_path / "b.json"
        p2.write_text(json.dumps({"transport": {}}))
        loader = ConfigLoader(None, p2, create_if_missing=False)
        assert loader.get_transport_params()["small_model_bytes"] is None

    def test_top_renders_rlhf_section(self):
        from relayrl_tpu.telemetry.top import render

        snapshot = {
            "enabled": True, "run_id": "r", "uptime_s": 1.0,
            "mono_ns": 10**9,
            "metrics": [
                {"name": "relayrl_rlhf_generated_tokens_total",
                 "kind": "counter", "value": 1234, "labels": {}},
                {"name": "relayrl_rlhf_stage_seconds", "kind": "histogram",
                 "labels": {"stage": "generate"}, "count": 10,
                 "buckets": [0.1, 1.0], "counts": [5, 5, 0], "sum": 2.0},
            ],
        }
        text = render(snapshot)
        assert "-- rlhf" in text
        assert "generated_tokens_total: 1.2k" in text
        assert "stage=generate" in text
