"""Hierarchical relay tree (ISSUE 11): frame-verbatim forwarding with
per-hop CRC, keyframe-cache resyncs, subtree trajectory spool/batching,
the publisher resync-request path, the fan-out subscriber gauge, and the
relay-SIGKILL chaos drills on zmq + grpc.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from tests._util import free_port

pytestmark = pytest.mark.relay

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def fresh_registry():
    from relayrl_tpu import telemetry

    registry = telemetry.Registry(run_id="test-relay")
    telemetry.set_registry(registry)
    yield registry
    telemetry.reset_for_tests()


# ---------------------------------------------------------------------------
# fakes (unit-level seams for RelayNode)
# ---------------------------------------------------------------------------

def _make_fakes():
    from relayrl_tpu.transport.base import AgentTransport, ServerTransport

    class FakeUpstream(AgentTransport):
        def __init__(self, handshake=(1, b"HANDSHAKE-V1")):
            super().__init__()
            self.handshake = handshake
            self.sent: list[tuple[str, bytes]] = []
            self.registered: list[str] = []
            self.resyncs = 0
            self.fetches = 0
            self.fail_sends = False
            self.identity = "fake-up"

        def fetch_model(self, timeout_s=60.0):
            self.fetches += 1
            return self.handshake

        def register(self, agent_id=None, timeout_s=10.0):
            self.registered.append(agent_id)
            return True

        def send_trajectory(self, payload, agent_id=None):
            if self.fail_sends:
                raise ConnectionError("upstream down (test)")
            self.sent.append((agent_id, payload))

        def start_model_listener(self):
            pass

        def request_resync(self, held_version=-1):
            self.resyncs += 1

        def close(self):
            pass

    class FakeDownstream(ServerTransport):
        def __init__(self):
            super().__init__()
            self.published: list[tuple[int, bytes]] = []
            self.started = False

        def start(self):
            self.started = True

        def stop(self):
            self.started = False

        def publish_model(self, version, bundle_bytes):
            self.published.append((int(version), bundle_bytes))

    return FakeUpstream, FakeDownstream


def _make_node(tmp_cwd, fake_up, fake_down, **kwargs):
    from relayrl_tpu.relay import RelayNode

    kwargs.setdefault("name", "t")
    kwargs.setdefault("batch_max", 1)
    return RelayNode(upstream_transport=fake_up,
                     downstream_transport=fake_down, **kwargs)


def _wire_frames(n_deltas: int = 1, keyframe_interval: int = 100,
                 base_version: int = 2):
    """(keyframe_frame, [delta frames...]) from a real encoder with the
    small-model passthrough disabled (frames, not v1 bundles)."""
    from relayrl_tpu.transport.modelwire import ModelWireEncoder

    enc = ModelWireEncoder(keyframe_interval=keyframe_interval,
                           small_model_bytes=0)
    rng = np.random.default_rng(0)
    params = {"w": rng.standard_normal((64, 8)).astype(np.float32)}
    arch = {"kind": "test"}
    key, _ = enc.encode(base_version, arch, params)
    deltas = []
    for k in range(n_deltas):
        params = {"w": params["w"] + np.float32(1e-3)}
        frame, info = enc.encode(base_version + 1 + k, arch, params)
        assert info["kind"] == "delta"
        deltas.append(frame)
    return key, deltas


# ---------------------------------------------------------------------------
# model plane: verbatim forwarding, per-hop CRC, cache, resync serving
# ---------------------------------------------------------------------------

class TestRelayModelPlane:
    def test_frames_forward_verbatim_bytes_in_bytes_out(self, tmp_cwd,
                                                        fresh_registry):
        FakeUpstream, FakeDownstream = _make_fakes()
        up, down = FakeUpstream(), FakeDownstream()
        node = _make_node(tmp_cwd, up, down)
        key, (delta,) = _wire_frames(n_deltas=1)
        node._on_upstream_model(2, key)
        node._on_upstream_model(3, delta)
        assert down.published == [(2, key), (3, delta)]
        # bytes out ARE bytes in — not equal-length, IDENTICAL
        assert down.published[0][1] is key or down.published[0][1] == key
        assert down.published[1][1] == delta
        # keyframe cached; delta passed through without touching it
        assert node._keyframe == (2, key)
        assert node._latest[0] == 3
        node.close(flush_timeout_s=0)

    def test_corrupt_frame_dies_at_this_hop(self, tmp_cwd, fresh_registry):
        FakeUpstream, FakeDownstream = _make_fakes()
        up, down = FakeUpstream(), FakeDownstream()
        node = _make_node(tmp_cwd, up, down)
        key, (delta,) = _wire_frames(n_deltas=1)
        node._on_upstream_model(2, key)
        corrupt = bytearray(delta)
        corrupt[-1] ^= 0x5A  # payload byte: header parses, CRC fails
        node._on_upstream_model(3, bytes(corrupt))
        # never re-broadcast rot; ask upstream for a keyframe instead
        assert down.published == [(2, key)]
        assert up.resyncs == 1
        assert node.stats()["frames_dropped"] == 1
        node.close(flush_timeout_s=0)

    def test_v1_bundle_updates_handshake_and_keyframe_cache(self, tmp_cwd,
                                                            fresh_registry):
        FakeUpstream, FakeDownstream = _make_fakes()
        up, down = FakeUpstream(), FakeDownstream()
        node = _make_node(tmp_cwd, up, down)
        node._on_upstream_model(5, b"V1-FULL-BUNDLE")
        assert down.published == [(5, b"V1-FULL-BUNDLE")]
        assert node._get_model() == (5, b"V1-FULL-BUNDLE")
        assert node._keyframe == (5, b"V1-FULL-BUNDLE")
        node.close(flush_timeout_s=0)

    def test_stale_delivery_never_rebroadcast(self, tmp_cwd,
                                              fresh_registry):
        FakeUpstream, FakeDownstream = _make_fakes()
        up, down = FakeUpstream(), FakeDownstream()
        node = _make_node(tmp_cwd, up, down)
        key, _ = _wire_frames(n_deltas=0)
        node._on_upstream_model(2, key)
        node._on_upstream_model(2, key)  # duplicate delivery
        assert len(down.published) == 1
        node.close(flush_timeout_s=0)

    def test_subtree_resync_served_from_cache_without_root(
            self, tmp_cwd, fresh_registry):
        FakeUpstream, FakeDownstream = _make_fakes()
        up, down = FakeUpstream(), FakeDownstream()
        node = _make_node(tmp_cwd, up, down, resync_min_interval_s=0.2)
        key, _ = _wire_frames(n_deltas=0)
        node._on_upstream_model(2, key)
        # late joiner (held 0 < cached keyframe 2): serve locally
        node._serve_subtree_resync(0)
        assert down.published[-1] == (2, key)
        assert node.stats()["resyncs_served"] == 1
        assert up.resyncs == 0  # never reached the root
        # a storm coalesces into the rate-limit window
        node._serve_subtree_resync(0)
        assert node.stats()["resyncs_served"] == 1
        node.close(flush_timeout_s=0)

    def test_midstream_divergence_escalates_past_stale_cache(
            self, tmp_cwd, fresh_registry):
        """A subscriber NEWER than the cached keyframe cannot be healed
        by it (decoders drop stale versions) — the relay must escalate
        to the root's force_keyframe instead of serving a useless
        re-broadcast forever."""
        FakeUpstream, FakeDownstream = _make_fakes()
        up, down = FakeUpstream(), FakeDownstream()
        node = _make_node(tmp_cwd, up, down)
        key, _ = _wire_frames(n_deltas=0)
        node._on_upstream_model(2, key)
        published_before = len(down.published)
        node._serve_subtree_resync(150)  # held >= cache version
        assert up.resyncs == 1           # escalated upstream
        assert len(down.published) == published_before  # no stale serve
        # unknown held: both — the cache serve is free, the escalation
        # guarantees the heal
        node._serve_subtree_resync(-1)
        assert up.resyncs == 2
        assert down.published[-1] == (2, key)
        node.close(flush_timeout_s=0)

    def test_cold_cache_resync_escalates_upstream(self, tmp_cwd,
                                                  fresh_registry):
        FakeUpstream, FakeDownstream = _make_fakes()
        up, down = FakeUpstream(), FakeDownstream()
        node = _make_node(tmp_cwd, up, down, keyframe_cache=False)
        node._serve_subtree_resync(0)
        assert up.resyncs == 1
        node.close(flush_timeout_s=0)

    def test_pull_surface_serves_latest_then_keyframe(self, tmp_cwd,
                                                      fresh_registry):
        """The grpc long-poll surface: a subscriber whose base matches
        gets the delta verbatim; a diverged one gets the cached
        keyframe (the resync that never touches the root)."""
        FakeUpstream, FakeDownstream = _make_fakes()
        up, down = FakeUpstream(), FakeDownstream()
        node = _make_node(tmp_cwd, up, down)
        key, (delta,) = _wire_frames(n_deltas=1)
        node._on_upstream_model(2, key)
        node._on_upstream_model(3, delta)
        assert node._get_model_update(2) == (3, delta)   # base matches
        assert node._get_model_update(0) == (2, key)     # diverged
        node.close(flush_timeout_s=0)

    def test_pull_surface_never_regresses_a_subscriber(self, tmp_cwd,
                                                       fresh_registry):
        """A poll client adopts the reply's version, so the relay must
        never answer with a blob OLDER than known_version (the stale
        handshake bundle would regress the subscriber into a hot
        stale-bundle loop). With only an undecodable newer delta on
        hand, serve the delta — the subscriber's base mismatch triggers
        its explicit ver=-1 resync."""
        FakeUpstream, FakeDownstream = _make_fakes()
        up, down = FakeUpstream(), FakeDownstream()
        # handshake v1; cached keyframe v2; delta v6 with base 5 — a
        # subscriber at known=4 can decode none of the caches
        node = _make_node(tmp_cwd, up, down)
        key, _ = _wire_frames(n_deltas=0)
        node._on_upstream_model(2, key)
        _, (d6,) = _wire_frames(n_deltas=1, base_version=5)
        node._on_upstream_model(6, d6)
        up.fetches = 0
        version, blob = node._get_model_update(4)
        assert version > 4, "served a blob that would regress the poller"
        assert (version, blob) == (6, d6)  # the mismatch-then-resync path
        node.close(flush_timeout_s=0)

    def test_header_mangled_frame_drops_without_killing_listener(
            self, tmp_cwd, fresh_registry):
        """A frame whose msgpack HEADER is corrupted (payload CRC still
        intact) must die at the hop as a counted drop — any exception
        escaping on_model would kill the upstream listener thread and
        silently freeze the whole subtree's model plane."""
        FakeUpstream, FakeDownstream = _make_fakes()
        up, down = FakeUpstream(), FakeDownstream()
        node = _make_node(tmp_cwd, up, down)
        key, (delta,) = _wire_frames(n_deltas=1)
        node._on_upstream_model(2, key)
        mangled = bytearray(delta)
        mangled[12] ^= 0xFF  # inside the msgpack header region
        node._on_upstream_model(3, bytes(mangled))  # must not raise
        assert down.published == [(2, key)]
        assert node.stats()["frames_dropped"] == 1
        node.close(flush_timeout_s=0)


# ---------------------------------------------------------------------------
# trajectory plane: verbatim ids, batching, spool restore
# ---------------------------------------------------------------------------

class TestRelayTrajectoryPlane:
    def test_single_forward_carries_tag_verbatim(self, tmp_cwd,
                                                 fresh_registry):
        FakeUpstream, FakeDownstream = _make_fakes()
        up, down = FakeUpstream(), FakeDownstream()
        node = _make_node(tmp_cwd, up, down, batch_max=1)
        node._on_subtree_trajectory("leaf-a#s7", b"PAYLOAD")
        assert up.sent == [("leaf-a#s7", b"PAYLOAD")]
        node.close(flush_timeout_s=0)

    def test_batched_forward_keeps_every_leaf_tag(self, tmp_cwd,
                                                  fresh_registry):
        from relayrl_tpu.transport.base import (
            BATCH_KIND_ENVELOPES,
            batch_kind,
            split_batch,
            unpack_trajectory_envelope,
        )

        FakeUpstream, FakeDownstream = _make_fakes()
        up, down = FakeUpstream(), FakeDownstream()
        node = _make_node(tmp_cwd, up, down, batch_max=3,
                          batch_linger_ms=50.0)
        for k in range(3):
            node._on_subtree_trajectory(f"leaf-{k}#s{k + 1}",
                                        f"P{k}".encode())
        deadline = time.monotonic() + 5
        while not up.sent and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(up.sent) == 1
        wire_id, container = up.sent[0]
        assert wire_id == node.batch_id and "#s" not in wire_id
        assert batch_kind(container) == BATCH_KIND_ENVELOPES
        inner = [unpack_trajectory_envelope(p)
                 for p in split_batch(container)]
        assert inner == [(f"leaf-{k}#s{k + 1}", f"P{k}".encode())
                         for k in range(3)]
        node.close(flush_timeout_s=0)

    def test_server_splits_batch_back_to_per_leaf_dedup(self, tmp_cwd,
                                                        fresh_registry):
        """The root half of the batched forward: an envelope batch
        entering the ingest funnel lands as N per-leaf, seq-deduped
        trajectories — relay batching is invisible to accounting."""
        from relayrl_tpu.runtime.server import TrainingServer
        from relayrl_tpu.transport.base import (
            BATCH_KIND_ENVELOPES,
            pack_batch,
            pack_trajectory_envelope,
        )
        from relayrl_tpu.types.trajectory import serialize_actions
        from relayrl_tpu.types.action import ActionRecord

        addrs = {
            "agent_listener_addr": f"tcp://127.0.0.1:{free_port()}",
            "trajectory_addr": f"tcp://127.0.0.1:{free_port()}",
            "model_pub_addr": f"tcp://127.0.0.1:{free_port()}",
        }
        server = TrainingServer(
            "REINFORCE", obs_dim=3, act_dim=2, env_dir=str(tmp_cwd),
            hyperparams={"traj_per_epoch": 100, "hidden_sizes": [8, 8]},
            **addrs)
        try:
            traj = serialize_actions([
                ActionRecord(obs=np.zeros(3, np.float32),
                             act=np.int32(0), rew=1.0),
                ActionRecord(rew=1.0, done=True),
            ])
            envs = [pack_trajectory_envelope(f"leaf-{k}#s1", traj)
                    for k in range(3)]
            container = pack_batch(BATCH_KIND_ENVELOPES, envs)
            server._on_trajectory("@relay/t", container)
            # duplicate batch (a replay): per-leaf dedup eats all of it
            server._on_trajectory("@relay/t", container)
            server.drain(timeout=30)
            acct = server.ingest_accounting()
            assert set(acct["agents"]) == {f"leaf-{k}" for k in range(3)}
            for row in acct["agents"].values():
                assert row == {"max_seq": 1, "accepted": 1,
                               "contiguous": True}
            assert acct["duplicates"] == 3
            assert server.stats["trajectories"] == 3
        finally:
            server.disable_server()

    def test_spool_survives_relay_death_with_tags_verbatim(
            self, tmp_cwd, fresh_registry, tmp_path):
        """File-backed relay spool: a dead-upstream relay retains the
        subtree's forwards on disk; the REPLACEMENT process restores and
        replays them with the original leaf ids untouched."""
        FakeUpstream, FakeDownstream = _make_fakes()
        up, down = FakeUpstream(), FakeDownstream()
        up.fail_sends = True  # upstream dark: everything spools
        spool_dir = str(tmp_path / "relay_spool")
        node = _make_node(tmp_cwd, up, down, batch_max=1,
                          spool_dir=spool_dir)
        for k in range(4):
            node._on_subtree_trajectory(f"leaf#s{k + 1}", f"P{k}".encode())
        assert node.spool.depth == 4
        node.close(flush_timeout_s=0)  # crash stand-in: no flush

        up2, down2 = FakeUpstream(), FakeDownstream()
        node2 = _make_node(tmp_cwd, up2, down2, batch_max=1,
                           spool_dir=spool_dir)
        deadline = time.monotonic() + 5
        while len(up2.sent) < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert up2.sent == [(f"leaf#s{k + 1}", f"P{k}".encode())
                            for k in range(4)]
        node2.close(flush_timeout_s=0)

    def test_verbatim_entries_never_mint_relay_seqs(self, tmp_cwd):
        """send_verbatim retains without a seq space: sent_counts stays
        empty, and the disk sentinel round-trips seq None."""
        from relayrl_tpu.runtime.spool import TrajectorySpool

        sent = []
        spool = TrajectorySpool(
            send_fn=lambda p, tid: sent.append((tid, p)),
            directory=str(tmp_cwd), name="verbatim")
        spool.send_verbatim(b"A", "x#s9")
        spool.send(b"B", "own-lane")
        assert sent == [("x#s9", b"A"), ("own-lane#s1", b"B")]
        assert spool.sent_counts() == {"own-lane": 1}
        spool.close()
        reloaded = TrajectorySpool(send_fn=None, directory=str(tmp_cwd),
                                   name="verbatim")
        assert [(e[0], e[1]) for e in reloaded._entries] == [
            ("x#s9", None), ("own-lane", 1)]
        assert reloaded.next_seq("x#s9") == 1  # no seq space minted
        reloaded.close()


# ---------------------------------------------------------------------------
# chunk reassembly + resync-request path (live zmq)
# ---------------------------------------------------------------------------

class TestRelayZmqIntegration:
    def test_chunked_keyframe_reassembled_before_rebroadcast(
            self, tmp_cwd, fresh_registry):
        """Root splits a large keyframe into chunk frames
        (transport.chunk_bytes); the relay's upstream listener must
        reassemble the ORIGINAL frame before the relay re-broadcasts —
        one whole frame downstream, byte-identical, re-chunked only by
        the relay's own plane (off here)."""
        from relayrl_tpu.transport.zmq_backend import (
            ZmqAgentTransport,
            ZmqServerTransport,
        )

        _FakeUpstream, FakeDownstream = _make_fakes()
        ports = [free_port() for _ in range(3)]
        root = ZmqServerTransport(
            agent_listener_addr=f"tcp://127.0.0.1:{ports[0]}",
            trajectory_addr=f"tcp://127.0.0.1:{ports[1]}",
            model_pub_addr=f"tcp://127.0.0.1:{ports[2]}",
            chunk_bytes=512)
        root.get_model = lambda: (1, b"HS")
        root.start()
        up = ZmqAgentTransport(
            agent_listener_addr=f"tcp://127.0.0.1:{ports[0]}",
            trajectory_addr=f"tcp://127.0.0.1:{ports[1]}",
            model_sub_addr=f"tcp://127.0.0.1:{ports[2]}")
        down = FakeDownstream()
        node = _make_node(tmp_cwd, up, down)
        try:
            key, _ = _wire_frames(n_deltas=0)  # ~2 KB >> 512B chunks
            assert len(key) > 512
            deadline = time.monotonic() + 10
            while not down.published and time.monotonic() < deadline:
                root.publish_model(2, key)  # re-publish beats slow-joiner
                time.sleep(0.2)
            assert down.published, "keyframe never traversed the hop"
            version, blob = down.published[0]
            assert version == 2 and blob == key  # reassembled, verbatim
        finally:
            node.close(flush_timeout_s=0)
            root.stop()

    def test_wire_base_mismatch_heals_in_one_publish(self, tmp_cwd,
                                                     fresh_registry):
        """ISSUE 11 satellite: with keyframe_interval=100, a mid-stream
        WireBaseMismatch used to black out for up to 100 publishes. The
        CMD_RESYNC path must heal it in <= 1: the diverged subscriber's
        request forces the publisher's NEXT publish to keyframe."""
        from relayrl_tpu.transport.modelwire import (
            ModelWireDecoder,
            ModelWireEncoder,
            WireBaseMismatch,
        )
        from relayrl_tpu.transport.zmq_backend import (
            ZmqAgentTransport,
            ZmqServerTransport,
        )

        ports = [free_port() for _ in range(3)]
        enc = ModelWireEncoder(keyframe_interval=100, small_model_bytes=0)
        root = ZmqServerTransport(
            agent_listener_addr=f"tcp://127.0.0.1:{ports[0]}",
            trajectory_addr=f"tcp://127.0.0.1:{ports[1]}",
            model_pub_addr=f"tcp://127.0.0.1:{ports[2]}")
        root.get_model = lambda: (0, b"HS")
        # the publisher-side hook (held version is a relay concern)
        root.on_resync = lambda held=-1: enc.force_keyframe()
        root.start()

        dec = ModelWireDecoder()
        versions: list[int] = []
        mismatches: list[int] = []
        sub = ZmqAgentTransport(
            agent_listener_addr=f"tcp://127.0.0.1:{ports[0]}",
            trajectory_addr=f"tcp://127.0.0.1:{ports[1]}",
            model_sub_addr=f"tcp://127.0.0.1:{ports[2]}")

        def on_model(version, blob):
            try:
                got = dec.decode(blob)
            except WireBaseMismatch as e:
                mismatches.append(version)
                sub.request_resync(e.held)
                return
            if got is not None:
                versions.append(got[0])

        sub.on_model = on_model
        sub.start_model_listener()
        try:
            rng = np.random.default_rng(1)
            params = {"w": rng.standard_normal((64, 8)).astype(np.float32)}
            arch = {"kind": "t"}

            def publish(version):
                nonlocal params
                params = {"w": params["w"] + np.float32(1e-3)}
                frame, info = enc.encode(version, arch, params)
                root.publish_model(version, frame)
                return info["kind"]

            # keyframe 1 must land (slow-joiner): re-publish until seen
            frame, _ = enc.encode(1, arch, params)
            deadline = time.monotonic() + 10
            while not versions and time.monotonic() < deadline:
                root.publish_model(1, frame)
                time.sleep(0.2)
            assert versions and versions[-1] == 1
            assert publish(2) == "delta"
            _wait_for(lambda: versions and versions[-1] == 2)
            # a delta the subscriber NEVER sees: encoder advances, the
            # wire doesn't — the next delivered delta's base mismatches
            params = {"w": params["w"] + np.float32(1e-3)}
            enc.encode(3, arch, params)
            assert publish(4) == "delta"
            _wait_for(lambda: mismatches)
            # the resync request must reach the ROUTER before the next
            # publish decides its kind
            _wait_for(lambda: enc._force_key)
            assert publish(5) == "keyframe"   # healed in ONE publish
            _wait_for(lambda: versions and versions[-1] == 5)
            assert dec.version == 5
        finally:
            sub.close()
            root.stop()

    def test_zmq_subscriber_gauge_counts_streams(self, tmp_cwd,
                                                 fresh_registry):
        """ISSUE 11 satellite: relayrl_transport_subscribers is the live
        stream count on the PUB plane — the signal that verifies a relay
        tree (root gauge == relay count, not actor count)."""
        import zmq

        from relayrl_tpu.transport.zmq_backend import ZmqServerTransport

        ports = [free_port() for _ in range(3)]
        root = ZmqServerTransport(
            agent_listener_addr=f"tcp://127.0.0.1:{ports[0]}",
            trajectory_addr=f"tcp://127.0.0.1:{ports[1]}",
            model_pub_addr=f"tcp://127.0.0.1:{ports[2]}")
        root.start()
        ctx = zmq.Context.instance()
        subs = []
        try:
            def gauge():
                snap = fresh_registry.snapshot()
                for m in snap["metrics"]:
                    if (m["name"] == "relayrl_transport_subscribers"
                            and m["labels"].get("backend") == "zmq"):
                        return m["value"]
                return None

            for _ in range(2):
                s = ctx.socket(zmq.SUB)
                s.connect(f"tcp://127.0.0.1:{ports[2]}")
                s.setsockopt(zmq.SUBSCRIBE, b"")
                subs.append(s)
            _wait_for(lambda: gauge() == 2)
            subs.pop().close(linger=0)
            _wait_for(lambda: gauge() == 1)
        finally:
            for s in subs:
                s.close(linger=0)
            root.stop()


def _wait_for(pred, timeout_s: float = 10.0, interval_s: float = 0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval_s)
    raise AssertionError(f"condition never held: {pred}")


class TestServerResyncPath:
    def test_resync_request_rate_limited_and_coalesced(self, tmp_cwd,
                                                       fresh_registry):
        from relayrl_tpu.runtime.server import TrainingServer

        addrs = {
            "agent_listener_addr": f"tcp://127.0.0.1:{free_port()}",
            "trajectory_addr": f"tcp://127.0.0.1:{free_port()}",
            "model_pub_addr": f"tcp://127.0.0.1:{free_port()}",
        }
        server = TrainingServer(
            "REINFORCE", obs_dim=3, act_dim=2, env_dir=str(tmp_cwd),
            hyperparams={"traj_per_epoch": 100, "hidden_sizes": [8, 8]},
            **addrs)
        try:
            assert server._wire_encoder is not None
            server._on_resync_request()
            server._on_resync_request()  # inside the window: coalesced
            assert server._wire_encoder._force_key is True
            assert server._m_resync_requests.total() == 2
            assert server._m_resync_granted.total() == 1
        finally:
            server.disable_server()


# ---------------------------------------------------------------------------
# relay-SIGKILL chaos drills (subprocess relay, live transports)
# ---------------------------------------------------------------------------

def _spawn_relay(scratch: str, cfg: dict, tag: str) -> tuple:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO_ROOT
    ready = os.path.join(scratch, f"{tag}_ready")
    stop = os.path.join(scratch, "relay_stop")
    result = os.path.join(scratch, f"{tag}_result.json")
    proc = subprocess.Popen(
        [sys.executable, "-m", "relayrl_tpu.relay",
         "--json", json.dumps(cfg),
         "--ready-file", ready, "--stop-file", stop,
         "--result-path", result],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    deadline = time.monotonic() + 60
    while not os.path.exists(ready) and time.monotonic() < deadline:
        if proc.poll() is not None:
            out, _ = proc.communicate()
            raise AssertionError(
                f"relay died at bring-up (rc={proc.returncode}):"
                f"\n{out[-3000:]}")
        time.sleep(0.05)
    assert os.path.exists(ready), "relay never became ready"
    return proc, stop, result


def _drive_episodes(agent, rng, n: int, obs_dim: int, steps: int = 3):
    for _ in range(n):
        for _ in range(steps):
            agent.request_for_action(
                rng.standard_normal(obs_dim).astype(np.float32))
        agent.flag_last_action(1.0, terminated=True)


def _relay_sigkill_drill(transport: str, tmp_path, tmp_cwd):
    """SIGKILL a mid-tree relay during a live run; replacement binds the
    same fan-out addresses + spool dir. Asserts zero loss / zero
    double-train per lane and that actors resync models through the
    replacement's cache."""
    from relayrl_tpu.runtime.agent import Agent
    from relayrl_tpu.runtime.server import TrainingServer

    scratch = str(tmp_path)
    obs_dim = 4
    if transport == "zmq":
        root_addrs = {
            "agent_listener_addr": f"tcp://127.0.0.1:{free_port()}",
            "trajectory_addr": f"tcp://127.0.0.1:{free_port()}",
            "model_pub_addr": f"tcp://127.0.0.1:{free_port()}",
        }
        upstream = {
            "agent_listener_addr": root_addrs["agent_listener_addr"],
            "trajectory_addr": root_addrs["trajectory_addr"],
            "model_sub_addr": root_addrs["model_pub_addr"],
            "probe": False,
        }
        down_port = free_port(), free_port(), free_port()
        downstream = {
            "agent_listener_addr": f"tcp://127.0.0.1:{down_port[0]}",
            "trajectory_addr": f"tcp://127.0.0.1:{down_port[1]}",
            "model_pub_addr": f"tcp://127.0.0.1:{down_port[2]}",
        }
        agent_addrs = {
            "agent_listener_addr": downstream["agent_listener_addr"],
            "trajectory_addr": downstream["trajectory_addr"],
            "model_sub_addr": downstream["model_pub_addr"],
        }
    else:  # grpc
        root_port = free_port()
        root_addrs = {"bind_addr": f"127.0.0.1:{root_port}",
                      "native_grpc": False}
        upstream = {"server_addr": f"127.0.0.1:{root_port}",
                    "probe": False}
        relay_port = free_port()
        downstream = {"bind_addr": f"127.0.0.1:{relay_port}"}
        agent_addrs = {"server_addr": f"127.0.0.1:{relay_port}"}

    server = TrainingServer(
        "REINFORCE", obs_dim=obs_dim, act_dim=2, env_dir=scratch,
        hyperparams={"traj_per_epoch": 4, "hidden_sizes": [16, 16]},
        server_type=transport, **root_addrs)
    relay_cfg = {
        "name": "drill", "upstream_type": transport, "upstream": upstream,
        "downstream_type": transport if transport == "grpc" else "zmq",
        "downstream": downstream,
        "spool_dir": os.path.join(scratch, "relay_spool"),
        "batch_max": 4, "batch_linger_ms": 5.0,
    }
    proc, stop_file, _res = _spawn_relay(scratch, relay_cfg, "primary")
    agents = []
    try:
        agents = [
            Agent(server_type=transport, handshake_timeout_s=60,
                  seed=k, probe=False,
                  model_path=os.path.join(scratch, f"m{k}.rlx"),
                  identity=f"drill-{k}", **agent_addrs)
            for k in range(2)
        ]
        rngs = [np.random.default_rng(k) for k in range(2)]
        for agent, rng in zip(agents, rngs):
            _drive_episodes(agent, rng, 8, obs_dim)
        version_at_kill = max(a.model_version for a in agents)

        proc.kill()  # the mid-tree SIGKILL
        proc.wait(timeout=30)
        for agent, rng in zip(agents, rngs):  # sends spool/queue locally
            _drive_episodes(agent, rng, 8, obs_dim)

        proc2, stop_file, result_path = _spawn_relay(
            scratch, relay_cfg, "replacement")
        for agent, rng in zip(agents, rngs):
            _drive_episodes(agent, rng, 8, obs_dim)

        # models must advance BEHIND the relay after the failover (the
        # replacement's cache + fresh subscription serve the subtree).
        # Keep the learner PUBLISHING while waiting: if every queued
        # trajectory trained before the replacement's subscription
        # joined, there is no further publish to observe until new
        # data arrives — exactly how a live fleet behaves.
        deadline = time.monotonic() + 90
        while (min(a.model_version for a in agents) <= version_at_kill
               and time.monotonic() < deadline):
            for agent, rng in zip(agents, rngs):
                _drive_episodes(agent, rng, 1, obs_dim)
            time.sleep(0.2)
        assert min(a.model_version for a in agents) > version_at_kill

        # at-least-once convergence: one FULL replay pass per agent
        for agent in agents:
            assert agent.spool.flush(deadline_s=60), "spool never flushed"
        # zmq PUSH is fire-and-forget: give the pipe a beat
        time.sleep(1.0)

        # tree down LAST (flushes the relay spool upstream), then
        # reconcile: every seq accepted exactly once, per lane
        with open(stop_file, "w") as f:
            f.write("stop")
        out2, _ = proc2.communicate(timeout=60)
        server.drain(timeout=60)
        sent = {}
        for agent in agents:
            sent.update(agent.spool.sent_counts())
        # 24 scripted episodes + however many the publish-wait drove
        assert sent and all(n >= 24 for n in sent.values()), sent
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            rows = server.ingest_accounting()["agents"]
            if all(ident in rows and rows[ident]["max_seq"] == n
                   and rows[ident]["contiguous"]
                   for ident, n in sent.items()):
                break
            time.sleep(0.5)
            server.drain(timeout=15)
        acct = server.ingest_accounting()
        for ident, n in sent.items():
            row = acct["agents"].get(ident)
            assert row == {"max_seq": n, "accepted": n,
                           "contiguous": True}, (ident, row, out2[-2000:])
        # zero double-train: unique episodes trained exactly once
        assert server.stats["trajectories"] == sum(sent.values())
        # the replacement actually restored + served the subtree
        repl = json.load(open(result_path))
        assert repl["stats"]["trajectory_frames_forwarded"] > 0
    finally:
        for agent in agents:
            agent.disable_agent()
        for p in (proc,):
            if p.poll() is None:
                p.kill()
        server.disable_server()


def test_relay_sigkill_drill_zmq(tmp_path, tmp_cwd, fresh_registry):
    _relay_sigkill_drill("zmq", tmp_path, tmp_cwd)


@pytest.mark.slow  # ISSUE 17 wall re-fit: transport twin of the fast zmq drill
def test_relay_sigkill_drill_grpc(tmp_path, tmp_cwd, fresh_registry):
    pytest.importorskip("grpc")
    _relay_sigkill_drill("grpc", tmp_path, tmp_cwd)
