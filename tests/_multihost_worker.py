"""Worker for the 2-process ``jax.distributed`` CPU test.

Each of two OS processes runs this script with 4 virtual CPU devices,
forming an 8-device global mesh across a real coordinator barrier — the
CPU-simulation equivalent SURVEY.md §4 prescribes for multi-host learner
validation (no 2-host TPU pod is available to CI). Exercises the paths
`tests/test_distributed_init.py` can only argument-check in one process:

* ``initialize_distributed`` actually reaching ``jax.distributed.initialize``
* coordinator-asymmetric ingest: rank 0 builds the batch,
  ``broadcast_from_coordinator`` ships it, every rank places + steps
* a dp×fsdp-sharded REINFORCE update executing across processes
* checkpoint save on the shared dir + restore with identical state

Usage: _multihost_worker.py <rank> <coordinator_port> <ckpt_dir>
Prints "MULTIHOST_OK rank=<r>" on success; any assert kills the process.
"""

import os
import sys

rank = int(sys.argv[1])
port = sys.argv[2]
ckpt_dir = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=4 "
    + os.environ.get("XLA_FLAGS", ""))

# A sitecustomize may have imported jax (snapshotting the platform) before
# this script ran; force the live config too. Module-scope on purpose:
# this file is a subprocess ENTRY SCRIPT, never imported, and the config
# must land before anything touches the backend.
import jax  # noqa: E402

# jaxlint: disable=IMP01
jax.config.update("jax_platforms", "cpu")

from relayrl_tpu.parallel import (  # noqa: E402
    broadcast_from_coordinator,
    initialize_distributed,
    is_coordinator,
    make_mesh,
    make_sharded_update,
    place_batch,
    place_state,
)

info = initialize_distributed(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=rank)
assert info == {"multi_host": True, "process_id": rank, "num_processes": 2}, info
assert is_coordinator() == (rank == 0)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

assert jax.process_count() == 2
# Entry script: querying the freshly-initialized backend here IS the test.
# jaxlint: disable=IMP01
assert len(jax.devices()) == 8, jax.devices()
# jaxlint: disable=IMP01
assert len(jax.local_devices()) == 4

from relayrl_tpu.algorithms.reinforce import (  # noqa: E402
    ReinforceState,
    make_optimizers,
    make_reinforce_update,
)
from relayrl_tpu.models import build_policy  # noqa: E402

B, T, OBS, ACT = 8, 16, 6, 3
arch = {"kind": "mlp_discrete", "obs_dim": OBS, "act_dim": ACT,
        "hidden_sizes": [16, 16], "has_critic": True}
policy = build_policy(arch)
params = policy.init_params(jax.random.PRNGKey(0))
tx_pi, tx_vf = make_optimizers(params, 3e-4, 1e-3)
state = ReinforceState(params=params, pi_opt_state=tx_pi.init(params),
                       vf_opt_state=tx_vf.init(params),
                       rng=jax.random.PRNGKey(1), step=jnp.int32(0))

mesh = make_mesh({"dp": -1, "fsdp": 2, "tp": 1, "sp": 1})
update = make_reinforce_update(policy, 3e-4, 1e-3, train_vf_iters=4,
                               gamma=0.99, lam=0.95, with_baseline=True)
sharded_update = make_sharded_update(update, mesh, state)
state = place_state(state, mesh)

# Coordinator-asymmetric ingest: only rank 0 "receives" the batch (the
# trajectory sockets bind there); everyone else contributes zeros and takes
# the coordinator's copy from the broadcast.
rng = np.random.default_rng(42 if is_coordinator() else 7)
host_batch = {
    "obs": rng.standard_normal((B, T, OBS)).astype(np.float32),
    "act": rng.integers(0, ACT, (B, T)).astype(np.int32),
    "act_mask": np.ones((B, T, ACT), np.float32),
    "rew": rng.standard_normal((B, T)).astype(np.float32),
    "val": rng.standard_normal((B, T)).astype(np.float32),
    "logp": rng.standard_normal((B, T)).astype(np.float32),
    "valid": np.ones((B, T), np.float32),
    "last_val": np.zeros((B,), np.float32),
}
if not is_coordinator():
    host_batch = {k: np.zeros_like(v) for k, v in host_batch.items()}
host_batch = broadcast_from_coordinator(host_batch)
# Both ranks must now hold the coordinator's data.
coord_rng = np.random.default_rng(42)
np.testing.assert_array_equal(
    host_batch["obs"], coord_rng.standard_normal((B, T, OBS)).astype(np.float32))

batch = place_batch(host_batch, mesh)
state, metrics = sharded_update(state, batch)
loss_pi = float(metrics["LossPi"])
assert np.isfinite(loss_pi)

# SPMD agreement: the replicated metric must be identical on both ranks.
from jax.experimental import multihost_utils  # noqa: E402

gathered = multihost_utils.process_allgather(np.float32(loss_pi))
assert gathered.shape[0] == 2
np.testing.assert_allclose(gathered[0], gathered[1], rtol=0, atol=0)

# Checkpoint under multi-host: all processes participate in the orbax save
# on the shared directory, then restore and compare.
from relayrl_tpu.checkpoint import CheckpointManager  # noqa: E402

mgr = CheckpointManager(ckpt_dir)
mgr.save(1, state, wait=True)
restored, _, _ = mgr.restore(state)
for a, b in zip(jax.tree_util.tree_leaves(state),
                jax.tree_util.tree_leaves(restored)):
    # Multi-host arrays are not fully addressable; compare the local shards.
    np.testing.assert_array_equal(np.asarray(a.addressable_data(0)),
                                  np.asarray(b.addressable_data(0)))
mgr.close()

# Long-context across hosts: ring attention with the sp ring spanning
# BOTH processes (sp=8 over the global mesh — K/V chunks ppermute across
# the process boundary, the CPU-simulation of ICI/DCN ring hops). The
# single-process version runs in __graft_entry__.dryrun_multichip; this
# is the cross-process proof behind the "long-context and distributed
# are first-class" claim.
from relayrl_tpu.algorithms.reinforce import (  # noqa: E402
    make_optimizers as _mk_opts,
)

sp_mesh = make_mesh({"dp": 1, "fsdp": 1, "tp": 1, "sp": 8})
t_arch = {"kind": "transformer_discrete", "obs_dim": OBS, "act_dim": ACT,
          "d_model": 32, "n_layers": 1, "n_heads": 2,
          "max_seq_len": 64, "has_critic": True, "attention": "ring"}
t_policy = build_policy(t_arch)
t_params = t_policy.init_params(jax.random.PRNGKey(5))
t_tx_pi, t_tx_vf = _mk_opts(t_params, 3e-4, 1e-3)
t_state = ReinforceState(params=t_params,
                         pi_opt_state=t_tx_pi.init(t_params),
                         vf_opt_state=t_tx_vf.init(t_params),
                         rng=jax.random.PRNGKey(6), step=jnp.int32(0))
t_update = make_reinforce_update(t_policy, 3e-4, 1e-3, train_vf_iters=1,
                                 gamma=0.99, lam=0.95, with_baseline=True)
t_sharded = make_sharded_update(t_update, sp_mesh, t_state,
                                donate_state=False, shard_time=True)
t_rng = np.random.default_rng(9)
t_T = 64  # 8 time shards of 8 across the two-process ring
t_host = {
    "obs": t_rng.standard_normal((2, t_T, OBS)).astype(np.float32),
    "act": t_rng.integers(0, ACT, (2, t_T)).astype(np.int32),
    "act_mask": np.ones((2, t_T, ACT), np.float32),
    "rew": np.ones((2, t_T), np.float32),
    "val": np.zeros((2, t_T), np.float32),
    "logp": np.zeros((2, t_T), np.float32),
    "valid": np.ones((2, t_T), np.float32),
    "last_val": np.zeros((2,), np.float32),
}
if not is_coordinator():
    # Make the broadcast load-bearing (as in the dp section above): the
    # non-coordinator must get its data FROM the collective, not from a
    # coincidentally-equal seed.
    t_host = {k: np.zeros_like(v) for k, v in t_host.items()}
t_host = broadcast_from_coordinator(t_host)
t_new, t_metrics = t_sharded(place_state(t_state, sp_mesh),
                             place_batch(t_host, sp_mesh, shard_time=True))
ring_loss = float(t_metrics["LossPi"])
assert np.isfinite(ring_loss)
assert int(np.asarray(t_new.step.addressable_data(0))) == 1
ring_gathered = multihost_utils.process_allgather(np.float32(ring_loss))
np.testing.assert_allclose(ring_gathered[0], ring_gathered[1], rtol=0,
                           atol=0)
print(f"MULTIHOST_RING_OK rank={rank} loss_pi={ring_loss:.6f}", flush=True)

print(f"MULTIHOST_OK rank={rank} loss_pi={loss_pi:.6f}", flush=True)
