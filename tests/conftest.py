"""Test harness: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective paths are
validated on XLA's host platform with 8 virtual devices (the standard JAX
technique for testing pjit/shard_map topologies without a pod).
"""

import os

# Must be set before jax (or anything importing jax) is imported. Force —
# the ambient environment points JAX_PLATFORMS at the real TPU (axon), and
# unit tests doing per-step host transfers over the device tunnel are
# 100-1000× slower than CPU (and the bench owns the real chip).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# NOTE: do NOT point JAX_COMPILATION_CACHE_DIR at a persistent cache
# here. It looks like a free wall-clock win for the subprocess drills,
# but on this jaxlib build the cache intermittently SIGABRTs/segfaults
# the orbax async checkpoint saves (tests/test_checkpoint.py) —
# reproduced twice under ISSUE 17 and reverted.

# Plugins (jaxtyping) import jax before this conftest runs, and jax.config
# snapshots JAX_PLATFORMS at import — update the live config too, which works
# as long as no backend has been initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def tmp_cwd(tmp_path, monkeypatch):
    """Run a test inside a throwaway cwd (config auto-create writes there)."""
    monkeypatch.chdir(tmp_path)
    return tmp_path
