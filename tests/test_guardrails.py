"""Training-health guardrails (relayrl_tpu/guardrails/ + the server
wiring): ingest validation + poison-agent quarantine, the divergence
watchdog, last-known-good auto-rollback, and ingest backpressure.

The acceptance contract under test (ISSUE 8):

* the validator is the semantic trust boundary — non-finite /
  malformed-but-decodable trajectories never reach the learner plane,
  and a hostile payload cannot crash the validator itself;
* a poison-*emitting* agent is quarantined (typed nack where the
  transport has a back-channel), then auto-paroled;
* the watchdog's device probes are OBSERVERS: guardrails-on params are
  BIT-identical to guardrails-off for REINFORCE and PPO;
* a watchdog trip rolls the learner back to the newest healthy-tagged
  checkpoint with a consistent dedup ledger and a forced keyframe, and
  the rollback budget degrades to halt-and-alarm;
* non-finite params NEVER publish.
"""

import json
import time
import warnings

import numpy as np
import pytest

from relayrl_tpu.algorithms import build_algorithm
from relayrl_tpu.guardrails import (
    AdmissionController,
    DivergenceWatchdog,
    GuardProbes,
    QuarantineBook,
    params_tree_finite,
    trajectory_reward,
    validate_trajectory,
)
from relayrl_tpu.guardrails.watchdog import (
    PROBE_NONFINITE,
    PROBE_PARAM_NORM,
    PROBE_UPDATE_NORM,
)
from relayrl_tpu.types.action import ActionRecord
from relayrl_tpu.types.trajectory import serialize_actions

pytestmark = pytest.mark.guardrails

OBS_DIM, ACT_DIM = 4, 2


def _episode(n=4, seed=0, rew=None, obs_fill=None, with_v=True):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        data = {"logp_a": np.float32(-0.69)}
        if with_v:
            data["v"] = np.float32(rng.standard_normal())
        obs = (np.full((OBS_DIM,), obs_fill, np.float32)
               if obs_fill is not None
               else rng.standard_normal(OBS_DIM).astype(np.float32))
        recs.append(ActionRecord(
            obs=obs,
            act=np.int64(rng.integers(ACT_DIM)),
            rew=float(rew) if (rew is not None and i == n - 1)
            else float(rng.random()),
            data=data,
            done=(i == n - 1),
        ))
    return recs


def _decoded(rew=1.0, n=2, agent="a"):
    from relayrl_tpu.types.columnar import DecodedTrajectory

    return DecodedTrajectory(
        agent_id=agent, n_steps=n, n_records=n, marker_truncated=False,
        columns={"o": np.zeros((n, OBS_DIM), np.float32),
                 "a": np.zeros((n,), np.int32),
                 "r": np.array([0.0] * (n - 1) + [rew], np.float32),
                 "t": np.array([False] * (n - 1) + [True]),
                 "u": np.zeros((n,), np.uint8),
                 "x": np.zeros((n,), np.uint8)},
        aux={"v": np.zeros((n,), np.float32),
             "logp_a": np.zeros((n,), np.float32)})


# ---------------------------------------------------------------------------
# validate.py — the semantic trust boundary
# ---------------------------------------------------------------------------
class TestValidator:
    def test_clean_records_pass(self):
        assert validate_trajectory(_episode()) is None

    def test_clean_decoded_passes(self):
        assert validate_trajectory(_decoded()) is None

    @pytest.mark.parametrize("poison,reason", [
        (dict(rew=float("nan")), "nonfinite"),
        (dict(rew=float("inf")), "nonfinite"),
        (dict(obs_fill=float("nan")), "nonfinite"),
    ])
    def test_nonfinite_records_rejected(self, poison, reason):
        assert validate_trajectory(_episode(**poison)) == reason

    def test_nonfinite_decoded_rejected(self):
        assert validate_trajectory(_decoded(rew=float("nan"))) == "nonfinite"

    def test_schema_non_record_items(self):
        assert validate_trajectory(["not-a-record"]) == "schema"
        assert validate_trajectory(object()) == "schema"

    def test_schema_bad_reward_type(self):
        recs = _episode()
        recs[0] = ActionRecord(obs=recs[0].obs, act=recs[0].act,
                               rew="1.0", data=recs[0].data,
                               done=recs[0].done)
        assert validate_trajectory(recs) == "schema"

    def test_dtype_object_obs_rejected(self):
        recs = _episode()
        evil = np.array([object()], dtype=object)
        recs[0] = ActionRecord(obs=evil, act=recs[0].act, rew=0.0,
                               data=recs[0].data, done=recs[0].done)
        assert validate_trajectory(recs) == "dtype"

    def test_dtype_string_aux_is_inert(self):
        # Stable contract with the finite guard: string/bytes/bool aux
        # values never reach the training path, so they must not reject.
        recs = _episode()
        recs[0] = ActionRecord(obs=recs[0].obs, act=recs[0].act, rew=0.0,
                               data={"tag": "ep-1", "v": np.float32(0.1),
                                     "logp_a": np.float32(-0.1)},
                               done=recs[0].done)
        assert validate_trajectory(recs) is None

    def test_length_bound(self):
        assert validate_trajectory(_episode(n=8), max_steps=4) == "length"
        assert validate_trajectory(_episode(n=4), max_steps=4) is None
        assert validate_trajectory(_episode(n=8), max_steps=0) is None

    def test_decoded_shape_mismatch(self):
        item = _decoded(n=3)
        item.columns["r"] = np.zeros((2,), np.float32)  # wrong leading dim
        assert validate_trajectory(item) == "shape"

    def test_decoded_object_column(self):
        item = _decoded()
        item.aux["v"] = np.array([object(), object()], dtype=object)
        assert validate_trajectory(item) == "dtype"

    def test_decoded_non_array_column(self):
        item = _decoded()
        item.columns["o"] = [[0.0] * OBS_DIM, [0.0] * OBS_DIM]
        assert validate_trajectory(item) == "schema"

    def test_validator_never_raises(self):
        class Hostile:
            def __len__(self):
                return 2

            def __iter__(self):
                raise RuntimeError("weaponized payload")

        assert validate_trajectory(Hostile()) == "validator_error"

    def test_bfloat16_nan_rejected(self):
        ml_dtypes = pytest.importorskip("ml_dtypes")
        recs = _episode()
        bad = np.array([0.1, float("nan"), 0.2, 0.3], ml_dtypes.bfloat16)
        recs[1] = ActionRecord(obs=bad, act=recs[1].act, rew=recs[1].rew,
                               data=recs[1].data, done=recs[1].done)
        assert validate_trajectory(recs) == "nonfinite"

    def test_trajectory_reward_both_shapes(self):
        recs = _episode(rew=2.0, n=3)
        want = sum(r.rew for r in recs)
        assert trajectory_reward(recs) == pytest.approx(want)
        assert trajectory_reward(_decoded(rew=3.0)) == pytest.approx(3.0)
        assert trajectory_reward(object()) is None

    def test_params_tree_finite(self):
        good = {"w": np.ones((3,), np.float32),
                "step": np.int32(7)}  # int leaves carry no signal
        assert params_tree_finite(good)
        bad = {"w": np.array([1.0, float("nan")], np.float32)}
        assert not params_tree_finite(bad)
        inf = {"w": np.array([np.inf], np.float32)}
        assert not params_tree_finite(inf)


class TestRejectionCounting:
    def test_every_rejection_reason_is_counted(self):
        """The Guardrails facade counts EVERY rejection under its stable
        reason label (the fuzz suite's counting contract, runnable
        without hypothesis)."""
        from relayrl_tpu import telemetry
        from relayrl_tpu.config.loader import ConfigLoader
        from relayrl_tpu.guardrails import Guardrails
        from relayrl_tpu.guardrails.validate import REASONS

        telemetry.reset_for_tests()
        telemetry.set_registry(telemetry.Registry(run_id="guard-test"))
        try:
            params = ConfigLoader("REINFORCE").get_guardrails_params()
            params["max_steps"] = 4
            g = Guardrails(params)
            rejects = [
                _episode(rew=float("nan")),       # nonfinite
                _episode(n=9),                    # length
                ["junk"],                         # schema
                object(),                         # schema
            ]
            for item in rejects:
                assert g.validate("fuzzer", item) is None
            snap = telemetry.get_registry().snapshot()
            rows = [m for m in snap["metrics"]
                    if m["name"] == "relayrl_guard_rejected_total"]
            assert sum(m["value"] for m in rows) == len(rejects)
            assert {m["labels"]["reason"] for m in rows} <= set(REASONS)
        finally:
            telemetry.reset_for_tests()

    def test_validation_off_still_feeds_reward_detector(self):
        """``ingest_validation: "off"`` stands down the validator and
        strikes — NOT a detector the operator armed: the reward-collapse
        feed must see every admitted trajectory in every mode."""
        from relayrl_tpu.config.loader import ConfigLoader
        from relayrl_tpu.guardrails import Guardrails

        params = ConfigLoader("REINFORCE").get_guardrails_params()
        params["reward_collapse_drop"] = 5.0
        for mode in ("off", "warn", "enforce"):
            params["ingest_validation"] = mode
            g = Guardrails(params)
            assert g.validate("a", _episode()) is not None
            assert len(g.watchdog._rewards) == 1, mode


# ---------------------------------------------------------------------------
# quarantine.py — strike accounting + parole lifecycle
# ---------------------------------------------------------------------------
class TestQuarantine:
    def test_below_threshold_stays_clean(self):
        book = QuarantineBook(strike_threshold=3, strike_window_s=60)
        assert book.strike("a", "nonfinite") is False
        assert book.strike("a", "nonfinite") is False
        assert not book.is_quarantined("a")

    def test_threshold_quarantines(self):
        book = QuarantineBook(strike_threshold=2, strike_window_s=60,
                              cooldown_s=300)
        assert book.strike("a", "nonfinite") is False
        assert book.strike("a", "nonfinite") is True
        assert book.is_quarantined("a")
        assert not book.is_quarantined("b")  # per-agent isolation
        assert book.quarantines_total == 1
        assert 0 < book.retry_after("a") <= 300
        assert book.retry_after("b") == 0.0

    def test_strikes_age_out_of_window(self):
        book = QuarantineBook(strike_threshold=2, strike_window_s=0.05)
        book.strike("a", "nonfinite")
        time.sleep(0.08)
        # the first strike aged out: this one is strike #1 again
        assert book.strike("a", "nonfinite") is False
        assert not book.is_quarantined("a")

    def test_lazy_parole_after_cooldown(self):
        book = QuarantineBook(strike_threshold=1, cooldown_s=0.05)
        assert book.strike("a", "nonfinite") is True
        assert book.is_quarantined("a")
        time.sleep(0.08)
        assert not book.is_quarantined("a")  # parole evaluated lazily
        assert book.paroles_total == 1
        # re-offending re-quarantines from a clean slate
        assert book.strike("a", "nonfinite") is True

    def test_accounting(self):
        book = QuarantineBook(strike_threshold=2)
        book.strike("a", "nonfinite")
        book.strike("b", "schema")
        book.strike("b", "schema")
        acct = book.accounting()
        assert acct["quarantined"] == ["b"]
        assert acct["strikes_pending"] == {"a": 1}
        assert acct["quarantines_total"] == 1


# ---------------------------------------------------------------------------
# admission.py — bounded ingest + shed policies
# ---------------------------------------------------------------------------
class TestAdmission:
    def test_admits_under_limit(self):
        adm = AdmissionController(soft_limit=4)
        assert adm.admit("a") == "admit"
        adm.note_enqueued("a")
        assert adm.accounting()["depth"] == 1

    def test_agent_fair_share_sheds_first(self):
        adm = AdmissionController(soft_limit=10, agent_share=0.2)
        assert adm.agent_cap == 2
        for _ in range(2):
            assert adm.admit("hog") == "admit"
            adm.note_enqueued("hog")
        assert adm.admit("hog") == "shed_agent"  # over its share
        assert adm.admit("polite") == "admit"    # fleet unaffected
        assert adm.accounting()["sheds"]["agent_share"] == 1

    def test_drop_oldest_evicts_at_limit(self):
        adm = AdmissionController(soft_limit=2, policy="drop_oldest",
                                  agent_share=1.0)
        for agent in ("a", "b"):
            adm.admit(agent)
            adm.note_enqueued(agent)
        assert adm.admit("c") == "evict"
        # the caller evicts the oldest, then enqueues the new arrival
        adm.note_dequeued("a")
        adm.note_enqueued("c")
        assert adm.accounting()["depth"] == 2
        assert adm.accounting()["sheds"]["drop_oldest"] == 1

    def test_nack_policy_refuses_at_limit(self):
        adm = AdmissionController(soft_limit=1, policy="nack",
                                  agent_share=1.0, retry_after_s=2.5)
        adm.admit("a")
        adm.note_enqueued("a")
        assert adm.admit("b") == "nack"
        assert adm.retry_after_s == 2.5
        assert adm.accounting()["sheds"]["nack"] == 1

    def test_dequeue_releases_pressure(self):
        adm = AdmissionController(soft_limit=1, policy="nack",
                                  agent_share=1.0)
        adm.admit("a")
        adm.note_enqueued("a")
        adm.note_dequeued("a")
        assert adm.admit("b") == "admit"


# ---------------------------------------------------------------------------
# watchdog.py — detectors + probes
# ---------------------------------------------------------------------------
class TestWatchdog:
    def _dog(self, **kw):
        return DivergenceWatchdog(**kw)

    def test_nonfinite_probe_trips(self):
        dog = self._dog()
        dog.observe_dispatch(1, {PROBE_NONFINITE: 3.0,
                                 PROBE_PARAM_NORM: 1.0})
        trip = dog.poll(fenced_count=1)
        assert trip is not None and trip.signal == "nonfinite_params"
        assert dog.trips_total == 1 and not dog.healthy()

    def test_param_norm_threshold(self):
        dog = self._dog(max_param_norm=10.0)
        dog.observe_dispatch(1, {PROBE_NONFINITE: 0.0,
                                 PROBE_PARAM_NORM: 5.0})
        assert dog.poll(1) is None and dog.healthy()
        dog.observe_dispatch(2, {PROBE_NONFINITE: 0.0,
                                 PROBE_PARAM_NORM: 50.0})
        trip = dog.poll(2)
        assert trip.signal == "param_norm" and trip.value == 50.0

    def test_param_norm_inf_trips_even_unset_threshold(self):
        # sumsq overflow → inf norm is divergence regardless of knob
        dog = self._dog(max_param_norm=0.0)
        dog.observe_dispatch(1, {PROBE_PARAM_NORM: float("inf")})
        assert dog.poll(1).signal == "param_norm"

    def test_update_norm_threshold(self):
        dog = self._dog(max_update_norm=1.0)
        dog.observe_dispatch(1, {PROBE_UPDATE_NORM: 4.2})
        assert dog.poll(1).signal == "update_norm"

    def test_loss_nonfinite_always_trips(self):
        dog = self._dog()
        dog.observe_dispatch(1, {"LossPi": float("nan")})
        assert dog.poll(1).signal == "loss_nonfinite"

    def test_loss_spike_over_rolling_median(self):
        dog = self._dog(loss_spike_factor=3.0, loss_window=4)
        for i, loss in enumerate([1.0, 1.1, 0.9], start=1):
            dog.observe_dispatch(i, {"LossPi": loss})
            assert dog.poll(i) is None
        dog.observe_dispatch(4, {"LossPi": 10.0})
        trip = dog.poll(4)
        assert trip is not None and trip.signal == "loss_spike"

    def test_reward_collapse(self):
        dog = self._dog(reward_collapse_drop=5.0, reward_window=4)
        for _ in range(4):
            dog.observe_reward(10.0)
        assert dog.poll(0) is None  # establishes the best mean
        for _ in range(4):
            dog.observe_reward(0.0)
        trip = dog.poll(0)
        assert trip is not None and trip.signal == "reward_collapse"

    def test_fence_gating(self):
        # probes for an unfenced dispatch must not resolve yet
        dog = self._dog()
        dog.observe_dispatch(5, {PROBE_NONFINITE: 1.0})
        assert dog.poll(fenced_count=4) is None
        assert dog.poll(fenced_count=5).signal == "nonfinite_params"

    def test_external_trip_surfaces_once(self):
        dog = self._dog()
        dog.trip_external("publish_nonfinite")
        assert not dog.healthy()
        trip = dog.poll(0)
        assert trip.signal == "publish_nonfinite"
        assert dog.poll(0) is None  # consumed

    def test_pending_probe_reads_unhealthy(self):
        """An unresolved probe may be the one carrying the NaN — the
        healthy-at-save tag must not vouch for it. The signal-path
        final checkpoint races the fence: quiesce resolves the device
        scalars but only a poll evaluates them, so a dispatch whose
        probe is still queued reads unhealthy until polled clean."""
        dog = self._dog()
        assert dog.healthy()  # nothing dispatched yet
        dog.observe_dispatch(1, {PROBE_NONFINITE: 1.0})
        assert not dog.healthy()            # queued, unevaluated
        assert dog.poll(fenced_count=0) is None  # still unfenced
        assert not dog.healthy()
        assert dog.poll(fenced_count=1).signal == "nonfinite_params"
        assert not dog.healthy()

    def test_reset_after_rollback_rearms(self):
        dog = self._dog(loss_spike_factor=3.0, loss_window=4,
                        reward_collapse_drop=1.0, reward_window=4)
        dog.observe_dispatch(1, {PROBE_NONFINITE: 1.0})
        assert dog.poll(1) is not None
        assert not dog.healthy()
        dog.reset_after_rollback()
        assert dog.healthy()
        assert dog.accounting()["pending_probes"] == 0
        # detector windows rebuilt from scratch on the restored line
        dog.observe_dispatch(2, {"LossPi": 1.0})
        assert dog.poll(2) is None


class TestGuardProbes:
    def test_probe_values(self):
        import jax

        probes = GuardProbes(update_norm=True)
        old = {"w": np.array([3.0, 4.0], np.float32)}
        copy = probes.pre_update(old)
        new = {"w": np.array([4.0, 5.0], np.float32)}
        out = probes.post_update(copy, new)
        resolved = {k: float(v) for k, v in out.items()}
        assert resolved[PROBE_NONFINITE] == 0
        assert resolved[PROBE_PARAM_NORM] == pytest.approx(
            float(np.sqrt(16 + 25)), rel=1e-6)
        assert resolved[PROBE_UPDATE_NORM] == pytest.approx(
            float(np.sqrt(2)), rel=1e-6)
        del jax

    def test_nonfinite_count(self):
        probes = GuardProbes(update_norm=False)
        assert probes.pre_update({"w": np.zeros(2, np.float32)}) is None
        out = probes.post_update(None, {
            "w": np.array([1.0, float("nan"), float("inf")], np.float32)})
        assert float(out[PROBE_NONFINITE]) == 2

    def test_integer_leaves_ignored(self):
        probes = GuardProbes(update_norm=False)
        out = probes.post_update(None, {"step": np.int32(7)})
        assert float(out[PROBE_NONFINITE]) == 0
        assert float(out[PROBE_PARAM_NORM]) == 0

    def test_probes_do_not_mutate_params(self):
        probes = GuardProbes(update_norm=True)
        tree = {"w": np.array([1.0, 2.0], np.float32)}
        before = tree["w"].copy()
        copy = probes.pre_update(tree)
        probes.post_update(copy, tree)
        np.testing.assert_array_equal(tree["w"], before)

    def test_actor_critic_states_are_probeable(self, tmp_cwd):
        """SAC/DDPG/TD3 keep trainable params across ``*_params`` fields
        instead of ``state.params`` — the probe tree must collect them
        (targets excluded) and the probed update must still train (the
        tier-1 regression: a probe AttributeError used to kill every
        actor-critic update when guardrails were on by default)."""
        algo = build_algorithm(
            "SAC", obs_dim=OBS_DIM, act_dim=2, env_dir=str(tmp_cwd),
            hidden_sizes=[8], batch_size=8, update_after=8,
            update_every=8,
            logger_kwargs={"output_dir": str(tmp_cwd / "logs")})
        tree = algo._guard_probe_tree()
        assert set(tree) >= {"actor_params", "critic_params"}
        assert not any(k.startswith("target_") for k in tree)
        algo._guard_probes = GuardProbes(update_norm=True)
        rng = np.random.default_rng(0)
        for _ in range(4):
            ep = [ActionRecord(
                obs=rng.standard_normal(OBS_DIM).astype(np.float32),
                act=rng.uniform(-1, 1, 2).astype(np.float32),
                rew=float(rng.random()), done=(i == 3))
                for i in range(4)]
            algo.receive_trajectory(ep)
        assert algo.version > 0, "SAC never updated with probes attached"
        assert algo._guard_probes is not None, \
            "probes self-disabled — the probe tree failed"
        assert float(algo._last_metrics[PROBE_NONFINITE]) == 0


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------
class TestConfig:
    def test_defaults(self, tmp_cwd):
        from relayrl_tpu.config.loader import ConfigLoader

        params = ConfigLoader("REINFORCE").get_guardrails_params()
        assert params["enabled"] is True
        assert params["ingest_validation"] == "enforce"
        assert params["strike_threshold"] == 3
        assert params["shed_policy"] == "drop_oldest"

    def test_malformed_values_degrade_to_defaults(self, tmp_path,
                                                  monkeypatch):
        from relayrl_tpu.config.loader import ConfigLoader

        monkeypatch.chdir(tmp_path)
        cfg = {"guardrails": {"strike_threshold": "bogus",
                              "loss_window": -3,
                              "shed_policy": "weird",
                              "ingest_validation": "nope",
                              "agent_share": 99,
                              "max_steps": "x"}}
        path = tmp_path / "cfg.json"
        path.write_text(json.dumps(cfg))
        params = ConfigLoader("REINFORCE",
                              str(path)).get_guardrails_params()
        assert params["strike_threshold"] == 3
        assert params["loss_window"] == 4      # clamped floor
        assert params["shed_policy"] == "drop_oldest"
        assert params["ingest_validation"] == "enforce"
        assert params["agent_share"] == 99.0 or params["agent_share"] >= 0
        assert params["max_steps"] is None

    def test_explicit_zero_max_steps_disables_length_bound(
            self, tmp_path, monkeypatch):
        """``max_steps: 0`` is the documented length-bound opt-out —
        build_guardrails must not conflate it with null (which derives
        the bound from max_traj_length)."""
        from relayrl_tpu.config.loader import ConfigLoader
        from relayrl_tpu.guardrails import build_guardrails

        monkeypatch.chdir(tmp_path)
        path = tmp_path / "cfg0.json"
        path.write_text(json.dumps({"guardrails": {"max_steps": 0}}))
        g = build_guardrails(ConfigLoader("REINFORCE", str(path)))
        assert g.params["max_steps"] == 0
        # and null still derives from max_traj_length
        path2 = tmp_path / "cfg_null.json"
        path2.write_text(json.dumps({"guardrails": {"max_steps": None}}))
        loader = ConfigLoader("REINFORCE", str(path2))
        g2 = build_guardrails(loader)
        assert g2.params["max_steps"] == loader.get_max_traj_length() > 0

    def test_null_trip_threshold_disables_detector(self, tmp_path,
                                                   monkeypatch):
        """default_config documents "0/null disables that detector" for
        the trip thresholds — an explicit null must map to 0 (off), not
        back to a default that keeps the detector armed."""
        from relayrl_tpu.config.loader import ConfigLoader

        monkeypatch.chdir(tmp_path)
        path = tmp_path / "null_thr.json"
        path.write_text(json.dumps(
            {"guardrails": {"max_param_norm": None,
                            "strike_window_s": None}}))
        params = ConfigLoader("REINFORCE", str(path)).get_guardrails_params()
        assert params["max_param_norm"] == 0.0   # null = detector OFF
        assert params["strike_window_s"] == 60.0  # non-threshold: default

    def test_unknown_top_level_section_warns_with_hint(self, tmp_path,
                                                       monkeypatch):
        from relayrl_tpu.config.loader import ConfigLoader

        monkeypatch.chdir(tmp_path)
        path = tmp_path / "typo.json"
        path.write_text(json.dumps({"guardrials": {"enabled": False}}))
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            ConfigLoader("REINFORCE", str(path))
        msgs = [str(w.message) for w in caught
                if "not recognized" in str(w.message)]
        assert any("guardrials" in m and "guardrails" in m for m in msgs), \
            msgs
        # once per process per file: a second loader stays silent
        with warnings.catch_warnings(record=True) as again:
            warnings.simplefilter("always")
            ConfigLoader("REINFORCE", str(path))
        assert not [w for w in again
                    if "not recognized" in str(w.message)]

    def test_unknown_key_inside_known_section_warns(self, tmp_path,
                                                    monkeypatch):
        from relayrl_tpu.config.loader import ConfigLoader

        monkeypatch.chdir(tmp_path)
        path = tmp_path / "typo2.json"
        path.write_text(json.dumps(
            {"guardrails": {"strike_treshold": 5},
             "transport": {"keyframe_intervall": 3}}))
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            ConfigLoader("REINFORCE", str(path))
        msgs = [str(w.message) for w in caught
                if "not recognized" in str(w.message)]
        assert any("guardrails.strike_treshold" in m
                   and "strike_threshold" in m for m in msgs), msgs
        assert any("transport.keyframe_intervall" in m for m in msgs)

    def test_algorithm_hyperparams_exempt_and_comments_exempt(
            self, tmp_path, monkeypatch):
        from relayrl_tpu.config.loader import ConfigLoader

        monkeypatch.chdir(tmp_path)
        path = tmp_path / "ok.json"
        path.write_text(json.dumps(
            {"algorithms": {"REINFORCE": {"my_custom_hyperparam": 1}},
             "_comment": "free-form notes",
             "guardrails": {"_comment_strikes": "why 3", "enabled": True}}))
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            ConfigLoader("REINFORCE", str(path))
        assert not [w for w in caught
                    if "not recognized" in str(w.message)]


# ---------------------------------------------------------------------------
# checkpoint ring: healthy-at-save tags + last-known-good restore
# ---------------------------------------------------------------------------
class TestCheckpointRing:
    def _algo(self, tmp_cwd):
        return build_algorithm(
            "REINFORCE", obs_dim=OBS_DIM, act_dim=ACT_DIM,
            env_dir=str(tmp_cwd), traj_per_epoch=1, hidden_sizes=[8],
            with_vf_baseline=False,
            logger_kwargs={"output_dir": str(tmp_cwd / "logs")})

    def test_healthy_ring_and_restore(self, tmp_cwd):
        from relayrl_tpu.checkpoint import (
            checkpoint_algorithm,
            restore_latest_healthy,
        )

        algo = self._algo(tmp_cwd)
        ckdir = str(tmp_cwd / "ck")
        algo.force_version(1)
        checkpoint_algorithm(algo, ckdir, wait=True,
                             extra_meta={"healthy": True})
        algo.force_version(2)
        checkpoint_algorithm(algo, ckdir, wait=True,
                             extra_meta={"healthy": True})
        algo.force_version(3)
        checkpoint_algorithm(algo, ckdir, wait=True,
                             extra_meta={"healthy": False})
        mgr = algo._ckpt_mgr
        assert mgr.healthy_steps() == [1, 2]
        assert mgr.read_extra(3)["healthy"] is False
        algo.force_version(9)
        step = restore_latest_healthy(algo, ckdir)
        assert step == 2
        assert algo.version == 2

    def test_no_healthy_step_raises(self, tmp_cwd):
        from relayrl_tpu.checkpoint import (
            checkpoint_algorithm,
            restore_latest_healthy,
        )

        algo = self._algo(tmp_cwd)
        ckdir = str(tmp_cwd / "ck")
        algo.force_version(1)
        checkpoint_algorithm(algo, ckdir, wait=True,
                             extra_meta={"healthy": False})
        with pytest.raises(FileNotFoundError):
            restore_latest_healthy(algo, ckdir)

    def test_untagged_step_never_a_rollback_target(self, tmp_cwd):
        # Pre-guardrails checkpoints carry no tag: conservatively
        # unhealthy (the operator can still restore them explicitly).
        from relayrl_tpu.checkpoint import checkpoint_algorithm

        algo = self._algo(tmp_cwd)
        ckdir = str(tmp_cwd / "ck")
        algo.force_version(1)
        checkpoint_algorithm(algo, ckdir, wait=True)  # no extra_meta
        assert algo._ckpt_mgr.healthy_steps() == []


# ---------------------------------------------------------------------------
# typed ingest nacks through the spool
# ---------------------------------------------------------------------------
class TestSpoolNacks:
    def test_quarantine_nack_discards_entry(self):
        from relayrl_tpu.runtime.spool import TrajectorySpool
        from relayrl_tpu.transport.base import (
            NACK_QUARANTINED,
            IngestNack,
        )

        calls = []

        def send_fn(payload, tagged):
            calls.append(tagged)
            raise IngestNack(NACK_QUARANTINED, "agent quarantined", 120.0)

        spool = TrajectorySpool(send_fn=send_fn)
        spool.send(b"poison", "evil")
        # delivered-and-refused: nothing retained, breaker untouched
        assert spool.depth == 0
        assert len(calls) == 1  # the nack escaped the retry loop
        assert spool.breaker.allow()

    def test_overload_nack_retains_for_replay(self):
        from relayrl_tpu.runtime.spool import TrajectorySpool
        from relayrl_tpu.transport.base import (
            NACK_OVERLOADED,
            IngestNack,
        )

        verdicts = [IngestNack(NACK_OVERLOADED, "overloaded", 0.5)]

        def send_fn(payload, tagged):
            if verdicts:
                raise verdicts.pop()

        spool = TrajectorySpool(send_fn=send_fn)
        spool.send(b"traj", "a")
        assert spool.depth == 1      # kept: the server asked for later
        assert spool.breaker.allow()   # an answer, not a failure
        assert spool.replay() == 1     # pressure cleared: replay lands
        assert spool.depth == 1      # at-least-once: retained until ack'd window moves

    def test_overload_nack_replays_on_live_connection(self):
        """Overload-nacked entries must come back WITHOUT a reconnect
        or breaker transition: the connection never broke, so the only
        triggers left are fresh sends — once the server's retry_after
        lapses, the next send fires a replay pass (pre-fix they sat
        spooled until end-of-run flush())."""
        from relayrl_tpu.runtime.spool import TrajectorySpool
        from relayrl_tpu.transport.base import (
            NACK_OVERLOADED,
            IngestNack,
        )

        delivered = []
        verdicts = [IngestNack(NACK_OVERLOADED, "overloaded", 0.0)]

        def send_fn(payload, tagged):
            if verdicts:
                raise verdicts.pop()
            delivered.append(tagged)

        spool = TrajectorySpool(send_fn=send_fn)
        spool.send(b"first", "a")   # nacked: retained, redelivery due
        assert spool.depth == 1 and not delivered
        assert spool._replay_due is not None
        time.sleep(0.3)             # past the clamped retry_after floor
        spool.send(b"second", "a")  # fresh send on the live connection
        # the fresh send landed AND the due replay re-shipped the window
        assert any(t.endswith("#s1") for t in delivered), delivered
        assert spool._replay_due is None

    def test_wire_failure_still_counts_against_breaker(self):
        from relayrl_tpu.runtime.spool import TrajectorySpool
        from relayrl_tpu.transport.retry import CircuitBreaker, RetryPolicy

        def send_fn(payload, tagged):
            raise ConnectionError("down")

        spool = TrajectorySpool(
            send_fn=send_fn,
            retry=RetryPolicy(base_delay_s=0.01, max_delay_s=0.01,
                              deadline_s=0.05, max_attempts=1),
            breaker=CircuitBreaker("t", failure_threshold=1,
                                   reset_timeout_s=60.0))
        spool.send(b"traj", "a")
        assert spool.depth == 1
        assert not spool.breaker.allow()  # real failures open the breaker


class TestReplayScrub:
    """Warn-posture decontamination: with the off-policy finite belt
    standing down, admitted poison in the replay ring must not survive
    a rollback (it would re-diverge every post-restore update until the
    budget burns down to halt)."""

    def _fill(self, buf, n, rng, poison_at=()):
        for i in range(n):
            rew = float("nan") if i in poison_at else float(rng.random())
            buf._put(rng.standard_normal(3).astype(np.float32),
                     rng.uniform(-1, 1, 2).astype(np.float32), rew,
                     rng.standard_normal(3).astype(np.float32), 0.0,
                     np.ones(2, np.float32))

    def test_scrub_drops_only_poison(self):
        from relayrl_tpu.data.step_buffer import StepReplayBuffer

        buf = StepReplayBuffer(obs_dim=3, act_dim=2, capacity=16,
                               discrete=False)
        rng = np.random.default_rng(0)
        self._fill(buf, 6, rng, poison_at=(1, 4))
        buf.obs[2, 0] = np.inf  # poison a second field class too
        assert buf.scrub_nonfinite() == 3
        assert buf.size == 3
        for name in ("obs", "obs2", "act", "mask2", "rew", "done"):
            assert np.isfinite(getattr(buf, name)[: buf.size]).all()
        assert buf.scrub_nonfinite() == 0  # idempotent on a clean ring

    def test_scrub_wrapped_ring_keeps_chronological_order(self):
        from relayrl_tpu.data.step_buffer import StepReplayBuffer

        buf = StepReplayBuffer(obs_dim=3, act_dim=2, capacity=4,
                               discrete=False)
        rng = np.random.default_rng(1)
        self._fill(buf, 6, rng)          # wraps: ptr=2, size=4
        marker = buf.rew[(buf.ptr + 1) % buf.capacity]  # 2nd-oldest kept
        buf.rew[buf.ptr] = np.nan        # poison the oldest survivor
        assert buf.scrub_nonfinite() == 1
        assert buf.size == 3 and buf.rew[0] == marker

    def test_warn_mode_rollback_scrubs_the_ring(self, tmp_cwd):
        algo = build_algorithm(
            "SAC", obs_dim=3, act_dim=2, env_dir=str(tmp_cwd),
            hidden_sizes=[8], batch_size=8, update_after=10_000,
            logger_kwargs={"output_dir": str(tmp_cwd / "logs")})
        algo.ingest_finite_guard = False  # the warn posture stands it down
        rng = np.random.default_rng(2)

        def ep(poison):
            return [ActionRecord(
                obs=rng.standard_normal(3).astype(np.float32),
                act=rng.uniform(-1, 1, 2).astype(np.float32),
                rew=float("nan") if (poison and i == 1)
                else float(rng.random()),
                done=(i == 3)) for i in range(4)]

        algo.accumulate(ep(poison=False))
        algo.accumulate(ep(poison=True))   # admitted: the belt is down
        assert not np.isfinite(algo.buffer.rew[: algo.buffer.size]).all()
        before = algo.buffer.size
        algo.reset_ingest_buffers()        # the rollback path's call
        assert algo.buffer.size == before - 1
        assert np.isfinite(algo.buffer.rew[: algo.buffer.size]).all()
        # enforce posture: the ring is finite by construction — kept
        algo.ingest_finite_guard = True
        algo.reset_ingest_buffers()
        assert algo.buffer.size == before - 1


class TestGrpcNackLive:
    def test_quarantine_nack_rides_the_wire(self, tmp_cwd):
        """The full back-channel loop on a live gRPC pair: a poison
        stream quarantines the agent server-side, the next send comes
        back as a typed nack, and the agent's spool DISCARDS the entry
        (counted in relayrl_spool_nacked_total) instead of retaining
        poison for replay."""
        pytest.importorskip("grpc")
        import sys

        sys.path.insert(0, str(__import__("pathlib").Path(
            __file__).parent))
        from _util import free_port

        from relayrl_tpu import telemetry
        from relayrl_tpu.runtime.agent import Agent
        from relayrl_tpu.runtime.server import TrainingServer

        telemetry.reset_for_tests()
        telemetry.set_registry(telemetry.Registry(run_id="grpc-nack"))
        cfg = {"guardrails": {"strike_threshold": 1,
                              "quarantine_cooldown_s": 300.0}}
        path = tmp_cwd / "grpc_guard.json"
        path.write_text(json.dumps(cfg))
        addr = f"127.0.0.1:{free_port()}"
        # native_grpc=False pins the pure-grpcio servicer — the plane
        # that carries the typed nack back-channel (the native C++ gRPC
        # server acks in C++ before Python sees the send, so quarantine
        # there sheds server-side like the broadcast planes).
        server = TrainingServer(
            "REINFORCE", obs_dim=OBS_DIM, act_dim=ACT_DIM,
            env_dir=str(tmp_cwd), config_path=str(path),
            server_type="grpc", bind_addr=addr, native_grpc=False,
            hyperparams={"traj_per_epoch": 100, "hidden_sizes": [8],
                         "with_vf_baseline": False})
        try:
            agent = Agent(server_type="grpc", server_addr=addr,
                          handshake_timeout_s=30, seed=0, probe=False)
            try:
                def play(n, rew):
                    for _ in range(n):
                        agent.request_for_action(
                            np.zeros(OBS_DIM, np.float32))
                    agent.flag_last_action(rew, terminated=True)

                play(2, float("nan"))  # strike 1 of 1 → quarantine
                deadline = time.monotonic() + 30
                while (server.guardrails.quarantine.quarantines_total < 1
                       and time.monotonic() < deadline):
                    time.sleep(0.02)
                assert server.guardrails.quarantine.quarantines_total == 1
                # quarantined: the NEXT (clean) send nacks on the wire
                # and the spool discards it — poison-agent sends never
                # pile up for replay (the PRE-quarantine entry stays
                # retained: successful sends hold their at-least-once
                # replay window as always)
                depth_before = agent.spool.depth
                play(2, 1.0)
                snap = telemetry.get_registry().snapshot()
                nacked = sum(m["value"] for m in snap["metrics"]
                             if m["name"] == "relayrl_spool_nacked_total")
                assert nacked >= 1, "the typed nack never reached the spool"
                assert agent.spool.depth == depth_before, \
                    "a quarantine-nacked entry was retained"
                # breaker untouched: a nack is an answer, not a failure
                assert agent.spool.breaker.allow()
            finally:
                agent.disable_agent()
        finally:
            server.disable_server()
            telemetry.reset_for_tests()


# ---------------------------------------------------------------------------
# server integration: the assembled plane
# ---------------------------------------------------------------------------
class StubTransport:
    def __init__(self):
        self.published = []
        self.on_trajectory = None
        self.on_trajectory_decoded = None
        self.get_model = None
        self.on_register = None
        self.on_unregister = None
        self.check_ingest = None

    def start(self):
        pass

    def stop(self):
        pass

    def publish_model(self, version, raw):
        self.published.append((int(version), len(raw)))


@pytest.fixture
def guard_server_factory(tmp_cwd, monkeypatch):
    """TrainingServer over a stub transport with a guardrails config
    written to disk; returns (server, stub)."""
    import relayrl_tpu.runtime.server as srv_mod

    def make(guardrails=None, learner=None, hp=None, start=True,
             algorithm="REINFORCE"):
        stub = StubTransport()
        monkeypatch.setattr(srv_mod, "make_server_transport",
                            lambda *a, **k: stub)
        cfg = {}
        if guardrails is not None:
            cfg["guardrails"] = guardrails
        if learner is not None:
            cfg["learner"] = learner
        path = tmp_cwd / "guard_config.json"
        path.write_text(json.dumps(cfg))
        hyper = {"traj_per_epoch": 2, "hidden_sizes": [8],
                 "with_vf_baseline": False, "seed_salt": 0, **(hp or {})}
        server = srv_mod.TrainingServer(
            algorithm, obs_dim=OBS_DIM, act_dim=ACT_DIM,
            env_dir=str(tmp_cwd), config_path=str(path),
            hyperparams=hyper, start=start)
        return server, stub

    return make


class TestServerIngestGuard:
    def test_poison_stream_rejected_struck_quarantined(
            self, guard_server_factory):
        srv, _ = guard_server_factory(
            guardrails={"strike_threshold": 2, "quarantine_cooldown_s": 300})
        try:
            srv.wait_warmup(120)
            poison = serialize_actions(_episode(rew=float("nan")))
            clean = serialize_actions(_episode(seed=7))
            srv._on_trajectory("evil", poison)
            srv._on_trajectory("evil", poison)   # strike 2 → quarantine
            srv._on_trajectory("good", clean)
            deadline = time.monotonic() + 30
            while (srv.guardrails.quarantine.quarantines_total < 1
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            acct = srv.guardrails_accounting()
            assert acct["quarantine"]["quarantined"] == ["evil"]
            assert acct["quarantine"]["quarantines_total"] == 1
            # quarantined agent's sends shed server-side now
            srv._on_trajectory("evil", clean)
            deadline = time.monotonic() + 30
            while (srv.stats["trajectories"] < 1
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            # only the good agent's episode reached the learner plane
            assert srv.stats["trajectories"] == 1
            params = __import__("jax").device_get(
                srv.algorithm.state.params)
            import jax

            for leaf in jax.tree_util.tree_leaves(params):
                assert np.isfinite(np.asarray(leaf)).all()
        finally:
            srv.disable_server()

    def test_check_ingest_verdicts(self, guard_server_factory):
        from relayrl_tpu.transport.base import (
            NACK_OVERLOADED,
            NACK_QUARANTINED,
        )

        srv, _ = guard_server_factory(
            guardrails={"strike_threshold": 1, "shed_policy": "nack",
                        "ingest_soft_limit": 1,
                        "quarantine_cooldown_s": 300,
                        "nack_retry_after_s": 2.0},
            start=False)
        try:
            assert srv._check_ingest("anyone") is None
            srv.guardrails.quarantine.strike("evil", "nonfinite")
            code, reason, retry = srv._check_ingest("evil")
            assert code == NACK_QUARANTINED and retry > 0
            # seq-tagged envelope ids resolve to the logical agent
            code, _, _ = srv._check_ingest("evil#s7")
            assert code == NACK_QUARANTINED
            # overload under the nack shed policy
            srv.guardrails.admission.note_enqueued("x")
            code, reason, retry = srv._check_ingest("other")
            assert code == NACK_OVERLOADED and retry == 2.0
        finally:
            srv.disable_server()

    def test_warn_mode_admits_but_strikes(self, guard_server_factory):
        srv, _ = guard_server_factory(
            guardrails={"ingest_validation": "warn",
                        "strike_threshold": 100})
        try:
            srv.wait_warmup(120)
            # warn mode stands the per-algorithm belt down too
            assert srv.algorithm.ingest_finite_guard is False
            poison = serialize_actions(_episode(rew=float("nan")))
            srv._on_trajectory("sloppy", poison)
            deadline = time.monotonic() + 30
            while (srv.stats["trajectories"] < 1
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            # admitted (observe-only) AND struck
            assert srv.stats["trajectories"] == 1
            acct = srv.guardrails_accounting()
            assert acct["quarantine"]["strikes_pending"].get("sloppy") == 1
        finally:
            srv.disable_server()

    def test_disabled_guardrails_build_nothing(self, guard_server_factory):
        srv, _ = guard_server_factory(guardrails={"enabled": False},
                                      start=False)
        try:
            assert srv.guardrails is None
            assert srv.guardrails_accounting() == {}
        finally:
            srv.disable_server()


class TestPublishGate:
    def test_nonfinite_params_never_publish(self, guard_server_factory):
        srv, stub = guard_server_factory(start=False)
        try:
            bad = {"w": np.array([1.0, float("nan")], np.float32)}
            srv._publish_params(99, {"obs_dim": OBS_DIM}, bad)
            assert stub.published == []
            assert srv.guardrails.watchdog is not None
            assert not srv.guardrails.watchdog.healthy()  # external trip
            trip = srv.guardrails.watchdog.poll(0)
            assert trip is not None
            assert trip.signal == "publish_nonfinite"
        finally:
            srv.disable_server()

    def test_finite_params_publish_normally(self, guard_server_factory):
        srv, stub = guard_server_factory(start=False)
        try:
            import jax

            host = jax.device_get(srv.algorithm.state.params)
            srv._publish_params(1, dict(srv.algorithm.arch), host)
            assert stub.published and stub.published[-1][0] == 1
        finally:
            srv.disable_server()


class TestRollback:
    def test_trip_rolls_back_to_healthy_and_resumes(
            self, guard_server_factory):
        import jax

        srv, stub = guard_server_factory(
            learner={"checkpoint_every_epochs": 1},
            guardrails={"checkpoint_ring": 5})
        try:
            srv.wait_warmup(120)
            for ep in [_episode(seed=i, n=6) for i in range(4)]:
                srv._decoded.put(ep)
            assert srv.drain(timeout=120)
            assert srv.algorithm.version == 2  # traj_per_epoch=2
            saved_params = jax.device_get(srv.algorithm.state.params)
            mgr = srv.algorithm._ckpt_mgr
            mgr.wait()
            assert mgr.healthy_steps(), "no healthy checkpoint retained"
            pre_version = srv.latest_model_version
            # poison the line: external trip surfaces on the next poll
            srv.guardrails.watchdog.trip_external("publish_nonfinite")
            for ep in [_episode(seed=10 + i, n=6) for i in range(2)]:
                srv._decoded.put(ep)
            deadline = time.monotonic() + 60
            while (srv.guardrails_accounting().get("rollbacks_total", 0) < 1
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            acct = srv.guardrails_accounting()
            assert acct["rollbacks_total"] == 1
            assert acct["halted"] is False
            # params returned to the newest healthy line…
            restored = jax.device_get(srv.algorithm.state.params)
            for a, b in zip(jax.tree_util.tree_leaves(saved_params),
                            jax.tree_util.tree_leaves(restored)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            # …under a version strictly beyond the poisoned line
            assert srv.algorithm.version > pre_version
            # the restored params were re-published (forced keyframe path)
            assert stub.published[-1][0] == srv.algorithm.version
            srv.drain(timeout=60)
        finally:
            srv.disable_server()

    def test_rollback_budget_degrades_to_halt(self, guard_server_factory):
        from relayrl_tpu.guardrails.watchdog import Trip

        srv, _ = guard_server_factory(
            guardrails={"max_rollbacks": 0}, start=False)
        try:
            assert not srv.guardrails_halted
            srv._execute_rollback(Trip("nonfinite_params", 1.0, 0.0))
            assert srv.guardrails_halted
            acct = srv.guardrails_accounting()
            assert acct["halted"] is True
            assert acct["rollbacks_total"] == 0
            # halted ingest sheds instead of queueing
            before = srv._ingest.qsize()
            srv._ingest_one("a", b"payload")
            assert srv._ingest.qsize() == before
        finally:
            srv.disable_server()

    def test_no_healthy_checkpoint_halts(self, guard_server_factory):
        from relayrl_tpu.guardrails.watchdog import Trip

        srv, _ = guard_server_factory(start=False)
        try:
            # no checkpoint was ever saved → restore raises → halt
            srv._execute_rollback(Trip("param_norm", 1e9, 1e6))
            assert srv.guardrails_halted
        finally:
            srv.disable_server()

    def test_checkpoints_carry_health_tag(self, guard_server_factory):
        srv, _ = guard_server_factory(
            learner={"checkpoint_every_epochs": 1})
        try:
            srv.wait_warmup(120)
            for ep in [_episode(seed=i, n=6) for i in range(2)]:
                srv._decoded.put(ep)
            assert srv.drain(timeout=120)
            mgr = srv.algorithm._ckpt_mgr
            mgr.wait()
            steps = mgr.healthy_steps()
            assert steps, "clean training must save healthy-tagged steps"
            assert mgr.read_extra(steps[-1])["healthy"] is True
        finally:
            srv.disable_server()


# ---------------------------------------------------------------------------
# probes are observers: bit-identical params on vs off
# ---------------------------------------------------------------------------
class TestBitIdentity:
    # Wall re-fit convention: REINFORCE is the fast per-algorithm
    # representative; the PPO twin rides the slow tier.
    @pytest.mark.parametrize("algo_name,hp", [
        ("REINFORCE", {"with_vf_baseline": True, "train_vf_iters": 2}),
        pytest.param("PPO", {"train_iters": 2, "minibatch_count": 2},
                     marks=pytest.mark.slow),
    ])
    def test_guardrails_probes_do_not_perturb_training(
            self, tmp_cwd, algo_name, hp):
        import jax

        def run(with_probes: bool):
            algo = build_algorithm(
                algo_name, obs_dim=OBS_DIM, act_dim=ACT_DIM,
                env_dir=str(tmp_cwd), traj_per_epoch=2, hidden_sizes=[16],
                seed_salt=0,
                logger_kwargs={"output_dir":
                               str(tmp_cwd / f"logs_{with_probes}")},
                **hp)
            if with_probes:
                algo._guard_probes = GuardProbes(update_norm=True)
            stream = [_episode(seed=100 + i, n=8) for i in range(6)]
            for ep in stream:
                algo.receive_trajectory(ep)
            assert algo.version > 0, "never trained"
            if with_probes:
                # the probe scalars really rode the metrics
                assert PROBE_PARAM_NORM in algo._last_metrics
                assert PROBE_UPDATE_NORM in algo._last_metrics
                assert algo._last_metrics[PROBE_NONFINITE] == 0
            return jax.device_get(algo.state.params)

        off = run(False)
        on = run(True)
        flat_off = jax.tree_util.tree_leaves(off)
        flat_on = jax.tree_util.tree_leaves(on)
        assert len(flat_off) == len(flat_on)
        for a, b in zip(flat_off, flat_on):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
