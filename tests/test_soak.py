"""Scaled-down multi-actor soak (the committed 64-actor numbers live in
benches/results/soak64.json; this keeps the harness itself green)."""

import os
import sys

import pytest

pytestmark = pytest.mark.slow

_BENCHES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "benches")


@pytest.fixture
def soak(monkeypatch, tmp_path):
    monkeypatch.syspath_prepend(_BENCHES)
    monkeypatch.chdir(tmp_path)
    import bench_soak

    return bench_soak


def test_multi_actor_soak_no_drops(soak):
    result = soak.run_soak(n_actors=8, agents_per_proc=4, duration_s=5.0,
                           traj_per_epoch=8)
    assert result["agents_completed"] == 8
    assert result["server_stats"]["dropped"] == 0
    assert result["ingest_backlog_after_drain"] == 0
    assert result["env_steps_total"] > 0


def test_ingest_blast_no_drops(soak):
    result = soak.run_ingest_blast(n_traj=300)
    assert result["drained"] is True
    assert result["server_stats"]["dropped"] == 0
    assert result["server_stats"]["trajectories"] == 300
