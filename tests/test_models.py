"""Model registry + policy ABI tests (ref model ABI: kernel.py:99-143 and
the load-time validator agent_wrapper.rs:88-168)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relayrl_tpu.models import build_policy, validate_policy
from relayrl_tpu.types.model_bundle import ModelBundle


def _discrete_arch(**kw):
    arch = {"kind": "mlp_discrete", "obs_dim": 4, "act_dim": 3,
            "hidden_sizes": [32, 32], "has_critic": True}
    arch.update(kw)
    return arch


class TestDiscretePolicy:
    def test_step_abi(self):
        policy = build_policy(_discrete_arch())
        params = policy.init_params(jax.random.PRNGKey(0))
        act, aux = policy.step(params, jax.random.PRNGKey(1),
                               jnp.zeros(4), jnp.ones(3))
        assert act.shape == ()
        assert set(aux) == {"logp_a", "v"}
        assert 0 <= int(act) < 3

    def test_batched_step(self):
        policy = build_policy(_discrete_arch())
        params = policy.init_params(jax.random.PRNGKey(0))
        obs = jnp.zeros((5, 4))
        act, aux = policy.step(params, jax.random.PRNGKey(1), obs, jnp.ones((5, 3)))
        assert act.shape == (5,)
        assert aux["logp_a"].shape == (5,)
        assert aux["v"].shape == (5,)

    def test_mask_forbids_actions(self):
        policy = build_policy(_discrete_arch())
        params = policy.init_params(jax.random.PRNGKey(0))
        mask = jnp.array([1.0, 0.0, 0.0])
        for i in range(20):
            act, _ = policy.step(params, jax.random.PRNGKey(i), jnp.ones(4), mask)
            assert int(act) == 0, "masked action sampled"

    def test_evaluate_consistent_with_step(self):
        policy = build_policy(_discrete_arch())
        params = policy.init_params(jax.random.PRNGKey(0))
        obs = jax.random.normal(jax.random.PRNGKey(2), (7, 4))
        act, aux = policy.step(params, jax.random.PRNGKey(3), obs, jnp.ones((7, 3)))
        logp, ent, v = policy.evaluate(params, obs, act, jnp.ones((7, 3)))
        np.testing.assert_allclose(np.asarray(logp), np.asarray(aux["logp_a"]), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(v), np.asarray(aux["v"]), rtol=1e-5)
        assert np.all(np.asarray(ent) >= 0)

    def test_no_critic_returns_zero_v(self):
        policy = build_policy(_discrete_arch(has_critic=False))
        params = policy.init_params(jax.random.PRNGKey(0))
        _, aux = policy.step(params, jax.random.PRNGKey(1), jnp.zeros(4), None)
        assert float(aux["v"]) == 0.0

    def test_validate_policy(self):
        policy = build_policy(_discrete_arch())
        params = policy.init_params(jax.random.PRNGKey(0))
        validate_policy(policy, params)  # should not raise

    def test_dims(self):
        policy = build_policy(_discrete_arch())
        assert policy.get_input_dim() == 4
        assert policy.get_output_dim() == 3


class TestContinuousPolicy:
    def _policy(self):
        return build_policy({"kind": "mlp_continuous", "obs_dim": 3, "act_dim": 2,
                             "hidden_sizes": [16], "has_critic": True})

    def test_step_abi(self):
        policy = self._policy()
        params = policy.init_params(jax.random.PRNGKey(0))
        act, aux = policy.step(params, jax.random.PRNGKey(1), jnp.zeros(3))
        assert act.shape == (2,)
        assert aux["logp_a"].shape == ()

    def test_mode_is_mean(self):
        policy = self._policy()
        params = policy.init_params(jax.random.PRNGKey(0))
        m1 = policy.mode(params, jnp.ones(3))
        m2 = policy.mode(params, jnp.ones(3))
        np.testing.assert_array_equal(np.asarray(m1), np.asarray(m2))

    def test_logp_matches_normal(self):
        from scipy import stats

        policy = self._policy()
        params = policy.init_params(jax.random.PRNGKey(0))
        obs = jnp.ones(3)
        act, aux = policy.step(params, jax.random.PRNGKey(5), obs)
        logp, _, _ = policy.evaluate(params, obs, act)
        mu = np.asarray(policy.mode(params, obs))
        log_std = np.asarray(params["params"]["log_std"])
        expected = stats.norm.logpdf(np.asarray(act), mu, np.exp(log_std)).sum()
        assert float(logp) == pytest.approx(expected, rel=1e-4)


class TestBundleRoundTrip:
    def test_params_survive_wire(self):
        policy = build_policy(_discrete_arch())
        params = policy.init_params(jax.random.PRNGKey(0))
        bundle = ModelBundle(version=1, arch=policy.arch, params=jax.device_get(params))
        restored = ModelBundle.from_bytes(bundle.to_bytes())
        policy2 = build_policy(restored.arch)
        obs = jnp.ones(4)
        a1, aux1 = policy.step(params, jax.random.PRNGKey(9), obs, jnp.ones(3))
        a2, aux2 = policy2.step(restored.params, jax.random.PRNGKey(9), obs, jnp.ones(3))
        assert int(a1) == int(a2)
        assert float(aux1["logp_a"]) == pytest.approx(float(aux2["logp_a"]), rel=1e-5)

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown model kind"):
            build_policy({"kind": "nope", "obs_dim": 1, "act_dim": 1})
