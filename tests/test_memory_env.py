"""RecallEnv mechanics (fast) + the long-context learning contrast (slow):
a transformer sequence policy solves the memory task; a per-step MLP is
capped at chance by construction."""

import numpy as np
import pytest

from relayrl_tpu.envs import RecallEnv, make


class TestRecallEnvMechanics:
    def test_registered(self):
        assert isinstance(make("Recall-v0", horizon=4), RecallEnv)

    def test_cue_shown_once_then_hidden(self):
        env = RecallEnv(horizon=5)
        obs, _ = env.reset(seed=0)
        assert obs[:2].sum() == 1.0          # one-hot cue at t=0
        for _ in range(3):
            obs, r, term, trunc, _ = env.step(0)
            assert obs[:2].sum() == 0.0      # hidden afterwards
            assert r == 0.0 and not term
            assert obs[2] == 0.0             # not yet the query step
        obs, r, term, trunc, _ = env.step(0)
        assert obs[2] == 1.0                 # query flag on final obs

    def test_only_query_action_scored(self):
        env = RecallEnv(horizon=3)
        for seed in range(10):
            obs, _ = env.reset(seed=seed)
            cue = int(np.argmax(obs[:2]))
            env.step(1 - cue)                # wrong mid-episode: irrelevant
            env.step(1 - cue)
            obs, r, term, trunc, _ = env.step(cue)
            assert (r, term) == (1.0, True)

    def test_wrong_recall_scores_zero(self):
        env = RecallEnv(horizon=2)
        obs, _ = env.reset(seed=1)
        cue = int(np.argmax(obs[:2]))
        env.step(0)
        _, r, term, _, _ = env.step(1 - cue)
        assert (r, term) == (0.0, True)

    def test_noise_keeps_cue_slot_clean_at_t0(self):
        env = RecallEnv(horizon=4, noise=0.5)
        obs, _ = env.reset(seed=2)
        assert set(np.unique(obs[:2])) <= {0.0, 1.0}
        obs, *_ = env.step(0)
        assert obs[:2].any()                 # distractor noise present


def _train(model_kind, extra, epochs, tmp_path):
    from relayrl_tpu.runtime.local_runner import LocalRunner

    # The algorithm seeds fold in os.getpid() (reference parity:
    # REINFORCE.py seeds seed + 10000*pid), which would make learning runs
    # differ per pytest process — seed_salt pins the fold-in so this test
    # trains the same network every run.
    runner = LocalRunner(
        RecallEnv(horizon=8), "REINFORCE", env_dir=str(tmp_path), seed=0,
        seed_salt=7,
        with_vf_baseline=True, gamma=1.0, lam=0.95, traj_per_epoch=32,
        pi_lr=1e-3, vf_lr=1e-3, train_vf_iters=20,
        bucket_lengths=(16,), model_kind=model_kind, **extra)
    best = 0.0
    for _ in range(epochs // 5):
        result = runner.train(epochs=5)
        best = max(best, result["avg_return_last_window"])
        if best >= 0.9:
            break
    return best


@pytest.mark.slow
class TestLongContextLearning:
    def test_transformer_solves_recall(self, tmp_path):
        best = _train("transformer_discrete",
                      {"d_model": 32, "n_layers": 1, "n_heads": 2,
                       "max_seq_len": 16}, epochs=80, tmp_path=tmp_path)
        assert best >= 0.9, f"transformer failed to solve recall: {best}"

    def test_mlp_capped_at_chance(self, tmp_path):
        best = _train("mlp_discrete", {"hidden_sizes": [64, 64]},
                      epochs=30, tmp_path=tmp_path)
        # Memoryless policy: E[return] = 0.5 regardless of training; allow
        # sampling slack above chance but nowhere near solved.
        assert best <= 0.8, f"memoryless policy should stay near 0.5: {best}"
