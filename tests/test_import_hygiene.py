"""BASELINE.md constraint: zero torch/CUDA imports in the training server.

The reference's learner is PyTorch end to end; this framework's entire
compute path is JAX/XLA, and the driver's north-star config explicitly
requires the server to run torch-free. A stray ``import torch`` anywhere
on the server path would cost ~1 GB RSS and seconds of import time per
process (torch IS installed in this environment, so the import would
succeed silently — only this test notices). Run in a subprocess so other
tests' imports can't contaminate ``sys.modules``.
"""

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code: str) -> str:
    import importlib.util

    # Self-check against vacuity: without torch installed, sys.modules can
    # never contain it and the guard would pass while proving nothing.
    assert importlib.util.find_spec("torch") is not None, \
        "hygiene test vacuous: torch not installed in this environment"
    env = dict(os.environ)
    # Repo root ONLY: the ambient PYTHONPATH may carry accelerator plugin
    # site dirs whose import blocks when the device tunnel is down — this
    # test is about OUR import graph, on the CPU backend.
    env["PYTHONPATH"] = _REPO
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout


def test_server_path_is_torch_free(tmp_cwd):
    stdout = _run(
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "from relayrl_tpu.runtime.server import TrainingServer\n"
        "srv = TrainingServer('REINFORCE', obs_dim=4, act_dim=2,\n"
        "                     env_dir='.', start=False,\n"
        "                     hyperparams={'hidden_sizes': [8]})\n"
        "bad = sorted(m for m in sys.modules\n"
        "             if m == 'torch' or m.startswith('torch.'))\n"
        "print('TORCH_MODULES', bad)\n")
    assert "TORCH_MODULES []" in stdout, stdout


def test_agent_path_is_torch_free(tmp_cwd):
    stdout = _run(
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import numpy as np\n"
        # The REAL agent entry point: importing runtime.agent pulls in the
        # whole agent-side transport graph at module level, so a stray
        # torch import anywhere on the actor path is caught here.
        "import relayrl_tpu.runtime.agent  # noqa: F401\n"
        "from relayrl_tpu.runtime.policy_actor import PolicyActor\n"
        "from relayrl_tpu.algorithms import build_algorithm\n"
        "alg = build_algorithm('REINFORCE', obs_dim=4, act_dim=2,\n"
        "                      env_dir='.', hidden_sizes=[8])\n"
        "actor = PolicyActor(alg.bundle())\n"
        "actor.request_for_action(np.zeros(4, np.float32))\n"
        "bad = sorted(m for m in sys.modules\n"
        "             if m == 'torch' or m.startswith('torch.'))\n"
        "print('TORCH_MODULES', bad)\n")
    assert "TORCH_MODULES []" in stdout, stdout
