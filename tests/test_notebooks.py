"""Harness contract for the example-notebook generator.

The committed notebooks under ``examples/notebooks/`` are genuinely
executed (their outputs are the evidence); re-executing them in CI is
minutes of wall clock, so the suite guards the *authoring* contract:
the generator still covers the reference's full 12-cell matrix
(reference: examples/ tree — REINFORCE ± baseline × {cartpole,
mountain_car, lunar_lander} × {zmq, grpc}), emits structurally valid
notebooks, and keeps the load-bearing cells (warmup wait, drain before
stats) that make the one-kernel topology correct.
"""

import subprocess
import sys
from pathlib import Path

import pytest

nbformat = pytest.importorskip(
    "nbformat", reason="notebook authoring needs nbformat")

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "examples" / "notebooks" / "make_notebooks.py"


def test_generator_authors_full_matrix(tmp_path):
    out = subprocess.run(
        [sys.executable, str(SCRIPT), "--no-execute",
         "--out", str(tmp_path / "nb")],
        capture_output=True, text=True, timeout=120,
        cwd=tmp_path, env={"PATH": "/usr/bin:/bin", "HOME": str(tmp_path),
                           "PYTHONPATH": str(REPO)})
    assert out.returncode == 0, out.stderr[-1500:]

    written = sorted((tmp_path / "nb").glob("*.ipynb"))
    names = {p.stem for p in written}
    expected = {f"{env}_reinforce_{tag}_{tr}"
                for env in ("cartpole", "mountaincar", "lunarlander")
                for tag in ("baseline", "nobaseline")
                for tr in ("zmq", "grpc")}
    assert expected <= names, expected - names

    for p in written:
        nb = nbformat.read(p, as_version=4)
        nbformat.validate(nb)
        src = "\n".join(c.source for c in nb.cells if c.cell_type == "code")
        # The cells that make one kernel hosting server+actor correct:
        assert "wait_warmup" in src, p.name
        assert "server.drain()" in src, p.name
        assert "disable_server()" in src, p.name
        # The explicit reference-style loop, not a helper call.
        assert "request_for_action" in src and "flag_last_action" in src, p.name


def test_generator_only_filter_rejects_nonsense(tmp_path):
    out = subprocess.run(
        [sys.executable, str(SCRIPT), "--no-execute", "--only", "nope-xyz",
         "--out", str(tmp_path / "nb")],  # a regression must clobber tmp,
        capture_output=True, text=True, timeout=60,  # never the committed set
        cwd=tmp_path, env={"PATH": "/usr/bin:/bin", "HOME": str(tmp_path),
                           "PYTHONPATH": str(REPO)})
    assert out.returncode != 0
    assert "matches none" in (out.stderr + out.stdout)
