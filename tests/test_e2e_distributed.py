"""End-to-end distributed loop: TrainingServer + Agent over real sockets.

This is the test the reference never had (SURVEY.md §4 — its only
multi-process validation is criterion benches): the full loop of §3.3 —
handshake → env steps → trajectory over the wire → learner update → model
publish → actor hot-swap — on localhost ephemeral ports.
"""

import socket
import time

import numpy as np
import pytest

from relayrl_tpu.runtime.agent import Agent, run_gym_loop
from relayrl_tpu.runtime.server import TrainingServer


from _util import free_port  # noqa: E402


def _wait_for(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def _zmq_addrs():
    return {
        "agent_listener_addr": f"tcp://127.0.0.1:{free_port()}",
        "trajectory_addr": f"tcp://127.0.0.1:{free_port()}",
        "model_pub_addr": f"tcp://127.0.0.1:{free_port()}",
    }


def _agent_addrs(server_addrs):
    return {
        "agent_listener_addr": server_addrs["agent_listener_addr"],
        "trajectory_addr": server_addrs["trajectory_addr"],
        "model_sub_addr": server_addrs["model_pub_addr"],
    }


class _RandomEnv:
    """Tiny deterministic env so e2e tests don't need gymnasium."""

    def __init__(self, obs_dim=4, horizon=6, seed=0):
        self._rng = np.random.default_rng(seed)
        self.obs_dim, self.horizon = obs_dim, horizon
        self._t = 0

    def reset(self, seed=None):
        self._t = 0
        return self._rng.standard_normal(self.obs_dim).astype(np.float32), {}

    def step(self, action):
        self._t += 1
        obs = self._rng.standard_normal(self.obs_dim).astype(np.float32)
        return obs, 1.0, self._t >= self.horizon, False, {}


@pytest.mark.parametrize("server_type", ["zmq", "grpc", "native"])
def test_full_loop_model_update_reaches_agent(tmp_cwd, server_type):
    if server_type == "zmq":
        server_addrs = _zmq_addrs()
        agent_addrs = _agent_addrs(server_addrs)
    else:
        if server_type == "native":
            from relayrl_tpu.transport.native_backend import native_available

            if not native_available():
                pytest.skip("native library not built")
        port = free_port()
        server_addrs = {"bind_addr": f"127.0.0.1:{port}"}
        agent_addrs = {"server_addr": f"127.0.0.1:{port}"}

    server = TrainingServer(
        "REINFORCE", obs_dim=4, act_dim=2, server_type=server_type,
        env_dir=str(tmp_cwd),
        hyperparams={"traj_per_epoch": 2, "hidden_sizes": [16],
                     "with_vf_baseline": False},
        **server_addrs,
    )
    if server_type == "grpc":
        server.transport.idle_timeout_s = 2.0
    try:
        agent = Agent(server_type=server_type, handshake_timeout_s=20,
                      seed=0, **agent_addrs)
        try:
            assert agent.model_version == 0
            env = _RandomEnv()
            run_gym_loop(agent, env, episodes=2, max_steps=10)

            assert _wait_for(lambda: server.stats["updates"] >= 1,
                             timeout=30), (
                f"learner never updated; stats={server.stats}")

            assert _wait_for(lambda: agent.model_version >= 1,
                             timeout=30), "hot-swap never happened"
            assert agent.transport.identity in server.agent_ids
        finally:
            agent.disable_agent()
    finally:
        server.disable_server()


def test_drain_then_shutdown_processes_inflight(tmp_cwd):
    """drain() must finish every already-sent trajectory (train + publish),
    and disable_server immediately after must not kill a mid-flight publish
    (the learner joins before the transport stops)."""
    server_addrs = _zmq_addrs()
    server = TrainingServer(
        "REINFORCE", obs_dim=4, act_dim=2, server_type="zmq",
        env_dir=str(tmp_cwd),
        hyperparams={"traj_per_epoch": 2, "hidden_sizes": [16],
                     "with_vf_baseline": False},
        **server_addrs,
    )
    agent = Agent(server_type="zmq", handshake_timeout_s=20, seed=0,
                  **_agent_addrs(server_addrs))
    try:
        env = _RandomEnv()
        run_gym_loop(agent, env, episodes=6, max_steps=10)
        # In-flight socket bytes are invisible to drain(): wait for arrival
        # first (6 episodes / traj_per_epoch 2 => exactly 3 updates)...
        _wait_for(lambda: server.stats["trajectories"] >= 6, timeout=60)
        # ...then drain guarantees processing/publishing has finished.
        assert server.drain(timeout=60)
        assert server.stats["updates"] == 3
        assert server.algorithm.version == 3
    finally:
        agent.disable_agent()
        server.disable_server()
    assert server.stats["dropped"] == 0


def test_multi_agent_zmq(tmp_cwd):
    """Several ZMQ agents against one server — the topology the reference's
    ZMQ plane cannot serve (SURVEY.md §2.3 socket-topology note)."""
    server_addrs = _zmq_addrs()
    server = TrainingServer(
        "REINFORCE", obs_dim=4, act_dim=2, server_type="zmq",
        env_dir=str(tmp_cwd), multiactor=True,
        hyperparams={"traj_per_epoch": 4, "hidden_sizes": [16],
                     "with_vf_baseline": False},
        **server_addrs,
    )
    agents = []
    try:
        for i in range(3):
            agents.append(Agent(server_type="zmq", handshake_timeout_s=20,
                                seed=i, **_agent_addrs(server_addrs)))
        env = _RandomEnv()
        for a in agents:
            run_gym_loop(a, env, episodes=2, max_steps=8)

        assert _wait_for(lambda: server.stats["updates"] >= 1, timeout=30)
        assert len(server.agent_ids) == 3

        for i, a in enumerate(agents):
            assert _wait_for(lambda a=a: a.model_version >= 1, timeout=30), \
                f"agent {i} never got the new model"
    finally:
        for a in agents:
            a.disable_agent()
        server.disable_server()


def test_server_checkpoint_resume(tmp_cwd):
    """Kill the server after training; a resumed server continues at the
    checkpointed version (beyond-reference capability, SURVEY.md §5.4)."""
    server_addrs = _zmq_addrs()
    hp = {"traj_per_epoch": 1, "hidden_sizes": [8], "with_vf_baseline": False,
          "checkpoint_every_epochs": 1}
    server = TrainingServer(
        "REINFORCE", obs_dim=4, act_dim=2, server_type="zmq",
        env_dir=str(tmp_cwd), hyperparams=hp, **server_addrs)
    try:
        agent = Agent(server_type="zmq", handshake_timeout_s=20, seed=0,
                      **_agent_addrs(server_addrs))
        try:
            run_gym_loop(agent, _RandomEnv(), episodes=3, max_steps=6)
            assert _wait_for(lambda: server.stats["updates"] >= 3,
                             timeout=30)
        finally:
            agent.disable_agent()
        trained_version = server.algorithm.version
        from relayrl_tpu.checkpoint import checkpoint_algorithm

        checkpoint_algorithm(server.algorithm, "checkpoints", wait=True)
    finally:
        server.disable_server()

    resumed = TrainingServer(
        "REINFORCE", obs_dim=4, act_dim=2, server_type="zmq",
        env_dir=str(tmp_cwd), hyperparams=hp, resume=True, **_zmq_addrs())
    try:
        assert resumed.algorithm.version == trained_version
    finally:
        resumed.disable_server()


def _transport_addr_pair(kind):
    """(server_addrs, agent_addrs) for any transport kind."""
    if kind == "zmq":
        srv = _zmq_addrs()
        return srv, _agent_addrs(srv)
    port = free_port()
    return ({"bind_addr": f"127.0.0.1:{port}"},
            {"server_addr": f"127.0.0.1:{port}"})


def _transports_available():
    from relayrl_tpu.transport.native_backend import native_available

    return ["zmq", "grpc"] + (["native"] if native_available() else [])


# Wall re-fit convention: zmq is the fast per-transport representative;
# the grpc/native twins exercise the same repoint path over a different
# socket and ride the slow tier.
@pytest.mark.parametrize("kind", [
    "zmq",
    pytest.param("grpc", marks=pytest.mark.slow),
    pytest.param("native", marks=pytest.mark.slow),
])
def test_agent_restart_and_repoint(tmp_cwd, kind):
    """Agent lifecycle parity (ref o3_agent.rs restart/enable/disable):
    restart against the same server keeps serving; restart with address
    overrides re-resolves to a DIFFERENT server — the reference's
    address-re-resolution semantic (training_server_wrapper.rs:69-90),
    agent side. Parametrized across all three transports: teardown +
    re-handshake is the transport-specific part."""
    if kind not in _transports_available():
        pytest.skip("native library not built (make -C native)")
    hp = {"traj_per_epoch": 1, "hidden_sizes": [8],
          "with_vf_baseline": False}
    addrs_a, ag_a = _transport_addr_pair(kind)
    srv_a = TrainingServer("REINFORCE", obs_dim=4, act_dim=2,
                           server_type=kind, env_dir=str(tmp_cwd),
                           hyperparams=hp, **addrs_a)
    try:
        agent = Agent(server_type=kind, handshake_timeout_s=20, **ag_a)
        try:
            v_a = agent.model_version
            act = agent.request_for_action(np.zeros(4, np.float32))
            assert act.get_act() is not None

            # Same-address restart: full teardown + re-handshake.
            agent.restart_agent()
            assert agent.active and agent.model_version >= v_a
            act = agent.request_for_action(np.zeros(4, np.float32))
            assert act.get_act() is not None

            # Re-point at a different server via addr overrides.
            addrs_b, ag_b = _transport_addr_pair(kind)
            srv_b = TrainingServer("REINFORCE", obs_dim=4, act_dim=2,
                                   server_type=kind,
                                   env_dir=str(tmp_cwd / "b"),
                                   hyperparams=hp, **addrs_b)
            try:
                agent.restart_agent(**ag_b)
                assert agent.active
                act = agent.request_for_action(np.zeros(4, np.float32))
                agent.flag_last_action(reward=1.0)
                assert _wait_for(lambda: srv_b.stats["trajectories"] >= 1)
                assert srv_a.stats["trajectories"] == 0, \
                    "trajectory went to the OLD server after re-point"
            finally:
                srv_b.disable_server()
        finally:
            agent.disable_agent()
    finally:
        srv_a.disable_server()


def test_server_restart(tmp_cwd):
    server_addrs = _zmq_addrs()
    server = TrainingServer(
        "REINFORCE", obs_dim=4, act_dim=2, server_type="zmq",
        env_dir=str(tmp_cwd),
        hyperparams={"traj_per_epoch": 1, "hidden_sizes": [8],
                     "with_vf_baseline": False},
        **server_addrs,
    )
    try:
        assert server.active
        server.restart_server()
        assert server.active
        # Still serves handshakes after restart.
        agent = Agent(server_type="zmq", handshake_timeout_s=20,
                      **_agent_addrs(server_addrs))
        try:
            assert agent.model_version >= 0
        finally:
            agent.disable_agent()
    finally:
        server.disable_server()


@pytest.mark.parametrize("algo,hp", [
    ("DQN", {"update_after": 8, "batch_size": 8, "updates_per_step": 0.25,
             "hidden_sizes": [16]}),
    ("IMPALA", {"traj_per_epoch": 2, "hidden_sizes": [16]}),
    ("C51", {"update_after": 8, "batch_size": 8, "updates_per_step": 0.25,
             "hidden_sizes": [16], "n_atoms": 11}),
    # Continuous actions over the wire: deterministic (DDPG/TD3) and
    # squashed-Gaussian (SAC) actors emit float vectors instead of scalar
    # ints (a different codec/actor path).
    ("SAC", {"update_after": 8, "batch_size": 8, "updates_per_step": 0.25,
             "hidden_sizes": [16], "discrete": False, "act_limit": 1.0}),
    ("DDPG", {"update_after": 8, "batch_size": 8, "updates_per_step": 0.25,
              "hidden_sizes": [16], "discrete": False, "act_limit": 1.0}),
    ("TD3", {"update_after": 8, "batch_size": 8, "updates_per_step": 0.25,
             "hidden_sizes": [16], "discrete": False, "act_limit": 1.0}),
])
def test_offpolicy_and_async_families_over_sockets(tmp_cwd, algo, hp):
    """Every non-on-policy algorithm in the registry runs the full
    distributed loop over real zmq sockets (REINFORCE/PPO are covered by
    the tests above): replay/warmup/target-net (DQN), distributional
    (C51), staleness-corrected async (IMPALA), and the three continuous
    actors (SAC/DDPG/TD3 — float action vectors on the wire)."""
    server_addrs = _zmq_addrs()
    agent_addrs = _agent_addrs(server_addrs)
    server = TrainingServer(
        algo, obs_dim=4, act_dim=2, server_type="zmq",
        env_dir=str(tmp_cwd), hyperparams=hp, **server_addrs)
    try:
        agent = Agent(server_type="zmq", handshake_timeout_s=20,
                      seed=0, **agent_addrs)
        try:
            env = _RandomEnv()
            deadline = time.monotonic() + 60
            while (server.stats["updates"] < 1
                   and time.monotonic() < deadline):
                run_gym_loop(agent, env, episodes=2, max_steps=10)
                time.sleep(0.02)
            assert server.stats["updates"] >= 1, (
                f"{algo} learner never updated; stats={server.stats}")
            assert server.stats["dropped"] == 0

            assert _wait_for(lambda: agent.model_version >= 1,
                             timeout=30), f"{algo} hot-swap never happened"
        finally:
            agent.disable_agent()
    finally:
        server.disable_server()


def test_uint8_pixel_frames_cross_the_wire_byte_sized(tmp_cwd):
    """The byte-sized pixel plane end-to-end (guards what
    benches/bench_pixel_wire.py measures at full scale): uint8 frames
    from the Atari pipeline stay uint8 through actor -> codec -> socket
    -> decode -> CNN learner, with per-step payload ~= obs_dim bytes
    (a float32 regression would quadruple it — exactly the silent
    upcast round 5 fixed in policy_actor.py)."""
    from relayrl_tpu.envs import make_atari

    server_addrs = _zmq_addrs()
    agent_addrs = _agent_addrs(server_addrs)
    frame, stack = 16, 2
    obs_dim = frame * frame * stack
    server = TrainingServer(
        "PPO", obs_dim=obs_dim, act_dim=3, server_type="zmq",
        env_dir=str(tmp_cwd),
        hyperparams={"model_kind": "cnn_discrete",
                     "obs_shape": [frame, frame, stack],
                     "conv_spec": [[4, 3, 2], [8, 3, 1]], "dense": 32,
                     "traj_per_epoch": 2, "minibatch_count": 1,
                     "train_iters": 1},
        **server_addrs)
    try:
        agent = Agent(server_type="zmq", handshake_timeout_s=30,
                      seed=0, **agent_addrs)
        from relayrl_tpu.utils.instrument import instrument_agent

        wire = instrument_agent(agent)  # shared with bench_pixel_wire
        try:
            env = make_atari("synthetic", frame_size=frame,
                             frame_stack=stack, frame_skip=2,
                             obs_dtype="uint8", raw_size=24, balls=1)
            deadline = time.monotonic() + 90
            while (server.stats["updates"] < 1
                   and time.monotonic() < deadline):
                run_gym_loop(agent, env, episodes=1, max_steps=40)
            assert server.stats["updates"] >= 1, server.stats
            assert server.stats["dropped"] == 0
            bytes_per_step = wire["bytes"] / wire["steps"]
            # obs_dim byte frame + a small fixed overhead; float32 would
            # be >= 4 * obs_dim
            assert obs_dim <= bytes_per_step < 2 * obs_dim, (
                f"pixel step costs {bytes_per_step:.0f} B on the wire "
                f"(frame is {obs_dim} B) — uint8 plane regressed")
        finally:
            agent.disable_agent()
    finally:
        server.disable_server()
