"""Ingest trust boundary: semantically poisoned trajectories can't reach
the learner.

The wire fuzz suites (test_fuzz_codec / test_native_transport_fuzz /
test_grpc_native_fuzz) prove malformed BYTES can't crash anything. This
layer covers the nastier case: a perfectly well-formed trajectory whose
floats are NaN/inf — from a buggy env, a corrupted actor, or an
adversary. Nothing would crash; the learner state would silently go NaN
and the next publish would poison every actor in the fleet. Both
algorithm families must drop such trajectories at ``accumulate`` (the
single choke point: receive_trajectory and the multi-host coordinator
both route through it), count the drop, and keep training on good data.
"""

import numpy as np
import pytest

from relayrl_tpu.algorithms import build_algorithm
from relayrl_tpu.types.action import ActionRecord
from relayrl_tpu.types.columnar import trajectory_is_finite


def _episode(obs_dim=4, n=4, rew=1.0, obs_fill=0.5, logp=-0.3):
    recs = []
    for t in range(n):
        recs.append(ActionRecord(
            obs=np.full((obs_dim,), obs_fill, np.float32),
            act=np.int32(1),
            rew=float(rew) if t == n - 1 else 0.0,
            data={"v": np.float32(0.1), "logp_a": np.float32(logp)},
            done=t == n - 1,
        ))
    return recs


class TestFiniteGuard:
    def test_clean_episode_passes(self):
        assert trajectory_is_finite(_episode())

    @pytest.mark.parametrize("poison", [
        dict(rew=float("nan")),
        dict(rew=float("inf")),
        dict(obs_fill=float("nan")),
        dict(logp=float("-inf")),
    ])
    def test_poisoned_episode_fails(self, poison):
        assert not trajectory_is_finite(_episode(**poison))

    def test_decoded_trajectory_representation(self):
        # The columnar fast path (native decode) must agree with the
        # record path on the same data.
        from relayrl_tpu.types.columnar import DecodedTrajectory

        def decoded(rew):
            return DecodedTrajectory(
                agent_id="a", n_steps=2, n_records=2,
                marker_truncated=False,
                columns={"o": np.zeros((2, 4), np.float32),
                         "a": np.zeros((2,), np.int32),
                         "r": np.array([0.0, rew], np.float32),
                         "t": np.array([False, True]),
                         "u": np.zeros((2,), np.uint8),
                         "x": np.zeros((2,), np.uint8)},
                aux={"v": np.zeros((2,), np.float32),
                     "logp_a": np.zeros((2,), np.float32)})

        assert trajectory_is_finite(decoded(1.0))
        assert not trajectory_is_finite(decoded(float("nan")))

    def test_bfloat16_nan_is_caught(self):
        # bfloat16 arrives via ml_dtypes with dtype.kind 'V'; a
        # kind-'f'-only check would wave its NaNs through.
        import ml_dtypes

        recs = _episode()
        bad = np.array([0.1, float("nan"), 0.2, 0.3],
                       ml_dtypes.bfloat16)
        recs[1] = ActionRecord(obs=bad, act=recs[1].act, rew=recs[1].rew,
                               data=recs[1].data, done=recs[1].done)
        assert not trajectory_is_finite(recs)

    def test_plain_list_aux_nan_is_caught(self):
        # Foreign encoders can deliver aux values as plain msgpack lists;
        # downstream batching np.asarray's them, so the guard must too.
        recs = _episode()
        recs[0] = ActionRecord(obs=recs[0].obs, act=recs[0].act,
                               rew=recs[0].rew,
                               data={"v": [float("nan")], "logp_a": -0.1},
                               done=recs[0].done)
        assert not trajectory_is_finite(recs)

    def test_string_aux_is_inert(self):
        recs = _episode()
        recs[0] = ActionRecord(obs=recs[0].obs, act=recs[0].act,
                               rew=recs[0].rew,
                               data={"tag": "episode-1", "v": 0.1,
                                     "logp_a": -0.1},
                               done=recs[0].done)
        assert trajectory_is_finite(recs)

    def test_neg_inf_mask_is_allowed(self):
        # Masks are consumed as `mask > 0`; -inf fills are semantically
        # inert and must NOT trip the guard.
        recs = [ActionRecord(obs=r.obs, act=r.act,
                             mask=np.array([1.0, -np.inf, 1.0, 1.0],
                                           np.float32),
                             rew=r.rew, data=r.data, done=r.done)
                for r in _episode()]
        assert trajectory_is_finite(recs)


class TestLearnerDropsPoison:
    def test_onpolicy_drops_and_keeps_training(self, tmp_cwd):
        alg = build_algorithm("REINFORCE", obs_dim=4, act_dim=2,
                              env_dir=str(tmp_cwd), traj_per_epoch=2,
                              hidden_sizes=[8])
        assert alg.accumulate(_episode(rew=float("nan"))) is None
        assert alg.dropped_nonfinite == 1
        # good episodes still fill the epoch buffer and train
        assert alg.receive_trajectory(_episode()) is False
        assert alg.receive_trajectory(_episode()) is True
        params = alg.state.params
        leaves = [np.asarray(x) for x in
                  __import__("jax").tree.leaves(params)]
        assert all(np.isfinite(a).all() for a in leaves), \
            "params went non-finite"

    def test_server_stats_mirror_drop_counter(self, tmp_cwd):
        # Operators watch server.stats, not algorithm internals.
        from relayrl_tpu.runtime.server import TrainingServer

        srv = TrainingServer("REINFORCE", obs_dim=4, act_dim=2,
                             env_dir=str(tmp_cwd), start=False,
                             hyperparams={"traj_per_epoch": 2,
                                          "hidden_sizes": [8]})
        try:
            assert srv.stats["dropped_nonfinite"] == 0
            srv._process_one(_episode(rew=float("nan")))
            assert srv.stats["dropped_nonfinite"] == 1
            srv._process_one(_episode())
            assert srv.stats["trajectories"] == 2
            assert srv.stats["dropped_nonfinite"] == 1
        finally:
            srv.disable_server()

    def test_offpolicy_drops_before_replay(self, tmp_cwd):
        alg = build_algorithm("DQN", obs_dim=4, act_dim=2,
                              env_dir=str(tmp_cwd), hidden_sizes=[8],
                              update_after=2, batch_size=2)
        before = len(alg.buffer)
        assert alg.accumulate(_episode(obs_fill=float("inf"))) is None
        assert alg.dropped_nonfinite == 1
        assert len(alg.buffer) == before, \
            "poisoned transitions entered the replay ring"
        alg.receive_trajectory(_episode())
        assert len(alg.buffer) > before
