"""Transport backend tests: handshake, trajectory ingest, model broadcast.

Covers the surface the reference only exercises through criterion benches
(SURVEY.md §4): ZMQ and gRPC planes against real sockets on localhost with
ephemeral ports.
"""

import socket
import threading
import time

import pytest

from relayrl_tpu.config import ConfigLoader
from relayrl_tpu.transport import (
    make_agent_transport,
    make_server_transport,
    pack_model_frame,
    unpack_model_frame,
    pack_trajectory_envelope,
    unpack_trajectory_envelope,
)


from _util import free_port  # noqa: E402


@pytest.fixture
def cfg(tmp_cwd):
    return ConfigLoader(create_if_missing=False)


class TestEnvelopes:
    def test_trajectory_envelope(self):
        agent_id, payload = unpack_trajectory_envelope(
            pack_trajectory_envelope("agent-1", b"\x01\x02"))
        assert agent_id == "agent-1" and payload == b"\x01\x02"

    def test_model_frame(self):
        ver, model = unpack_model_frame(pack_model_frame(5, b"params"))
        assert ver == 5 and model == b"params"


def _roundtrip(server, make_agent):
    """Shared scenario: handshake → register → trajectory → broadcast."""
    received = []
    model_bytes = b"MODEL-V1-PARAMS"
    server.get_model = lambda: (1, model_bytes)
    server.on_trajectory = lambda aid, payload: received.append((aid, payload))
    registered = []
    server.on_register = registered.append
    server.start()
    try:
        agent = make_agent()
        try:
            version, fetched = agent.fetch_model(timeout_s=10)
            assert (version, fetched) == (1, model_bytes)
            assert agent.register(agent.identity, timeout_s=10)

            agent.send_trajectory(b"traj-bytes")
            deadline = time.monotonic() + 5
            while not received and time.monotonic() < deadline:
                time.sleep(0.01)
            assert received and received[0][1] == b"traj-bytes"
            assert received[0][0] == agent.identity

            got = threading.Event()
            swaps = []

            def on_model(ver, model):
                swaps.append((ver, model))
                got.set()

            agent.on_model = on_model
            agent.start_model_listener()
            time.sleep(0.3)  # let SUB subscription propagate
            server.get_model = lambda: (2, b"MODEL-V2")
            server.publish_model(2, b"MODEL-V2")
            assert got.wait(timeout=10), "model update never arrived"
            assert swaps[-1] == (2, b"MODEL-V2")

            if registered:
                assert agent.identity in registered
        finally:
            agent.close()
    finally:
        server.stop()


class TestZmqTransport:
    def test_full_roundtrip(self, cfg):
        ports = [free_port() for _ in range(3)]
        server = make_server_transport(
            "zmq", cfg,
            agent_listener_addr=f"tcp://127.0.0.1:{ports[0]}",
            trajectory_addr=f"tcp://127.0.0.1:{ports[1]}",
            model_pub_addr=f"tcp://127.0.0.1:{ports[2]}")

        def make_agent():
            return make_agent_transport(
                "zmq", cfg,
                agent_listener_addr=f"tcp://127.0.0.1:{ports[0]}",
                trajectory_addr=f"tcp://127.0.0.1:{ports[1]}",
                model_sub_addr=f"tcp://127.0.0.1:{ports[2]}")

        _roundtrip(server, make_agent)

    def test_handshake_timeout_when_no_server(self, cfg):
        port = free_port()
        agent = make_agent_transport(
            "zmq", cfg,
            agent_listener_addr=f"tcp://127.0.0.1:{port}",
            trajectory_addr=f"tcp://127.0.0.1:{free_port()}",
            model_sub_addr=f"tcp://127.0.0.1:{free_port()}")
        try:
            with pytest.raises(TimeoutError):
                agent.fetch_model(timeout_s=1.0)
        finally:
            agent.close()

    def test_multi_agent_broadcast(self, cfg):
        # The reference's ZMQ plane cannot do this (agent-side bind,
        # agent_zmq.rs:632-638); PUB/SUB must reach every agent.
        ports = [free_port() for _ in range(3)]
        server = make_server_transport(
            "zmq", cfg,
            agent_listener_addr=f"tcp://127.0.0.1:{ports[0]}",
            trajectory_addr=f"tcp://127.0.0.1:{ports[1]}",
            model_pub_addr=f"tcp://127.0.0.1:{ports[2]}")
        server.get_model = lambda: (1, b"m1")
        server.start()
        agents, events = [], []
        try:
            for _ in range(3):
                a = make_agent_transport(
                    "zmq", cfg,
                    agent_listener_addr=f"tcp://127.0.0.1:{ports[0]}",
                    trajectory_addr=f"tcp://127.0.0.1:{ports[1]}",
                    model_sub_addr=f"tcp://127.0.0.1:{ports[2]}")
                ev = threading.Event()
                a.on_model = lambda v, m, ev=ev: ev.set()
                a.start_model_listener()
                agents.append(a)
                events.append(ev)
            time.sleep(0.5)
            server.publish_model(2, b"m2")
            for i, ev in enumerate(events):
                assert ev.wait(timeout=10), f"agent {i} missed the broadcast"
        finally:
            for a in agents:
                a.close()
            server.stop()


class TestNativeTransport:
    @pytest.fixture(autouse=True)
    def _require_lib(self):
        from relayrl_tpu.transport.native_backend import native_available

        if not native_available():
            pytest.skip("native library not built (make -C native)")

    def test_full_roundtrip(self, cfg):
        port = free_port()
        server = make_server_transport("native", cfg, bind_addr=f"127.0.0.1:{port}")

        def make_agent():
            return make_agent_transport("native", cfg,
                                        server_addr=f"127.0.0.1:{port}")

        _roundtrip(server, make_agent)

    def test_handshake_timeout_when_no_server(self, cfg):
        agent = make_agent_transport("native", cfg,
                                     server_addr=f"127.0.0.1:{free_port()}")
        try:
            with pytest.raises(TimeoutError):
                agent.fetch_model(timeout_s=1.0)
        finally:
            agent.close()

    def test_large_model_broadcast(self, cfg):
        # model bigger than the binding's initial 1 MiB buffer: exercises the
        # grow-and-retry path on both handshake and subscription channels
        port = free_port()
        server = make_server_transport("native", cfg, bind_addr=f"127.0.0.1:{port}")
        big = bytes(range(256)) * (8 * 1024 * 3)  # ~6 MiB
        server.get_model = lambda: (1, big)
        server.start()
        try:
            agent = make_agent_transport("native", cfg,
                                         server_addr=f"127.0.0.1:{port}")
            try:
                ver, fetched = agent.fetch_model(timeout_s=15)
                assert ver == 1 and fetched == big
                got = threading.Event()
                out = {}

                def on_model(v, m):
                    out["m"] = (v, m)
                    got.set()

                agent.on_model = on_model
                agent.start_model_listener()
                time.sleep(0.3)
                server.publish_model(2, big + b"tail")
                assert got.wait(timeout=15)
                assert out["m"][0] == 2 and out["m"][1] == big + b"tail"
            finally:
                agent.close()
        finally:
            server.stop()


class TestNativeReconnect:
    @pytest.fixture(autouse=True)
    def _require_lib(self):
        from relayrl_tpu.transport.native_backend import native_available

        if not native_available():
            pytest.skip("native library not built (make -C native)")

    def test_ping_alive(self, cfg):
        port = free_port()
        server = make_server_transport("native", cfg,
                                       bind_addr=f"127.0.0.1:{port}")
        server.start()
        try:
            agent = make_agent_transport("native", cfg,
                                         server_addr=f"127.0.0.1:{port}")
            try:
                agent.fetch_model(timeout_s=10)
                assert agent.ping() == 0
            finally:
                agent.close()
        finally:
            server.stop()

    def test_traj_send_survives_server_restart(self, cfg):
        port = free_port()
        server = make_server_transport("native", cfg,
                                       bind_addr=f"127.0.0.1:{port}")
        got = []
        server.on_trajectory = lambda aid, p: got.append(p)
        server.start()
        agent = make_agent_transport("native", cfg,
                                     server_addr=f"127.0.0.1:{port}")
        try:
            agent.fetch_model(timeout_s=10)
            agent.send_trajectory(b"before")
            server.stop()

            server2 = make_server_transport("native", cfg,
                                            bind_addr=f"127.0.0.1:{port}")
            got2 = []
            server2.on_trajectory = lambda aid, p: got2.append(p)
            server2.start()
            try:
                # The C++ client redials the stored endpoint on the failed
                # send and retries once — no new transport object needed.
                deadline = time.monotonic() + 10
                while not got2 and time.monotonic() < deadline:
                    try:
                        agent.send_trajectory(b"after")
                    except RuntimeError:
                        pass  # redial window still open
                    time.sleep(0.1)
                assert got2 and got2[-1] == b"after"
            finally:
                server2.stop()
        finally:
            agent.close()

    def test_sub_resubscribes_after_restart(self, cfg):
        port = free_port()
        server = make_server_transport("native", cfg,
                                       bind_addr=f"127.0.0.1:{port}")
        server.start()
        agent = make_agent_transport("native", cfg,
                                     server_addr=f"127.0.0.1:{port}")
        try:
            agent.fetch_model(timeout_s=10)
            got = threading.Event()
            agent.on_model = lambda v, m: got.set()
            agent.start_model_listener()
            time.sleep(0.3)
            server.stop()
            server2 = make_server_transport("native", cfg,
                                            bind_addr=f"127.0.0.1:{port}")
            server2.start()
            try:
                # sub loop notices the dead socket, redials, replays the
                # Subscribe frame; the next broadcast must arrive.
                deadline = time.monotonic() + 10
                while not got.is_set() and time.monotonic() < deadline:
                    server2.publish_model(5, b"post-restart")
                    time.sleep(0.25)
                assert got.is_set(), "subscriber never recovered"
            finally:
                server2.stop()
        finally:
            agent.close()

    def test_idle_reaping_server_stays_up(self, cfg):
        from relayrl_tpu.transport.native_backend import NativeServerTransport

        port = free_port()
        server = NativeServerTransport(bind_addr=f"127.0.0.1:{port}",
                                       idle_timeout_s=0.3)
        server.start()
        try:
            agent = make_agent_transport("native", cfg,
                                         server_addr=f"127.0.0.1:{port}")
            try:
                agent.fetch_model(timeout_s=10)
                time.sleep(1.0)  # connection idles past the reap timeout
                # Reaped server-side; the client's next send redials.
                agent.send_trajectory(b"again")
                assert agent.ping(timeout_s=2.0) in (0, 1)
            finally:
                agent.close()
        finally:
            server.stop()


class TestElasticRegistry:
    """Unregister-on-death (beyond the reference: its registry is an
    append-only Vec, training_server_wrapper.rs:159-163). The native
    server maps each control connection to the agent id it registered and
    reports the id when the connection dies, so fleets under churn reap
    ghosts."""

    @pytest.fixture(autouse=True)
    def _require_lib(self):
        from relayrl_tpu.transport.native_backend import native_available

        if not native_available():
            pytest.skip("native library not built (make -C native)")

    def test_unregister_fires_when_connection_dies(self, cfg):
        port = free_port()
        server = make_server_transport("native", cfg,
                                       bind_addr=f"127.0.0.1:{port}")
        regs, unregs = [], []
        server.on_register = regs.append
        server.on_unregister = unregs.append
        server.start()
        try:
            agent = make_agent_transport("native", cfg,
                                         server_addr=f"127.0.0.1:{port}")
            agent.fetch_model(timeout_s=10)
            assert agent.register("agent-A", timeout_s=10)
            deadline = time.monotonic() + 5
            while "agent-A" not in regs and time.monotonic() < deadline:
                time.sleep(0.02)
            assert regs == ["agent-A"]

            agent.close()  # kernel closes the control conn (same path as
            #                a crash/kill -9: read returns 0 server-side)
            deadline = time.monotonic() + 10
            while "agent-A" not in unregs and time.monotonic() < deadline:
                time.sleep(0.02)
            assert unregs == ["agent-A"]
        finally:
            server.stop()

    def test_reconnect_replays_registration(self, cfg):
        # A transient disconnect self-heals via the C++ redial; the
        # registration must be replayed so the (restarted) server's
        # registry still contains the live agent.
        port = free_port()
        server = make_server_transport("native", cfg,
                                       bind_addr=f"127.0.0.1:{port}")
        server.start()
        agent = make_agent_transport("native", cfg,
                                     server_addr=f"127.0.0.1:{port}")
        try:
            agent.fetch_model(timeout_s=10)
            assert agent.register("agent-R", timeout_s=10)
            server.stop()

            server2 = make_server_transport("native", cfg,
                                            bind_addr=f"127.0.0.1:{port}")
            regs2 = []
            server2.on_register = regs2.append
            server2.start()
            try:
                deadline = time.monotonic() + 10
                while "agent-R" not in regs2 and time.monotonic() < deadline:
                    try:
                        agent.send_trajectory(b"t")  # forces redial+replay
                    except RuntimeError:
                        pass
                    time.sleep(0.1)
                assert "agent-R" in regs2, "registration not replayed"
            finally:
                server2.stop()
        finally:
            agent.close()


class TestGrpcTransport:
    def test_full_roundtrip(self, cfg):
        port = free_port()
        server = make_server_transport("grpc", cfg, bind_addr=f"127.0.0.1:{port}")
        server.idle_timeout_s = 5.0

        def make_agent():
            return make_agent_transport("grpc", cfg, server_addr=f"127.0.0.1:{port}")

        _roundtrip(server, make_agent)

    def test_long_poll_times_out_cleanly(self, cfg):
        port = free_port()
        server = make_server_transport("grpc", cfg, bind_addr=f"127.0.0.1:{port}")
        server.idle_timeout_s = 0.5
        server.get_model = lambda: (1, b"m")
        server.start()
        try:
            agent = make_agent_transport("grpc", cfg, server_addr=f"127.0.0.1:{port}")
            try:
                ver, _ = agent.fetch_model(timeout_s=5)
                assert ver == 1
                t0 = time.monotonic()
                assert agent._poll_once(first=False, timeout_s=10) is None
                assert time.monotonic() - t0 < 5, "long poll ignored idle timeout"
            finally:
                agent.close()
        finally:
            server.stop()


class TestProtocolNegotiation:
    """probe_endpoint + mixed-fleet fail-fast (VERDICT r2 weak #3: a
    mismatched pair must error at construction, not time out remotely)."""

    def test_probe_identifies_zmq(self, cfg):
        ports = [free_port() for _ in range(3)]
        server = make_server_transport(
            "zmq", cfg,
            agent_listener_addr=f"tcp://127.0.0.1:{ports[0]}",
            trajectory_addr=f"tcp://127.0.0.1:{ports[1]}",
            model_pub_addr=f"tcp://127.0.0.1:{ports[2]}")
        server.start()
        try:
            from relayrl_tpu.transport import probe_endpoint

            assert probe_endpoint("127.0.0.1", ports[0]) == "zmq"
        finally:
            server.stop()

    def test_probe_identifies_native(self, cfg):
        from relayrl_tpu.transport.native_backend import native_available

        if not native_available():
            pytest.skip("native library not built")
        from relayrl_tpu.transport import probe_endpoint

        port = free_port()
        server = make_server_transport("native", cfg,
                                       bind_addr=f"127.0.0.1:{port}")
        server.start()
        try:
            assert probe_endpoint("127.0.0.1", port) == "native"
        finally:
            server.stop()

    def test_probe_identifies_grpc(self, cfg):
        from relayrl_tpu.transport import probe_endpoint

        port = free_port()
        server = make_server_transport("grpc", cfg,
                                       bind_addr=f"127.0.0.1:{port}")
        server.start()
        try:
            assert probe_endpoint("127.0.0.1", port) == "grpc"
        finally:
            server.stop()

    def test_probe_unreachable(self):
        from relayrl_tpu.transport import probe_endpoint

        assert probe_endpoint("127.0.0.1", free_port()) == "unreachable"

    def test_typoed_server_type_raises_value_error(self, cfg):
        # A typo must surface as the ValueError, not burn probe time or
        # masquerade as a protocol mismatch.
        with pytest.raises(ValueError, match="unknown server_type"):
            make_agent_transport("zqm", cfg)

    def test_mismatched_pair_errors_fast(self, cfg):
        # A native agent pointed at a zmq server must raise within 1 s
        # instead of retrying fetch_model into a timeout.
        from relayrl_tpu.transport import ProtocolMismatchError
        from relayrl_tpu.transport.native_backend import native_available

        if not native_available():
            pytest.skip("native library not built")
        ports = [free_port() for _ in range(3)]
        server = make_server_transport(
            "zmq", cfg,
            agent_listener_addr=f"tcp://127.0.0.1:{ports[0]}",
            trajectory_addr=f"tcp://127.0.0.1:{ports[1]}",
            model_pub_addr=f"tcp://127.0.0.1:{ports[2]}")
        server.start()
        try:
            t0 = time.monotonic()
            with pytest.raises(ProtocolMismatchError, match="zmq"):
                make_agent_transport("native", cfg,
                                     server_addr=f"127.0.0.1:{ports[0]}")
            assert time.monotonic() - t0 < 1.0
        finally:
            server.stop()

    def test_zmq_agent_against_native_server_errors_fast(self, cfg):
        from relayrl_tpu.transport import ProtocolMismatchError
        from relayrl_tpu.transport.native_backend import native_available

        if not native_available():
            pytest.skip("native library not built")
        port = free_port()
        server = make_server_transport("native", cfg,
                                       bind_addr=f"127.0.0.1:{port}")
        server.start()
        try:
            t0 = time.monotonic()
            with pytest.raises(ProtocolMismatchError, match="native"):
                make_agent_transport(
                    "zmq", cfg,
                    agent_listener_addr=f"tcp://127.0.0.1:{port}",
                    trajectory_addr=f"tcp://127.0.0.1:{free_port()}",
                    model_sub_addr=f"tcp://127.0.0.1:{free_port()}")
            assert time.monotonic() - t0 < 1.0
        finally:
            server.stop()

    def test_auto_agent_negotiates_to_live_server(self, cfg):
        # Even when the native .so is available locally (old auto would
        # pick native), an auto agent must converge on the server's
        # actual protocol.
        ports = [free_port() for _ in range(3)]
        server = make_server_transport(
            "zmq", cfg,
            agent_listener_addr=f"tcp://127.0.0.1:{ports[0]}",
            trajectory_addr=f"tcp://127.0.0.1:{ports[1]}",
            model_pub_addr=f"tcp://127.0.0.1:{ports[2]}")
        server.get_model = lambda: (3, b"negotiated")
        server.start()
        try:
            agent = make_agent_transport(
                "auto", cfg,
                server_addr=f"127.0.0.1:{free_port()}",  # native: dead
                agent_listener_addr=f"tcp://127.0.0.1:{ports[0]}",
                trajectory_addr=f"tcp://127.0.0.1:{ports[1]}",
                model_sub_addr=f"tcp://127.0.0.1:{ports[2]}")
            try:
                assert agent.fetch_model(timeout_s=10) == (3, b"negotiated")
            finally:
                agent.close()
        finally:
            server.stop()


class TestAutoBackend:
    def test_auto_resolves_to_native_or_zmq(self, tmp_cwd):
        from relayrl_tpu.transport import _resolve_auto
        from relayrl_tpu.transport.native_backend import native_available

        want = "native" if native_available() else "zmq"
        assert _resolve_auto() == want

    def test_auto_builds_matching_pair(self, tmp_cwd):
        # server_type="auto" must yield a working server/agent pair
        # end-to-end (whichever backend it resolves to).
        import threading

        from relayrl_tpu.config import ConfigLoader
        from relayrl_tpu.transport import (
            make_agent_transport,
            make_server_transport,
        )

        cfg = ConfigLoader(None, None)
        port = free_port()
        overrides_server = {
            "bind_addr": f"127.0.0.1:{port}",
            "agent_listener_addr": f"tcp://127.0.0.1:{port}",
            "trajectory_addr": f"tcp://127.0.0.1:{free_port()}",
            "model_pub_addr": f"tcp://127.0.0.1:{free_port()}",
        }
        server = make_server_transport("auto", cfg, **overrides_server)
        got = []
        done = threading.Event()
        server.get_model = lambda: (7, b"params")
        server.on_trajectory = lambda aid, p: (got.append(p), done.set())
        server.start()
        agent_overrides = {
            "server_addr": overrides_server["bind_addr"],
            "agent_listener_addr": overrides_server["agent_listener_addr"],
            "trajectory_addr": overrides_server["trajectory_addr"],
            "model_sub_addr": overrides_server["model_pub_addr"],
        }
        agent = make_agent_transport("auto", cfg, **agent_overrides)
        try:
            version, payload = agent.fetch_model(timeout_s=30)
            assert (version, payload) == (7, b"params")
            agent.send_trajectory(b"episode-bytes")
            assert done.wait(timeout=30)
            assert got == [b"episode-bytes"]
        finally:
            agent.close()
            server.stop()
