"""MoE per-token top-k routing + expert parallelism over the ``ep`` axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relayrl_tpu.models import build_policy
from relayrl_tpu.parallel import make_mesh
from relayrl_tpu.parallel.sharding import param_pspec

ARCH = {"kind": "transformer_moe_discrete", "obs_dim": 6, "act_dim": 3,
        "d_model": 16, "n_layers": 2, "n_heads": 2, "max_seq_len": 8,
        "moe_experts": 4}


def _policy_params(seed=0):
    policy = build_policy(ARCH)
    return policy, policy.init_params(jax.random.PRNGKey(seed))


class TestMoELayer:
    def test_expert_weights_stacked(self):
        _, params = _policy_params()
        moe = params["params"]["block_0"]["moe"]
        assert moe["moe_w_up"].shape == (4, 16, 64)
        assert moe["moe_w_down"].shape == (4, 64, 16)

    def test_forward_finite_and_batch_shaped(self):
        policy, params = _policy_params()
        obs = jnp.asarray(
            np.random.default_rng(0).standard_normal((3, 8, 6)), jnp.float32)
        logp, ent, v = policy.evaluate(params, obs,
                                       jnp.zeros((3, 8), jnp.int32))
        assert logp.shape == (3, 8)
        assert bool(jnp.isfinite(logp).all() and jnp.isfinite(v).all())

    def test_causal_routing(self):
        # Per-token routing must keep the policy causal: logp at step t may
        # not change when FUTURE observations change (capacity-competition
        # routing schemes violate this — the reason top-k per token was
        # chosen; see models/moe.py docstring).
        policy, params = _policy_params()
        rng = np.random.default_rng(3)
        obs = jnp.asarray(rng.standard_normal((1, 8, 6)), jnp.float32)
        act = jnp.zeros((1, 8), jnp.int32)
        obs2 = obs.at[:, 5:].set(
            jnp.asarray(rng.standard_normal((1, 3, 6)), jnp.float32))
        logp1, _, v1 = policy.evaluate(params, obs, act)
        logp2, _, v2 = policy.evaluate(params, obs2, act)
        np.testing.assert_allclose(logp1[0, :5], logp2[0, :5],
                                   atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(v1[0, :5], v2[0, :5],
                                   atol=1e-6, rtol=1e-6)

    def test_single_expert_builds(self):
        # moe_experts=1 (and init's 1-token trace) must not crash top_k.
        policy = build_policy({**ARCH, "moe_experts": 1})
        params = policy.init_params(jax.random.PRNGKey(0))
        obs = jnp.zeros((1, 8, 6), jnp.float32)
        logp, _, _ = policy.evaluate(params, obs, jnp.zeros((1, 8), jnp.int32))
        assert bool(jnp.isfinite(logp).all())

    def test_grads_reach_every_expert(self):
        # With top-2 of 4 experts over 16 tokens, every expert receives
        # assignments at init (uniform-ish gate) — all must get gradient.
        policy, params = _policy_params()
        obs = jnp.asarray(
            np.random.default_rng(1).standard_normal((2, 8, 6)), jnp.float32)

        def loss(p):
            logp, ent, v = policy.evaluate(p, obs,
                                           jnp.zeros((2, 8), jnp.int32))
            return logp.sum() + v.sum()

        g = jax.grad(loss)(params)
        for layer in ("block_0", "block_1"):
            mass = jnp.abs(g["params"][layer]["moe"]["moe_w_up"]).sum((1, 2))
            assert bool((mass > 0).all()), f"{layer}: dead expert {mass}"

    def test_moe_differs_from_dense_family(self):
        dense_arch = {**ARCH, "kind": "transformer_discrete"}
        dense_arch.pop("moe_experts")
        dense = build_policy(dense_arch)
        p = dense.init_params(jax.random.PRNGKey(0))
        assert "moe" not in p["params"]["block_0"]
        assert "mlp_up" in p["params"]["block_0"]


class TestExpertParallel:
    def test_expert_pspec(self):
        mesh = make_mesh({"dp": -1, "ep": 4})
        key = jax.tree_util.DictKey
        path = (key("params"), key("block_0"), key("moe"), key("moe_w_up"))
        spec = param_pspec(path, jnp.zeros((4, 16, 64)), mesh)
        assert spec[0] == "ep"
        # the gate must stay replicated
        gate_path = (key("params"), key("block_0"), key("moe"),
                     key("moe_gate"), key("kernel"))
        assert param_pspec(gate_path, jnp.zeros((16, 4)), mesh) == \
            jax.sharding.PartitionSpec()

    # ISSUE 17 wall re-fit: the heaviest compile in the fast wall (~30 s
    # on the 1-core CI host); ep-mesh stepping stays covered fast by the
    # MULTICHIP dryrun and the dp-mesh pipelined locks in
    # tests/test_multichip_pipeline.py.
    @pytest.mark.slow
    def test_sharded_update_on_ep_mesh(self):
        from relayrl_tpu.algorithms.reinforce import (
            ReinforceState,
            make_optimizers,
            make_reinforce_update,
        )
        from relayrl_tpu.parallel import (
            make_sharded_update,
            place_batch,
            place_state,
        )

        mesh = make_mesh({"dp": 2, "ep": 4})
        policy, params = _policy_params()
        tx_pi, tx_vf = make_optimizers(params, 3e-4, 1e-3)
        state = ReinforceState(params=params, pi_opt_state=tx_pi.init(params),
                               vf_opt_state=tx_vf.init(params),
                               rng=jax.random.PRNGKey(1), step=jnp.int32(0))
        update = make_reinforce_update(policy, 3e-4, 1e-3, 1, 0.99, 0.95,
                                       with_baseline=True)
        rng = np.random.default_rng(0)
        B, T = 8, 8
        batch = {
            "obs": rng.standard_normal((B, T, 6)).astype(np.float32),
            "act": rng.integers(0, 3, (B, T)).astype(np.int32),
            "act_mask": np.ones((B, T, 3), np.float32),
            "rew": np.ones((B, T), np.float32),
            "val": np.zeros((B, T), np.float32),
            "logp": np.zeros((B, T), np.float32),
            "valid": np.ones((B, T), np.float32),
            "last_val": np.zeros((B,), np.float32),
        }
        sharded = make_sharded_update(update, mesh, state, donate_state=False)
        new_state, metrics = sharded(place_state(state, mesh),
                                     place_batch(batch, mesh))
        jax.block_until_ready(new_state)
        assert int(new_state.step) == 1
        assert np.isfinite(float(metrics["LossPi"]))
        # result must match the unsharded update (same math, GSPMD layout)
        single = update(state, {k: jnp.asarray(v) for k, v in batch.items()})
        np.testing.assert_allclose(
            float(metrics["LossPi"]), float(single[1]["LossPi"]),
            atol=1e-4, rtol=1e-4)


class TestUtilizationMonitor:
    def test_fractions_sum_to_one_per_layer(self):
        from relayrl_tpu.models.moe import expert_utilization

        policy, params = _policy_params()
        obs = np.random.default_rng(5).standard_normal((2, 8, 6)).astype(
            np.float32)
        util = expert_utilization(ARCH, params, obs)
        assert set(util) == {"block_0", "block_1"}
        for layer, frac in util.items():
            assert frac.shape == (4,)
            np.testing.assert_allclose(float(frac.sum()), 1.0, atol=1e-5)
            # near-uniform at init: no expert should be collapsed-out
            assert float(frac.max()) < 0.9, (layer, frac)
