"""Wheel-bundled native plane: loader preference + setup.py contract.

The full proof is CI's installed-wheel smoke (scripts/wheel_smoke.py in
a clean venv — ci.yml `wheel` job); these are the fast in-tree contract
pieces: the ctypes-extension filename mapping that puts the .so INSIDE
the package, and the loader preferring a bundled library over the
source-tree one so an installed user never silently downgrades.
"""

import os

from relayrl_tpu.transport import native_backend


class TestLoaderPreference:
    def test_bundled_library_wins(self, monkeypatch, tmp_path):
        fake = tmp_path / "librelayrl_native.so"
        fake.write_bytes(b"")
        import relayrl_tpu._native as native_pkg

        monkeypatch.setattr(native_pkg, "bundled_library_path",
                            lambda: str(fake))
        assert native_backend._find_library() == str(fake)

    def test_source_tree_fallback(self, monkeypatch):
        import relayrl_tpu._native as native_pkg

        monkeypatch.setattr(native_pkg, "bundled_library_path", lambda: None)
        found = native_backend._find_library()
        # In this checkout the make-built lib exists; wherever it is, it
        # must NOT claim to be the bundled one.
        if found is not None:
            assert os.sep + "_native" + os.sep not in found

    def test_bundled_path_helper_is_honest(self):
        from relayrl_tpu._native import bundled_library_path

        p = bundled_library_path()
        # Source checkout: no .so inside the package dir (wheel builds
        # put it there); if present it must exist.
        assert p is None or os.path.isfile(p)


class TestSetupContract:
    def _mod(self):
        import importlib.util
        import sys

        spec = importlib.util.spec_from_file_location(
            "relayrl_setup", os.path.join(os.path.dirname(__file__),
                                          os.pardir, "setup.py"))
        mod = importlib.util.module_from_spec(spec)
        # setup() runs on import; neuter it
        import setuptools

        orig = setuptools.setup
        setuptools.setup = lambda **kw: None
        try:
            sys.modules["relayrl_setup"] = mod
            spec.loader.exec_module(mod)
        finally:
            setuptools.setup = orig
            sys.modules.pop("relayrl_setup", None)
        return mod

    def test_ext_filename_has_no_python_abi_suffix(self):
        mod = self._mod()
        builder = mod.build_ctypes_ext.__new__(mod.build_ctypes_ext)
        got = builder.get_ext_filename("relayrl_tpu._native.relayrl_native")
        assert got == os.path.join("relayrl_tpu", "_native",
                                   "librelayrl_native.so")

    def test_wheel_tag_is_py3_none(self):
        # the .so is ctypes — the wheel must not claim a CPython ABI
        mod = self._mod()
        src = open(os.path.join(os.path.dirname(__file__), os.pardir,
                                "setup.py")).read()
        assert '"py3", "none", plat' in src

    def test_ext_sources_exist_and_cover_native(self):
        mod = self._mod()
        repo = os.path.join(os.path.dirname(__file__), os.pardir)
        src = open(os.path.join(repo, "setup.py")).read()
        for cc in ("transport.cc", "codec.cc", "grpc_server.cc"):
            assert cc in src, f"setup.py must compile native/{cc}"
            assert os.path.isfile(os.path.join(repo, "native", cc))
