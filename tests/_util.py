"""Shared test helpers (imported as ``_util`` — conftest adds tests/ to
sys.path via rootdir)."""

import socket


def free_port() -> int:
    """An ephemeral localhost port (bound momentarily, then released)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
