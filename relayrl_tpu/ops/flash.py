"""Fused flash-attention Pallas TPU kernel (forward) + blockwise VJP.

The reference has no attention at all (SURVEY.md §5.7 — its largest model
is a 2x128 MLP, relayrl_framework/src/native/python/algorithms/REINFORCE/
kernel.py:14-21); :mod:`relayrl_tpu.ops.attention` adds dense and blockwise
(lax.scan online-softmax) variants. This module is the TPU-kernel tier of
the same op: one fused Pallas kernel that keeps the running-softmax state
``(acc, m, l)`` in VMEM scratch across the KV grid axis, so the [Tq, Tk]
score matrix never materializes in HBM and the two matmuls per block hit
the MXU back-to-back.

Grid layout: ``(B*H, num_q_blocks, num_kv_blocks)`` with the KV axis
innermost — TPU grids execute sequentially, so scratch initialized at
``kv == 0`` and finalized at ``kv == last`` implements the flash
recurrence without inter-kernel communication. Causal blocks strictly
above the diagonal are predicated off with ``pl.when`` (their loads still
happen — index maps are static — but the matmuls are skipped).

The backward pass recomputes attention blockwise in plain JAX from the
saved ``(out, lse)`` residuals — the standard flash-attention VJP identity

    ds = p * (dp - rowsum(do * o))

with O(T * block) peak memory, letting XLA fuse it; a hand-written Pallas
backward kernel is a further step if profiles demand it.

Numerics: scores/softmax in float32 regardless of input dtype; the second
matmul runs in float32 against the f32 accumulator (MXU-friendly since
p is produced on-core). Outputs cast back to the input dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, causal: bool, block_q: int, block_kv: int, scale: float):
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = pl.program_id(1) * block_q
    k_start = ik * block_kv
    # Causal: the whole KV block is masked iff its first key comes after the
    # last query of this Q block.
    live = (k_start <= q_start + block_q - 1) if causal else True

    @pl.when(live)
    def _block():
        # Inputs stay in their storage dtype (bf16 in production): the MXU
        # runs bf16 x bf16 -> f32 at full rate, while casting to f32 first
        # would quarter the matmul throughput. Softmax math is f32.
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # Masked entries carry s == _NEG_INF; exp(s - m_new) underflows to 0
        # except when m_new itself is _NEG_INF (a fully-masked row, which
        # causal + ik==0 never produces for valid rows) — guard anyway.
        p = jnp.where(s > 0.5 * _NEG_INF, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(ik == pl.num_programs(2) - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[:] = (m_ref[:] + jnp.log(l)).reshape(lse_ref.shape)


@functools.lru_cache(maxsize=None)
def _build_fwd(T: int, D: int, causal: bool, block_q: int, block_kv: int,
               in_dtype_name: str, interpret: bool):
    """Compile-cached pallas_call for a [BH, T, D] layout forward."""
    kernel = functools.partial(
        _fwd_kernel, causal=causal, block_q=block_q, block_kv=block_kv,
        scale=1.0 / (D ** 0.5))
    grid = (None, T // block_q, T // block_kv)  # BH filled per call

    def call(qr, kr, vr):
        bh = qr.shape[0]
        return pl.pallas_call(
            kernel,
            grid=(bh,) + grid[1:],
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_kv, D), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_kv, D), lambda b, i, j: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                # Trailing singleton keeps the lse block (block_q, 1)-tiled,
                # which the Mosaic layout rules accept (a bare (1, block_q)
                # block would violate the (8, 128) tile constraint).
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, T, D), jnp.dtype(in_dtype_name)),
                jax.ShapeDtypeStruct((bh, T, 1), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, D), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
            ],
            interpret=interpret,
        )(qr, kr, vr)

    return call


def _bthd_to_bht(x):
    """[B,T,H,D] -> [B*H, T, D] (the kernel's flat layout)."""
    B, T, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)


def _bht_to_bthd(x, B, H):
    BH, T, D = x.shape
    return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def _fwd(q, k, v, causal, block_q, block_kv, interpret):
    B, T, H, D = q.shape
    call = _build_fwd(T, D, causal, block_q, block_kv, q.dtype.name,
                      interpret)
    out, lse = call(_bthd_to_bht(q), _bthd_to_bht(k), _bthd_to_bht(v))
    return _bht_to_bthd(out, B, H), lse.reshape(B, H, T)


def _bwd_blockwise(q, k, v, out, lse, do, causal, block_kv):
    """Flash-attention VJP by blockwise recompute from (out, lse).

    All math in f32 over the flat [BH, T, D] layout; a lax.scan over KV
    blocks bounds peak memory at O(T * block_kv) like the forward.
    """
    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    qf = _bthd_to_bht(q).astype(jnp.float32)
    kf = _bthd_to_bht(k).astype(jnp.float32)
    vf = _bthd_to_bht(v).astype(jnp.float32)
    dof = _bthd_to_bht(do).astype(jnp.float32)
    of = _bthd_to_bht(out).astype(jnp.float32)
    lsef = lse.reshape(B * H, T)

    delta = jnp.sum(dof * of, axis=-1)          # [BH, T]
    n_blocks = T // block_kv
    k_blocks = jnp.moveaxis(kf.reshape(-1, n_blocks, block_kv, D), 1, 0)
    v_blocks = jnp.moveaxis(vf.reshape(-1, n_blocks, block_kv, D), 1, 0)
    q_pos = jnp.arange(T)

    def scan_step(dq, blk):
        k_blk, v_blk, j = blk
        kv_pos = j * block_kv + jnp.arange(block_kv)
        s = jnp.einsum("bqd,bkd->bqk", qf, k_blk,
                       preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lsef[..., None])
        if causal:
            p = jnp.where((q_pos[:, None] >= kv_pos[None, :])[None], p, 0.0)
        dv_j = jnp.einsum("bqk,bqd->bkd", p, dof,
                          preferred_element_type=jnp.float32)
        dp = jnp.einsum("bqd,bkd->bqk", dof, v_blk,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, k_blk,
                             preferred_element_type=jnp.float32)
        dk_j = jnp.einsum("bqk,bqd->bkd", ds, qf,
                          preferred_element_type=jnp.float32)
        return dq, (dk_j, dv_j)

    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        scan_step, jnp.zeros_like(qf),
        (k_blocks, v_blocks, jnp.arange(n_blocks)))
    dk = jnp.moveaxis(dk_blocks, 0, 1).reshape(-1, T, D)
    dv = jnp.moveaxis(dv_blocks, 0, 1).reshape(-1, T, D)
    return (_bht_to_bthd(dq, B, H).astype(q.dtype),
            _bht_to_bthd(dk, B, H).astype(k.dtype),
            _bht_to_bthd(dv, B, H).astype(v.dtype))


@functools.lru_cache(maxsize=None)
def _make_flash(causal: bool, block_q: int, block_kv: int, interpret: bool):
    @jax.custom_vjp
    def flash(q, k, v):
        out, _ = _fwd(q, k, v, causal, block_q, block_kv, interpret)
        return out

    def fwd(q, k, v):
        out, lse = _fwd(q, k, v, causal, block_q, block_kv, interpret)
        return out, (q, k, v, out, lse)

    def bwd(res, do):
        q, k, v, out, lse = res
        return _bwd_blockwise(q, k, v, out, lse, do, causal, block_kv)

    flash.defvjp(fwd, bwd)
    return flash


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, block_q: int = 128,
                    block_kv: int = 128,
                    interpret: bool | None = None) -> jax.Array:
    """Fused attention on ``[B, T, H, D]`` via a Pallas TPU kernel.

    ``interpret=None`` auto-selects: compiled on TPU backends, interpreter
    mode elsewhere (slow — tests only; CPU production paths should call
    :func:`relayrl_tpu.ops.attention.blockwise_attention` instead, which is
    what the model-level ``attention="flash"`` config does off-TPU).
    Requires ``T`` divisible by both block sizes; callers pad or fall back.
    """
    B, T, H, D = q.shape
    block_q = min(block_q, T)
    block_kv = min(block_kv, T)
    if T % block_q or T % block_kv:
        raise ValueError(
            f"seq len {T} not divisible by blocks ({block_q}, {block_kv})")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    return _make_flash(causal, block_q, block_kv, interpret)(q, k, v)
