"""Fused flash-attention Pallas TPU kernels (forward + two-pass VJP).

The reference has no attention at all (SURVEY.md §5.7 — its largest model
is a 2x128 MLP, relayrl_framework/src/native/python/algorithms/REINFORCE/
kernel.py:14-21); :mod:`relayrl_tpu.ops.attention` adds dense and blockwise
(lax.scan online-softmax) variants. This module is the TPU-kernel tier of
the same op: one fused Pallas kernel that keeps the running-softmax state
``(acc, m, l)`` in VMEM scratch across the KV grid axis, so the [Tq, Tk]
score matrix never materializes in HBM and the two matmuls per block hit
the MXU back-to-back.

Grid layout: ``(B*H, num_q_blocks, num_kv_blocks)`` with the KV axis
innermost — TPU grids execute sequentially, so scratch initialized at
``kv == 0`` and finalized at ``kv == last`` implements the flash
recurrence without inter-kernel communication. Causal blocks strictly
above the diagonal are predicated off with ``pl.when`` (their loads still
happen — index maps are static — but the matmuls are skipped).

The backward pass is two more Pallas kernels (the standard two-pass flash
VJP — no atomics or cross-block communication): a dq pass (grid q-major,
KV innermost, accumulator in VMEM) and a dk/dv pass (grid kv-major, Q
innermost), both recomputing p from the saved log-sum-exp residual and
using the identity ``ds = p * (dp - rowsum(do * o))``. Peak memory stays
O(T * block).

VPU economy (the kernels are partly elementwise-bound at head_dim 64 —
the two block matmuls only quarter-fill the MXU contraction depth, so the
[block_q, block_kv] softmax traffic shows up on the critical path; a
same-session on-chip A/B measured the changes below 2.2x faster fwd at
T=2048 / 1.4x at T=8192 on v5e — ratios, not absolute ms, since the
tunneled chip's throughput drifts between sessions; benches/README.md
carries the caveat):

* **log2-space softmax**: ``1/sqrt(D) * log2(e)`` is folded into q OUTSIDE
  the kernel (one fused elementwise on the [BH, T, D] operand, 16x fewer
  multiplies than scaling every [block_q, block_kv] score tile), so the
  in-kernel recurrence uses ``exp2`` — faster than ``exp`` on the VPU —
  and the saved residual is the log2-space LSE. The backward finalizers
  undo the folding per output tile: ``dq = scale * acc`` and
  ``dk = acc / log2(e)`` (dk's score-recompute contracts against the
  pre-scaled q), a [block, D]-sized multiply once per block instead of a
  [block_q, block_kv] one per grid step.
* **diagonal specialization**: causal masking (two iotas, a compare and a
  select over the full score tile) runs only on blocks that straddle the
  diagonal; strictly-below blocks take a mask-free path. The separate
  underflow guard the masked path used to carry is gone: with the KV axis
  innermost the first block (k_start = 0) is live for every query row, so
  the running max is finite from step 0 and ``exp2(-1e30 - m)`` flushes
  to exactly 0 for masked entries.

Numerics: scores/softmax in float32 regardless of input dtype; the second
matmul runs in float32 against the f32 accumulator (MXU-friendly since
p is produced on-core). Outputs cast back to the input dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LOG2E = 1.4426950408889634


def _masked_scores2(q_ref, k_ref, q_start, k_start, masked: bool,
                    block_q: int, block_kv: int):
    """Log2-space score tile for the current block pair — the recompute
    shared by the forward and both backward kernels. q arrives pre-scaled
    by ``log2(e)/sqrt(D)`` so no per-tile multiply is needed. Inputs stay
    in their storage dtype (bf16 in production): the MXU runs
    bf16 x bf16 -> f32 at full rate, while casting to f32 first would
    quarter the matmul throughput; softmax math stays f32."""
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if masked:
        q_pos = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 0)
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    return s


def _dispatch(update, q_ref, k_ref, q_start, k_start, causal: bool,
              block_q: int, block_kv: int):
    """Shared block-class dispatch for all three kernels: skip blocks
    strictly above the causal diagonal, run mask-free on ``interior``
    blocks (strictly at-or-below it), and pay the iota/compare/select
    masking only on blocks that straddle the diagonal. ``live`` iff the
    block's first key comes no later than its last query. Keeping this in
    one place keeps forward and backward masking synchronized by
    construction."""
    if not causal:
        update(_masked_scores2(q_ref, k_ref, q_start, k_start, False,
                               block_q, block_kv))
        return
    live = k_start <= q_start + block_q - 1
    interior = k_start + block_kv - 1 <= q_start

    @pl.when(interior)
    def _interior():
        update(_masked_scores2(q_ref, k_ref, q_start, k_start, False,
                               block_q, block_kv))

    @pl.when(live & jnp.logical_not(interior))
    def _diagonal():
        update(_masked_scores2(q_ref, k_ref, q_start, k_start, True,
                               block_q, block_kv))


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, causal: bool, block_q: int, block_kv: int):
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = pl.program_id(1) * block_q
    k_start = ik * block_kv

    def update(s):
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # Masked entries carry s == _NEG_INF; with KV innermost, block
        # ik == 0 is fully live, so m_new is finite for every valid row
        # and exp2(_NEG_INF - m_new) flushes to exactly 0.
        p = jnp.exp2(s - m_new)
        corr = jnp.exp2(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    _dispatch(update, q_ref, k_ref, q_start, k_start, causal,
              block_q, block_kv)

    @pl.when(ik == pl.num_programs(2) - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # log2-space LSE — the backward recomputes p = exp2(s2 - lse2).
        lse_ref[:] = (m_ref[:] + jnp.log2(l)).reshape(lse_ref.shape)


@functools.lru_cache(maxsize=None)
def _build_fwd(T: int, D: int, causal: bool, block_q: int, block_kv: int,
               in_dtype_name: str, interpret: bool):
    """Compile-cached pallas_call for a [BH, T, D] layout forward."""
    kernel = functools.partial(
        _fwd_kernel, causal=causal, block_q=block_q, block_kv=block_kv)
    grid = (None, T // block_q, T // block_kv)  # BH filled per call

    def call(qr, kr, vr):
        bh = qr.shape[0]
        return pl.pallas_call(
            kernel,
            grid=(bh,) + grid[1:],
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_kv, D), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_kv, D), lambda b, i, j: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                # Trailing singleton keeps the lse block (block_q, 1)-tiled,
                # which the Mosaic layout rules accept (a bare (1, block_q)
                # block would violate the (8, 128) tile constraint).
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, T, D), jnp.dtype(in_dtype_name)),
                jax.ShapeDtypeStruct((bh, T, 1), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, D), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
            ],
            interpret=interpret,
        )(qr, kr, vr)

    return call


def _bthd_to_bht(x):
    """[B,T,H,D] -> [B*H, T, D] (the kernel's flat layout)."""
    B, T, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)


def _bht_to_bthd(x, B, H):
    BH, T, D = x.shape
    return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def _prescale_q(qr):
    """Fold softmax scale and the exp->exp2 base change into q: one fused
    elementwise over [BH, T, D] instead of a multiply on every
    [block_q, block_kv] score tile inside the kernels."""
    D = qr.shape[-1]
    c = _LOG2E / (D ** 0.5)
    return (qr.astype(jnp.float32) * c).astype(qr.dtype)


def _fwd(q, k, v, causal, block_q, block_kv, interpret):
    B, T, H, D = q.shape
    call = _build_fwd(T, D, causal, block_q, block_kv, q.dtype.name,
                      interpret)
    out, lse2 = call(_prescale_q(_bthd_to_bht(q)), _bthd_to_bht(k),
                     _bthd_to_bht(v))
    return _bht_to_bthd(out, B, H), lse2.reshape(B, H, T)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, causal: bool, block_q: int, block_kv: int,
               scale: float):
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = pl.program_id(1) * block_q
    k_start = ik * block_kv

    def update(s):
        p = jnp.exp2(s - lse_ref[0])                      # [bq, bk]
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _dispatch(update, q_ref, k_ref, q_start, k_start, causal,
              block_q, block_kv)

    @pl.when(ik == pl.num_programs(2) - 1)
    def _finalize():
        # acc holds d/d(q.k) contractions; one [block_q, D] multiply undoes
        # the score scaling (ds was accumulated in natural space).
        dq_ref[0] = (acc_ref[:] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, causal: bool,
                block_q: int, block_kv: int):
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = iq * block_q
    k_start = pl.program_id(1) * block_kv

    def update(s):
        p = jnp.exp2(s - lse_ref[0])                      # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _dispatch(update, q_ref, k_ref, q_start, k_start, causal,
              block_q, block_kv)

    @pl.when(iq == pl.num_programs(2) - 1)
    def _finalize():
        # dk contracted ds against the PRE-SCALED q (scale * log2e folded
        # in), while true dk = scale * (ds^T @ q_unscaled) — so divide the
        # extra log2e back out. dv never touches scores: exact as-is.
        dk_ref[0] = (dk_acc[:] * (1.0 / _LOG2E)).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


@functools.lru_cache(maxsize=None)
def _build_bwd(T: int, D: int, causal: bool, block_q: int, block_kv: int,
               in_dtype_name: str, interpret: bool):
    """Compile-cached backward pallas_calls over the [BH, T, D] layout:
    a dq pass (grid q-major, KV innermost) and a dk/dv pass (grid kv-major,
    Q innermost) — the standard two-pass flash backward, so neither pass
    needs atomics or cross-block communication."""
    dtype = jnp.dtype(in_dtype_name)
    scale = 1.0 / (D ** 0.5)
    dq_kernel = functools.partial(_dq_kernel, causal=causal, block_q=block_q,
                                  block_kv=block_kv, scale=scale)
    dkv_kernel = functools.partial(_dkv_kernel, causal=causal,
                                   block_q=block_q, block_kv=block_kv)
    row_spec_q = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    row_spec_kv_inner = pl.BlockSpec((1, block_q, 1),
                                     lambda b, j, i: (b, i, 0))

    def call(qr, kr, vr, dor, lse, delta):
        bh = qr.shape[0]
        dq = pl.pallas_call(
            dq_kernel,
            grid=(bh, T // block_q, T // block_kv),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_kv, D), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_kv, D), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                row_spec_q,
                row_spec_q,
            ],
            out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct((bh, T, D), dtype),
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
            interpret=interpret,
        )(qr, kr, vr, dor, lse, delta)
        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid=(bh, T // block_kv, T // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((1, block_kv, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_kv, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
                row_spec_kv_inner,
                row_spec_kv_inner,
            ],
            out_specs=[
                pl.BlockSpec((1, block_kv, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_kv, D), lambda b, j, i: (b, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, T, D), dtype),
                jax.ShapeDtypeStruct((bh, T, D), dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_kv, D), jnp.float32),
                pltpu.VMEM((block_kv, D), jnp.float32),
            ],
            interpret=interpret,
        )(qr, kr, vr, dor, lse, delta)
        return dq, dk, dv

    return call


def _bwd_pallas(q, k, v, out, lse2, do, causal, block_q, block_kv, interpret):
    B, T, H, D = q.shape
    qr, kr, vr, dor = (_bthd_to_bht(x) for x in (q, k, v, do))
    qr = _prescale_q(qr)  # the kernels recompute log2-space scores
    of = _bthd_to_bht(out)
    delta = jnp.sum(dor.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)              # [BH, T, 1]
    lse3 = lse2.reshape(B * H, T, 1)
    call = _build_bwd(T, D, causal, block_q, block_kv, q.dtype.name,
                      interpret)
    dq, dk, dv = call(qr, kr, vr, dor, lse3, delta)
    return (_bht_to_bthd(dq, B, H), _bht_to_bthd(dk, B, H),
            _bht_to_bthd(dv, B, H))


@functools.lru_cache(maxsize=None)
def _make_flash(causal: bool, block_q: int, block_kv: int, interpret: bool):
    @jax.custom_vjp
    def flash(q, k, v):
        out, _ = _fwd(q, k, v, causal, block_q, block_kv, interpret)
        return out

    def fwd(q, k, v):
        out, lse2 = _fwd(q, k, v, causal, block_q, block_kv, interpret)
        return out, (q, k, v, out, lse2)

    def bwd(res, do):
        q, k, v, out, lse2 = res
        return _bwd_pallas(q, k, v, out, lse2, do, causal, block_q, block_kv,
                           interpret)

    flash.defvjp(fwd, bwd)
    return flash


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, block_q: int = 1024,
                    block_kv: int = 1024,
                    interpret: bool | None = None) -> jax.Array:
    """Fused attention on ``[B, T, H, D]`` via a Pallas TPU kernel.

    ``interpret=None`` auto-selects: compiled on TPU backends, interpreter
    mode elsewhere (slow — tests only; CPU production paths should call
    :func:`relayrl_tpu.ops.attention.blockwise_attention` instead, which is
    what the model-level ``attention="flash"`` config does off-TPU).
    Requires ``T`` divisible by both block sizes; callers pad or fall back.

    Default blocks are 1024 (clamped to T): the grid-step count dominates
    kernel wall time on v5e at these head dims — halving either block
    measured slower at both T=2048 and T=8192 (512-KV: ~1.15-1.35x; and
    the lax.scan recompute VJP this kernel replaced was ~2x slower still).
    benches/results/attention.json holds the CURRENT committed numbers
    (run benches/bench_attention.py to refresh). Shrink blocks only if
    VMEM pressure forces it (the in-kernel score tile is
    block_q x block_kv f32).
    """
    B, T, H, D = q.shape
    block_q = min(block_q, T)
    block_kv = min(block_kv, T)
    if T % block_q or T % block_kv:
        raise ValueError(
            f"seq len {T} not divisible by blocks ({block_q}, {block_kv})")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    return _make_flash(causal, block_q, block_kv, interpret)(q, k, v)
