"""Epoch buffer: accumulate episodes host-side, emit device-ready batches.

Capability parity with the reference's REINFORCE buffer
(reference: relayrl_framework/src/native/python/algorithms/REINFORCE/
replay_buffer.py — per-step store, GAE on finish_path at :48-79, normalized
get() at :81-111), restructured for TPU: the host buffer only pads and
stacks; **all math (GAE, normalization) happens inside the jitted learner
step on device** so ingest overlaps compute and nothing round-trips
(SURVEY.md §7.4 item 1).
"""

from __future__ import annotations

from typing import Sequence

from relayrl_tpu.data.batching import (
    BatchStaging,
    PaddedTrajectory,
    TrajectoryBatch,
    pad_decoded,
    pad_trajectory,
    pick_bucket,
    repad_trajectory,
    stack_trajectories,
)
from relayrl_tpu.types.action import ActionRecord
from relayrl_tpu.types.columnar import DecodedTrajectory

DEFAULT_BUCKETS = (64, 256, 1000)


class EpochBuffer:
    """Collects ``traj_per_epoch`` episodes, then drains one batch.

    Bucketing: each episode pads to the smallest configured bucket that fits;
    the drained batch uses the largest bucket present, so the learner step
    compiles once per (batch_size, bucket) pair.
    """

    def __init__(
        self,
        obs_dim: int,
        act_dim: int,
        traj_per_epoch: int,
        discrete: bool = True,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        max_traj_length: int | None = None,
        staging_slots: int = 3,
    ):
        self.obs_dim = int(obs_dim)
        self.act_dim = int(act_dim)
        self.traj_per_epoch = int(traj_per_epoch)
        self.discrete = bool(discrete)
        # Sorted (and deduped) ONCE here; pick_bucket and warmup's
        # smallest-first early stop rely on ascending order instead of
        # re-sorting per trajectory on the ingest path.
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        if max_traj_length is not None:
            self.buckets = tuple(b for b in self.buckets if b <= max_traj_length) or (
                int(max_traj_length),
            )
        # Construction-time invariant for every later ascending-order
        # consumer (guards future edits to the two rebuilds above).
        assert all(a < b for a, b in zip(self.buckets, self.buckets[1:])), \
            f"bucket lengths must be strictly ascending: {self.buckets}"
        # Zero-alloc assembly: drained batches write into a ring of
        # persistent staging slabs instead of eight np.stack allocations
        # per epoch. staging_slots=0 disables (every drain allocates —
        # required when drained batches outlive `slots` further drains,
        # e.g. the multi-host broadcast queue).
        self._staging = (BatchStaging(staging_slots, self.obs_dim,
                                      self.act_dim, self.discrete)
                         if staging_slots else None)
        self._pending: list[PaddedTrajectory] = []
        self.episode_returns: list[float] = []
        self.episode_lengths: list[int] = []

    def disable_staging(self) -> None:
        """Switch drain() back to allocate-per-call (consumers that hold
        drained batches across drains — the multi-host ready queue)."""
        self._staging = None

    def __len__(self) -> int:
        return len(self._pending)

    @property
    def ready(self) -> bool:
        return len(self._pending) >= self.traj_per_epoch

    def add_episode(
        self, actions: Sequence[ActionRecord] | DecodedTrajectory
    ) -> bool:
        """Pad + buffer one episode; True when a batch is ready to drain.

        Accepts either the ActionRecord list (Python decode path) or a
        :class:`DecodedTrajectory` from the native columnar decoder —
        ``len()`` of both is the raw record count, so bucketing is
        identical across paths."""
        bucket = pick_bucket(len(actions), self.buckets)
        if isinstance(actions, DecodedTrajectory):
            padded = pad_decoded(
                actions, bucket, self.obs_dim, self.act_dim, self.discrete)
        else:
            padded = pad_trajectory(
                actions, bucket, self.obs_dim, self.act_dim, self.discrete
            )
        self._pending.append(padded)
        self.episode_returns.append(float(padded.rew.sum()))
        self.episode_lengths.append(padded.length)
        return self.ready

    def drain(self) -> TrajectoryBatch:
        """Emit the epoch batch (and clear). All episodes pad to the
        largest bucket present so the stack is rectangular.

        With staging enabled (the default), the batch views a persistent
        slab that is REUSED after ``staging_slots`` further drains of
        the same shape — valid under the algorithm in-flight window
        (``slots = window + 1``: the update that consumed this slab is
        fenced before it can be overwritten), but callers that hold
        batches longer (multi-host ready queues) must
        :meth:`disable_staging` first."""
        if not self._pending:
            raise ValueError("drain() on empty buffer")
        take = self._pending[: self.traj_per_epoch]
        self._pending = self._pending[self.traj_per_epoch:]
        horizon = max(t.obs.shape[0] for t in take)
        if self._staging is not None:
            return stack_trajectories(
                take, out=self._staging.acquire(len(take), horizon))
        return stack_trajectories([repad_trajectory(t, horizon) for t in take])

    def pop_episode_stats(self) -> tuple[list[float], list[int]]:
        rets, lens = self.episode_returns, self.episode_lengths
        self.episode_returns, self.episode_lengths = [], []
        return rets, lens

    def reset(self) -> None:
        """Drop the part-filled epoch (and its stats) — the guardrail
        rollback path: episodes buffered on a rolled-back line of
        history must not leak into the restored line's first epoch."""
        self._pending.clear()
        self.episode_returns.clear()
        self.episode_lengths.clear()
