"""Uniform step replay buffer (the off-policy ingest path).

The reference's only buffer is the on-policy epoch buffer of its REINFORCE
learner (reference: relayrl_framework/src/native/python/_common/_algorithms/
BaseReplayBuffer.py contract + algorithms/REINFORCE/replay_buffer.py); its
registry nonetheless whitelists DQN/C51/DDPG/SAC/TD3
(config_loader.rs:148-159), which need transition replay. This is that
buffer, TPU-shaped: a fixed-capacity ring of transitions in pinned host
numpy arrays, sampling fixed-size batches (one jit signature) ready for
``jax.device_put``.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from relayrl_tpu.types.action import ActionRecord


class StepReplayBuffer:
    """Ring buffer of ``(obs, act, rew, obs2, done)`` transitions.

    ``add_episode`` unrolls an ActionRecord trajectory: record ``t`` holds
    ``(obs_t, act_t, rew_t)`` (terminal markers already folded by the caller
    or carrying their reward here), ``obs2`` comes from record ``t+1``. A
    time-limit truncation whose marker carries the post-step observation is
    stored with ``done=0`` and that observation as the bootstrap successor;
    a truncated final step without one is dropped — its bootstrap target is
    unknowable without ``obs_{T+1}``.
    """

    def __init__(self, obs_dim: int, act_dim: int, capacity: int,
                 discrete: bool = True, seed: int = 0,
                 obs_dtype=np.float32):
        self.obs_dim, self.act_dim = int(obs_dim), int(act_dim)
        self.capacity = int(capacity)
        self.discrete = bool(discrete)
        # uint8 rings (pixel replay): 4x less host memory, 4x smaller
        # checkpoint aux snapshots, and 4x less host->device transfer
        # per sampled batch — samples keep the stored dtype and the CNN
        # q-trunk casts + scales /255 on-device (models/cnn.py). Float
        # observations written into a uint8 ring would truncate; pair
        # this with the env pipeline's obs_dtype="uint8".
        self.obs_dtype = np.dtype(obs_dtype)
        if self.obs_dtype not in (np.dtype(np.float32), np.dtype(np.uint8)):
            raise ValueError(f"obs_dtype must be float32|uint8, "
                             f"got {self.obs_dtype}")
        self.obs = np.zeros((capacity, obs_dim), self.obs_dtype)
        self.obs2 = np.zeros((capacity, obs_dim), self.obs_dtype)
        if discrete:
            self.act = np.zeros((capacity,), np.int32)
        else:
            self.act = np.zeros((capacity, act_dim), np.float32)
        self.mask2 = np.ones((capacity, act_dim), np.float32)
        self.rew = np.zeros((capacity,), np.float32)
        self.done = np.zeros((capacity,), np.float32)
        self.ptr = 0
        self.size = 0
        self.total_steps = 0
        self._seed = int(seed)
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self.size

    def _check_obs_dtype(self, incoming) -> None:
        """Fail fast on the documented footgun: float observations into a
        uint8 ring would silently floor to all-zero (the learner-side
        obs_dtype knob must be PAIRED with the env pipeline's)."""
        if (self.obs_dtype == np.uint8
                and np.issubdtype(np.dtype(incoming), np.floating)):
            raise ValueError(
                "float observations fed to a uint8 replay ring — set the "
                "env pipeline's obs_dtype=\"uint8\" too (envs/atari.py), "
                "or drop the algorithm's obs_dtype knob")

    def _put(self, obs, act, rew, obs2, done, mask2):
        i = self.ptr
        self.obs[i] = obs
        self.obs2[i] = obs2
        if self.discrete:
            self.act[i] = int(np.asarray(act).reshape(-1)[0])
        else:
            self.act[i] = np.asarray(act, np.float32).reshape(-1)[: self.act_dim]
        self.mask2[i] = mask2
        self.rew[i] = float(rew)
        self.done[i] = float(done)
        self.ptr = (i + 1) % self.capacity
        self.size = min(self.size + 1, self.capacity)
        self.total_steps += 1

    def _put_many(self, obs, act, rew, obs2, done, mask2) -> int:
        """Vectorized ring insert (columnar fast path)."""
        k = len(rew)
        if k == 0:
            return 0
        idx = (self.ptr + np.arange(k)) % self.capacity
        self.obs[idx] = obs
        self.obs2[idx] = obs2
        if self.discrete:
            self.act[idx] = act.reshape(k, -1)[:, 0]
        else:
            self.act[idx] = act.reshape(k, -1)[:, : self.act_dim]
        self.mask2[idx] = mask2
        self.rew[idx] = rew
        self.done[idx] = done
        self.ptr = int((self.ptr + k) % self.capacity)
        self.size = int(min(self.size + k, self.capacity))
        self.total_steps += k
        return k

    def add_decoded(self, dt) -> int:
        """Columnar fast path of :meth:`add_episode` for a
        :class:`relayrl_tpu.types.columnar.DecodedTrajectory` (markers
        already folded by the native decoder). Same transition semantics
        as the ActionRecord loop below; parity enforced by
        tests/test_native_codec.py."""
        cols = dt.columns
        T = dt.n_steps
        if T == 0 or "o" not in cols or "a" not in cols:
            return 0
        self._check_obs_dtype(cols["o"].dtype)
        obs = cols["o"].reshape(T, -1)[:, : self.obs_dim].astype(
            self.obs_dtype, copy=False)
        act = cols["a"]
        rew = cols["r"].astype(np.float32, copy=False)
        done_last = bool(cols["t"][T - 1])
        trunc_last = dt.marker_truncated or bool(cols["x"][T - 1])

        obs2 = np.zeros((T, self.obs_dim), self.obs_dtype)
        if T > 1:
            obs2[: T - 1] = obs[1:]
        mask2 = np.ones((T, self.act_dim), np.float32)
        if "m" in cols:
            m = cols["m"].reshape(T, -1)[:, : self.act_dim].astype(
                np.float32, copy=False)
            if T > 1:
                mask2[: T - 1] = m[1:]
        done = np.zeros((T,), np.float32)

        n = T
        if trunc_last or not done_last:
            # Time-limit ending: bootstrap through the boundary (done=0)
            # using the marker's successor obs — or drop the last
            # transition when no successor was shipped.
            if dt.final_obs is None:
                n = T - 1
            else:
                obs2[T - 1] = np.asarray(
                    dt.final_obs, self.obs_dtype).reshape(-1)[: self.obs_dim]
                if dt.final_mask is not None:
                    mask2[T - 1] = np.asarray(
                        dt.final_mask, np.float32).reshape(-1)[: self.act_dim]
        else:
            done[T - 1] = 1.0
        return self._put_many(obs[:n], act[:n], rew[:n], obs2[:n], done[:n],
                              mask2[:n])

    def add_episode(self, actions: Sequence[ActionRecord]) -> int:
        """Unroll one trajectory into transitions; returns how many stored."""
        from relayrl_tpu.data.batching import fold_trailing_markers
        from relayrl_tpu.types.columnar import DecodedTrajectory

        if isinstance(actions, DecodedTrajectory):
            return self.add_decoded(actions)

        # A truncation marker may carry the post-step observation — the
        # bootstrap successor for the final transition — and its action
        # mask, so masked bootstrap targets stay legal.
        steps, final_obs, truncated, final_mask = fold_trailing_markers(actions)
        for rec in steps:  # one dtype check per episode (uint8 footgun)
            if rec.obs is not None:
                self._check_obs_dtype(np.asarray(rec.obs).dtype)
                break
        stored = 0
        ones = np.ones((self.act_dim,), np.float32)
        for t, rec in enumerate(steps):
            if rec.obs is None or rec.act is None:
                continue
            is_last = t == len(steps) - 1
            if is_last:
                if truncated or rec.truncated or not rec.done:
                    # Time-limit ending: the value target must bootstrap
                    # through the boundary (done=0). That needs a real
                    # successor obs — without one the transition is
                    # unknowable and dropped.
                    if final_obs is None:
                        break
                    obs2 = final_obs.reshape(-1)[: self.obs_dim]
                    mask2 = (ones if final_mask is None
                             else np.asarray(final_mask, np.float32)
                             .reshape(-1)[: self.act_dim])
                    done = 0.0
                else:
                    obs2 = np.zeros((self.obs_dim,), self.obs_dtype)
                    mask2 = ones
                    done = 1.0
            else:
                nxt = steps[t + 1]
                if nxt.obs is None:
                    continue
                obs2 = np.asarray(nxt.obs, self.obs_dtype).reshape(-1)[: self.obs_dim]
                mask2 = (np.asarray(nxt.mask, np.float32).reshape(-1)[: self.act_dim]
                         if nxt.mask is not None else ones)
                done = 0.0
            obs = np.asarray(rec.obs, self.obs_dtype).reshape(-1)[: self.obs_dim]
            self._put(obs, rec.act, rec.rew, obs2, done, mask2)
            stored += 1
        return stored

    def scrub_nonfinite(self) -> int:
        """Drop every stored transition carrying a non-finite value in
        any float field, compacting the survivors to the front of the
        ring in chronological order. Returns how many were dropped.

        Normally the ring is finite by construction (the off-policy
        ingest belt rejects non-finite trajectories before ``add_*``);
        under guardrails' ``ingest_validation: "warn"`` posture that
        belt stands down, and a post-rollback ring may hold admitted
        poison that would re-diverge every update after the restore —
        this is the rollback path's decontamination pass."""
        s = self.size
        if s == 0:
            return 0
        if s == self.capacity and self.ptr:
            order = np.r_[self.ptr:s, 0:self.ptr]
        else:
            order = np.arange(s)
        keep = np.isfinite(self.rew[order]) & np.isfinite(self.done[order])
        keep &= np.isfinite(self.mask2[order]).all(axis=1)
        if self.obs_dtype != np.uint8:  # uint8 cannot hold NaN/Inf
            keep &= np.isfinite(self.obs[order]).all(axis=1)
            keep &= np.isfinite(self.obs2[order]).all(axis=1)
        if not self.discrete:
            keep &= np.isfinite(self.act[order]).all(axis=1)
        dropped = int(s - keep.sum())
        if dropped == 0:
            return 0
        kept = order[keep]
        for name in ("obs", "obs2", "act", "mask2", "rew", "done"):
            arr = getattr(self, name)
            arr[: len(kept)] = arr[kept]
        self.size = len(kept)
        self.ptr = self.size % self.capacity
        return dropped

    def state_arrays(self) -> dict[str, np.ndarray]:
        """Stored transitions in CHRONOLOGICAL order plus counters — the
        checkpoint payload (SURVEY §5.4: the reference loses its buffer on
        every restart; here off-policy resume keeps it). Only the filled
        region is saved; when the ring has wrapped, rolling by ``ptr``
        makes index 0 the oldest transition, so a restored buffer
        overwrites oldest-first exactly like the original would have."""
        s = self.size
        if s == self.capacity and self.ptr:
            order = np.r_[self.ptr:s, 0:self.ptr]
        else:
            order = np.arange(s)
        return {
            "obs": self.obs[order], "obs2": self.obs2[order],
            "act": self.act[order], "mask2": self.mask2[order],
            "rew": self.rew[order], "done": self.done[order],
            # 0-d ndarrays, not numpy scalars: orbax's standard handler
            # rejects np.int64 scalar leaves (Unsupported type) — the
            # arrays restore through int() identically.
            "size": np.asarray(s, np.int64),
            "total_steps": np.asarray(self.total_steps, np.int64),
        }

    def load_state_arrays(self, d) -> None:
        """Inverse of :meth:`state_arrays`, tolerant of a capacity change:
        a buffer smaller than the checkpoint keeps the most recent
        transitions. The numpy sample RNG is reseeded deterministically
        from (seed, total_steps) rather than checkpointed — jax RNG state
        (inside the train state) restores exactly; the host-side sampler
        only needs independence, not replay."""
        n = int(d["size"])
        keep = min(n, self.capacity)
        sl = slice(n - keep, n)  # most recent when shrinking
        saved_obs_dt = np.asarray(d["obs"]).dtype
        if saved_obs_dt != self.obs_dtype:
            # A silent cast would corrupt the restored experience
            # (float [0,1] floors to all-zero bytes; bytes into a float
            # ring are 255x the live obs scale). Flip the ring dtype to
            # match the checkpoint, or start fresh.
            raise ValueError(
                f"checkpointed replay obs dtype {saved_obs_dt} != ring "
                f"obs_dtype {self.obs_dtype}; resume with a matching "
                f"obs_dtype (values are NOT rescalable across the flip)")
        for name in ("obs", "obs2", "act", "mask2", "rew", "done"):
            getattr(self, name)[:keep] = np.asarray(d[name])[sl]
        self.size = keep
        self.ptr = keep % self.capacity
        self.total_steps = int(d["total_steps"])
        self._rng = np.random.default_rng(
            (self._seed, self.total_steps))

    _SAMPLE_FIELDS = ("obs", "act", "rew", "obs2", "mask2", "done")

    def make_sample_out(self, batch_size: int) -> dict[str, np.ndarray]:
        """Allocate one reusable staging dict for :meth:`sample`'s
        ``out=`` — shaped/dtyped exactly like a fresh sample."""
        b = int(batch_size)
        return {name: np.empty((b,) + getattr(self, name).shape[1:],
                               getattr(self, name).dtype)
                for name in self._SAMPLE_FIELDS}

    def sample(self, batch_size: int,
               out: dict[str, np.ndarray] | None = None
               ) -> dict[str, np.ndarray]:
        """Uniform sample of a fixed-size batch (with replacement).

        ``out`` (from :meth:`make_sample_out`) gathers in place instead
        of allocating six fresh arrays per draw — the returned dict IS
        ``out``, valid until the caller reuses the buffers (the
        off-policy sample ring sizes itself so reuse trails the
        in-flight update window)."""
        if self.size == 0:
            raise ValueError("sample() on empty buffer")
        idx = self._rng.integers(0, self.size, size=int(batch_size))
        if out is None:
            return {name: getattr(self, name)[idx]
                    for name in self._SAMPLE_FIELDS}
        for name in self._SAMPLE_FIELDS:
            np.take(getattr(self, name), idx, axis=0, out=out[name])
        return out
