"""Host-side data staging: padding, bucketing, epoch buffers."""

from relayrl_tpu.data.batching import (
    BatchStaging,
    PaddedTrajectory,
    TrajectoryBatch,
    pad_trajectory,
    pick_bucket,
    repad_trajectory,
    stack_trajectories,
)
from relayrl_tpu.data.replay_buffer import DEFAULT_BUCKETS, EpochBuffer
from relayrl_tpu.data.step_buffer import StepReplayBuffer

__all__ = [
    "BatchStaging",
    "StepReplayBuffer",
    "PaddedTrajectory",
    "TrajectoryBatch",
    "pad_trajectory",
    "pick_bucket",
    "repad_trajectory",
    "stack_trajectories",
    "EpochBuffer",
    "DEFAULT_BUCKETS",
]
