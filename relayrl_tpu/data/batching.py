"""Variable-length trajectories → fixed-shape padded/masked batches.

The reference pickles arbitrary-length ``Vec<RelayRLAction>`` and loops over
actions in Python (reference: relayrl_framework/src/native/python/algorithms/
REINFORCE/REINFORCE.py:70-95 unpacks one action at a time into the buffer).
Under XLA every distinct shape is a recompilation, so here trajectories are
padded to **bucketed** lengths with a validity mask and stacked into
``[B, T, ...]`` batches — the learner compiles once per bucket, not once per
episode length (SURVEY.md §7.4 item 3).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from relayrl_tpu.types.action import ActionRecord


@dataclasses.dataclass
class PaddedTrajectory:
    """One episode padded to ``T`` with host (numpy) arrays."""

    obs: np.ndarray        # [T, obs_dim] f32
    act: np.ndarray        # [T] i32 (discrete) or [T, act_dim] f32
    act_mask: np.ndarray   # [T, act_dim] f32
    rew: np.ndarray        # [T] f32
    val: np.ndarray        # [T] f32 — critic value stored at sample time
    logp: np.ndarray       # [T] f32 — behavior log-prob stored at sample time
    valid: np.ndarray      # [T] f32
    length: int
    terminated: bool       # final action had done=True
    last_val: float        # bootstrap value for truncated episodes


@dataclasses.dataclass
class TrajectoryBatch:
    """Stacked episodes ``[B, T, ...]`` — the learner-step input."""

    obs: np.ndarray        # [B, T, obs_dim]
    act: np.ndarray        # [B, T] or [B, T, act_dim]
    act_mask: np.ndarray   # [B, T, act_dim]
    rew: np.ndarray        # [B, T]
    val: np.ndarray        # [B, T]
    logp: np.ndarray       # [B, T]
    valid: np.ndarray      # [B, T]
    last_val: np.ndarray   # [B]

    @property
    def batch_size(self) -> int:
        return self.obs.shape[0]

    @property
    def horizon(self) -> int:
        return self.obs.shape[1]

    def as_dict(self) -> dict[str, np.ndarray]:
        # Shallow on purpose: dataclasses.asdict would deep-copy every
        # array, silently undoing the staging-slab zero-alloc path (the
        # batch must stay a VIEW of the persistent buffers all the way
        # to device placement). Consumers only read.
        return {f.name: getattr(self, f.name)
                for f in dataclasses.fields(self)}

    @classmethod
    def zeros(cls, batch_size: int, horizon: int, obs_dim: int, act_dim: int,
              discrete: bool = True) -> dict[str, np.ndarray]:
        """Zero batch dict with this schema's exact keys/dtypes/shapes —
        the single owner used by the multi-host broadcast protocol, where
        non-coordinator processes must hold a pytree-identical template
        before ``broadcast_one_to_all`` fills it."""
        b, t = int(batch_size), int(horizon)
        act = (np.zeros((b, t), np.int32) if discrete
               else np.zeros((b, t, act_dim), np.float32))
        return {
            "obs": np.zeros((b, t, obs_dim), np.float32),
            "act": act,
            "act_mask": np.zeros((b, t, act_dim), np.float32),
            "rew": np.zeros((b, t), np.float32),
            "val": np.zeros((b, t), np.float32),
            "logp": np.zeros((b, t), np.float32),
            "valid": np.zeros((b, t), np.float32),
            "last_val": np.zeros((b,), np.float32),
        }


def fold_trailing_markers(
    actions: Sequence[ActionRecord],
) -> tuple[list[ActionRecord], np.ndarray | None, bool, np.ndarray | None]:
    """Fold ``flag_last_action`` markers (act-less records) into the last
    real step.

    The marker's reward is added to the preceding step and its done /
    truncated flags OR-merged in. Returns ``(steps, final_obs, truncated,
    final_mask)`` where ``final_obs`` is the post-step observation a
    truncation marker may carry (the off-policy bootstrap successor),
    ``truncated`` is True if any marker flagged a time-limit ending, and
    ``final_mask`` is the marker's action mask for that successor state
    (action-masked envs). Shared by the epoch and step replay buffers so
    marker semantics cannot diverge between them.
    """
    steps = list(actions)
    final_obs: np.ndarray | None = None
    final_mask: np.ndarray | None = None
    truncated = False
    while steps and steps[-1].act is None:
        marker = steps.pop()
        truncated = truncated or marker.truncated
        if marker.obs is not None:
            final_obs = np.asarray(marker.obs, np.float32)
        if marker.mask is not None:
            final_mask = np.asarray(marker.mask, np.float32)
        if steps:
            last = steps[-1]
            steps[-1] = ActionRecord(
                obs=last.obs, act=last.act, mask=last.mask,
                rew=last.rew + marker.rew, data=last.data,
                done=last.done or marker.done,
                truncated=last.truncated or marker.truncated,
            )
    return steps, final_obs, truncated, final_mask


def pick_bucket(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket ≥ length (lengths above the largest clamp to it).

    One scan, no per-call ``sorted()`` — this runs once per ingested
    trajectory and the old re-sort was pure hot-path overhead
    (:class:`~relayrl_tpu.data.EpochBuffer` sorts its buckets once at
    construction; the scan keeps the public API order-independent for
    any other caller)."""
    best = largest = None
    for b in buckets:
        b = int(b)
        if length <= b and (best is None or b < best):
            best = b
        if largest is None or b > largest:
            largest = b
    return best if best is not None else largest


def pad_trajectory(
    actions: Sequence[ActionRecord],
    horizon: int,
    obs_dim: int,
    act_dim: int,
    discrete: bool = True,
) -> PaddedTrajectory:
    """ActionRecords → fixed-shape padded arrays.

    Aux ``logp_a``/``v`` come from the action's data dict (the reference's
    REINFORCE reads ``data['v']``/``data['logp_a']`` the same way). Episodes
    longer than ``horizon`` are truncated (bootstrapped from the stored value
    of the last kept step).
    """
    if not actions:
        raise ValueError("empty trajectory")
    # ``flag_last_action`` terminates an episode with a marker record that
    # carries only the final reward + done flag (no obs/act — ref:
    # agent_zmq.rs:605-610). Markers are not steps: fold their reward into
    # the preceding real step so the policy-gradient loss never sees a
    # fictitious action at a zero observation.
    actions, _, _, _ = fold_trailing_markers(actions)
    if not actions:
        raise ValueError("trajectory contained only terminal markers")
    n = min(len(actions), horizon)

    obs = np.zeros((horizon, obs_dim), dtype=np.float32)
    act = np.zeros((horizon,), dtype=np.int32) if discrete else np.zeros(
        (horizon, act_dim), dtype=np.float32)
    act_mask = np.zeros((horizon, act_dim), dtype=np.float32)
    act_mask[:n] = 1.0
    rew = np.zeros((horizon,), dtype=np.float32)
    val = np.zeros((horizon,), dtype=np.float32)
    logp = np.zeros((horizon,), dtype=np.float32)
    valid = np.zeros((horizon,), dtype=np.float32)

    for t in range(n):
        a = actions[t]
        if a.obs is not None:
            obs[t] = np.asarray(a.obs, dtype=np.float32).reshape(-1)[:obs_dim]
        if a.act is not None:
            if discrete:
                act[t] = int(np.asarray(a.act).reshape(-1)[0])
            else:
                act[t] = np.asarray(a.act, dtype=np.float32).reshape(-1)[:act_dim]
        if a.mask is not None:
            act_mask[t] = np.asarray(a.mask, dtype=np.float32).reshape(-1)[:act_dim]
        rew[t] = float(a.rew)
        data = a.data or {}
        val[t] = float(np.asarray(data.get("v", 0.0)).reshape(-1)[0]) if "v" in data else 0.0
        logp[t] = (
            float(np.asarray(data.get("logp_a", 0.0)).reshape(-1)[0])
            if "logp_a" in data else 0.0
        )
        valid[t] = 1.0

    # ``terminated`` means a true terminal state: the value target stops
    # there. A time-limit truncation (Gymnasium ``truncated``) must still
    # bootstrap — v(s_{T+1}) is unavailable on the wire, so the stored
    # v(s_T) is the standard stand-in (the reference never bootstraps:
    # finish_path(last_val=0)).
    terminated = (bool(actions[n - 1].done)
                  and not bool(actions[n - 1].truncated)
                  and n == len(actions))
    last_val = 0.0 if terminated else float(val[n - 1])
    return PaddedTrajectory(
        obs=obs, act=act, act_mask=act_mask, rew=rew, val=val, logp=logp,
        valid=valid, length=n, terminated=terminated, last_val=last_val,
    )


def pad_decoded(
    dt,
    horizon: int,
    obs_dim: int,
    act_dim: int,
    discrete: bool = True,
) -> PaddedTrajectory:
    """Columnar fast path of :func:`pad_trajectory`.

    ``dt`` is a :class:`relayrl_tpu.types.columnar.DecodedTrajectory` (the
    native decoder already folded terminal markers), so padding is pure
    vectorized slice assignment — no per-step Python loop. Semantics are
    kept identical to the ActionRecord path (tests/test_native_codec.py
    asserts byte equality of the padded outputs across both paths).
    """
    cols, aux = dt.columns, dt.aux
    total = dt.n_steps
    if total == 0:
        raise ValueError("trajectory contained only terminal markers"
                         if dt.n_records else "empty trajectory")
    n = min(total, horizon)

    obs = np.zeros((horizon, obs_dim), dtype=np.float32)
    if "o" in cols:
        flat = cols["o"].reshape(total, -1)
        if flat.shape[1] < obs_dim:
            raise ValueError(
                f"obs has {flat.shape[1]} features, expected >= {obs_dim}")
        obs[:n] = flat[:n, :obs_dim]
    if discrete:
        act = np.zeros((horizon,), dtype=np.int32)
        if "a" in cols:
            act[:n] = cols["a"].reshape(total, -1)[:n, 0]
    else:
        act = np.zeros((horizon, act_dim), dtype=np.float32)
        if "a" in cols:
            act[:n] = cols["a"].reshape(total, -1)[:n, :act_dim]
    act_mask = np.zeros((horizon, act_dim), dtype=np.float32)
    if "m" in cols:
        act_mask[:n] = cols["m"].reshape(total, -1)[:n, :act_dim]
    else:
        act_mask[:n] = 1.0
    rew = np.zeros((horizon,), dtype=np.float32)
    rew[:n] = cols["r"][:n]
    val = np.zeros((horizon,), dtype=np.float32)
    if "v" in aux:
        val[:n] = aux["v"].reshape(total, -1)[:n, 0]
    logp = np.zeros((horizon,), dtype=np.float32)
    if "logp_a" in aux:
        logp[:n] = aux["logp_a"].reshape(total, -1)[:n, 0]
    valid = np.zeros((horizon,), dtype=np.float32)
    valid[:n] = 1.0

    done = cols["t"]
    trunc = cols["x"]
    terminated = (bool(done[n - 1]) and not bool(trunc[n - 1])
                  and n == total)
    last_val = 0.0 if terminated else float(val[n - 1])
    return PaddedTrajectory(
        obs=obs, act=act, act_mask=act_mask, rew=rew, val=val, logp=logp,
        valid=valid, length=n, terminated=terminated, last_val=last_val,
    )


_BATCH_FIELDS = ("obs", "act", "act_mask", "rew", "val", "logp", "valid")


def stack_trajectories(
    trajs: Sequence[PaddedTrajectory],
    out: dict[str, np.ndarray] | None = None,
) -> TrajectoryBatch:
    """Padded episodes → one ``[B, T, ...]`` batch.

    Without ``out`` this is the original allocate-per-call path (eight
    fresh ``np.stack``/``asarray`` allocations; requires same-horizon
    inputs). With ``out`` — a persistent staging dict from
    :class:`BatchStaging` — every row writes in place (shorter episodes
    zero-fill their tail, subsuming :func:`repad_trajectory`), and the
    returned batch VIEWS the staging arrays: it is valid until the
    staging slot is reused (see :meth:`EpochBuffer.drain`'s contract).
    """
    if out is None:
        horizons = {t.obs.shape[0] for t in trajs}
        if len(horizons) != 1:
            raise ValueError(f"mixed horizons in batch: {sorted(horizons)}")
        return TrajectoryBatch(
            obs=np.stack([t.obs for t in trajs]),
            act=np.stack([t.act for t in trajs]),
            act_mask=np.stack([t.act_mask for t in trajs]),
            rew=np.stack([t.rew for t in trajs]),
            val=np.stack([t.val for t in trajs]),
            logp=np.stack([t.logp for t in trajs]),
            valid=np.stack([t.valid for t in trajs]),
            last_val=np.asarray([t.last_val for t in trajs], dtype=np.float32),
        )
    b, horizon = out["obs"].shape[:2]
    if len(trajs) != b:
        raise ValueError(f"staging batch is {b} rows, got {len(trajs)} episodes")
    for i, t in enumerate(trajs):
        n = t.obs.shape[0]
        if n > horizon:
            raise ValueError(f"cannot shrink padded trajectory {n} -> {horizon}")
        for name in _BATCH_FIELDS:
            dst, src = out[name][i], getattr(t, name)
            dst[:n] = src
            if n < horizon:
                dst[n:] = 0  # stale rows from the slab's previous epoch
        out["last_val"][i] = t.last_val
    return TrajectoryBatch(**{name: out[name] for name in _BATCH_FIELDS},
                           last_val=out["last_val"])


class BatchStaging:
    """Ring of persistent ``[B, T, ...]`` host staging slabs, one ring
    per distinct (batch, horizon) shape — the zero-alloc steady state
    for epoch assembly. A slab is handed out round-robin and REUSED
    after ``slots`` further acquires of the same shape; the owner must
    guarantee the slab's previous consumer is done by then (the
    algorithm in-flight window provides exactly that: with window W and
    ``slots = W + 1``, the update that read slab k has been fenced
    before drain k+W+1 overwrites it)."""

    def __init__(self, slots: int, obs_dim: int, act_dim: int,
                 discrete: bool = True):
        if slots < 1:
            raise ValueError("BatchStaging needs at least one slot")
        self.slots = int(slots)
        self.obs_dim, self.act_dim = int(obs_dim), int(act_dim)
        self.discrete = bool(discrete)
        self._rings: dict[tuple[int, int], list[dict[str, np.ndarray]]] = {}
        self._next: dict[tuple[int, int], int] = {}

    def acquire(self, batch_size: int, horizon: int) -> dict[str, np.ndarray]:
        key = (int(batch_size), int(horizon))
        ring = self._rings.setdefault(key, [])
        if len(ring) < self.slots:
            ring.append(TrajectoryBatch.zeros(
                key[0], key[1], self.obs_dim, self.act_dim, self.discrete))
            return ring[-1]
        i = self._next.get(key, 0)
        self._next[key] = (i + 1) % self.slots
        return ring[i]


def repad_trajectory(traj: PaddedTrajectory, horizon: int) -> PaddedTrajectory:
    """Grow (or validate) a padded episode to a new horizon."""
    cur = traj.obs.shape[0]
    if cur == horizon:
        return traj
    if cur > horizon:
        raise ValueError(f"cannot shrink padded trajectory {cur} -> {horizon}")
    pad = horizon - cur

    def _grow(arr):
        width = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
        return np.pad(arr, width)

    return PaddedTrajectory(
        obs=_grow(traj.obs), act=_grow(traj.act), act_mask=_grow(traj.act_mask),
        rew=_grow(traj.rew), val=_grow(traj.val), logp=_grow(traj.logp),
        valid=_grow(traj.valid), length=traj.length, terminated=traj.terminated,
        last_val=traj.last_val,
    )
