"""JAX version-compat surface for ``shard_map``.

``shard_map`` has lived at three addresses across JAX releases:

- ``jax.experimental.shard_map.shard_map`` with a ``check_rep`` kwarg
  (the 0.4.x series, including the 0.4.37 this repo pins);
- ``jax.shard_map`` with ``check_rep`` (early 0.5/0.6 promotions);
- ``jax.shard_map`` with the kwarg renamed ``check_vma`` (0.7+, where
  ``check_rep`` is removed and the experimental module is a deprecation
  shim that raises).

Every call site in this repo goes through :func:`shard_map` below, which
binds whichever surface the installed JAX exposes exactly once at import
time and normalizes the kwarg: callers always say ``check_vma`` (the
forward-looking name) and the resolver translates to ``check_rep`` when
the installed surface wants the old spelling. If no surface resolves,
:func:`shard_map` raises ONE pointed error naming the installed JAX
version instead of letting 21 call sites fail with scattered
AttributeErrors — keep it that way (see docs/testing.md).

Direct ``jax.shard_map`` / ``jax.experimental.shard_map`` references
outside this module are flagged by jaxlint rule JAX07.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable

import jax

__all__ = ["shard_map", "shard_map_impl_name"]


def _resolve() -> tuple[Callable[..., Any], str, str]:
    """Return ``(raw_fn, kwarg_name, surface_name)`` for the installed JAX.

    ``hasattr(jax, "shard_map")`` is safe on every release: on versions
    where the top-level name is a deprecation stub it raises
    AttributeError (so hasattr is False) without side effects.
    """
    fn = getattr(jax, "shard_map", None)
    surface = "jax.shard_map"
    if fn is None:
        try:
            from jax.experimental.shard_map import shard_map as fn  # type: ignore
        except Exception:
            fn = None
        surface = "jax.experimental.shard_map.shard_map"
    if fn is None:
        raise RuntimeError(
            f"no shard_map surface found in the installed jax=={jax.__version__}: "
            "neither jax.shard_map nor jax.experimental.shard_map.shard_map "
            "resolves. The relayrl_tpu.parallel.compat resolver knows the "
            "0.4.x experimental surface (check_rep) and the 0.7+ top-level "
            "surface (check_vma); this JAX exposes neither, so the compat "
            "layer needs a new binding — fix it HERE, not at the call sites.")
    try:
        params = inspect.signature(fn).parameters
        kwarg = "check_vma" if "check_vma" in params else "check_rep"
    except (TypeError, ValueError):  # C-level or wrapped beyond inspection
        kwarg = "check_vma" if surface == "jax.shard_map" else "check_rep"
    return fn, kwarg, surface


_RAW, _KWARG, _SURFACE = None, None, None


def _binding() -> tuple[Callable[..., Any], str, str]:
    global _RAW, _KWARG, _SURFACE
    if _RAW is None:
        _RAW, _KWARG, _SURFACE = _resolve()
    return _RAW, _KWARG, _SURFACE


def shard_map_impl_name() -> str:
    """The fully-qualified surface the resolver bound (for diagnostics)."""
    return _binding()[2]


def shard_map(f: Callable[..., Any] | None = None, *, mesh, in_specs,
              out_specs, check_vma: bool = True, **kwargs):
    """Version-portable ``shard_map``.

    Same contract as ``jax.shard_map``: map ``f`` over ``mesh`` with
    per-argument ``in_specs``/``out_specs``. Callers always pass
    ``check_vma`` (never ``check_rep``); the resolver renames it for
    surfaces that predate the rename. ``f=None`` returns a decorator,
    matching the upstream partial-application convention.
    """
    raw, kwarg, _ = _binding()
    if f is None:
        return lambda g: shard_map(g, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_specs, check_vma=check_vma,
                                   **kwargs)
    kwargs[kwarg] = check_vma
    return raw(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               **kwargs)
