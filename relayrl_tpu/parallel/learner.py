"""Sharded learner-step compilation.

Takes the pure ``(state, batch) -> (state, metrics)`` update an algorithm
already defines and re-jits it over a mesh with explicit in/out shardings:
batch split over dp×fsdp, state placed by the param rules, metrics
replicated. XLA GSPMD inserts every collective (SURVEY.md §5.8 — the
reference's "communication backend" is sockets between processes; the
TPU-native learner's backend is ICI/DCN collectives compiled by XLA).
"""

from __future__ import annotations

from typing import Callable

import jax
from jax.sharding import Mesh

from relayrl_tpu.parallel.sharding import (
    batch_sharding,
    replicated,
    state_shardings,
)


def make_sharded_update(update_fn: Callable, mesh: Mesh, state_template,
                        donate_state: bool = True) -> Callable:
    """Compile ``update_fn`` with mesh shardings.

    ``state_template`` is an abstract or concrete state pytree used to derive
    placements; the returned callable expects state already placed (use
    :func:`place_state` once) and a host or device batch dict.
    """
    state_sh = state_shardings(state_template, mesh)
    batch_sh = batch_sharding(mesh)

    def batch_shardings_for(batch):
        return {k: batch_sh for k in batch}

    compiled_cache = {}

    def sharded_update(state, batch):
        key = tuple(sorted((k, tuple(v.shape), str(v.dtype))
                           for k, v in batch.items()))
        fn = compiled_cache.get(key)
        if fn is None:
            fn = jax.jit(
                update_fn,
                in_shardings=(state_sh, batch_shardings_for(batch)),
                out_shardings=(state_sh, replicated(mesh)),
                donate_argnums=(0,) if donate_state else (),
            )
            compiled_cache[key] = fn
        return fn(state, batch)

    return sharded_update


def place_state(state, mesh: Mesh):
    """Device-put a host/single-device state onto the mesh per the rules."""
    return jax.device_put(state, state_shardings(state, mesh))


def place_batch(batch: dict, mesh: Mesh) -> dict:
    """Host batch → device-sharded arrays (the jax.device_put ingest path —
    BASELINE.md north-star names this explicitly)."""
    sh = batch_sharding(mesh)
    return {k: jax.device_put(v, sh) for k, v in batch.items()}
