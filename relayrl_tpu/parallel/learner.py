"""Sharded learner-step compilation.

Takes the pure ``(state, batch) -> (state, metrics)`` update an algorithm
already defines and re-jits it over a mesh with explicit in/out shardings:
batch split over dp×fsdp, state placed by the param rules, metrics
replicated. XLA GSPMD inserts every collective (SURVEY.md §5.8 — the
reference's "communication backend" is sockets between processes; the
TPU-native learner's backend is ICI/DCN collectives compiled by XLA).
"""

from __future__ import annotations

from typing import Callable

import jax
from jax.sharding import Mesh

from relayrl_tpu.parallel.context import use_mesh
from relayrl_tpu.parallel.sharding import (
    batch_sharding,
    replicated,
    sequence_batch_pspec,
    state_shardings,
)
from jax.sharding import NamedSharding


def make_sharded_update(update_fn: Callable, mesh: Mesh, state_template,
                        donate_state: bool = True,
                        shard_time: bool = False) -> Callable:
    """Compile ``update_fn`` with mesh shardings.

    ``state_template`` is an abstract or concrete state pytree used to derive
    placements; the returned callable expects state already placed (use
    :func:`place_state` once) and a host or device batch dict.

    ``shard_time=True`` additionally shards axis 1 (time) of rank>=2 batch
    arrays over ``sp`` — the sequence-parallel path for transformer policies
    whose attention runs as a ring over ``sp``. The mesh is installed as the
    ambient mesh (:mod:`relayrl_tpu.parallel.context`) around tracing so
    ``attention: "ring"`` models pick it up.
    """
    state_sh = state_shardings(state_template, mesh)

    def batch_shardings_for(batch):
        return batch_shardings(mesh, batch, shard_time)

    compiled_cache = {}

    def sharded_update(state, batch):
        key = tuple(sorted((k, tuple(v.shape), str(v.dtype))
                           for k, v in batch.items()))
        fn = compiled_cache.get(key)
        if fn is None:
            fn = jax.jit(
                update_fn,
                in_shardings=(state_sh, batch_shardings_for(batch)),
                out_shardings=(state_sh, replicated(mesh)),
                donate_argnums=(0,) if donate_state else (),
            )
            compiled_cache[key] = fn
        with use_mesh(mesh):
            return fn(state, batch)

    return sharded_update


def batch_shardings(mesh: Mesh, batch: dict, shard_time: bool = False) -> dict:
    """Per-key NamedShardings for a batch dict: batch axis over dp×fsdp,
    plus (``shard_time=True``) the time axis of rank>=2 arrays over ``sp``."""
    if shard_time:
        return {
            k: NamedSharding(mesh, sequence_batch_pspec(mesh, v.ndim))
            for k, v in batch.items()
        }
    sh = batch_sharding(mesh)
    return {k: sh for k in batch}


def _global_put(x, sharding):
    """Place one host array under a sharding that may span processes.

    Single-process (and any fully-addressable sharding): plain
    ``jax.device_put``. Multi-host: the mesh's devices are not all
    addressable from this process, so build the global array from this
    process's copy of the (host-global) data — each process contributes
    the slices its local devices own. Callers must hold the same host
    values on every process (the coordinator-ingest path broadcasts the
    batch first; states are constructed identically from shared seeds).
    """
    if sharding.is_fully_addressable:
        return jax.device_put(x, sharding)
    import numpy as np

    x = np.asarray(x)
    # global_shape MUST be passed: without it JAX deduces the global shape
    # by SCALING every process-spanning sharded dim by its process count —
    # i.e. it treats x as this process's private shard. Our convention is
    # the opposite (x is the host-global array, identical on every
    # process), and the deduction silently DUPLICATED batch rows along dp
    # (benign for mean-reduced losses, 2x wasted compute) and doubled the
    # time axis under cross-process sp (positional-table overflow).
    return jax.make_array_from_process_local_data(sharding, x,
                                                  global_shape=x.shape)


def place_state(state, mesh: Mesh):
    """Device-put a host/single-device state onto the mesh per the rules."""
    return jax.tree_util.tree_map(_global_put, state,
                                  state_shardings(state, mesh))


def place_batch(batch: dict, mesh: Mesh, shard_time: bool = False) -> dict:
    """Host batch → device-sharded arrays (the jax.device_put ingest path —
    BASELINE.md north-star names this explicitly). ``shard_time`` must match
    the :func:`make_sharded_update` flag. Works on multi-host meshes (the
    batch must be host-global and identical across processes — see
    :func:`relayrl_tpu.parallel.distributed.broadcast_from_coordinator`)."""
    sh = batch_shardings(mesh, batch, shard_time)
    return {k: _global_put(v, sh[k]) for k, v in batch.items()}
