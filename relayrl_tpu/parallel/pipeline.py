"""Pipeline parallelism: GPipe-style microbatch schedule over the ``pp``
mesh axis.

The reference has no model parallelism of any kind (SURVEY.md §2.3
"Parallelism strategies: none present"); this is one of the TPU-first
additions §7.1 item 12 requires. Design follows the standard JAX/SPMD
pipeline recipe: the layer stack is *stacked* on a leading axis sharded
over ``pp`` (each device owns a contiguous stage of layers), activations
hand off stage-to-stage with ``lax.ppermute`` (neighbor ICI hops — the
``pp`` axis is last in the mesh order so stages are adjacent devices),
and a ``lax.scan`` over ``n_micro + n_stages - 1`` ticks drains the
bubble. Everything is differentiable (scan + ppermute + psum transpose
cleanly), so the same function serves forward and backward of the jitted
learner step.

Schedule (stage s processes microbatch ``t - s`` at tick ``t``)::

    tick:     0    1    2    3    4        (M=3 microbatches, S=3 stages)
    stage 0:  m0   m1   m2   -    -
    stage 1:  -    m0   m1   m2   -
    stage 2:  -    -    m0   m1   m2   ->  outputs at ticks S-1 .. S+M-2

The final psum over ``pp`` replicates the last stage's outputs to every
stage (activation-sized, negligible next to the matmuls), which keeps the
output spec pp-free so downstream loss code is unchanged.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from relayrl_tpu.parallel.compat import shard_map
from relayrl_tpu.parallel.mesh import data_axes


def resolve_microbatches(local_batch: int, n_stages: int,
                         requested: int | None = None) -> int:
    """Pick a microbatch count: the requested value when it divides the
    per-data-shard batch, else the largest divisor of ``local_batch`` not
    exceeding ``max(requested, n_stages)`` (more microbatches shrink the
    pipeline bubble — fraction (S-1)/(M+S-1))."""
    if requested is not None and local_batch % requested == 0:
        return requested
    target = max(requested or 0, n_stages)
    best = 1
    for m in range(1, local_batch + 1):
        if local_batch % m == 0 and m <= target:
            best = m
    return best


def pipeline_apply(stage_fn: Callable, stage_params, x: jax.Array,
                   mesh: Mesh, n_microbatches: int | None = None,
                   axis: str = "pp") -> jax.Array:
    """Apply a pipelined layer stack to activations ``x``.

    ``stage_params``: pytree whose leaves have a leading layer axis
    divisible by the ``pp`` size (placed with ``P("pp", ...)`` by the param
    rules); each device receives its own ``layers_per_stage`` slice.
    ``stage_fn(local_params, h) -> h`` applies one stage's layers (usually
    an inner ``lax.scan`` over the local slice).
    ``x``: global ``[B, ...]`` activations, batch sharded over dp×fsdp.
    """
    n_stages = mesh.shape[axis]
    if n_stages <= 1:
        return stage_fn(stage_params, x)
    leaves = jax.tree.leaves(stage_params)
    bad = [tuple(l.shape) for l in leaves if l.shape[0] % n_stages != 0]
    if bad:
        raise ValueError(
            f"layer stack of {leaves[0].shape[0]} layers is not divisible "
            f"by the pp mesh axis ({n_stages} stages); pick n_layers as a "
            f"multiple of pp (offending leaf shapes: {bad[:3]})")
    daxes = data_axes(mesh)
    bspec = daxes if daxes else None
    data = math.prod(mesh.shape[ax] for ax in daxes) if daxes else 1
    local_b = x.shape[0] // data
    n_micro = resolve_microbatches(local_b, n_stages, n_microbatches)

    x_spec = P(bspec, *([None] * (x.ndim - 1)))
    param_specs = jax.tree.map(
        lambda leaf: P(*((axis,) + (None,) * (leaf.ndim - 1))), stage_params)

    def per_device(params_local, x_local):
        s_idx = jax.lax.axis_index(axis)
        mbs = x_local.reshape(n_micro, local_b // n_micro,
                              *x_local.shape[1:])
        ticks = n_micro + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(buf, t):
            feed = jax.lax.dynamic_index_in_dim(
                mbs, jnp.minimum(t, n_micro - 1), axis=0, keepdims=False)
            inp = jnp.where(s_idx == 0, feed, buf)
            out = stage_fn(params_local, inp)
            nxt = jax.lax.ppermute(out, axis, perm)
            return nxt, out

        _, outs = jax.lax.scan(tick, jnp.zeros_like(mbs[0]),
                               jnp.arange(ticks))
        # Valid outputs live on the LAST stage at ticks S-1 .. S+M-2;
        # everything else is bubble garbage — zero it and psum to
        # replicate the result across stages.
        ys = jax.lax.dynamic_slice_in_dim(outs, n_stages - 1, n_micro,
                                          axis=0)
        ys = jnp.where(s_idx == n_stages - 1, ys, jnp.zeros_like(ys))
        ys = jax.lax.psum(ys, axis)
        return ys.reshape(x_local.shape)

    return shard_map(
        per_device, mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stage_params, x)
