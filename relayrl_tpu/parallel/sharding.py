"""Sharding rules: param PartitionSpecs + batch specs for a mesh.

The recipe (per the public scaling-book methodology): pick a mesh, annotate
in/out shardings on the jitted step, let XLA GSPMD insert the collectives
(psum for DP grads, all-gathers for FSDP params, reduce-scatters as needed)
— nothing here ever calls a collective directly for the learner path.

Rules implemented:

* **dp**    — params replicated, batch sharded on axis 0; GSPMD turns the
              grad sum into a psum over ``dp``.
* **fsdp**  — every param whose first axis is divisible by the ``fsdp`` size
              is sharded there (ZeRO-3 style); XLA all-gathers per layer.
* **tp**    — MLP trunks alternate column/row parallel over ``tp``:
              even layers split output features P(None, "tp"), odd layers
              split input features P("tp", None) — one psum per pair.
"""

from __future__ import annotations

import re
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_pspec(mesh: Mesh) -> P:
    """Leading (batch) axis sharded over dp×fsdp; rest replicated."""
    from relayrl_tpu.parallel.mesh import data_axes

    axes = data_axes(mesh)
    return P(axes if axes else None)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_pspec(mesh))


def sequence_batch_pspec(mesh: Mesh, ndim: int) -> P:
    """Spec for a ``[B, T, ...]`` batch array: batch over dp×fsdp AND time
    over ``sp`` (the sequence-parallel ingest path feeding ring attention).
    Rank-1 arrays (per-episode scalars like ``last_val``) shard batch only."""
    from relayrl_tpu.parallel.mesh import data_axes

    axes = data_axes(mesh)
    b = axes if axes else None
    if ndim >= 2 and mesh.shape.get("sp", 1) > 1:
        return P(b, "sp")
    return P(b)


_DENSE_LAYER = re.compile(r"dense_(\d+)$")


def param_pspec(path: tuple, leaf: Any, mesh: Mesh) -> P:
    """PartitionSpec for one param leaf, by tree path + shape."""
    tp = mesh.shape.get("tp", 1)
    fsdp = mesh.shape.get("fsdp", 1)
    pp = mesh.shape.get("pp", 1)
    names = [getattr(k, "key", getattr(k, "name", str(k))) for k in path]
    shape = getattr(leaf, "shape", ())

    # -- pipeline parallel: stacked layer stacks (leading axis = layers)
    #    live under a "blocks" subtree (models/transformer.py pp family);
    #    each pp stage owns a contiguous slice of layers. Checked before
    #    ep/fsdp so neither grabs the layer axis (a future pp-stacked MoE
    #    family would have both "blocks" and "moe" in its paths — the
    #    leading axis is layers and must go to pp).
    if pp > 1 and "blocks" in names and len(shape) >= 1 \
            and shape[0] % pp == 0:
        return P(*(("pp",) + (None,) * (len(shape) - 1)))

    # -- expert parallel: stacked MoE expert weights (leading axis =
    #    experts) live under a "moe" module (models/moe.py); gate stays
    #    replicated (its 2D kernel is filtered by the ndim>=3 guard).
    ep = mesh.shape.get("ep", 1)
    if ep > 1 and any(str(n) == "moe" for n in names) and len(shape) >= 3 \
            and shape[0] % ep == 0:
        return P(*(("ep",) + (None,) * (len(shape) - 1)))

    # -- tensor parallel: alternate split of MLP trunk Dense kernels --
    if tp > 1 and len(shape) == 2:
        for name in names:
            m = _DENSE_LAYER.search(str(name))
            if m and "kernel" in names:
                layer = int(m.group(1))
                if layer % 2 == 0 and shape[1] % tp == 0:
                    return _maybe_fsdp(P(None, "tp"), shape, fsdp, axis=0)
                if layer % 2 == 1 and shape[0] % tp == 0:
                    return P("tp", None)
    # bias of a column-parallel layer follows its output split
    if tp > 1 and len(shape) == 1 and "bias" in names:
        for name in names:
            m = _DENSE_LAYER.search(str(name))
            if m and int(m.group(1)) % 2 == 0 and shape[0] % tp == 0:
                return P("tp")

    # -- fsdp: shard the first divisible axis --
    if fsdp > 1:
        for axis, dim in enumerate(shape):
            if dim % fsdp == 0 and dim >= fsdp:
                return P(*([None] * axis), "fsdp")
    return P()


def _maybe_fsdp(spec: P, shape, fsdp: int, axis: int) -> P:
    """Layer a leading-axis fsdp split under a tp split when both fit."""
    if fsdp > 1 and len(shape) > axis and shape[axis] % fsdp == 0:
        parts = list(spec)
        if parts[axis] is None:
            parts[axis] = "fsdp"
            return P(*parts)
    return spec


def params_shardings(params, mesh: Mesh):
    """Pytree of NamedShardings matching ``params``."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, param_pspec(path, leaf, mesh)),
        params,
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def state_shardings(state, mesh: Mesh):
    """Shardings for a full train state tree.

    Optimizer moments live under paths that still contain the layer names
    (optax trees mirror the param tree), so the same path-based rules place
    them exactly like their params; scalars/RNG keys fall through to
    replicated.
    """
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, param_pspec(path, leaf, mesh)),
        state,
    )
