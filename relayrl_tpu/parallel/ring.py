"""Ring attention: causal attention with the sequence sharded over ``sp``.

The reference has nothing to mirror here (SURVEY.md §5.7 — no ring
attention, no context/sequence parallelism of any kind); this is a
TPU-first component designed for the hardware: each ``sp`` device holds one
contiguous chunk of the sequence, queries stay resident, and K/V chunks
rotate around the ring via ``jax.lax.ppermute`` — neighbor exchanges that
ride the ICI torus — while an online-softmax accumulator (shared with
:func:`relayrl_tpu.ops.attention.blockwise_attention`) combines each
incoming block. HBM cost per device is O(T/sp · T/sp) scores instead of
O(T²), and no device ever materializes the full K/V.

Causality across devices falls out of global positions: device ``i`` holds
queries ``[i·C, (i+1)·C)`` and, at round ``r``, the K/V chunk of device
``(i - r) mod n`` — blocks strictly in the future are masked to exact
zeros by the combine step (finite mask fill, no NaNs), so the result is
bitwise-comparable to dense attention on the gathered sequence.

Differentiable: the rotation is a ``lax.scan`` of ``ppermute`` calls, both
of which have transpose rules, so the backward pass is itself a ring pass
in the opposite direction — no custom VJP needed for correctness.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from relayrl_tpu.parallel.compat import shard_map
from relayrl_tpu.ops.attention import attention_block_combine, finalize_attention

_NEG_INF = -1e30


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           axis_name: str, axis_size: int,
                           causal: bool = True) -> jax.Array:
    """Per-shard ring attention body — call INSIDE ``shard_map``.

    ``q, k, v``: local chunks ``[B, C, H, D]`` where the global sequence is
    ``n = axis_size`` chunks laid out contiguously over ``axis_name``.
    """
    B, C, H, D = q.shape
    idx = jax.lax.axis_index(axis_name)
    local_pos = jnp.arange(C)
    q_pos = idx * C + local_pos

    o = jnp.zeros((B, H, C, D), jnp.float32)
    m = jnp.full((B, H, C), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, C), jnp.float32)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def mask_for(kv_idx):
        if not causal:
            return jnp.ones((C, C), bool)
        return q_pos[:, None] >= (kv_idx * C + local_pos)[None, :]

    # Round 0 consumes the local chunk with no communication; rounds
    # 1..n-1 rotate-then-combine, so exactly n-1 neighbor exchanges happen
    # (no dead final rotation).
    o_m_l = attention_block_combine((o, m, l), q, k, v, mask_for(idx))

    def round_step(carry, r):
        o_m_l, k_blk, v_blk = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        kv_idx = (idx - r) % axis_size
        o_m_l = attention_block_combine(o_m_l, q, k_blk, v_blk, mask_for(kv_idx))
        return (o_m_l, k_blk, v_blk), None

    if axis_size > 1:
        ((o, m, l), _, _), _ = jax.lax.scan(
            round_step, (o_m_l, k, v), jnp.arange(1, axis_size))
    else:
        o, m, l = o_m_l
    return finalize_attention(o, l, q.dtype)


def make_ring_attention(mesh: Mesh, axis_name: str = "sp",
                        causal: bool = True, batch_axes=("dp", "fsdp")):
    """Global-view ring attention ``[B, T, H, D] -> [B, T, H, D]``.

    Wraps :func:`ring_attention_sharded` in ``jax.shard_map`` over ``mesh``:
    time sharded on ``axis_name``, batch on whichever of ``batch_axes`` the
    mesh actually has (>1), everything else replicated. Composable under an
    outer ``jit`` — XLA sees only ppermutes between fused compute blocks.
    """
    axis_size = mesh.shape[axis_name]
    b_axes = tuple(ax for ax in batch_axes if mesh.shape.get(ax, 1) > 1)
    spec = P(b_axes if b_axes else None, axis_name, None, None)
    body = partial(ring_attention_sharded, axis_name=axis_name,
                   axis_size=axis_size, causal=causal)
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)
