"""Device-mesh construction.

The reference has no device story at all (SURVEY.md §2.3: "Parallelism
strategies: none present") — the TPU-native framework makes the mesh a
first-class config object. Axis convention (order matters for ICI layout):

* ``dp``   — data parallel (batch split, gradient psum)
* ``fsdp`` — fully-sharded data parallel (params sharded, batch also split)
* ``ep``   — expert parallel (MoE expert stacks sharded over experts —
             :mod:`relayrl_tpu.models.moe`; GSPMD inserts the
             dispatch/combine collectives)
* ``tp``   — tensor parallel (weight matrices split within a layer)
* ``sp``   — sequence/context parallel (trajectory time axis, ring
             collectives — long-context path)
* ``pp``   — pipeline parallel (layer stages, ppermute activation
             hand-off — :mod:`relayrl_tpu.parallel.pipeline`); last in the
             axis order so consecutive stages land on adjacent device ids
             (ICI neighbors on a real slice)

Config form (learner.mesh in relayrl_config.json): ``{"dp": -1, "fsdp": 1,
"ep": 1, "tp": 1, "sp": 1, "pp": 1}`` where -1 means "fill with the
remaining devices".
"""

from __future__ import annotations

from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "fsdp", "ep", "tp", "sp", "pp")


def resolve_mesh_shape(spec: Mapping[str, int], n_devices: int) -> dict[str, int]:
    """Resolve a mesh spec against a device count (one -1 axis fills)."""
    shape = {ax: int(spec.get(ax, 1)) for ax in AXES}
    fill_axes = [ax for ax, v in shape.items() if v == -1]
    if len(fill_axes) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {fill_axes}")
    fixed = 1
    for ax, v in shape.items():
        if v != -1:
            if v <= 0:
                raise ValueError(f"mesh axis {ax} must be positive or -1, got {v}")
            fixed *= v
    if fill_axes:
        if n_devices % fixed != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed axes product {fixed}")
        shape[fill_axes[0]] = n_devices // fixed
    else:
        if fixed != n_devices:
            raise ValueError(
                f"mesh {shape} needs {fixed} devices but {n_devices} available")
    return shape


def make_mesh(spec: Mapping[str, int] | None = None,
              devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build a Mesh over the given (default: all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    shape = resolve_mesh_shape(spec or {"dp": -1}, len(devices))
    dims = [shape[ax] for ax in AXES]
    arr = np.asarray(devices).reshape(dims)
    return Mesh(arr, AXES)


def single_device_mesh() -> Mesh:
    return make_mesh({ax: 1 for ax in AXES}, jax.devices()[:1])


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """Axes the batch dimension shards over (dp and fsdp both consume batch)."""
    return tuple(ax for ax in ("dp", "fsdp") if mesh.shape[ax] > 1)
