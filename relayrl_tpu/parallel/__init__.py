"""Parallelism: meshes, sharding rules, sharded learner compilation.

First-class in this framework where the reference has none (SURVEY.md §2.3
"Parallelism strategies: none present"; §7.1 item 12 requires DP, sharded
buffers, TP/FSDP, and sequence-parallel hooks).
"""

from relayrl_tpu.parallel.mesh import (
    AXES,
    data_axes,
    make_mesh,
    resolve_mesh_shape,
    single_device_mesh,
)
from relayrl_tpu.parallel.sharding import (
    batch_pspec,
    batch_sharding,
    param_pspec,
    params_shardings,
    replicated,
    sequence_batch_pspec,
    state_shardings,
)
from relayrl_tpu.parallel.learner import (
    make_sharded_update,
    place_batch,
    place_state,
)
from relayrl_tpu.parallel.compat import shard_map, shard_map_impl_name
from relayrl_tpu.parallel.context import current_mesh, use_mesh
from relayrl_tpu.parallel.distributed import (
    broadcast_from_coordinator,
    initialize_distributed,
    is_coordinator,
)
from relayrl_tpu.parallel.ring import (
    make_ring_attention,
    ring_attention_sharded,
)
from relayrl_tpu.parallel.ring_flash import (
    make_ring_flash_attention,
    ring_flash_attention_sharded,
)

__all__ = [
    "AXES",
    "data_axes",
    "make_mesh",
    "resolve_mesh_shape",
    "single_device_mesh",
    "batch_pspec",
    "batch_sharding",
    "param_pspec",
    "params_shardings",
    "replicated",
    "sequence_batch_pspec",
    "state_shardings",
    "make_sharded_update",
    "place_batch",
    "place_state",
    "shard_map",
    "shard_map_impl_name",
    "current_mesh",
    "use_mesh",
    "broadcast_from_coordinator",
    "initialize_distributed",
    "is_coordinator",
    "make_ring_attention",
    "ring_attention_sharded",
    "make_ring_flash_attention",
    "ring_flash_attention_sharded",
]
