"""Parallelism: meshes, sharding rules, sharded learner compilation.

First-class in this framework where the reference has none (SURVEY.md §2.3
"Parallelism strategies: none present"; §7.1 item 12 requires DP, sharded
buffers, TP/FSDP, and sequence-parallel hooks).
"""

from relayrl_tpu.parallel.mesh import (
    AXES,
    data_axes,
    make_mesh,
    resolve_mesh_shape,
    single_device_mesh,
)
from relayrl_tpu.parallel.sharding import (
    batch_pspec,
    batch_sharding,
    param_pspec,
    params_shardings,
    replicated,
    state_shardings,
)
from relayrl_tpu.parallel.learner import (
    make_sharded_update,
    place_batch,
    place_state,
)

__all__ = [
    "AXES",
    "data_axes",
    "make_mesh",
    "resolve_mesh_shape",
    "single_device_mesh",
    "batch_pspec",
    "batch_sharding",
    "param_pspec",
    "params_shardings",
    "replicated",
    "state_shardings",
    "make_sharded_update",
    "place_batch",
    "place_state",
]
