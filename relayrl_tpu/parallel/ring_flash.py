"""Ring attention with Pallas flash chunk kernels (TPU sp fast path).

:mod:`relayrl_tpu.parallel.ring` implements sequence-parallel causal
attention with an XLA online-softmax combine per ring round — correct
everywhere, and differentiable for free (``ppermute``/``scan`` transpose
rules). This module is the TPU-kernel tier of the same design: each
round's "attend local queries to the visiting K/V chunk" is ONE fused
Pallas kernel carrying the flash state ``(acc, m, l)`` in and out, so the
[C, C] per-round score matrix never materializes in HBM and the chunk
compute inherits the flash kernel's economics (log2-space softmax with
the scale pre-folded into q, diagonal-only masking — ops/flash.py).

The ring structure makes per-round masking *block-structured*: with the
global sequence laid out contiguously over the ``sp`` axis, the chunk a
device attends at round r is entirely in the past (full attention),
entirely in the future (skip — ``lax.cond`` passes the carry through
without even launching the kernel), or the local diagonal chunk
(standard causal masking on local positions). The kernels take that
3-way ``mode`` as an SMEM scalar, because under SPMD it is a traced
per-device value, not a Python constant.

Backward is a manual two-pass ring (no autodiff through the forward
scan): once the forward's final log2-space LSE is known, every
(q-chunk, kv-chunk) pair's gradient is independent — the same identity
the flash VJP uses (``ds = p * (dp - rowsum(do*o))``). dq accumulates
locally while K/V revisit; dk/dv accumulate on buffers that ROTATE WITH
their chunk: after n compute-then-rotate rounds each chunk's gradient
arrives back home on the device that owns it. One ``jax.custom_vjp``
wraps the whole sharded body, so nothing differentiates through
``pallas_call`` itself.

The reference has nothing to mirror here (SURVEY.md §5.7 — no sequence
parallelism of any kind); this composes two components the reference
also lacks (ring ppermute topology, flash kernels) into the TPU-first
long-context path. Parity with the scan ring and with dense attention is
tested on the CPU mesh in interpret mode (tests/test_attention.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from relayrl_tpu.parallel.compat import shard_map
from relayrl_tpu.ops.flash import (
    _LOG2E,
    _NEG_INF,
    _bht_to_bthd,
    _bthd_to_bht,
    _masked_scores2,
    _prescale_q,
)

# Per-round chunk relationship (SMEM scalar; traced per device).
MODE_SKIP, MODE_FULL, MODE_DIAG = 0, 1, 2


def _mode_dispatch(update, mode, q_ref, k_ref, q_start, k_start,
                   block_q: int, block_kv: int):
    """Block-class dispatch under a dynamic mode: FULL runs every block
    unmasked; DIAG runs the standard causal split on local positions
    (mask-free below the diagonal, iota/compare/select on it, skip
    above); SKIP fires neither predicate (callers lax.cond the whole
    kernel away for SKIP — this is belt-and-braces)."""
    full = mode == MODE_FULL
    diag = mode == MODE_DIAG
    live = k_start <= q_start + block_q - 1
    interior = k_start + block_kv - 1 <= q_start

    @pl.when(full | (diag & interior))
    def _unmasked():
        update(_masked_scores2(q_ref, k_ref, q_start, k_start, False,
                               block_q, block_kv))

    @pl.when(diag & live & jnp.logical_not(interior))
    def _masked():
        update(_masked_scores2(q_ref, k_ref, q_start, k_start, True,
                               block_q, block_kv))


def _chunk_fwd_kernel(mode_ref, q_ref, k_ref, v_ref, o_in_ref, m_in_ref,
                      l_in_ref, o_out_ref, m_out_ref, l_out_ref,
                      acc_ref, m_ref, l_ref, *, block_q: int, block_kv: int):
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():  # resume the carried flash state
        acc_ref[:] = o_in_ref[0]
        m_ref[:] = m_in_ref[0]
        l_ref[:] = l_in_ref[0]

    def update(s):
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp2(s - m_new)
        corr = jnp.exp2(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    _mode_dispatch(update, mode_ref[0], q_ref, k_ref,
                   pl.program_id(1) * block_q, ik * block_kv,
                   block_q, block_kv)

    @pl.when(ik == pl.num_programs(2) - 1)
    def _flush():  # hand the state back to the ring carry (unfinalized)
        o_out_ref[0] = acc_ref[:]
        m_out_ref[0] = m_ref[:]
        l_out_ref[0] = l_ref[:]


def _chunk_dq_kernel(mode_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                     delta_ref, dq_in_ref, dq_out_ref, acc_ref, *,
                     block_q: int, block_kv: int):
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = dq_in_ref[0]

    def update(s):
        p = jnp.exp2(s - lse_ref[0])
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _mode_dispatch(update, mode_ref[0], q_ref, k_ref,
                   pl.program_id(1) * block_q, ik * block_kv,
                   block_q, block_kv)

    @pl.when(ik == pl.num_programs(2) - 1)
    def _flush():  # still d/d(q.k)-space; * scale happens once, at the end
        dq_out_ref[0] = acc_ref[:]


def _chunk_dkv_kernel(mode_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, dk_in_ref, dv_in_ref, dk_out_ref,
                      dv_out_ref, dk_acc, dv_acc, *, block_q: int,
                      block_kv: int):
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = dk_in_ref[0]
        dv_acc[:] = dv_in_ref[0]

    def update(s):
        p = jnp.exp2(s - lse_ref[0])
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _mode_dispatch(update, mode_ref[0], q_ref, k_ref,
                   iq * block_q, pl.program_id(1) * block_kv,
                   block_q, block_kv)

    @pl.when(iq == pl.num_programs(2) - 1)
    def _flush():  # contracted against pre-scaled q; / log2e at the end
        dk_out_ref[0] = dk_acc[:]
        dv_out_ref[0] = dv_acc[:]


@functools.lru_cache(maxsize=None)
def _build_chunk_calls(C: int, D: int, block_q: int, block_kv: int,
                       in_dtype_name: str, interpret: bool):
    """Compile-cached pallas_calls for one [BH, C, D] chunk round.

    ``in_dtype_name`` is only an lru_cache key: every chunk output is
    deliberately float32 — the flash/gradient state must stay full
    precision across ring rounds, and the final cast happens once at the
    end of the ring.
    """
    nq, nk = C // block_q, C // block_kv
    mode_spec = pl.BlockSpec(memory_space=pltpu.SMEM)

    qi = lambda b, i, j: (b, i, 0)   # q-major rows (dq/fwd grids)
    ki = lambda b, i, j: (b, j, 0)
    qj = lambda b, j, i: (b, i, 0)   # kv-major grids (dkv)
    kj = lambda b, j, i: (b, j, 0)

    def blk(shape, imap):
        return pl.BlockSpec(shape, imap)

    fwd_kernel = functools.partial(_chunk_fwd_kernel, block_q=block_q,
                                   block_kv=block_kv)
    dq_kernel = functools.partial(_chunk_dq_kernel, block_q=block_q,
                                  block_kv=block_kv)
    dkv_kernel = functools.partial(_chunk_dkv_kernel, block_q=block_q,
                                   block_kv=block_kv)

    def fwd(mode, qs, k, v, o, m, l):
        bh = qs.shape[0]
        return pl.pallas_call(
            fwd_kernel,
            grid=(bh, nq, nk),
            in_specs=[
                mode_spec,
                blk((1, block_q, D), qi), blk((1, block_kv, D), ki),
                blk((1, block_kv, D), ki),
                blk((1, block_q, D), qi),             # o_in (f32)
                blk((1, block_q, 1), qi),             # m_in
                blk((1, block_q, 1), qi),             # l_in
            ],
            out_specs=[
                blk((1, block_q, D), qi),
                blk((1, block_q, 1), qi),
                blk((1, block_q, 1), qi),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, C, D), jnp.float32),
                jax.ShapeDtypeStruct((bh, C, 1), jnp.float32),
                jax.ShapeDtypeStruct((bh, C, 1), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, D), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
            ],
            interpret=interpret,
        )(mode, qs, k, v, o, m, l)

    def dq(mode, qs, k, v, do, lse2, delta, dq_acc):
        bh = qs.shape[0]
        return pl.pallas_call(
            dq_kernel,
            grid=(bh, nq, nk),
            in_specs=[
                mode_spec,
                blk((1, block_q, D), qi), blk((1, block_kv, D), ki),
                blk((1, block_kv, D), ki), blk((1, block_q, D), qi),
                blk((1, block_q, 1), qi), blk((1, block_q, 1), qi),
                blk((1, block_q, D), qi),             # dq_in (f32)
            ],
            out_specs=blk((1, block_q, D), qi),
            out_shape=jax.ShapeDtypeStruct((bh, C, D), jnp.float32),
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
            interpret=interpret,
        )(mode, qs, k, v, do, lse2, delta, dq_acc)

    def dkv(mode, qs, k, v, do, lse2, delta, dk_acc, dv_acc):
        bh = qs.shape[0]
        return pl.pallas_call(
            dkv_kernel,
            grid=(bh, nk, nq),
            in_specs=[
                mode_spec,
                blk((1, block_q, D), qj), blk((1, block_kv, D), kj),
                blk((1, block_kv, D), kj), blk((1, block_q, D), qj),
                blk((1, block_q, 1), qj), blk((1, block_q, 1), qj),
                blk((1, block_kv, D), kj),            # dk_in (f32)
                blk((1, block_kv, D), kj),            # dv_in (f32)
            ],
            out_specs=[
                blk((1, block_kv, D), kj),
                blk((1, block_kv, D), kj),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, C, D), jnp.float32),
                jax.ShapeDtypeStruct((bh, C, D), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_kv, D), jnp.float32),
                pltpu.VMEM((block_kv, D), jnp.float32),
            ],
            interpret=interpret,
        )(mode, qs, k, v, do, lse2, delta, dk_acc, dv_acc)

    return fwd, dq, dkv


def pick_chunk_block(C: int, cap: int = 1024) -> int | None:
    """Largest power-of-two divisor of the chunk length, capped; None when
    the chunk can't tile (callers fall back to the scan ring)."""
    b = 8
    if C % b:
        return None
    while b * 2 <= min(cap, C) and C % (b * 2) == 0:
        b *= 2
    return b


def _resolve_chunk_config(C: int, block: int | None,
                          interpret: bool | None) -> tuple[int, bool]:
    """Shared block-resolution/tile-validation/interpret-default policy for
    the sharded ring and the single-device cost model — one copy, so the
    bench rows always measure the same kernels the ring runs."""
    if block is None:
        block = pick_chunk_block(C)
    if block is None or C % block:
        raise ValueError(
            f"chunk length {C} does not tile (block={block}); use the scan "
            f"ring (relayrl_tpu.parallel.ring) for this shape")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    return int(block), bool(interpret)


def _finalize_chunk_state(o, l, out_dtype):
    """acc/l -> output chunk (the flash finalize; 1e-30 guards fully-masked
    rows, which only padding can produce). Returns (out, l_safe)."""
    l_safe = jnp.maximum(l, 1e-30)
    return (o / l_safe).astype(out_dtype), l_safe


def _round_mode(idx, r, axis_size, causal: bool):
    kv_idx = (idx - r) % axis_size
    if not causal:
        return jnp.int32(MODE_FULL), kv_idx
    mode = jnp.where(kv_idx == idx, MODE_DIAG,
                     jnp.where(kv_idx < idx, MODE_FULL, MODE_SKIP))
    return mode.astype(jnp.int32), kv_idx


@functools.lru_cache(maxsize=None)
def _make_ring_flash(axis_name: str, axis_size: int, causal: bool,
                     block: int, interpret: bool):
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def _calls(C, D, dtype):
        return _build_chunk_calls(C, D, block, block, dtype.name, interpret)

    @jax.custom_vjp
    def ring(q, k, v):
        out, _ = _fwd(q, k, v)
        return out

    def _fwd(q, k, v):
        B, C, H, D = q.shape
        fwd_call, _, _ = _calls(C, D, q.dtype)
        qs = _prescale_q(_bthd_to_bht(q))
        kb, vb = _bthd_to_bht(k), _bthd_to_bht(v)
        # Non-causal mode schedules are position-independent; an unused
        # axis_index would leave a dead partition_id op outside any manual
        # sharding annotation, which the SPMD partitioner rejects.
        idx = jax.lax.axis_index(axis_name) if causal else jnp.int32(0)
        bh = qs.shape[0]
        o = jnp.zeros((bh, C, D), jnp.float32)
        m = jnp.full((bh, C, 1), _NEG_INF, jnp.float32)
        l = jnp.zeros((bh, C, 1), jnp.float32)

        def compute(mode, kb, vb, oml):
            return jax.lax.cond(
                mode > 0,
                lambda a: tuple(fwd_call(mode[None], qs, a[0], a[1], *a[2])),
                lambda a: a[2],
                (kb, vb, tuple(oml)))

        # Round 0 on the local chunk, no communication; rounds 1..n-1
        # rotate then combine (no dead final rotation, as in ring.py).
        mode, _ = _round_mode(idx, 0, axis_size, causal)
        oml = compute(mode, kb, vb, (o, m, l))

        def round_step(carry, r):
            oml, kb, vb = carry
            kb = jax.lax.ppermute(kb, axis_name, perm)
            vb = jax.lax.ppermute(vb, axis_name, perm)
            mode, _ = _round_mode(idx, r, axis_size, causal)
            oml = compute(mode, kb, vb, oml)
            return (oml, kb, vb), None

        if axis_size > 1:
            (oml, _, _), _ = jax.lax.scan(
                round_step, (oml, kb, vb), jnp.arange(1, axis_size))
        o, m, l = oml
        out_f, l_safe = _finalize_chunk_state(o, l, q.dtype)
        out = _bht_to_bthd(out_f, B, H)
        lse2 = m + jnp.log2(l_safe)                      # [BH, C, 1], log2
        return out, lse2

    def fwd(q, k, v):
        out, lse2 = _fwd(q, k, v)
        return out, (q, k, v, out, lse2)

    def bwd(res, do):
        q, k, v, out, lse2 = res
        B, C, H, D = q.shape
        _, dq_call, dkv_call = _calls(C, D, q.dtype)
        scale = 1.0 / (D ** 0.5)
        qs = _prescale_q(_bthd_to_bht(q))
        kb, vb = _bthd_to_bht(k), _bthd_to_bht(v)
        dor, of = _bthd_to_bht(do), _bthd_to_bht(out)
        delta = jnp.sum(dor.astype(jnp.float32) * of.astype(jnp.float32),
                        axis=-1, keepdims=True)
        idx = jax.lax.axis_index(axis_name) if causal else jnp.int32(0)
        bh = qs.shape[0]
        dq_acc = jnp.zeros((bh, C, D), jnp.float32)
        dk_acc = jnp.zeros_like(dq_acc)
        dv_acc = jnp.zeros_like(dq_acc)

        def compute(r_mode, kb, vb, dq_acc, dk_acc, dv_acc):
            # One cond for both passes: the dq and dk/dv kernels share the
            # skip schedule by construction.
            return jax.lax.cond(
                r_mode > 0,
                lambda a: (dq_call(r_mode[None], qs, a[0], a[1], dor, lse2,
                                   delta, a[2]),
                           *dkv_call(r_mode[None], qs, a[0], a[1], dor,
                                     lse2, delta, a[3], a[4])),
                lambda a: (a[2], a[3], a[4]),
                (kb, vb, dq_acc, dk_acc, dv_acc))

        # Round 0 on the local chunk; rounds 1..n-1 rotate-then-compute
        # (kb/vb get no dead final rotation, mirroring the forward). dk/dv
        # accumulate on buffers that ROTATE WITH their chunk, so they need
        # one more rotation after the last compute to arrive home —
        # n rotations total for n rounds of contributions.
        mode0, _ = _round_mode(idx, 0, axis_size, causal)
        dq_acc, dk_acc, dv_acc = compute(mode0, kb, vb, dq_acc, dk_acc,
                                         dv_acc)

        def round_step(carry, r):
            dq_acc, kb, vb, dk_acc, dv_acc = carry
            kb = jax.lax.ppermute(kb, axis_name, perm)
            vb = jax.lax.ppermute(vb, axis_name, perm)
            dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
            dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
            mode, _ = _round_mode(idx, r, axis_size, causal)
            dq_acc, dk_acc, dv_acc = compute(mode, kb, vb, dq_acc, dk_acc,
                                             dv_acc)
            return (dq_acc, kb, vb, dk_acc, dv_acc), None

        if axis_size > 1:
            (dq_acc, _, _, dk_acc, dv_acc), _ = jax.lax.scan(
                round_step, (dq_acc, kb, vb, dk_acc, dv_acc),
                jnp.arange(1, axis_size))
        dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
        dq = _bht_to_bthd((dq_acc * scale).astype(q.dtype), B, H)
        dk = _bht_to_bthd((dk_acc * (1.0 / _LOG2E)).astype(k.dtype), B, H)
        dv = _bht_to_bthd(dv_acc.astype(v.dtype), B, H)
        return dq, dk, dv

    ring.defvjp(fwd, bwd)
    return ring


def ring_flash_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                                 axis_name: str, axis_size: int,
                                 causal: bool = True,
                                 block: int | None = None,
                                 interpret: bool | None = None) -> jax.Array:
    """Per-shard flash-chunk ring attention — call INSIDE ``shard_map``.

    Same contract as :func:`relayrl_tpu.parallel.ring.ring_attention_sharded`
    (local chunks ``[B, C, H, D]``, global sequence contiguous over
    ``axis_name``); the chunk length must tile by 8 — use
    :func:`pick_chunk_block` and fall back to the scan ring when it
    returns None.
    """
    block, interpret = _resolve_chunk_config(q.shape[1], block, interpret)
    return _make_ring_flash(axis_name, axis_size, causal, block,
                            interpret)(q, k, v)


def chunked_flash_local(q: jax.Array, k: jax.Array, v: jax.Array,
                        n_chunks: int, causal: bool = True,
                        block: int | None = None,
                        interpret: bool | None = None) -> jax.Array:
    """Single-device emulation of the ring's per-chunk kernel schedule
    (forward only) — the ring cost model without a pod.

    Runs the same flash state-carry chunk kernels the sp ring uses, but
    with every chunk local: q-chunk i visits kv-chunks 0..i (causal)
    under the same FULL/DIAG mode schedule, with the ``(acc, m, l)``
    state bounced through HBM between calls exactly as the ring carries
    it between rounds. Comparing this against the fused
    :func:`relayrl_tpu.ops.flash.flash_attention` at equal T measures
    what ring chunking costs per device (state-carry HBM traffic +
    per-call overhead) separately from ICI transfer time, which this
    deliberately excludes. ``benches/bench_attention.py`` emits rows for
    it on TPU.
    """
    B, T, H, D = q.shape
    if T % n_chunks:
        raise ValueError(f"T={T} not divisible by n_chunks={n_chunks}")
    C = T // n_chunks
    block, interpret = _resolve_chunk_config(C, block, interpret)
    fwd_call, _, _ = _build_chunk_calls(C, D, block, block,
                                        q.dtype.name, interpret)
    qs = _prescale_q(_bthd_to_bht(q))
    kr, vr = _bthd_to_bht(k), _bthd_to_bht(v)
    bh = qs.shape[0]
    outs = []
    for iq in range(n_chunks):
        qc = jax.lax.dynamic_slice_in_dim(qs, iq * C, C, axis=1)
        o = jnp.zeros((bh, C, D), jnp.float32)
        m = jnp.full((bh, C, 1), _NEG_INF, jnp.float32)
        l = jnp.zeros((bh, C, 1), jnp.float32)
        last = iq if causal else n_chunks - 1
        for kv in range(last + 1):
            mode = jnp.full((1,), MODE_DIAG if (causal and kv == iq)
                            else MODE_FULL, jnp.int32)
            kc = jax.lax.dynamic_slice_in_dim(kr, kv * C, C, axis=1)
            vc = jax.lax.dynamic_slice_in_dim(vr, kv * C, C, axis=1)
            o, m, l = fwd_call(mode, qc, kc, vc, o, m, l)
        out_f, _ = _finalize_chunk_state(o, l, q.dtype)
        outs.append(out_f)
    return _bht_to_bthd(jnp.concatenate(outs, axis=1), B, H)


def make_ring_flash_attention(mesh: Mesh, axis_name: str = "sp",
                              causal: bool = True,
                              batch_axes=("dp", "fsdp"),
                              block: int | None = None,
                              interpret: bool | None = None):
    """Global-view flash-chunk ring attention ``[B, T, H, D] -> same``.

    Drop-in for :func:`relayrl_tpu.parallel.ring.make_ring_attention` with
    the per-round combine running as Pallas chunk kernels.
    """
    axis_size = mesh.shape[axis_name]
    b_axes = tuple(ax for ax in batch_axes if mesh.shape.get(ax, 1) > 1)
    spec = P(b_axes if b_axes else None, axis_name, None, None)
    body = functools.partial(ring_flash_attention_sharded,
                             axis_name=axis_name, axis_size=axis_size,
                             causal=causal, block=block, interpret=interpret)
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)
