"""Value-based and continuous-control model families (off-policy stack).

The reference whitelists C51/DDPG/DQN/SAC/TD3 in its algorithm registry but
implements none of them (reference: relayrl_framework/src/sys_utils/
config_loader.rs:148-159 — only REINFORCE parses to params); this module
supplies the model halves for the full registry, TPU-native.

Two kinds of artifacts:

* **Registered policy kinds** — what ships to actors through
  :class:`~relayrl_tpu.types.ModelBundle` with the uniform ``step`` ABI:
  ``qnet_discrete`` (epsilon-greedy over Q), ``c51_discrete``
  (epsilon-greedy over expected atom values), ``ddpg_continuous``
  (deterministic tanh actor + Gaussian exploration noise), and
  ``sac_continuous`` (squashed-Gaussian sampler). Exploration knobs
  (``epsilon``, ``act_noise``) ride in the arch config so the learner can
  anneal them per publish without a new code path on the actor.
* **Learner-only critic modules** — Q(s) / Q(s,a) / twin / distributional
  heads used inside jitted updates; never serialized to actors.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from relayrl_tpu.models.base import Policy, mlp_sizes, register_model
from relayrl_tpu.models.mlp import _MASK_FILL, MLPTrunk, _compute_dtype

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def _q_trunk(hidden_sizes, compute_dtype, obs_shape, conv_spec, dense,
             scale_obs) -> nn.Module:
    """The shared trunk switch for both q-heads: ``obs_shape`` set →
    Nature conv trunk over pixel observations (flat wire vectors or
    [..., H, W, C]); None → MLP trunk (the reference-parity default).
    One construction site keeps the DQN and C51 pixel trunks identical."""
    if obs_shape is not None:
        from relayrl_tpu.models.cnn import NATURE_CONV, ConvTrunk

        return ConvTrunk(obs_shape, conv_spec or NATURE_CONV, dense,
                         scale_obs, compute_dtype, name="q_trunk")
    return MLPTrunk(hidden_sizes, "relu", compute_dtype, name="q_trunk")


class DiscreteQNet(nn.Module):
    """obs -> Q[A] (DQN head); trunk per :func:`_q_trunk`."""

    act_dim: int
    hidden_sizes: Sequence[int]
    compute_dtype: Any = jnp.float32
    obs_shape: Sequence[int] | None = None
    conv_spec: Sequence[Sequence[int]] | None = None
    dense: int = 512
    scale_obs: bool = True

    @nn.compact
    def __call__(self, obs):
        h = _q_trunk(self.hidden_sizes, self.compute_dtype, self.obs_shape,
                     self.conv_spec, self.dense, self.scale_obs)(obs)
        q = nn.Dense(self.act_dim, dtype=self.compute_dtype, name="q_head")(h)
        return q.astype(jnp.float32)


class DistributionalQNet(nn.Module):
    """obs -> logits[A, n_atoms] (C51 head); trunk per :func:`_q_trunk`."""

    act_dim: int
    n_atoms: int
    hidden_sizes: Sequence[int]
    compute_dtype: Any = jnp.float32
    obs_shape: Sequence[int] | None = None
    conv_spec: Sequence[Sequence[int]] | None = None
    dense: int = 512
    scale_obs: bool = True

    @nn.compact
    def __call__(self, obs):
        h = _q_trunk(self.hidden_sizes, self.compute_dtype, self.obs_shape,
                     self.conv_spec, self.dense, self.scale_obs)(obs)
        logits = nn.Dense(self.act_dim * self.n_atoms,
                          dtype=self.compute_dtype, name="q_head")(h)
        return logits.astype(jnp.float32).reshape(
            *logits.shape[:-1], self.act_dim, self.n_atoms)


# Arch keys that switch a q-net to the pixel (conv-trunk) variant; the
# DQN/C51 _setup()s copy exactly these from hyperparams into the arch so
# actor-side build_policy and learner-side module construction agree.
PIXEL_ARCH_KEYS = ("obs_shape", "conv_spec", "dense", "scale_obs")


def conv_trunk_kwargs(arch: Mapping[str, Any]) -> dict:
    """Arch → the pixel-trunk kwargs shared by the q-net builders and the
    DQN/C51 learner modules (both must construct identical module configs
    or the param trees diverge)."""
    obs_shape = arch.get("obs_shape")
    if obs_shape is None:
        return {}
    from relayrl_tpu.models.cnn import (
        NATURE_CONV,
        resolve_conv_spec,
        validate_conv_spec,
    )

    spec = (resolve_conv_spec(arch["conv_spec"])
            if arch.get("conv_spec") else None)
    validate_conv_spec(obs_shape, spec or NATURE_CONV)
    return {
        "obs_shape": tuple(int(d) for d in obs_shape),
        "conv_spec": spec,
        "dense": int(arch.get("dense", 512)),
        "scale_obs": bool(arch.get("scale_obs", True)),
    }


class QValueNet(nn.Module):
    """(obs, act) -> scalar Q (DDPG/TD3/SAC critic)."""

    hidden_sizes: Sequence[int]
    compute_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs, act):
        x = jnp.concatenate([obs, act], axis=-1)
        h = MLPTrunk(self.hidden_sizes, "relu", self.compute_dtype,
                     name="q_trunk")(x)
        q = nn.Dense(1, dtype=self.compute_dtype, name="q_head")(h)
        return jnp.squeeze(q.astype(jnp.float32), axis=-1)


class TwinQNet(nn.Module):
    """Two independent Q(s,a) heads (TD3/SAC clipped double-Q)."""

    hidden_sizes: Sequence[int]
    compute_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs, act):
        q1 = QValueNet(self.hidden_sizes, self.compute_dtype, name="q1")(obs, act)
        q2 = QValueNet(self.hidden_sizes, self.compute_dtype, name="q2")(obs, act)
        return q1, q2


class DeterministicActor(nn.Module):
    """obs -> tanh-squashed action scaled to act_limit (DDPG/TD3 actor)."""

    act_dim: int
    act_limit: float
    hidden_sizes: Sequence[int]
    compute_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs):
        h = MLPTrunk(self.hidden_sizes, "relu", self.compute_dtype,
                     name="pi_trunk")(obs)
        a = nn.Dense(self.act_dim, dtype=self.compute_dtype, name="pi_head")(h)
        return self.act_limit * jnp.tanh(a.astype(jnp.float32))


class SquashedGaussianActor(nn.Module):
    """obs -> (mu, log_std) of a pre-squash Gaussian (SAC actor)."""

    act_dim: int
    hidden_sizes: Sequence[int]
    compute_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs):
        h = MLPTrunk(self.hidden_sizes, "relu", self.compute_dtype,
                     name="pi_trunk")(obs)
        mu = nn.Dense(self.act_dim, dtype=self.compute_dtype, name="pi_mu")(h)
        log_std = nn.Dense(self.act_dim, dtype=self.compute_dtype,
                           name="pi_log_std")(h)
        log_std = jnp.clip(log_std.astype(jnp.float32), LOG_STD_MIN, LOG_STD_MAX)
        return mu.astype(jnp.float32), log_std


def squashed_gaussian_sample(rng, mu, log_std, act_limit: float):
    """Sample a tanh-squashed Gaussian action + its log-prob (with the
    tanh change-of-variables correction, computed in the numerically stable
    softplus form)."""
    std = jnp.exp(log_std)
    pre = mu + std * jax.random.normal(rng, mu.shape, mu.dtype)
    logp = jnp.sum(
        -0.5 * (jnp.square((pre - mu) / std) + 2 * log_std
                + jnp.log(2 * jnp.pi)), axis=-1)
    # log(1 - tanh(x)^2) = 2*(log2 - x - softplus(-2x))
    logp -= jnp.sum(2.0 * (jnp.log(2.0) - pre - jax.nn.softplus(-2.0 * pre)),
                    axis=-1)
    return act_limit * jnp.tanh(pre), logp


def _masked_argmax(values, mask):
    if mask is not None:
        values = jnp.where(mask > 0, values, _MASK_FILL)
    return jnp.argmax(values, axis=-1), values


def _eps_greedy(rng, greedy, values, mask, epsilon):
    """Epsilon-greedy over the valid-action set."""
    explore_rng, pick_rng = jax.random.split(rng)
    if mask is None:
        mask = jnp.ones_like(values)
    random_act = jax.random.categorical(
        pick_rng, jnp.where(mask > 0, 0.0, _MASK_FILL), axis=-1)
    explore = jax.random.bernoulli(
        explore_rng, epsilon, greedy.shape)
    return jnp.where(explore, random_act, greedy)


@register_model("qnet_discrete")
def build_qnet_discrete(arch: Mapping[str, Any]) -> Policy:
    """Epsilon-greedy policy over a Q-network (the DQN actor artifact).
    ``arch["epsilon"]`` is the exploration rate actors apply; the learner
    anneals it per model publish."""
    module = DiscreteQNet(
        act_dim=int(arch["act_dim"]),
        hidden_sizes=mlp_sizes(arch),
        compute_dtype=_compute_dtype(arch),
        **conv_trunk_kwargs(arch),
    )
    obs_dim = int(arch["obs_dim"])
    epsilon_default = float(arch.get("epsilon", 0.05))

    def init_params(rng):
        return module.init(rng, jnp.zeros((1, obs_dim), jnp.float32))

    def step(params, rng, obs, mask=None, epsilon=None):
        # ``epsilon`` may arrive as a traced scalar (PolicyActor passes the
        # annealed value per call) so a new publish never retraces.
        eps = epsilon if epsilon is not None else epsilon_default
        q = module.apply(params, obs)
        greedy, q_masked = _masked_argmax(q, mask)
        act = _eps_greedy(rng, greedy, q, mask, eps)
        v = jnp.max(q_masked, axis=-1)
        return act, {"logp_a": jnp.zeros_like(v), "v": v}

    def evaluate(params, obs, act, mask=None):
        q = module.apply(params, obs)
        _, q_masked = _masked_argmax(q, mask)
        q_a = jnp.take_along_axis(
            q, jnp.asarray(act)[..., None].astype(jnp.int32), axis=-1
        ).squeeze(-1)
        return jnp.zeros_like(q_a), jnp.zeros_like(q_a), q_a

    def mode(params, obs, mask=None):
        q = module.apply(params, obs)
        return _masked_argmax(q, mask)[0]

    return Policy(arch=dict(arch), init_params=init_params, step=step,
                  evaluate=evaluate, mode=mode)


def c51_support(arch: Mapping[str, Any]) -> jax.Array:
    return jnp.linspace(float(arch.get("v_min", -10.0)),
                        float(arch.get("v_max", 10.0)),
                        int(arch.get("n_atoms", 51)))


@register_model("c51_discrete")
def build_c51_discrete(arch: Mapping[str, Any]) -> Policy:
    """Epsilon-greedy policy over C51 expected values."""
    module = DistributionalQNet(
        act_dim=int(arch["act_dim"]),
        n_atoms=int(arch.get("n_atoms", 51)),
        hidden_sizes=mlp_sizes(arch),
        compute_dtype=_compute_dtype(arch),
        **conv_trunk_kwargs(arch),
    )
    obs_dim = int(arch["obs_dim"])
    epsilon_default = float(arch.get("epsilon", 0.05))
    support = c51_support(arch)

    def expected_q(params, obs):
        logits = module.apply(params, obs)
        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.sum(probs * support, axis=-1)

    def init_params(rng):
        return module.init(rng, jnp.zeros((1, obs_dim), jnp.float32))

    def step(params, rng, obs, mask=None, epsilon=None):
        eps = epsilon if epsilon is not None else epsilon_default
        q = expected_q(params, obs)
        greedy, q_masked = _masked_argmax(q, mask)
        act = _eps_greedy(rng, greedy, q, mask, eps)
        v = jnp.max(q_masked, axis=-1)
        return act, {"logp_a": jnp.zeros_like(v), "v": v}

    def evaluate(params, obs, act, mask=None):
        q = expected_q(params, obs)
        q_a = jnp.take_along_axis(
            q, jnp.asarray(act)[..., None].astype(jnp.int32), axis=-1
        ).squeeze(-1)
        return jnp.zeros_like(q_a), jnp.zeros_like(q_a), q_a

    def mode(params, obs, mask=None):
        return _masked_argmax(expected_q(params, obs), mask)[0]

    return Policy(arch=dict(arch), init_params=init_params, step=step,
                  evaluate=evaluate, mode=mode)


@register_model("ddpg_continuous")
def build_ddpg_continuous(arch: Mapping[str, Any]) -> Policy:
    """Deterministic tanh actor with Gaussian exploration noise
    (``arch["act_noise"]``; set 0 for evaluation actors)."""
    act_limit = float(arch.get("act_limit", 1.0))
    module = DeterministicActor(
        act_dim=int(arch["act_dim"]),
        act_limit=act_limit,
        hidden_sizes=mlp_sizes(arch),
        compute_dtype=_compute_dtype(arch),
    )
    obs_dim = int(arch["obs_dim"])
    act_noise_default = float(arch.get("act_noise", 0.1))

    def init_params(rng):
        return module.init(rng, jnp.zeros((1, obs_dim), jnp.float32))

    def step(params, rng, obs, mask=None, act_noise=None):
        del mask
        noise = act_noise if act_noise is not None else act_noise_default
        a = module.apply(params, obs)
        a = a + noise * jax.random.normal(rng, a.shape, a.dtype)
        a = jnp.clip(a, -act_limit, act_limit)
        zero = jnp.zeros(a.shape[:-1], jnp.float32)
        return a, {"logp_a": zero, "v": zero}

    def evaluate(params, obs, act, mask=None):
        del act, mask
        a = module.apply(params, obs)
        zero = jnp.zeros(a.shape[:-1], jnp.float32)
        return zero, zero, zero

    def mode(params, obs, mask=None):
        del mask
        return module.apply(params, obs)

    return Policy(arch=dict(arch), init_params=init_params, step=step,
                  evaluate=evaluate, mode=mode)


@register_model("sac_continuous")
def build_sac_continuous(arch: Mapping[str, Any]) -> Policy:
    """Squashed-Gaussian stochastic actor (SAC)."""
    act_limit = float(arch.get("act_limit", 1.0))
    module = SquashedGaussianActor(
        act_dim=int(arch["act_dim"]),
        hidden_sizes=mlp_sizes(arch),
        compute_dtype=_compute_dtype(arch),
    )
    obs_dim = int(arch["obs_dim"])

    def init_params(rng):
        return module.init(rng, jnp.zeros((1, obs_dim), jnp.float32))

    def step(params, rng, obs, mask=None):
        del mask
        mu, log_std = module.apply(params, obs)
        a, logp = squashed_gaussian_sample(rng, mu, log_std, act_limit)
        return a, {"logp_a": logp, "v": jnp.zeros_like(logp)}

    def evaluate(params, obs, act, mask=None):
        del act, mask
        mu, log_std = module.apply(params, obs)
        ent = jnp.sum(log_std, axis=-1)  # up-to-constant Gaussian entropy
        zero = jnp.zeros(ent.shape, jnp.float32)
        return zero, ent, zero

    def mode(params, obs, mask=None):
        del mask
        mu, _ = module.apply(params, obs)
        return act_limit * jnp.tanh(mu)

    return Policy(arch=dict(arch), init_params=init_params, step=step,
                  evaluate=evaluate, mode=mode)
