"""Convolutional (Atari-class) actor-critic policies.

The reference has no pixel models (its only kernels are 2×128 MLPs —
reference: relayrl_framework/src/native/python/algorithms/REINFORCE/
kernel.py:12-84), but the driver's north-star configs require a CNN pixel
policy for PPO Atari Pong and IMPALA Breakout (BASELINE.md). This is the
Nature-DQN trunk as a flax module: three convs + a 512 dense, shared
between the categorical policy head and the value head.

Compute notes (TPU): convs run in the configured compute dtype (bf16 feeds
the MXU's conv path); the trunk is shared between pi and vf heads (unlike
the MLP family's separate trunks) because conv features dominate FLOPs —
one trunk halves HBM traffic. Observations arrive as flat wire vectors and
are reshaped to ``(H, W, C)`` NHWC inside the module, so the transport/codec
layer stays rank-agnostic.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from relayrl_tpu.models.base import Policy, register_model
from relayrl_tpu.models.mlp import (
    _MASK_FILL,
    _categorical_entropy,
    _categorical_logp,
    _compute_dtype,
)

# (features, kernel, stride) — the Nature-DQN trunk.
NATURE_CONV = ((32, 8, 4), (64, 4, 2), (64, 3, 1))

# TPU-native trunk: same geometry (kernels/strides/receptive field) as the
# Nature trunk, channel widths raised to MXU-lane multiples (64/128). The
# Nature widths are shape-hostile to a 128x128 systolic array — conv1's
# 32 output channels occupy <=25% of the lanes on ~40% of the FLOPs
# (docs/parallelism.md roofline section). This spec spends ~4x the
# arithmetic of NATURE_CONV but maps it where the MXU can actually retire
# it; pick it with ``conv_spec="tpu"`` in the arch/hyperparams.
TPU_CONV = ((64, 8, 4), (128, 4, 2), (128, 3, 1))

CONV_PRESETS = {"nature": NATURE_CONV, "tpu": TPU_CONV}


def resolve_conv_spec(spec) -> tuple:
    """Resolve a conv spec that may be a preset name ("nature"/"tpu") or an
    explicit ((features, kernel, stride), ...) sequence."""
    if isinstance(spec, str):
        try:
            return CONV_PRESETS[spec.lower()]
        except KeyError:
            raise ValueError(
                f"unknown conv preset {spec!r}; known: {sorted(CONV_PRESETS)}"
            ) from None
    return tuple(tuple(int(x) for x in row) for row in spec)


def validate_conv_spec(obs_shape, conv_spec) -> None:
    """Fail fast when a conv stack collapses the feature map to nothing
    (VALID padding): with the Nature trunk anything under ~36 px dies at
    the third layer, and the eventual failure is an opaque
    ZeroDivisionError inside the initializer. Raises with per-layer sizes
    so the user can shrink the spec or grow the frame."""
    h, w = int(obs_shape[0]), int(obs_shape[1])
    sizes = [(h, w)]
    for feat, kern, stride in conv_spec:
        h = (h - int(kern)) // int(stride) + 1
        w = (w - int(kern)) // int(stride) + 1
        sizes.append((h, w))
        if h <= 0 or w <= 0:
            raise ValueError(
                f"conv_spec {tuple(map(tuple, conv_spec))} collapses a "
                f"{obs_shape[0]}x{obs_shape[1]} frame to {h}x{w} (layer "
                f"sizes {sizes}); use a larger frame (Nature trunk needs "
                f">= 36 px) or a shallower conv_spec")


class ConvTrunk(nn.Module):
    obs_shape: Sequence[int]  # (H, W, C)
    conv_spec: Sequence[Sequence[int]] = NATURE_CONV
    dense: int = 512
    scale_obs: bool = True
    compute_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        # Accept flat wire obs [..., H*W*C] (the transport format) or
        # already-shaped [..., H, W, C]; run convs on [N, H, W, C].
        shape = tuple(self.obs_shape)
        flat_dim = shape[0] * shape[1] * shape[2]
        if x.shape[-1] == flat_dim:
            batch_shape = x.shape[:-1]
        elif x.shape[-3:] == shape:
            batch_shape = x.shape[:-3]
        else:
            raise ValueError(
                f"obs trailing shape {x.shape} matches neither ({flat_dim},) "
                f"nor {shape}")
        x = x.reshape((-1,) + shape) if batch_shape else x.reshape((1,) + shape)
        x = x.astype(self.compute_dtype)
        if self.scale_obs:
            x = x / jnp.asarray(255.0, self.compute_dtype)
        for i, (feat, kern, stride) in enumerate(self.conv_spec):
            x = nn.Conv(feat, (kern, kern), strides=(stride, stride),
                        padding="VALID", dtype=self.compute_dtype,
                        name=f"conv_{i}")(x)
            x = nn.relu(x)
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(self.dense, dtype=self.compute_dtype,
                             name="trunk_dense")(x))
        if not batch_shape:
            return x[0]
        return x.reshape(*batch_shape, -1)


class ConvActorCritic(nn.Module):
    act_dim: int
    obs_shape: Sequence[int]
    conv_spec: Sequence[Sequence[int]] = NATURE_CONV
    dense: int = 512
    scale_obs: bool = True
    has_critic: bool = True
    compute_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs, mask=None):
        feats = ConvTrunk(self.obs_shape, self.conv_spec, self.dense,
                          self.scale_obs, self.compute_dtype,
                          name="trunk")(obs)
        logits = nn.Dense(self.act_dim, dtype=self.compute_dtype,
                          name="pi_head")(feats)
        logits = logits.astype(jnp.float32)
        if mask is not None:
            logits = jnp.where(mask > 0, logits, _MASK_FILL)
        if self.has_critic:
            v = nn.Dense(1, dtype=self.compute_dtype, name="vf_head")(feats)
            v = jnp.squeeze(v.astype(jnp.float32), axis=-1)
        else:
            v = jnp.zeros(logits.shape[:-1], dtype=jnp.float32)
        return logits, v


@register_model("cnn_discrete")
def build_cnn_discrete(arch: Mapping[str, Any]) -> Policy:
    obs_shape = tuple(int(d) for d in arch["obs_shape"])
    if len(obs_shape) != 3:
        raise ValueError(f"cnn_discrete needs obs_shape (H, W, C), got {obs_shape}")
    conv_spec = resolve_conv_spec(arch.get("conv_spec", NATURE_CONV))
    validate_conv_spec(obs_shape, conv_spec)
    obs_dim = int(jnp.prod(jnp.array(obs_shape)))
    arch = dict(arch)
    arch.setdefault("obs_dim", obs_dim)
    if int(arch["obs_dim"]) != obs_dim:
        raise ValueError(
            f"obs_dim {arch['obs_dim']} != prod(obs_shape) {obs_dim}")

    module = ConvActorCritic(
        act_dim=int(arch["act_dim"]),
        obs_shape=obs_shape,
        conv_spec=conv_spec,
        dense=int(arch.get("dense", 512)),
        scale_obs=bool(arch.get("scale_obs", True)),
        has_critic=bool(arch.get("has_critic", True)),
        compute_dtype=_compute_dtype(arch),
    )

    def init_params(rng):
        return module.init(rng, jnp.zeros((1, obs_dim), jnp.float32))

    def step(params, rng, obs, mask=None):
        logits, v = module.apply(params, obs, mask)
        act = jax.random.categorical(rng, logits, axis=-1)
        logp = _categorical_logp(logits, act)
        return act, {"logp_a": logp, "v": v}

    def evaluate(params, obs, act, mask=None):
        logits, v = module.apply(params, obs, mask)
        return _categorical_logp(logits, act), _categorical_entropy(logits), v

    def mode(params, obs, mask=None):
        logits, _ = module.apply(params, obs, mask)
        return jnp.argmax(logits, axis=-1)

    return Policy(arch=arch, init_params=init_params, step=step,
                  evaluate=evaluate, mode=mode)
