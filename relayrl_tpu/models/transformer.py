"""Decoder-only transformer sequence policy (the long-context model family).

No counterpart exists in the reference — its only models are 2x128 MLPs
(relayrl_framework/src/native/python/algorithms/REINFORCE/kernel.py:14-21)
and SURVEY.md §5.7 records long-context support as absent. This family is
the TPU-first addition: a causal transformer over the trajectory time axis,
so the policy conditions on history instead of a single observation, with
three attention backends selected by arch config:

* ``"dense"``     — plain softmax attention (small T, correctness anchor)
* ``"blockwise"`` — online-softmax scan over KV blocks (long T, one device)
* ``"ring"``      — ring attention over the mesh ``sp`` axis
                    (:mod:`relayrl_tpu.parallel.ring`); requires an ambient
                    mesh (``parallel.context.use_mesh``) at trace time and
                    falls back to blockwise without one, so the SAME arch
                    config applies on CPU actor hosts and the TPU learner
                    (the heterogeneous-placement requirement of SURVEY.md
                    §7.4 item 2).

Sequence ABI: ``evaluate(params, obs[B,T,D], act[B,T], mask[B,T,A]) ->
(logp[B,T], ent[B,T], v[B,T])`` — same shapes the per-step MLP family
broadcasts to, so REINFORCE/PPO updates take this policy unchanged.
``step`` treats the second-to-last axis as time (``[T,D]`` or ``[B,T,D]``)
and returns the action at the last position; a bare ``[D]`` obs is a
context of one.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from relayrl_tpu.models.base import Policy, register_model
from relayrl_tpu.models.mlp import (
    _MASK_FILL,
    _categorical_entropy,
    _categorical_logp,
    _compute_dtype,
)
from relayrl_tpu.ops.attention import blockwise_attention, dense_attention


def _resolve_attention(arch: Mapping[str, Any]) -> Callable:
    """Arch config -> [B,T,H,D]x3 -> [B,T,H,D] attention callable."""
    kind = arch.get("attention", "dense")
    block = int(arch.get("attention_block", 128))
    if kind == "dense":
        return lambda q, k, v: dense_attention(q, k, v, causal=True)
    if kind == "blockwise":
        return lambda q, k, v: blockwise_attention(q, k, v, block, causal=True)
    if kind == "flash":
        def flash_or_local(q, k, v):
            # Pallas kernel on TPU; off-TPU (CPU actor hosts, CI) the same
            # arch config resolves to the lax.scan blockwise path — the
            # heterogeneous-placement rule ring attention also follows.
            import jax as _jax

            from relayrl_tpu.ops.flash import flash_attention

            T = q.shape[1]
            if _jax.default_backend() == "tpu" and T % min(block, T) == 0:
                return flash_attention(q, k, v, causal=True,
                                       block_q=block, block_kv=block)
            if T % block == 0:
                return blockwise_attention(q, k, v, block, causal=True)
            return dense_attention(q, k, v, causal=True)
        return flash_or_local
    if kind == "ring":
        def ring_or_local(q, k, v):
            from relayrl_tpu.parallel.context import current_mesh
            from relayrl_tpu.parallel.ring import make_ring_attention

            mesh = current_mesh()
            if mesh is None or mesh.shape.get("sp", 1) <= 1:
                if q.shape[1] % block == 0:
                    return blockwise_attention(q, k, v, block, causal=True)
                return dense_attention(q, k, v, causal=True)
            return make_ring_attention(mesh)(q, k, v)
        return ring_or_local
    raise ValueError(f"unknown attention kind {kind!r}")


class TransformerBlock(nn.Module):
    d_model: int
    n_heads: int
    mlp_ratio: int
    attn_fn: Callable
    compute_dtype: Any

    @nn.compact
    def __call__(self, x):
        B, T, _ = x.shape
        head_dim = self.d_model // self.n_heads
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x)
        h = h.astype(self.compute_dtype)
        qkv = nn.Dense(3 * self.d_model, dtype=self.compute_dtype,
                       name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (B, T, self.n_heads, head_dim)
        attn = self.attn_fn(q.reshape(shape), k.reshape(shape),
                            v.reshape(shape))
        attn = attn.reshape(B, T, self.d_model)
        x = x + nn.Dense(self.d_model, dtype=self.compute_dtype,
                         name="attn_out")(attn).astype(x.dtype)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_mlp")(x)
        h = h.astype(self.compute_dtype)
        h = nn.Dense(self.mlp_ratio * self.d_model, dtype=self.compute_dtype,
                     name="mlp_up")(h)
        h = nn.gelu(h)
        h = nn.Dense(self.d_model, dtype=self.compute_dtype, name="mlp_down")(h)
        return x + h.astype(x.dtype)


class TransformerCore(nn.Module):
    """Obs sequence -> per-step (logits, v). Residual stream stays f32."""

    act_dim: int
    d_model: int
    n_layers: int
    n_heads: int
    mlp_ratio: int
    max_seq_len: int
    has_critic: bool
    attn_fn: Callable
    compute_dtype: Any

    @nn.compact
    def __call__(self, obs, mask=None):
        B, T, _ = obs.shape
        x = nn.Dense(self.d_model, dtype=jnp.float32, name="obs_embed")(obs)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02), (self.max_seq_len, self.d_model),
            jnp.float32)
        x = x + jax.lax.dynamic_slice_in_dim(pos, 0, T, axis=0)[None]
        for i in range(self.n_layers):
            x = TransformerBlock(
                self.d_model, self.n_heads, self.mlp_ratio, self.attn_fn,
                self.compute_dtype, name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        logits = nn.Dense(self.act_dim, dtype=jnp.float32,
                          name="pi_head")(x)
        if mask is not None:
            logits = jnp.where(mask > 0, logits, _MASK_FILL)
        if self.has_critic:
            # Shared-trunk actor-critic: unlike the MLP family's separate
            # vf_trunk, the critic reads the policy-shaped features, so the
            # vf optimizer partition (labels by `vf*` prefix) trains only
            # this head — a 2-layer MLP rather than a single linear probe to
            # give the vf steps real capacity.
            h = nn.Dense(self.d_model, dtype=jnp.float32, name="vf_head_up")(x)
            v = nn.Dense(1, dtype=jnp.float32, name="vf_head")(nn.tanh(h))
            v = jnp.squeeze(v, axis=-1)
        else:
            v = jnp.zeros(logits.shape[:-1], jnp.float32)
        return logits, v


def _as_btd(obs, mask):
    """Normalize step/evaluate inputs to [B, T, D] (+ mask [B, T, A])."""
    obs = jnp.asarray(obs)
    if obs.ndim == 1:          # [D] -> context of one
        obs, lead = obs[None, None], "scalar"
    elif obs.ndim == 2:        # [T, D]
        obs, lead = obs[None], "seq"
    else:                      # [B, T, D]
        lead = "batch"
    if mask is not None:
        mask = jnp.asarray(mask)
        while mask.ndim < 3:
            mask = mask[None]
    return obs, mask, lead


@register_model("transformer_discrete")
def build_transformer_discrete(arch: Mapping[str, Any]) -> Policy:
    obs_dim = int(arch["obs_dim"])
    max_seq_len = int(arch.get("max_seq_len", 1024))
    core = TransformerCore(
        act_dim=int(arch["act_dim"]),
        d_model=int(arch.get("d_model", 128)),
        n_layers=int(arch.get("n_layers", 2)),
        n_heads=int(arch.get("n_heads", 4)),
        mlp_ratio=int(arch.get("mlp_ratio", 4)),
        max_seq_len=max_seq_len,
        has_critic=bool(arch.get("has_critic", True)),
        attn_fn=_resolve_attention(arch),
        compute_dtype=_compute_dtype(arch),
    )

    def init_params(rng):
        return core.init(rng, jnp.zeros((1, 1, obs_dim), jnp.float32))

    def step(params, rng, obs, mask=None):
        obs, mask, lead = _as_btd(obs, mask)
        logits, v = core.apply(params, obs, mask)
        logits_last, v_last = logits[:, -1], v[:, -1]
        act = jax.random.categorical(rng, logits_last, axis=-1)
        logp = _categorical_logp(logits_last, act)
        if lead != "batch":
            act, logp, v_last = act[0], logp[0], v_last[0]
        return act, {"logp_a": logp, "v": v_last}

    def evaluate(params, obs, act, mask=None):
        obs, mask, lead = _as_btd(obs, mask)
        act_b = jnp.asarray(act)
        while act_b.ndim < 2:  # scalar -> [1,1], [T] -> [1,T]
            act_b = act_b[None]
        logits, v = core.apply(params, obs, mask)
        logp = _categorical_logp(logits, act_b)
        ent = _categorical_entropy(logits)
        if lead != "batch":
            logp, ent, v = logp[0], ent[0], v[0]
        if lead == "scalar":
            logp, ent, v = logp[0], ent[0], v[0]
        return logp, ent, v

    def mode(params, obs, mask=None):
        obs, mask, lead = _as_btd(obs, mask)
        logits, _ = core.apply(params, obs, mask)
        act = jnp.argmax(logits[:, -1], axis=-1)
        return act if lead == "batch" else act[0]

    def _window_logits(params, window, t, mask):
        obs_b, mask_b, _ = _as_btd(window, mask)
        logits, v = core.apply(params, obs_b, mask_b)
        idx = jnp.clip(t - 1, 0, obs_b.shape[1] - 1)
        return logits[0, idx], v[0, idx]

    def step_window(params, rng, window, t, mask=None):
        """Act from a right-zero-padded history window ``[W, obs_dim]``
        with ``t`` real rows: the readout position t-1 only attends
        positions < t (causal), so the zero padding is never seen and one
        fixed shape serves every history length — the actor-side fix for
        the train(full sequence)/serve(context-1) mismatch."""
        logits_t, v_t = _window_logits(params, window, t, mask)
        act = jax.random.categorical(rng, logits_t, axis=-1)
        return act, {"logp_a": _categorical_logp(logits_t, act), "v": v_t}

    def mode_window(params, window, t, mask=None):
        """Greedy readout from the history window (the deterministic-eval
        counterpart of step_window)."""
        logits_t, _ = _window_logits(params, window, t, mask)
        return jnp.argmax(logits_t, axis=-1)

    return Policy(arch=dict(arch), init_params=init_params, step=step,
                  evaluate=evaluate, mode=mode, step_window=step_window,
                  mode_window=mode_window)
