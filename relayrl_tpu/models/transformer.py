"""Decoder-only transformer sequence policy (the long-context model family).

No counterpart exists in the reference — its only models are 2x128 MLPs
(relayrl_framework/src/native/python/algorithms/REINFORCE/kernel.py:14-21)
and SURVEY.md §5.7 records long-context support as absent. This family is
the TPU-first addition: a causal transformer over the trajectory time axis,
so the policy conditions on history instead of a single observation, with
four attention backends selected by arch config:

* ``"dense"``     — plain softmax attention (small T, correctness anchor)
* ``"blockwise"`` — online-softmax scan over KV blocks (long T, one device)
* ``"flash"``     — fused Pallas TPU kernels (ops/flash.py; resolves to
                    blockwise off-TPU)
* ``"ring"``      — ring attention over the mesh ``sp`` axis
                    (:mod:`relayrl_tpu.parallel.ring`); requires an ambient
                    mesh (``parallel.context.use_mesh``) at trace time and
                    falls back to blockwise without one, so the SAME arch
                    config applies on CPU actor hosts and the TPU learner
                    (the heterogeneous-placement requirement of SURVEY.md
                    §7.4 item 2).

Sequence ABI: ``evaluate(params, obs[B,T,D], act[B,T], mask[B,T,A]) ->
(logp[B,T], ent[B,T], v[B,T])`` — same shapes the per-step MLP family
broadcasts to, so REINFORCE/PPO updates take this policy unchanged.
``step`` treats the second-to-last axis as time (``[T,D]`` or ``[B,T,D]``)
and returns the action at the last position; a bare ``[D]`` obs is a
context of one.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from relayrl_tpu.models.base import Policy, register_model
from relayrl_tpu.models.mlp import (
    _MASK_FILL,
    _categorical_entropy,
    _categorical_logp,
    _compute_dtype,
)
from relayrl_tpu.ops.attention import blockwise_attention, dense_attention


def _resolve_attention(arch: Mapping[str, Any]) -> Callable:
    """Arch config -> [B,T,H,D]x3 -> [B,T,H,D] attention callable."""
    kind = arch.get("attention", "dense")
    block = int(arch.get("attention_block", 128))
    if kind == "dense":
        return lambda q, k, v: dense_attention(q, k, v, causal=True)
    if kind == "blockwise":
        return lambda q, k, v: blockwise_attention(q, k, v, block, causal=True)
    if kind == "flash":
        def flash_or_local(q, k, v):
            # Pallas kernel on TPU; off-TPU (CPU actor hosts, CI) the same
            # arch config resolves to the lax.scan blockwise path — the
            # heterogeneous-placement rule ring attention also follows.
            # The kernel has its OWN block knob (arch "flash_block"):
            # grid-step count dominates kernel wall time so it wants large
            # blocks, while the lax.scan fallback's "attention_block" is a
            # memory/fusion knob that wants small ones — one shared key
            # would silently deoptimize whichever path tuned second.
            import jax as _jax

            from relayrl_tpu.ops.flash import flash_attention

            T = q.shape[1]
            fblock = int(arch.get("flash_block", 1024))
            if _jax.default_backend() == "tpu" and T % min(fblock, T) == 0:
                return flash_attention(q, k, v, causal=True,
                                       block_q=fblock, block_kv=fblock)
            if T % block == 0:
                return blockwise_attention(q, k, v, block, causal=True)
            return dense_attention(q, k, v, causal=True)
        return flash_or_local
    if kind == "ring":
        def ring_or_local(q, k, v):
            from relayrl_tpu.parallel.context import current_mesh
            from relayrl_tpu.parallel.ring import make_ring_attention
            from relayrl_tpu.parallel.ring_flash import (
                make_ring_flash_attention,
                pick_chunk_block,
            )

            mesh = current_mesh()
            if mesh is None or mesh.shape.get("sp", 1) <= 1:
                if q.shape[1] % block == 0:
                    return blockwise_attention(q, k, v, block, causal=True)
                return dense_attention(q, k, v, causal=True)
            # On TPU the per-round combine runs as Pallas flash chunk
            # kernels when the local chunk tiles; the scan ring is the
            # portable fallback (and the off-TPU path, where the kernel
            # would run in the interpreter).
            chunk = q.shape[1] // mesh.shape["sp"]
            if (jax.default_backend() == "tpu"
                    and pick_chunk_block(chunk) is not None):
                return make_ring_flash_attention(mesh)(q, k, v)
            return make_ring_attention(mesh)(q, k, v)
        return ring_or_local
    raise ValueError(f"unknown attention kind {kind!r}")


class TransformerBlock(nn.Module):
    d_model: int
    n_heads: int
    mlp_ratio: int
    attn_fn: Callable
    compute_dtype: Any
    # MoE variant: >0 replaces the dense FFN with a per-token top-k MoE of
    # this many experts (models/moe.py; weights shard over the mesh ``ep``
    # axis). 0 keeps the dense mlp_up/mlp_down FFN — param names for the
    # dense family are unchanged.
    moe_experts: int = 0
    moe_top_k: int = 2

    @nn.compact
    def __call__(self, x, cache=None, t=None, readout_idx=None):
        """Full mode (``cache=None``): x ``[B, T, d]`` -> ``[B, T, d]``.

        Decode mode: x is ONE position ``[B, 1, d]``; ``cache`` is this
        layer's ``(k, v)`` pair ``[B, W, H, hd]`` and ``t`` the write
        index. Attention runs q against the cache prefix (positions <= t)
        instead of recomputing the whole window — O(W) per step vs the
        window path's O(W^2). Returns ``(out, new_cache)``. Param
        names/creation order are identical in both modes (init always runs
        the full path), so one param tree serves both.

        Readout mode (``readout_idx`` set, final layer of the window
        path): x is the full window ``[B, W, d]`` but only row
        ``readout_idx`` is ever read by the heads, so k/v project over
        every row (earlier positions must still be attended) while the
        query, attention-output projection, and MLP run for the ONE
        readout row — the dead (W-1)/W of the final block's compute that
        the full path pays per actor step. Returns ``[B, 1, d]``. The
        row's attention is computed densely (a 1-row query is trivially
        dense; every backend computes the same causal function)."""
        B, T, _ = x.shape
        head_dim = self.d_model // self.n_heads
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x)
        h = h.astype(self.compute_dtype)
        qkv = nn.Dense(3 * self.d_model, dtype=self.compute_dtype,
                       name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (B, T, self.n_heads, head_dim)
        q, k, v = (a.reshape(shape) for a in (q, k, v))
        if readout_idx is not None:
            q_row = jax.lax.dynamic_slice_in_dim(q, readout_idx, 1, axis=1)
            attn = dense_attention(q_row, k, v, causal=True,
                                   q_offset=readout_idx)
            attn = attn.reshape(B, 1, self.d_model)
            x = jax.lax.dynamic_slice_in_dim(x, readout_idx, 1, axis=1)
            x = x + nn.Dense(self.d_model, dtype=self.compute_dtype,
                             name="attn_out")(attn).astype(x.dtype)
            h = nn.LayerNorm(dtype=jnp.float32, name="ln_mlp")(x)
            h = h.astype(self.compute_dtype)
            h = nn.Dense(self.mlp_ratio * self.d_model,
                         dtype=self.compute_dtype, name="mlp_up")(h)
            h = nn.gelu(h)
            h = nn.Dense(self.d_model, dtype=self.compute_dtype,
                         name="mlp_down")(h)
            return x + h.astype(x.dtype)
        if cache is None:
            attn = self.attn_fn(q, k, v)
            new_cache = None
        else:
            k_cache, v_cache = cache
            k_cache = jax.lax.dynamic_update_slice_in_dim(
                k_cache, k.astype(k_cache.dtype), t, axis=1)
            v_cache = jax.lax.dynamic_update_slice_in_dim(
                v_cache, v.astype(v_cache.dtype), t, axis=1)
            # Query j sits at absolute position t+j (T=1 per-step decode;
            # T=W prefill rebuilds the whole prefix in one dispatch) —
            # exactly dense_attention's offset-causal mask, so the cached
            # path shares the window path's attention code verbatim.
            attn = dense_attention(q, k_cache, v_cache, causal=True,
                                   q_offset=t)
            new_cache = (k_cache, v_cache)
        attn = attn.reshape(B, T, self.d_model)
        x = x + nn.Dense(self.d_model, dtype=self.compute_dtype,
                         name="attn_out")(attn).astype(x.dtype)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_mlp")(x)
        if self.moe_experts > 0:
            from relayrl_tpu.models.moe import MoEMLP

            h = MoEMLP(self.d_model, self.mlp_ratio * self.d_model,
                       self.moe_experts, self.moe_top_k,
                       self.compute_dtype, name="moe")(h)
            out = x + h.astype(x.dtype)
        else:
            h = h.astype(self.compute_dtype)
            h = nn.Dense(self.mlp_ratio * self.d_model,
                         dtype=self.compute_dtype, name="mlp_up")(h)
            h = nn.gelu(h)
            h = nn.Dense(self.d_model, dtype=self.compute_dtype,
                         name="mlp_down")(h)
            out = x + h.astype(x.dtype)
        return out if cache is None else (out, new_cache)


def _embed_obs(parent: nn.Module, obs, d_model: int, max_seq_len: int,
               start=0):
    """Obs embedding + positional table, built in the CALLER's param scope
    (layer names land flat: obs_embed / pos_embed) — the single source of
    truth shared by TransformerCore (full AND cached-decode modes, which
    differ only in the ``start`` position) and the pipeline family's
    _PPEmbed."""
    _, T, _ = obs.shape
    x = nn.Dense(d_model, dtype=jnp.float32, name="obs_embed")(obs)
    pos = parent.param("pos_embed", nn.initializers.normal(0.02),
                       (max_seq_len, d_model), jnp.float32)
    return x + jax.lax.dynamic_slice_in_dim(pos, start, T, axis=0)[None]


def _readout_heads(x, mask, act_dim: int, d_model: int, has_critic: bool):
    """Final LN + pi/vf heads in the caller's scope (shared with _PPReadout;
    the vf optimizer partition keys off these exact `vf*` names)."""
    x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
    logits = nn.Dense(act_dim, dtype=jnp.float32, name="pi_head")(x)
    if mask is not None:
        logits = jnp.where(mask > 0, logits, _MASK_FILL)
    if has_critic:
        # Shared-trunk actor-critic: unlike the MLP family's separate
        # vf_trunk, the critic reads the policy-shaped features, so the
        # vf optimizer partition (labels by `vf*` prefix) trains only
        # this head — a 2-layer MLP rather than a single linear probe to
        # give the vf steps real capacity.
        h = nn.Dense(d_model, dtype=jnp.float32, name="vf_head_up")(x)
        v = nn.Dense(1, dtype=jnp.float32, name="vf_head")(nn.tanh(h))
        v = jnp.squeeze(v, axis=-1)
    else:
        v = jnp.zeros(logits.shape[:-1], jnp.float32)
    return logits, v


class TransformerCore(nn.Module):
    """Obs sequence -> per-step (logits, v). Residual stream stays f32."""

    act_dim: int
    d_model: int
    n_layers: int
    n_heads: int
    mlp_ratio: int
    max_seq_len: int
    has_critic: bool
    attn_fn: Callable
    compute_dtype: Any
    moe_experts: int = 0
    moe_top_k: int = 2

    @nn.compact
    def __call__(self, obs, mask=None, cache=None, t=None, readout_t=None):
        """Full mode: obs ``[B, T, D]`` -> (logits, v). Decode mode
        (``cache`` = tuple of per-layer (k, v) pairs, ``t`` = position):
        obs is ``[B, 1, D]``; returns ``((logits, v), new_cache)`` for the
        single position. Readout mode (``readout_t`` = dynamic row index):
        obs is a full window ``[B, W, D]`` but only position ``readout_t``
        is decoded — layers ``0..L-2`` run over every row (deeper layers
        attend all earlier positions' hidden states, so those are live),
        the final layer runs row-only (its other rows feed nothing), and
        the heads see the one row; returns ``(logits[B, A], v[B])``. Init
        always traces the full path, so all modes share one param tree."""
        decode = cache is not None
        x = _embed_obs(self, obs, self.d_model, self.max_seq_len,
                       start=t if decode else 0)
        if readout_t is not None:
            idx = jnp.asarray(readout_t, jnp.int32)
            for i in range(self.n_layers - 1):
                x = TransformerBlock(
                    self.d_model, self.n_heads, self.mlp_ratio,
                    self.attn_fn, self.compute_dtype,
                    moe_experts=self.moe_experts,
                    moe_top_k=self.moe_top_k, name=f"block_{i}")(x)
            final = TransformerBlock(
                self.d_model, self.n_heads, self.mlp_ratio, self.attn_fn,
                self.compute_dtype, moe_experts=self.moe_experts,
                moe_top_k=self.moe_top_k,
                name=f"block_{self.n_layers - 1}")
            if self.moe_experts > 0:
                # MoE routing is a cross-token decision — no per-row
                # shortcut; run the block whole and slice the row.
                x = jax.lax.dynamic_slice_in_dim(final(x), idx, 1, axis=1)
            else:
                x = final(x, readout_idx=idx)
            mask_row = None
            if mask is not None:
                mask_row = jax.lax.dynamic_slice_in_dim(mask, idx, 1,
                                                        axis=1)
            logits, v = _readout_heads(x, mask_row, self.act_dim,
                                       self.d_model, self.has_critic)
            return logits[:, 0], v[:, 0]
        new_cache = []
        for i in range(self.n_layers):
            block = TransformerBlock(
                self.d_model, self.n_heads, self.mlp_ratio, self.attn_fn,
                self.compute_dtype, moe_experts=self.moe_experts,
                moe_top_k=self.moe_top_k, name=f"block_{i}")
            if decode:
                x, layer_cache = block(x, cache=cache[i], t=t)
                new_cache.append(layer_cache)
            else:
                x = block(x)
        heads = _readout_heads(x, mask, self.act_dim, self.d_model,
                               self.has_critic)
        return (heads, tuple(new_cache)) if decode else heads


def _as_btd(obs, mask):
    """Normalize step/evaluate inputs to [B, T, D] (+ mask [B, T, A])."""
    obs = jnp.asarray(obs)
    if obs.ndim == 1:          # [D] -> context of one
        obs, lead = obs[None, None], "scalar"
    elif obs.ndim == 2:        # [T, D]
        obs, lead = obs[None], "seq"
    else:                      # [B, T, D]
        lead = "batch"
    if mask is not None:
        mask = jnp.asarray(mask)
        while mask.ndim < 3:
            mask = mask[None]
    return obs, mask, lead


def _policy_from_apply(arch: Mapping[str, Any], init_params, apply_fn,
                       apply_row_fn=None) -> Policy:
    """Build the sequence-policy ABI (step/evaluate/mode/windowed variants)
    over any ``apply_fn(params, obs[B,T,D], mask) -> (logits[B,T,A],
    v[B,T])`` — shared by the plain and pipeline transformer families.

    ``apply_row_fn(params, obs[B,W,D], mask, idx) -> (logits[B,A], v[B])``
    is the optional readout-row-only forward for the window paths
    (step_window/mode_window): the full forward computes logits for every
    window row and reads one, so a family that can decode just the
    readout row (TransformerCore readout mode) skips the final layer's
    dead (W-1)/W — the per-step win every window-driven actor tier
    (vector batched step_window, serving sessions, the fused anakin scan)
    inherits from this one seam, which is also what keeps their bytes
    identical to each other. Families without a row decode (the pipeline
    family's staged apply) omit it and keep the full-forward readout."""

    def step(params, rng, obs, mask=None):
        obs, mask, lead = _as_btd(obs, mask)
        logits, v = apply_fn(params, obs, mask)
        logits_last, v_last = logits[:, -1], v[:, -1]
        act = jax.random.categorical(rng, logits_last, axis=-1)
        logp = _categorical_logp(logits_last, act)
        if lead != "batch":
            act, logp, v_last = act[0], logp[0], v_last[0]
        return act, {"logp_a": logp, "v": v_last}

    def evaluate(params, obs, act, mask=None):
        obs, mask, lead = _as_btd(obs, mask)
        act_b = jnp.asarray(act)
        while act_b.ndim < 2:  # scalar -> [1,1], [T] -> [1,T]
            act_b = act_b[None]
        logits, v = apply_fn(params, obs, mask)
        logp = _categorical_logp(logits, act_b)
        ent = _categorical_entropy(logits)
        if lead != "batch":
            logp, ent, v = logp[0], ent[0], v[0]
        if lead == "scalar":
            logp, ent, v = logp[0], ent[0], v[0]
        return logp, ent, v

    def mode(params, obs, mask=None):
        obs, mask, lead = _as_btd(obs, mask)
        logits, _ = apply_fn(params, obs, mask)
        act = jnp.argmax(logits[:, -1], axis=-1)
        return act if lead == "batch" else act[0]

    def _window_logits(params, window, t, mask):
        obs_b, mask_b, _ = _as_btd(window, mask)
        idx = jnp.clip(t - 1, 0, obs_b.shape[1] - 1)
        if apply_row_fn is not None:
            logits_r, v_r = apply_row_fn(params, obs_b, mask_b, idx)
            return logits_r[0], v_r[0]
        logits, v = apply_fn(params, obs_b, mask_b)
        return logits[0, idx], v[0, idx]

    def step_window(params, rng, window, t, mask=None):
        """Act from a right-zero-padded history window ``[W, obs_dim]``
        with ``t`` real rows: the readout position t-1 only attends
        positions < t (causal), so the zero padding is never seen and one
        fixed shape serves every history length — the actor-side fix for
        the train(full sequence)/serve(context-1) mismatch."""
        logits_t, v_t = _window_logits(params, window, t, mask)
        act = jax.random.categorical(rng, logits_t, axis=-1)
        return act, {"logp_a": _categorical_logp(logits_t, act), "v": v_t}

    def mode_window(params, window, t, mask=None):
        """Greedy readout from the history window (the deterministic-eval
        counterpart of step_window)."""
        logits_t, _ = _window_logits(params, window, t, mask)
        return jnp.argmax(logits_t, axis=-1)

    return Policy(arch=dict(arch), init_params=init_params, step=step,
                  evaluate=evaluate, mode=mode, step_window=step_window,
                  mode_window=mode_window)


def _make_core(arch: Mapping[str, Any], moe_experts: int = 0) -> TransformerCore:
    """Arch -> TransformerCore module (shared by the policy builders and
    diagnostics like :func:`relayrl_tpu.models.moe.expert_utilization`,
    which re-applies the same module with captured intermediates)."""
    return TransformerCore(
        act_dim=int(arch["act_dim"]),
        d_model=int(arch.get("d_model", 128)),
        n_layers=int(arch.get("n_layers", 2)),
        n_heads=int(arch.get("n_heads", 4)),
        mlp_ratio=int(arch.get("mlp_ratio", 4)),
        max_seq_len=int(arch.get("max_seq_len", 1024)),
        has_critic=bool(arch.get("has_critic", True)),
        attn_fn=_resolve_attention(arch),
        compute_dtype=_compute_dtype(arch),
        moe_experts=moe_experts,
        moe_top_k=int(arch.get("moe_top_k", 2)),
    )


def _build_core_policy(arch: Mapping[str, Any], moe_experts: int = 0) -> Policy:
    obs_dim = int(arch["obs_dim"])
    core = _make_core(arch, moe_experts)

    def init_params(rng):
        return core.init(rng, jnp.zeros((1, 1, obs_dim), jnp.float32))

    head_dim = core.d_model // core.n_heads
    cache_dtype = core.compute_dtype

    def init_cache(length: int, batch_size: int = 1):
        """Zeroed per-layer (k, v) caches for incremental decoding."""
        shape = (batch_size, int(length), core.n_heads, head_dim)
        return tuple(
            (jnp.zeros(shape, cache_dtype), jnp.zeros(shape, cache_dtype))
            for _ in range(core.n_layers))

    def step_cached(params, rng, cache, obs, t, mask=None):
        """One O(W) decode step: writes position ``t`` into the cache and
        samples the action for it. Numerics match ``step_window`` at the
        same position (tests/test_kv_cache.py)."""
        obs = jnp.asarray(obs)
        if obs.ndim == 1:                       # [D] -> [1,1,D]
            obs = obs[None, None]
        elif obs.ndim == 2:                     # [B,D] -> [B,1,D]
            obs = obs[:, None]
        mask_b = None
        if mask is not None:
            mask_b = jnp.asarray(mask)
            if mask_b.ndim == 1:                # [A] -> [1,1,A]
                mask_b = mask_b[None, None]
            elif mask_b.ndim == 2:              # [B,A] -> [B,1,A]
                mask_b = mask_b[:, None]
        (logits, v), new_cache = core.apply(params, obs, mask_b,
                                            cache=cache, t=t)
        logits_t, v_t = logits[:, 0], v[:, 0]
        act = jax.random.categorical(rng, logits_t, axis=-1)
        aux = {"logp_a": _categorical_logp(logits_t, act), "v": v_t}
        if obs.shape[0] == 1:
            act = act[0]
            aux = {k: a[0] for k, a in aux.items()}
        return act, aux, new_cache

    def prefill_cache(params, cache, window):
        """Rebuild the whole cache from a padded window in ONE dispatch
        (post-hot-swap path): runs decode mode with T = W queries at
        t=0. Padding rows write garbage K/V beyond the real prefix, which
        later per-step decodes never attend (their causal mask stops at
        the current t) and overwrite in order."""
        window = jnp.asarray(window)
        if window.ndim == 2:
            window = window[None]
        _, new_cache = core.apply(params, window, None, cache=cache, t=0)
        return new_cache

    policy = _policy_from_apply(
        arch, init_params, core.apply,
        apply_row_fn=lambda params, obs, mask, idx: core.apply(
            params, obs, mask, readout_t=idx))
    import dataclasses as _dc

    return _dc.replace(policy, init_cache=init_cache,
                       step_cached=step_cached,
                       prefill_cache=prefill_cache)


@register_model("transformer_discrete")
def build_transformer_discrete(arch: Mapping[str, Any]) -> Policy:
    return _build_core_policy(arch)


@register_model("transformer_moe_discrete")
def build_transformer_moe_discrete(arch: Mapping[str, Any]) -> Policy:
    """Transformer whose FFNs are per-token top-k MoE layers (models/moe.py
    — NOT expert-choice, which is non-causal for policies); expert stacks
    shard over the mesh ``ep`` axis via the param rules. Same sequence ABI
    as transformer_discrete."""
    return _build_core_policy(arch, moe_experts=int(arch.get("moe_experts", 4)))


class _PPEmbed(nn.Module):
    """Input half of the pipeline transformer (stage-0-adjacent params);
    delegates to the shared :func:`_embed_obs` so names/math match
    TransformerCore exactly."""

    d_model: int
    max_seq_len: int

    @nn.compact
    def __call__(self, obs):
        return _embed_obs(self, obs, self.d_model, self.max_seq_len)


class _PPReadout(nn.Module):
    """Output half: delegates to the shared :func:`_readout_heads` (the vf
    optimizer partition keys off the same `vf*` names)."""

    act_dim: int
    d_model: int
    has_critic: bool

    @nn.compact
    def __call__(self, x, mask=None):
        return _readout_heads(x, mask, self.act_dim, self.d_model,
                              self.has_critic)


_PP_IO_KEYS = ("obs_embed", "pos_embed")


@register_model("transformer_pp_discrete")
def build_transformer_pp_discrete(arch: Mapping[str, Any]) -> Policy:
    """Pipeline-parallel transformer: identical math to
    ``transformer_discrete`` but the layer stack is STACKED on a leading
    axis (param subtree ``blocks``, sharded ``P("pp", ...)`` by the rules in
    parallel/sharding.py). With an ambient mesh whose ``pp`` axis > 1 the
    stack runs as a GPipe microbatch pipeline over ``pp``
    (:func:`relayrl_tpu.parallel.pipeline.pipeline_apply`); otherwise a
    plain ``lax.scan`` over layers — so the SAME arch config serves CPU
    actor hosts and the pipelined TPU learner (SURVEY.md §7.4 item 2).
    """
    obs_dim = int(arch["obs_dim"])
    d_model = int(arch.get("d_model", 128))
    n_layers = int(arch.get("n_layers", 2))
    n_micro = arch.get("pp_microbatches")
    block = TransformerBlock(
        d_model, int(arch.get("n_heads", 4)), int(arch.get("mlp_ratio", 4)),
        _resolve_attention(arch), _compute_dtype(arch))
    embed = _PPEmbed(d_model, int(arch.get("max_seq_len", 1024)))
    readout = _PPReadout(int(arch["act_dim"]), d_model,
                         bool(arch.get("has_critic", True)))

    def init_params(rng):
        r_embed, r_read, r_blocks = jax.random.split(rng, 3)
        e = embed.init(r_embed, jnp.zeros((1, 1, obs_dim), jnp.float32))
        r = readout.init(r_read, jnp.zeros((1, 1, d_model), jnp.float32))
        stacked = jax.vmap(
            lambda k: block.init(k, jnp.zeros((1, 1, d_model), jnp.float32))
        )(jax.random.split(r_blocks, n_layers))
        return {"params": {**e["params"], **r["params"],
                           "blocks": stacked["params"]}}

    def _stage(local_blocks, h):
        return jax.lax.scan(
            lambda c, p: (block.apply({"params": p}, c), None),
            h, local_blocks)[0]

    def apply_fn(params, obs, mask=None):
        from relayrl_tpu.parallel.context import current_mesh

        inner = params["params"]
        x = embed.apply(
            {"params": {k: inner[k] for k in _PP_IO_KEYS}}, obs)
        mesh = current_mesh()
        if mesh is not None and mesh.shape.get("pp", 1) > 1:
            from relayrl_tpu.parallel.pipeline import pipeline_apply

            x = pipeline_apply(_stage, inner["blocks"], x, mesh,
                               n_microbatches=n_micro)
        else:
            x = _stage(inner["blocks"], x)
        ro = {k: v for k, v in inner.items()
              if k not in _PP_IO_KEYS + ("blocks",)}
        return readout.apply({"params": ro}, x, mask)

    return _policy_from_apply(arch, init_params, apply_fn)
