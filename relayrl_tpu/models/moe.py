"""Mixture-of-experts MLP with per-token top-k routing (the ``ep`` family).

No counterpart exists in the reference (its only models are 2x128 MLPs —
SURVEY.md §2.5); this is a TPU-first capacity-scaling component: the
transformer block's FFN becomes E experts whose stacked weights shard over
the mesh ``ep`` axis (rule in parallel/sharding.py), so parameter capacity
scales with devices.

Routing is **per-token top-k** (default k=2): each token's gate picks its
own experts from its own features alone, so routing is exactly causal and
IDENTICAL between training batches and single-window actor serving — a
hard requirement for RL policies, where logp at step t must condition only
on history (capacity-competition schemes like expert-choice or
token-dropping leak future timesteps / sibling sequences into the gate and
bias the policy gradient).

Dispatch is dense: every expert runs on every token and the top-k mask
zeroes the rest in the combine einsum. That spends E× the FFN FLOPs of a
capacity-based sparse dispatch — the honest tradeoff at RL model scale,
where exactness beats the flop savings; under GSPMD each ``ep`` shard
computes only its own experts and the combine contracts over E with a
psum. A sparse gather/scatter dispatch is a later optimization for models
where the FFN dominates.

No auxiliary load-balancing loss is applied (see
:func:`expert_utilization` for the rationale and the monitoring hook for
the gate-collapse failure mode that omission leaves open).

Shapes: tokens flatten to ``[N = B*T, d]``; expert stacks are
``moe_w_up [E, d, ff]`` / ``moe_w_down [E, ff, d]``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


class MoEMLP(nn.Module):
    """Per-token top-k MoE FFN over flattened tokens (dense dispatch)."""

    d_model: int
    d_ff: int
    n_experts: int
    top_k: int
    compute_dtype: Any

    @nn.compact
    def __call__(self, x):
        B, T, d = x.shape
        n = B * T
        k = max(1, min(self.top_k, self.n_experts))
        tokens = x.reshape(n, d)

        # Gate in f32; per-token top-k -> renormalized combine weights,
        # scattered back to a dense [N, E] mask (static shapes, XLA-safe).
        gate = nn.Dense(self.n_experts, dtype=jnp.float32, name="moe_gate")(
            tokens.astype(jnp.float32))
        top_vals, top_idx = jax.lax.top_k(gate, k)          # [N, k]
        top_w = jax.nn.softmax(top_vals, axis=-1)           # [N, k]
        weights = jnp.zeros((n, self.n_experts), jnp.float32)
        weights = weights.at[
            jnp.arange(n)[:, None], top_idx].set(top_w)     # [N, E]

        w_up = self.param(
            "moe_w_up", nn.initializers.lecun_normal(batch_axis=(0,)),
            (self.n_experts, d, self.d_ff), jnp.float32)
        w_down = self.param(
            "moe_w_down", nn.initializers.lecun_normal(batch_axis=(0,)),
            (self.n_experts, self.d_ff, d), jnp.float32)

        # Monitoring hook: per-expert share of combine mass (weights sum to
        # 1 per token, so load/ n == fraction of routing mass per expert).
        # Inert unless applied with mutable=["intermediates"] — see
        # expert_utilization() below.
        self.sow("intermediates", "expert_load", weights.sum(axis=0))

        h = jnp.einsum("nd,edf->enf", tokens.astype(self.compute_dtype),
                       w_up.astype(self.compute_dtype),
                       preferred_element_type=jnp.float32)
        h = nn.gelu(h)
        out = jnp.einsum("enf,efd->end", h.astype(self.compute_dtype),
                         w_down.astype(self.compute_dtype),
                         preferred_element_type=jnp.float32)
        y = jnp.einsum("ne,end->nd", weights, out)          # psum over ep
        return y.reshape(B, T, d).astype(x.dtype)


def expert_utilization(arch, params, obs, mask=None) -> dict:
    """Per-layer routing-mass fraction per expert — the gate-collapse
    monitor.

    No auxiliary load-balancing loss is applied during training (a
    deliberate omission: at RL model scale the dense dispatch keeps
    collapsed gates *correct*, just wasteful, and an aux loss would have to
    be plumbed through every algorithm's update). The standard top-k
    failure mode — the gate collapsing onto a few experts — is therefore
    something to MONITOR: call this on a representative batch and alarm
    when the max fraction nears 1.0.

    Returns ``{layer_name: [E] fractions summing to 1}``.
    """
    import jax.numpy as _jnp

    from relayrl_tpu.models.transformer import _make_core

    core = _make_core(arch, moe_experts=int(arch.get("moe_experts", 4)))
    _, state = core.apply(params, _jnp.asarray(obs), mask,
                          mutable=["intermediates"])
    out = {}
    for layer, sub in state["intermediates"].items():
        if not layer.startswith("block_"):
            continue
        load = sub["moe"]["expert_load"][0]
        out[layer] = load / _jnp.maximum(load.sum(), 1e-9)
    return out
