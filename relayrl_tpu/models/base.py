"""Model registry + the policy ABI.

The reference's model ABI is a TorchScript module exporting ``step(obs, mask)
-> (act, {logp_a, v})`` plus ``get_input_dim``/``get_output_dim``, validated
by a dummy forward on every load (reference: relayrl_framework/src/native/
python/algorithms/REINFORCE/kernel.py:99-143 and src/network/client/
agent_wrapper.rs:88-168). TorchScript ships code; JAX params are data-only,
so here the ABI is an **architecture config** (a JSON-able dict) resolved
through this registry into a :class:`Policy` — a bundle of pure functions
that run identically on the TPU learner and on CPU actor hosts (SURVEY.md
§7.4 item 2).

Arch config schema::

    {"kind": "<registry key>", "obs_dim": int, "act_dim": int, ...}
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np

_REGISTRY: dict[str, Callable[[Mapping[str, Any]], "Policy"]] = {}


def register_model(kind: str):
    def deco(builder):
        _REGISTRY[kind] = builder
        return builder
    return deco


def build_policy(arch: Mapping[str, Any]) -> "Policy":
    kind = arch.get("kind")
    if kind not in _REGISTRY:
        raise ValueError(f"unknown model kind {kind!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[kind](arch)


# Model-shape hyperparams that algorithms forward verbatim from their
# hyperparam dict into the arch config when present, so any policy family
# (e.g. model_kind="transformer_discrete" with d_model/n_layers/attention)
# is reachable through the algorithm ctor without per-algorithm plumbing.
ARCH_PASSTHROUGH_KEYS = (
    "d_model", "n_layers", "n_heads", "mlp_ratio", "max_seq_len",
    "attention", "attention_block", "actor_context",
    "moe_experts", "moe_top_k", "pp_microbatches",
)


def apply_arch_overrides(arch: dict, params: Mapping[str, Any]) -> dict:
    """Copy any present ARCH_PASSTHROUGH_KEYS from hyperparams into arch.

    Algorithms call this once, right before ``build_policy(self.arch)``.
    Sequence-model keys on a non-sequence kind almost always mean a
    forgotten ``model_kind`` — warn instead of silently training the
    default MLP with the overrides ignored.
    """
    copied = [k for k in ARCH_PASSTHROUGH_KEYS if k in params]
    for key in copied:
        arch[key] = params[key]
    kind = str(arch.get("kind", ""))
    if copied and (kind.startswith("mlp") or kind.startswith("cnn")):
        import warnings

        warnings.warn(
            f"model overrides {copied} have no effect on model kind "
            f"{kind!r} — did you forget model_kind="
            f"\"transformer_discrete\" (or another sequence kind)?",
            stacklevel=2)
    return arch


@dataclasses.dataclass(frozen=True)
class Policy:
    """Pure-function policy bundle.

    * ``init_params(rng) -> params``
    * ``step(params, rng, obs, mask) -> (act, aux)`` — sampling forward;
      ``aux`` always contains ``logp_a`` and ``v`` (v=0 without a critic),
      mirroring the reference's step ABI. Works on single obs ``[obs_dim]``
      or batches ``[..., obs_dim]``.
    * ``evaluate(params, obs, act, mask) -> (logp, entropy, v)`` — the
      learner-side forward for loss computation on ``[..., obs_dim]``.
    * ``mode(params, obs, mask) -> act`` — deterministic action (greedy).
    * ``step_window(params, rng, window, t, mask) -> (act, aux)`` —
      optional, sequence policies only: act from a fixed-size
      right-zero-padded observation window ``[W, obs_dim]`` whose first
      ``t`` rows are real. One jit signature regardless of history length
      (causal attention never attends past the read position, so the
      padding is inert). PolicyActor uses this to serve sequence policies
      with real context instead of context-1 per request.
    """

    arch: dict[str, Any]
    init_params: Callable
    step: Callable
    evaluate: Callable
    mode: Callable
    step_window: Callable | None = None
    mode_window: Callable | None = None
    # KV-cache incremental serving (sequence policies): ``init_cache(W,
    # batch_size) -> cache`` and ``step_cached(params, rng, cache, obs, t,
    # mask) -> (act, aux, new_cache)`` — O(W) per step vs step_window's
    # full-window recompute. Numerics identical to step_window while
    # t < W (PolicyActor falls back to the window path past that, and
    # replays the window to rebuild the cache after a model hot-swap).
    init_cache: Callable | None = None
    step_cached: Callable | None = None
    # ``prefill_cache(params, cache, window) -> cache`` rebuilds the whole
    # cache from the padded window in one dispatch (used after hot-swaps).
    prefill_cache: Callable | None = None

    @property
    def input_dim(self) -> int:
        return int(self.arch["obs_dim"])

    @property
    def output_dim(self) -> int:
        return int(self.arch["act_dim"])

    # -- reference getter parity --
    def get_input_dim(self) -> int:
        return self.input_dim

    def get_output_dim(self) -> int:
        return self.output_dim


def validate_policy(policy: Policy, params) -> None:
    """Dummy-forward validation on load (ref: agent_wrapper.rs:88-168 runs a
    zero-obs ``step`` and asserts the output shape/aux dict)."""
    obs_shape = policy.arch.get("obs_shape") or (policy.input_dim,)
    obs = jnp.zeros(tuple(obs_shape), dtype=jnp.float32)
    mask = jnp.ones((policy.output_dim,), dtype=jnp.float32)
    act, aux = policy.step(params, jax.random.PRNGKey(0), obs, mask)
    if not isinstance(aux, dict) or "logp_a" not in aux:
        raise ValueError("policy step ABI violation: aux dict missing 'logp_a'")
    act_arr = np.asarray(act)
    if act_arr.ndim > 1:
        raise ValueError(f"policy step returned act of rank {act_arr.ndim} for single obs")


def mlp_sizes(arch: Mapping[str, Any]) -> tuple[int, ...]:
    return tuple(int(h) for h in arch.get("hidden_sizes", (128, 128)))
