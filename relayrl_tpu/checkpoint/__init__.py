"""Checkpoint/resume (full train state, async orbax saves)."""

from relayrl_tpu.checkpoint.manager import (
    CheckpointManager,
    checkpoint_algorithm,
    restore_algorithm,
    restore_latest_healthy,
)

__all__ = ["CheckpointManager", "checkpoint_algorithm",
           "restore_algorithm", "restore_latest_healthy"]
