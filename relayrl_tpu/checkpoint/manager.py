"""Checkpoint/resume of the full learner state.

The reference's only checkpoint is the TorchScript policy file — restarting
the server loses optimizer/buffer/epoch state (SURVEY.md §5.4; its
Logger.save_state is dead code referencing an unimported joblib,
utils/logger.py:200-229). Here a checkpoint is the complete train state:
params, both optimizer states, RNG key, step counter, plus host-side
counters (epoch, model version), via orbax with async save.
"""

from __future__ import annotations

import json
import os
import os.path as osp
from typing import Any

import jax


class CheckpointManager:
    """Thin orbax wrapper: numbered step directories + latest-step resume."""

    DEFAULT_MAX_TO_KEEP = 3

    def __init__(self, directory: str,
                 max_to_keep: int = DEFAULT_MAX_TO_KEEP):
        import orbax.checkpoint as ocp

        self.directory = osp.abspath(directory)
        self.max_to_keep = max_to_keep
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, state: Any, extra: dict | None = None,
             wait: bool = False, aux: Any = None,
             overwrite: bool = False) -> None:
        """Async save of the state pytree (+ JSON-able extras; ``aux`` is
        an optional host-array pytree — replay buffer contents — that
        older checkpoints simply don't carry). ``overwrite=True`` makes a
        same-step collision land at the next free step number instead of
        being silently skipped (orbax no-ops a repeat save; its
        ``force=True`` does not overwrite) — the signal path uses it so a
        final save that collides with an aux-less periodic save at the
        same version still lands WITH the replay snapshot. (Collision
        behavior differs by manager instance: the instance that made the
        earlier save silently skips the repeat — its should_save gate —
        while a FRESH instance raises StepAlreadyExistsError; overwrite
        handles both by checking all_steps up front.) Bumping (not
        delete-then-rewrite) means an interrupted final save can never
        destroy the existing checkpoint; step numbers are labels — the
        true version is inside state/extra."""
        import orbax.checkpoint as ocp

        args = {
            "state": ocp.args.StandardSave(state),
            # always present so restore() can ask for it unconditionally
            "extra": ocp.args.JsonSave(extra if extra is not None else {}),
        }
        if aux is not None:
            args["aux"] = ocp.args.StandardSave(aux)
        if overwrite:
            existing = self._mgr.all_steps()
            if step in existing:
                step = max(existing) + 1
        self._mgr.save(step, args=ocp.args.Composite(**args))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, state_template: Any, step: int | None = None,
                load_aux: bool = True) -> tuple[Any, dict, Any]:
        """Restore (state, extra, aux) at ``step`` (default latest); aux
        is None for checkpoints that predate it (shapes are whatever was
        saved — no template, the ring length varies between saves).
        ``load_aux=False`` skips even reading the aux arrays — a
        multi-process resume of a single-host checkpoint must not haul a
        coordinator-only replay buffer onto every rank."""
        import orbax.checkpoint as ocp

        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        items = {
            "state": ocp.args.StandardRestore(state_template),
            "extra": ocp.args.JsonRestore(),
        }
        has_aux = load_aux and "aux" in (self._mgr.item_metadata(step) or {})
        if has_aux:
            items["aux"] = ocp.args.StandardRestore()
        restored = self._mgr.restore(step, args=ocp.args.Composite(**items))
        extra = dict(restored.get("extra") or {})
        aux = restored.get("aux")
        if load_aux and aux is None:
            aux = self._restore_aux_fallback(step)
        return restored["state"], extra, aux

    def _restore_aux_fallback(self, newer_than: int) -> Any:
        """Newest retained step OLDER than ``newer_than`` that carries an
        aux snapshot. With ``checkpoint_aux_every > 1`` the latest step
        usually has no replay snapshot — a crash-resume should still get
        the newest retained experience rather than an empty ring (replay
        data a few versions stale is valid off-policy experience; the
        params/optimizer still come from the latest step)."""
        import orbax.checkpoint as ocp

        for s in sorted(self._mgr.all_steps(), reverse=True):
            if s >= newer_than:
                continue
            if "aux" in (self._mgr.item_metadata(s) or {}):
                restored = self._mgr.restore(s, args=ocp.args.Composite(
                    aux=ocp.args.StandardRestore()))
                return restored.get("aux")
        return None

    def read_extra(self, step: int) -> dict:
        """The JSON extras of one retained step WITHOUT touching the
        state arrays — how the rollback path reads health tags cheaply
        (a step predating the tag returns {} → treated unhealthy by
        :meth:`healthy_steps`, conservatively)."""
        import orbax.checkpoint as ocp

        restored = self._mgr.restore(
            step, args=ocp.args.Composite(extra=ocp.args.JsonRestore()))
        return dict(restored.get("extra") or {})

    def healthy_steps(self) -> list[int]:
        """Retained steps whose save-time extras carry ``healthy: true``
        (ascending). The guardrail rollback ring: the server tags each
        periodic save with the watchdog's verdict AFTER quiescing the
        in-flight window, so a healthy tag means every update baked into
        that step had its probes resolved clean."""
        out = []
        for step in self._mgr.all_steps():
            try:
                if self.read_extra(step).get("healthy"):
                    out.append(step)
            except Exception:
                continue  # unreadable step: never a rollback target
        return sorted(out)

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


def checkpoint_algorithm(algo, directory: str | None = None,
                         wait: bool = False,
                         include_aux: bool = True,
                         overwrite: bool = False,
                         max_to_keep: int | None = None,
                         extra_meta: dict | None = None) -> CheckpointManager:
    """Save an algorithm's full state (convenience used by the server).

    ``include_aux=False`` skips the replay-buffer snapshot: for a large
    ring (say 1M transitions) ``state_arrays()`` is a synchronous
    multi-hundred-MB copy on the calling (learner) thread before orbax
    even starts writing, so the server throttles aux to every Nth
    periodic save (``learner.checkpoint_aux_every``) while final/signal
    saves always carry it. Callers using an aux cadence must pass
    ``max_to_keep >= cadence`` so retention always holds at least one
    aux-carrying step for crash-resume (the server does)."""
    directory = directory or osp.join(".", "checkpoints")
    want_keep = max_to_keep or CheckpointManager.DEFAULT_MAX_TO_KEEP
    mgr = getattr(algo, "_ckpt_mgr", None)
    # Recreate the cached manager when the caller needs MORE retention —
    # reusing a keep-3 manager under an aux cadence of 10 would
    # garbage-collect every aux-carrying step and void the crash-resume
    # guarantee the cadence relies on.
    if (mgr is None or mgr.directory != osp.abspath(directory)
            or mgr.max_to_keep < want_keep):
        if mgr is not None and mgr.directory == osp.abspath(directory):
            mgr.close()
        mgr = CheckpointManager(directory, max_to_keep=want_keep)
        algo._ckpt_mgr = mgr
    extra = {
        "epoch": int(getattr(algo, "epoch", 0)),
        "version": int(algo.version),
        "arch": algo.arch,
    }
    freeze_info = getattr(algo, "freeze_info", None)
    if freeze_info:
        # The learner.freeze mask rides every checkpoint (patterns +
        # frozen-leaf accounting, minus the per-path listing — extras
        # are JSON, keep them small): a resume can verify it restores
        # under the same partition (restore_algorithm checks), and an
        # operator reading the checkpoint knows which leaves were frozen
        # without re-deriving the regex match.
        extra["freeze"] = {k: v for k, v in freeze_info.items()
                          if k != "frozen_paths"}
    if extra_meta:
        # Caller metadata rides the JSON extras (the guardrail plane's
        # healthy-at-save tag); the reserved keys above win on collision.
        extra = {**dict(extra_meta), **extra}
    # aux (replay buffer) is single-host only: on a multi-process mesh the
    # orbax save is collective and every rank must contribute an identical
    # structure, but the buffer lives on the coordinator alone — multi-host
    # resume refills the ring instead (docs/operations.md).
    aux = None
    if include_aux and jax.process_count() == 1:
        aux = algo.checkpoint_aux()
    mgr.save(int(algo.version), jax.device_get(algo.state), extra, wait=wait,
             aux=aux, overwrite=overwrite)
    return mgr


def restore_latest_healthy(algo, directory: str | None = None) -> int:
    """Last-known-good restore: roll ``algo`` back to the NEWEST retained
    checkpoint tagged ``healthy: true`` at save time. Returns the
    restored step. Raises FileNotFoundError when no healthy step is
    retained — the rollback path then degrades to halt-and-alarm rather
    than restoring a step the watchdog never cleared.

    Uses the algorithm's cached manager when it matches the directory
    (the live server's, with its retention settings); callers must
    :meth:`CheckpointManager.wait` out any in-flight async save first so
    the step listing is settled."""
    directory = directory or osp.join(".", "checkpoints")
    mgr = getattr(algo, "_ckpt_mgr", None)
    own = mgr is None or mgr.directory != osp.abspath(directory)
    if own:
        mgr = CheckpointManager(directory)
    try:
        healthy = mgr.healthy_steps()
        if not healthy:
            raise FileNotFoundError(
                f"no healthy-tagged checkpoint retained in {directory}")
        restore_algorithm(algo, directory, step=healthy[-1], manager=mgr)
        return healthy[-1]
    finally:
        if own:
            mgr.close()


def restore_algorithm(algo, directory: str | None = None,
                      step: int | None = None,
                      manager: CheckpointManager | None = None) -> None:
    """Restore a previously checkpointed algorithm in place."""
    directory = directory or osp.join(".", "checkpoints")
    mgr = manager if manager is not None else CheckpointManager(directory)
    resolved = mgr.latest_step() if step is None else step
    if resolved is not None:
        # learner.freeze guard BEFORE the array restore: a mismatched
        # mask changes the multi_transform opt-state STRUCTURE, so orbax
        # would otherwise fail with a cryptic tree error — and where the
        # structures happen to agree (pattern change within one label
        # set) the resume would silently start training leaves the
        # checkpointed line held frozen. Extras are JSON: reading them
        # first is cheap.
        saved_freeze = (mgr.read_extra(resolved).get("freeze")
                        or {}).get("patterns", [])
        live_freeze = list((getattr(algo, "freeze_info", None)
                            or {}).get("patterns", []))
        if saved_freeze != live_freeze:
            raise ValueError(
                f"checkpoint learner.freeze {saved_freeze} != configured "
                f"{live_freeze}; align the config with the checkpointed "
                "mask (or retrain from scratch)")
    # Symmetric with the save-side gate: the replay buffer is a
    # coordinator-only host structure, so a multi-process resume of a
    # single-host checkpoint skips it (the ring refills) instead of
    # loading it onto every rank.
    state, extra, aux = mgr.restore(jax.device_get(algo.state), resolved,
                                    load_aux=jax.process_count() == 1)
    if extra.get("arch") and json.dumps(extra["arch"], sort_keys=True) != \
            json.dumps(algo.arch, sort_keys=True):
        raise ValueError(
            f"checkpoint arch {extra.get('arch')} != algorithm arch {algo.arch}")
    algo.state = jax.device_put(state)
    algo.epoch = int(extra.get("epoch", 0))
    # The async-publish version mirror (base.py _dispatched_updates)
    # re-syncs from the restored step before the next dispatch.
    algo._dispatched_updates = None
    if aux is not None:
        algo.restore_aux(aux)
    if manager is None:
        mgr.close()
