"""TokenGen: token-level autoregressive generation env (numpy built-in).

The RLHF workload plane's environment (ISSUE 13): one episode is one
generation. The agent sees the current **token context window** — an
int32 buffer of length ``prompt_len + max_new_tokens`` holding the
sampled prompt followed by the tokens generated so far (zero-padded
ahead of the write position; token 0 is reserved as EOS/pad) — and emits
the next token as its action. The episode ends when the agent emits EOS
or fills ``max_new_tokens``; at that boundary a pluggable **scorer**
pays the whole sequence's reward in one terminal step (per-step reward
is always 0.0 — the RLHF shape: credit arrives only at the end of the
generation).

``scorer=None`` is the *decoupled-dataflow* mode: terminal reward stays
0.0 and a downstream score stage assigns it before the episode reaches
the learner (``relayrl_tpu/rlhf/scheduler.py`` — generate and score run
as separate pipeline stages). With a scorer attached the env is
self-contained (CI loops, the anakin tier via the pure-JAX twin).

Both endings are ``terminated`` (never ``truncated``): reaching
``max_new_tokens`` is part of the MDP — the scorer pays the full return
at that boundary and there is no post-boundary state to bootstrap
through, unlike a time-limit cut of an ongoing task.

Dynamics are all-integer (prompt sampling, buffer writes, flags), so
the pure-JAX twin (``envs/jax/tokengen.py``) holds FULL bitwise parity
on observation/flags/counters; the reward is bit-equal too whenever the
two planes share the scorer implementation (the built-in scorers expose
one jitted implementation to both — relayrl_tpu/rlhf/scorers.py).
"""

from __future__ import annotations

import numpy as np

from relayrl_tpu.envs.spaces import Box, Discrete

EOS_TOKEN = 0


def _resolve_scorer(scorer):
    """Accept a scorer object (``score_np(tokens, prompt_len, gen_len)``
    and/or the traceable ``score_jax`` twin), a plain host callable with
    the ``score_np`` signature, a registered scorer name, or None
    (decoupled mode — reward assigned downstream by the score stage)."""
    if scorer is None:
        return None
    if isinstance(scorer, str):
        # Lazy so `import relayrl_tpu.envs` stays light; the names live
        # beside the scheduler that consumes them.
        from relayrl_tpu.rlhf.scorers import make_scorer

        return make_scorer(scorer)
    if (callable(getattr(scorer, "score_np", None))
            or callable(getattr(scorer, "score_jax", None))):
        return scorer
    if callable(scorer):
        class _Wrapped:
            score_np = staticmethod(scorer)
        return _Wrapped()
    raise ValueError(f"scorer must be None, a name, a callable, or expose "
                     f"score_np/score_jax; got {type(scorer).__name__}")


class TokenGenEnv:
    """One generation per episode: obs = int32 token context window,
    action = next token, terminal at EOS/max_new_tokens, scored at the
    boundary."""

    def __init__(self, vocab_size: int = 8, prompt_len: int = 3,
                 max_new_tokens: int = 8, scorer=None):
        if vocab_size < 2:
            raise ValueError("vocab_size must be >= 2 (EOS + 1 real token)")
        if prompt_len < 1 or max_new_tokens < 1:
            raise ValueError("prompt_len and max_new_tokens must be >= 1")
        self.vocab_size = int(vocab_size)
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.context_len = self.prompt_len + self.max_new_tokens
        self.scorer = _resolve_scorer(scorer)
        self.observation_space = Box(0, self.vocab_size - 1,
                                     shape=(self.context_len,),
                                     dtype=np.int32)
        self.action_space = Discrete(self.vocab_size)
        self._rng = np.random.default_rng()
        self._tokens = np.zeros(self.context_len, np.int32)
        self._t = 0

    def reset(self, seed: int | None = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._tokens = np.zeros(self.context_len, np.int32)
        # Prompts draw from the REAL vocabulary [1, V): an EOS inside the
        # prompt would alias the pad region and make gen_len ambiguous.
        self._tokens[: self.prompt_len] = self._rng.integers(
            1, self.vocab_size, self.prompt_len, dtype=np.int32)
        self._t = 0
        return self._tokens.copy(), {}

    def step(self, action):
        token = int(np.clip(int(action), 0, self.vocab_size - 1))
        self._tokens[self.prompt_len + self._t] = token
        self._t += 1
        terminated = (token == EOS_TOKEN) or (self._t >= self.max_new_tokens)
        reward = 0.0
        if terminated and self.scorer is not None:
            reward = float(self.scorer.score_np(
                self._tokens, self.prompt_len, self._t))
        return self._tokens.copy(), reward, terminated, False, {}
