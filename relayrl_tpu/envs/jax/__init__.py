"""On-device environment registry (pure-JAX twins of the built-ins).

Entries here are first-class registry citizens alongside the numpy
built-ins: ``envs.list_envs()`` folds both in, ``envs.make_jax`` resolves
an id to a :class:`~relayrl_tpu.envs.jax.base.JaxEnv` instance, and the
fused rollout engine (``runtime/anakin.py``, ``actor.jax_env`` knob) looks
envs up through this one table. Ids deliberately match the host twins so a
config can flip ``actor.host_mode`` between ``"vector"`` and ``"anakin"``
without renaming the task.
"""

from relayrl_tpu.envs.jax.bandit import BanditState, JaxBandit
from relayrl_tpu.envs.jax.base import JaxEnv, step_autoreset, tree_where
from relayrl_tpu.envs.jax.cartpole import CartPoleState, JaxCartPole
from relayrl_tpu.envs.jax.gridworld import GridWorldState, JaxGridWorld
from relayrl_tpu.envs.jax.pendulum import JaxPendulum, PendulumState
from relayrl_tpu.envs.jax.recall import JaxRecall, RecallState
from relayrl_tpu.envs.jax.tokengen import JaxTokenGen, TokenGenState

JAX_ENVS = {
    "CartPole-v1": JaxCartPole,
    "Pendulum-v1": JaxPendulum,
    "Recall-v0": JaxRecall,
    "GridWorld-v0": JaxGridWorld,
    "Bandit-v0": JaxBandit,
    "TokenGen-v0": JaxTokenGen,
}


def make_jax(env_id: str, **kwargs) -> JaxEnv:
    """Create an on-device env by id (the JAX-side ``envs.make``)."""
    if env_id not in JAX_ENVS:
        from relayrl_tpu.envs import list_envs

        raise ValueError(
            f"unknown JAX env {env_id!r}; on-device envs: "
            f"{sorted(JAX_ENVS)} (full registry: {list_envs()})")
    return JAX_ENVS[env_id](**kwargs)


__all__ = ["JaxEnv", "JAX_ENVS", "make_jax", "step_autoreset", "tree_where",
           "JaxCartPole", "CartPoleState", "JaxPendulum", "PendulumState",
           "JaxRecall", "RecallState", "JaxGridWorld", "GridWorldState",
           "JaxBandit", "BanditState", "JaxTokenGen", "TokenGenState"]
