"""The on-device environment ABI: pure functions over pytree state.

The host-bound built-ins (``envs/classic.py``, ``envs/memory.py``) step one
Python call at a time — ~30k env-steps/s end to end even behind the vector
actor host, because ``SyncVectorEnv`` batches the *policy* dispatch while
each env lane remains a numpy loop. The Podracer Anakin pattern
(arXiv:2104.06272) and Jumanji (arXiv:2306.09884) move the env itself onto
the device: dynamics become jittable pure functions, whole trajectory
windows fuse into one ``jit(vmap(lax.scan(policy ∘ env.step)))`` dispatch
(``runtime/anakin.py``), and lanes never leave the chip mid-window.

ABI (functional, Jumanji/gymnax-shaped, Gymnasium field semantics)::

    reset(key)          -> (state, obs)
    step(state, action) -> (state, obs, reward, terminated, truncated)

* ``state`` is a NamedTuple of arrays (lax.scan-able: fixed shapes/dtypes,
  no Python objects). ``step`` is deterministic given ``state`` — all
  stochasticity enters through ``reset(key)`` (and, for envs with
  observation noise, a key field carried *inside* the state).
* ``reward``/``terminated``/``truncated`` follow the numpy built-ins'
  Gymnasium step contract exactly, field for field — the dynamics-parity
  goldens (tests/test_jax_envs.py) hold each JAX env against its numpy
  twin step for step.
* Dtypes are pinned: float32 observations/rewards, int32 counters, bool
  flags. The numpy built-ins compute in float64 and round at the obs
  boundary; XLA also contracts mul+add chains into FMAs — so continuous
  observations agree to a few float32 ulp per step (measured ≤2 ulp on
  this backend, asserted by the goldens), while every discrete field
  (rewards where integral, flags, counters, Recall's whole observation)
  is exactly equal. Within the JAX path itself, same seed + same compiled
  program ⇒ byte-identical trajectories across processes.

``step_autoreset`` is the in-scan episode-boundary composition: a done
lane resets *inside the same scan iteration* via ``jnp.where`` masking
(under ``vmap``, ``lax.cond`` lowers to select anyway — computing the
cheap reset unconditionally keeps one fused program), so lanes never
leave the device between episodes. It mirrors ``SyncVectorEnv``'s
autoreset surface: the returned ``obs`` is already the next episode's
first observation and the pre-reset observation rides alongside for
time-limit bootstrapping.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class JaxEnv:
    """Base class carrying the space metadata; subclasses implement the
    functional ``reset``/``step`` pair. Instances hold only static
    configuration (horizon, physics constants) — never per-episode state —
    so one instance serves every lane of a fused rollout."""

    observation_space: Any
    action_space: Any

    @property
    def obs_dim(self) -> int:
        return int(self.observation_space.shape[0])

    def reset(self, key) -> tuple[NamedTuple, jnp.ndarray]:
        raise NotImplementedError

    def step(self, state, action):
        raise NotImplementedError


def tree_where(pred, on_true, on_false):
    """Per-leaf ``jnp.where`` over two same-structure pytrees; ``pred`` is
    a scalar (or broadcastable) bool. The masking primitive the in-scan
    autoreset is built from."""
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b),
                        on_true, on_false)


def step_autoreset(env: JaxEnv, key, state, action):
    """One env step with the episode boundary folded into the scan body.

    Returns ``(key, state, obs, reward, terminated, truncated,
    final_obs)`` where, for a lane that just finished, ``state``/``obs``
    are already the NEXT episode's reset state/observation (seeded from a
    fresh split of ``key`` — the per-lane key stream makes every lane's
    episode sequence reproducible from the rollout seed alone) and
    ``final_obs`` is the pre-reset observation (the ``final_observation``
    of the Gymnasium VectorEnv convention, needed for time-limit
    bootstrapping). For an unfinished lane, ``final_obs`` equals ``obs``
    and the reset branch is masked out by ``jnp.where``.

    The key splits every step, done or not: a data-dependent split count
    would make the key stream depend on episode lengths, breaking the
    fixed-seed reproducibility contract the determinism goldens pin.
    """
    stepped_state, stepped_obs, reward, terminated, truncated = env.step(
        state, action)
    done = jnp.logical_or(terminated, truncated)
    key, reset_key = jax.random.split(key)
    reset_state, reset_obs = env.reset(reset_key)
    next_state = tree_where(done, reset_state, stepped_state)
    next_obs = jnp.where(done, reset_obs, stepped_obs)
    return (key, next_state, next_obs, reward, terminated, truncated,
            stepped_obs)
