"""Recall memory task as pure JAX — the on-device twin of
``envs/memory.RecallEnv``.

Integer-derived observations (cue one-hot, query flag, phase fraction), so
with ``noise=0`` (the default) the parity goldens hold this env to FULL
bitwise equality against the numpy twin — observation, reward, and flags —
whenever ``horizon`` is a power of two (the single ``t/horizon`` division
then rounds identically in float32 and float64). The optional distractor
noise draws from the state-carried PRNG key instead of a host ``Generator``
(the one necessarily PRNG-specific departure).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from relayrl_tpu.envs.jax.base import JaxEnv
from relayrl_tpu.envs.spaces import Box, Discrete


class RecallState(NamedTuple):
    cue: jnp.ndarray  # [] int32
    t: jnp.ndarray    # [] int32
    key: jnp.ndarray  # [2] uint32 — consumed only when noise > 0


class JaxRecall(JaxEnv):
    """Remember-the-cue: obs = [cue one-hot (t=0 only), is_query, t/T]."""

    def __init__(self, horizon: int = 8, n_cues: int = 2,
                 noise: float = 0.0):
        if horizon < 2:
            raise ValueError("horizon must be >= 2 (cue step + query step)")
        self.horizon = int(horizon)
        self.n_cues = int(n_cues)
        self.noise = float(noise)
        self.observation_space = Box(-np.inf, np.inf,
                                     shape=(self.n_cues + 2,))
        self.action_space = Discrete(self.n_cues)

    def _obs(self, cue, t, noise_key) -> jnp.ndarray:
        if self.noise > 0.0:
            distractor = self.noise * jax.random.normal(
                noise_key, (self.n_cues,), jnp.float32)
        else:
            distractor = jnp.zeros((self.n_cues,), jnp.float32)
        head = jnp.where(t == 0, jax.nn.one_hot(cue, self.n_cues,
                                                dtype=jnp.float32),
                         distractor)
        is_query = (t == self.horizon - 1).astype(jnp.float32)
        phase = t.astype(jnp.float32) / self.horizon
        return jnp.concatenate([head, jnp.stack([is_query, phase])])

    def reset(self, key):
        cue_key, noise_key, carry_key = jax.random.split(key, 3)
        cue = jax.random.randint(cue_key, (), 0, self.n_cues, jnp.int32)
        state = RecallState(cue=cue, t=jnp.int32(0), key=carry_key)
        return state, self._obs(cue, state.t, noise_key)

    def step(self, state, action):
        is_query = state.t == self.horizon - 1
        reward = jnp.where(
            jnp.logical_and(
                is_query,
                jnp.asarray(action).astype(jnp.int32) == state.cue),
            jnp.float32(1.0), jnp.float32(0.0))
        t = state.t + 1
        key, noise_key = jax.random.split(state.key)
        new = RecallState(cue=state.cue, t=t, key=key)
        terminated = t >= self.horizon
        return (new, self._obs(state.cue, t, noise_key), reward,
                terminated, jnp.bool_(False))
