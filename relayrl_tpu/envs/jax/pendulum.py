"""Pendulum-v1 swing-up as pure JAX — the on-device twin of
``envs/classic.PendulumEnv``.

Same torque-limited dynamics in the same operation order (constants
imported from the numpy class), float32 throughout; the reward is computed
from the PRE-update angle exactly like the numpy twin. Continuous action:
anything that squeezes to a scalar (the MLP-continuous policy emits
``[1]``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import jax.random
import numpy as np

from relayrl_tpu.envs.classic import PendulumEnv
from relayrl_tpu.envs.jax.base import JaxEnv
from relayrl_tpu.envs.spaces import Box


class PendulumState(NamedTuple):
    theta: jnp.ndarray      # [] float32
    theta_dot: jnp.ndarray  # [] float32
    t: jnp.ndarray          # [] int32


class JaxPendulum(JaxEnv):
    """Functional pendulum swing-up, Gymnasium Pendulum-v1 semantics."""

    def __init__(self, max_steps: int | None = None):
        c = PendulumEnv
        high = np.array([1.0, 1.0, c.MAX_SPEED], np.float32)
        self.observation_space = Box(-high, high)
        self.action_space = Box(-c.MAX_TORQUE, c.MAX_TORQUE, shape=(1,))
        self.max_steps = int(max_steps or c.MAX_STEPS)

    def reset(self, key):
        k_theta, k_vel = jax.random.split(key)
        theta = jax.random.uniform(k_theta, (), jnp.float32, -np.pi, np.pi)
        theta_dot = jax.random.uniform(k_vel, (), jnp.float32, -1.0, 1.0)
        state = PendulumState(theta=theta, theta_dot=theta_dot,
                              t=jnp.int32(0))
        return state, self._obs(state)

    def step(self, state, action):
        c = PendulumEnv
        u = jnp.clip(
            jnp.squeeze(jnp.asarray(action, jnp.float32)),
            -c.MAX_TORQUE, c.MAX_TORQUE)
        theta, theta_dot = state.theta, state.theta_dot
        norm_theta = ((theta + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_theta**2 + 0.1 * theta_dot**2 + 0.001 * u**2

        theta_dot = theta_dot + (
            3 * c.G / (2 * c.L) * jnp.sin(theta)
            + 3.0 / (c.M * c.L**2) * u
        ) * c.DT
        theta_dot = jnp.clip(theta_dot, -c.MAX_SPEED, c.MAX_SPEED)
        theta = theta + theta_dot * c.DT
        t = state.t + 1
        new = PendulumState(theta=theta, theta_dot=theta_dot, t=t)
        return (new, self._obs(new), -cost,
                jnp.bool_(False), t >= self.max_steps)

    def _obs(self, state: PendulumState) -> jnp.ndarray:
        return jnp.stack([jnp.cos(state.theta), jnp.sin(state.theta),
                          state.theta_dot])
