"""GridWorld as pure JAX — the on-device twin of
``envs/gridworld.GridWorldEnv``.

All-integer dynamics (int32 positions, clamped moves, exactly-integral
rewards), so the parity golden holds this env to FULL bitwise equality
against the numpy twin — observation, reward, and both flags — with no
float-tolerance carve-out. The int32 ``[row, col]`` observation is the
point: under the anakin tier it rides the columnar trajectory wire as an
int32 column (types/columnar.py), exercising the non-float obs path end
to end.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from relayrl_tpu.envs.jax.base import JaxEnv
from relayrl_tpu.envs.spaces import Box, Discrete

# Same action table as the numpy twin (envs/gridworld.MOVES).
_MOVES = jnp.array([[-1, 0], [1, 0], [0, -1], [0, 1]], jnp.int32)


class GridWorldState(NamedTuple):
    pos: jnp.ndarray  # [2] int32
    t: jnp.ndarray    # [] int32


class JaxGridWorld(JaxEnv):
    """Reach the corner: obs = int32 ``[row, col]``; actions
    up/down/left/right; reward 1.0 exactly at the goal."""

    def __init__(self, size: int = 5, max_steps: int = 50):
        if size < 2:
            raise ValueError("size must be >= 2 (start and goal differ)")
        self.size = int(size)
        self.max_steps = int(max_steps)
        self.observation_space = Box(0, self.size - 1, shape=(2,),
                                     dtype=np.int32)
        self.action_space = Discrete(4)

    def reset(self, key):
        # Uniform over the non-goal cells (the goal owns the last linear
        # index) — the same distribution the numpy twin draws from.
        idx = jax.random.randint(key, (), 0, self.size * self.size - 1,
                                 jnp.int32)
        pos = jnp.stack([idx // self.size, idx % self.size])
        state = GridWorldState(pos=pos.astype(jnp.int32), t=jnp.int32(0))
        return state, state.pos

    def step(self, state, action):
        move = _MOVES[jnp.asarray(action).astype(jnp.int32)]
        pos = jnp.clip(state.pos + move, 0, self.size - 1)
        t = state.t + 1
        terminated = jnp.all(pos == self.size - 1)
        reward = jnp.where(terminated, jnp.float32(1.0), jnp.float32(0.0))
        truncated = t >= self.max_steps
        return (GridWorldState(pos=pos, t=t), pos, reward,
                terminated, truncated)
