"""Bandit battery as pure JAX — the on-device twin of ``envs/bandit.py``.

All-integer dynamics (context draw, target-arm residue, 0/1 reward,
flags), so the parity golden holds FULL bitwise equality — observation,
reward, both flags — with no float carve-out (the GridWorld precedent).
One-step episodes make this the fastest regression signal the anakin
tier and the RLHF scheduler can run against: every scanned step crosses
an episode boundary, so autoreset, terminal folding, and credit
assignment are all exercised at the maximum possible rate.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from relayrl_tpu.envs.jax.base import JaxEnv
from relayrl_tpu.envs.spaces import Box, Discrete


class BanditState(NamedTuple):
    ctx: jnp.ndarray  # [] int32


class JaxBandit(JaxEnv):
    """One-step contextual bandit: obs = int32 one-hot context; reward
    1.0 exactly when the arm equals ``(ctx * mult + shift) % n_arms``."""

    def __init__(self, n_contexts: int = 8, n_arms: int = 4,
                 mult: int = 3, shift: int = 1):
        if n_contexts < 1 or n_arms < 2:
            raise ValueError("need n_contexts >= 1 and n_arms >= 2")
        self.n_contexts = int(n_contexts)
        self.n_arms = int(n_arms)
        self.mult = int(mult)
        self.shift = int(shift)
        self.observation_space = Box(0, 1, shape=(self.n_contexts,),
                                     dtype=np.int32)
        self.action_space = Discrete(self.n_arms)

    def _obs(self, ctx) -> jnp.ndarray:
        return (jnp.arange(self.n_contexts, dtype=jnp.int32)
                == ctx).astype(jnp.int32)

    def reset(self, key):
        ctx = jax.random.randint(key, (), 0, self.n_contexts, jnp.int32)
        return BanditState(ctx=ctx), self._obs(ctx)

    def step(self, state, action):
        arm = jnp.clip(jnp.asarray(action).astype(jnp.int32), 0,
                       self.n_arms - 1)
        target = (state.ctx * self.mult + self.shift) % self.n_arms
        reward = jnp.where(arm == target, jnp.float32(1.0),
                           jnp.float32(0.0))
        return (state, self._obs(state.ctx), reward, jnp.bool_(True),
                jnp.bool_(False))
