"""TokenGen as pure JAX — the on-device twin of ``envs/tokengen.py``.

All-integer dynamics (prompt sampling, token buffer writes, flags), so
the parity goldens hold this env to FULL bitwise equality against the
numpy twin on observation/flags/counters from injected states. The
reward is paid by the pluggable scorer at the terminal step; the
built-in scorers (relayrl_tpu/rlhf/scorers.py) expose one jitted
implementation to both planes, so the scored reward is bit-equal too.

``scorer.score_jax(tokens, prompt_len, gen_len)`` must be traceable
(pure function of the int32 token buffer; ``prompt_len`` arrives as a
static Python int). A :class:`~relayrl_tpu.rlhf.scorers.
RewardModelScorer` closes over its frozen transformer params — static
per-instance configuration under the JaxEnv contract, exactly like
physics constants — so the whole episode, scoring included, fuses into
the anakin ``jit(vmap(lax.scan))`` rollout.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from relayrl_tpu.envs.jax.base import JaxEnv
from relayrl_tpu.envs.spaces import Box, Discrete
from relayrl_tpu.envs.tokengen import EOS_TOKEN, _resolve_scorer


class TokenGenState(NamedTuple):
    tokens: jnp.ndarray  # [prompt_len + max_new_tokens] int32
    t: jnp.ndarray       # [] int32 — generated-token count


class JaxTokenGen(JaxEnv):
    """One generation per episode: obs = int32 token context window,
    action = next token, terminal at EOS/max_new_tokens (both are
    ``terminated`` — the scorer pays the full return at the boundary,
    there is nothing to bootstrap through)."""

    def __init__(self, vocab_size: int = 8, prompt_len: int = 3,
                 max_new_tokens: int = 8, scorer=None):
        if vocab_size < 2:
            raise ValueError("vocab_size must be >= 2 (EOS + 1 real token)")
        if prompt_len < 1 or max_new_tokens < 1:
            raise ValueError("prompt_len and max_new_tokens must be >= 1")
        self.vocab_size = int(vocab_size)
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.context_len = self.prompt_len + self.max_new_tokens
        self.scorer = _resolve_scorer(scorer)
        if (self.scorer is not None
                and not callable(getattr(self.scorer, "score_jax", None))):
            raise ValueError(
                "the on-device TokenGen needs a traceable scorer "
                "(score_jax); host-only callables serve the numpy twin / "
                "the decoupled score stage (rlhf/scheduler.py)")
        self.observation_space = Box(0, self.vocab_size - 1,
                                     shape=(self.context_len,),
                                     dtype=np.int32)
        self.action_space = Discrete(self.vocab_size)

    def reset(self, key):
        prompt = jax.random.randint(key, (self.prompt_len,), 1,
                                    self.vocab_size, jnp.int32)
        tokens = jnp.zeros(self.context_len, jnp.int32)
        tokens = jax.lax.dynamic_update_slice_in_dim(tokens, prompt, 0,
                                                     axis=0)
        state = TokenGenState(tokens=tokens, t=jnp.int32(0))
        return state, tokens

    def step(self, state, action):
        token = jnp.clip(jnp.asarray(action).astype(jnp.int32), 0,
                         self.vocab_size - 1)
        tokens = state.tokens.at[self.prompt_len + state.t].set(token)
        t = state.t + 1
        terminated = jnp.logical_or(token == EOS_TOKEN,
                                    t >= self.max_new_tokens)
        if self.scorer is not None:
            reward = jnp.where(
                terminated,
                jnp.asarray(self.scorer.score_jax(tokens, self.prompt_len, t),
                            jnp.float32),
                jnp.float32(0.0))
        else:
            reward = jnp.float32(0.0)
        new = TokenGenState(tokens=tokens, t=t)
        return new, tokens, reward, terminated, jnp.bool_(False)
