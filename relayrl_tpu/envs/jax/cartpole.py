"""CartPole-v1 dynamics as pure JAX — the on-device twin of
``envs/classic.CartPoleEnv``.

The step math is the same Barto-Sutton-Anderson equations in the same
operation order (the parity goldens diff the two step for step); physics
constants are imported from the numpy class so the twins can never drift
apart. Computation is float32 throughout — see the precision note in
``envs/jax/base.py``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from relayrl_tpu.envs.classic import CartPoleEnv
from relayrl_tpu.envs.jax.base import JaxEnv
from relayrl_tpu.envs.spaces import Box, Discrete

import numpy as np


class CartPoleState(NamedTuple):
    state: jnp.ndarray  # [4] float32: x, x_dot, theta, theta_dot
    t: jnp.ndarray      # [] int32 steps taken this episode


class JaxCartPole(JaxEnv):
    """Functional cart-pole, Gymnasium CartPole-v1 semantics."""

    def __init__(self, max_steps: int | None = None):
        self.observation_space = Box(-np.inf, np.inf, shape=(4,))
        self.action_space = Discrete(2)
        self.max_steps = int(max_steps or CartPoleEnv.MAX_STEPS)

    def reset(self, key):
        state = jax.random.uniform(key, (4,), jnp.float32, -0.05, 0.05)
        return CartPoleState(state=state, t=jnp.int32(0)), state

    def step(self, state, action):
        c = CartPoleEnv
        x, x_dot, theta, theta_dot = (state.state[0], state.state[1],
                                      state.state[2], state.state[3])
        force = jnp.where(jnp.asarray(action).astype(jnp.int32) == 1,
                          jnp.float32(c.FORCE_MAG), jnp.float32(-c.FORCE_MAG))
        cos_t, sin_t = jnp.cos(theta), jnp.sin(theta)
        total_mass = c.MASS_CART + c.MASS_POLE
        pole_ml = c.MASS_POLE * c.HALF_LENGTH

        temp = (force + pole_ml * theta_dot**2 * sin_t) / total_mass
        theta_acc = (c.GRAVITY * sin_t - cos_t * temp) / (
            c.HALF_LENGTH * (4.0 / 3.0 - c.MASS_POLE * cos_t**2 / total_mass)
        )
        x_acc = temp - pole_ml * theta_acc * cos_t / total_mass

        x = x + c.TAU * x_dot
        x_dot = x_dot + c.TAU * x_acc
        theta = theta + c.TAU * theta_dot
        theta_dot = theta_dot + c.TAU * theta_acc
        new = jnp.stack([x, x_dot, theta, theta_dot])
        t = state.t + 1

        terminated = jnp.logical_or(jnp.abs(x) > c.X_LIMIT,
                                    jnp.abs(theta) > c.THETA_LIMIT)
        # Independent flags, exactly like the numpy twin (Gymnasium allows
        # both true on the same step; terminated-beats-truncated precedence
        # is the consumer's job — flag_last_action / the anakin unstacker).
        truncated = t >= self.max_steps
        return (CartPoleState(state=new, t=t), new, jnp.float32(1.0),
                terminated, truncated)
