"""Stacked-env driver: N gym-likes behind one batched reset/step surface.

The vector actor plane (``runtime/vector_actor.py``) steps N environment
lanes against a single batched policy dispatch; this module supplies the
matching env side — a synchronous vector wrapper over the built-in (or
Gymnasium) gym-likes with **per-env autoreset**: a lane that terminates or
truncates is reset inside the same ``step`` call, its pre-reset
observation preserved in that lane's info dict under
``"final_observation"`` (the Gymnasium VectorEnv convention) so time-limit
bootstrapping still sees the successor state.

Synchronous on purpose: the policy apply is the batched, jitted part; env
dynamics here are cheap numpy loops, and a thread/process pool per env
would reintroduce exactly the oversubscription the vector host removes.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np


class SyncVectorEnv:
    """N same-shaped gym-like envs stepped in lockstep with autoreset."""

    def __init__(self, env_fns: Sequence[Callable[[], object]]):
        if not env_fns:
            raise ValueError("SyncVectorEnv needs at least one env factory")
        self.envs = [fn() for fn in env_fns]
        self.num_envs = len(self.envs)
        self.observation_space = self.envs[0].observation_space
        self.action_space = self.envs[0].action_space
        self._base_seed: int | None = None
        self._episode = [0] * self.num_envs  # per-lane episode index

    def reset(self, seed: int | None = None):
        """Reset every lane; per-lane seeds are ``seed + lane`` so lanes
        decorrelate while the whole stack stays reproducible."""
        self._base_seed = None if seed is None else int(seed)
        self._episode = [0] * self.num_envs
        obs_rows, infos = [], []
        for lane, env in enumerate(self.envs):
            obs, info = env.reset(
                seed=None if seed is None else seed + lane)
            obs_rows.append(np.asarray(obs))
            infos.append(info)
        return np.stack(obs_rows), infos

    def _autoreset_seed(self, lane: int) -> int | None:
        """Derived per-lane seed for episode ``e`` of lane ``k``:
        ``base + k + num_envs * e`` — episode 0 is exactly ``reset(seed)``'s
        ``seed + lane`` contract, and the stride keeps every (lane,
        episode) seed distinct, so a seeded vector stack is reproducible
        across its WHOLE run, not just the first episode per lane.
        Unseeded stacks keep the old behavior (entropy-seeded resets)."""
        if self._base_seed is None:
            return None
        return self._base_seed + lane + self.num_envs * self._episode[lane]

    def step(self, actions):
        """Step every lane; finished lanes autoreset in place.

        Returns ``(obs[N,...], rewards[N], terminated[N], truncated[N],
        infos)`` where a finished lane's ``obs`` row is already the reset
        observation of its NEXT episode and its info dict carries
        ``final_observation`` (the pre-reset obs) plus ``reset_info``
        (the info dict of the autoreset — previously discarded, which
        lost e.g. Gymnasium envs' reset-time seeds/options echo).
        """
        obs_rows, rewards, terms, truncs, infos = [], [], [], [], []
        for lane, (env, action) in enumerate(zip(self.envs, actions)):
            obs, reward, terminated, truncated, info = env.step(action)
            if terminated or truncated:
                info = dict(info)
                info["final_observation"] = np.asarray(obs)
                self._episode[lane] += 1
                obs, reset_info = env.reset(seed=self._autoreset_seed(lane))
                info["reset_info"] = reset_info
            obs_rows.append(np.asarray(obs))
            rewards.append(reward)
            terms.append(bool(terminated))
            truncs.append(bool(truncated))
            infos.append(info)
        return (np.stack(obs_rows), np.asarray(rewards, np.float32),
                np.asarray(terms, bool), np.asarray(truncs, bool), infos)

    def close(self) -> None:
        for env in self.envs:
            close = getattr(env, "close", None)
            if close is not None:
                close()


def make_vector(env_id: str, num_envs: int, **kwargs) -> SyncVectorEnv:
    """``envs.make`` × N behind the stacked surface."""
    from relayrl_tpu.envs import make

    return SyncVectorEnv(
        [(lambda _env_id=env_id: make(_env_id, **kwargs))
         for _ in range(num_envs)])
