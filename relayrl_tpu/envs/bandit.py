"""Bandit battery: one-step contextual bandit (numpy built-in).

The fast-regression-signal env for scheduler/learner plumbing (ISSUE 13
satellite; ROADMAP item 5's "bandit batteries"): every episode is ONE
step — observe an integer context, pick an arm, collect 1.0 iff the arm
matches the context's deterministic target ``(ctx * mult + shift) %
n_arms`` — so a learner's reward curve responds within a handful of
epochs and a broken ingest/credit path shows up in seconds, not
minutes.

Observations are an int32 one-hot of the context (0/1 integers, like
GridWorld's raw coordinates: exercises the integer obs path; the
learner casts at the padding boundary). Dynamics are ALL integer —
context draw, target arithmetic, 0/1 reward, flags — so the pure-JAX
twin (``envs/jax/bandit.py``) holds FULL bitwise parity with no float
carve-out.
"""

from __future__ import annotations

import numpy as np

from relayrl_tpu.envs.spaces import Box, Discrete


class BanditEnv:
    """One-step contextual bandit: obs = int32 one-hot context; reward
    1.0 exactly when the arm equals ``(ctx * mult + shift) % n_arms``."""

    def __init__(self, n_contexts: int = 8, n_arms: int = 4,
                 mult: int = 3, shift: int = 1):
        if n_contexts < 1 or n_arms < 2:
            raise ValueError("need n_contexts >= 1 and n_arms >= 2")
        self.n_contexts = int(n_contexts)
        self.n_arms = int(n_arms)
        self.mult = int(mult)
        self.shift = int(shift)
        self.observation_space = Box(0, 1, shape=(self.n_contexts,),
                                     dtype=np.int32)
        self.action_space = Discrete(self.n_arms)
        self._rng = np.random.default_rng()
        self._ctx = 0

    def _obs(self) -> np.ndarray:
        obs = np.zeros(self.n_contexts, np.int32)
        obs[self._ctx] = 1
        return obs

    def target_arm(self, ctx: int) -> int:
        """The deterministic correct arm for a context — part of the
        twin-parity contract (the JAX env computes the same residue)."""
        return (int(ctx) * self.mult + self.shift) % self.n_arms

    def reset(self, seed: int | None = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._ctx = int(self._rng.integers(self.n_contexts))
        return self._obs(), {}

    def step(self, action):
        arm = int(np.clip(int(action), 0, self.n_arms - 1))
        reward = 1.0 if arm == self.target_arm(self._ctx) else 0.0
        # Every episode is one step; the terminal obs is the (unchanged)
        # context one-hot — there is no successor state to encode.
        return self._obs(), reward, True, False, {}
