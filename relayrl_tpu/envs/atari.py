"""Atari-style pixel pipeline: preprocessing wrapper + synthetic pixel env.

The reference has no pixel path at all (its envs are the Gymnasium
classic-control notebooks — reference: examples/README.md:125-152); the
driver's north-star configs (BASELINE.md: "PPO Atari Pong (CNN)",
"IMPALA-style ... Breakout ×256 actors") need the standard DQN-lineage
preprocessing in front of the ``cnn_discrete``/IMPALA families:

* frame-skip with max-pool over the last two raw frames (flicker removal)
* grayscale + bilinear resize to ``frame_size``² (84×84 default)
* frame-stack of the last ``frame_stack`` processed frames (NHWC channels)
* uint8 [0,255] → float32 [0,1] happens at the wire boundary so replay
  stays byte-sized

`make_atari` wraps a real ALE env when `ale_py` is installed; the image
bakes no ALE, so `SyntheticPixelEnv` — a paddle/ball toy with real reward
structure rendered to raw RGB frames — stands in to exercise the identical
pipeline end-to-end (tests + examples run anywhere).
"""

from __future__ import annotations

import numpy as np

from relayrl_tpu.envs.spaces import Box, Discrete


def _to_grayscale(frame: np.ndarray) -> np.ndarray:
    """RGB uint8 (H, W, 3) → luma uint8 (H, W) (ITU-R 601, the ALE/cv2
    weighting)."""
    if frame.ndim == 2:
        return frame
    return (frame @ np.array([0.299, 0.587, 0.114], np.float32)).astype(np.uint8)


def _resize_bilinear(img: np.ndarray, size: int) -> np.ndarray:
    """uint8 (H, W) → (size, size) bilinear. cv2 when available (what the
    DQN lineage uses), numpy fallback with the same sampling grid."""
    try:
        import cv2

        return cv2.resize(img, (size, size), interpolation=cv2.INTER_LINEAR)
    except ImportError:
        h, w = img.shape
        ys = np.linspace(0, h - 1, size)
        xs = np.linspace(0, w - 1, size)
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[:, None]
        wx = (xs - x0)[None, :]
        f = img.astype(np.float32)
        top = f[y0][:, x0] * (1 - wx) + f[y0][:, x1] * wx
        bot = f[y1][:, x0] * (1 - wx) + f[y1][:, x1] * wx
        return (top * (1 - wy) + bot * wy).astype(np.uint8)


class AtariPreprocessing:
    """Standard DQN preprocessing around any raw-pixel env.

    The wrapped env's ``step`` must return an RGB (or grayscale) uint8
    frame as observation. Exposes flat float32 observations of shape
    ``frame_size * frame_size * frame_stack`` in [0, 1] — the wire layout
    the ``cnn_discrete`` family reshapes to NHWC (models/cnn.py keeps the
    transport rank-agnostic).
    """

    def __init__(self, env, frame_size: int = 84, frame_stack: int = 4,
                 frame_skip: int = 4, max_pool: bool = True,
                 obs_dtype: str = "float32"):
        if frame_skip < 1:
            raise ValueError("frame_skip must be >= 1")
        if obs_dtype not in ("float32", "uint8"):
            raise ValueError(f"obs_dtype must be float32|uint8, "
                             f"got {obs_dtype!r}")
        self.env = env
        self.frame_size = frame_size
        self.frame_stack = frame_stack
        self.frame_skip = frame_skip
        self.max_pool = max_pool
        # "uint8": ship raw [0,255] bytes — 4x smaller trajectories on
        # the wire (the 84x84x4 north-star step is 28 KB as bytes vs
        # 113 KB as float32); off-policy learners can extend the saving
        # to replay + checkpoints with the algorithm-side
        # obs_dtype="uint8" knob (StepReplayBuffer's byte ring — the
        # two must be paired; the ring rejects float obs). Pair with
        # the CNN trunk's
        # default scale_obs=True (/255 on-device, models/cnn.py:105) for
        # unit-range inputs. NOTE the legacy float32 mode ALREADY
        # pre-normalizes to [0,1]; under scale_obs=True the net then
        # sees [0, 1/255] — consistent train/serve (the committed pixel
        # goldens learned in that regime) but not unit-range; uint8 mode
        # is the clean path.
        self.obs_dtype = obs_dtype
        self._stack = np.zeros((frame_size, frame_size, frame_stack), np.uint8)
        n = getattr(env.action_space, "n", None)
        self.action_space = env.action_space if n is not None else Discrete(2)
        flat = frame_size * frame_size * frame_stack
        self.observation_space = (
            Box(low=0, high=255, shape=(flat,), dtype=np.uint8)
            if obs_dtype == "uint8"
            else Box(low=0.0, high=1.0, shape=(flat,), dtype=np.float32))

    @property
    def obs_shape(self) -> tuple[int, int, int]:
        """(H, W, C) for the model arch's ``obs_shape``."""
        return (self.frame_size, self.frame_size, self.frame_stack)

    def _process(self, frame: np.ndarray) -> np.ndarray:
        return _resize_bilinear(_to_grayscale(np.asarray(frame)),
                                self.frame_size)

    def _push(self, processed: np.ndarray) -> None:
        self._stack = np.concatenate(
            [self._stack[:, :, 1:], processed[:, :, None]], axis=2)

    def _obs(self) -> np.ndarray:
        if self.obs_dtype == "uint8":
            return self._stack.reshape(-1).copy()
        return (self._stack.astype(np.float32) / 255.0).reshape(-1)

    def reset(self, seed: int | None = None):
        frame, info = self.env.reset(seed=seed)
        processed = self._process(frame)
        # Fill the whole stack with the first frame (standard init).
        self._stack = np.repeat(processed[:, :, None], self.frame_stack, axis=2)
        return self._obs(), info

    def step(self, action):
        total_reward, terminated, truncated, info = 0.0, False, False, {}
        prev_frame = None
        frame = None
        for _ in range(self.frame_skip):
            prev_frame = frame
            frame, reward, terminated, truncated, info = self.env.step(action)
            total_reward += float(reward)
            if terminated or truncated:
                break
        raw = np.asarray(frame)
        if self.max_pool and prev_frame is not None:
            raw = np.maximum(raw, np.asarray(prev_frame))
        self._push(self._process(raw))
        return self._obs(), total_reward, terminated, truncated, info


class SyntheticPixelEnv:
    """Catch-style pixel toy: move a paddle to intercept a falling ball.

    Raw RGB uint8 frames (``raw_size``² × 3), 3 actions (left/stay/right),
    +1 for a catch, -1 for a miss, episode ends after ``balls`` drops.
    Reward depends on behavior (not random), so CNN learning tests can
    assert improvement; random policy averages ~paddle_width/raw_size per
    ball.
    """

    def __init__(self, raw_size: int = 64, balls: int = 4, seed: int = 0,
                 shaped: bool = False):
        self.raw_size = raw_size
        self.balls = balls
        self.shaped = shaped  # add potential-based distance shaping
        self._rng = np.random.default_rng(seed)
        self.action_space = Discrete(3)
        self.observation_space = Box(
            low=0, high=255, shape=(raw_size, raw_size, 3), dtype=np.uint8)
        # Sprites must survive grayscale + downsize to the model's frame:
        # ball is a bright block ~1/10th of the board, paddle a full-width
        # strip of rows with a brighter catch zone.
        self._ball_r = max(1, raw_size // 20)
        self._paddle_half = max(2, raw_size // 10)
        self._paddle = raw_size // 2
        self._ball_x = 0
        self._ball_y = 0
        self._caught = 0

    def _frame(self) -> np.ndarray:
        f = np.zeros((self.raw_size, self.raw_size, 3), np.uint8)
        r = self._ball_r
        y = min(self._ball_y, self.raw_size - 1)
        f[max(0, y - r):y + r + 1,
          max(0, self._ball_x - r):self._ball_x + r + 1] = (255, 255, 255)
        lo = max(0, self._paddle - self._paddle_half)
        hi = min(self.raw_size, self._paddle + self._paddle_half + 1)
        f[-3:, lo:hi] = (200, 200, 200)
        return f

    def _new_ball(self) -> None:
        self._ball_x = int(self._rng.integers(self.raw_size))
        self._ball_y = 0

    def reset(self, seed: int | None = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._paddle = self.raw_size // 2
        self._caught = 0
        self._new_ball()
        return self._frame(), {}

    def step(self, action):
        prev_dist = abs(self._ball_x - self._paddle)
        self._paddle = int(np.clip(self._paddle + (int(action) - 1) * 3,
                                   0, self.raw_size - 1))
        self._ball_y += 2
        reward = 0.0
        if self.shaped:
            # Potential-based shaping (closing distance pays): dense credit
            # for pixel-perception tests with tight wall-clock budgets.
            reward += (prev_dist - abs(self._ball_x - self._paddle)) / 10.0
        if self._ball_y >= self.raw_size - 1:
            reward += (1.0 if abs(self._ball_x - self._paddle)
                       <= self._paddle_half else -1.0)
            self._caught += 1
            self._new_ball()
        terminated = self._caught >= self.balls
        return self._frame(), reward, terminated, False, {}


def make_atari(env_id: str = "synthetic", frame_size: int = 84,
               frame_stack: int = 4, frame_skip: int = 4,
               obs_dtype: str = "float32",
               **env_kwargs) -> AtariPreprocessing:
    """Preprocessed pixel env. ``"synthetic"`` uses the in-repo toy; any
    other id requires a Gymnasium ALE install (``gymnasium[atari]``) and is
    wrapped with the identical pipeline (ALE's own frameskip is disabled so
    this wrapper owns it). ``obs_dtype="uint8"`` ships byte-range frames
    (4x smaller wire/replay payloads; see AtariPreprocessing)."""
    if env_id == "synthetic":
        raw = SyntheticPixelEnv(**env_kwargs)
    else:
        import gymnasium

        raw = gymnasium.make(env_id, frameskip=1, **env_kwargs)
    return AtariPreprocessing(raw, frame_size=frame_size,
                              frame_stack=frame_stack, frame_skip=frame_skip,
                              obs_dtype=obs_dtype)
