"""GridWorld: integer-observation navigation (numpy built-in).

A ``size × size`` grid; the agent starts on a uniformly random non-goal
cell and must reach the fixed goal in the far corner. Observations are
the agent's **raw int32 coordinates** ``[row, col]`` — deliberately not
one-hot or normalized floats: this env exists (with its pure-JAX twin,
``envs/jax/gridworld.py``) to exercise the integer-column path of the
columnar trajectory wire end to end, where obs ship as an int32 column
and only become float at the learner's padding boundary.

Dynamics are all-integer (moves clamp at the borders, reward is exactly
``1.0`` on reaching the goal and ``0.0`` otherwise), so the JAX twin's
parity golden holds FULL bitwise equality — observation, reward, flags —
with no float-tolerance carve-out (tests/test_jax_envs.py).
"""

from __future__ import annotations

import numpy as np

from relayrl_tpu.envs.spaces import Box, Discrete

# action -> (d_row, d_col); order is part of the twin-parity contract
MOVES = np.array([[-1, 0], [1, 0], [0, -1], [0, 1]], np.int32)


class GridWorldEnv:
    """Reach the corner: obs = int32 ``[row, col]``; actions
    up/down/left/right; reward 1.0 exactly at the goal."""

    def __init__(self, size: int = 5, max_steps: int = 50):
        if size < 2:
            raise ValueError("size must be >= 2 (start and goal differ)")
        self.size = int(size)
        self.max_steps = int(max_steps)
        self.goal = np.array([self.size - 1, self.size - 1], np.int32)
        self.observation_space = Box(0, self.size - 1, shape=(2,),
                                     dtype=np.int32)
        self.action_space = Discrete(4)
        self._rng = np.random.default_rng()
        self._pos = np.zeros(2, np.int32)
        self._t = 0

    def reset(self, seed: int | None = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        # Uniform over the size*size - 1 non-goal cells: the goal owns
        # the LAST linear index, so drawing below it excludes exactly it.
        idx = int(self._rng.integers(self.size * self.size - 1))
        self._pos = np.array([idx // self.size, idx % self.size], np.int32)
        self._t = 0
        return self._pos.copy(), {}

    def step(self, action):
        move = MOVES[int(action)]
        self._pos = np.clip(self._pos + move, 0, self.size - 1)
        self._t += 1
        terminated = bool((self._pos == self.goal).all())
        reward = 1.0 if terminated else 0.0
        truncated = self._t >= self.max_steps
        return self._pos.copy(), reward, terminated, truncated, {}
