"""Built-in environments.

The reference validates end-to-end on Gymnasium classic-control tasks
(reference: examples/README.md:125-152 and the 12 example notebooks —
CartPole, LunarLander). Gymnasium is not a dependency of this image, so the
framework ships self-contained numpy implementations of the standard
classic-control dynamics behind the same ``reset``/``step`` API; examples
and learning tests run anywhere, and a real Gymnasium env drops in
unchanged (:func:`make` prefers Gymnasium when it is importable).
"""

from relayrl_tpu.envs.atari import (
    AtariPreprocessing,
    SyntheticPixelEnv,
    make_atari,
)
from relayrl_tpu.envs.bandit import BanditEnv
from relayrl_tpu.envs.classic import CartPoleEnv, PendulumEnv
from relayrl_tpu.envs.gridworld import GridWorldEnv
from relayrl_tpu.envs.memory import RecallEnv
from relayrl_tpu.envs.spaces import Box, Discrete
from relayrl_tpu.envs.tokengen import TokenGenEnv
from relayrl_tpu.envs.vector import SyncVectorEnv, make_vector

_BUILTIN = {
    "CartPole-v1": CartPoleEnv,
    "Pendulum-v1": PendulumEnv,
    # Memory task (no Gymnasium counterpart): built-in only.
    "Recall-v0": RecallEnv,
    # Integer-observation navigation (no Gymnasium counterpart):
    # exercises the columnar wire's int32 obs column end to end.
    "GridWorld-v0": GridWorldEnv,
    # One-step contextual bandit battery: the fastest regression signal
    # for learner/scheduler plumbing (all-integer dynamics).
    "Bandit-v0": BanditEnv,
    # Token-level autoregressive generation (the RLHF workload plane):
    # one episode = one generation, scored at the terminal boundary.
    "TokenGen-v0": TokenGenEnv,
}


def list_envs() -> dict[str, list[str]]:
    """One view of every env the framework can resolve, keyed by plane:

    * ``"builtin"`` — the host-side numpy built-ins (``envs.make``).
    * ``"jax"`` — the on-device pure-JAX registry (``envs.make_jax``, the
      fused-rollout plane of ``runtime/anakin.py``); empty on hosts
      without jax installed.
    * ``"gymnasium"`` — installed Gymnasium ids when the package is
      importable (the full registry, typically hundreds of ids; callers
      that print it should summarize, as ``make``'s error message does).

    The JAX subpackage imports lazily so ``relayrl_tpu.envs`` stays
    jax-free for host-only consumers (same reason the built-ins are pure
    numpy)."""
    try:
        from relayrl_tpu.envs.jax import JAX_ENVS

        jax_ids = sorted(JAX_ENVS)
    except ImportError:  # host-only consumer: no on-device plane
        jax_ids = []

    out = {"builtin": sorted(_BUILTIN), "jax": jax_ids}
    try:
        import gymnasium

        out["gymnasium"] = sorted(gymnasium.registry)
    except ImportError:
        pass
    return out


def make(env_id: str, **kwargs):
    """Create an env by id — Gymnasium if installed, else the built-in."""
    try:
        import gymnasium
    except ImportError:
        gymnasium = None
    # Dispatch on registry membership, don't catch gymnasium.make errors —
    # a missing extra (box2d) or bad kwarg must surface, not silently swap
    # in different dynamics.
    if gymnasium is not None and env_id in gymnasium.registry:
        return gymnasium.make(env_id, **kwargs)
    if env_id in _BUILTIN:
        return _BUILTIN[env_id](**kwargs)
    known = list_envs()
    gym_note = ("" if gymnasium else " [gymnasium not installed]")
    raise ValueError(
        f"unknown env {env_id!r}{gym_note}; built-ins: {known['builtin']}, "
        f"on-device (jax): {known['jax']}"
        + (f", gymnasium: {len(known['gymnasium'])} ids"
           if "gymnasium" in known else "")
    )


def make_jax(env_id: str, **kwargs):
    """Create an on-device pure-JAX env by id (lazy import: keeps plain
    ``import relayrl_tpu.envs`` free of the jax dependency)."""
    from relayrl_tpu.envs.jax import make_jax as _make_jax

    return _make_jax(env_id, **kwargs)


__all__ = ["make", "make_jax", "list_envs", "make_atari",
           "AtariPreprocessing", "SyntheticPixelEnv",
           "CartPoleEnv", "PendulumEnv", "RecallEnv", "GridWorldEnv",
           "BanditEnv", "TokenGenEnv",
           "Box", "Discrete", "SyncVectorEnv", "make_vector"]
