"""Built-in environments.

The reference validates end-to-end on Gymnasium classic-control tasks
(reference: examples/README.md:125-152 and the 12 example notebooks —
CartPole, LunarLander). Gymnasium is not a dependency of this image, so the
framework ships self-contained numpy implementations of the standard
classic-control dynamics behind the same ``reset``/``step`` API; examples
and learning tests run anywhere, and a real Gymnasium env drops in
unchanged (:func:`make` prefers Gymnasium when it is importable).
"""

from relayrl_tpu.envs.atari import (
    AtariPreprocessing,
    SyntheticPixelEnv,
    make_atari,
)
from relayrl_tpu.envs.classic import CartPoleEnv, PendulumEnv
from relayrl_tpu.envs.memory import RecallEnv
from relayrl_tpu.envs.spaces import Box, Discrete
from relayrl_tpu.envs.vector import SyncVectorEnv, make_vector

_BUILTIN = {
    "CartPole-v1": CartPoleEnv,
    "Pendulum-v1": PendulumEnv,
    # Memory task (no Gymnasium counterpart): built-in only.
    "Recall-v0": RecallEnv,
}


def make(env_id: str, **kwargs):
    """Create an env by id — Gymnasium if installed, else the built-in."""
    try:
        import gymnasium
    except ImportError:
        gymnasium = None
    # Dispatch on registry membership, don't catch gymnasium.make errors —
    # a missing extra (box2d) or bad kwarg must surface, not silently swap
    # in different dynamics.
    if gymnasium is not None and env_id in gymnasium.registry:
        return gymnasium.make(env_id, **kwargs)
    if env_id in _BUILTIN:
        return _BUILTIN[env_id](**kwargs)
    raise ValueError(
        f"unknown env {env_id!r} (not in gymnasium{'' if gymnasium else ' [not installed]'}); "
        f"built-ins: {sorted(_BUILTIN)}"
    )


__all__ = ["make", "make_atari", "AtariPreprocessing", "SyntheticPixelEnv",
           "CartPoleEnv", "PendulumEnv", "RecallEnv", "Box", "Discrete",
           "SyncVectorEnv", "make_vector"]
