"""Memory tasks: environments solvable ONLY by conditioning on history.

The reference trains exclusively on fully-observed classic control
(examples/ tree); these built-ins exist to exercise the long-context model
family end-to-end — a per-step MLP policy is capped at chance by
construction, while a sequence policy (transformer over the trajectory
time axis) can solve them by attending back to the cue.

``RecallEnv``: at t=0 the observation shows a one-hot cue; every later
observation hides it. At the final ("query") step the agent must emit the
action matching the cue: reward +1, else 0. Expected return of any
memoryless policy = 1/n_cues; a policy with memory reaches 1.0.
"""

from __future__ import annotations

import numpy as np

from relayrl_tpu.envs.spaces import Box, Discrete


class RecallEnv:
    """Remember-the-cue: obs = [cue one-hot (t=0 only), is_query, t/T].

    ``horizon`` actions per episode; only the last one is scored. The
    distractor phase can optionally carry observation noise to stop
    policies keying on spurious features.
    """

    def __init__(self, horizon: int = 8, n_cues: int = 2,
                 noise: float = 0.0):
        if horizon < 2:
            raise ValueError("horizon must be >= 2 (cue step + query step)")
        self.horizon = int(horizon)
        self.n_cues = int(n_cues)
        self.noise = float(noise)
        self.observation_space = Box(-np.inf, np.inf,
                                     shape=(self.n_cues + 2,))
        self.action_space = Discrete(self.n_cues)
        self._rng = np.random.default_rng()
        self._cue = 0
        self._t = 0

    def _obs(self) -> np.ndarray:
        obs = np.zeros(self.n_cues + 2, np.float32)
        if self._t == 0:
            obs[self._cue] = 1.0
        elif self.noise > 0.0:
            obs[: self.n_cues] = self._rng.normal(
                0.0, self.noise, self.n_cues)
        obs[self.n_cues] = 1.0 if self._t == self.horizon - 1 else 0.0
        obs[self.n_cues + 1] = self._t / self.horizon
        return obs

    def reset(self, seed: int | None = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._cue = int(self._rng.integers(self.n_cues))
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        is_query = self._t == self.horizon - 1
        reward = float(int(action) == self._cue) if is_query else 0.0
        self._t += 1
        terminated = self._t >= self.horizon
        return self._obs(), reward, terminated, False, {}
