"""REINFORCE (vanilla policy gradient) ± value baseline, as a jitted XLA
program.

Capability parity with the reference's only implemented algorithm
(reference: relayrl_framework/src/native/python/algorithms/REINFORCE/
REINFORCE.py — config-driven ctor at :16-62, ``receive_trajectory`` buffering
+ train-every-``traj_per_epoch`` at :70-95, one policy-gradient step
``-(logp*adv).mean()`` plus ``train_vf_iters`` value MSE steps with KL/entropy
diagnostics at :97-125,141-160, ``save()`` via torch.jit at :64-68).

TPU-first redesign:
* The whole epoch update — GAE-λ, advantage normalization, the policy step
  and **all** value iterations — is ONE jitted function on padded ``[B, T]``
  batches (``lax.fori_loop`` for the vf iterations). The reference loops in
  Python over scipy outputs; here a single XLA program touches HBM once.
* Two optimizers (pi_lr / vf_lr, matching the reference) act on one shared
  param tree via ``optax.multi_transform`` partitions.
* State (params + both opt states + RNG + counters) is a pytree — donate-able
  on update and fully checkpointable.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import jax.numpy as jnp
import optax
from flax import struct

from relayrl_tpu.algorithms.base import register_algorithm
from relayrl_tpu.algorithms.onpolicy import OnPolicyAlgorithm
from relayrl_tpu.models import build_policy
from relayrl_tpu.models.base import apply_arch_overrides
from relayrl_tpu.ops import gae_advantages, masked_mean_std, normalize_advantages


class ReinforceState(struct.PyTreeNode):
    params: Any
    pi_opt_state: Any
    vf_opt_state: Any
    rng: jax.Array
    step: jax.Array  # i32 scalar — doubles as the model version


def _param_labels(params) -> Any:
    """Label each leaf 'pi' or 'vf' by its top-level module name."""

    def label_tree(tree, label):
        return jax.tree.map(lambda _: label, tree)

    inner = params["params"]
    labeled = {
        name: label_tree(sub, "vf" if name.startswith("vf") else "pi")
        for name, sub in inner.items()
    }
    return {"params": labeled}


def make_optimizers(params, pi_lr: float, vf_lr: float, freeze=()):
    """The (tx_pi, tx_vf) pair every actor-critic algorithm here uses: two
    optimizers over ONE shared param tree, partitioned by the pi/vf labels —
    the single source of truth for the partition (ctor and jitted update
    must agree or opt-state structure silently drifts).

    ``freeze`` (regex strings over leaf paths — the ``learner.freeze``
    knob, algorithms/freeze.py) adds a third partition whose leaves
    neither optimizer ever moves: frozen leaves stay bit-identical
    across updates, which is what makes them free on the wire-v2 delta
    plane. The label is only added when patterns are present, so
    freeze-less opt-state trees (and their checkpoints) are unchanged."""
    labels = _param_labels(params)
    txs_pi = {"pi": optax.adam(pi_lr), "vf": optax.set_to_zero()}
    txs_vf = {"pi": optax.set_to_zero(), "vf": optax.adam(vf_lr)}
    if freeze:
        from relayrl_tpu.algorithms.freeze import freeze_labels

        labels = freeze_labels(params, freeze, base_labels=labels)
        txs_pi["freeze"] = optax.set_to_zero()
        txs_vf["freeze"] = optax.set_to_zero()
    return (optax.multi_transform(txs_pi, labels),
            optax.multi_transform(txs_vf, labels))


def make_reinforce_update(policy, pi_lr: float, vf_lr: float,
                          train_vf_iters: int, gamma: float, lam: float,
                          with_baseline: bool, freeze=()):
    """Build the pure (state, batch) -> (state, metrics) epoch update."""

    def update(state: ReinforceState, batch: Mapping[str, jax.Array]):
        tx_pi, tx_vf = make_optimizers(state.params, pi_lr, vf_lr, freeze)
        obs, act, act_mask = batch["obs"], batch["act"], batch["act_mask"]
        rew, val, valid = batch["rew"], batch["val"], batch["valid"]
        last_val = batch["last_val"]

        if with_baseline:
            adv, ret = gae_advantages(rew, val, valid, gamma, lam, last_val)
        else:
            # Without a baseline the advantage IS the reward-to-go
            # (ref: PolicyWithoutBaseline path).
            adv, ret = gae_advantages(rew, jnp.zeros_like(val), valid,
                                      gamma, 1.0, jnp.zeros_like(last_val))
        adv = normalize_advantages(adv, valid)
        n_valid = jnp.maximum(jnp.sum(valid), 1.0)

        # --- policy step (one, as in the reference) ---
        def pi_loss_fn(params):
            logp, ent, _ = policy.evaluate(params, obs, act, act_mask)
            loss = -jnp.sum(logp * adv * valid) / n_valid
            return loss, (logp, ent)

        (pi_loss, (logp_new, ent)), grads = jax.value_and_grad(
            pi_loss_fn, has_aux=True)(state.params)
        updates, pi_opt_state = tx_pi.update(grads, state.pi_opt_state, state.params)
        params = optax.apply_updates(state.params, updates)

        # Diagnostics (ref REINFORCE.py:141-160): approx KL vs the behavior
        # log-probs stored at sample time, mean entropy, post-update Δloss.
        old_logp = batch["logp"]
        approx_kl = jnp.sum((old_logp - logp_new) * valid) / n_valid
        entropy = jnp.sum(ent * valid) / n_valid
        pi_loss_after, _ = pi_loss_fn(params)

        # --- value steps (train_vf_iters, fori_loop on device) ---
        def vf_loss_fn(params):
            _, _, v = policy.evaluate(params, obs, act, act_mask)
            return jnp.sum(jnp.square(v - ret) * valid) / n_valid

        vf_loss_before = vf_loss_fn(params) if with_baseline else jnp.float32(0)

        def vf_body(_, carry):
            params, opt_state = carry
            grads = jax.grad(vf_loss_fn)(params)
            updates, opt_state = tx_vf.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        if with_baseline:
            # NOTE: loop unrolling (unroll=4/8) was measured and does NOT
            # help here — interleaved A/B on the v5e showed identical
            # steady-state throughput (~103 updates/s) for unroll 1/4/8;
            # apparent gains in sequential sweeps were ambient chip-state
            # windows (throughput drifts 100-160 up/s across minutes).
            params, vf_opt_state = jax.lax.fori_loop(
                0, train_vf_iters, vf_body, (params, state.vf_opt_state))
            vf_loss_after = vf_loss_fn(params)
        else:
            vf_opt_state = state.vf_opt_state
            vf_loss_after = jnp.float32(0)

        adv_mean, adv_std = masked_mean_std(adv, valid)
        metrics = {
            "LossPi": pi_loss,
            "DeltaLossPi": pi_loss_after - pi_loss,
            "KL": approx_kl,
            "Entropy": entropy,
            "LossV": vf_loss_before,
            "DeltaLossV": vf_loss_after - vf_loss_before,
            "AdvMean": adv_mean,
            "AdvStd": adv_std,
        }
        new_state = ReinforceState(
            params=params,
            pi_opt_state=pi_opt_state,
            vf_opt_state=vf_opt_state,
            rng=state.rng,
            step=state.step + 1,
        )
        return new_state, metrics

    return update


@register_algorithm("REINFORCE")
class REINFORCE(OnPolicyAlgorithm):
    """Host-side REINFORCE orchestration (ctor parity with
    REINFORCE.py:16-62: ``REINFORCE(env_dir, config_path, obs_dim, act_dim,
    buf_size, **hyperparam overrides)``)."""

    ALGO_NAME = "REINFORCE"

    def _setup(self, params: dict, learner: dict, rng: jax.Array) -> None:
        self.with_baseline = bool(params.get("with_vf_baseline", False))
        self.gamma = float(params.get("gamma", 0.98))
        self.lam = float(params.get("lam", 0.97))

        self.arch = {
            "kind": str(params.get(
                "model_kind",
                "mlp_discrete" if self.discrete else "mlp_continuous")),
            "obs_dim": self.obs_dim,
            "act_dim": self.act_dim,
            "hidden_sizes": list(params.get("hidden_sizes", [128, 128])),
            "activation": "tanh",
            "has_critic": self.with_baseline,
            # learner.precision config → compute dtype (bf16 feeds the MXU);
            # actors inherit it through the arch so learner/actor agree.
            "precision": str(learner.get("precision", "float32")),
        }
        apply_arch_overrides(self.arch, params)
        self.policy = build_policy(self.arch)

        init_rng, state_rng = jax.random.split(rng)
        net_params = self.policy.init_params(init_rng)
        freeze = self._resolve_freeze(params, learner, net_params)
        update = make_reinforce_update(
            self.policy,
            pi_lr=float(params.get("pi_lr", 3e-4)),
            vf_lr=float(params.get("vf_lr", 1e-3)),
            train_vf_iters=int(params.get("train_vf_iters", 80)),
            gamma=self.gamma,
            lam=self.lam,
            with_baseline=self.with_baseline,
            freeze=freeze,
        )
        self._update = jax.jit(update, donate_argnums=0)

        tx_pi, tx_vf = make_optimizers(
            net_params, float(params.get("pi_lr", 3e-4)),
            float(params.get("vf_lr", 1e-3)), freeze)
        self.state = ReinforceState(
            params=net_params,
            pi_opt_state=tx_pi.init(net_params),
            vf_opt_state=tx_vf.init(net_params),
            rng=state_rng,
            step=jnp.int32(0),
        )

    def _log_keys(self):
        keys = ["LossPi", "DeltaLossPi", "KL", "Entropy"]
        if self.with_baseline:
            keys += ["LossV", "DeltaLossV"]
        return keys
