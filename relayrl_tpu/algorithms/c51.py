"""C51 (categorical distributional DQN) as a jitted XLA program.

Fills the reference's registry slot (whitelisted, never implemented —
relayrl_framework/src/sys_utils/config_loader.rs:148-159). The categorical
projection of the Bellman-updated support onto the fixed atom grid is
expressed as two one-hot matmuls (scatter-free, MXU-friendly) so the whole
update — target distribution, projection, cross-entropy, Adam, polyak —
compiles into one device program.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import struct

from relayrl_tpu.algorithms.base import register_algorithm
from relayrl_tpu.algorithms.offpolicy import (
    EpsilonGreedyMixin,
    OffPolicyAlgorithm,
    polyak_update,
)
from relayrl_tpu.models import build_policy
from relayrl_tpu.models.mlp import _MASK_FILL, _compute_dtype
from relayrl_tpu.models.q_networks import DistributionalQNet


class C51State(struct.PyTreeNode):
    params: Any
    target_params: Any
    opt_state: Any
    step: jax.Array


def categorical_projection(support: jax.Array, probs: jax.Array,
                           rew: jax.Array, done: jax.Array,
                           gamma: float) -> jax.Array:
    """Project ``T z = r + gamma (1-d) z`` back onto ``support``.

    ``probs [B, N]`` is the next-state distribution of the chosen action;
    returns the projected target distribution ``[B, N]``. One-hot matmul
    formulation: each source atom j splits its mass between floor/ceil
    neighbor bins of its Bellman-updated position.
    """
    n = support.shape[0]
    v_min, v_max = support[0], support[-1]
    dz = (v_max - v_min) / (n - 1)
    tz = jnp.clip(rew[:, None] + gamma * (1.0 - done[:, None]) * support[None],
                  v_min, v_max)
    b = (tz - v_min) / dz                      # [B, N] fractional bin
    low = jnp.floor(b)
    high = jnp.ceil(b)
    # When b lands exactly on a bin (low == high) give it all mass via the
    # `low` branch: weight_low = (high - b) + (low == high).
    w_low = (high - b) + (low == high).astype(b.dtype)
    w_high = b - low
    onehot_low = jax.nn.one_hot(low.astype(jnp.int32), n, dtype=b.dtype)
    onehot_high = jax.nn.one_hot(high.astype(jnp.int32), n, dtype=b.dtype)
    # [B, N_src] x [B, N_src, N_bin] -> [B, N_bin]
    return jnp.einsum("bj,bjn->bn", probs * w_low, onehot_low) + jnp.einsum(
        "bj,bjn->bn", probs * w_high, onehot_high)


def make_c51_update(module: DistributionalQNet, support: jax.Array,
                    gamma: float, lr: float, polyak: float):
    tx = optax.adam(lr)

    def update(state: C51State, batch):
        obs, act, rew = batch["obs"], batch["act"], batch["rew"]
        obs2, mask2, done = batch["obs2"], batch["mask2"], batch["done"]

        logits2 = module.apply(state.target_params, obs2)   # [B, A, N]
        probs2 = jax.nn.softmax(logits2, axis=-1)
        q2 = jnp.sum(probs2 * support, axis=-1)             # [B, A]
        a2 = jnp.argmax(jnp.where(mask2 > 0, q2, _MASK_FILL), axis=-1)
        probs2_a = jnp.take_along_axis(
            probs2, a2[:, None, None], axis=1).squeeze(1)   # [B, N]
        target_dist = categorical_projection(support, probs2_a, rew, done,
                                             gamma)

        def loss_fn(params):
            logits = module.apply(params, obs)              # [B, A, N]
            logp = jax.nn.log_softmax(logits, axis=-1)
            logp_a = jnp.take_along_axis(
                logp, act[:, None, None].astype(jnp.int32), axis=1).squeeze(1)
            loss = -jnp.mean(jnp.sum(target_dist * logp_a, axis=-1))
            q_a = jnp.sum(jnp.exp(logp_a) * support, axis=-1)
            return loss, q_a

        (loss, q_a), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        target_params = polyak_update(params, state.target_params, polyak)
        metrics = {"LossQ": loss, "QVals": jnp.mean(q_a)}
        return C51State(params=params, target_params=target_params,
                        opt_state=opt_state, step=state.step + 1), metrics

    return update


@register_algorithm("C51")
class C51(EpsilonGreedyMixin, OffPolicyAlgorithm):
    ALGO_NAME = "C51"
    DEFAULT_DISCRETE = True

    def _setup(self, params: dict, learner: dict) -> None:
        eps0 = self._setup_epsilon(params)
        n_atoms = int(params.get("n_atoms", 51))
        self.arch = {
            "kind": "c51_discrete",
            "obs_dim": self.obs_dim,
            "act_dim": self.act_dim,
            "hidden_sizes": list(params.get("hidden_sizes", [128, 128])),
            "n_atoms": n_atoms,
            "v_min": float(params.get("v_min", -10.0)),
            "v_max": float(params.get("v_max", 10.0)),
            "epsilon": eps0,
            "precision": str(learner.get("precision", "float32")),
        }
        from relayrl_tpu.models.q_networks import (
            PIXEL_ARCH_KEYS,
            conv_trunk_kwargs,
        )

        for key in PIXEL_ARCH_KEYS:
            if key in params:
                self.arch[key] = params[key]
        self.policy = build_policy(self.arch)

        self._module = DistributionalQNet(
            act_dim=self.act_dim,
            n_atoms=n_atoms,
            hidden_sizes=tuple(self.arch["hidden_sizes"]),
            compute_dtype=_compute_dtype(self.arch),
            **conv_trunk_kwargs(self.arch))
        support = jnp.linspace(self.arch["v_min"], self.arch["v_max"], n_atoms)
        net_params = self.policy.init_params(self._rng_init)
        tx = optax.adam(float(params.get("lr", 1e-3)))
        self.state = C51State(
            params=net_params,
            target_params=jax.tree.map(jnp.copy, net_params),
            opt_state=tx.init(net_params),
            step=jnp.int32(0),
        )
        update = make_c51_update(
            self._module, support,
            gamma=self.gamma,
            lr=float(params.get("lr", 1e-3)),
            polyak=self.polyak,
        )
        self._update = jax.jit(update, donate_argnums=0)

    def _actor_params(self):
        return self.state.params
