"""IMPALA-style async A2C with V-trace, as one jitted XLA program.

Beyond-reference capability (the reference has a single synchronous learner
fed by one socket — SURVEY.md §3.3): this learner is built for a fleet of
async actors running stale policies — the BASELINE.md north-star config
"IMPALA-style async A2C, 256 actors". Each trajectory carries the behavior
policy's ``logp_a``; the update importance-weights it to the current policy
with clipped V-trace ratios, then takes one combined A2C step (policy
gradient on the rho-clipped advantage + value MSE to the vs targets +
entropy bonus) with a single optimizer.

Staleness tolerance is the whole point: ``receive_trajectory`` trains on
every ``traj_per_epoch`` batch regardless of which model version produced
it, and publishes after every update so the actor fleet continuously
hot-swaps (the version-gated swap path of runtime/policy_actor.py).
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import jax.numpy as jnp
import optax
from flax import struct

from relayrl_tpu.algorithms.base import register_algorithm
from relayrl_tpu.algorithms.onpolicy import OnPolicyAlgorithm
from relayrl_tpu.models import build_policy
from relayrl_tpu.models.base import apply_arch_overrides
from relayrl_tpu.ops.gae import masked_mean_std
from relayrl_tpu.ops.vtrace import vtrace


class ImpalaState(struct.PyTreeNode):
    params: Any
    opt_state: Any
    rng: jax.Array  # host-side sampling key for act(); unused by the update
    step: jax.Array


def make_impala_tx(lr: float, max_grad_norm: float, freeze=(),
                   params_template=None):
    """The single owner of IMPALA's optimizer chain (ctor opt-state init
    and the jitted update must agree or the state structure silently
    drifts): global-norm clip → adam, optionally wrapped in the
    ``learner.freeze`` multi_transform mask (algorithms/freeze.py) —
    frozen leaves never move, so they are bit-identical across updates
    and free on the wire-v2 delta plane. ``params_template`` (any tree
    with the params' structure) is required when ``freeze`` is given."""
    from relayrl_tpu.algorithms.freeze import masked_optimizer

    tx = optax.chain(
        optax.clip_by_global_norm(max_grad_norm),
        optax.adam(lr),
    )
    if freeze and params_template is None:
        raise ValueError("freeze patterns need a params_template")
    return masked_optimizer(tx, params_template, freeze)


def make_impala_update(policy, lr: float, gamma: float, vf_coef: float,
                       ent_coef: float, rho_bar: float, c_bar: float,
                       max_grad_norm: float, freeze=(),
                       params_template=None):
    tx = make_impala_tx(lr, max_grad_norm, freeze, params_template)

    def update(state: ImpalaState, batch: Mapping[str, jax.Array]):
        obs, act, act_mask = batch["obs"], batch["act"], batch["act_mask"]
        rew, valid = batch["rew"], batch["valid"]
        behavior_logp = batch["logp"]
        last_val = batch["last_val"]
        n_valid = jnp.maximum(jnp.sum(valid), 1.0)

        def loss_fn(params):
            logp, ent, v = policy.evaluate(params, obs, act, act_mask)
            vt = vtrace(behavior_logp, jax.lax.stop_gradient(logp), rew,
                        jax.lax.stop_gradient(v), valid, gamma,
                        last_val=last_val, rho_bar=rho_bar, c_bar=c_bar)
            pg_loss = -jnp.sum(logp * vt.pg_adv * valid) / n_valid
            vf_loss = jnp.sum(jnp.square(v - vt.vs) * valid) / n_valid
            ent_mean = jnp.sum(ent * valid) / n_valid
            total = pg_loss + vf_coef * vf_loss - ent_coef * ent_mean
            return total, (pg_loss, vf_loss, ent_mean, vt.rho, logp)

        (total, (pg_loss, vf_loss, ent_mean, rho, logp_new)), grads = (
            jax.value_and_grad(loss_fn, has_aux=True)(state.params))
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)

        rho_mean, _ = masked_mean_std(rho, valid)
        kl = jnp.sum((behavior_logp - logp_new) * valid) / n_valid
        metrics = {
            "LossPi": pg_loss,
            "LossV": vf_loss,
            "Entropy": ent_mean,
            "LossTotal": total,
            "RhoMean": rho_mean,
            "KL": kl,
        }
        return ImpalaState(params=params, opt_state=opt_state, rng=state.rng,
                           step=state.step + 1), metrics

    return update


@register_algorithm("IMPALA")
class IMPALA(OnPolicyAlgorithm):
    """Host orchestration: same epoch-buffer ingest as REINFORCE/PPO, but
    the update is staleness-corrected so it works with many async actors."""

    ALGO_NAME = "IMPALA"

    def _setup(self, params: dict, learner: dict, rng: jax.Array) -> None:
        # obs_shape implies the pixel trunk, as in PPO/DQN/C51; an explicit
        # model_kind (e.g. transformer_discrete) still wins.
        default_kind = ("cnn_discrete" if "obs_shape" in params
                        else "mlp_discrete" if self.discrete
                        else "mlp_continuous")
        kind = str(params.get("model_kind", default_kind))
        self.arch = {
            "kind": kind,
            "obs_dim": self.obs_dim,
            "act_dim": self.act_dim,
            "hidden_sizes": list(params.get("hidden_sizes", [128, 128])),
            "has_critic": True,
            "precision": str(learner.get("precision", "float32")),
        }
        if kind == "cnn_discrete" and "obs_shape" in params:
            self.arch["obs_shape"] = list(params["obs_shape"])
            # Same pixel-trunk passthrough as PPO (ppo.py): without it a
            # conv_spec="tpu"/dense override silently trains the Nature
            # trunk.
            for key in ("conv_spec", "dense", "scale_obs"):
                if key in params:
                    self.arch[key] = params[key]
        apply_arch_overrides(self.arch, params)
        self.policy = build_policy(self.arch)

        init_rng, state_rng = jax.random.split(rng)
        net_params = self.policy.init_params(init_rng)
        lr = float(params.get("lr", 3e-4))
        max_grad_norm = float(params.get("max_grad_norm", 40.0))
        freeze = self._resolve_freeze(params, learner, net_params)
        tx = make_impala_tx(lr, max_grad_norm, freeze, net_params)
        self.state = ImpalaState(
            params=net_params,
            opt_state=tx.init(net_params),
            rng=state_rng,
            step=jnp.int32(0),
        )
        update = make_impala_update(
            self.policy, lr=lr, gamma=self.gamma,
            vf_coef=float(params.get("vf_coef", 0.5)),
            ent_coef=float(params.get("ent_coef", 0.01)),
            rho_bar=float(params.get("rho_bar", 1.0)),
            c_bar=float(params.get("c_bar", 1.0)),
            max_grad_norm=max_grad_norm, freeze=freeze,
            params_template=net_params)
        self._update = jax.jit(update, donate_argnums=0)

    def _log_keys(self):
        return ("LossPi", "LossV", "Entropy", "RhoMean", "KL")
