"""Shared host-side orchestration for the on-policy algorithm family.

REINFORCE, PPO, and IMPALA share one loop (the reference runs it inside its
learner subprocess — relayrl_framework/src/native/python/algorithms/
REINFORCE/REINFORCE.py:70-95: buffer episodes, train every
``traj_per_epoch``, log, save): episodes stream into an
:class:`~relayrl_tpu.data.EpochBuffer`, full epochs drain into one jitted
update, and ``receive_trajectory -> True`` drives the server's model
publish. Subclasses implement ``_setup`` (arch/policy/state + the pure
jitted ``(state, batch) -> (state, metrics)`` update) and ``_log_keys``.
"""

from __future__ import annotations

import os
from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from relayrl_tpu.algorithms.base import AlgorithmBase, anchor_path
from relayrl_tpu.config import ConfigLoader
from relayrl_tpu.data import EpochBuffer
from relayrl_tpu.types.action import ActionRecord
from relayrl_tpu.types.model_bundle import ModelBundle
from relayrl_tpu.utils import EpochLogger, setup_logger_kwargs


class OnPolicyAlgorithm(AlgorithmBase):
    """Epoch-buffer learner loop shared by REINFORCE/PPO/IMPALA."""

    ALGO_NAME = "ONPOLICY"  # subclasses override

    def __init__(
        self,
        env_dir: str | None = None,
        config_path: str | None = None,
        obs_dim: int = 4,
        act_dim: int = 2,
        buf_size: int | None = None,
        logger_kwargs: Mapping[str, Any] | None = None,
        **overrides,
    ):
        loader = ConfigLoader(self.ALGO_NAME, config_path,
                              create_if_missing=False)
        params = loader.get_algorithm_params()
        params.update(overrides)
        learner = loader.get_learner_params()

        self.obs_dim, self.act_dim = int(obs_dim), int(act_dim)
        self.discrete = bool(params.get("discrete", True))
        self.traj_per_epoch = int(params.get("traj_per_epoch", 8))
        self.gamma = float(params.get("gamma", 0.99))
        seed = int(params.get("seed", 1))
        # Ref seeds `seed + 10000 * proc_id` (REINFORCE.py:40-42); fold_in is
        # the JAX-native equivalent with better key hygiene.
        # seed_salt overrides the pid fold-in for deterministic runs
        # (learning tests, reproducibility studies) without patching os.
        salt = int(params.get("seed_salt", os.getpid()))
        rng = jax.random.fold_in(jax.random.PRNGKey(seed), salt)

        # Subclass: sets self.arch, self.policy, self.state, self._update.
        self._setup(params, learner, rng)

        # Async-dispatch window (runtime/pipeline): how many updates may
        # be dispatched-but-unfenced. 0 = fence every dispatch (the old
        # synchronous behavior).
        self.max_inflight_updates = int(params.get(
            "max_inflight_updates",
            learner.get("max_inflight_updates", 2)))

        self.buffer = EpochBuffer(
            obs_dim=self.obs_dim,
            act_dim=self.act_dim,
            traj_per_epoch=self.traj_per_epoch,
            discrete=self.discrete,
            # Hyperparam override first: short fixed-horizon tasks (memory
            # envs) want tight buckets so sequence models size max_seq_len
            # to the real episode length, not the default padding.
            buckets=params.get(
                "bucket_lengths",
                learner.get("bucket_lengths", (64, 256, 1000))),
            max_traj_length=loader.get_max_traj_length(),
            # Staging slabs are reused after (window + 1) drains — by
            # then the window has fenced the update that consumed the
            # slab (see EpochBuffer.drain's reuse contract).
            staging_slots=self.max_inflight_updates + 1,
        )

        lk = dict(logger_kwargs) if logger_kwargs else setup_logger_kwargs(
            f"relayrl-{self.ALGO_NAME.lower()}", seed,
            data_dir=os.path.join(env_dir or ".", "logs"))
        self.logger = EpochLogger(**lk)
        self.logger.save_config({"algorithm": self.ALGO_NAME, **params,
                                 "obs_dim": obs_dim, "act_dim": act_dim})
        self.epoch = 0
        self._last_metrics: dict[str, float] = {}
        # A relative model path (the default "server_model.rlx") anchors
        # under env_dir so example runs don't litter the caller's cwd; an
        # absolute configured path is honoured verbatim.
        self.server_model_path = anchor_path(
            loader.get_server_model_path(), env_dir)
        self._mesh = None    # set by enable_multihost
        self._place = None   # mesh-aware batch placement

    # -- subclass contract --
    def _setup(self, params: dict, learner: dict, rng: jax.Array) -> None:
        raise NotImplementedError

    def _resolve_freeze(self, params: dict, learner: dict,
                        net_params) -> tuple[str, ...]:
        """The ``learner.freeze`` knob (per-algorithm ``freeze`` override
        wins): validated regex patterns over param leaf paths →
        optax.multi_transform masks (algorithms/freeze.py). Records
        ``self.freeze_info`` — which rides every checkpoint's JSON
        extras and is what the wire-v2 frozen-leaf savings claim is
        audited against. Shared by the whole family so the mask
        semantics cannot drift between REINFORCE/PPO/IMPALA."""
        from relayrl_tpu.algorithms.freeze import (
            freeze_info,
            normalize_freeze_spec,
        )

        patterns = normalize_freeze_spec(
            params.get("freeze", learner.get("freeze")))
        if not patterns:
            return ()
        self.freeze_info = freeze_info(net_params, patterns)
        if self.freeze_info["frozen_leaves"] == 0:
            import warnings

            warnings.warn(
                f"learner.freeze patterns {list(patterns)} matched no "
                f"param leaves — check them against e.g. "
                f"'params/block_0/qkv/kernel' style paths")
        print(f"[{self.ALGO_NAME}] learner.freeze: "
              f"{self.freeze_info['frozen_leaves']}/"
              f"{self.freeze_info['total_leaves']} leaves frozen "
              f"({self.freeze_info['frozen_bytes']} bytes) by "
              f"{list(patterns)}", flush=True)
        return patterns

    def _log_keys(self) -> Sequence[str]:
        return ("LossPi",)

    # -- reference contract --
    def receive_trajectory(self, actions) -> bool:
        """Accepts ``Sequence[ActionRecord]`` (Python decode) or a
        :class:`~relayrl_tpu.types.columnar.DecodedTrajectory` (native
        columnar decode — markers pre-folded)."""
        batch = self.accumulate(actions)
        if batch is None:
            return False
        self.train_on_batch(batch)
        self.log_epoch()
        return True

    def accumulate(self, item):
        """Buffer one trajectory WITHOUT training; returns the drained
        epoch batch dict when the buffer fills, else None. This is the
        single owner of the empty/marker-only validation;
        :meth:`receive_trajectory` is accumulate + train + log, and the
        multi-host server calls accumulate alone on the coordinator (the
        training step is collective — :meth:`train_on_batch` runs on
        every process with the broadcast batch)."""
        from relayrl_tpu.types.columnar import (
            DecodedTrajectory,
            trajectory_is_finite,
        )

        if isinstance(item, DecodedTrajectory):
            if item.n_steps == 0:
                return None
        elif not item or all(a.act is None for a in item):
            # Marker-only trajectories (stranded by a capacity flush)
            # carry no steps; padding would raise on the empty fold.
            return None
        if self.ingest_finite_guard and not trajectory_is_finite(item):
            self._drop_nonfinite()
            return None
        if self.buffer.add_episode(item):
            return self.buffer.drain().as_dict()
        return None

    def train_on_batch(self, host_batch: Mapping[str, Any]) -> Mapping[str, float]:
        """One jitted update on an assembled batch dict (host or device
        arrays), dispatched asynchronously: metrics come back as a
        :class:`~relayrl_tpu.runtime.pipeline.LazyMetrics` that fences
        only when read (``log_epoch``/``stats``), and the in-flight
        window bounds how far dispatch runs ahead of the device.
        Multi-host: every process must call this with the same batch
        (see the server's broadcast loop)."""
        from relayrl_tpu.runtime.pipeline import LazyMetrics

        self._sync_version_mirror()
        # Health-probe base copy BEFORE the donating update (guardrails
        # plane; None without probes) — see base._guard_pre_update.
        probe_base = self._guard_pre_update()
        self.state, metrics = self._update(self.state,
                                           self._to_device(host_batch))
        self._dispatched_updates += 1
        metrics = self._guard_merge_probes(metrics, probe_base)
        self._last_metrics = LazyMetrics(metrics)
        self.inflight.push(metrics, version=self.dispatched_version)
        return self._last_metrics

    def train_model(self) -> Mapping[str, float]:
        return self.train_on_batch(self.buffer.drain().as_dict())

    def mh_zero_batch(self, b: int, t: int) -> dict:
        """Placeholder epoch batch (shape/dtype only) that non-coordinators
        feed the batch broadcast — the descriptor carries (B, T)."""
        from relayrl_tpu.data.batching import TrajectoryBatch

        return TrajectoryBatch.zeros(b, t, self.obs_dim, self.act_dim,
                                     self.discrete)

    def warmup(self, should_continue=None) -> int:
        """Epoch batches are always ``[traj_per_epoch, bucket]`` — one
        compile per configured bucket length covers every batch this
        family can ever assemble. Buckets go smallest-first (they arrive
        sorted): short-episode tasks hit the small buckets, so an
        early-stopped warmup has most likely already compiled the shape
        that is about to be needed."""
        if self._warmup_is_collective():
            return 0
        compiled = 0
        for t in self.buffer.buckets:
            if self.traj_per_epoch * int(t) > self.warmup_max_elements:
                break  # buckets ascend: everything further is bigger
            if should_continue is not None and not should_continue():
                break
            self._warmup_update(
                self.mh_zero_batch(self.traj_per_epoch, int(t)))
            compiled += 1
        return compiled

    def maybe_log_epoch(self) -> None:
        # One collective update == one epoch for the on-policy family.
        self.log_epoch()

    def enable_multihost(self, mesh) -> None:
        """Re-compile the update over a (possibly multi-process) mesh and
        place the state on it. Call once, on every process, right after
        construction (identical seeds give identical initial state; see
        TrainingServer's seed_salt handling)."""
        from relayrl_tpu.parallel import (
            make_sharded_update,
            place_batch,
            place_state,
        )

        self._mesh = mesh
        self._update = make_sharded_update(self._update, mesh, self.state)
        self.state = place_state(self.state, mesh)
        self._place = lambda b: place_batch(b, mesh)
        # The broadcast loop queues assembled batches (_mh_ready) for an
        # unbounded time before training them — staging-slab reuse would
        # corrupt them — so host assembly keeps copying (staging off).
        # The in-flight window itself survives: the sharded update is a
        # non-blocking dispatch exactly like the single-host one (the
        # collective lives inside the XLA program, not on the host), so
        # the broadcast loop overlaps ingest/broadcast/prefetch with the
        # in-flight updates under the same max_inflight_updates bound.
        self.buffer.disable_staging()
        self._inflight = None  # rebuilt over the (unchanged) window bound
        # One jitted params gather, reused by every bundle() call (a fresh
        # lambda per call would retrace + recompile the all-gather each
        # publish).
        from relayrl_tpu.parallel.sharding import replicated

        self._gather_params = jax.jit(lambda p: p,
                                      out_shardings=replicated(mesh))

    def reset_ingest_buffers(self) -> None:
        """Guardrail rollback: a poisoned stream may have part-filled the
        epoch buffer; those episodes belong to the rolled-back line."""
        self.buffer.reset()

    def capture_epoch_stats(self, updated: bool):
        """One update == one epoch for this family: a log is due exactly
        when an update dispatched. Pops the episode stats NOW so
        episodes arriving while the update is still in flight land in
        the next epoch's row, not this one's."""
        if not updated:
            return None
        return self.buffer.pop_episode_stats()

    def log_epoch(self, stats=None, metrics=None) -> None:
        """``stats``/``metrics`` are deferred :meth:`capture_epoch_stats`
        payloads (the pipelined server logs an epoch only after its
        update's fence, by which time ``_last_metrics`` may already
        belong to a newer update); without them the episode stats pop
        here and the latest metrics apply (the direct/synchronous
        path). Reading the metrics is what fences the update."""
        rets, lens = (self.buffer.pop_episode_stats() if stats is None
                      else stats)
        if metrics is None:
            metrics = self._last_metrics
        self.epoch += 1
        self.logger.store(EpRet=rets or [0.0], EpLen=lens or [0])
        self.logger.log_tabular("Epoch", self.epoch)
        self.logger.log_tabular("EpRet", with_min_and_max=True)
        self.logger.log_tabular("EpLen", average_only=True)
        for key in self._log_keys():
            self.logger.log_tabular(key, metrics.get(key, 0.0))
        self.logger.dump_tabular()

    def save(self, path=None) -> None:
        self.bundle().save(path or self.server_model_path)

    def _publish_params(self):
        return self.state.params

    def bundle(self) -> ModelBundle:
        """Serialize the current policy for actors.

        Multi-host: params may be sharded across processes; an all-gather
        (re-shard to replicated) assembles the full copy — which makes
        this a COLLECTIVE when ``jax.process_count() > 1``: every process
        must call it at the same point (the server's broadcast loop does).
        """
        params = self.state.params
        if self._mesh is not None and jax.process_count() > 1:
            params = self._gather_params(params)
            host_params = jax.tree_util.tree_map(
                lambda x: np.asarray(x.addressable_data(0)), params)
        else:
            host_params = jax.device_get(params)
        return ModelBundle(version=self.version, arch=self.arch,
                           params=host_params)

    @property
    def version(self) -> int:
        step = self.state.step
        try:
            return int(step)
        except Exception:  # multi-host replicated array: read a local shard
            return int(np.asarray(step.addressable_data(0)))

    # convenience for in-process actors/tests
    def act(self, obs, mask=None):
        rng, sub = jax.random.split(self.state.rng)
        self.state = self.state.replace(rng=rng)
        act, aux = self._jitted_policy_step()(self.state.params, sub,
                                              jnp.asarray(obs), mask)
        return np.asarray(act), {k: np.asarray(v) for k, v in aux.items()}
