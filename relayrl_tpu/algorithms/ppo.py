"""PPO (clipped surrogate) as a single jitted XLA program.

The reference whitelists PPO in its algorithm registry but never implements
it (reference: relayrl_framework/src/sys_utils/config_loader.rs:397-433 —
only REINFORCE parses to params), and the driver's north-star configs call
for PPO on Atari (BASELINE.md). This is the full algorithm, TPU-first:

* GAE-λ, advantage normalization, and **all** train iterations × minibatches
  run inside ONE jitted update on padded ``[B, T]`` batches: a
  ``lax.scan`` over shuffled trajectory-row minibatches (gather by permuted
  indices keeps shapes static — no recompilation per epoch).
* KL early stopping (stop policy updates once approx-KL exceeds
  ``1.5 × target_kl``) is a boolean carried through the scan that zeroes
  the policy update — compiler-friendly ``lax`` control flow, no Python
  branching on device values.
* Two optimizers (pi_lr / vf_lr) on the shared param tree via
  ``optax.multi_transform``; for the shared-trunk CNN family the pi/vf
  split follows top-level module names, with trunk params owned by pi.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import jax.numpy as jnp
import optax
from flax import struct

from relayrl_tpu.algorithms.base import register_algorithm
from relayrl_tpu.algorithms.onpolicy import OnPolicyAlgorithm
from relayrl_tpu.algorithms.reinforce import make_optimizers
from relayrl_tpu.models import build_policy
from relayrl_tpu.models.base import apply_arch_overrides
from relayrl_tpu.ops import gae_advantages, masked_mean_std, normalize_advantages


class PPOState(struct.PyTreeNode):
    params: Any
    pi_opt_state: Any
    vf_opt_state: Any
    rng: jax.Array
    step: jax.Array  # i32 scalar — doubles as the model version


def make_ppo_update(
    policy,
    pi_lr: float,
    vf_lr: float,
    clip_ratio: float,
    train_iters: int,
    minibatch_count: int,
    ent_coef: float,
    vf_coef: float,
    target_kl: float,
    gamma: float,
    lam: float,
    freeze=(),
):
    """Build the pure ``(state, batch) -> (state, metrics)`` epoch update."""

    def update(state: PPOState, batch: Mapping[str, jax.Array]):
        tx_pi, tx_vf = make_optimizers(state.params, pi_lr, vf_lr, freeze)
        obs, act, act_mask = batch["obs"], batch["act"], batch["act_mask"]
        rew, val, valid = batch["rew"], batch["val"], batch["valid"]
        old_logp, last_val = batch["logp"], batch["last_val"]
        B = obs.shape[0]
        mb_rows = B // minibatch_count

        adv, ret = gae_advantages(rew, val, valid, gamma, lam, last_val)
        adv = normalize_advantages(adv, valid)

        def minibatch_loss(params, idx):
            o = jnp.take(obs, idx, axis=0)
            a = jnp.take(act, idx, axis=0)
            m = jnp.take(act_mask, idx, axis=0)
            ad = jnp.take(adv, idx, axis=0)
            rt = jnp.take(ret, idx, axis=0)
            lp_old = jnp.take(old_logp, idx, axis=0)
            vl = jnp.take(valid, idx, axis=0)
            n = jnp.maximum(jnp.sum(vl), 1.0)

            logp, ent, v = policy.evaluate(params, o, a, m)
            ratio = jnp.exp(logp - lp_old)
            clipped = jnp.clip(ratio, 1.0 - clip_ratio, 1.0 + clip_ratio)
            pi_loss = -jnp.sum(jnp.minimum(ratio * ad, clipped * ad) * vl) / n
            v_loss = jnp.sum(jnp.square(v - rt) * vl) / n
            entropy = jnp.sum(ent * vl) / n
            approx_kl = jnp.sum((lp_old - logp) * vl) / n
            clip_frac = jnp.sum(
                (jnp.abs(ratio - 1.0) > clip_ratio).astype(jnp.float32) * vl
            ) / n
            total = pi_loss + vf_coef * v_loss - ent_coef * entropy
            aux = {"pi_loss": pi_loss, "v_loss": v_loss, "entropy": entropy,
                   "kl": approx_kl, "clip_frac": clip_frac}
            return total, aux

        grad_fn = jax.value_and_grad(minibatch_loss, has_aux=True)

        def mb_step(carry, idx):
            params, pi_opt, vf_opt, stop_pi = carry
            (_, aux), grads = grad_fn(params, idx)

            # KL early stop (SpinningUp semantics): once KL > 1.5*target_kl,
            # POLICY params and pi optimizer state both freeze for the rest
            # of the epoch (select old-vs-new, branch-free; merely zeroing
            # grads would keep params moving via Adam momentum). Value
            # updates continue.
            pi_updates, pi_opt_new = tx_pi.update(grads, pi_opt, params)
            params_new = optax.apply_updates(params, pi_updates)

            def freeze(new, old):
                return jax.tree.map(
                    lambda n, o: jnp.where(stop_pi, o, n), new, old)

            params = freeze(params_new, params)
            pi_opt = freeze(pi_opt_new, pi_opt)

            vf_updates, vf_opt = tx_vf.update(grads, vf_opt, params)
            params = optax.apply_updates(params, vf_updates)

            stop_pi = jnp.logical_or(stop_pi, aux["kl"] > 1.5 * target_kl)
            return (params, pi_opt, vf_opt, stop_pi), aux

        # train_iters sweeps, each a fresh shuffle of trajectory rows.
        rng, *shuffle_rngs = jax.random.split(state.rng, train_iters + 1)
        idx_sets = jnp.stack([
            jax.random.permutation(r, B)[: mb_rows * minibatch_count].reshape(
                minibatch_count, mb_rows)
            for r in shuffle_rngs
        ]).reshape(train_iters * minibatch_count, mb_rows)

        init = (state.params, state.pi_opt_state, state.vf_opt_state,
                jnp.bool_(False))
        (params, pi_opt, vf_opt, stopped), auxes = jax.lax.scan(
            mb_step, init, idx_sets)

        adv_mean, adv_std = masked_mean_std(adv, valid)
        first = jax.tree.map(lambda x: x[0], auxes)
        last = jax.tree.map(lambda x: x[-1], auxes)
        metrics = {
            "LossPi": first["pi_loss"],
            "DeltaLossPi": last["pi_loss"] - first["pi_loss"],
            "LossV": first["v_loss"],
            "DeltaLossV": last["v_loss"] - first["v_loss"],
            "KL": last["kl"],
            "Entropy": last["entropy"],
            "ClipFrac": jnp.mean(auxes["clip_frac"]),
            "StopIter": jnp.float32(stopped),
            "AdvMean": adv_mean,
            "AdvStd": adv_std,
        }
        new_state = PPOState(params=params, pi_opt_state=pi_opt,
                             vf_opt_state=vf_opt, rng=rng,
                             step=state.step + 1)
        return new_state, metrics

    return update


@register_algorithm("PPO")
class PPO(OnPolicyAlgorithm):
    """Host-side PPO orchestration (same ctor shape as REINFORCE —
    reference REINFORCE.py:16-62 — so the training server treats all
    algorithms uniformly)."""

    ALGO_NAME = "PPO"

    def _setup(self, params: dict, learner: dict, rng: jax.Array) -> None:
        self.minibatch_count = int(params.get("minibatch_count", 4))
        if self.traj_per_epoch % self.minibatch_count:
            raise ValueError(
                f"traj_per_epoch ({self.traj_per_epoch}) must be divisible by "
                f"minibatch_count ({self.minibatch_count})")
        self.lam = float(params.get("lam", 0.95))

        obs_shape = params.get("obs_shape")
        if obs_shape is not None:
            kind = "cnn_discrete"
        else:
            kind = "mlp_discrete" if self.discrete else "mlp_continuous"
        self.arch = {
            "kind": str(params.get("model_kind", kind)),
            "obs_dim": self.obs_dim,
            "act_dim": self.act_dim,
            "hidden_sizes": list(params.get("hidden_sizes", [128, 128])),
            "activation": str(params.get("activation", "tanh")),
            "has_critic": True,
            "precision": str(learner.get("precision", "float32")),
        }
        if obs_shape is not None:
            self.arch["obs_shape"] = [int(d) for d in obs_shape]
            for key in ("conv_spec", "dense", "scale_obs"):
                if key in params:
                    self.arch[key] = params[key]
        apply_arch_overrides(self.arch, params)
        self.policy = build_policy(self.arch)

        init_rng, state_rng = jax.random.split(rng)
        net_params = self.policy.init_params(init_rng)
        freeze = self._resolve_freeze(params, learner, net_params)
        update = make_ppo_update(
            self.policy,
            pi_lr=float(params.get("pi_lr", 3e-4)),
            vf_lr=float(params.get("vf_lr", 1e-3)),
            clip_ratio=float(params.get("clip_ratio", 0.2)),
            train_iters=int(params.get("train_iters", 4)),
            minibatch_count=self.minibatch_count,
            ent_coef=float(params.get("ent_coef", 0.0)),
            vf_coef=float(params.get("vf_coef", 0.5)),
            target_kl=float(params.get("target_kl", 0.015)),
            gamma=self.gamma,
            lam=self.lam,
            freeze=freeze,
        )
        self.update_fn = update  # undecorated — parallel layer re-jits this
        self._update = jax.jit(update, donate_argnums=0)

        tx_pi, tx_vf = make_optimizers(
            net_params, float(params.get("pi_lr", 3e-4)),
            float(params.get("vf_lr", 1e-3)), freeze)
        self.state = PPOState(
            params=net_params,
            pi_opt_state=tx_pi.init(net_params),
            vf_opt_state=tx_vf.init(net_params),
            rng=state_rng,
            step=jnp.int32(0),
        )

    def _log_keys(self):
        return ("LossPi", "DeltaLossPi", "LossV", "DeltaLossV", "KL",
                "Entropy", "ClipFrac")
