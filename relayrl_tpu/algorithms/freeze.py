"""Frozen-layer optimizer masks (``learner.freeze``), promoted to
first-class config from the bench-only recipe of
benches/bench_model_wire.py (the 7.7x RLHF-finetune headline row).

``learner.freeze`` is a regex (or list of regexes) matched against
"/"-joined parameter leaf paths (e.g. ``params/block_0/qkv/kernel``).
Matching leaves are partitioned to ``optax.set_to_zero()`` via
``optax.multi_transform`` — NOT ``optax.masked``, which passes raw
gradients through for unmasked leaves and silently moves the "frozen"
params (caught in-bench, PR 5). Frozen leaves are therefore
bit-identical across any number of updates, which is also what makes
them free on the wire: model-wire v2's delta encoder skips unchanged
leaves outright, so every frozen leaf lands in
``relayrl_wire_publish_bytes_saved_total`` on every publish.

Consumers: the on-policy family (IMPALA's single optimizer chain;
REINFORCE/PPO's pi/vf partition grows a third "freeze" label). The
chosen patterns + frozen-leaf accounting ride every checkpoint's JSON
extras (``freeze`` key) so a resume can verify the mask it restores
under (checkpoint/manager.py).
"""

from __future__ import annotations

import re
from typing import Any, Sequence

import jax


def normalize_freeze_spec(spec) -> tuple[str, ...]:
    """Config value -> tuple of regex source strings. Accepts None/""
    (no freezing), one string, or a list of strings; anything that does
    not compile is rejected HERE (the loader calls this at load time —
    the unknown-key warning convention's validate-early cousin) so a
    typo'd pattern fails the config read, not the Nth training step."""
    if spec is None or spec == "" or spec == []:
        return ()
    patterns = [spec] if isinstance(spec, str) else list(spec)
    out = []
    for p in patterns:
        if not isinstance(p, str) or not p:
            raise ValueError(
                f"learner.freeze entries must be non-empty regex strings; "
                f"got {p!r}")
        try:
            re.compile(p)
        except re.error as e:
            raise ValueError(
                f"learner.freeze pattern {p!r} is not a valid regex: {e}"
            ) from e
        out.append(p)
    return tuple(out)


def leaf_path(path) -> str:
    """One KeyPath -> the "/"-joined string form patterns match against
    (flax dict trees yield e.g. ``params/block_0/qkv/kernel``)."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def freeze_info(params, patterns: Sequence[str]) -> dict[str, Any]:
    """Accounting for checkpoints/telemetry: which patterns, how many
    leaves/bytes they froze, and the frozen paths themselves (sorted) —
    the checkpoint extras surface (``extra["freeze"]``) and what the
    wire-v2 savings claim is audited against."""
    compiled = [re.compile(p) for p in patterns]
    frozen, total, frozen_bytes = [], 0, 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        total += 1
        name = leaf_path(path)
        if any(c.search(name) for c in compiled):
            frozen.append(name)
            frozen_bytes += getattr(leaf, "nbytes", 0)
    return {
        "patterns": list(patterns),
        "frozen_leaves": len(frozen),
        "total_leaves": total,
        "frozen_bytes": int(frozen_bytes),
        "frozen_paths": sorted(frozen),
    }


def freeze_labels(params, patterns: Sequence[str], base_labels=None):
    """Label pytree for ``optax.multi_transform``: frozen leaves get
    ``"freeze"``; the rest keep ``base_labels`` (an existing partition —
    REINFORCE/PPO's pi/vf labels) or ``"train"`` when None."""
    compiled = [re.compile(p) for p in patterns]

    def label(path, _leaf, base):
        name = leaf_path(path)
        if any(c.search(name) for c in compiled):
            return "freeze"
        return base

    if base_labels is None:
        return jax.tree_util.tree_map_with_path(
            lambda p, leaf: label(p, leaf, "train"), params)
    return jax.tree_util.tree_map_with_path(label, params, base_labels)


def masked_optimizer(tx, params, patterns: Sequence[str]):
    """Wrap a whole-tree optimizer so leaves matching ``patterns`` never
    move: ``multi_transform({train: tx, freeze: set_to_zero})``. No-op
    (returns ``tx``) with empty patterns, so call sites stay
    unconditional."""
    import optax

    patterns = tuple(patterns or ())
    if not patterns:
        return tx
    return optax.multi_transform(
        {"train": tx, "freeze": optax.set_to_zero()},
        freeze_labels(params, patterns))
