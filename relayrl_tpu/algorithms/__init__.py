"""Learner algorithms (ref layer L7, SURVEY.md §1).

Importing this package registers the built-in algorithms with the registry;
the training server resolves ``algorithm_name`` through
:func:`build_algorithm` (the dynamic-import analogue of the reference's
python_algorithm_reply.py:41-46).
"""

from relayrl_tpu.algorithms.base import (
    AlgorithmBase,
    build_algorithm,
    register_algorithm,
    registered_algorithms,
)
from relayrl_tpu.algorithms.reinforce import REINFORCE, ReinforceState
from relayrl_tpu.algorithms.ppo import PPO, PPOState

__all__ = [
    "AlgorithmBase",
    "build_algorithm",
    "register_algorithm",
    "registered_algorithms",
    "REINFORCE",
    "ReinforceState",
    "PPO",
    "PPOState",
]
