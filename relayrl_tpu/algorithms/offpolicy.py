"""Shared host-side orchestration for the off-policy algorithm family.

The reference registry whitelists C51/DDPG/DQN/SAC/TD3 without implementing
them (reference: relayrl_framework/src/sys_utils/config_loader.rs:148-159);
each of those here is a thin subclass of this base: transitions stream into
a :class:`~relayrl_tpu.data.StepReplayBuffer`, and after a warmup the
learner runs jitted gradient steps per received trajectory (the
"update-to-data ratio"), publishing a fresh actor policy each time
(``receive_trajectory -> True`` drives the server's model push exactly as
for the on-policy family — training_zmq.rs:1016-1029 behavior).

Subclasses implement ``_setup`` (build policy/arch/state + the pure jitted
``(state, batch) -> (state, metrics)`` update) and ``_actor_params``
(the slice of learner state that ships to actors in the ModelBundle).
"""

from __future__ import annotations

import os
from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from relayrl_tpu.algorithms.base import AlgorithmBase, anchor_path
from relayrl_tpu.config import ConfigLoader
from relayrl_tpu.data.step_buffer import StepReplayBuffer
from relayrl_tpu.types.action import ActionRecord
from relayrl_tpu.types.model_bundle import ModelBundle
from relayrl_tpu.utils import EpochLogger, setup_logger_kwargs


def polyak_update(online_params, target_params, polyak: float):
    """target <- polyak * target + (1 - polyak) * online (SpinningUp
    convention: polyak close to 1 means slow targets)."""
    return optax.incremental_update(online_params, target_params,
                                    step_size=1.0 - polyak)


class OffPolicyAlgorithm(AlgorithmBase):
    """Transition-replay learner loop shared by DQN/C51/DDPG/TD3/SAC."""

    ALGO_NAME = "OFFPOLICY"  # subclasses override
    DEFAULT_DISCRETE = True

    def __init__(
        self,
        env_dir: str | None = None,
        config_path: str | None = None,
        obs_dim: int = 4,
        act_dim: int = 2,
        buf_size: int | None = None,
        logger_kwargs: Mapping[str, Any] | None = None,
        **overrides,
    ):
        loader = ConfigLoader(self.ALGO_NAME, config_path,
                              create_if_missing=False)
        params = loader.get_algorithm_params()
        params.update(overrides)
        learner = loader.get_learner_params()

        self.obs_dim, self.act_dim = int(obs_dim), int(act_dim)
        self.gamma = float(params.get("gamma", 0.99))
        self.polyak = float(params.get("polyak", 0.995))
        self.batch_size = int(params.get("batch_size", 256))
        self.update_after = int(params.get("update_after", 1000))
        self.updates_per_step = float(params.get("updates_per_step", 1.0))
        # Bound on jitted updates per receive_trajectory call: a long
        # episode past warmup owes stored*updates_per_step updates, but
        # running them all inside one ingest call starves the ingest queue
        # and delays the model publish for the whole burst. The backlog is
        # carried in ``_update_debt`` and amortized across future calls.
        self.max_updates_per_ingest = int(
            params.get("max_updates_per_ingest", 64))
        if self.max_updates_per_ingest < 1:
            raise ValueError(
                "max_updates_per_ingest must be >= 1 (it bounds the jitted "
                "updates run per ingest call; use updates_per_step=0 to "
                "disable training on ingest)")
        self._update_debt = 0.0
        # Dispatch fusion: run K sampled-batch updates inside ONE jitted
        # call (lax.scan over a [K, B, ...] stack). Small per-update
        # batches on a fast accelerator are dominated by per-dispatch
        # host->device latency (benches/README.md DQN chip row: a 2x128
        # MLP at B=256 spends more time on dispatch than math); fusing K
        # of them amortizes that fixed cost without changing the math —
        # the scan threads state through the same K sequential updates
        # the unfused loop would run. Single-host only (the multi-host
        # broadcast loop ships one batch per collective step).
        self.updates_per_dispatch = max(
            1, int(params.get("updates_per_dispatch", 1)))
        self._update_k = None  # compiled lazily on first fused dispatch
        # Async-dispatch window (runtime/pipeline): how many updates may
        # be dispatched-but-unfenced. 0 = fence every dispatch.
        self.max_inflight_updates = int(params.get(
            "max_inflight_updates",
            learner.get("max_inflight_updates", 2)))
        # Persistent sample staging (zero-alloc steady state): sampled
        # batches write into a ring of reusable host buffers instead of
        # eight fresh fancy-index allocations per draw. Ring slots are
        # reused only after (round + window + 1) further draws — by then
        # the update that consumed the slot has been fenced by the
        # in-flight window (same proof as EpochBuffer's staging slabs).
        self._sample_ring: list[dict] = []
        self._sample_slot = 0
        self.traj_per_epoch = int(params.get("traj_per_epoch", 8))
        seed = int(params.get("seed", 1))
        # Param init is deterministic given the seed (reproducible learners);
        # only the action-sampling stream folds in the pid so concurrent
        # actor processes explore differently.
        self._rng_init = jax.random.PRNGKey(seed)
        self._rng_state = jax.random.fold_in(
            jax.random.PRNGKey(seed ^ 0x5EED),
            int(params.get("seed_salt", os.getpid())))

        self.buffer = StepReplayBuffer(
            obs_dim=self.obs_dim,
            act_dim=self.act_dim,
            capacity=int(buf_size or params.get("buffer_size", 100_000)),
            discrete=bool(params.get("discrete", self.DEFAULT_DISCRETE)),
            seed=seed,
            # "uint8" for pixel replay (pair with envs obs_dtype="uint8"):
            # 4x smaller ring/aux-checkpoint/device-transfer; the CNN
            # q-trunk casts + scales /255 on-device.
            obs_dtype=str(params.get("obs_dtype", "float32")),
        )

        # Subclass: sets self.policy, self.arch, self.state, self._update.
        self._setup(params, learner)

        lk = dict(logger_kwargs) if logger_kwargs else setup_logger_kwargs(
            f"relayrl-{self.ALGO_NAME.lower()}", seed,
            data_dir=os.path.join(env_dir or ".", "logs"))
        self.logger = EpochLogger(**lk)
        self.logger.save_config({"algorithm": self.ALGO_NAME, **params,
                                 "obs_dim": obs_dim, "act_dim": act_dim})
        self.epoch = 0
        self._traj_since_log = 0
        self._ep_returns: list[float] = []
        self._ep_lengths: list[int] = []
        self._last_metrics: dict[str, float] = {}
        self._mesh = None    # set by enable_multihost
        self._place = None   # mesh-aware batch placement
        # Relative default ("server_model.rlx") anchors under env_dir so
        # example runs don't litter the caller's cwd (see anchor_path).
        self.server_model_path = anchor_path(
            loader.get_server_model_path(), env_dir)

    # -- subclass contract --
    def _setup(self, params: dict, learner: dict) -> None:
        raise NotImplementedError

    def _actor_params(self):
        """Slice of self.state that the registered policy kind applies."""
        raise NotImplementedError

    def _publish_arch(self) -> dict:
        """Arch shipped with the bundle (hook for annealing exploration)."""
        return self.arch

    def _metric_keys(self) -> Sequence[str]:
        return ("LossQ",)

    # -- reference contract --
    def receive_trajectory(self, actions) -> bool:
        """Accepts ``Sequence[ActionRecord]`` (Python decode) or a
        :class:`~relayrl_tpu.types.columnar.DecodedTrajectory` (native
        columnar decode — marker rewards already folded, so the reward
        totals agree across paths)."""
        # accumulate() owns the empty/marker-only validation and the
        # update-debt ledger; here (single-host) the sampled batches train
        # immediately. Empty/marker-only trajectories (a capacity flush
        # can strand the terminal marker in its own send) store nothing
        # and log no phantom zero-length episode.
        batches = self.accumulate(actions)
        trained = False
        if batches:
            self.train_on_batches(batches)
            trained = True
        if self._traj_since_log >= self.traj_per_epoch:
            self.log_epoch()
        return trained

    def train_model(self) -> Mapping[str, float]:
        self._train_batches(1)
        return self._last_metrics

    def _train_batches(self, n: int) -> None:
        self.train_on_batches(
            [self.buffer.sample(self.batch_size) for _ in range(int(n))])

    def _fused_update(self):
        """jit(scan(update)) over a stacked [K, B, ...] batch — one
        dispatch for K sequential updates (same math as the loop; the
        inner already-jitted update inlines into the scan trace)."""
        if self._update_k is None:
            def run(state, stacked):
                return jax.lax.scan(
                    lambda s, b: self._update(s, b), state, stacked)

            self._update_k = jax.jit(run, donate_argnums=0)
        return self._update_k

    def train_on_batches(self, host_batches: Sequence[Mapping[str, Any]]
                         ) -> Mapping[str, float]:
        """Run the due updates, fusing groups of ``updates_per_dispatch``
        into single jitted dispatches; the remainder (and the K=1 or
        multi-host cases) go through the per-batch path."""
        k = self.updates_per_dispatch
        i, n = 0, len(host_batches)
        # _place is the mesh-aware [B, ...] placement — fused stacks are
        # [K, B, ...] and multi-host updates are one-batch collectives,
        # so fusion is single-host only.
        while k > 1 and self._place is None and n - i >= k:
            from relayrl_tpu.runtime.pipeline import LazyMetrics

            chunk = host_batches[i:i + k]
            # Device-prefetched batches stack ON DEVICE (async dispatch):
            # np.stack on a just-uploaded jax.Array would block on the
            # H2D, read it back, and re-upload the stack — a fence on the
            # dispatch-only thread.
            stacked = {
                key: (jnp.stack([b[key] for b in chunk])
                      if isinstance(chunk[0][key], jax.Array)
                      else np.stack([np.asarray(b[key]) for b in chunk]))
                for key in chunk[0]}
            self._sync_version_mirror()
            probe_base = self._guard_pre_update()
            self.state, ms = self._fused_update()(
                self.state, self._to_device(stacked))
            self._dispatched_updates += k
            # Per-row device slices dispatch lazily — no host readback on
            # the dispatch path; resolution happens where the values are
            # read (log_epoch / a test's _last_metrics access). Probes
            # cover the whole fused dispatch (the k-th update's params).
            self._last_metrics = LazyMetrics(self._guard_merge_probes(
                {key: v[-1] for key, v in ms.items()}, probe_base))
            self.inflight.push((ms, self._last_metrics.device),
                               version=self.dispatched_version)
            i += k
        for b in host_batches[i:]:
            self.train_on_batch(b)
        return self._last_metrics

    def train_on_batch(self, host_batch: Mapping[str, Any]
                       ) -> Mapping[str, float]:
        """One jitted update on a sampled transition batch, dispatched
        asynchronously (metrics resolve lazily; the in-flight window
        bounds outstanding updates). Multi-host: every process calls
        this with the same (broadcast) batch — the replay buffer itself
        stays coordinator-side."""
        from relayrl_tpu.runtime.pipeline import LazyMetrics

        self._sync_version_mirror()
        probe_base = self._guard_pre_update()
        self.state, metrics = self._update(self.state,
                                           self._to_device(host_batch))
        self._dispatched_updates += 1
        metrics = self._guard_merge_probes(metrics, probe_base)
        self._last_metrics = LazyMetrics(metrics)
        self.inflight.push(metrics, version=self.dispatched_version)
        # No logger.store here (the old per-update rows were never
        # consumed: log_epoch passes explicit values to log_tabular, so
        # the stored lists only grew for the life of the process — and as
        # device scalars they would also pin XLA buffers).
        return self._last_metrics

    def reset_ingest_buffers(self) -> None:
        """Guardrail rollback: stale-but-finite replay experience is
        valid off-policy data, so the ring is normally kept (or replaced
        wholesale by the restored checkpoint's aux snapshot). But when
        the ingest finite belt is standing down (guardrails' "warn"
        posture sets ``ingest_finite_guard = False``), admitted poison
        may sit in the ring — including inside a restored aux snapshot,
        whose healthy-at-save tag covers the params, not unsampled
        experience — and every post-restore update would re-diverge
        until the rollback budget burns down to halt. Scrub it."""
        if not self.ingest_finite_guard:
            dropped = self.buffer.scrub_nonfinite()
            if dropped:
                print(f"[guardrails] replay ring scrubbed after rollback: "
                      f"{dropped} non-finite transition(s) dropped",
                      flush=True)

    # -- multi-host contract (server broadcast loop; SURVEY §7.4 item 5) --
    def accumulate(self, item):
        """Coordinator-side ingest WITHOUT training: store the episode,
        keep the update-debt ledger, and return the list of sampled
        training batches now due (None when no update is due — warmup, or
        updates_per_step=0). The training step itself is collective:
        :meth:`train_on_batch` runs on every process with each batch."""
        from relayrl_tpu.types.columnar import (
            DecodedTrajectory,
            trajectory_is_finite,
        )

        if isinstance(item, DecodedTrajectory):
            if item.n_steps == 0:
                return None
            rew_total = item.total_reward
        elif not item or all(a.act is None for a in item):
            return None
        else:
            rew_total = float(sum(a.rew for a in item))
        if self.ingest_finite_guard and not trajectory_is_finite(item):
            # Replay poisoning is worse than the on-policy case — a
            # non-finite transition keeps resampling forever.
            self._drop_nonfinite()
            return None
        stored = self.buffer.add_episode(item)
        self._ep_returns.append(rew_total)
        self._ep_lengths.append(stored)
        self._traj_since_log += 1
        if (self.updates_per_step <= 0
                or self.buffer.total_steps < self.update_after
                or stored == 0):
            return None
        self._update_debt += stored * self.updates_per_step
        n = min(self.max_updates_per_ingest, max(1, int(self._update_debt)))
        self._update_debt = max(0.0, self._update_debt - n)
        return [self._sample_staged(n) for _ in range(n)]

    def _sample_staged(self, round_size: int) -> dict:
        """One sampled batch written into a reusable staging slot (no
        per-draw allocation). Falls back to fresh allocations on a
        multi-process mesh, where the broadcast loop may queue batches
        (``_mh_ready``) long enough for the ring to lap them."""
        if self._place is not None or self._sample_ring is None:
            return self.buffer.sample(self.batch_size)
        # One in-flight WINDOW ENTRY covers up to updates_per_dispatch
        # batches (a fused dispatch pushes once for k consumed batches),
        # so the reuse distance must count batches, not dispatches:
        # while W entries are unfenced, W*k slots may still be feeding
        # async H2D transfers.
        need = (round_size
                + self.max_inflight_updates * self.updates_per_dispatch + 1)
        while len(self._sample_ring) < need:
            self._sample_ring.append(
                self.buffer.make_sample_out(self.batch_size))
        self._sample_slot = (self._sample_slot + 1) % len(self._sample_ring)
        return self.buffer.sample(self.batch_size,
                                  out=self._sample_ring[self._sample_slot])

    def mh_zero_batch(self, b: int, t: int) -> dict:
        """Placeholder transition batch matching :meth:`StepReplayBuffer.
        sample`'s schema — what non-coordinators feed the broadcast
        (values are overwritten; only shape/dtype matter). ``t`` is unused
        (transition batches have no time axis); the descriptor's second
        slot carries obs_dim instead."""
        act = (np.zeros((b,), np.int32) if self.buffer.discrete
               else np.zeros((b, self.act_dim), np.float32))
        obs_dt = self.buffer.obs_dtype  # warmup must match the ring dtype
        return {
            "obs": np.zeros((b, self.obs_dim), obs_dt),
            "act": act,
            "rew": np.zeros((b,), np.float32),
            "obs2": np.zeros((b, self.obs_dim), obs_dt),
            "mask2": np.ones((b, self.act_dim), np.float32),
            "done": np.zeros((b,), np.float32),
        }

    def checkpoint_aux(self):
        """Replay buffer contents (chronological) + counters: a resumed
        off-policy learner keeps its experience instead of re-warming from
        an empty ring (the reference loses everything but policy weights
        on restart — SURVEY §5.4)."""
        if len(self.buffer) == 0:
            return None
        return {"replay": self.buffer.state_arrays()}

    def restore_aux(self, aux) -> None:
        if aux and "replay" in aux:
            self.buffer.load_state_arrays(aux["replay"])

    def warmup(self, should_continue=None) -> int:
        """Replay samples are always ``[batch_size]`` transitions — one
        compile covers every training batch this family draws (two when
        dispatch fusion is on: the [K, B, ...] scan shape as well)."""
        if self._warmup_is_collective():
            return 0
        if self.batch_size > self.warmup_max_elements:
            return 0
        if should_continue is not None and not should_continue():
            return 0
        self._warmup_update(self.mh_zero_batch(self.batch_size, 0))
        done = 1
        k = self.updates_per_dispatch
        if (k > 1 and k * self.batch_size <= self.warmup_max_elements
                and (should_continue is None or should_continue())):
            single = self.mh_zero_batch(self.batch_size, 0)
            stacked = {key: np.stack([v] * k) for key, v in single.items()}
            # same copy/donation discipline as the single-shape warmup
            self._warmup_update(stacked, update_fn=self._fused_update())
            done += 1
        return done

    def maybe_log_epoch(self) -> None:
        """Epoch logging is per ``traj_per_epoch`` trajectories, not per
        update (the broadcast loop calls this after every collective
        step)."""
        if self._traj_since_log >= self.traj_per_epoch:
            self.log_epoch()

    def capture_epoch_stats(self, updated: bool):
        """A log is due on trajectory cadence — even without an update
        (pre-``update_after`` warmup still logs). Pops the episode
        counters NOW so the deferred log row matches what the old
        synchronous path would have printed."""
        if self._traj_since_log < self.traj_per_epoch:
            return None
        stats = (self._ep_returns or [0.0], self._ep_lengths or [0],
                 self.buffer.total_steps)
        self._ep_returns, self._ep_lengths = [], []
        self._traj_since_log = 0
        return stats

    def enable_multihost(self, mesh) -> None:
        """Re-compile the update over a (possibly multi-process) mesh and
        place the state on it; see OnPolicyAlgorithm.enable_multihost."""
        from relayrl_tpu.parallel import (
            make_sharded_update,
            place_batch,
            place_state,
        )
        from relayrl_tpu.parallel.sharding import replicated

        self._mesh = mesh
        self._update = make_sharded_update(self._update, mesh, self.state)
        self.state = place_state(self.state, mesh)
        self._place = lambda b: place_batch(b, mesh)
        self._gather_params = jax.jit(lambda p: p,
                                      out_shardings=replicated(mesh))
        # _mh_ready may hold sampled batches unboundedly before the
        # broadcast ships them, so sample-ring slot reuse is unsafe —
        # fall back to fresh per-sample allocations. The in-flight
        # window survives: the sharded update dispatches async (the
        # collective lives inside the XLA program, not on the host),
        # bounded by the same max_inflight_updates.
        self._inflight = None  # rebuilt over the (unchanged) window bound
        self._sample_ring = None

    def log_epoch(self, stats=None, metrics=None) -> None:
        """``stats``/``metrics`` are deferred :meth:`capture_epoch_stats`
        payloads (the pipelined server logs an epoch only after its
        update's fence, by which time ``_last_metrics`` may already
        belong to a newer update); without them the counters pop here
        and the latest metrics apply (the direct/synchronous path)."""
        if stats is None:
            stats = (self._ep_returns or [0.0], self._ep_lengths or [0],
                     self.buffer.total_steps)
            self._ep_returns, self._ep_lengths = [], []
            self._traj_since_log = 0
        if metrics is None:
            metrics = self._last_metrics
        rets, lens, total_steps = stats
        self.epoch += 1
        self.logger.store(EpRet=rets, EpLen=lens)
        self.logger.log_tabular("Epoch", self.epoch)
        self.logger.log_tabular("EpRet", with_min_and_max=True)
        self.logger.log_tabular("EpLen", average_only=True)
        self.logger.log_tabular("TotalEnvInteracts", total_steps)
        for key in self._metric_keys():
            self.logger.log_tabular(key, metrics.get(key, 0.0))
        self.logger.dump_tabular()

    def save(self, path=None) -> None:
        self.bundle().save(path or self.server_model_path)

    def _publish_params(self):
        return self._actor_params()

    def bundle(self) -> ModelBundle:
        """Multi-host: params may be sharded across processes; the jitted
        re-shard to replicated assembles the full copy, making this a
        COLLECTIVE when ``jax.process_count() > 1`` (the server's
        broadcast loop calls it at the same point on every process)."""
        params = self._actor_params()
        if self._mesh is not None and jax.process_count() > 1:
            params = self._gather_params(params)
            host_params = jax.tree_util.tree_map(
                lambda x: np.asarray(x.addressable_data(0)), params)
        else:
            host_params = jax.device_get(params)
        return ModelBundle(version=self.version, arch=self._publish_arch(),
                           params=host_params)

    @property
    def version(self) -> int:
        step = self.state.step
        try:
            return int(step)
        except Exception:  # multi-host replicated array: read a local shard
            return int(np.asarray(step.addressable_data(0)))

    # convenience for in-process actors/tests
    def act(self, obs, mask=None):
        from relayrl_tpu.types.model_bundle import exploration_kwargs

        self._rng_state, sub = jax.random.split(self._rng_state)
        # Current (possibly annealed) exploration knobs ride as traced args.
        explore = exploration_kwargs(self._publish_arch())
        act, aux = self._jitted_policy_step()(
            self._actor_params(), sub, jnp.asarray(obs), mask, **explore)
        return np.asarray(act), {k: np.asarray(v) for k, v in aux.items()}


class EpsilonGreedyMixin:
    """Linear epsilon annealing shared by the epsilon-greedy family
    (DQN/C51): parse the schedule in ``_setup`` via ``_setup_epsilon``,
    publish the current value in the bundle arch."""

    def _setup_epsilon(self, params: dict) -> float:
        self.eps_start = float(params.get("epsilon_start", 1.0))
        self.eps_end = float(params.get("epsilon_end", 0.05))
        self.eps_decay_steps = int(params.get("epsilon_decay_steps", 10_000))
        return self.eps_start

    def current_epsilon(self) -> float:
        frac = min(1.0, self.buffer.total_steps / max(1, self.eps_decay_steps))
        return self.eps_start + frac * (self.eps_end - self.eps_start)

    def _publish_arch(self) -> dict:
        return {**self.arch, "epsilon": self.current_epsilon()}

    def _metric_keys(self):
        return ("LossQ", "QVals")
