"""DQN (+ double-Q) as a jitted XLA program.

Fills the reference's registry slot (whitelisted, never implemented —
relayrl_framework/src/sys_utils/config_loader.rs:148-159). One jitted
update: Huber TD loss on Q(s,a) against a (double-)Q target, Adam, and a
polyak-averaged target network — all fused into a single device program per
gradient step. Actors receive the Q-net as an epsilon-greedy
``qnet_discrete`` policy whose epsilon the learner anneals linearly per
publish (exploration rides the arch config, not actor code).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import struct

from relayrl_tpu.algorithms.base import register_algorithm
from relayrl_tpu.algorithms.offpolicy import (
    EpsilonGreedyMixin,
    OffPolicyAlgorithm,
    polyak_update,
)
from relayrl_tpu.models import build_policy
from relayrl_tpu.models.mlp import _MASK_FILL, _compute_dtype
from relayrl_tpu.models.q_networks import DiscreteQNet


class DQNState(struct.PyTreeNode):
    params: Any
    target_params: Any
    opt_state: Any
    step: jax.Array


def make_dqn_update(module: DiscreteQNet, gamma: float, lr: float,
                    polyak: float, double_q: bool):
    tx = optax.adam(lr)

    def update(state: DQNState, batch):
        obs, act, rew = batch["obs"], batch["act"], batch["rew"]
        obs2, mask2, done = batch["obs2"], batch["mask2"], batch["done"]

        q2_target = module.apply(state.target_params, obs2)
        q2_target_masked = jnp.where(mask2 > 0, q2_target, _MASK_FILL)
        if double_q:
            q2_online = module.apply(state.params, obs2)
            a2 = jnp.argmax(jnp.where(mask2 > 0, q2_online, _MASK_FILL), -1)
            next_q = jnp.take_along_axis(
                q2_target, a2[..., None], axis=-1).squeeze(-1)
        else:
            next_q = jnp.max(q2_target_masked, axis=-1)
        target = rew + gamma * (1.0 - done) * next_q

        def loss_fn(params):
            q = module.apply(params, obs)
            q_a = jnp.take_along_axis(
                q, act[..., None].astype(jnp.int32), axis=-1).squeeze(-1)
            return jnp.mean(optax.huber_loss(q_a, target)), q_a

        (loss, q_a), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        target_params = polyak_update(params, state.target_params, polyak)
        metrics = {"LossQ": loss, "QVals": jnp.mean(q_a)}
        return DQNState(params=params, target_params=target_params,
                        opt_state=opt_state, step=state.step + 1), metrics

    return update


@register_algorithm("DQN")
class DQN(EpsilonGreedyMixin, OffPolicyAlgorithm):
    ALGO_NAME = "DQN"
    DEFAULT_DISCRETE = True

    def _setup(self, params: dict, learner: dict) -> None:
        eps0 = self._setup_epsilon(params)
        self.arch = {
            "kind": "qnet_discrete",
            "obs_dim": self.obs_dim,
            "act_dim": self.act_dim,
            "hidden_sizes": list(params.get("hidden_sizes", [128, 128])),
            "epsilon": eps0,
            "precision": str(learner.get("precision", "float32")),
        }
        # Pixel variant: obs_shape switches the q-net to the Nature conv
        # trunk (same arch keys as the cnn_discrete family).
        from relayrl_tpu.models.q_networks import (
            PIXEL_ARCH_KEYS,
            conv_trunk_kwargs,
        )

        for key in PIXEL_ARCH_KEYS:
            if key in params:
                self.arch[key] = params[key]
        self.policy = build_policy(self.arch)

        self._module = DiscreteQNet(
            act_dim=self.act_dim,
            hidden_sizes=tuple(self.arch["hidden_sizes"]),
            compute_dtype=_compute_dtype(self.arch),
            **conv_trunk_kwargs(self.arch))
        net_params = self.policy.init_params(self._rng_init)
        tx = optax.adam(float(params.get("lr", 1e-3)))
        self.state = DQNState(
            params=net_params,
            target_params=jax.tree.map(jnp.copy, net_params),
            opt_state=tx.init(net_params),
            step=jnp.int32(0),
        )
        update = make_dqn_update(
            self._module,
            gamma=self.gamma,
            lr=float(params.get("lr", 1e-3)),
            polyak=self.polyak,
            double_q=bool(params.get("double_q", True)),
        )
        self._update = jax.jit(update, donate_argnums=0)

    def _actor_params(self):
        return self.state.params
